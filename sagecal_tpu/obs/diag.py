"""``sagecal-tpu diag`` — observability CLI.

Subcommands:

- ``manifest [--out FILE] [--kernel-path xla|fused]`` — collect a
  :class:`~sagecal_tpu.obs.events.RunManifest` for THIS host/backend and
  print (or write) it as JSON.  Exits non-zero only on I/O failure: a
  broken accelerator backend is *recorded in the manifest*, not fatal.
- ``validate FILE`` — check a manifest JSON (or the ``run_manifest``
  event of a JSONL log) against the schema; exit 1 with a problem list
  if invalid.
- ``events FILE`` — summarize a JSONL event log: event counts by type,
  run ids, time span, and solver-convergence / ADMM-residual digests.
- ``prom [--events FILE]`` — dump the in-process metrics registry in
  Prometheus text format (optionally re-ingesting phase timings from an
  event log first, so a finished run can be exported after the fact).
- ``perf FILE_OR_DIR`` — performance attribution for a run: per-function
  compile count / lower+compile seconds / flops / bytes from the
  ``jit_compile`` events, per-phase device-memory watermarks, and the
  transfer-audit summary.  Exit 1 when the log has no perf events (the
  run was not telemetry-enabled or nothing instrumented ran).
- ``gate NEW --baseline BASE [--tol T] [--metric name=tol ...]`` — the
  perf-regression gate: compare a fresh bench JSON against the pinned
  baseline with per-metric tolerances and direction semantics
  (throughput dropping or bytes/memory rising beyond tolerance fails).
  Exit 1 on any regression or when nothing is comparable; exit 2
  (REFUSED) on an evidence-class mismatch between the records — a
  cpu-wallclock run cannot prove or regress tpu-wallclock pins
  (``--strict`` forces the comparison; per-metric mismatches are
  excluded with a printed note).

- ``roofline TRACE [--events LOG] [--device-kind K]`` — per-kernel-
  family roofline attribution from a device-profile capture
  (:mod:`sagecal_tpu.obs.devprof`): measured device time per family,
  MFU / HBM-BW-util against the :mod:`sagecal_tpu.obs.roofline` peak
  table, compute- vs memory-bound classification, dispatch-gap stats,
  and the ROADMAP-item-1 lever each family implicates.  Exit 1 when
  the trace holds no device-op events.

- ``evidence [RECORD] [--history FILE]`` — the evidence-class ledger:
  every gate-able metric of a bench record with its class
  (tpu-wallclock / cpu-wallclock / aot-bytes / aot-hlo) and whether
  the claim is wall-clock-proven or AOT-proven.  Exit 1 on any
  unclassified claim (the machine check behind ROADMAP:34-36).

- ``quality FILE [--out-dir DIR]`` — calibration-quality report from a
  run's ``solve_quality`` / ``admm_round`` events: per-station and
  per-baseline chi^2 heatmaps as PPM images, consensus health per tile,
  and a machine-readable ``quality_report.json``.  Exit 1 when the run
  diverged (non-finite gains/chi^2, consensus runaway, or a recorded
  ``solver_diverged`` event); ``--fail-degraded`` also fails on
  degradation (station outliers, heavy down-weighting).

- ``lint [paths...] [--format json|text] [--baseline FILE]`` — the
  jaxlint static-analysis gate (:mod:`sagecal_tpu.analysis`): the
  JL001-JL015 JAX/kernel-discipline rules + the report-only JL900
  dead-import sweep over the given paths (default: the installed
  ``sagecal_tpu``).  Exit 1 on new (non-baselined) findings.

- ``kernelcheck [--json] [--crosscheck] [--backend B]`` — the kernel
  contract checker (:mod:`sagecal_tpu.analysis.kernel_check`): proves
  the Pallas grids' VMEM budgets (``FULL_CLUSTER_TILE``,
  ``_BATCH_ROWS_MAX``), grid coverage, the banked
  ``KERNEL_VMEM_TABLE.json`` freshness, and the JL013-JL015 kernel
  lints.  Exit 1 on any violation.

- ``trace FILE [--chrome OUT] [--straggler-ratio R]`` — span-tree
  report from a ``SAGECAL_TRACE=1`` run's span JSONL: tree, per-name
  attribution, critical path, and the per-band straggler table;
  ``--chrome`` re-exports a Perfetto-loadable ``trace.json``.  Exit 1
  when the file holds no spans.

- ``flight FILE [--ring-tail N]`` — render a flight-recorder dump
  (``flight_dump.json``): dump reason, exception, device state,
  all-thread stacks, and the activity-ring tail.  Exit 1 when the file
  is missing or not a dump.

- ``load OUT_DIR [--slo FILE] [--knee-tol T] [--report FILE]`` — the
  load/capacity report of a ``sagecal-tpu load`` run: throughput- and
  goodput-vs-offered-load curve per step, saturation knee, shed rate
  under overload, queue-growth rates, the Little's-law (L = λW)
  cross-check of the live timeline against the post-hoc manifest
  reconstruction, and the latest autoscale recommendation.  Exit 1
  when the timeline is missing/invalid or the cross-checks disagree.

- ``drift OUT_DIR... [--report FILE]`` — numerical-drift report from
  shadow-audit ledgers (``drift.jsonl``; :mod:`sagecal_tpu.obs.shadow`):
  per-(path-pair, bucket, dtype) distributions with provable quantile
  bounds against the central tolerance policy
  (``shadow.DRIFT_TOLERANCES``).  Exit 1 on any tolerance breach or
  structural ledger problem; exit 0 with a warning when no samples.

- ``audit OUT_DIR [--events LOG] [--queue DIR] [--max-skew S]
  [--slack S] [--json] [-V]`` — the event-sourced fleet audit
  (:mod:`sagecal_tpu.obs.audit`): validate every record file through
  the schema registry (ok/torn/foreign/out-of-schema), replay the
  fleet purely from the records, and assert the conservation laws
  (enqueued == served + shed + failed + pending, one manifest per
  request, lease-epoch monotonicity with steals only after TTL
  expiry, span-chain completeness, counter monotonicity, timeline
  depth bounds, clock-skew feasibility, sequence holes, unregistered
  files).  Exit 1 on any violation or observability gap, exit 2
  (INSUFFICIENT) when there are no queue items to conserve.
  ``SAGECAL_AUDIT_INJECT=drop_event|tear_record|forge_manifest|
  skew_clock`` injects an in-memory fault to prove the detector.

- ``replay OUT_DIR [--events LOG] [--queue DIR] [--json] [-V]`` — the
  reconstruction alone (:mod:`sagecal_tpu.obs.replay`): queue state,
  per-request dispositions, per-worker lifecycle, per-writer clock
  offsets estimated from happens-before edges, and replayed SLO
  attainment.  Exit 2 when there is nothing to replay.

Runs standalone (``python -m sagecal_tpu.obs.diag ...``) or via the
``diag`` subcommand of the main CLI (:mod:`sagecal_tpu.apps.cli`).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from sagecal_tpu.obs.events import (
    RunManifest,
    read_events,
    read_events_merged,
    validate_manifest,
)
from sagecal_tpu.obs.perf import (
    GATE_DEFAULT_TOLERANCE,
    aggregate_perf_events,
    format_gate_report,
    format_perf_report,
    gate_compare,
)
from sagecal_tpu.obs.registry import get_registry, telemetry


def _cmd_manifest(args) -> int:
    m = RunManifest.collect(kernel_path=args.kernel_path)
    text = json.dumps(m.to_dict(), indent=2, sort_keys=True)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
        print(f"wrote manifest to {args.out}")
    else:
        print(text)
    return 0


def _load_manifest_dict(path: str) -> Optional[dict]:
    with open(path, "r", encoding="utf-8") as f:
        head = f.read()
    try:
        d = json.loads(head)
        if isinstance(d, dict):
            return d
    except json.JSONDecodeError:
        pass
    # fall back: a JSONL event log — take its run_manifest event
    for ev in read_events(path):
        if ev.get("type") == "run_manifest":
            return ev
    return None


def _cmd_validate(args) -> int:
    d = _load_manifest_dict(args.file)
    if d is None:
        print(f"{args.file}: no manifest found", file=sys.stderr)
        return 1
    problems = validate_manifest(d)
    if problems:
        for p in problems:
            print(f"{args.file}: {p}", file=sys.stderr)
        return 1
    print(
        f"{args.file}: valid manifest (run {d.get('run_id')}, "
        f"{d.get('platform')}/{d.get('device_kind')} x{d.get('num_devices')}, "
        f"kernel={d.get('kernel_path')})"
    )
    return 0


def _finite(xs) -> List[float]:
    out = []
    for x in xs:
        if isinstance(x, (int, float)) and x == x:
            out.append(float(x))
    return out


def _cmd_events(args) -> int:
    # merged read: picks up per-process suffixed companions
    # (SAGECAL_EVENT_LOG_PER_PROCESS=1 runs) alongside the base log
    evs = read_events_merged(args.file)
    if not evs:
        print(f"{args.file}: no events", file=sys.stderr)
        return 1
    by_type: dict = {}
    for e in evs:
        by_type[e.get("type", "?")] = by_type.get(e.get("type", "?"), 0) + 1
    runs = sorted({e.get("run_id", "?") for e in evs})
    ts = [e["ts"] for e in evs if isinstance(e.get("ts"), (int, float))]
    span = (max(ts) - min(ts)) if ts else 0.0
    print(f"{args.file}: {len(evs)} events, {len(runs)} run(s), "
          f"{span:.1f}s span")
    for t in sorted(by_type):
        print(f"  {t}: {by_type[t]}")
    # convergence digest: final cost per cluster record
    conv = [e for e in evs if e.get("type") == "cluster_convergence"]
    if conv:
        finals = []
        for e in conv:
            costs = _finite(e.get("cost", []))
            if costs:
                finals.append(costs[-1])
        if finals:
            print(f"  convergence: {len(conv)} cluster records, "
                  f"final cost min={min(finals):.4g} max={max(finals):.4g}")
    admm = [e for e in evs if e.get("type") == "admm_round"]
    if admm:
        last = admm[-1]
        pr = _finite(last.get("primal_res", []))
        dr = _finite(last.get("dual_res", []))
        if pr and dr:
            print(f"  admm: {len(admm)} rounds, last primal_res "
                  f"max={max(pr):.4g}, dual_res max={max(dr):.4g}")
    tiles = [e for e in evs if e.get("type") == "tile_done"]
    if tiles:
        secs = _finite(sum(_finite((e.get("phase_seconds") or {}).values()))
                       for e in tiles)
        tot = sum(secs) if secs else 0.0
        print(f"  tiles: {len(tiles)} done, {tot:.2f}s in phases")
    # elastic digest: checkpoint/resume lifecycle (sagecal_tpu/elastic/)
    ckpts = [e for e in evs if e.get("type") == "checkpoint_written"]
    if ckpts:
        last = ckpts[-1]
        print(f"  checkpoints: {len(ckpts)} written, last "
              f"{last.get('path', '?')} (tile {last.get('tile_index', '?')})")
    for e in evs:
        if e.get("type") == "resume_started":
            print(f"  resume: started from {e.get('path', '?')} "
                  f"(tile {e.get('tile_index', '?')})")
        elif e.get("type") == "resume_refused":
            print(f"  resume: REFUSED - {e.get('mismatch', '?')} mismatch "
                  f"vs {e.get('path', '?')}")
    return 0


def _cmd_prom(args) -> int:
    with telemetry(True):
        reg = get_registry()
        if args.events:
            for e in read_events(args.events):
                if e.get("type") == "tile_done":
                    for phase, dt in (e.get("phase_seconds") or {}).items():
                        if isinstance(dt, (int, float)):
                            reg.observe("phase_seconds", float(dt),
                                        phase=str(phase))
                elif e.get("type") == "bench_result":
                    thr = e.get("value")
                    if isinstance(thr, (int, float)):
                        reg.gauge_set(
                            "bench_lbfgs_iters_per_second", float(thr),
                            kernel="fused" if e.get("fused_kernel")
                            else "xla",
                        )
        sys.stdout.write(reg.to_prometheus() or "# no metrics recorded\n")
    return 0


def _cmd_perf(args) -> int:
    import glob
    import os

    paths = [args.path]
    if os.path.isdir(args.path):
        paths = sorted(glob.glob(os.path.join(args.path, "*.jsonl")))
        if not paths:
            print(f"{args.path}: no *.jsonl event logs", file=sys.stderr)
            return 1
    evs: List[dict] = []
    for p in paths:
        evs.extend(read_events(p))
    agg = aggregate_perf_events(evs)
    print(format_perf_report(agg))
    if not agg["functions"]:
        # an empty attribution table means the run was not perf-observable
        # — fail so CI catches a silently un-instrumented pipeline
        return 1
    return 0


def _load_record(path: str) -> Optional[dict]:
    """A bench record: a JSON dict, or the last ``bench_result``-shaped
    line of a JSONL stream (bench.py prints one record per line)."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    try:
        d = json.loads(text)
        if isinstance(d, dict):
            return d
    except json.JSONDecodeError:
        pass
    rec = None
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(d, dict) and isinstance(d.get("value"), (int, float)):
            rec = d
    return rec


def _cmd_gate(args) -> int:
    new = _load_record(args.new)
    base = _load_record(args.baseline)
    if new is None or base is None:
        which = args.new if new is None else args.baseline
        print(f"{which}: no bench record found", file=sys.stderr)
        return 1
    from sagecal_tpu.obs.evidence import metric_evidence, record_evidence
    from sagecal_tpu.obs.perf import GATE_DEFAULT_METRICS

    # evidence refusal (PR 16): a record proven one way must never gate
    # against pins proven another — the old platform-mismatch SKIP
    # (exit 0) let a CPU-fallback run silently "pass" the TPU gate.
    # REFUSE loudly instead; --strict still forces the comparison.
    ev_new, ev_base = record_evidence(new), record_evidence(base)
    if ev_new and ev_base and ev_new != ev_base and not args.strict:
        print(f"gate: REFUSED — evidence-class mismatch (new {ev_new} "
              f"vs baseline {ev_base}): a {ev_new} measurement cannot "
              f"prove or regress a {ev_base} claim; re-bench on matching "
              f"hardware, or rerun with --strict to force the comparison",
              file=sys.stderr)
        return 2
    tolerances = {}
    for spec in args.metric or []:
        name, _, tol = spec.partition("=")
        try:
            tolerances[name] = float(tol) if tol else args.tol
        except ValueError:
            print(f"bad --metric spec: {spec!r} (want name=tol)",
                  file=sys.stderr)
            return 2
    # per-metric refusal: satellite metrics carry their own class in
    # the `evidence_classes` override map (an aot-hlo bytes row rides a
    # tpu-wallclock headline); drop — with a printed note — any metric
    # whose classes resolve on both sides and differ
    names = list(GATE_DEFAULT_METRICS)
    for extra in tolerances:
        if extra not in names:
            names.append(extra)
    kept = []
    for m in names:
        a, b = metric_evidence(new, m), metric_evidence(base, m)
        if a and b and a != b and not args.strict:
            if m in new and m in base:
                print(f"gate: metric {m} excluded — evidence-class "
                      f"mismatch ({a} vs baseline {b})")
            tolerances.pop(m, None)
            continue
        kept.append(m)
    failures, rows = gate_compare(new, base, tolerances=tolerances,
                                  default_tol=args.tol,
                                  metrics=tuple(kept))
    print(format_gate_report(rows, failures))
    for fail in failures:
        print(f"REGRESSION: {fail}", file=sys.stderr)
    if not rows:
        # nothing comparable is itself a failure: the gate must never
        # silently pass because a record lost its metrics
        return 1
    return 1 if failures else 0


def _cmd_roofline(args) -> int:
    import os

    from sagecal_tpu.obs.devprof import (
        attribute_trace,
        ledger_from_events,
        newest_trace_path,
    )
    from sagecal_tpu.obs.roofline import (
        build_report,
        format_report,
        set_kernel_gauges,
    )

    path = args.trace
    if os.path.isdir(path):
        found = newest_trace_path(path)
        if not found:
            print(f"{path}: no *.trace.json[.gz] under it — was the "
                  f"capture armed (SAGECAL_DEVICE_PROFILE / "
                  f"--device-profile)?", file=sys.stderr)
            return 1
        path = found
    attribution = attribute_trace(path,
                                  gap_threshold_us=args.gap_threshold_us)
    if not attribution["n_op_events"]:
        print(f"{path}: no device-op events (ph=X with args.hlo_op or "
              f"on an 'XLA Ops' track) — not a device-profile trace?",
              file=sys.stderr)
        return 1
    ledger = ledger_from_events(args.events) if args.events else {}
    kind = args.device_kind
    if kind is None:
        # the trace itself is device-agnostic; ask the live backend
        # (guarded: parsing a TPU trace on a laptop is legitimate)
        try:
            import jax

            kind = jax.devices()[0].device_kind
        except Exception:
            kind = None
    report = build_report(attribution, ledger, kind, dtype=args.dtype)
    set_kernel_gauges(report)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True, default=float))
    else:
        print(f"trace: {path}")
        print(format_report(report))
    return 0


def _cmd_evidence(args) -> int:
    from sagecal_tpu.obs.evidence import (
        is_valid,
        metric_evidence,
        proof_kind,
        record_evidence,
    )
    from sagecal_tpu.obs.perf import (
        GATE_DEFAULT_METRICS,
        GATE_HIGHER_BETTER,
        GATE_LOWER_BETTER,
        read_bench_history,
    )

    rec = _load_record(args.record)
    if rec is None:
        print(f"{args.record}: no bench record found", file=sys.stderr)
        return 1
    # the banked claims = every gate-able metric present in the record
    # (gate direction tables + defaults); config fields like
    # serve_batch_width are not claims and carry no class
    names = []
    for m in (*GATE_HIGHER_BETTER, *GATE_LOWER_BETTER,
              *GATE_DEFAULT_METRICS):
        if m in rec and m not in names:
            names.append(m)
    rc = 0
    ev_rec = record_evidence(rec)
    print(f"{args.record}: record-level evidence "
          f"{ev_rec or 'UNCLASSIFIED'}")
    w = max((len(m) for m in names), default=8) + 2
    print(f"{'metric':<{w}}{'value':>14}  {'evidence':<15}proof")
    counts = {}
    for m in names:
        ev = metric_evidence(rec, m)
        kind = proof_kind(ev)
        counts[kind] = counts.get(kind, 0) + 1
        v = rec.get(m)
        vs = f"{v:>14.6g}" if isinstance(v, (int, float)) \
            and not isinstance(v, bool) else f"{str(v):>14}"
        print(f"{m:<{w}}{vs}  {ev or 'UNCLASSIFIED':<15}{kind}")
        if not is_valid(ev):
            rc = 1
    # an evidence_classes override naming an unknown class is a bug in
    # the producer, not a missing stamp — flag it too
    for m, ev in (rec.get("evidence_classes") or {}).items():
        if not is_valid(ev):
            print(f"EVIDENCE: override {m}={ev!r} is not a known class",
                  file=sys.stderr)
            rc = 1
    summary = ", ".join(f"{n} {k}" for k, n in sorted(counts.items()))
    print(f"claims: {summary or 'none'}")
    if args.history:
        rows = read_bench_history(args.history)
        unclassified = sum(1 for r in rows if record_evidence(r) is None)
        print(f"{args.history}: {len(rows)} rows, "
              f"{unclassified} unclassified")
        if unclassified:
            print(f"EVIDENCE: {unclassified} history rows carry no "
                  f"resolvable evidence class — run "
                  f"tools/backfill_bench_history.py", file=sys.stderr)
            rc = 1
    if rc:
        print("EVIDENCE: unclassified claims present", file=sys.stderr)
    return rc


def _cmd_quality(args) -> int:
    import os

    from sagecal_tpu.obs.quality import (
        analyze_events,
        write_baseline_heatmap,
        write_station_heatmap,
    )

    evs = read_events(args.file)
    if not evs:
        print(f"{args.file}: no events", file=sys.stderr)
        return 1
    report = analyze_events(evs, trend_thresh=args.trend_thresh)

    out_dir = args.out_dir or os.path.dirname(os.path.abspath(args.file))
    os.makedirs(out_dir, exist_ok=True)
    images = {}
    if report["station_matrix"] is not None:
        p = os.path.join(out_dir, "station_chi2.ppm")
        write_station_heatmap(report["station_matrix"], p)
        images["station_chi2"] = p
    if report["baseline_total"] is not None:
        p = os.path.join(out_dir, "baseline_chi2.ppm")
        write_baseline_heatmap(report["baseline_total"], p)
        images["baseline_chi2"] = p

    json_report = {
        k: v for k, v in report.items()
        if k not in ("station_matrix", "baseline_total")
    }
    json_report["images"] = images
    # arrays inside solves/consensus entries were already listified by
    # analyze_events / the event log round-trip
    rp = os.path.join(out_dir, "quality_report.json")
    with open(rp, "w", encoding="utf-8") as f:
        json.dump(json_report, f, indent=2, sort_keys=True, default=float)
        f.write("\n")

    verdict = ("DIVERGED" if report["diverged"]
               else "DEGRADED" if report["degraded"] else "OK")
    print(f"{args.file}: quality {verdict} "
          f"({report['n_solve_quality_events']} solve_quality events, "
          f"{len(report['consensus'])} consensus rounds)")
    for r in report["reasons"]:
        print(f"  {r}")
    for name, p in images.items():
        print(f"  {name} -> {p}")
    print(f"  report -> {rp}")
    if report["diverged"]:
        return 1
    if args.fail_degraded and report["degraded"]:
        return 1
    return 0


def _fmt_bound(b) -> str:
    lo, hi = b
    return f"[{lo:.3g}, {hi:.3g}]"


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    import math

    rank = min(len(sorted_vals),
               max(1, math.ceil(q * len(sorted_vals) - 1e-9)))
    return sorted_vals[rank - 1]


def _cmd_serve(args) -> int:
    """Fleet-view serve report: per-tenant/per-bucket latency tables
    (exact percentiles from manifests + merged-histogram bounds), cache
    hit ratio, queue-depth timeline, lifecycle completeness, SLO budget
    status and bench trends.  Exit 1 on a burning SLO, an incomplete
    lifecycle (when spans were provided), or nothing to report."""
    from sagecal_tpu.obs.aggregate import (
        fleet_view,
        lifecycle_report,
        queue_depth_timeline,
        quantile_bounds_from_state,
        state_counter_total,
        state_label_values,
    )
    from sagecal_tpu.obs.perf import (
        bench_trend,
        format_bench_trend,
        read_bench_history,
    )
    from sagecal_tpu.obs.slo import (
        evaluate_results,
        format_slo_report,
        load_slo_specs,
    )

    out_dirs = list(args.out_dir)
    view = fleet_view(
        out_dirs,
        event_paths=args.events or (),
        span_paths=args.spans or (),
    )
    results = view["results"]
    state = view["state"]
    if not results and not state.get("counters"):
        print("no result manifests or metric snapshots under: "
              + ", ".join(out_dirs), file=sys.stderr)
        return 1
    rc = 0
    print(f"serve fleet view: {len(results)} requests, "
          f"{view['snapshots']} worker snapshot(s), "
          f"{len(view['spans'])} spans")

    # -- per-tenant latency table: exact from manifests, bounds from
    # the merged cross-process histograms
    by_tenant: dict = {}
    for r in results:
        by_tenant.setdefault(str(r.get("tenant", "?")), []).append(r)
    qs = (0.5, 0.95, 0.99)
    print("\nper-tenant latency (exact from manifests; [lo, hi] = "
          "merged-histogram quantile bounds):")
    print(f"{'tenant':<16s}{'n':>5s}{'ok':>5s}{'div':>5s}"
          f"{'p50':>9s}{'p95':>9s}{'p99':>9s}  histogram bounds")
    tenants = sorted(set(by_tenant)
                     | set(state_label_values(
                         state, "serve_request_latency_seconds",
                         "tenant")))
    for t in tenants:
        rs = by_tenant.get(t, [])
        lats = sorted(float(r.get("latency_s", 0.0)) for r in rs)
        ok = sum(1 for r in rs if r.get("verdict") == "ok")
        bounds = quantile_bounds_from_state(
            state, "serve_request_latency_seconds", qs, tenant=t)
        btxt = " ".join(
            f"p{int(q * 100)}={_fmt_bound(bounds[q])}"
            for q in qs if q in bounds) or "(no snapshot)"
        print(f"{t:<16s}{len(rs):>5d}{ok:>5d}{len(rs) - ok:>5d}"
              f"{_percentile(lats, 0.5):>9.3f}"
              f"{_percentile(lats, 0.95):>9.3f}"
              f"{_percentile(lats, 0.99):>9.3f}  {btxt}")

    # -- per-bucket table + cache hit ratio
    by_bucket: dict = {}
    for r in results:
        by_bucket.setdefault(str(r.get("bucket", "?")), []).append(r)
    if by_bucket:
        print("\nper-bucket:")
        print(f"{'bucket':<28s}{'n':>5s}{'p50_s':>9s}{'max_s':>9s}"
              "  kernel_path")
        for b in sorted(by_bucket):
            lats = sorted(float(r.get("latency_s", 0.0))
                          for r in by_bucket[b])
            # which kernel actually solved this bucket's requests —
            # stamped per manifest by the service (the capability
            # check is per (bucket, fingerprint), so mixed values
            # here mean the bucket re-routed mid-run)
            paths = sorted({str(r.get("kernel_path", "?"))
                            for r in by_bucket[b]})
            print(f"{b:<28s}{len(lats):>5d}"
                  f"{_percentile(lats, 0.5):>9.3f}{lats[-1]:>9.3f}"
                  f"  {'+'.join(paths)}")
    hits = state_counter_total(
        state, "serve_executable_cache_hits_total")
    misses = state_counter_total(
        state, "serve_executable_cache_misses_total")
    if hits or misses:
        total = hits + misses
        print(f"\nexecutable cache: {hits:g} hits / {misses:g} misses "
              f"({hits / total:.1%} hit ratio, fleet-wide)")
    aot = {k: state_counter_total(
        state, f"serve_executable_cache_aot_{k}_total")
        for k in ("hits", "misses", "errors", "saves")}
    if any(aot.values()):
        print(f"AOT artifact store: {aot['hits']:g} loads / "
              f"{aot['saves']:g} saves / {aot['misses']:g} misses / "
              f"{aot['errors']:g} bad artifacts (recompiled)")

    # -- fleet lease queue (auto-detected <out_dir>/queue, the fleet
    # coordinator's default layout): claim/steal health at a glance
    for d in out_dirs:
        qdir = os.path.join(d, "queue")
        if not os.path.isdir(qdir):
            continue
        from sagecal_tpu.fleet.queue import LeaseQueue

        q = LeaseQueue(qdir, worker="diag")
        st = q.stats()
        fails = sum(q.failure_count(i.request_id) for i in q.items())
        print(f"\nlease queue {qdir}: {st['done']}/{st['items']} done, "
              f"{st['leased']} live leases, "
              f"{st['expired_leases']} expired leases (stealable), "
              f"{fails} failure markers")
        if st["expired_leases"]:
            for it in q.pending():
                lease = q.read_lease(it.request_id)
                if lease is not None:
                    print(f"  EXPIRED: {it.request_id} held by "
                          f"{lease.get('worker', '?')}")

    # -- queue-depth timeline from manifests alone
    line = queue_depth_timeline(results, max_points=args.timeline_points)
    if line:
        peak = max(d for _, d in line)
        print(f"\nqueue depth timeline (from manifests; peak {peak}):")
        width = 40
        for t, d in line:
            bar = "#" * int(width * d / max(peak, 1))
            print(f"  t+{t:8.2f}s {d:>4d} {bar}")

    # -- lifecycle completeness (when spans are available)
    if view["spans"]:
        lr = lifecycle_report(view["spans"], results)
        print(f"\nlifecycle traces: {lr['complete']}/{lr['traces']} "
              f"complete ({lr['compile_traces']} compile, "
              f"{lr['cache_hit_traces']} cache-hit), "
              f"{lr['manifests_matched']}/{lr['manifests_with_trace']} "
              f"manifests matched to a complete trace")
        for p in lr["manifest_problems"][:10]:
            print(f"  INCOMPLETE: {p}")
        if not lr["ok"]:
            rc = 1

    # -- SLO budget status (burning -> nonzero exit, mirroring
    # `diag quality`'s divergence verdict)
    specs = {}
    if args.slo:
        specs = load_slo_specs(args.slo)
    if specs:
        evals = evaluate_results(specs, results)
        print("\nSLO budget status:")
        print(format_slo_report(evals))
        for ev in evals:
            if ev["burning"]:
                print(f"SLO BURNING: tenant {ev['tenant']} burn rates "
                      f"{['%.2f' % b for b in ev['burn_rates']]} over "
                      f"windows {ev['windows_s']}s", file=sys.stderr)
                rc = 1

    # -- bench trend over the last K history rows
    hist = read_bench_history(args.bench_history)
    if hist:
        trend = bench_trend(hist, last_k=args.last_k)
        print(f"\nbench trend (last {args.last_k} comparable of "
              f"{len(hist)} runs):")
        print(format_bench_trend(trend))
        # surface what the evidence filter dropped: silence here is how
        # CPU-fallback rows used to pass as TPU trend
        from sagecal_tpu.obs.evidence import comparable, record_evidence

        ev_new = record_evidence(hist[-1])
        fp = hist[-1].get("config_fingerprint")
        excluded = sum(1 for r in hist
                       if r.get("config_fingerprint") == fp
                       and not comparable(record_evidence(r), ev_new))
        if excluded:
            print(f"(evidence filter: {excluded} same-config rows "
                  f"excluded — evidence class differs from newest "
                  f"[{ev_new}])")

    if args.report:
        doc = {
            "requests": len(results),
            "snapshots": view["snapshots"],
            "tenants": {
                t: {
                    "n": len(by_tenant.get(t, [])),
                    "ok": sum(1 for r in by_tenant.get(t, [])
                              if r.get("verdict") == "ok"),
                }
                for t in tenants
            },
            "cache": {"hits": hits, "misses": misses},
            "slo": evaluate_results(specs, results) if specs else [],
            "exit": rc,
        }
        with open(args.report, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=True, default=float)
            f.write("\n")
        print(f"\nreport -> {args.report}")
    print("\nSERVE: " + ("UNHEALTHY" if rc else "OK"))
    return rc


def _cmd_load(args) -> int:
    """Load/capacity report of one ``sagecal-tpu load`` out-dir:
    curve + knee + shed + Little's-law cross-check + recommendation.
    Exit 1 on a missing/invalid timeline or a failed cross-check."""
    from sagecal_tpu.obs.capacity import (
        analyze_load_run, format_load_report,
    )
    from sagecal_tpu.obs.slo import load_slo_specs
    from sagecal_tpu.obs.timeline import (
        read_timeline, timeline_path, validate_timeline,
    )

    out_dir = args.out_dir
    specs = {}
    slo = args.slo or os.path.join(out_dir, "workload", "slo.json")
    if os.path.exists(slo):
        specs = load_slo_specs(slo)
    rc = 0
    rows = read_timeline(timeline_path(out_dir))
    problems = validate_timeline(rows)
    if problems:
        print(f"timeline {timeline_path(out_dir)}: INVALID",
              file=sys.stderr)
        for p in problems[:10]:
            print(f"  {p}", file=sys.stderr)
        rc = 1
    try:
        report = analyze_load_run(
            out_dir, specs, knee_tol=args.knee_tol,
            littles_rtol=args.littles_rtol,
            littles_atol=args.littles_atol)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"{out_dir}: {e}", file=sys.stderr)
        return 1
    print(format_load_report(report))
    if not report["littles_law"]["ok"]:
        print("LITTLES-LAW CROSS-CHECK FAILED: live timeline, "
              "post-hoc reconstruction and λW disagree beyond "
              "tolerance", file=sys.stderr)
        rc = 1
    if report["reconcile"].get("comparable") \
            and not report["reconcile"]["ok"]:
        print("LIVE/POST-HOC DEPTH MISMATCH: the two queue-depth "
              "views disagree beyond tolerance", file=sys.stderr)
        rc = 1
    if args.report:
        with open(args.report, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True,
                      default=float)
            f.write("\n")
        print(f"report -> {args.report}")
    print("LOAD: " + ("UNHEALTHY" if rc else "OK"))
    return rc


def _cmd_drift(args) -> int:
    """Numerical-drift report of serve/fleet out-dirs: per-(path-pair,
    bucket, dtype) shadow-audit distributions with provable quantile
    bounds against the central tolerance policy.  Exit 1 on any
    tolerance breach or structural ledger problem; exit 0 with a
    warning when no samples exist (shadow auditing off)."""
    from sagecal_tpu.obs.drift import analyze_drift, format_drift_report
    from sagecal_tpu.obs.shadow import (
        drift_path, read_drift, validate_drift,
    )

    rows = []
    for d in args.out_dir:
        path = d if os.path.isfile(d) else drift_path(d)
        rows.extend(read_drift(path))
    rows.sort(key=lambda r: float(r.get("ts", 0.0)))
    rc = 0
    problems = validate_drift(rows) if rows else []
    if problems:
        print("drift ledger: INVALID", file=sys.stderr)
        for p in problems[:10]:
            print(f"  {p}", file=sys.stderr)
        rc = 1
    report = analyze_drift(rows, validate_problems=problems)
    for line in format_drift_report(report):
        print(line)
    if report["n_exceeded"]:
        rc = 1
    if args.report:
        with open(args.report, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True,
                      default=float)
            f.write("\n")
        print(f"report -> {args.report}")
    if not rows:
        # no samples is WARN-not-fail: a rate-0 run has nothing to
        # gate, and failing would force shadow auditing on everyone
        print("DRIFT: NO SAMPLES (warn)")
        return 0
    print("DRIFT: " + ("EXCEEDED" if rc else "OK"))
    return rc


def _cmd_trace(args) -> int:
    from sagecal_tpu.obs.trace import (
        format_trace_report,
        read_spans,
        write_chrome_trace,
    )

    try:
        spans = read_spans(args.file)
    except OSError as e:
        print(f"{args.file}: {e}", file=sys.stderr)
        return 1
    if not spans:
        print(f"{args.file}: no spans (was the run SAGECAL_TRACE=1?)",
              file=sys.stderr)
        return 1
    print(format_trace_report(spans, ratio_thresh=args.straggler_ratio))
    if args.chrome:
        p = write_chrome_trace(spans, args.chrome)
        print(f"chrome trace -> {p}")
    return 0


def _cmd_flight(args) -> int:
    from sagecal_tpu.obs.flight import format_dump, read_dump

    try:
        doc = read_dump(args.file)
    except (OSError, json.JSONDecodeError) as e:
        print(f"{args.file}: {e}", file=sys.stderr)
        return 1
    if not isinstance(doc, dict) or "reason" not in doc:
        print(f"{args.file}: not a flight-recorder dump", file=sys.stderr)
        return 1
    print(format_dump(doc, ring_tail=args.ring_tail))
    return 0


def _cmd_lint(args) -> int:
    # the jaxlint package is import-light by design (stdlib ast only):
    # deferring keeps `diag manifest` usable before backend selection
    from sagecal_tpu.analysis.cli import main as lint_main

    return lint_main(args.lint_args)


def _cmd_kernelcheck(args) -> int:
    # lazy: the checker is stdlib-only unless --crosscheck asks for a
    # compiled memory_analysis() comparison (which imports jax)
    from sagecal_tpu.analysis.kernel_check import main as kc_main

    return kc_main(args.kernelcheck_args)


def _cmd_protocol(args) -> int:
    """Exhaustively model-check the fleet lease/stream protocols
    (real queue + owner-lease code over the simulated fs).  Exit 0
    when every invariant holds on every reachable state, 1 on any
    violation (with the shortest counterexample trace printed)."""
    from sagecal_tpu.analysis.protocol_check import run_protocol_check

    report = run_protocol_check(
        workers=args.workers, crash_budget=args.crashes,
        tick_budget=args.ticks, deadline_s=args.deadline,
        log=print)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    if not report["ok"]:
        return 1
    return 0


def _cmd_audit(args) -> int:
    """Event-sourced fleet audit: schema-registry validation, replay,
    conservation-law checks.  Exit 0 clean / 1 violation or gap / 2
    insufficient records (nothing to conserve)."""
    from sagecal_tpu.obs.audit import format_audit, run_audit

    report = run_audit(
        args.out_dir, events_path=args.events, queue_dir=args.queue,
        max_skew_s=args.max_skew, slack_s=args.slack,
        inject=args.inject)
    if args.json:
        print(json.dumps(report.to_doc(), indent=2, sort_keys=True))
    else:
        print(format_audit(report, verbose=args.verbose))
    if args.report:
        with open(args.report, "w") as f:
            json.dump(report.to_doc(), f, indent=2, sort_keys=True)
        print(f"audit report -> {args.report}")
    return report.exit_code()


def _cmd_replay(args) -> int:
    """Deterministic fleet replay from records alone (no live state).
    Exit 2 when there is nothing to replay."""
    from sagecal_tpu.obs.replay import format_replay, load_run, replay

    rec = load_run(args.out_dir, events_path=args.events,
                   queue_dir=args.queue)
    if not rec.items and not rec.manifests and not rec.events:
        print(f"{args.out_dir}: no replayable records "
              "(no queue items, manifests, or events)", file=sys.stderr)
        return 2
    state = replay(rec)
    if args.json:
        print(json.dumps(state.to_doc(), indent=2, sort_keys=True))
    else:
        print(format_replay(state, verbose=args.verbose))
    if args.report:
        with open(args.report, "w") as f:
            json.dump(state.to_doc(), f, indent=2, sort_keys=True)
        print(f"replay state -> {args.report}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="sagecal-tpu diag",
        description="observability diagnostics (manifests, event logs, "
                    "Prometheus export)",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    mp = sub.add_parser("manifest", help="collect + print a run manifest")
    mp.add_argument("--out", default=None, help="write JSON here instead of stdout")
    mp.add_argument("--kernel-path", default="xla", choices=("xla", "fused"))
    mp.set_defaults(fn=_cmd_manifest)

    vp = sub.add_parser("validate", help="validate a manifest JSON / event log")
    vp.add_argument("file")
    vp.set_defaults(fn=_cmd_validate)

    ep = sub.add_parser("events", help="summarize a JSONL event log")
    ep.add_argument("file")
    ep.set_defaults(fn=_cmd_events)

    pp = sub.add_parser("prom", help="Prometheus text dump of the registry")
    pp.add_argument("--events", default=None,
                    help="re-ingest phase timings from this event log first")
    pp.set_defaults(fn=_cmd_prom)

    fp = sub.add_parser(
        "perf", help="per-function compile/flops/bytes/memory attribution")
    fp.add_argument("path",
                    help="JSONL event log, or a run directory of *.jsonl")
    fp.set_defaults(fn=_cmd_perf)

    gp = sub.add_parser("gate", help="bench regression gate vs a baseline")
    gp.add_argument("new", help="fresh bench JSON record")
    gp.add_argument("--baseline", required=True,
                    help="pinned baseline bench JSON record")
    gp.add_argument("--tol", type=float, default=GATE_DEFAULT_TOLERANCE,
                    help="default relative tolerance (default 0.10)")
    gp.add_argument("--metric", action="append", default=None,
                    metavar="NAME=TOL",
                    help="gate an extra metric (repeatable), e.g. "
                         "analytic_tflops_per_sec=0.15")
    gp.add_argument("--strict", action="store_true",
                    help="compare even across an evidence-class mismatch")
    gp.set_defaults(fn=_cmd_gate)

    rp = sub.add_parser(
        "roofline",
        help="per-kernel-family roofline attribution from a device-"
             "profile trace (devprof capture)",
    )
    rp.add_argument("trace",
                    help="a *.trace.json[.gz] file, or a capture dir "
                         "(newest trace under it is used)")
    rp.add_argument("--events", default=None,
                    help="JSONL event log whose jit_compile events "
                         "supply the flops/bytes ledger for MFU/BW-util")
    rp.add_argument("--device-kind", default=None,
                    help="override the device kind (default: the live "
                         "jax.devices()[0].device_kind)")
    rp.add_argument("--dtype", default="bf16",
                    help="peak-table dtype column (default bf16)")
    rp.add_argument("--gap-threshold-us", type=float, default=1000.0,
                    help="host gap (us) splitting device busy windows "
                         "for the dispatch analysis (default 1000)")
    rp.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    rp.set_defaults(fn=_cmd_roofline)

    evp = sub.add_parser(
        "evidence",
        help="evidence-class ledger: which banked claims are wall-"
             "clock-proven vs AOT-proven (exit 1 on any unclassified)",
    )
    evp.add_argument("record", nargs="?", default="BENCH_BASELINE.json",
                     help="bench record / baseline JSON (default "
                          "BENCH_BASELINE.json)")
    evp.add_argument("--history", default=None,
                     help="also audit this BENCH_HISTORY.jsonl for "
                          "unclassified rows")
    evp.set_defaults(fn=_cmd_evidence)

    sp = sub.add_parser(
        "serve",
        help="fleet serve report: latency/SLO/cache/lifecycle across "
             "worker out-dirs (exit 1 on burning SLO)",
    )
    sp.add_argument("out_dir", nargs="+",
                    help="serve --out-dir(s): result manifests + "
                         "metrics-*.json worker snapshots")
    sp.add_argument("--events", action="append", default=None,
                    metavar="FILE_OR_DIR",
                    help="JSONL event log(s) to fold in (repeatable)")
    sp.add_argument("--spans", action="append", default=None,
                    metavar="FILE_OR_DIR",
                    help="span JSONL(s) from SAGECAL_TRACE runs "
                         "(repeatable); enables lifecycle completeness "
                         "audit")
    sp.add_argument("--slo", default="",
                    help="slo.json (or request manifest with a 'slos' "
                         "key); burning tenant -> exit 1")
    sp.add_argument("--bench-history", default=None,
                    help="BENCH_HISTORY.jsonl (default: "
                         "$SAGECAL_BENCH_HISTORY or ./BENCH_HISTORY.jsonl)")
    sp.add_argument("--last-k", type=int, default=5,
                    help="bench-trend window (default 5)")
    sp.add_argument("--timeline-points", type=int, default=24,
                    help="max rows in the queue-depth timeline")
    sp.add_argument("--report", default=None,
                    help="also write a machine-readable JSON report")
    sp.set_defaults(fn=_cmd_serve)

    ldp = sub.add_parser(
        "load",
        help="load/capacity report: throughput-vs-offered curve, "
             "saturation knee, shed rate, Little's-law cross-check, "
             "autoscale recommendation (exit 1 on disagreement)",
    )
    ldp.add_argument("out_dir",
                     help="a `sagecal-tpu load` --out-dir (manifests "
                          "+ timeline.jsonl + load_steps.json)")
    ldp.add_argument("--slo", default="",
                     help="slo.json for goodput deadlines (default "
                          "<out_dir>/workload/slo.json)")
    ldp.add_argument("--knee-tol", type=float, default=0.10,
                     help="throughput this fraction below offered = "
                          "saturated (default 0.10)")
    ldp.add_argument("--littles-rtol", type=float, default=0.35,
                     help="relative tolerance of the L = λW "
                          "cross-check (default 0.35)")
    ldp.add_argument("--littles-atol", type=float, default=1.0,
                     help="absolute depth slack of the cross-check "
                          "(default 1.0 items)")
    ldp.add_argument("--report", default=None,
                     help="also write the machine-readable JSON "
                          "report here")
    ldp.set_defaults(fn=_cmd_load)

    dp = sub.add_parser(
        "drift",
        help="numerical-drift report from shadow-audit ledgers: "
             "per-(path-pair, bucket, dtype) distributions vs the "
             "central tolerance policy (exit 1 on any breach; exit 0 "
             "+ warning when no samples)",
    )
    dp.add_argument("out_dir", nargs="+",
                    help="serve/fleet --out-dir(s) holding drift.jsonl "
                         "(a ledger file path also works)")
    dp.add_argument("--report", default=None,
                    help="also write the machine-readable JSON report")
    dp.set_defaults(fn=_cmd_drift)

    aup = sub.add_parser(
        "audit",
        help="event-sourced fleet audit: schema-registry validation, "
             "deterministic replay, conservation-law gating (exit 1 "
             "on violation/gap, 2 on insufficient records)",
    )
    aup.add_argument("out_dir",
                     help="a fleet/load/serve --out-dir (queue/ + "
                          "manifests + sagecal_events.jsonl + "
                          "timeline.jsonl)")
    aup.add_argument("--events", default=None,
                     help="event log override (default "
                          "<out_dir>/sagecal_events.jsonl)")
    aup.add_argument("--queue", default=None,
                     help="queue dir override (default <out_dir>/queue)")
    aup.add_argument("--max-skew", type=float, default=30.0,
                     help="max tolerated per-writer clock offset, "
                          "seconds (default 30)")
    aup.add_argument("--slack", type=float, default=3.0,
                     help="timing slack for lease/timeline checks, "
                          "seconds (default 3)")
    aup.add_argument("--inject", default=None,
                     choices=("drop_event", "tear_record",
                              "forge_manifest", "skew_clock"),
                     help="inject an in-memory fault to prove the "
                          "detector (also: SAGECAL_AUDIT_INJECT)")
    aup.add_argument("--json", action="store_true",
                     help="print the full report as JSON")
    aup.add_argument("--report", default=None,
                     help="also write the machine-readable JSON report")
    aup.add_argument("-V", "--verbose", action="store_true",
                     help="list every violation and per-writer detail")
    aup.set_defaults(fn=_cmd_audit)

    rpp = sub.add_parser(
        "replay",
        help="deterministic fleet replay from records alone: queue "
             "state, request dispositions, worker lifecycle, clock "
             "offsets, SLO attainment (exit 2 when nothing to replay)",
    )
    rpp.add_argument("out_dir",
                     help="a fleet/load/serve --out-dir")
    rpp.add_argument("--events", default=None,
                     help="event log override (default "
                          "<out_dir>/sagecal_events.jsonl)")
    rpp.add_argument("--queue", default=None,
                     help="queue dir override (default <out_dir>/queue)")
    rpp.add_argument("--json", action="store_true",
                     help="print the replayed state as JSON")
    rpp.add_argument("--report", default=None,
                     help="also write the replayed state JSON here")
    rpp.add_argument("-V", "--verbose", action="store_true",
                     help="per-request and per-writer detail")
    rpp.set_defaults(fn=_cmd_replay)

    qp = sub.add_parser(
        "quality",
        help="calibration-quality report + chi^2 heatmaps from an event log",
    )
    qp.add_argument("file", help="JSONL event log of a telemetry run")
    qp.add_argument("--out-dir", default=None,
                    help="directory for the PPM heatmaps + JSON report "
                         "(default: alongside the event log)")
    qp.add_argument("--trend-thresh", type=float, default=2.0,
                    help="ADMM primal-residual growth treated as "
                         "divergence (default 2.0)")
    qp.add_argument("--fail-degraded", action="store_true",
                    help="exit non-zero on degradation too, not just "
                         "divergence")
    qp.set_defaults(fn=_cmd_quality)

    tp = sub.add_parser(
        "trace",
        help="span-tree report + straggler table from a span JSONL",
    )
    tp.add_argument("file", help="span JSONL (SAGECAL_TRACE_LOG)")
    tp.add_argument("--chrome", default=None, metavar="OUT",
                    help="also write a Perfetto-loadable trace.json here")
    tp.add_argument("--straggler-ratio", type=float, default=None,
                    help="slowest/median detection threshold (default "
                         "SAGECAL_STRAGGLER_RATIO or 1.5)")
    tp.set_defaults(fn=_cmd_trace)

    dp = sub.add_parser(
        "flight",
        help="render a flight-recorder dump (hang/crash forensics)",
    )
    dp.add_argument("file", help="flight_dump.json from a stall or crash")
    dp.add_argument("--ring-tail", type=int, default=20,
                    help="activity-ring entries to show (default 20)")
    dp.set_defaults(fn=_cmd_flight)

    lp = sub.add_parser(
        "lint",
        help="jaxlint static-analysis gate (JL001-JL015 + JL900)",
    )
    lp.add_argument("lint_args", nargs=argparse.REMAINDER,
                    help="arguments forwarded to jaxlint "
                         "(paths, --format, --baseline, --rules, ...); "
                         "default lints the installed sagecal_tpu")
    lp.set_defaults(fn=_cmd_lint)

    kcp = sub.add_parser(
        "kernelcheck",
        help="kernel contract checker: VMEM budgets, grid coverage, "
             "table freshness, JL013-JL015 (exit 1 on violation)",
    )
    kcp.add_argument("kernelcheck_args", nargs=argparse.REMAINDER,
                     help="arguments forwarded to kernel_check "
                          "(--json, --crosscheck, --backend, --table, "
                          "--no-table-check)")
    kcp.set_defaults(fn=_cmd_kernelcheck)

    pcp = sub.add_parser(
        "protocol",
        help="model-check the fleet lease + stream owner-lease "
             "protocols (exhaustive interleavings, crash injection)",
    )
    pcp.add_argument("--workers", type=int, default=2,
                     help="logical queue workers to interleave "
                          "(default 2 = exhaustive in seconds)")
    pcp.add_argument("--crashes", type=int, default=1,
                     help="crash injections per schedule (default 1)")
    pcp.add_argument("--ticks", type=int, default=2,
                     help="clock advances per schedule (default 2)")
    pcp.add_argument("--deadline", type=float, default=55.0,
                     help="per-scenario exploration deadline seconds")
    pcp.add_argument("--json", action="store_true",
                     help="print the full report as JSON")
    pcp.set_defaults(fn=_cmd_protocol)
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    # argparse REMAINDER cannot capture a leading option (bpo-17050:
    # `diag lint --format json ...` dies in the TOP-level parser), so
    # the pass-through subcommands forward by hand
    if argv and argv[0] == "lint":
        from sagecal_tpu.analysis.cli import main as lint_main
        return lint_main(argv[1:])
    if argv and argv[0] == "kernelcheck":
        from sagecal_tpu.analysis.kernel_check import main as kc_main
        return kc_main(argv[1:])
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
