"""Cross-path numerical-drift ledger analysis: distributions, watchdog.

:mod:`sagecal_tpu.obs.shadow` produces the raw material — one JSONL
record per shadow-audited request.  This module is everything that
happens with those records:

- :func:`check_drift` — the in-process hook the auditor calls per
  record: refresh the ``sagecal_drift_*`` gauges, count watchdog
  escalations, and emit ``shadow_drift_check`` / ``drift_exceeded``
  events into the quality stream.  Drift is degraded-not-diverged and
  report-only by default; ``--abort-on-drift`` escalation is the
  app's decision (serve/service.py), exactly like
  ``abort_on_divergence``.
- :func:`aggregate_drift` — fold records into per-(path-pair, bucket,
  dtype) :class:`~sagecal_tpu.obs.registry._Histogram` distributions,
  reusing the registry's merge/quantile-bounds machinery so reports
  state PROVABLE quantile intervals, not point estimates (the load
  bench discipline).
- :func:`analyze_drift` + :func:`format_drift_report` — the ``diag
  drift`` backend: per-group distribution table with p50/p99 bounds,
  tolerance-policy echo, breach list, sampling honesty (budget skips).

Import-light (stdlib + numpy): ``diag drift`` reads ledgers on
machines without jax.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from sagecal_tpu.obs.registry import _Histogram, get_registry
from sagecal_tpu.obs.shadow import lookup_tolerances

#: log-spaced relative-error buckets shared by every drift histogram —
#: one fixed layout so shards from different workers merge (the
#: _Histogram contract), spanning f64 dust (1e-12) through order-unity
#: disagreement
DRIFT_HIST_BUCKETS = (
    1e-12, 1e-10, 1e-8, 1e-7, 1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0,
)

#: the ledger metrics that get a distribution per group
DRIFT_METRICS = ("cost_rel_delta", "gain_rel_err_max", "chi2_rel_delta")


def check_drift(elog, record: dict, log=None) -> Tuple[str, List[str]]:
    """The per-record watchdog hook (mirrors ``check_hier_predict``):
    gauges, escalation counter, and the event-stream record.

    ``record`` is the ledger row the auditor just appended (verdict
    already decided by the tolerance policy).  Emits a
    ``shadow_drift_check`` event always and a ``drift_exceeded`` event
    on breach; a drifted path never DIVERGES a run on its own (the
    production solve watchdog owns that verdict — drift escalation to
    an abort is the app's ``--abort-on-drift`` opt-in)."""
    verdict = str(record.get("verdict", "ok"))
    reasons = list(record.get("reasons") or [])
    pair = str(record.get("path_pair", ""))

    reg = get_registry()
    labels = {"path_pair": pair}
    cost = record.get("cost_rel_delta")
    if cost is not None:
        reg.gauge_set("sagecal_drift_cost_rel_delta", float(cost),
                      help="final-cost relative delta of the latest "
                           "shadow audit, production vs reference path",
                      **labels)
    gain = record.get("gain_rel_err_max")
    if gain is not None:
        reg.gauge_set("sagecal_drift_gain_rel_err", float(gain),
                      help="max per-station gain relative error of the "
                           "latest shadow audit", **labels)
    chi2 = record.get("chi2_rel_delta")
    if chi2 is not None:
        reg.gauge_set("sagecal_drift_chi2_rel_delta", float(chi2),
                      help="total chi^2 relative delta of the latest "
                           "shadow audit", **labels)
    reg.counter_inc("sagecal_drift_audits_total", verdict=verdict,
                    path_pair=pair,
                    help="shadow audits completed, by verdict")
    if verdict != "ok":
        reg.counter_inc("sagecal_quality_watchdog_total",
                        help="watchdog escalations", verdict="degraded")

    if elog is not None:
        elog.emit("shadow_drift_check", verdict=verdict, reasons=reasons,
                  request_id=record.get("request_id"),
                  path_pair=pair, bucket=record.get("bucket"),
                  kernel_path=record.get("kernel_path"),
                  cost_rel_delta=cost, gain_rel_err_max=gain,
                  chi2_rel_delta=chi2)
        if verdict != "ok":
            elog.emit("drift_exceeded", reasons=reasons,
                      request_id=record.get("request_id"),
                      path_pair=pair, bucket=record.get("bucket"))
    if log is not None and verdict != "ok":
        log(f"drift watchdog: {verdict} [{pair}] "
            f"({', '.join(reasons)})")
    return verdict, reasons


# ---------------------------------------------------------- aggregation


def _group_key(row: dict) -> Tuple[str, str, str]:
    return (str(row.get("path_pair", "?")),
            str(row.get("bucket", "?")),
            str(row.get("solver_dtype", "?")))


def aggregate_drift(rows: Sequence[dict]) -> Dict[tuple, dict]:
    """Fold ledger records into per-(path_pair, bucket, solver dtype)
    groups, each carrying one :class:`_Histogram` per drift metric plus
    verdict counts and the exact observed maxima (the quantile bounds
    tighten against the observed extremes, so the sampled max always
    lies inside the reported p99 interval — pinned in tests)."""
    groups: Dict[tuple, dict] = {}
    for row in rows:
        g = groups.setdefault(_group_key(row), {
            "n": 0, "exceeded": 0,
            "hist": {m: _Histogram(DRIFT_HIST_BUCKETS)
                     for m in DRIFT_METRICS},
            "max": {m: None for m in DRIFT_METRICS},
            "shadow_s": 0.0,
        })
        g["n"] += 1
        if row.get("verdict") == "drift_exceeded":
            g["exceeded"] += 1
        g["shadow_s"] += float(row.get("shadow_s", 0.0) or 0.0)
        for m in DRIFT_METRICS:
            v = row.get(m)
            if v is None or not np.isfinite(float(v)):
                continue
            v = float(v)
            g["hist"][m].observe(v)
            g["max"][m] = v if g["max"][m] is None else max(g["max"][m], v)
    return groups


def drift_quantiles(groups: Dict[tuple, dict],
                    qs=(0.5, 0.99)) -> Dict[tuple, dict]:
    """Provable quantile-bound intervals per group/metric:
    ``{group: {metric: {"p50": (lo, hi), "p99": (lo, hi), ...}}}``."""
    out: Dict[tuple, dict] = {}
    for key, g in groups.items():
        out[key] = {}
        for m, h in g["hist"].items():
            if h.count == 0:
                continue
            out[key][m] = {
                f"p{int(q * 100)}": h.quantile_bounds(q) for q in qs}
    return out


# -------------------------------------------------------------- reports


def analyze_drift(rows: Sequence[dict],
                  validate_problems: Optional[List[str]] = None) -> dict:
    """Build the ``diag drift`` report from a ledger's records."""
    groups = aggregate_drift(rows)
    quant = drift_quantiles(groups)
    breaches = [
        {"request_id": r.get("request_id"),
         "path_pair": r.get("path_pair"), "bucket": r.get("bucket"),
         "reasons": r.get("reasons") or []}
        for r in rows if r.get("verdict") == "drift_exceeded"
    ]
    report = {
        "n_records": len(rows),
        "n_exceeded": len(breaches),
        "breaches": breaches,
        "groups": [
            {
                "path_pair": key[0], "bucket": key[1], "dtype": key[2],
                "n": g["n"], "exceeded": g["exceeded"],
                "shadow_s": g["shadow_s"],
                "max": dict(g["max"]),
                "quantiles": {
                    m: {p: list(b) for p, b in qb.items()
                        if b is not None}
                    for m, qb in quant.get(key, {}).items()},
                "tolerances": lookup_tolerances(key[0]),
            }
            for key, g in sorted(groups.items())
        ],
        "problems": list(validate_problems or []),
    }
    return report


def format_drift_report(report: dict) -> List[str]:
    """Human-readable ``diag drift`` lines."""
    lines: List[str] = []
    if report["n_records"] == 0:
        lines.append("drift: no samples (shadow auditing off or "
                     "nothing sampled yet) — nothing to gate")
        return lines
    lines.append(f"drift: {report['n_records']} shadow audit(s), "
                 f"{report['n_exceeded']} over tolerance")
    for g in report["groups"]:
        lines.append(f"  {g['path_pair']}  bucket={g['bucket']}  "
                     f"dtype={g['dtype']}  n={g['n']}  "
                     f"exceeded={g['exceeded']}  "
                     f"shadow={g['shadow_s']:.2f}s")
        for m in DRIFT_METRICS:
            qb = g["quantiles"].get(m)
            if not qb:
                continue
            tol = g["tolerances"].get(m)
            parts = [f"    {m:<18s} max={g['max'][m]:.3e}"]
            for p, (lo, hi) in sorted(qb.items()):
                parts.append(f"{p}∈[{lo:.1e},{hi:.1e}]")
            parts.append(f"tol={tol:.1e}" if tol is not None else "")
            lines.append("  ".join(x for x in parts if x))
    for b in report["breaches"]:
        lines.append(f"  BREACH {b['request_id']} [{b['path_pair']}]: "
                     + "; ".join(map(str, b["reasons"])))
    for p in report["problems"]:
        lines.append(f"  problem: {p}")
    return lines
