"""Run manifests and the JSONL event log.

Every app run can emit a structured, append-only event stream: one JSON
object per line, first line a :class:`RunManifest` snapshot (platform,
device kind, precision config, kernel path), then per-tile / per-round
events (phase timings, convergence records, ADMM residual traces, bench
outcomes).  The log is plain JSONL so it greps/joins with standard
tools and round-trips losslessly through :func:`read_events`.

Everything here is host-side and host-callback-free: jitted solver code
returns telemetry as auxiliary pytree outputs (obs/records.py) and the
app feeds them to an :class:`EventLog` after the solve returns.

Enable with ``SAGECAL_TELEMETRY=1``; pick the path with
``SAGECAL_EVENT_LOG=/path/to/run.jsonl`` (default
``./sagecal_events.jsonl``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time
import uuid
from typing import Any, Dict, Iterator, List, Optional

SCHEMA_VERSION = 1

# manifest keys that must be present for a manifest to validate
_REQUIRED_MANIFEST_KEYS = (
    "schema_version", "run_id", "platform", "device_kind", "num_devices",
    "jax_version", "jaxlib_version", "x64_enabled",
)


def writer_identity() -> str:
    """This process's stable writer identity, stamped on every emitted
    record so the offline auditor (obs/ledger.py) can attribute lines
    in a shared O_APPEND file to their writer and detect per-writer
    sequence holes.  ``<worker>@<pid>``: the fleet worker name when
    ``SAGECAL_WORKER_ID`` is set (coordinator-spawned workers), else a
    pid-derived stand-in.  The part before ``@`` is the writer's clock
    domain (one wall clock per process; a respawned worker is a new
    domain instance but shares the worker-name prefix)."""
    wid = os.environ.get("SAGECAL_WORKER_ID", "").strip()
    pid = os.getpid()
    return f"{wid or 'p%d' % pid}@{pid}"


def _jsonable(x):
    """Best-effort conversion of numpy/jax scalars and arrays to plain
    JSON types (events must never fail to serialize)."""
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    # numpy / jax array-likes (incl. 0-d scalars) — no hard dependency
    # on either package at import time
    item = getattr(x, "item", None)
    tolist = getattr(x, "tolist", None)
    try:
        if tolist is not None and getattr(x, "ndim", 0) > 0:
            return _jsonable(tolist())
        if item is not None:
            return _jsonable(item())
    except Exception:
        pass
    return repr(x)


@dataclasses.dataclass
class RunManifest:
    """What ran, where, and how — the header record of every event log.

    ``collect()`` is tolerant of a broken accelerator plugin: a backend
    query failure is RECORDED (``backend_error`` set, device fields
    "unknown") instead of raised, so the manifest survives exactly the
    failure modes it exists to document (axon probe failures, CPU
    fallbacks)."""

    schema_version: int = SCHEMA_VERSION
    run_id: str = ""
    created_unix: float = 0.0
    argv: List[str] = dataclasses.field(default_factory=list)
    pid: int = 0
    platform: str = "unknown"
    device_kind: str = "unknown"
    num_devices: int = 0
    jax_version: str = "unknown"
    jaxlib_version: str = "unknown"
    x64_enabled: bool = False
    kernel_path: str = "xla"  # "xla" | "fused"
    backend_error: Optional[str] = None
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @classmethod
    def collect(cls, kernel_path: str = "xla", run_id: Optional[str] = None,
                **extra) -> "RunManifest":
        m = cls(
            run_id=run_id or uuid.uuid4().hex[:12],
            created_unix=time.time(),
            argv=list(sys.argv),
            pid=os.getpid(),
            kernel_path=kernel_path,
            env={
                k: v for k, v in os.environ.items()
                if k.startswith("SAGECAL_") or k in ("JAX_PLATFORMS",)
            },
            extra={k: _jsonable(v) for k, v in extra.items()},
        )
        try:
            import jax

            m.jax_version = jax.__version__
            try:
                import jaxlib

                m.jaxlib_version = jaxlib.__version__
            except Exception:
                pass
            m.x64_enabled = bool(jax.config.jax_enable_x64)
            devs = jax.devices()
            m.platform = devs[0].platform if devs else "none"
            m.device_kind = devs[0].device_kind if devs else "none"
            m.num_devices = len(devs)
        except Exception as e:  # wedged/failed backend: record, don't raise
            m.backend_error = f"{type(e).__name__}: {e}"
        return m

    def to_dict(self) -> dict:
        return _jsonable(dataclasses.asdict(self))


def validate_manifest(d: dict) -> List[str]:
    """Return a list of problems (empty = valid manifest dict)."""
    problems = []
    for k in _REQUIRED_MANIFEST_KEYS:
        if k not in d:
            problems.append(f"missing key: {k}")
    if d.get("schema_version") not in (None, SCHEMA_VERSION):
        problems.append(
            f"schema_version {d.get('schema_version')} != {SCHEMA_VERSION}"
        )
    if "num_devices" in d and not isinstance(d["num_devices"], int):
        problems.append("num_devices not an int")
    return problems


class EventLog:
    """Append-only JSONL event sink.

    Each :meth:`emit` writes one line ``{"ts": ..., "run_id": ...,
    "type": <type>, ...fields}`` as a SINGLE ``os.write`` on an
    ``O_APPEND`` fd — POSIX appends of one buffer never interleave, so
    concurrent writers (multi-process distributed / multihost runs)
    sharing one file cannot corrupt each other's lines.  There is no
    userspace buffering, so a crashed run keeps every event up to the
    crash.  Usable as a context manager."""

    def __init__(self, path: str, run_id: Optional[str] = None,
                 manifest: Optional[RunManifest] = None):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._fd: Optional[int] = os.open(
            path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
        if manifest is not None and not manifest.run_id:
            manifest.run_id = uuid.uuid4().hex[:12]
        self.run_id = run_id or (
            manifest.run_id if manifest is not None else uuid.uuid4().hex[:12]
        )
        self.writer = writer_identity()
        self._seq = 0
        if manifest is not None:
            self.emit("run_manifest", **manifest.to_dict())

    @property
    def closed(self) -> bool:
        return self._fd is None

    def emit(self, type: str, **fields) -> None:
        fd = self._fd
        if fd is None:
            return
        rec = {"ts": time.time(), "run_id": self.run_id, "type": type}
        for k, v in fields.items():
            if k not in rec:
                rec[k] = _jsonable(v)
        # audit stamps go LAST so the byte layout existing consumers
        # key on (ts/run_id/type prefix, then caller fields) is
        # unchanged: writer identity + a per-writer sequence number
        # (hole detection) + a monotonic reading (ordering within a
        # writer survives wall-clock steps)
        if "writer" not in rec:
            rec["writer"] = self.writer
        if "mono" not in rec:
            rec["mono"] = time.monotonic()
        if "seq" not in rec:
            rec["seq"] = self._seq
            self._seq += 1
        os.write(fd, (json.dumps(rec) + "\n").encode("utf-8"))

    def close(self) -> None:
        fd, self._fd = self._fd, None
        if fd is not None:
            os.close(fd)

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_events(path: str) -> List[dict]:
    """Load every event of a JSONL log (skips blank/corrupt lines rather
    than failing — a killed run may leave a truncated last line)."""
    out = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def iter_events(path: str) -> Iterator[dict]:
    for e in read_events(path):
        yield e


def expand_event_paths(path: str) -> List[str]:
    """Resolve an event-log argument to the set of JSONL files it names:
    a directory expands to its ``*.jsonl`` files (plus per-process
    ``*.jsonl.<pid>`` siblings); a file expands to itself plus any
    ``<file>.<pid>`` companions written by
    ``SAGECAL_EVENT_LOG_PER_PROCESS=1`` runs."""
    import glob as _glob

    if os.path.isdir(path):
        out = sorted(_glob.glob(os.path.join(path, "*.jsonl")))
        out += sorted(p for p in _glob.glob(os.path.join(path, "*.jsonl.*"))
                      if p.rsplit(".", 1)[-1].isdigit())
        return out
    out = [path] if os.path.exists(path) else []
    out += sorted(p for p in _glob.glob(path + ".*")
                  if p.rsplit(".", 1)[-1].isdigit())
    return out


def read_events_merged(path: str) -> List[dict]:
    """Read + merge events from every file :func:`expand_event_paths`
    resolves, in stable timestamp order (the ``diag``-side merge for
    per-process suffixed logs)."""
    events: List[dict] = []
    for p in expand_event_paths(path):
        events.extend(read_events(p))
    events.sort(key=lambda e: float(e.get("ts", 0.0)))
    return events


def default_event_log(manifest: Optional[RunManifest] = None,
                      path: Optional[str] = None) -> Optional[EventLog]:
    """The app-side entry: an :class:`EventLog` at ``SAGECAL_EVENT_LOG``
    (or ``./sagecal_events.jsonl``) when telemetry is enabled, else
    None — callers guard every emit with ``if log is not None``.

    ``SAGECAL_EVENT_LOG_PER_PROCESS=1`` suffixes the path with the pid
    (one file per writer; ``diag events`` merges the companions) for
    multihost launchers that cannot share an O_APPEND fd safely, e.g.
    on network filesystems where append atomicity is not guaranteed."""
    from sagecal_tpu.obs.registry import _TRUTHY, telemetry_enabled

    if not telemetry_enabled():
        return None
    path = path or os.environ.get("SAGECAL_EVENT_LOG") or "sagecal_events.jsonl"
    per_proc = os.environ.get(
        "SAGECAL_EVENT_LOG_PER_PROCESS", "").strip().lower() in _TRUTHY
    if per_proc:
        path = f"{path}.{os.getpid()}"
    return EventLog(path, manifest=manifest)
