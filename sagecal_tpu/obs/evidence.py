"""Evidence classes: what KIND of proof stands behind each metric.

ROADMAP:34-36 complains in prose that every device-side gain since
PR 10 is "AOT-proven, not wall-clock-proven"; nothing machine-readable
distinguished the two, so a CPU-fallback bench row could be
trend-compared against round-5 TPU rows and silently pass.  This
module turns the complaint into a checked invariant:

- every bench record / baseline metric / history row carries an
  ``evidence`` class from :data:`EVIDENCE_CLASSES`, stamped at
  measurement time;
- ``diag gate`` and ``bench_trend`` call :func:`comparable` and REFUSE
  cross-evidence comparisons with an explicit message;
- ``diag evidence`` renders which headline claims are wall-clock-proven
  vs AOT-proven (:func:`proof_kind`).

Classes
-------
``tpu-wallclock``
    measured wall-clock on real TPU hardware — the only class that
    proves a speed claim end-to-end.
``cpu-wallclock``
    measured wall-clock on the CPU fallback — proves correctness and
    relative behaviour of the machinery, not device speed.
``aot-bytes``
    derived from XLA ``cost_analysis`` bytes/flops of an AOT-compiled
    program — proves the compiler *scheduled* less traffic, not that
    the device ran faster.
``aot-hlo``
    derived from inspecting compiled HLO structure (e.g. counting
    collective bytes per ADMM round) — the weakest class: proves shape
    of the program only.

``gpu-wallclock`` is reserved for the multi-backend arc (ROADMAP
item 5) and accepted everywhere classes are validated.

Stdlib-only: imported by diag paths that must not touch jax.
"""

from __future__ import annotations

from typing import Dict, Optional

EVIDENCE_CLASSES = ("tpu-wallclock", "cpu-wallclock", "gpu-wallclock",
                    "aot-bytes", "aot-hlo")

#: classes that prove a wall-clock claim (vs AOT/static proof)
WALLCLOCK_CLASSES = ("tpu-wallclock", "cpu-wallclock", "gpu-wallclock")


def is_valid(cls: Optional[str]) -> bool:
    return cls in EVIDENCE_CLASSES


def proof_kind(cls: Optional[str]) -> str:
    """"wall-clock-proven" | "AOT-proven" | "unclassified" — the
    vocabulary of ROADMAP:34-36, for ``diag evidence``."""
    if cls in WALLCLOCK_CLASSES:
        return "wall-clock-proven"
    if cls in EVIDENCE_CLASSES:
        return "AOT-proven"
    return "unclassified"


def wallclock_evidence(platform: Optional[str]) -> Optional[str]:
    """The wall-clock evidence class a timing measured on ``platform``
    earns (``jax.default_backend()`` strings), None when unknown."""
    if not platform:
        return None
    p = str(platform).lower()
    if p in ("tpu", "cpu", "gpu", "cuda", "rocm"):
        if p in ("cuda", "rocm"):
            p = "gpu"
        return f"{p}-wallclock"
    return None


def record_evidence(rec: dict) -> Optional[str]:
    """Resolve the record-level evidence class of a bench record or
    history row: an explicit ``evidence`` field wins, else derive the
    wall-clock class from ``platform``.  None when unresolvable —
    callers must treat None as *compatible with anything* (pre-v2 rows
    and synthetic test rows carry neither field)."""
    ev = rec.get("evidence")
    if is_valid(ev):
        return ev
    return wallclock_evidence(rec.get("platform"))


def metric_evidence(rec: dict, metric: str) -> Optional[str]:
    """Evidence class of one metric in a record: the per-metric
    ``evidence_classes`` override map wins (satellite benches ride
    along a TPU headline but are AOT- or CPU-proven), else the
    record-level class."""
    overrides = rec.get("evidence_classes") or {}
    ev = overrides.get(metric)
    if is_valid(ev):
        return ev
    return record_evidence(rec)


def comparable(a: Optional[str], b: Optional[str]) -> bool:
    """Whether two evidence classes may be gate/trend-compared.  Only a
    RESOLVED mismatch refuses; an unresolvable side (None) compares —
    refusing legacy rows would brick every pre-v2 history file."""
    if a is None or b is None:
        return True
    return a == b


def classify_history_row(row: dict) -> Optional[str]:
    """Backfill classifier for schema-v1 history rows (no ``evidence``
    field): all v1 rows are bench timing rows, so the class is the
    wall-clock class of their ``platform``.  Rows predating the
    platform stamp fall back on ``mode``/``backend`` hints; None when
    nothing resolves (left unclassified rather than guessed)."""
    ev = record_evidence(row)
    if ev is not None:
        return ev
    for key in ("backend", "mode"):
        ev = wallclock_evidence(row.get(key))
        if ev is not None:
            return ev
    return None


def bench_evidence_classes(platform: Optional[str]) -> Dict[str, str]:
    """The per-metric override map for a full bench.py record: the
    headline timing metrics inherit the record-level (platform) class,
    while satellite metrics carry the class of how THEY were actually
    measured — AOT cost-analysis, HLO inspection, or the CPU/f64
    subprocess harnesses that run regardless of headline platform
    (provenance per the ``*_note`` fields of BENCH_BASELINE.json)."""
    wall = wallclock_evidence(platform) or "cpu-wallclock"
    out: Dict[str, str] = {
        # XLA cost-analysis derived (AOT-proven: bytes/flops SCHEDULED)
        "xla_cost_analysis_bytes_accessed": "aot-bytes",
        "coh_bf16_xla_cost_analysis_bytes_accessed": "aot-bytes",
        "hier_predict_speedup": "aot-bytes",
        # compiled-HLO structure inspection (ADMM collective traffic)
        "admm_collective_bytes_per_round": "aot-hlo",
        "admm_collective_bytes_reduction": "aot-hlo",
        # harnesses that run on f64/NumPy or subprocess CPU workers
        # regardless of the headline platform (per the *_note
        # provenance prose in BENCH_BASELINE.json)
        "refine_flux_err": "cpu-wallclock",
        "refine_outer_iters_per_sec": "cpu-wallclock",
        "latency_to_first_solution_s": "cpu-wallclock",
        "stream_warm_speedup": "cpu-wallclock",
        "fleet_solves_per_sec_2workers": "cpu-wallclock",
        "hier_predict_max_rel_err": "cpu-wallclock",
        "admm_straggler_ratio": "cpu-wallclock",
        # load/capacity rows: stepped-ramp load vs subprocess CPU
        # workers (bench.run_load_bench) — honest CPU wall-clock, never
        # a device-speed claim
        "saturation_throughput_solves_per_sec": "cpu-wallclock",
        "shed_rate_under_overload": "cpu-wallclock",
        "goodput_fraction_at_saturation": "cpu-wallclock",
        # numerical-truth rows (bench.run_shadow_drift_bench): the
        # drift ratio is dtype/kernel truth, but it is measured on the
        # CPU interpret-mode kernels — a TPU MXU pass may round
        # differently, so the class is honest cpu-wallclock, never a
        # device claim
        "shadow_drift_batched_vs_xla_p99": "cpu-wallclock",
        "shadow_drift_bf16_vs_f32_p99": "cpu-wallclock",
        # wall-clock headline + serve/coherency rows follow the run's
        # platform: bench measures them on the live device
        "value": wall,
        "vs_baseline": wall,
        "analytic_tflops_per_sec": wall,
        "analytic_hbm_gb_per_sec": wall,
        "mfu_vs_device_peak": wall,
        "bw_util_vs_device_peak": wall,
        "warm_start_speedup": wall,
        "coh_bf16_iters_per_sec": wall,
        "solves_per_sec_per_chip": wall,
        "serve_batch_speedup": wall,
        "serve_p50_latency_s": wall,
        "compile_seconds_total": wall,
        "peak_device_memory_bytes": wall,
    }
    return out
