"""In-process flight recorder: ring buffer, heartbeat, hang watchdog,
crash dumps.

The watch scripts (``tpu_watch.sh`` and friends) can only observe a run
from outside; when a run hangs in a wedged collective or dies on an
uncaught exception, the interesting state is *inside* the process.  A
:class:`FlightRecorder` keeps:

- a bounded ring buffer of recent activity records (spans, phases,
  events; ``SAGECAL_FLIGHT_RING`` entries, default 256);
- a heartbeat file (``SAGECAL_HEARTBEAT_FILE``, default
  ``.sagecal_heartbeat``) rewritten atomically by a daemon watchdog
  thread — watch scripts treat a *fresh mtime* as "process alive" (a
  hard hang that stops the watchdog thread also stops the mtime, so
  staleness is a honest kill signal);
- a hang watchdog: if no activity is recorded for
  ``SAGECAL_STALL_SECONDS`` (default 300) the recorder dumps all-thread
  Python stacks, the ring tail, and (when jax is already imported)
  device / live-array state to ``flight_dump.json`` — it does NOT kill
  the run, and records ``stall_resolved`` if activity resumes;
- crash handlers: :func:`install_crash_handlers` chains a process-wide
  ``sys.excepthook`` and a SIGTERM handler that write a flight dump,
  run every registered crash flusher (the elastic checkpoint manager
  registers one, so a preempted run persists its last completed tile),
  reap active tile-prefetch threads, flush every registered JSONL
  event log with a ``run_aborted`` event carrying the dump path, then
  defer to the previous handler.

Everything is host-side, stdlib-only at import time, and inert unless
``SAGECAL_FLIGHT=1`` (crash handlers still flush event logs without a
recorder; the dump path is simply absent).
"""

from __future__ import annotations

import collections
import json
import os
import signal
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

# v2: dumps carry a writer-identity stamp (obs/ledger.py accepts both)
DUMP_SCHEMA_VERSION = 2

_TRUTHY = ("1", "true", "yes", "on")

DEFAULT_RING = 256
DEFAULT_STALL_SECONDS = 300.0
DEFAULT_HEARTBEAT_FILE = ".sagecal_heartbeat"
DEFAULT_DUMP_FILE = "flight_dump.json"


def _env_enabled() -> bool:
    return os.environ.get("SAGECAL_FLIGHT", "").strip().lower() in _TRUTHY


_enabled: Optional[bool] = None


def flight_enabled() -> bool:
    """Master flight-recorder switch: ``set_flight`` override if set,
    otherwise the ``SAGECAL_FLIGHT`` env var."""
    if _enabled is not None:
        return _enabled
    return _env_enabled()


def set_flight(on: Optional[bool]) -> None:
    """Force the flight recorder on/off (``None`` restores env-var
    control)."""
    global _enabled
    _enabled = on


def _jsonable(x):
    from sagecal_tpu.obs.events import _jsonable as ev_jsonable

    return ev_jsonable(x)


# last elastic checkpoint written/resumed in this process; flight dumps
# and heartbeats carry it so `diag flight` can point an operator at the
# exact file a `--resume` restart will pick up
_LAST_CHECKPOINT: Optional[str] = None


def note_checkpoint(path: str) -> None:
    """Record the most recent checkpoint path (elastic/checkpoint.py
    calls this on every write and on resume)."""
    global _LAST_CHECKPOINT
    _LAST_CHECKPOINT = path
    fr = _GLOBAL
    if fr is not None:
        fr.record("checkpoint", name=os.path.basename(path), path=path)


def last_checkpoint_path() -> Optional[str]:
    return _LAST_CHECKPOINT


def _atomic_write_json(path: str, doc: dict) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def _thread_stacks() -> List[dict]:
    """All-thread Python stacks via ``sys._current_frames`` (the same
    state ``faulthandler`` prints, but structured)."""
    names = {t.ident: (t.name, t.daemon) for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        name, daemon = names.get(tid, ("?", False))
        out.append({
            "tid": tid,
            "name": name,
            "daemon": daemon,
            "stack": [ln.rstrip("\n")
                      for ln in traceback.format_stack(frame)],
        })
    return out


def _device_state() -> dict:
    """Device / live-array snapshot — guarded: only queried when jax is
    ALREADY imported (a dump must never be the thing that initializes a
    wedged backend)."""
    if "jax" not in sys.modules:
        return {"jax_imported": False}
    out: Dict[str, Any] = {"jax_imported": True}
    try:
        jax = sys.modules["jax"]
        devs = jax.devices()
        out["num_devices"] = len(devs)
        out["platform"] = devs[0].platform if devs else "none"
        out["device_kind"] = devs[0].device_kind if devs else "none"
    except Exception as e:
        out["device_error"] = f"{type(e).__name__}: {e}"
        return out
    try:
        arrays = list(jax.live_arrays())
        out["live_arrays"] = len(arrays)
        out["live_array_bytes"] = int(
            sum(a.size * a.dtype.itemsize for a in arrays))
    except Exception as e:
        out["live_array_error"] = f"{type(e).__name__}: {e}"
    return out


def _device_profile_trace() -> Optional[str]:
    """Newest device-profile trace when capture was armed — guarded
    like :func:`_device_state`: only consulted when devprof is ALREADY
    imported, so the crash path never imports anything new."""
    devprof = sys.modules.get("sagecal_tpu.obs.devprof")
    if devprof is None:
        return None
    try:
        path = devprof.last_trace_path()
        if path:
            return path
        root = os.environ.get("SAGECAL_DEVICE_PROFILE")
        if root and os.path.isdir(root):
            return devprof.newest_trace_path(root)
    except Exception:
        pass
    return None


class FlightRecorder:
    """Bounded activity ring + heartbeat file + hang watchdog."""

    def __init__(self,
                 heartbeat_path: Optional[str] = None,
                 dump_path: Optional[str] = None,
                 ring_size: Optional[int] = None,
                 stall_seconds: Optional[float] = None,
                 run_id: Optional[str] = None):
        env = os.environ
        self.heartbeat_path = heartbeat_path or env.get(
            "SAGECAL_HEARTBEAT_FILE") or DEFAULT_HEARTBEAT_FILE
        self.dump_path = dump_path or env.get(
            "SAGECAL_FLIGHT_DUMP") or DEFAULT_DUMP_FILE
        if ring_size is None:
            try:
                ring_size = int(env.get("SAGECAL_FLIGHT_RING", ""))
            except ValueError:
                ring_size = DEFAULT_RING
        if stall_seconds is None:
            try:
                stall_seconds = float(env.get("SAGECAL_STALL_SECONDS", ""))
            except ValueError:
                stall_seconds = DEFAULT_STALL_SECONDS
        self.ring_size = max(int(ring_size), 8)
        self.stall_seconds = float(stall_seconds)
        self.run_id = run_id or ""
        self._ring: collections.deque = collections.deque(
            maxlen=self.ring_size)
        self._lock = threading.Lock()
        self._last_activity = time.monotonic()
        self._last_beat = 0.0
        self._stalled = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.dumps: List[str] = []

    # -- activity -----------------------------------------------------

    def record(self, kind: str, name: str = "", **fields) -> None:
        """Record one activity entry; refreshes the stall clock and
        closes an open stall window (``stall_resolved``)."""
        self._append(kind, name, **fields)
        self._last_activity = time.monotonic()
        if self._stalled:
            self._stalled = False
            self._append("stall_resolved", name,
                         stall_seconds=self.stall_seconds)
        # opportunistic beat so short-lived processes leave a heartbeat
        # even before the watchdog's first tick (rate-limited to 1/s)
        now = time.monotonic()
        if now - self._last_beat >= 1.0:
            self.heartbeat()

    def _append(self, kind: str, name: str = "", **fields) -> None:
        entry = {"ts": time.time(), "kind": kind, "name": name}
        for k, v in fields.items():
            if k not in entry:
                entry[k] = _jsonable(v)
        with self._lock:
            self._ring.append(entry)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def seconds_since_activity(self) -> float:
        return time.monotonic() - self._last_activity

    # -- heartbeat ----------------------------------------------------

    def heartbeat(self, closed: bool = False) -> None:
        """Atomically rewrite the heartbeat file.  Watch scripts key on
        the file *mtime* (see tpu_watch.sh); the JSON body carries the
        richer state for humans and ``diag``."""
        doc = {
            "pid": os.getpid(),
            "ts": time.time(),
            "run_id": self.run_id,
            "last_activity_age": round(self.seconds_since_activity(), 3),
            "stalled": self._stalled,
            "ring_len": len(self._ring),
            "closed": closed,
            "last_checkpoint": _LAST_CHECKPOINT,
        }
        try:
            _atomic_write_json(self.heartbeat_path, doc)
            self._last_beat = time.monotonic()
        except OSError:
            pass

    # -- watchdog -----------------------------------------------------

    def start(self, poll_seconds: Optional[float] = None) -> None:
        """Start the daemon watchdog thread (idempotent): writes the
        heartbeat every poll and dumps once per stall window when no
        activity arrives for ``stall_seconds``."""
        if self._thread is not None and self._thread.is_alive():
            return
        if poll_seconds is None:
            poll_seconds = max(0.05, min(self.stall_seconds / 4.0, 10.0))
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._watch, args=(float(poll_seconds),),
            name="sagecal-flight-watchdog", daemon=True)
        self._thread.start()

    def _watch(self, poll_seconds: float) -> None:
        while not self._stop.wait(poll_seconds):
            self.heartbeat()
            if (not self._stalled
                    and self.seconds_since_activity() > self.stall_seconds):
                self._stalled = True
                self._append("hang_detected",
                             stall_seconds=self.stall_seconds,
                             idle_seconds=round(
                                 self.seconds_since_activity(), 3))
                try:
                    self.dump("stall")
                except Exception:
                    pass

    def stop(self) -> None:
        """Stop the watchdog and leave a final ``closed`` heartbeat so
        watch scripts can tell clean shutdown from death."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None
        self.heartbeat(closed=True)

    # -- dumps --------------------------------------------------------

    def dump(self, reason: str, exc_info=None) -> str:
        """Write the forensic dump (all-thread stacks + ring tail +
        guarded device state) atomically to :attr:`dump_path`."""
        doc: Dict[str, Any] = {
            "schema_version": DUMP_SCHEMA_VERSION,
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "run_id": self.run_id,
            "argv": list(sys.argv),
            "stall_seconds": self.stall_seconds,
            "last_activity_age": round(self.seconds_since_activity(), 3),
            "env": {k: v for k, v in os.environ.items()
                    if k.startswith("SAGECAL_") or k == "JAX_PLATFORMS"},
            "threads": _thread_stacks(),
            "ring": self.snapshot(),
            "device_state": _device_state(),
            "last_checkpoint": _LAST_CHECKPOINT,
            "device_profile_trace": _device_profile_trace(),
        }
        from sagecal_tpu.obs.events import writer_identity

        doc["writer"] = writer_identity()
        doc["mono"] = time.monotonic()
        if exc_info is not None:
            tp, val, tb = exc_info
            doc["exception"] = {
                "type": getattr(tp, "__name__", str(tp)),
                "value": str(val),
                "traceback": traceback.format_exception(tp, val, tb),
            }
        _atomic_write_json(self.dump_path, doc)
        self.dumps.append(self.dump_path)
        return self.dump_path


_GLOBAL: Optional[FlightRecorder] = None
_GLOBAL_LOCK = threading.Lock()


def get_flight_recorder(run_id: Optional[str] = None
                        ) -> Optional[FlightRecorder]:
    """The process flight recorder, started on first use, when
    ``SAGECAL_FLIGHT=1``; None when disabled."""
    global _GLOBAL
    if not flight_enabled():
        return None
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = FlightRecorder(run_id=run_id)
            _GLOBAL.start()
        elif run_id and not _GLOBAL.run_id:
            _GLOBAL.run_id = run_id
        return _GLOBAL


def active_recorder() -> Optional[FlightRecorder]:
    """The already-started recorder, if any — never creates one (so
    library call sites can feed activity without owning lifecycle)."""
    return _GLOBAL


def note_activity(kind: str, name: str = "", **fields) -> None:
    """Feed one activity record to the active recorder (no-op without
    one).  Called from tracer span exits and app phase loops."""
    fr = _GLOBAL
    if fr is not None:
        fr.record(kind, name, **fields)


def reset_flight_recorder() -> None:
    """Stop and drop the process recorder (tests)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        fr, _GLOBAL = _GLOBAL, None
    if fr is not None:
        fr.stop()


def close_flight_recorder() -> None:
    """Clean-shutdown counterpart of :func:`get_flight_recorder`: stop
    the watchdog and leave the final ``closed`` heartbeat so watch
    scripts can tell a finished run from a dead one.  Apps call this
    only on the SUCCESS path — a crash must leave the recorder (and
    its ring) alive for the excepthook's dump."""
    reset_flight_recorder()


# ---------------------------------------------------------------------------
# crash handlers: excepthook + SIGTERM -> dump + event-log flush


# Event logs to flush on crash.  Plain list (not weak): apps register
# right after opening and the set stays tiny; closed logs are skipped.
_EVENT_LOGS: List[Any] = []
_PREV_EXCEPTHOOK = None
_PREV_SIGTERM = None
_INSTALLED = False


def register_event_log(elog) -> None:
    """Register a JSONL event log for crash-time flushing."""
    if elog is not None and elog not in _EVENT_LOGS:
        _EVENT_LOGS.append(elog)


def unregister_event_log(elog) -> None:
    try:
        _EVENT_LOGS.remove(elog)
    except ValueError:
        pass


def _flush_event_logs(reason: str, dump_path: Optional[str]) -> None:
    for elog in list(_EVENT_LOGS):
        try:
            if getattr(elog, "closed", False):
                continue
            elog.emit("run_aborted", reason=reason, flight_dump=dump_path,
                      last_checkpoint=_LAST_CHECKPOINT)
            elog.close()
        except Exception:
            pass


# Crash flushers run BEFORE the event logs close so their own events
# (checkpoint_written) still land in the log; the elastic checkpoint
# manager is the canonical registrant.  Same plain-list pattern as
# _EVENT_LOGS.
_CRASH_FLUSHERS: List[Any] = []


def register_crash_flusher(fn) -> None:
    """Register a zero-arg callable invoked from the SIGTERM/excepthook
    path (exceptions swallowed — a flusher must never mask the crash)."""
    if fn is not None and fn not in _CRASH_FLUSHERS:
        _CRASH_FLUSHERS.append(fn)


def unregister_crash_flusher(fn) -> None:
    try:
        _CRASH_FLUSHERS.remove(fn)
    except ValueError:
        pass


def _run_crash_flushers() -> None:
    for fn in list(_CRASH_FLUSHERS):
        try:
            fn()
        except Exception:
            pass
    # reap tile-prefetch worker threads so teardown can't hang past the
    # checkpoint flush; guarded on the module being loaded already (the
    # crash path must never import h5py/jax into a dying process)
    ds_mod = sys.modules.get("sagecal_tpu.io.dataset")
    if ds_mod is not None:
        try:
            ds_mod.cancel_active_prefetchers()
        except Exception:
            pass


def _crash_dump(reason: str, exc_info=None) -> Optional[str]:
    fr = _GLOBAL if _GLOBAL is not None else get_flight_recorder()
    if fr is None:
        return None
    try:
        return fr.dump(reason, exc_info=exc_info)
    except Exception:
        return None


def _excepthook(tp, val, tb) -> None:
    _run_crash_flushers()  # before the dump: it records last_checkpoint
    path = _crash_dump("uncaught_exception", exc_info=(tp, val, tb))
    _flush_event_logs(f"uncaught_exception:{getattr(tp, '__name__', tp)}",
                      path)
    hook = _PREV_EXCEPTHOOK or sys.__excepthook__
    hook(tp, val, tb)


def _sigterm_handler(signum, frame) -> None:
    # checkpoint first: the dump/flush below is forensics, the flusher
    # is the state a `--resume` restart needs to exist
    _run_crash_flushers()
    path = _crash_dump("sigterm")
    _flush_event_logs("sigterm", path)
    prev = _PREV_SIGTERM
    if callable(prev):
        prev(signum, frame)
        return
    # restore the previous disposition and re-deliver so the process
    # still dies with the default SIGTERM exit status
    signal.signal(signum, prev if prev is not None else signal.SIG_DFL)
    os.kill(os.getpid(), signum)


def install_crash_handlers() -> None:
    """Install the process-wide ``sys.excepthook`` + SIGTERM handler
    (idempotent; both chain to whatever was installed before).  Called
    from every app entrypoint so an uncaught exception can no longer
    lose buffered events."""
    global _INSTALLED, _PREV_EXCEPTHOOK, _PREV_SIGTERM
    if _INSTALLED:
        return
    _PREV_EXCEPTHOOK = sys.excepthook
    sys.excepthook = _excepthook
    try:  # signal handlers only installable from the main thread
        _PREV_SIGTERM = signal.getsignal(signal.SIGTERM)
        signal.signal(signal.SIGTERM, _sigterm_handler)
    except ValueError:
        _PREV_SIGTERM = None
    _INSTALLED = True


def uninstall_crash_handlers() -> None:
    """Restore the previous excepthook / SIGTERM handler (tests)."""
    global _INSTALLED, _PREV_EXCEPTHOOK, _PREV_SIGTERM
    if not _INSTALLED:
        return
    if sys.excepthook is _excepthook and _PREV_EXCEPTHOOK is not None:
        sys.excepthook = _PREV_EXCEPTHOOK
    try:
        if signal.getsignal(signal.SIGTERM) is _sigterm_handler:
            signal.signal(signal.SIGTERM,
                          _PREV_SIGTERM if _PREV_SIGTERM is not None
                          else signal.SIG_DFL)
    except ValueError:
        pass
    _PREV_EXCEPTHOOK = None
    _PREV_SIGTERM = None
    _INSTALLED = False


# ---------------------------------------------------------------------------
# dump readers (diag flight)


def read_dump(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def format_dump(doc: dict, ring_tail: int = 20) -> str:
    """Human rendering of a flight dump for ``diag flight``."""
    lines = [
        f"flight dump: reason={doc.get('reason', '?')} "
        f"pid={doc.get('pid')} run_id={doc.get('run_id') or '-'}",
        f"written: {time.strftime('%Y-%m-%d %H:%M:%SZ', time.gmtime(doc.get('ts', 0)))}"
        f"  last activity {doc.get('last_activity_age', '?')}s before dump",
    ]
    exc = doc.get("exception")
    if exc:
        lines.append(f"exception: {exc.get('type')}: {exc.get('value')}")
    ckpt = doc.get("last_checkpoint")
    lines.append(
        f"last checkpoint: {ckpt} (restart with --resume)" if ckpt
        else "last checkpoint: none (run had no checkpointing enabled)")
    dp = doc.get("device_profile_trace")
    if dp:
        lines.append(f"device-profile trace: {dp} "
                     f"(feed to `diag roofline`)")
    dev = doc.get("device_state") or {}
    if dev.get("jax_imported"):
        lines.append(
            f"devices: {dev.get('num_devices', '?')}x "
            f"{dev.get('device_kind', '?')} ({dev.get('platform', '?')}), "
            f"live arrays: {dev.get('live_arrays', '?')} "
            f"({dev.get('live_array_bytes', 0)} bytes)")
    else:
        lines.append("devices: jax not imported at dump time")
    threads = doc.get("threads") or []
    lines.append(f"threads: {len(threads)}")
    for t in threads:
        tag = " [daemon]" if t.get("daemon") else ""
        lines.append(f"--- thread {t.get('name', '?')} "
                     f"(tid={t.get('tid')}){tag}")
        for frame_line in t.get("stack", []):
            for sub in frame_line.split("\n"):
                if sub.strip():
                    lines.append("    " + sub.strip())
    ring = doc.get("ring") or []
    lines.append(f"ring buffer: {len(ring)} entries "
                 f"(last {min(ring_tail, len(ring))} shown)")
    for e in ring[-ring_tail:]:
        ts = time.strftime("%H:%M:%S", time.gmtime(e.get("ts", 0)))
        extra = {k: v for k, v in e.items()
                 if k not in ("ts", "kind", "name")}
        lines.append(f"  {ts}  {e.get('kind', '?'):<16s} "
                     f"{e.get('name', ''):<24s} "
                     f"{json.dumps(extra) if extra else ''}".rstrip())
    return "\n".join(lines)
