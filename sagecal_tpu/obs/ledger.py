"""The record-family schema registry + validating reader.

The repo emits a dozen independent JSONL / JSON record families
(events, spans, timeline rows, drift ledger, lease/queue docs, result
manifests, metrics snapshots, load steps, flight dumps, bench history,
heartbeats).  Every ad-hoc reader so far silently SKIPS lines it cannot
parse — the right behaviour on the serving path, but fatal for an
auditor: a silently dropped line is exactly the evidence a post-mortem
needs.  This module is the single place that knows, for every family:

- which file names it lives under (``pattern``),
- the discriminator (``kind`` field) separating it from foreign lines,
- the required keys and the set of known schema versions,
- which field carries the writer identity and which orders records.

:func:`classify_line` / :func:`read_validated` classify every line as
one of

- ``ok``           — parses, right family, schema-complete
- ``torn``         — not valid JSON (truncated tail, interleaved write)
- ``foreign``      — valid JSON but another family's record (or not an
  object at all)
- ``out_of_schema`` — right family but missing required keys or an
  unknown schema version

instead of skipping, and :func:`scan_out_dir` maps every record-looking
file in a run directory to its family, flagging unregistered files for
the observability-gap report.  The replay engine (obs/replay.py) and
the conservation-law auditor (obs/audit.py) are built on these reads.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
import os
from typing import Any, Dict, List, Optional, Tuple

#: registry version (bump when a family is added or re-shaped)
LEDGER_SCHEMA_VERSION = 1

OK = "ok"
TORN = "torn"
FOREIGN = "foreign"
OUT_OF_SCHEMA = "out_of_schema"
STATUSES = (OK, TORN, FOREIGN, OUT_OF_SCHEMA)


@dataclasses.dataclass(frozen=True)
class RecordFamily:
    """One registered record family: where it lives and what a valid
    record must carry."""

    name: str
    container: str               # "jsonl" (line-oriented) | "json"
    pattern: str                 # glob over the path relative to out-dir
    required: Tuple[str, ...]    # keys every valid record carries
    kind_field: str = ""         # discriminator field ("" = none)
    kind_value: str = ""
    version_field: str = ""      # schema-version field ("" = unversioned)
    known_versions: Tuple[int, ...] = ()
    writer_field: str = ""       # writer-identity field ("" = none)
    order_field: str = "ts"      # same-writer ordering key
    seq_field: str = ""          # per-writer sequence field, if stamped
    description: str = ""

    def matches(self, rel_path: str) -> bool:
        return fnmatch.fnmatch(rel_path.replace(os.sep, "/"), self.pattern)


#: every record family the repo emits, in discovery-priority order
#: (first pattern match wins in :func:`match_family`)
REGISTRY: Tuple[RecordFamily, ...] = (
    RecordFamily(
        name="event", container="jsonl",
        pattern="*events*.jsonl*",
        required=("ts", "run_id", "type"),
        writer_field="writer", seq_field="seq",
        description="append-only event log (obs/events.py EventLog); "
        "per-process companions carry a numeric pid suffix"),
    RecordFamily(
        name="span", container="jsonl",
        pattern="*trace*.jsonl*",
        required=("kind", "schema_version", "trace_id", "span_id",
                  "name", "ts", "dur", "pid"),
        kind_field="kind", kind_value="span",
        version_field="schema_version", known_versions=(1, 2),
        writer_field="writer", seq_field="seq",
        description="execution spans (obs/trace.py Tracer); v2 adds "
        "writer/mono/seq stamps"),
    RecordFamily(
        name="timeline", container="jsonl",
        pattern="timeline.jsonl",
        required=("schema_version", "kind", "ts", "items", "done",
                  "waiting", "leased", "expired_leases",
                  "alive_workers"),
        kind_field="kind", kind_value="fleet_timeline",
        version_field="schema_version", known_versions=(1, 2),
        writer_field="writer", seq_field="seq",
        description="live fleet timeline (obs/timeline.py "
        "TimelineSampler); v2 adds writer/mono/seq stamps"),
    RecordFamily(
        name="drift", container="jsonl",
        pattern="drift.jsonl",
        required=("schema_version", "kind", "ts", "request_id",
                  "path_pair", "kernel_path", "verdict", "shadow_s"),
        kind_field="kind", kind_value="shadow_drift",
        version_field="schema_version", known_versions=(1,),
        writer_field="writer", seq_field="seq",
        description="shadow-solve drift ledger (obs/shadow.py)"),
    RecordFamily(
        name="bench_history", container="jsonl",
        pattern="BENCH_HISTORY.jsonl",
        required=("history_schema_version", "ts", "metric"),
        version_field="history_schema_version", known_versions=(1, 2),
        description="bench regression history (obs/perf.py)"),
    RecordFamily(
        name="queue_item", container="json",
        pattern="queue/item-*.json",
        required=("request_id", "tenant", "request", "enqueued_at"),
        order_field="enqueued_at",
        description="queued work item (fleet/queue.py WorkItem); "
        "written once by the enqueuer, never rewritten"),
    RecordFamily(
        name="queue_lease", container="json",
        pattern="queue/lease-*.json",
        required=("worker", "request_id", "acquired_at", "renewed_at",
                  "expires_at"),
        writer_field="worker", order_field="acquired_at",
        description="one lease epoch (fleet/queue.py); epoch number in "
        "the filename (lease-<rid>.e<NNNNNN>.json), published "
        "exclusively, never rewritten; chains are swept on complete()"),
    RecordFamily(
        name="queue_done", container="json",
        pattern="queue/done-*.json",
        required=("request_id", "worker", "completed_at"),
        writer_field="worker", order_field="completed_at",
        description="completion marker (fleet/queue.py complete())"),
    RecordFamily(
        name="queue_fail", container="json",
        pattern="queue/fail-*.json",
        required=("request_id", "worker", "ts", "error"),
        writer_field="worker",
        description="per-attempt failure record (fleet/queue.py "
        "record_failure()); one unique file per attempt"),
    RecordFamily(
        name="result_manifest", container="json",
        pattern="*.result.json",
        required=("request_id", "tenant", "verdict", "enqueued_at",
                  "completed_at", "latency_s"),
        order_field="completed_at",
        description="per-request result manifest (serve/request.py "
        "write_result_manifest); the durable commit record of a solve, "
        "shed refusal, or terminal error"),
    RecordFamily(
        name="metrics_snapshot", container="json",
        pattern="metrics-*.json",
        required=("kind", "schema_version", "ts", "pid", "worker_id",
                  "state"),
        kind_field="kind", kind_value="metrics_snapshot",
        version_field="schema_version", known_versions=(1,),
        writer_field="worker_id",
        description="per-worker registry snapshot (obs/aggregate.py); "
        "atomically rewritten, newest-per-worker wins"),
    RecordFamily(
        name="load_steps", container="json",
        pattern="load_steps.json",
        required=("schema_version", "kind", "seed", "arrival",
                  "t_start", "steps", "submitted"),
        kind_field="kind", kind_value="load_steps",
        version_field="schema_version", known_versions=(1, 2),
        writer_field="writer", order_field="t_start",
        description="offered-load ground truth (fleet/loadgen.py); "
        "v2 adds the writer stamp"),
    RecordFamily(
        name="flight_dump", container="json",
        pattern="flight_dump*.json",
        required=("schema_version", "reason", "ts", "pid", "run_id"),
        version_field="schema_version", known_versions=(1, 2),
        writer_field="writer",
        description="flight-recorder forensic dump (obs/flight.py); "
        "v2 adds the writer stamp"),
    RecordFamily(
        name="heartbeat", container="json",
        pattern=".sagecal_heartbeat",
        required=("pid", "ts"),
        description="liveness heartbeat (obs/flight.py); rewritten in "
        "place, only the newest beat survives"),
)

_BY_NAME = {f.name: f for f in REGISTRY}

#: out-dir artifacts that LOOK like records but are derived reports /
#: opaque payloads, deliberately outside the audit surface.  Anything
#: json-ish in an out-dir matching neither REGISTRY nor this list is an
#: unregistered record file — an observability gap.
IGNORED_PATTERNS: Tuple[str, ...] = (
    "load_report.json",            # derived from timeline+manifests
    "scale_recommendation.json",   # derived recommender output
    "recommended_workers.json",    # derived recommender output
    "quality_report.json",         # derived quality report
    "audit_report.json",           # our own output
    "replay_state.json",           # our own output
    "workload/*.json",             # synthetic workload inputs
    "requests.json",               # fleet request-spec input
    "slo.json",                    # SLO policy input
    "aot-store/*",                 # serialized executables (binary)
    "*.trace.json",                # Chrome-trace exports (derived)
    "trace.json",
    "device_profile*.json*",       # device-profiler artifacts
    "*.tmp.*", "*.tmp",            # atomic-write staging leftovers
)


def family(name: str) -> RecordFamily:
    return _BY_NAME[name]


def match_family(rel_path: str) -> Optional[RecordFamily]:
    """The registered family owning a path (relative to the out-dir),
    or None for unregistered files.  Patterns with a directory part
    (queue/...) also match on basename so explicitly-passed queue dirs
    living outside the out-dir still resolve."""
    path = rel_path.replace(os.sep, "/")
    base = os.path.basename(path)
    for fam in REGISTRY:
        pat_base = fam.pattern.rsplit("/", 1)[-1]
        if (fam.matches(path) or fam.matches(base)
                or fnmatch.fnmatch(base, pat_base)):
            return fam
    return None


def is_ignored(rel_path: str) -> bool:
    base = rel_path.replace(os.sep, "/")
    return any(fnmatch.fnmatch(base, pat)
               or fnmatch.fnmatch(os.path.basename(base), pat)
               for pat in IGNORED_PATTERNS)


# --------------------------------------------------------- classification


@dataclasses.dataclass
class Classified:
    """One classified record (or unparseable fragment)."""

    status: str                  # one of STATUSES
    record: Optional[dict]       # parsed object (None when torn)
    reason: str = ""
    line_no: int = 0             # 1-based; 0 for whole-file documents
    path: str = ""


def _classify_obj(fam: RecordFamily, obj: Any, line_no: int = 0,
                  path: str = "") -> Classified:
    if not isinstance(obj, dict):
        return Classified(FOREIGN, None, "not a JSON object",
                          line_no, path)
    if fam.kind_field:
        kind = obj.get(fam.kind_field)
        if kind != fam.kind_value:
            return Classified(FOREIGN, obj,
                              f"kind {kind!r} != {fam.kind_value!r}",
                              line_no, path)
    elif fam.name == "event" and obj.get("kind") == "span":
        # spans share the JSONL idiom; a span line inside an event log
        # is a mis-routed writer, not an event
        return Classified(FOREIGN, obj, "span record in an event log",
                          line_no, path)
    missing = [k for k in fam.required if k not in obj]
    if missing:
        return Classified(OUT_OF_SCHEMA, obj,
                          f"missing keys: {', '.join(missing)}",
                          line_no, path)
    if fam.version_field and fam.known_versions:
        sv = obj.get(fam.version_field)
        if sv not in fam.known_versions:
            return Classified(OUT_OF_SCHEMA, obj,
                              f"{fam.version_field} {sv!r} not in "
                              f"{fam.known_versions}", line_no, path)
    return Classified(OK, obj, "", line_no, path)


def classify_line(fam: RecordFamily, line: str, line_no: int = 0,
                  path: str = "") -> Optional[Classified]:
    """Classify one JSONL line (None for blank lines)."""
    line = line.strip()
    if not line:
        return None
    try:
        obj = json.loads(line)
    except json.JSONDecodeError as e:
        return Classified(TORN, None, f"unparseable: {e}", line_no, path)
    return _classify_obj(fam, obj, line_no, path)


@dataclasses.dataclass
class ValidatedFile:
    """Every line/document of one file, classified."""

    path: str
    family: str
    records: List[Classified] = dataclasses.field(default_factory=list)

    def by_status(self, status: str) -> List[Classified]:
        return [c for c in self.records if c.status == status]

    @property
    def ok(self) -> List[dict]:
        return [c.record for c in self.records if c.status == OK]

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in STATUSES}
        for c in self.records:
            out[c.status] += 1
        return out


def read_validated(path: str, fam: RecordFamily) -> ValidatedFile:
    """Read one file under a family's schema, classifying every line
    (jsonl) or the whole document (json) instead of skipping."""
    vf = ValidatedFile(path=path, family=fam.name)
    if fam.container == "jsonl":
        try:
            with open(path, "r", encoding="utf-8", errors="replace") as f:
                for i, line in enumerate(f, 1):
                    c = classify_line(fam, line, line_no=i, path=path)
                    if c is not None:
                        vf.records.append(c)
        except OSError as e:
            vf.records.append(Classified(TORN, None, f"unreadable: {e}",
                                         0, path))
        return vf
    # whole-document json
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            text = f.read()
    except OSError as e:
        vf.records.append(Classified(TORN, None, f"unreadable: {e}",
                                     0, path))
        return vf
    if not text.strip():
        vf.records.append(Classified(TORN, None, "empty document",
                                     0, path))
        return vf
    try:
        obj = json.loads(text)
    except json.JSONDecodeError as e:
        vf.records.append(Classified(TORN, None, f"unparseable: {e}",
                                     0, path))
        return vf
    vf.records.append(_classify_obj(fam, obj, 0, path))
    return vf


# --------------------------------------------------------------- discovery


@dataclasses.dataclass
class OutDirScan:
    """Every record file in an out-dir, mapped to its family."""

    out_dir: str
    files: List[ValidatedFile] = dataclasses.field(default_factory=list)
    unregistered: List[str] = dataclasses.field(default_factory=list)
    ignored: List[str] = dataclasses.field(default_factory=list)

    def by_family(self, name: str) -> List[ValidatedFile]:
        return [vf for vf in self.files if vf.family == name]

    def ok_records(self, name: str) -> List[dict]:
        out: List[dict] = []
        for vf in self.by_family(name):
            out.extend(vf.ok)
        return out

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in STATUSES}
        for vf in self.files:
            for s, n in vf.counts().items():
                out[s] += n
        return out


def _record_like(name: str) -> bool:
    base = os.path.basename(name)
    if base == ".sagecal_heartbeat":
        return True
    if ".json" not in base:
        return False
    stem = base.split(".json", 1)[0]
    suffix = base[len(stem):]
    # .json / .jsonl plus optional numeric per-process suffixes
    if suffix in (".json", ".jsonl"):
        return True
    rest = suffix.replace(".jsonl", "").replace(".json", "").strip(".")
    return rest.isdigit()


def scan_out_dir(out_dir: str,
                 extra_paths: Optional[List[str]] = None) -> OutDirScan:
    """Discover + classify every record file under ``out_dir`` (plus
    any explicit ``extra_paths``, e.g. an event log configured outside
    the out-dir).  Record-looking files owned by no registered family
    land in ``unregistered`` — an observability gap."""
    scan = OutDirScan(out_dir=out_dir)
    seen = set()
    candidates: List[Tuple[str, str]] = []  # (abs path, rel path)
    for root, dirs, names in os.walk(out_dir):
        dirs[:] = [d for d in dirs if d not in ("aot-store",)]
        for n in sorted(names):
            p = os.path.join(root, n)
            rel = os.path.relpath(p, out_dir)
            candidates.append((p, rel))
    for p in (extra_paths or []):
        if p and os.path.exists(p) and os.path.abspath(p) not in {
                os.path.abspath(c[0]) for c in candidates}:
            candidates.append((p, os.path.basename(p)))
    for p, rel in candidates:
        ap = os.path.abspath(p)
        if ap in seen:
            continue
        seen.add(ap)
        if not _record_like(rel):
            continue
        if is_ignored(rel):
            scan.ignored.append(rel)
            continue
        fam = match_family(rel)
        if fam is None:
            scan.unregistered.append(rel)
            continue
        scan.files.append(read_validated(p, fam))
    return scan


# ------------------------------------------------------- sequence analysis


def sequence_holes(records: List[dict], seq_field: str = "seq",
                   writer_field: str = "writer") -> Dict[str, List[int]]:
    """Per-writer holes in the stamped sequence numbers: for each
    writer, the missing integers strictly between its observed min and
    max.  A writer that simply stopped (crash, SIGKILL) leaves NO hole;
    a dropped or lost record in the middle does."""
    by_writer: Dict[str, List[int]] = {}
    for r in records:
        w = r.get(writer_field)
        s = r.get(seq_field)
        if isinstance(w, str) and isinstance(s, int):
            by_writer.setdefault(w, []).append(s)
    holes: Dict[str, List[int]] = {}
    for w, seqs in by_writer.items():
        have = set(seqs)
        missing = [i for i in range(min(have), max(have) + 1)
                   if i not in have]
        if missing:
            holes[w] = missing
    return holes


def registry_table() -> List[Dict[str, Any]]:
    """The registry as plain dicts (diag/docs rendering)."""
    return [dataclasses.asdict(f) for f in REGISTRY]
