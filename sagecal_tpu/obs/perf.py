"""Performance observability: compile/recompile tracking, device-memory
watermarks, host-transfer auditing, and the bench regression gate.

PR 1 made the *science* observable (solver traces, ADMM residuals,
manifests, JSONL events); this module makes the *performance*
observable.  Four pieces:

- :func:`instrumented_jit` — a drop-in ``jax.jit`` replacement adopted
  by the solvers (``lm``/``robust``/``rtr``/``lbfgs``/``sage``), the
  fused RIME kernel wrappers and the device-mesh ADMM driver.  With
  telemetry off it is a single flag check on top of the plain jitted
  call (the jaxpr, output signature, and jit cache are untouched).
  With telemetry on it keys every call by an *abstract input
  signature* (pytree structure + leaf shape/dtype + static-arg
  values), AOT-compiles each new signature through
  ``.lower()``/``.compile()`` so lowering and compile wall-times are
  measured separately, pulls ``compiled.cost_analysis()`` flops/bytes,
  and feeds everything into the PR-1 metrics registry plus a compile
  event stream that apps drain into their JSONL logs.  The per-name
  compile counter IS the recompile detector: a second compile of the
  same name means a signature change (new shapes, a changed static
  config) retraced the function.
- device-memory watermarks (:func:`device_memory_snapshot`,
  :func:`record_memory_watermark`) via ``device.memory_stats()`` with
  a graceful host-RSS fallback on backends that expose no allocator
  stats (CPU), plus an on-demand
  ``jax.profiler.device_memory_profile`` dump
  (:func:`dump_memory_profile`).
- :class:`TransferAudit` — an opt-in ``jax.transfer_guard("log")``
  context (``SAGECAL_TRANSFER_AUDIT=1``) that captures the guard's
  C++ stderr lines, classifies host<->device transfers by direction,
  and surfaces them as registry counters + a ``transfer_audit`` event.
- the perf-regression gate (:func:`gate_compare`) behind
  ``sagecal-tpu diag gate``: a fresh bench JSON is compared against a
  pinned baseline with per-metric tolerances and direction semantics
  (throughput up = good, bytes/memory up = bad); any out-of-tolerance
  metric is a nonzero exit.  ``tpu_kernel_check.sh`` runs it after the
  fused bench, turning the BENCH_*.json trajectory into a contract.

Everything here is host-side; nothing touches a tracer.  jax/numpy are
imported lazily so ``sagecal_tpu.obs`` stays importable before backend
selection.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from sagecal_tpu.obs.registry import get_registry, telemetry_enabled

_TRUTHY = ("1", "true", "yes", "on")
_AUDIT_ENV = "SAGECAL_TRANSFER_AUDIT"
_MEMPROF_ENV = "SAGECAL_MEMORY_PROFILE"
_COMPILE_CACHE_ENV = "SAGECAL_COMPILE_CACHE"

# ------------------------------------------------------------------ store

_LOCK = threading.Lock()
# per-function aggregates: name -> dict(compiles, lower_seconds,
# compile_seconds, flops, bytes_accessed, dispatches)
_FN_STATS: Dict[str, Dict[str, float]] = {}
# compile event stream the apps drain into their JSONL logs (bounded:
# a runaway retrace loop must not grow host memory without bound)
_COMPILE_EVENTS: List[dict] = []
_MAX_COMPILE_EVENTS = 4096
# per-phase peak-memory watermarks (bytes)
_WATERMARKS: Dict[str, float] = {}
# persistent-compilation-cache hit/miss counts observed through
# jax.monitoring ('/jax/compilation_cache/cache_hits|cache_misses'):
# a hit means XLA skipped the compile and deserialized a cached
# executable — a WARM compile; note_compile still records the (short)
# wall time, so the pair lets `diag perf` split warm from cold
_CACHE_EVENTS: Dict[str, int] = {"hits": 0, "misses": 0}
_cache_listener_installed = False


def reset_perf_stats() -> None:
    """Clear the module-level perf store (tests)."""
    with _LOCK:
        _FN_STATS.clear()
        _COMPILE_EVENTS.clear()
        _WATERMARKS.clear()
        _CACHE_EVENTS["hits"] = 0
        _CACHE_EVENTS["misses"] = 0


def perf_stats() -> Dict[str, Dict[str, float]]:
    """Per-instrumented-function aggregate snapshot."""
    with _LOCK:
        return {k: dict(v) for k, v in _FN_STATS.items()}


def drain_compile_events() -> List[dict]:
    """Return and clear the pending compile events (app -> JSONL)."""
    with _LOCK:
        evs, _COMPILE_EVENTS[:] = list(_COMPILE_EVENTS), []
    return evs


def note_compile(name: str, lower_seconds: float, compile_seconds: float,
                 flops: Optional[float] = None,
                 bytes_accessed: Optional[float] = None,
                 signature: str = "", aot: bool = True) -> dict:
    """Record one compilation of ``name`` into the registry, the
    per-function aggregates, and the compile event stream.  Public so
    code that already AOT-compiles itself (bench.py) reports through
    the same channel as :func:`instrumented_jit`."""
    with _LOCK:
        st = _FN_STATS.setdefault(name, {
            "compiles": 0, "lower_seconds": 0.0, "compile_seconds": 0.0,
            "flops": 0.0, "bytes_accessed": 0.0, "dispatches": 0,
        })
        st["compiles"] += 1
        st["lower_seconds"] += lower_seconds
        st["compile_seconds"] += compile_seconds
        if flops:
            st["flops"] = float(flops)
        if bytes_accessed:
            st["bytes_accessed"] = float(bytes_accessed)
        n = st["compiles"]
        ev = {
            "fn": name, "signature": signature, "n_compiles": n,
            "lower_seconds": round(lower_seconds, 6),
            "compile_seconds": round(compile_seconds, 6),
            "flops": flops, "bytes_accessed": bytes_accessed, "aot": aot,
        }
        if len(_COMPILE_EVENTS) < _MAX_COMPILE_EVENTS:
            _COMPILE_EVENTS.append(ev)
    reg = get_registry()
    reg.counter_inc(
        "jit_compiles_total", 1.0,
        help="XLA compilations per instrumented function (a count > 1 "
             "for one fn means a recompile: new shapes or a changed "
             "static config)", fn=name,
    )
    reg.observe("jit_lower_seconds", lower_seconds,
                help="trace+lower wall-time per compilation", fn=name)
    reg.observe("jit_compile_seconds", compile_seconds,
                help="XLA compile wall-time per compilation", fn=name)
    if flops:
        reg.gauge_set("xla_cost_analysis_flops", float(flops),
                      help="compiled.cost_analysis() flops of the last "
                           "compilation", fn=name)
    if bytes_accessed:
        reg.gauge_set("xla_cost_analysis_bytes_accessed",
                      float(bytes_accessed),
                      help="compiled.cost_analysis() bytes accessed of "
                           "the last compilation", fn=name)
    return ev


def _cache_event_listener(event: str, **_kw) -> None:
    """jax.monitoring listener: count persistent-compilation-cache
    hits/misses and bump the registry so warm compiles are visible in
    scrapes without waiting for an event-log drain."""
    if event == "/jax/compilation_cache/cache_hits":
        key = "hits"
        name = "jit_persistent_cache_hits_total"
        txt = ("XLA compilations served from the persistent compilation "
               "cache (warm compiles: deserialization, no codegen)")
    elif event == "/jax/compilation_cache/cache_misses":
        key = "misses"
        name = "jit_persistent_cache_misses_total"
        txt = ("XLA compilations not found in the persistent compilation "
               "cache (cold compiles: full codegen, then written back)")
    else:
        return
    with _LOCK:
        _CACHE_EVENTS[key] += 1
    get_registry().counter_inc(name, 1.0, help=txt)


def _install_cache_listener() -> None:
    global _cache_listener_installed
    if _cache_listener_installed:
        return
    try:
        import jax.monitoring

        jax.monitoring.register_event_listener(_cache_event_listener)
        _cache_listener_installed = True
    except Exception:
        pass


def compile_cache_stats() -> Dict[str, int]:
    """Persistent-compilation-cache hit/miss counts observed so far."""
    with _LOCK:
        return dict(_CACHE_EVENTS)


def enable_persistent_compilation_cache(path: Optional[str] = None):
    """Point JAX's persistent compilation cache at ``path`` (default:
    the ``SAGECAL_COMPILE_CACHE`` env var, falling back to
    ``JAX_COMPILATION_CACHE_DIR``) and install the cache-hit monitoring
    listener, so a second process compiling the same program deserializes
    the cached executable instead of re-running XLA codegen.

    Every app entry (fullbatch/minibatch/distributed/federated/serve)
    and bench.py call this once at startup; with neither env var set it
    is a no-op returning None, so bare library use is unaffected.  The
    min-compile-time floor is dropped to 0 s: calibration programs are
    few and large, so caching everything is strictly a win."""
    path = (path or os.environ.get(_COMPILE_CACHE_ENV)
            or os.environ.get("JAX_COMPILATION_CACHE_DIR"))
    if not path:
        return None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        return None
    _install_cache_listener()
    return path


def _cost_analysis(compiled) -> Tuple[Optional[float], Optional[float]]:
    """(flops, bytes_accessed) from a Compiled, or (None, None).  The
    axon TPU backend under-reports flops (BENCH_r02: ~35 MFLOP for a
    ~2.5 GFLOP program) — record for attribution, don't headline."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0)) or None
        by = float(cost.get("bytes accessed", 0.0)) or None
        return flops, by
    except Exception:
        return None, None


_COLLECTIVE_RE = None

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}


def _hlo_shape_bytes(shapes: str) -> int:
    import re

    total = 0
    for m in re.finditer(r"(\w+)\[([\d,]*)\]", shapes):
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(m.group(1), 4)
    return total


def collective_cost_analysis(compiled) -> dict:
    """Static cross-device communication analysis of a compiled HLO.

    Parses ``compiled.as_text()`` and attributes every collective op
    (all-reduce / all-gather / all-to-all / reduce-scatter /
    collective-permute) by its OUTPUT bytes to either the steady-state
    round loop — any computation reachable from a ``while`` op's body —
    or one-time setup/teardown.  ``collective_bytes_per_round`` is the
    per-device bytes a single iteration of the round loop moves through
    collectives: the honest comms floor the transpose-reduced consensus
    z-step exists to shrink (each op is counted once per round; the mesh
    ADMM keeps its collectives out of nested inner loops).

    Returns ``{}`` when no HLO text is available (e.g. a backend without
    ``as_text``), otherwise::

        {"collective_bytes_total":     sum over every collective op,
         "collective_bytes_per_round": sum inside while-body-reachable
                                       computations,
         "collective_ops_per_round":   op count in the round loop,
         "collective_breakdown":       {op_kind: per-round bytes}}
    """
    import re

    try:
        txt = compiled.as_text()
    except Exception:
        return {}
    if not isinstance(txt, str) or not txt:
        return {}
    comp_head = re.compile(
        r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->\s*\S.*\{\s*$"
    )
    coll_re = re.compile(
        r"=\s*\(?([^)=]*?)\)?\s*"
        r"(all-reduce|all-gather|all-to-all|reduce-scatter|"
        r"collective-permute)(-start)?\("
    )
    ref_re = re.compile(
        r"(?:condition|body|to_apply|calls|update_computation|select|"
        r"scatter)=%?([\w.\-]+)"
    )
    ref_set_re = re.compile(
        r"(?:called_computations|branch_computations)=\{([^}]*)\}"
    )
    while_re = re.compile(r"=\s*\(?[^)=]*\)?\s*while\(")
    colls: Dict[str, list] = {}
    refs: Dict[str, set] = {}
    while_bodies: set = set()
    cur = None
    for raw in txt.splitlines():
        line = raw.strip()
        m = comp_head.match(raw) or comp_head.match(line)
        if m:
            cur = m.group(1)
            colls.setdefault(cur, [])
            refs.setdefault(cur, set())
            continue
        if cur is None:
            continue
        cm = coll_re.search(line)
        if cm:  # "-done" halves of async pairs don't match the regex
            colls[cur].append(
                (cm.group(2), _hlo_shape_bytes(cm.group(1)))
            )
        names = set(ref_re.findall(line))
        for grp in ref_set_re.findall(line):
            names.update(
                n.strip().lstrip("%") for n in grp.split(",") if n.strip()
            )
        refs[cur].update(names)
        if while_re.search(line):
            wm = re.search(r"body=%?([\w.\-]+)", line)
            if wm:
                while_bodies.add(wm.group(1))
    # computations reachable from any while body run once per round
    reach: set = set()
    stack = [b for b in while_bodies if b in colls]
    while stack:
        c = stack.pop()
        if c in reach:
            continue
        reach.add(c)
        stack.extend(r for r in refs.get(c, ()) if r in colls)
    per_round = 0
    nops = 0
    breakdown: Dict[str, float] = {}
    total = 0
    for c, items in colls.items():
        for op, b in items:
            total += b
            if c in reach:
                per_round += b
                nops += 1
                breakdown[op] = breakdown.get(op, 0.0) + b
    return {
        "collective_bytes_total": float(total),
        "collective_bytes_per_round": float(per_round),
        "collective_ops_per_round": int(nops),
        "collective_breakdown": breakdown,
    }


# -------------------------------------------------------- instrumented_jit


def _as_tuple(x) -> tuple:
    if x is None:
        return ()
    if isinstance(x, (list, tuple)):
        return tuple(x)
    return (x,)


class _InstrumentedJit:
    """Callable wrapper produced by :func:`instrumented_jit`."""

    def __init__(self, fn: Callable, name: Optional[str], jit_kwargs: dict):
        import jax

        self._fn = fn
        self.name = name or getattr(fn, "__name__", repr(fn))
        self._jitted = jax.jit(fn, **jit_kwargs)
        # kept for the SAGECAL_CHECKIFY contract path, which rebuilds
        # the jit around checkify(fn) with the same static declarations
        self._jit_kwargs = dict(jit_kwargs)
        self._checked = None
        self._checkify_broken = False
        self._static_argnums = frozenset(
            int(i) for i in _as_tuple(jit_kwargs.get("static_argnums"))
        )
        self._static_argnames = frozenset(
            _as_tuple(jit_kwargs.get("static_argnames"))
        )
        # donated buffers make the AOT executable single-shot-unsafe to
        # share with the jit cache; fall back to first-call timing there
        self._aot_ok = not any(k.startswith("donate") for k in jit_kwargs)
        # signature -> Compiled (AOT path) | None (seen, jit-cache path)
        self._compiled: Dict[Any, Any] = {}
        self.__wrapped__ = fn
        self.__doc__ = getattr(fn, "__doc__", None)

    # -- signature keying ------------------------------------------------
    def _leaf_desc(self, x) -> str:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            return f"{dtype}{tuple(shape)}"
        # dynamic python scalars are traced weak-typed: the VALUE does
        # not retrace, only the type does
        if isinstance(x, (bool, int, float, complex)):
            return f"py:{type(x).__name__}"
        return repr(x)

    def _sig_key(self, args, kwargs):
        import jax

        stat = tuple(
            (i, repr(args[i])) for i in sorted(self._static_argnums)
            if i < len(args)
        ) + tuple(
            (k, repr(kwargs[k])) for k in sorted(self._static_argnames)
            if k in kwargs
        )
        dyn_args = tuple(
            a for i, a in enumerate(args) if i not in self._static_argnums
        )
        dyn_kwargs = {
            k: v for k, v in kwargs.items() if k not in self._static_argnames
        }
        leaves, treedef = jax.tree_util.tree_flatten((dyn_args, dyn_kwargs))
        return (stat, str(treedef), tuple(self._leaf_desc(x) for x in leaves))

    def _dyn_call_args(self, args, kwargs):
        dyn_args = tuple(
            a for i, a in enumerate(args) if i not in self._static_argnums
        )
        dyn_kwargs = {
            k: v for k, v in kwargs.items() if k not in self._static_argnames
        }
        return dyn_args, dyn_kwargs

    # -- compile paths ---------------------------------------------------
    def _aot_compile(self, sig, args, kwargs):
        t0 = time.perf_counter()
        lowered = self._jitted.lower(*args, **kwargs)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        flops, by = _cost_analysis(compiled)
        note_compile(self.name, t1 - t0, t2 - t1, flops, by,
                     signature=_sig_hash(sig), aot=True)
        return compiled

    def __call__(self, *args, **kwargs):
        # contract path first: SAGECAL_CHECKIFY must catch NaNs even in
        # runs with telemetry off.  Only at the outermost entry: when
        # this wrapper is reached from inside another trace (jit/vmap of
        # a caller), the checkify error value would itself be a tracer
        # and err.get() cannot run — the outer checked entry already
        # covers those frames.
        from sagecal_tpu.obs import contracts

        if contracts.checkify_active() and not self._checkify_broken:
            try:
                if self._checked is None:
                    self._checked = contracts.checked_jit(
                        self._fn, self._jit_kwargs)
                err, out = self._checked(*args, **kwargs)
            except Exception as e:
                # checkify cannot wrap everything (Pallas kernels,
                # donated buffers, exotic shardings): record once, then
                # permanently route this wrapper unchecked
                self._checkify_broken = True
                self._checked = None
                contracts.note_unsupported(self.name, repr(e))
            else:
                contracts.raise_if_error(err, self.name)
                return out
        if not telemetry_enabled():
            return self._jitted(*args, **kwargs)
        sig = self._sig_key(args, kwargs)
        entry = self._compiled.get(sig)
        get_registry().counter_inc(
            "jit_dispatches_total", 1.0,
            help="calls into instrumented jitted functions", fn=self.name,
        )
        if entry is None and sig not in self._compiled:
            if self._aot_ok:
                try:
                    entry = self._aot_compile(sig, args, kwargs)
                except Exception:
                    entry = None
            if entry is None:
                # AOT refused (donation, exotic args): time the first
                # dispatch — compile + first execution together
                t0 = time.perf_counter()
                out = self._jitted(*args, **kwargs)
                dt = time.perf_counter() - t0
                note_compile(self.name, 0.0, dt, signature=_sig_hash(sig),
                             aot=False)
                self._compiled[sig] = None
                return out
            self._compiled[sig] = entry
        if entry is not None:
            dyn_args, dyn_kwargs = self._dyn_call_args(args, kwargs)
            try:
                return entry(*dyn_args, **dyn_kwargs)
            except Exception:
                # sharding/commitment mismatch with the AOT executable:
                # permanently route this signature through the jit cache
                self._compiled[sig] = None
        return self._jitted(*args, **kwargs)

    # passthroughs so the wrapper stays a drop-in jax.jit replacement
    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def clear_cache(self) -> None:
        self._compiled.clear()
        try:
            self._jitted.clear_cache()
        except Exception:
            pass

    @property
    def compiles(self) -> int:
        """Compilations recorded under this wrapper's name (aggregated
        across wrapper instances sharing the name)."""
        return int(perf_stats().get(self.name, {}).get("compiles", 0))


def _sig_hash(sig) -> str:
    return hashlib.sha1(repr(sig).encode()).hexdigest()[:12]


def instrumented_jit(fn: Optional[Callable] = None, *,
                     name: Optional[str] = None, **jit_kwargs):
    """``jax.jit`` with compile/recompile telemetry (module docstring).

    Usable bare (``instrumented_jit(f)``), with options
    (``instrumented_jit(f, name="solver", static_argnames=("cfg",))``)
    or as a decorator factory.  All other keyword arguments pass
    through to ``jax.jit``.
    """
    if fn is None:
        def deco(f):
            return _InstrumentedJit(f, name, jit_kwargs)
        return deco
    return _InstrumentedJit(fn, name, jit_kwargs)


# ------------------------------------------------------------ device memory


def device_memory_snapshot(device=None) -> dict:
    """Current/peak device-memory bytes.  ``device.memory_stats()``
    where the backend exposes allocator stats (TPU/GPU); graceful
    fallback to host RSS (``source: host_rss``) on backends that
    return None (CPU) or raise — the numbers stay meaningful for the
    host-side pipeline stages."""
    stats = None
    kind = "unknown"
    try:
        import jax

        device = device or jax.local_devices()[0]
        kind = getattr(device, "device_kind", "unknown")
        stats = device.memory_stats()
    except Exception:
        stats = None
    if stats:
        inuse = stats.get("bytes_in_use", 0)
        return {
            "source": "device",
            "device_kind": kind,
            "bytes_in_use": int(inuse),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", inuse)),
            "bytes_limit": int(stats["bytes_limit"])
            if "bytes_limit" in stats else None,
        }
    rss = peak = 0
    try:
        import resource

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        with open("/proc/self/statm") as f:
            rss = int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        rss = rss or peak
    return {
        "source": "host_rss",
        "device_kind": kind,
        "bytes_in_use": int(rss or peak),
        "peak_bytes_in_use": int(peak or rss),
        "bytes_limit": None,
    }


def record_memory_watermark(phase: str, device=None) -> Optional[dict]:
    """Sample the device-memory snapshot and fold its peak into the
    per-``phase`` watermark (registry gauge ``peak_device_memory_bytes``
    + the module store :func:`memory_watermarks` reads).  No-op (None)
    when telemetry is off, so hot paths call it unguarded."""
    if not telemetry_enabled():
        return None
    snap = device_memory_snapshot(device)
    peak = float(snap.get("peak_bytes_in_use") or 0)
    with _LOCK:
        if peak > _WATERMARKS.get(phase, -1.0):
            _WATERMARKS[phase] = peak
    reg = get_registry()
    prev = reg.get_gauge("peak_device_memory_bytes", phase=phase)
    if prev is None or peak > prev:
        reg.gauge_set(
            "peak_device_memory_bytes", peak,
            help="peak device (or host-RSS fallback) bytes observed per "
                 "pipeline phase", phase=phase,
        )
    reg.gauge_set("device_memory_bytes_in_use",
                  float(snap.get("bytes_in_use") or 0),
                  help="device bytes in use at the last phase sample",
                  phase=phase)
    return snap


def memory_watermarks() -> Dict[str, float]:
    """Per-phase peak bytes recorded so far (for the run-end event)."""
    with _LOCK:
        return dict(_WATERMARKS)


def dump_memory_profile(path: Optional[str] = None) -> Optional[str]:
    """Write a ``jax.profiler.device_memory_profile()`` pprof dump to
    ``path`` (default: the ``SAGECAL_MEMORY_PROFILE`` env var; no-op
    returning None when neither is set or the profiler fails)."""
    path = path or os.environ.get(_MEMPROF_ENV)
    if not path:
        return None
    try:
        import jax

        prof = jax.profiler.device_memory_profile()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "wb") as f:
            f.write(prof)
        return path
    except Exception:
        return None


# ---------------------------------------------------------- transfer audit


def transfer_audit_enabled() -> bool:
    return os.environ.get(_AUDIT_ENV, "").strip().lower() in _TRUTHY


class TransferAudit:
    """Opt-in implicit host<->device transfer audit.

    Inside the context, ``jax.transfer_guard("log")`` is active and the
    guard's C++ log lines (``guard_lib.cc`` writes straight to fd 2 —
    Python logging never sees them) are captured through an fd-level
    stderr redirect.  On exit the captured stream is replayed to the
    real stderr (nothing is swallowed), lines are classified by
    direction into :attr:`counts`, samples are kept, and registry
    counters ``transfer_guard_transfers_total{direction=...}`` are
    bumped.  ``emit(elog)`` writes one ``transfer_audit`` event.

    Disabled (``enabled=False`` / env unset) the context is a no-op, so
    apps wrap their loops unconditionally."""

    _MARKS = (
        ("host-to-device transfer:", "host_to_device"),
        ("device-to-host transfer:", "device_to_host"),
        ("device-to-device transfer:", "device_to_device"),
    )

    def __init__(self, enabled: Optional[bool] = None, max_samples: int = 20):
        self.enabled = transfer_audit_enabled() if enabled is None else enabled
        self.max_samples = max_samples
        self.counts: Dict[str, int] = {}
        self.samples: List[str] = []
        self._guard = None
        self._tmp = None
        self._saved_fd = None

    def __enter__(self) -> "TransferAudit":
        if not self.enabled:
            return self
        import jax

        self._guard = jax.transfer_guard("log")
        self._guard.__enter__()
        try:
            sys.stderr.flush()
        except Exception:
            pass
        self._tmp = tempfile.TemporaryFile()
        self._saved_fd = os.dup(2)
        os.dup2(self._tmp.fileno(), 2)
        return self

    def __exit__(self, *exc) -> bool:
        # idempotent: apps close the audit before emitting its counts
        # AND in a finally for the exception path
        if not self.enabled or self._saved_fd is None:
            return False
        try:
            sys.stderr.flush()
        except Exception:
            pass
        os.dup2(self._saved_fd, 2)
        os.close(self._saved_fd)
        self._saved_fd = None
        self._guard.__exit__(*exc)
        self._tmp.seek(0)
        text = self._tmp.read().decode("utf-8", errors="replace")
        self._tmp.close()
        if text:
            # replay: warnings and guard lines stay visible on stderr
            try:
                sys.stderr.write(text)
                sys.stderr.flush()
            except Exception:
                pass
        for line in text.splitlines():
            for mark, direction in self._MARKS:
                if mark in line:
                    self.counts[direction] = self.counts.get(direction, 0) + 1
                    if len(self.samples) < self.max_samples:
                        self.samples.append(line[line.index(mark):][:200])
                    break
        reg = get_registry()
        for direction, n in self.counts.items():
            reg.counter_inc(
                "transfer_guard_transfers_total", float(n),
                help="implicit transfers observed by the "
                     "SAGECAL_TRANSFER_AUDIT=1 jax.transfer_guard audit",
                direction=direction,
            )
        return False

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    def emit(self, elog) -> None:
        """One ``transfer_audit`` JSONL event (no-op when disabled or
        the app runs without an event log)."""
        if elog is None or not self.enabled:
            return
        elog.emit("transfer_audit", counts=self.counts, total=self.total,
                  samples=self.samples)


# ----------------------------------------------- app-side emit convenience


def emit_perf_events(elog, device=None) -> None:
    """Drain pending compile events and the memory watermarks into an
    app's JSONL event log (one ``jit_compile`` event per compilation +
    one ``memory_watermark`` summary).  Safe to call with ``elog=None``
    (events stay queued for a later drain) and at any cadence."""
    if elog is None:
        return
    for ev in drain_compile_events():
        elog.emit("jit_compile", **ev)
    cache = compile_cache_stats()
    if cache.get("hits") or cache.get("misses"):
        # warm/cold split of this run's XLA compiles: hits came from the
        # persistent compilation cache (deserialize, no codegen)
        elog.emit("jit_cache_hit", hits=int(cache.get("hits", 0)),
                  misses=int(cache.get("misses", 0)))
    marks = memory_watermarks()
    if marks:
        elog.emit("memory_watermark", phases=marks,
                  snapshot=device_memory_snapshot(device))


# ----------------------------------------------------- diag perf aggregation


def aggregate_perf_events(events: List[dict]) -> dict:
    """Fold a JSONL event list into the ``diag perf`` attribution
    tables: per-function compile stats, per-phase memory watermarks,
    and transfer-audit counts."""
    fns: Dict[str, Dict[str, float]] = {}
    mem: Dict[str, float] = {}
    transfers: Dict[str, int] = {}
    cache = {"hits": 0, "misses": 0}
    snapshot = None
    for e in events:
        t = e.get("type")
        if t == "jit_cache_hit":
            for k in ("hits", "misses"):
                v = e.get(k)
                if isinstance(v, (int, float)):
                    cache[k] += int(v)
        elif t == "jit_compile":
            st = fns.setdefault(str(e.get("fn", "?")), {
                "compiles": 0, "lower_seconds": 0.0, "compile_seconds": 0.0,
                "flops": 0.0, "bytes_accessed": 0.0,
            })
            st["compiles"] += 1
            for k in ("lower_seconds", "compile_seconds"):
                v = e.get(k)
                if isinstance(v, (int, float)):
                    st[k] += float(v)
            for k in ("flops", "bytes_accessed"):
                v = e.get(k)
                if isinstance(v, (int, float)) and v:
                    st[k] = float(v)
        elif t == "memory_watermark":
            for phase, v in (e.get("phases") or {}).items():
                if isinstance(v, (int, float)):
                    mem[str(phase)] = max(mem.get(str(phase), 0.0), float(v))
            snapshot = e.get("snapshot") or snapshot
        elif t == "transfer_audit":
            for d, n in (e.get("counts") or {}).items():
                if isinstance(n, (int, float)):
                    transfers[str(d)] = transfers.get(str(d), 0) + int(n)
    return {"functions": fns, "memory": mem, "transfers": transfers,
            "compile_cache": cache, "memory_snapshot": snapshot}


def _fmt_bytes(n: Optional[float]) -> str:
    if not n:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}TiB"


def format_perf_report(agg: dict) -> str:
    """Human table for ``diag perf`` from :func:`aggregate_perf_events`
    output (also used on a live :func:`perf_stats` snapshot)."""
    lines = []
    fns = agg.get("functions") or {}
    if fns:
        w = max(len(n) for n in fns) + 2
        lines.append(f"{'function':<{w}}{'compiles':>9}{'lower_s':>9}"
                     f"{'compile_s':>11}{'gflops':>10}{'bytes':>10}")
        for name in sorted(fns, key=lambda n: -fns[n]["compile_seconds"]):
            st = fns[name]
            gf = st.get("flops", 0.0) / 1e9
            lines.append(
                f"{name:<{w}}{int(st['compiles']):>9}"
                f"{st['lower_seconds']:>9.2f}{st['compile_seconds']:>11.2f}"
                f"{(f'{gf:.2f}' if gf else '-'):>10}"
                f"{_fmt_bytes(st.get('bytes_accessed')):>10}"
            )
    else:
        lines.append("no jit_compile events (run with SAGECAL_TELEMETRY=1 "
                     "and an instrumented path)")
    cache = agg.get("compile_cache") or {}
    if cache.get("hits") or cache.get("misses"):
        h, m = int(cache.get("hits", 0)), int(cache.get("misses", 0))
        lines.append(f"persistent compile cache: {h} warm (cache hit), "
                     f"{m} cold (full compile)")
    mem = agg.get("memory") or {}
    if mem:
        lines.append("memory watermarks (peak per phase):")
        for phase in sorted(mem, key=mem.get, reverse=True):
            lines.append(f"  {phase}: {_fmt_bytes(mem[phase])}")
        snap = agg.get("memory_snapshot") or {}
        if snap.get("source"):
            lines.append(f"  source: {snap['source']} "
                         f"({snap.get('device_kind', 'unknown')})")
    transfers = agg.get("transfers") or {}
    if transfers:
        tot = sum(transfers.values())
        parts = ", ".join(f"{d}={n}" for d, n in sorted(transfers.items()))
        lines.append(f"transfer audit: {tot} implicit transfers ({parts})")
    return "\n".join(lines)


# ------------------------------------------------------------------- gate

# metric direction semantics: a regression is a drop for higher-better
# metrics and a rise for lower-better ones.  Metrics not listed are
# informational and never gate.
GATE_HIGHER_BETTER = (
    "value", "vs_baseline", "vs_reference_cpu",
    "analytic_tflops_per_sec", "analytic_hbm_gb_per_sec",
    "mfu_vs_device_peak", "bw_util_vs_device_peak",
    "warm_start_speedup", "coh_bf16_iters_per_sec",
    "solves_per_sec_per_chip", "serve_batch_speedup",
    "admm_collective_bytes_reduction", "refine_outer_iters_per_sec",
    "stream_warm_speedup", "fleet_solves_per_sec_2workers",
    "hier_predict_speedup", "saturation_throughput_solves_per_sec",
    "goodput_fraction_at_saturation",
)
GATE_LOWER_BETTER = (
    "xla_cost_analysis_bytes_accessed", "peak_device_memory_bytes",
    "compile_seconds_total", "coh_bf16_xla_cost_analysis_bytes_accessed",
    "serve_p50_latency_s", "admm_collective_bytes_per_round",
    "admm_straggler_ratio", "refine_flux_err",
    "latency_to_first_solution_s", "hier_predict_max_rel_err",
    # opt-in gate (--metric shed_rate_under_overload=tol): the shed
    # rate is admission-POLICY-shaped, not pure capacity, so it is
    # direction-tagged here but left out of GATE_DEFAULT_METRICS
    "shed_rate_under_overload",
    # numerical-truth rows (bench.run_shadow_drift_bench): p99 upper
    # bounds of live cross-path gain drift — a RISE means a kernel
    # path's numerics moved away from the xla/f32 reference
    "shadow_drift_batched_vs_xla_p99",
    "shadow_drift_bf16_vs_f32_p99",
)
# the metrics gated when present in BOTH records (others opt in via
# --metric name=tol)
GATE_DEFAULT_METRICS = (
    "value", "xla_cost_analysis_bytes_accessed", "peak_device_memory_bytes",
    "warm_start_speedup", "coh_bf16_iters_per_sec",
    "coh_bf16_xla_cost_analysis_bytes_accessed",
    "solves_per_sec_per_chip", "serve_batch_speedup", "serve_p50_latency_s",
    "admm_collective_bytes_per_round", "admm_collective_bytes_reduction",
    "refine_flux_err", "refine_outer_iters_per_sec",
    "latency_to_first_solution_s", "fleet_solves_per_sec_2workers",
    "hier_predict_speedup", "hier_predict_max_rel_err",
    "saturation_throughput_solves_per_sec",
    "goodput_fraction_at_saturation",
    "shadow_drift_batched_vs_xla_p99", "shadow_drift_bf16_vs_f32_p99",
)
GATE_DEFAULT_TOLERANCE = 0.10


def gate_compare(new: dict, baseline: dict,
                 tolerances: Optional[Dict[str, float]] = None,
                 default_tol: float = GATE_DEFAULT_TOLERANCE,
                 metrics: Optional[Tuple[str, ...]] = None):
    """Compare a fresh bench record against the pinned baseline.

    Returns ``(failures, rows)``: ``failures`` is the list of
    human-readable regression strings (empty = gate passes); ``rows``
    is one ``(metric, base, new, ratio, tol, status)`` tuple per
    compared metric for the report table.  A metric is compared when
    it is numeric and non-zero in the baseline and present in the new
    record; per-metric tolerances override ``default_tol``."""
    tolerances = tolerances or {}
    names = list(metrics if metrics is not None else GATE_DEFAULT_METRICS)
    for extra in tolerances:
        if extra not in names:
            names.append(extra)
    failures, rows = [], []
    for m in names:
        b, n = baseline.get(m), new.get(m)
        if not isinstance(b, (int, float)) or not isinstance(n, (int, float)):
            continue
        if b == 0:
            continue
        tol = float(tolerances.get(m, default_tol))
        ratio = float(n) / float(b)
        if m in GATE_LOWER_BETTER:
            bad = ratio > 1.0 + tol
            direction = "rose"
        else:
            bad = ratio < 1.0 - tol
            direction = "dropped"
        status = "FAIL" if bad else "ok"
        rows.append((m, float(b), float(n), ratio, tol, status))
        if bad:
            failures.append(
                f"{m} {direction} beyond tolerance: baseline {b:g} -> "
                f"{n:g} (ratio {ratio:.3f}, tol {tol:.0%})"
            )
    return failures, rows


def format_gate_report(rows, failures) -> str:
    lines = []
    if rows:
        w = max(len(r[0]) for r in rows) + 2
        lines.append(f"{'metric':<{w}}{'baseline':>14}{'new':>14}"
                     f"{'ratio':>8}{'tol':>7}  status")
        for m, b, n, ratio, tol, status in rows:
            lines.append(f"{m:<{w}}{b:>14.6g}{n:>14.6g}{ratio:>8.3f}"
                         f"{tol:>6.0%}  {status}")
    else:
        lines.append("no comparable metrics between the two records")
        lines.append("GATE: FAIL (nothing comparable)")
        return "\n".join(lines)
    lines.append("GATE: " + ("FAIL" if failures else "PASS"))
    return "\n".join(lines)


# --------------------------------------------------------- bench history

# one line per bench run, forever: the perf trajectory the single-slot
# BENCH_BASELINE.json diff cannot hold.  Schema-versioned JSONL next to
# the repo root (or SAGECAL_BENCH_HISTORY); `diag serve` renders trend
# deltas over the last K rows against the gate direction tables above.
# v2 (PR 16): rows additionally stamp `evidence` (evidence class of the
# record, see obs/evidence.py) and carry `device_kind`; v1 rows are
# upgraded in place by tools/backfill_bench_history.py and both schemas
# stay readable forever.
BENCH_HISTORY_SCHEMA_VERSION = 2
DEFAULT_BENCH_HISTORY = "BENCH_HISTORY.jsonl"


def bench_history_path(path: Optional[str] = None) -> str:
    return path or os.environ.get("SAGECAL_BENCH_HISTORY") \
        or DEFAULT_BENCH_HISTORY


def _git_rev() -> str:
    try:
        import subprocess

        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else "unknown"
    except Exception:
        return "unknown"


def append_bench_history(rec: dict, path: Optional[str] = None) -> str:
    """Append one bench record to the history JSONL (single O_APPEND
    write — concurrent bench runs never tear lines).  Stamps schema
    version, wall-clock, git revision and a fingerprint of the bench
    config so trend rows are only compared like-for-like.  Returns the
    path written."""
    from sagecal_tpu.elastic.checkpoint import config_fingerprint

    path = bench_history_path(path)
    cfg_keys = ("mode", "shape", "iters", "batch", "dtype", "backend",
                "kernel", "device_kind", "platform")
    from sagecal_tpu.obs.evidence import record_evidence

    row = {
        "history_schema_version": BENCH_HISTORY_SCHEMA_VERSION,
        "ts": time.time(),
        "git_rev": _git_rev(),
        "config_fingerprint": config_fingerprint(
            **{k: rec.get(k) for k in cfg_keys if k in rec})[:16],
    }
    # schema v2: stamp the evidence class at measurement time (explicit
    # field wins, else derived from platform); rows where neither
    # resolves stay unstamped rather than guessed
    ev = record_evidence(rec)
    if ev is not None:
        row["evidence"] = ev
    for k, v in rec.items():
        row.setdefault(k, v)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd = os.open(path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
    try:
        os.write(fd, (json.dumps(row, default=str) + "\n").encode("utf-8"))
    finally:
        os.close(fd)
    return path


def read_bench_history(path: Optional[str] = None) -> List[dict]:
    """Every parseable row of the bench history, in file order (skips
    corrupt lines like every other JSONL reader here)."""
    path = bench_history_path(path)
    if not os.path.exists(path):
        return []
    out = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(row, dict):
                out.append(row)
    return out


def bench_trend(history: List[dict], last_k: int = 5,
                metrics: Optional[Tuple[str, ...]] = None) -> List[dict]:
    """Trend deltas over the last K same-fingerprint runs: for each
    metric present in the newest row, the oldest-in-window -> newest
    ratio plus a direction verdict from the gate tables (``better`` /
    ``worse`` / ``flat`` / ``info``)."""
    from sagecal_tpu.obs.evidence import comparable, record_evidence

    if not history:
        return []
    newest = history[-1]
    fp = newest.get("config_fingerprint")
    # evidence refusal (PR 16): rows whose evidence class RESOLVES and
    # mismatches the newest row's are not trend-comparable (a CPU
    # fallback run must never trend against TPU rows); rows where
    # neither `evidence` nor `platform` resolves (pre-v2 / synthetic)
    # stay comparable, so legacy history keeps working
    ev_new = record_evidence(newest)
    window = [r for r in history
              if r.get("config_fingerprint") == fp
              and comparable(record_evidence(r), ev_new)][-max(last_k, 2):]
    if len(window) < 2:
        return []
    oldest = window[0]
    names = metrics if metrics is not None else tuple(
        m for m in GATE_DEFAULT_METRICS if m in newest)
    out = []
    for m in names:
        a, b = oldest.get(m), newest.get(m)
        if not isinstance(a, (int, float)) or not isinstance(b, (int, float)) \
                or isinstance(a, bool) or isinstance(b, bool) or a == 0:
            continue
        ratio = float(b) / float(a)
        if m in GATE_LOWER_BETTER:
            verdict = ("better" if ratio < 0.98
                       else "worse" if ratio > 1.02 else "flat")
        elif m in GATE_HIGHER_BETTER:
            verdict = ("better" if ratio > 1.02
                       else "worse" if ratio < 0.98 else "flat")
        else:
            verdict = "info"
        out.append({
            "metric": m, "first": float(a), "last": float(b),
            "ratio": ratio, "runs": len(window), "verdict": verdict,
            "first_rev": str(oldest.get("git_rev", "?")),
            "last_rev": str(newest.get("git_rev", "?")),
        })
    return out


def format_bench_trend(trend: List[dict]) -> str:
    """Trend table for ``diag serve``."""
    if not trend:
        return "(no bench history trend: fewer than 2 comparable runs)"
    w = max(len(t["metric"]) for t in trend) + 2
    lines = [f"{'metric':<{w}}{'first':>14}{'last':>14}{'ratio':>8}"
             f"{'runs':>6}  trend"]
    for t in trend:
        lines.append(
            f"{t['metric']:<{w}}{t['first']:>14.6g}{t['last']:>14.6g}"
            f"{t['ratio']:>8.3f}{t['runs']:>6}  {t['verdict']} "
            f"({t['first_rev']} -> {t['last_rev']})")
    return "\n".join(lines)
