"""Host-side calibration-quality analysis: reports, watchdog, heatmaps.

The device half of this layer (:mod:`sagecal_tpu.ops.quality`) returns
fixed-shape :class:`~sagecal_tpu.ops.quality.SolveQuality` pytrees from
inside the jitted solves.  This module is everything that happens AFTER
the solve returns on the host:

- :func:`quality_to_host` — materialize a (possibly cluster-stacked)
  ``SolveQuality`` into plain numpy arrays keyed by field name.
- :func:`assess_quality` — the watchdog verdict: ``"ok"`` /
  ``"degraded"`` / ``"diverged"`` with human-readable reasons.  Divergence
  means the solution is unusable (non-finite gains or chi^2); degradation
  means it is suspect (a station's chi^2 is a large outlier, the robust
  weights flattened most of the data).
- :func:`check_and_emit` — the one-call app hook: emit a
  ``solve_quality`` event, update registry gauges, and escalate to a
  ``quality_degraded`` / ``solver_diverged`` event when warranted.
- :func:`assess_consensus` — the ADMM side of the watchdog, reading the
  per-band residual trajectories that distributed/minibatch runs attach
  to their ``admm_round`` events.
- :func:`check_hier_predict` — the hierarchical-sky-predict side of the
  watchdog: gauges the sampled a-posteriori error of
  ``predict_coherencies_hier`` and degrades the verdict when it
  violates the configured (order, theta) error knob.
- PPM heatmap writers + :func:`analyze_events` backing ``diag quality``.

Nothing here imports jax; everything operates on materialized numpy
arrays (the ``obs`` package contract — usable before backend selection
and on hosts without a device).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from sagecal_tpu.obs.registry import get_registry
from sagecal_tpu.utils.ppm import write_ppm

# A station whose chi^2 exceeds this multiple of the median (over
# stations with data) is flagged as an outlier — the classic "one bad
# station" signature the reference finds by eyeballing residual images.
CHI2_OUTLIER_RATIO = 25.0
# Degradation threshold on the effectively down-weighted fraction: when
# the robust weights have flattened more than this share of the
# unflagged data, the Gaussian interpretation of chi^2 is gone.
DOWNWEIGHT_WARN_FRAC = 0.5
# ADMM primal-residual growth (final / trajectory-min) beyond this is
# divergence, matching parallel.consensus.consensus_health's default.
CONSENSUS_TREND_THRESH = 2.0


class DivergenceAbort(RuntimeError):
    """Raised by apps running with ``abort_on_divergence`` when the
    watchdog reports a diverged solve (after the structured
    ``run_aborted`` event is emitted)."""


def _np(x) -> Optional[np.ndarray]:
    return None if x is None else np.asarray(x)


def quality_to_host(q) -> dict:
    """Materialize a ``SolveQuality`` (or the dict sagefit returns, or an
    already-converted dict) into ``{field: numpy array}`` with ``None``
    fields dropped.  Stacked leading axes (per-cluster quality out of the
    SAGE EM scan) are preserved."""
    if q is None:
        return {}
    if isinstance(q, dict):
        # sagefit's {"em": per-cluster SolveQuality, "final": SolveQuality}
        return {k: quality_to_host(v) for k, v in q.items() if v is not None}
    d = q._asdict() if hasattr(q, "_asdict") else dict(q)
    return {k: _np(v) for k, v in d.items() if v is not None}


def _total_chi2(qd: dict) -> Optional[float]:
    ch = qd.get("chi2_chunk")
    if ch is None:
        return None
    return float(np.sum(ch))


def _station_chi2(qd: dict) -> Optional[np.ndarray]:
    st = qd.get("chi2_station")
    if st is None:
        return None
    st = np.asarray(st, float)
    # per-cluster stacks reduce to total attribution per station
    return st.reshape(-1, st.shape[-1]).sum(axis=0) if st.ndim > 1 else st


def assess_quality(
    qd: dict,
    chi2_outlier_ratio: float = CHI2_OUTLIER_RATIO,
    downweight_warn: float = DOWNWEIGHT_WARN_FRAC,
) -> Tuple[str, List[str]]:
    """Watchdog verdict for one solve's host-side quality dict.

    Returns ``(verdict, reasons)`` with verdict one of ``"ok"``,
    ``"degraded"``, ``"diverged"``.  Accepts the output of
    :func:`quality_to_host` on any solver's quality (missing fields are
    simply not checked); sagefit's ``{"em": ..., "final": ...}`` bundles
    are assessed on the ``final`` entry.
    """
    if "final" in qd or "em" in qd:
        qd = qd.get("final", qd.get("em", {}))
    reasons: List[str] = []
    diverged = False

    nf = qd.get("nonfinite_count")
    if nf is not None and float(np.sum(nf)) > 0:
        diverged = True
        reasons.append(f"nonfinite_gains:{int(np.sum(nf))}")

    st = _station_chi2(qd)
    if st is not None:
        if not np.all(np.isfinite(st)):
            diverged = True
            reasons.append("nonfinite_chi2")
        else:
            active = st[st > 0]
            med = float(np.median(active)) if active.size else 0.0
            if med > 0:
                bad = np.nonzero(st > chi2_outlier_ratio * med)[0]
                if bad.size:
                    reasons.append(
                        "station_chi2_outlier:"
                        + ",".join(str(int(b)) for b in bad)
                    )

    dw = qd.get("downweighted_frac")
    if dw is not None and float(np.max(dw)) > downweight_warn:
        reasons.append(f"downweighted_frac:{float(np.max(dw)):.3f}")

    if diverged:
        return "diverged", reasons
    return ("degraded", reasons) if reasons else ("ok", reasons)


def assess_consensus(
    primal_res_band,
    dual_res_band,
    trend_thresh: float = CONSENSUS_TREND_THRESH,
    ages=None,
    staleness: Optional[int] = None,
) -> Tuple[str, List[str], dict]:
    """ADMM watchdog: per-band health from the (nadmm, Nf) residual
    trajectories (the arrays distributed runs attach to ``admm_round``
    events).  Returns ``(verdict, reasons, health)`` where ``health`` has
    the per-band ``ratio`` / ``trend`` / ``diverged`` arrays of
    :func:`sagecal_tpu.parallel.consensus.consensus_health` (the shared
    definition — imported lazily so this module stays jax-free until an
    ADMM run actually uses it).

    ``ages`` / ``staleness``: the bounded-staleness run's final ledger
    ages and bound (``--consensus-staleness``).  A band solving on
    K-round-old consensus targets legitimately tracks its trajectory
    minimum more loosely, so the trend threshold relaxes by
    ``(1 + age)`` per band, while a STARVED band (age beyond the bound,
    dropped from the Z solve) is divergence outright — both criteria
    live in ``consensus_health``."""
    from sagecal_tpu.parallel.consensus import consensus_health

    pr = np.atleast_2d(np.asarray(primal_res_band, float))
    du = np.atleast_2d(np.asarray(dual_res_band, float))
    ratio, trend, diverged = (
        np.asarray(x) for x in consensus_health(
            pr, du, trend_thresh, ages=ages, staleness=staleness)
    )
    health = {"ratio": ratio, "trend": trend, "diverged": diverged}
    reasons: List[str] = []
    bad = np.nonzero(diverged)[0]
    if bad.size:
        reasons.append(
            "consensus_diverged_bands:" + ",".join(str(int(b)) for b in bad)
        )
        return "diverged", reasons, health
    return "ok", reasons, health


def quality_summary(qd: dict) -> dict:
    """Compact JSON-ready summary of one solve's quality dict (full
    per-station / per-baseline arrays ride along for the heatmaps)."""
    if "final" in qd or "em" in qd:
        qd = qd.get("final", qd.get("em", {}))
    out: dict = {}
    tot = _total_chi2(qd)
    if tot is not None:
        out["chi2_total"] = tot
    st = _station_chi2(qd)
    if st is not None:
        out["chi2_station"] = st
        if st.size and np.all(np.isfinite(st)):
            out["chi2_station_worst"] = int(np.argmax(st))
    for k in ("chi2_baseline", "nonfinite_count", "nu", "weight_hist",
              "downweighted_frac", "flagged_frac", "station_amp",
              "station_amp_spread", "station_phase_spread",
              "identity_departure"):
        if qd.get(k) is not None:
            out[k] = qd[k]
    return out


def check_and_emit(
    elog,
    quality,
    log=None,
    **context,
) -> Tuple[str, List[str]]:
    """The app-side hook: assess one solve's quality, emit the
    ``solve_quality`` event (plus ``quality_degraded`` /
    ``solver_diverged`` on escalation), and refresh registry gauges.

    ``elog`` may be None (telemetry off) — the assessment still runs so
    the caller can abort on divergence either way.  ``context`` fields
    (tile, cluster, app, ...) are copied onto every emitted event.
    Returns ``(verdict, reasons)``.
    """
    qd = quality_to_host(quality)
    verdict, reasons = assess_quality(qd)
    summary = quality_summary(qd)

    reg = get_registry()
    if "chi2_total" in summary:
        reg.gauge_set("sagecal_quality_chi2_total", summary["chi2_total"],
                      help="total chi^2 of the latest solve")
    nf = summary.get("nonfinite_count")
    if nf is not None:
        reg.gauge_set("sagecal_quality_nonfinite_params",
                      float(np.sum(nf)),
                      help="non-finite gain parameters in the latest solve")
    dw = summary.get("downweighted_frac")
    if dw is not None:
        reg.gauge_set("sagecal_quality_downweighted_frac",
                      float(np.max(dw)),
                      help="fraction of unflagged data down-weighted "
                           "below 0.5 by the robust weights")
    if verdict != "ok":
        reg.counter_inc("sagecal_quality_watchdog_total",
                        help="watchdog escalations", verdict=verdict)

    if elog is not None:
        elog.emit("solve_quality", verdict=verdict, reasons=reasons,
                  **summary, **context)
        if verdict == "diverged":
            elog.emit("solver_diverged", reasons=reasons, **context)
        elif verdict == "degraded":
            elog.emit("quality_degraded", reasons=reasons, **context)
    if log is not None and verdict != "ok":
        log(f"quality watchdog: {verdict} ({', '.join(reasons)})")
    return verdict, reasons


def check_hier_predict(
    elog,
    rel_err: float,
    bound: float,
    log=None,
    **context,
) -> Tuple[str, List[str]]:
    """Watchdog hook for the hierarchical sky predict: verify the
    sampled a-posteriori error of a ``predict_coherencies_hier`` call
    against the configured error knob.

    ``rel_err`` is the sampled relative error
    (:func:`sagecal_tpu.sky.predict.sampled_error_estimate`);
    ``bound`` is the knob it must stay under (the app's
    ``hier_max_rel_err``, by default at least as large as the a-priori
    Taylor bound of the chosen (order, theta)).  Emits a
    ``hier_predict_check`` event, refreshes the
    ``sagecal_hier_predict_error`` gauge, and escalates to a
    ``quality_degraded`` event + watchdog counter when the knob is
    violated (or the estimate went non-finite).  Returns
    ``(verdict, reasons)`` — ``"ok"`` or ``"degraded"``; a violated
    expansion never DIVERGES a run on its own (the solve watchdog
    owns that verdict).
    """
    rel_err = float(rel_err)
    bound = float(bound)
    verdict, reasons = "ok", []
    if not np.isfinite(rel_err):
        verdict = "degraded"
        reasons.append("hier predict error is non-finite")
    elif rel_err > bound:
        verdict = "degraded"
        reasons.append(
            f"hier predict sampled rel err {rel_err:.3e} exceeds "
            f"bound {bound:.3e}")

    reg = get_registry()
    reg.gauge_set("sagecal_hier_predict_error",
                  rel_err if np.isfinite(rel_err) else -1.0,
                  help="sampled relative error of the latest "
                       "hierarchical sky prediction vs exact")
    if verdict != "ok":
        reg.counter_inc("sagecal_quality_watchdog_total",
                        help="watchdog escalations", verdict=verdict)

    if elog is not None:
        elog.emit("hier_predict_check", verdict=verdict, reasons=reasons,
                  rel_err=rel_err, bound=bound, **context)
        if verdict == "degraded":
            elog.emit("quality_degraded", reasons=reasons, **context)
    if log is not None and verdict != "ok":
        log(f"hier predict watchdog: {verdict} ({', '.join(reasons)})")
    return verdict, reasons


def abort_if_diverged(elog, verdict: str, reasons: Sequence[str],
                      **context) -> None:
    """The ``--abort-on-divergence`` exit path: emit a structured
    ``run_aborted`` event, close the log, and raise
    :class:`DivergenceAbort`."""
    if verdict != "diverged":
        return
    if elog is not None:
        elog.emit("run_aborted", reason="solver_diverged",
                  details=list(reasons), **context)
        elog.close()
    raise DivergenceAbort(
        "solver diverged (" + ", ".join(reasons) + "); aborting "
        "(abort_on_divergence)"
    )


# ---------------------------------------------------------------- heatmaps


def _lognorm(a: np.ndarray) -> np.ndarray:
    """Non-negative array -> [0,1] on a log1p scale (chi^2 spans orders
    of magnitude; linear scaling would show only the worst cell).
    Non-finite cells render hot (1.0)."""
    a = np.asarray(a, float)
    bad = ~np.isfinite(a)
    a = np.where(bad, 0.0, np.maximum(a, 0.0))
    v = np.log1p(a)
    top = float(v.max()) if v.size else 0.0
    out = v / top if top > 0 else np.zeros_like(v)
    return np.where(bad, 1.0, out)


def _upscale(img: np.ndarray, min_px: int = 256) -> np.ndarray:
    """Integer-replicate a small matrix so each cell is a visible block
    (PPM viewers do no interpolation)."""
    h, w = img.shape
    s = max(1, int(np.ceil(min_px / max(h, w, 1))))
    return np.kron(img, np.ones((s, s))) if s > 1 else img


def write_station_heatmap(chi2_station, path: str, min_px: int = 256):
    """Per-station chi^2 heatmap: rows = solves/tiles (or clusters),
    columns = stations, log-normalized blue->green->red."""
    a = np.atleast_2d(np.asarray(chi2_station, float))
    write_ppm(path, _upscale(_lognorm(a), min_px))


def write_baseline_heatmap(chi2_baseline, path: str, min_px: int = 256):
    """Per-baseline chi^2 heatmap: the (N, N) attribution symmetrized
    (rows scatter to (p, q) only), log-normalized."""
    a = np.asarray(chi2_baseline, float)
    a = a + a.T
    write_ppm(path, _upscale(_lognorm(a), min_px))


# ------------------------------------------------------- event-log analysis


def analyze_events(events: Sequence[dict],
                   trend_thresh: float = CONSENSUS_TREND_THRESH) -> dict:
    """Build the ``diag quality`` report from a run's event list.

    Reads every ``solve_quality`` event (re-assessing each with the
    current thresholds) and every ``admm_round`` event carrying per-band
    residual trajectories (assessed with :func:`assess_consensus`).  Any
    ``solver_diverged`` / ``run_aborted`` event recorded by the run
    itself also marks the report diverged.  Returns a dict with
    ``diverged`` / ``degraded`` flags, per-solve summaries, consensus
    health, and the stacked arrays the heatmap writers want
    (``station_matrix`` rows = solves, ``baseline_total``)."""
    solves: List[dict] = []
    station_rows: List[np.ndarray] = []
    baseline_total: Optional[np.ndarray] = None
    consensus: List[dict] = []
    diverged = False
    degraded = False
    reasons: List[str] = []

    for e in events:
        t = e.get("type")
        if t in ("solver_diverged", "run_aborted"):
            diverged = True
            reasons.append(
                f"{t}:" + ",".join(map(str, e.get("reasons")
                                       or e.get("details") or []))
            )
        elif t == "solve_quality":
            qd = {k: np.asarray(v) for k, v in e.items()
                  if k in ("chi2_station", "chi2_baseline", "chi2_chunk",
                           "nonfinite_count", "downweighted_frac")
                  and v is not None}
            verdict, why = assess_quality(qd)
            rec = {k: e.get(k) for k in ("tile", "cluster", "epoch")
                   if k in e}
            rec.update(verdict=verdict, reasons=why,
                       chi2_total=e.get("chi2_total"),
                       nu=e.get("nu"))
            solves.append(rec)
            if verdict == "diverged":
                diverged = True
                reasons.extend(why)
            elif verdict == "degraded":
                degraded = True
                reasons.extend(why)
            st = _station_chi2(qd)
            if st is not None:
                station_rows.append(st)
            bl = qd.get("chi2_baseline")
            if bl is not None:
                bl = np.asarray(bl, float)
                bl = bl.reshape((-1,) + bl.shape[-2:]).sum(axis=0)
                baseline_total = (
                    bl if baseline_total is None else baseline_total + bl
                )
        elif t == "consensus_health":
            # minibatch runs assess in-process and record the verdict
            rec = {k: e.get(k) for k in ("epoch", "minibatch", "tile",
                                         "verdict", "reasons", "ratio",
                                         "trend") if k in e}
            consensus.append(rec)
            if e.get("verdict") == "diverged":
                diverged = True
                reasons.extend(e.get("reasons") or ["consensus_diverged"])
        elif t == "admm_round" and e.get("primal_res_band") is not None:
            verdict, why, health = assess_consensus(
                e["primal_res_band"], e["dual_res_band"], trend_thresh
            )
            consensus.append({
                "tile": e.get("tile"), "verdict": verdict,
                "reasons": why,
                "ratio": health["ratio"].tolist(),
                "trend": health["trend"].tolist(),
            })
            if verdict == "diverged":
                diverged = True
                reasons.extend(why)

    return {
        "diverged": diverged,
        "degraded": degraded,
        "reasons": reasons,
        "n_solve_quality_events": len(solves),
        "solves": solves,
        "consensus": consensus,
        "station_matrix": (
            np.stack(station_rows) if station_rows else None
        ),
        "baseline_total": baseline_total,
    }
