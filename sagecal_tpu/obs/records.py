"""Fixed-shape per-iteration solver trace records (jit/scan-safe).

Solver telemetry cannot use host callbacks (axon dispatch is async and
callbacks would break AOT + the fused Pallas path), so each solver
optionally returns an :class:`IterTrace` as an extra *pytree output*:
preallocated ``(itmax, ...)`` arrays carried through the solver's
``lax.while_loop`` / ``lax.scan`` and written at the live iteration
index.  Shapes are compile-time constants (the static ``itmax``), so the
record is scan/vmap-composable: stacking over clusters or EM passes just
adds leading axes.

Collection is opt-in per call (``collect_trace=True`` or
``SageConfig.collect_telemetry``) and *statically* gated: with the flag
off the solver builds the exact same jaxpr as before — the trace slot in
results is ``None`` (an empty pytree), i.e. zero extra jitted outputs
(regression-tested in tests/test_obs.py).

Rows past the executed iteration count keep their ``init`` fill (NaN for
cost-like fields), so host-side consumers can trim with
``~isnan(cost)`` or the solver's ``iterations`` output.
"""

from __future__ import annotations

from typing import Any, NamedTuple


class IterTrace(NamedTuple):
    """One solver run's per-iteration telemetry.

    Leading axis of every field is the iteration index (static itmax);
    trailing axes are solver-specific (e.g. the hybrid-chunk axis for LM,
    none for the joint LBFGS).  Wrappers (robust EM, SAGE's cluster scan)
    stack further axes *in front*.

    Fields:
      cost:      objective value after the iteration
      grad_norm: gradient norm used by the solver's own termination test
                 (inf-norm for LM, 2-norm for LBFGS/RTR)
      step:      step size (||dp|| for LM, accepted alpha for LBFGS,
                 ||eta|| for RTR's TR step)
      ls_evals:  cost-function evaluations consumed by the iteration's
                 line search / trial acceptance
      nu:        robust Student's-t nu in effect (constant for
                 non-robust solvers)
    """

    cost: Any
    grad_norm: Any
    step: Any
    ls_evals: Any
    nu: Any


def init_trace(itmax: int, shape=(), dtype=None) -> IterTrace:
    """NaN-filled trace of ``(itmax,) + shape`` per field (NaN marks
    never-executed iterations; ls_evals uses 0)."""
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32
    nanfill = jnp.full((itmax,) + tuple(shape), jnp.nan, dtype)
    return IterTrace(
        cost=nanfill,
        grad_norm=nanfill,
        step=nanfill,
        ls_evals=jnp.zeros((itmax,) + tuple(shape), dtype),
        nu=jnp.full((itmax,), jnp.nan, dtype),
    )


def write_trace(trace: IterTrace, i, *, cost=None, grad_norm=None,
                step=None, ls_evals=None, nu=None) -> IterTrace:
    """Write iteration ``i``'s row (traced index ok); ``None`` fields
    keep their previous value."""
    upd = {}
    for name, val in (("cost", cost), ("grad_norm", grad_norm),
                      ("step", step), ("ls_evals", ls_evals), ("nu", nu)):
        if val is not None:
            upd[name] = getattr(trace, name).at[i].set(val)
    return trace._replace(**upd)


def _reduce_chunk_axis(name, a):
    """Collapse the trailing hybrid-chunk axis NaN-awarely: total cost /
    line-search evals across chunks, worst-case grad norm / step.  Rows
    where every chunk is NaN (never executed) stay NaN."""
    import numpy as np

    finite = np.isfinite(a)
    anyf = finite.any(-1)
    if name in ("cost", "ls_evals"):
        red = np.where(finite, a, 0.0).sum(-1)
    else:
        red = np.where(finite, a, -np.inf).max(-1)
    return np.where(anyf, red, np.nan)


def sage_convergence_records(telemetry) -> list:
    """Flatten ``SageResult.telemetry`` into per-cluster convergence
    records for the JSONL event log: one dict per cluster with
    finite-filtered per-iteration cost/grad_norm/step/ls_evals/nu
    (EM passes concatenated in execution order), plus one record for the
    joint LBFGS polish (``cluster=None``).  EM passes of different
    solver modes (OS subsets, robust EM stacks) flatten independently,
    so heterogeneous trace shapes concatenate cleanly."""
    import numpy as np

    if not telemetry:
        return []
    out = []
    per_pass = []
    for tr in telemetry.get("em") or ():
        cost = np.asarray(tr.cost)  # leading axis = cluster
        M = cost.shape[0]
        flat = {}
        for name in tr._fields:
            a = np.asarray(getattr(tr, name))
            if a.ndim == cost.ndim:  # field carries the chunk axis
                a = _reduce_chunk_axis(name, a)
            flat[name] = a.reshape(M, -1)
        per_pass.append(flat)
    if per_pass:
        for m in range(per_pass[0]["cost"].shape[0]):
            cost = np.concatenate([p["cost"][m] for p in per_pass])
            keep = np.isfinite(cost)
            rec = {"cluster": m, "iterations": int(keep.sum())}
            for name in IterTrace._fields:
                vals = np.concatenate([p[name][m] for p in per_pass])[keep]
                rec[name] = [
                    float(v) if np.isfinite(v) else None for v in vals
                ]
            out.append(rec)
    lb = telemetry.get("lbfgs")
    if lb is not None:
        cost = np.asarray(lb.cost).reshape(-1)
        keep = np.isfinite(cost)
        rec = {"cluster": None, "solver": "lbfgs",
               "iterations": int(keep.sum())}
        for name in IterTrace._fields:
            vals = np.asarray(getattr(lb, name)).reshape(-1)[keep]
            rec[name] = [float(v) if np.isfinite(v) else None for v in vals]
        out.append(rec)
    return out


def trace_to_host(trace) -> dict:
    """Materialize a (possibly nested/stacked) trace pytree into plain
    nested lists for the JSONL event log; NaN rows are preserved (they
    mark unexecuted iterations)."""
    import numpy as np

    if trace is None:
        return {}
    return {
        name: np.asarray(getattr(trace, name)).tolist()
        for name in trace._fields
    }
