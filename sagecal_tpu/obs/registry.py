"""Metrics registry: counters / gauges / histograms, Prometheus export.

Host-side half of the telemetry layer (see :mod:`sagecal_tpu.obs`).
Nothing in here touches a tracer: jitted code returns fixed-shape trace
records (:mod:`sagecal_tpu.obs.records`) as auxiliary pytree outputs,
and the *host* feeds the materialized numbers into this registry after
the solve returns.  That keeps collection host-callback-free — no
``io_callback``/``debug.callback`` inside traced code, so the fused
Pallas path and AOT compilation are unaffected.

Zero-cost-when-disabled: :func:`get_registry` hands out a shared
:class:`NullRegistry` whose mutators are no-ops when telemetry is off
(``SAGECAL_TELEMETRY`` unset / falsy), so instrumented call sites never
need their own guards.
"""

from __future__ import annotations

import bisect
import math
import os
import threading
from contextlib import contextmanager
from typing import Dict, Optional, Tuple

_TRUTHY = ("1", "true", "yes", "on")


def _env_enabled() -> bool:
    return os.environ.get("SAGECAL_TELEMETRY", "").strip().lower() in _TRUTHY


_enabled: Optional[bool] = None  # None -> defer to the env var


def telemetry_enabled() -> bool:
    """Master telemetry switch: ``set_telemetry`` override if set,
    otherwise the ``SAGECAL_TELEMETRY`` env var."""
    if _enabled is not None:
        return _enabled
    return _env_enabled()


def set_telemetry(on: Optional[bool]) -> None:
    """Force telemetry on/off for this process (``None`` restores env-var
    control).  Solvers read the flag at *trace* time; flipping it after a
    function was jitted does not retrace cached signatures."""
    global _enabled
    _enabled = on


@contextmanager
def telemetry(on: bool = True):
    """Scoped :func:`set_telemetry` (used by tests)."""
    global _enabled
    prev = _enabled
    _enabled = on
    try:
        yield
    finally:
        _enabled = prev


# default histogram buckets: wall-clock seconds from sub-ms to minutes
_DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0, 300.0
)


def _labels_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _fmt_labels(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


class _Histogram:
    __slots__ = ("buckets", "counts", "count", "total", "vmin", "vmax")

    def __init__(self, buckets):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")

    def observe(self, value: float) -> None:
        self.counts[bisect.bisect_left(self.buckets, value)] += 1
        self.count += 1
        self.total += value
        self.vmin = min(self.vmin, value)
        self.vmax = max(self.vmax, value)

    def snapshot(self) -> dict:
        """JSON-able full state (bucket edges included so shards from
        different processes can be merged after the fact)."""
        return {
            "buckets": list(self.buckets),
            "counts": list(self.counts),
            "count": self.count,
            "sum": self.total,
            "min": self.vmin if self.count else None,
            "max": self.vmax if self.count else None,
        }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "_Histogram":
        h = cls(snap["buckets"])
        counts = list(snap["counts"])
        if len(counts) != len(h.counts):
            raise ValueError(
                f"histogram snapshot has {len(counts)} buckets, "
                f"expected {len(h.counts)}")
        h.counts = [int(c) for c in counts]
        h.count = int(snap["count"])
        h.total = float(snap["sum"])
        if h.count:
            h.vmin = float(snap["min"])
            h.vmax = float(snap["max"])
        return h

    def merge(self, other: "_Histogram") -> None:
        """Fold ``other`` into this histogram in place.  Bucket layouts
        must match exactly — merging is only defined shard-by-shard over
        the same metric."""
        if self.buckets != other.buckets:
            raise ValueError(
                f"cannot merge histograms with different buckets: "
                f"{self.buckets} vs {other.buckets}")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.total += other.total
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)

    def quantile_bounds(self, q: float) -> Optional[Tuple[float, float]]:
        """Exact (lower, upper) bound on the q-quantile from bucket
        counts alone.  The true quantile provably lies in the returned
        closed interval; ``None`` when the histogram is empty."""
        if self.count == 0:
            return None
        q = min(max(float(q), 0.0), 1.0)
        rank = min(self.count, max(1, math.ceil(q * self.count - 1e-9)))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                lo = self.buckets[i - 1] if i > 0 else float("-inf")
                hi = self.buckets[i] if i < len(self.buckets) else float("inf")
                # observed extremes tighten open-ended edges
                lo = max(lo, self.vmin)
                hi = min(hi, self.vmax)
                return (lo, hi)
        return (self.vmin, self.vmax)


class MetricsRegistry:
    """Threadsafe counter/gauge/histogram store with Prometheus text
    export (exposition format 0.0.4).  Metric names should be
    ``snake_case``; labels are free-form key/value strings."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Dict[tuple, float]] = {}
        self._gauges: Dict[str, Dict[tuple, float]] = {}
        self._histograms: Dict[str, Dict[tuple, _Histogram]] = {}
        self._help: Dict[str, str] = {}

    @property
    def enabled(self) -> bool:
        return True

    def counter_inc(self, name: str, value: float = 1.0,
                    help: Optional[str] = None, **labels) -> None:
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            series = self._counters.setdefault(name, {})
            key = _labels_key(labels)
            series[key] = series.get(key, 0.0) + float(value)

    def gauge_set(self, name: str, value: float,
                  help: Optional[str] = None, **labels) -> None:
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            self._gauges.setdefault(name, {})[_labels_key(labels)] = float(value)

    def observe(self, name: str, value: float,
                buckets=_DEFAULT_BUCKETS,
                help: Optional[str] = None, **labels) -> None:
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            series = self._histograms.setdefault(name, {})
            key = _labels_key(labels)
            if key not in series:
                series[key] = _Histogram(buckets)
            series[key].observe(float(value))

    def get_counter(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get(name, {}).get(_labels_key(labels), 0.0)

    def get_gauge(self, name: str, **labels) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name, {}).get(_labels_key(labels))

    def snapshot(self) -> dict:
        """Plain-dict dump (JSONL-embeddable; see obs.events)."""
        with self._lock:
            out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
            for name, series in self._counters.items():
                for key, v in series.items():
                    out["counters"][name + _fmt_labels(key)] = v
            for name, series in self._gauges.items():
                for key, v in series.items():
                    out["gauges"][name + _fmt_labels(key)] = v
            for name, series in self._histograms.items():
                for key, h in series.items():
                    out["histograms"][name + _fmt_labels(key)] = h.snapshot()
            return out

    def export_state(self) -> dict:
        """Structured, JSON-able, label-preserving dump — the mergeable
        counterpart of :meth:`snapshot`.  Labels are kept as explicit
        ``[key, value]`` pairs (not flattened into a display string) so
        another process can reconstruct the exact series and fold shards
        together (see :mod:`sagecal_tpu.obs.aggregate`)."""
        with self._lock:
            return {
                "schema_version": 1,
                "counters": [
                    {"name": name, "labels": [list(kv) for kv in key],
                     "value": v}
                    for name, series in self._counters.items()
                    for key, v in series.items()
                ],
                "gauges": [
                    {"name": name, "labels": [list(kv) for kv in key],
                     "value": v}
                    for name, series in self._gauges.items()
                    for key, v in series.items()
                ],
                "histograms": [
                    {"name": name, "labels": [list(kv) for kv in key],
                     **h.snapshot()}
                    for name, series in self._histograms.items()
                    for key, h in series.items()
                ],
            }

    def restore_state(self, state: dict) -> None:
        """Fold an :meth:`export_state` document back into this registry
        (used on ``--resume`` so counters stay monotonic across
        preemptions).  Counters and histograms accumulate; gauges are
        only restored where no fresher value exists."""
        if not state:
            return
        with self._lock:
            for ent in state.get("counters", ()):
                key = tuple(tuple(kv) for kv in ent["labels"])
                series = self._counters.setdefault(ent["name"], {})
                series[key] = series.get(key, 0.0) + float(ent["value"])
            for ent in state.get("gauges", ()):
                key = tuple(tuple(kv) for kv in ent["labels"])
                series = self._gauges.setdefault(ent["name"], {})
                series.setdefault(key, float(ent["value"]))
            for ent in state.get("histograms", ()):
                key = tuple(tuple(kv) for kv in ent["labels"])
                series = self._histograms.setdefault(ent["name"], {})
                incoming = _Histogram.from_snapshot(ent)
                if key in series:
                    series[key].merge(incoming)
                else:
                    series[key] = incoming

    def to_prometheus(self) -> str:
        """Prometheus text exposition (scrape a long run by dumping this
        to a file the node exporter's textfile collector watches)."""
        lines = []
        with self._lock:
            for name in sorted(self._counters):
                if name in self._help:
                    lines.append(f"# HELP {name} {self._help[name]}")
                lines.append(f"# TYPE {name} counter")
                for key, v in sorted(self._counters[name].items()):
                    lines.append(f"{name}{_fmt_labels(key)} {v:g}")
            for name in sorted(self._gauges):
                if name in self._help:
                    lines.append(f"# HELP {name} {self._help[name]}")
                lines.append(f"# TYPE {name} gauge")
                for key, v in sorted(self._gauges[name].items()):
                    lines.append(f"{name}{_fmt_labels(key)} {v:g}")
            for name in sorted(self._histograms):
                if name in self._help:
                    lines.append(f"# HELP {name} {self._help[name]}")
                lines.append(f"# TYPE {name} histogram")
                for key, h in sorted(self._histograms[name].items()):
                    cum = 0
                    for b, c in zip(h.buckets, h.counts):
                        cum += c
                        le = _fmt_labels(key + (("le", f"{b:g}"),))
                        lines.append(f"{name}_bucket{le} {cum}")
                    le = _fmt_labels(key + (("le", "+Inf"),))
                    lines.append(f"{name}_bucket{le} {h.count}")
                    lines.append(f"{name}_sum{_fmt_labels(key)} {h.total:g}")
                    lines.append(f"{name}_count{_fmt_labels(key)} {h.count}")
        return "\n".join(lines) + ("\n" if lines else "")

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._help.clear()


class NullRegistry(MetricsRegistry):
    """No-op registry handed out when telemetry is disabled: mutators
    return immediately, reads report empty.  Shared singleton, so
    instrumented call sites stay branch-free."""

    def __init__(self) -> None:
        super().__init__()

    @property
    def enabled(self) -> bool:
        return False

    def counter_inc(self, name, value=1.0, help=None, **labels):
        pass

    def gauge_set(self, name, value, help=None, **labels):
        pass

    def observe(self, name, value, buckets=_DEFAULT_BUCKETS, help=None,
                **labels):
        pass


_GLOBAL = MetricsRegistry()
_NULL = NullRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry when telemetry is on, else the shared
    :class:`NullRegistry`."""
    return _GLOBAL if telemetry_enabled() else _NULL
