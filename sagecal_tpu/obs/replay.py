"""Deterministic fleet-state replay from production telemetry.

Reconstructs what a finished (or killed) fleet/load run actually did —
queue contents by state, lease epoch chains, per-worker lifecycle,
per-request dispositions and span trees, SLO attainment — purely from
the on-disk records of the run, read through the validating ledger
(obs/ledger.py).  Nothing here consults live state: the replay is a
pure function of the record files, so two readers of the same out-dir
always reconstruct the same fleet.

Clock model: every writer (coordinator/loadgen process, each worker)
stamps records with its OWN wall clock, and wall clocks step and skew.
Instead of trusting them, the replay estimates a per-clock-domain
offset from happens-before edges that are true by construction:

- enqueue -> first lease claim of the item   (coordinator -> worker)
- seed/spawn -> the worker's first record    (coordinator -> worker)
- a worker's last record -> ``fleet_done``   (worker -> coordinator)

Each edge ``a -> b`` bounds the writer offsets: with true time
``T = t + off(domain)``, ``off(A) - off(B) <= t_b - t_a``.  Folding
every edge against the reference domain (the coordinator) yields a
feasible interval ``[lo, hi]`` per domain; the estimate is the
in-interval value closest to zero.  An empty interval, or an estimate
beyond the audit's skew bound, is evidence of a stepped/forged clock —
the ``clock_skew`` violation in obs/audit.py.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Dict, List, Optional, Tuple

from sagecal_tpu.obs import ledger

#: request dispositions the conservation law sums over
SERVED, SHED, FAILED, PENDING = "served", "shed", "failed", "pending"


def domain_of(writer: Optional[str]) -> Optional[str]:
    """A writer identity's clock domain (``w0@1234`` -> ``w0``): one
    wall clock per process; respawns of a worker share its name and,
    on one host, its clock."""
    if not isinstance(writer, str) or not writer:
        return None
    return writer.split("@", 1)[0]


# ----------------------------------------------------------- raw records


@dataclasses.dataclass
class RunRecords:
    """Every validated record of one run, grouped by family."""

    out_dir: str
    scan: ledger.OutDirScan
    events: List[dict]
    spans: List[dict]
    timeline: List[dict]
    drift: List[dict]
    manifests: List[dict]
    items: Dict[str, dict]                       # rid -> item doc
    leases: Dict[str, List[Tuple[int, dict]]]    # rid -> [(epoch, doc)]
    done: Dict[str, dict]                        # rid -> done doc
    fails: Dict[str, List[dict]]                 # rid -> fail docs
    metrics: List[dict]
    load_steps: Optional[dict]
    flight_dumps: List[dict]

    def all_files(self) -> List[ledger.ValidatedFile]:
        return list(self.scan.files)


def _parse_lease_name(base: str) -> Optional[Tuple[str, int]]:
    """``lease-<rid>.e<NNNNNN>.json`` -> (rid, epoch)."""
    if not (base.startswith("lease-") and base.endswith(".json")):
        return None
    stem = base[len("lease-"):-len(".json")]
    rid, sep, ep = stem.rpartition(".e")
    if not sep or not ep.isdigit():
        return None
    return rid, int(ep)


def load_run(out_dir: str, events_path: Optional[str] = None,
             queue_dir: Optional[str] = None) -> RunRecords:
    """Read + classify every record of a run.  ``events_path`` /
    ``queue_dir`` override the defaults (``<out_dir>/sagecal_events.
    jsonl`` + per-process companions, ``<out_dir>/queue``)."""
    from sagecal_tpu.obs.events import expand_event_paths

    queue_dir = queue_dir or os.path.join(out_dir, "queue")
    extra: List[str] = []
    ev_default = events_path or os.path.join(out_dir,
                                             "sagecal_events.jsonl")
    extra.extend(expand_event_paths(ev_default))
    if os.path.isdir(queue_dir) and not os.path.abspath(
            queue_dir).startswith(os.path.abspath(out_dir) + os.sep):
        for n in sorted(os.listdir(queue_dir)):
            extra.append(os.path.join(queue_dir, n))
    scan = ledger.scan_out_dir(out_dir, extra_paths=extra)

    events = scan.ok_records("event")
    events.sort(key=lambda e: (float(e.get("ts", 0.0))))
    spans = scan.ok_records("span")
    timeline = scan.ok_records("timeline")
    timeline.sort(key=lambda r: (r.get("seq", -1), float(r.get("ts", 0.0))))
    drift = scan.ok_records("drift")
    manifests = scan.ok_records("result_manifest")
    metrics = scan.ok_records("metrics_snapshot")
    steps = scan.ok_records("load_steps")
    dumps = scan.ok_records("flight_dump")

    items: Dict[str, dict] = {}
    for doc in scan.ok_records("queue_item"):
        items[str(doc["request_id"])] = doc
    done: Dict[str, dict] = {}
    for doc in scan.ok_records("queue_done"):
        done[str(doc["request_id"])] = doc
    fails: Dict[str, List[dict]] = {}
    for doc in scan.ok_records("queue_fail"):
        fails.setdefault(str(doc["request_id"]), []).append(doc)
    leases: Dict[str, List[Tuple[int, dict]]] = {}
    for vf in scan.by_family("queue_lease"):
        parsed = _parse_lease_name(os.path.basename(vf.path))
        for doc in vf.ok:
            rid = str(doc.get("request_id", ""))
            epoch = parsed[1] if parsed else -1
            if parsed and parsed[0] != rid:
                # keep it, the auditor flags the mismatch
                pass
            leases.setdefault(rid or (parsed[0] if parsed else "?"),
                              []).append((epoch, doc))
    for chain in leases.values():
        chain.sort(key=lambda t: t[0])

    return RunRecords(
        out_dir=out_dir, scan=scan, events=events, spans=spans,
        timeline=timeline, drift=drift, manifests=manifests,
        items=items, leases=leases, done=done, fails=fails,
        metrics=metrics, load_steps=steps[0] if steps else None,
        flight_dumps=dumps)


# ------------------------------------------------------- clock estimation


@dataclasses.dataclass
class ClockEstimate:
    """One clock domain's offset bounds relative to the reference
    domain (add ``est`` to the domain's timestamps to translate them
    into reference time)."""

    domain: str
    lo: float = -math.inf
    hi: float = math.inf
    edges: int = 0
    feasible: bool = True

    @property
    def est(self) -> float:
        if not self.feasible:
            # midpoint of the (inverted) bounds: the least-bad guess
            return 0.5 * (self.lo + self.hi)
        lo = self.lo if self.lo != -math.inf else None
        hi = self.hi if self.hi != math.inf else None
        if lo is not None and lo > 0:
            return lo
        if hi is not None and hi < 0:
            return hi
        return 0.0


def _first_last_event_ts(events: List[dict]) -> Dict[str, Tuple[float, float]]:
    out: Dict[str, Tuple[float, float]] = {}
    for e in events:
        d = domain_of(e.get("writer"))
        ts = e.get("ts")
        if d is None or not isinstance(ts, (int, float)):
            continue
        lo, hi = out.get(d, (math.inf, -math.inf))
        out[d] = (min(lo, float(ts)), max(hi, float(ts)))
    return out


def estimate_clocks(rec: RunRecords) -> Tuple[str, Dict[str, ClockEstimate], List[str]]:
    """Per-domain clock offsets from happens-before edges; returns
    ``(reference_domain, {domain: estimate}, anomalies)`` where
    anomalies are same-domain records observed out of happens-before
    order (a clock stepping backwards inside one writer)."""
    # reference domain: the timeline writer (coordinator samples it),
    # else the coordinator/loadgen run_manifest, else the most common
    # event writer
    ref: Optional[str] = None
    for row in rec.timeline:
        ref = domain_of(row.get("writer")) or ref
        if ref:
            break
    if ref is None:
        for e in rec.events:
            if e.get("type") == "run_manifest":
                role = (e.get("extra") or {}).get("role", "")
                if role in ("coordinator", "loadgen"):
                    ref = domain_of(e.get("writer"))
                    break
    if ref is None:
        counts: Dict[str, int] = {}
        for e in rec.events:
            d = domain_of(e.get("writer"))
            if d:
                counts[d] = counts.get(d, 0) + 1
        ref = max(counts, key=counts.get) if counts else "coordinator"

    clocks: Dict[str, ClockEstimate] = {}
    anomalies: List[str] = []

    def clock(domain: str) -> ClockEstimate:
        if domain not in clocks:
            clocks[domain] = ClockEstimate(domain=domain)
        return clocks[domain]

    def edge(dom_a: Optional[str], t_a, dom_b: Optional[str], t_b,
             label: str) -> None:
        """Happens-before ``a -> b``: off(A) - off(B) <= t_b - t_a."""
        if (dom_a is None or dom_b is None
                or not isinstance(t_a, (int, float))
                or not isinstance(t_b, (int, float))):
            return
        t_a, t_b = float(t_a), float(t_b)
        if dom_a == dom_b:
            if t_a > t_b + 1e-3:
                anomalies.append(
                    f"{label}: same-writer order inverted in domain "
                    f"{dom_a} ({t_a:.3f} > {t_b:.3f})")
            return
        if dom_a == ref:
            c = clock(dom_b)
            c.lo = max(c.lo, t_a - t_b)
            c.edges += 1
        elif dom_b == ref:
            c = clock(dom_a)
            c.hi = min(c.hi, t_b - t_a)
            c.edges += 1

    # enqueue -> first claim / first recorded processing of the item
    for rid, item in rec.items.items():
        enq = item.get("enqueued_at")
        chain = rec.leases.get(rid, [])
        if chain:
            _, first = chain[0]
            edge(ref, enq, domain_of(first.get("worker")),
                 first.get("acquired_at"), f"enqueue->claim {rid}")
        d = rec.done.get(rid)
        if d is not None:
            edge(ref, enq, domain_of(d.get("worker")),
                 d.get("completed_at"), f"enqueue->done {rid}")
    # claim -> manifest commit (same worker: sanity; cross: bound)
    mf_by_rid = {str(m.get("request_id")): m for m in rec.manifests}
    for rid, d in rec.done.items():
        m = mf_by_rid.get(rid)
        if m is not None:
            edge(domain_of(d.get("worker")), m.get("started_at"),
                 domain_of(d.get("worker")), m.get("completed_at"),
                 f"solve->manifest {rid}")
    # seed -> each worker's first record; worker's last -> fleet_done
    seeded_ts = None
    done_ts = None
    for e in rec.events:
        if e.get("type") == "fleet_seeded" and seeded_ts is None:
            seeded_ts = e.get("ts")
        if e.get("type") == "fleet_done":
            done_ts = e.get("ts")
    spans_fl = _first_last_event_ts(rec.events)
    for dom, (first_ts, last_ts) in spans_fl.items():
        if dom == ref:
            continue
        if seeded_ts is not None:
            edge(ref, seeded_ts, dom, first_ts, f"spawn->first {dom}")
        if done_ts is not None:
            edge(dom, last_ts, ref, done_ts, f"last->fleet_done {dom}")
    clock(ref).lo, clock(ref).hi = 0.0, 0.0

    for c in clocks.values():
        if c.lo > c.hi + 1e-3:
            c.feasible = False
    return ref, clocks, anomalies


# --------------------------------------------------------------- replay


@dataclasses.dataclass
class RequestReplay:
    """One request's reconstructed lifecycle."""

    request_id: str
    tenant: str = ""
    state: str = PENDING          # served | shed | failed | pending
    sub_state: str = ""           # pending detail: waiting|leased|expired
    verdict: str = ""
    worker: str = ""
    enqueued_at: float = 0.0
    started_at: Optional[float] = None
    completed_at: Optional[float] = None
    latency_s: Optional[float] = None
    deadline: Optional[float] = None
    trace_id: str = ""
    epochs: int = 0
    manifest_count: int = 0
    has_done_marker: bool = False
    attempts_failed: int = 0


@dataclasses.dataclass
class ReplayState:
    """The reconstructed fleet, plus everything the auditor gates on."""

    out_dir: str
    reference_domain: str
    requests: Dict[str, RequestReplay]
    counts: Dict[str, int]
    queue_counts: Dict[str, int]
    workers: Dict[str, Dict[str, Any]]
    clocks: Dict[str, ClockEstimate]
    clock_anomalies: List[str]
    slo: Dict[str, Any]
    now: float
    records: RunRecords

    def to_doc(self) -> Dict[str, Any]:
        return {
            "out_dir": self.out_dir,
            "reference_domain": self.reference_domain,
            "now": self.now,
            "counts": dict(self.counts),
            "queue_counts": dict(self.queue_counts),
            "requests": {rid: dataclasses.asdict(r)
                         for rid, r in sorted(self.requests.items())},
            "workers": self.workers,
            "clocks": {d: {"lo": None if c.lo == -math.inf else c.lo,
                           "hi": None if c.hi == math.inf else c.hi,
                           "est": c.est, "edges": c.edges,
                           "feasible": c.feasible}
                       for d, c in sorted(self.clocks.items())},
            "clock_anomalies": list(self.clock_anomalies),
            "slo": self.slo,
        }


def _verdict_state(verdict: str) -> str:
    if verdict == "shed":
        return SHED
    if verdict == "error":
        return FAILED
    return SERVED


def replay(rec: RunRecords, now: Optional[float] = None) -> ReplayState:
    """Reconstruct the fleet purely from ``rec``.  ``now`` fixes the
    instant pending leases are judged against (default: the latest
    reference-translated timestamp observed anywhere in the run)."""
    ref, clocks, anomalies = estimate_clocks(rec)

    def translate(dom: Optional[str], t) -> Optional[float]:
        if not isinstance(t, (int, float)):
            return None
        off = clocks[dom].est if dom in clocks else 0.0
        return float(t) + off

    # latest observed instant (reference time) = replay "now"
    latest = 0.0
    for e in rec.events:
        t = translate(domain_of(e.get("writer")), e.get("ts"))
        latest = max(latest, t or 0.0)
    for row in rec.timeline:
        latest = max(latest, float(row.get("ts", 0.0)))
    for m in rec.manifests:
        rid = str(m.get("request_id"))
        dom = domain_of((rec.done.get(rid) or {}).get("worker"))
        t = translate(dom, m.get("completed_at"))
        latest = max(latest, t or 0.0)
    for chain in rec.leases.values():
        for _, doc in chain:
            t = translate(domain_of(doc.get("worker")),
                          doc.get("renewed_at"))
            latest = max(latest, t or 0.0)
    for item in rec.items.values():
        latest = max(latest, float(item.get("enqueued_at") or 0.0))
    if now is None:
        now = latest

    mf_by_rid: Dict[str, List[dict]] = {}
    for m in rec.manifests:
        mf_by_rid.setdefault(str(m.get("request_id")), []).append(m)

    requests: Dict[str, RequestReplay] = {}
    queue_counts = {"items": 0, "done": 0, "waiting": 0, "leased": 0,
                    "expired_leases": 0}
    for rid, item in sorted(rec.items.items()):
        r = RequestReplay(
            request_id=rid, tenant=str(item.get("tenant", "")),
            enqueued_at=float(item.get("enqueued_at") or 0.0),
            deadline=item.get("deadline"))
        chain = rec.leases.get(rid, [])
        r.epochs = len(chain)
        mfs = mf_by_rid.get(rid, [])
        r.manifest_count = len(mfs)
        r.has_done_marker = rid in rec.done
        r.attempts_failed = len(rec.fails.get(rid, []))
        queue_counts["items"] += 1
        if mfs:
            m = mfs[0]
            r.verdict = str(m.get("verdict", ""))
            r.state = _verdict_state(r.verdict)
            r.started_at = m.get("started_at")
            r.completed_at = m.get("completed_at")
            r.latency_s = m.get("latency_s")
            r.trace_id = str(m.get("trace_id", "") or "")
            r.worker = str((rec.done.get(rid) or {}).get("worker", ""))
        elif rid in rec.done:
            # done marker without a manifest: the auditor flags it;
            # replay counts it as served so the disposition total still
            # reflects the queue's view
            d = rec.done[rid]
            r.state = _verdict_state(str(d.get("verdict", "")))
            r.verdict = str(d.get("verdict", ""))
            r.completed_at = d.get("completed_at")
            r.worker = str(d.get("worker", ""))
        else:
            r.state = PENDING
            if chain:
                epoch, head = chain[-1]
                dom = domain_of(head.get("worker"))
                exp = translate(dom, head.get("expires_at"))
                if head.get("expires_at", 0.0) == 0.0:
                    # released: immediately claimable, but the queue's
                    # live stats() buckets a surviving head as expired
                    r.sub_state = "expired"
                elif exp is not None and exp > now:
                    r.sub_state = "leased"
                else:
                    r.sub_state = "expired"
                r.worker = str(head.get("worker", ""))
            else:
                r.sub_state = "waiting"
        requests[rid] = r
        if rid in rec.done:
            queue_counts["done"] += 1
        elif r.sub_state == "leased":
            queue_counts["leased"] += 1
        elif r.sub_state == "expired":
            queue_counts["expired_leases"] += 1
        else:
            queue_counts["waiting"] += 1

    counts = {"enqueued": len(requests), SERVED: 0, SHED: 0,
              FAILED: 0, PENDING: 0}
    for r in requests.values():
        counts[r.state] += 1

    # per-worker lifecycle from events + metrics snapshots
    workers: Dict[str, Dict[str, Any]] = {}

    def worker(name: str) -> Dict[str, Any]:
        return workers.setdefault(name, {
            "pids": [], "claims": 0, "first_ts": None, "last_ts": None,
            "events": 0, "done_summary": None, "respawns": 0})

    for e in rec.events:
        w = e.get("writer")
        dom = domain_of(w)
        role_worker = isinstance(dom, str) and dom != ref
        if role_worker:
            wk = worker(dom)
            wk["events"] += 1
            ts = e.get("ts")
            if isinstance(ts, (int, float)):
                if wk["first_ts"] is None:
                    wk["first_ts"] = float(ts)
                wk["last_ts"] = float(ts)
            if isinstance(w, str) and "@" in w:
                pid = w.rsplit("@", 1)[1]
                if pid not in wk["pids"]:
                    wk["pids"].append(pid)
        t = e.get("type")
        if t == "fleet_claimed":
            worker(str(e.get("worker", dom or "?")))["claims"] += (
                int(e.get("n", 1) or 1))
        elif t == "fleet_worker_done":
            worker(str(e.get("worker", dom or "?")))["done_summary"] = {
                k: e.get(k) for k in ("cycles", "solved", "wall_s")}
        elif t == "worker_respawned":
            worker(str(e.get("worker", "?")))["respawns"] += 1
    for snap in rec.metrics:
        wk = worker(str(snap.get("worker_id", "?")))
        wk["snapshot_ts"] = snap.get("ts")

    # SLO attainment, replayed from the manifests alone (sheds are
    # refusals, not latency samples — the anti-latch rule)
    lat = sorted(float(r.latency_s) for r in requests.values()
                 if r.state == SERVED and isinstance(r.latency_s,
                                                     (int, float)))
    breaches = 0
    judged = 0
    for r in requests.values():
        if r.state != SERVED or r.deadline in (None, 0):
            continue
        dom = domain_of(rec.done.get(r.request_id, {}).get("worker"))
        ct = translate(dom, r.completed_at)
        if ct is None:
            continue
        judged += 1
        if ct > float(r.deadline):
            breaches += 1
    slo = {
        "served": counts[SERVED], "shed": counts[SHED],
        "failed": counts[FAILED],
        "p50_latency_s": lat[len(lat) // 2] if lat else None,
        "p95_latency_s": lat[min(len(lat) - 1,
                                 int(0.95 * len(lat)))] if lat else None,
        "deadline_judged": judged, "deadline_breaches": breaches,
        "deadline_attainment": (1.0 - breaches / judged) if judged
        else None,
    }

    return ReplayState(
        out_dir=rec.out_dir, reference_domain=ref, requests=requests,
        counts=counts, queue_counts=queue_counts, workers=workers,
        clocks=clocks, clock_anomalies=anomalies, slo=slo,
        now=float(now), records=rec)


def format_replay(state: ReplayState, verbose: bool = False) -> str:
    """Human-readable reconstruction (the ``diag replay`` body)."""
    lines: List[str] = []
    c = state.counts
    lines.append(f"replayed fleet state: {state.out_dir}")
    lines.append(
        f"  requests: {c['enqueued']} enqueued = {c[SERVED]} served "
        f"+ {c[SHED]} shed + {c[FAILED]} failed + {c[PENDING]} pending")
    q = state.queue_counts
    lines.append(
        f"  queue:    {q['items']} items, {q['done']} done, "
        f"{q['waiting']} waiting, {q['leased']} leased, "
        f"{q['expired_leases']} expired")
    for name in sorted(state.workers):
        w = state.workers[name]
        pids = ",".join(w["pids"]) or "-"
        summary = w.get("done_summary") or {}
        lines.append(
            f"  worker {name}: pids [{pids}] claims={w['claims']} "
            f"events={w['events']} respawns={w['respawns']}"
            + (f" solved={summary.get('solved')}" if summary else ""))
    lines.append(f"  clock reference: {state.reference_domain}")
    for dom in sorted(state.clocks):
        cl = state.clocks[dom]
        if dom == state.reference_domain:
            continue
        lo = "-inf" if cl.lo == -math.inf else f"{cl.lo:+.3f}"
        hi = "+inf" if cl.hi == math.inf else f"{cl.hi:+.3f}"
        flag = "" if cl.feasible else "  INFEASIBLE"
        lines.append(f"    {dom}: offset in [{lo}, {hi}] s, "
                     f"est {cl.est:+.3f} s ({cl.edges} edges){flag}")
    for a in state.clock_anomalies:
        lines.append(f"    anomaly: {a}")
    s = state.slo
    att = s["deadline_attainment"]
    lines.append(
        "  slo:      p50="
        + (f"{s['p50_latency_s']:.3f}s" if s["p50_latency_s"] is not None
           else "-")
        + " p95="
        + (f"{s['p95_latency_s']:.3f}s" if s["p95_latency_s"] is not None
           else "-")
        + f" deadline attainment "
        + (f"{att:.1%} ({s['deadline_judged']} judged)" if att is not None
           else "- (no deadlines judged)"))
    if verbose:
        for rid in sorted(state.requests):
            r = state.requests[rid]
            lines.append(
                f"    {rid}: {r.state}"
                + (f"/{r.sub_state}" if r.sub_state else "")
                + (f" verdict={r.verdict}" if r.verdict else "")
                + (f" worker={r.worker}" if r.worker else "")
                + f" epochs={r.epochs} manifests={r.manifest_count}")
    return "\n".join(lines)
