"""Per-``device_kind`` roofline model: peaks, intensity, per-kernel MFU.

The observability stack's hardware-truth layer (PR 16).  Before this
module the repo carried exactly one peak constant
(``V5E_BF16_PEAK_FLOPS`` in bench.py) and two v5e-specific run-level
gauges — correct on the one TPU the paper was benched on, silently
wrong everywhere else, and blind below the whole-run boundary.  Here:

- :data:`PEAK_TABLE` — editable per-``device_kind`` peaks (FLOP/s per
  dtype + HBM GB/s).  v5e is the hardware-validated entry; the ``cpu``
  entry is NOMINAL (order-of-magnitude single-core figures) and exists
  so the whole roofline machinery runs in CI on the CPU fallback; GPU
  rows slot in alongside when ROADMAP item 5 lands a second backend.
- :func:`lookup_peaks` — resolve a live ``jax.devices()[0].device_kind``
  string against the table (case-insensitive, alias-aware).  Unknown
  kinds resolve to ``None`` — callers must report "unknown device kind,
  add a PEAK_TABLE entry" rather than a silently-wrong MFU.
- :func:`classify_intensity` — arithmetic intensity (FLOP/byte) vs the
  device ridge point: compute- vs memory-bound per kernel family.
- :func:`build_report` — join a devprof per-kernel-family attribution
  (measured device seconds) against the ``instrumented_jit``
  cost-analysis ledger (per-dispatch flops/bytes) into per-family
  MFU / HBM-BW-utilization rows, ranked by wasted device time, each
  naming the ROADMAP-item-1 lever it implicates.  ``diag roofline``
  renders it; :func:`set_kernel_gauges` exports the same numbers as
  per-family registry gauges (``kernel_mfu`` / ``kernel_bw_util``).

Import-light by design (stdlib only): usable before backend selection
and inside ``diag`` without touching jax.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from sagecal_tpu.obs.registry import get_registry

# ------------------------------------------------------------ peak table

#: Canonical device peaks.  ``peak_flops`` is per-chip FLOP/s by compute
#: dtype; ``hbm_gbps`` is the memory-system bandwidth the bandwidth
#: roofline divides by.  ``nominal: True`` marks entries that are
#: order-of-magnitude placeholders (the CPU CI entry), not datasheet
#: numbers — reports carry the flag so a CPU-fallback MFU is never
#: mistaken for a hardware claim.
PEAK_TABLE: Dict[str, dict] = {
    "tpu v5e": {
        "label": "TPU v5e",
        # 197 TFLOP/s bf16 per chip (the round-5 headline denominator);
        # f32 matmuls on the v5e MXU run via multi-pass bf16 at ~half
        # the bf16 rate — the f32 row keeps same-device comparisons
        # honest, not a datasheet quote.
        "peak_flops": {"bf16": 197e12, "f32": 98.5e12},
        "hbm_gbps": 819.0,
    },
    "cpu": {
        "label": "host CPU (nominal single-core)",
        # NOMINAL figures for the single-core CI host: ~10 GFLOP/s
        # sustained scalar-ish f32 and ~10 GB/s main-memory stream.
        # They exist so the roofline machinery (lookup, intensity,
        # MFU, report, gate plumbing) is exercised end-to-end in CI;
        # never quote them as hardware truth.
        "peak_flops": {"f32": 1e10, "bf16": 1e10, "f64": 5e9},
        "hbm_gbps": 10.0,
        "nominal": True,
    },
    # ROADMAP item 5 (multi-backend): add GPU rows here, e.g.
    # "nvidia h100 80gb hbm3": {"label": "H100 SXM", "peak_flops":
    #     {"bf16": 989e12, "f32": 67e12}, "hbm_gbps": 3350.0},
}

#: device_kind strings observed in the wild -> canonical table key.
KIND_ALIASES: Dict[str, str] = {
    "tpu v5 lite": "tpu v5e",
    "tpu v5litepod": "tpu v5e",
    "cpu (unknown)": "cpu",
    "unknown": "cpu",  # CPU backend device_kind on some jaxlibs
}


def normalize_kind(device_kind: Optional[str]) -> str:
    return (device_kind or "").strip().lower()


def lookup_peaks(device_kind: Optional[str]) -> Optional[dict]:
    """The PEAK_TABLE entry for a live ``device_kind`` string, or None
    when the hardware is unknown (callers must surface that, never
    substitute a wrong peak)."""
    k = normalize_kind(device_kind)
    if not k:
        return None
    k = KIND_ALIASES.get(k, k)
    if k in PEAK_TABLE:
        return PEAK_TABLE[k]
    # tolerate vendor decorations ("TPU v5e (chips=1)")
    for key in PEAK_TABLE:
        if key in k:
            return PEAK_TABLE[key]
    return None


def peak_flops(device_kind: Optional[str],
               dtype: str = "bf16") -> Optional[float]:
    peaks = lookup_peaks(device_kind)
    if peaks is None:
        return None
    fl = peaks["peak_flops"]
    return float(fl.get(dtype) or fl.get("f32") or 0.0) or None


def peak_hbm_gbps(device_kind: Optional[str]) -> Optional[float]:
    peaks = lookup_peaks(device_kind)
    return float(peaks["hbm_gbps"]) if peaks else None


# --------------------------------------------------------- roofline math


def ridge_intensity(peaks: dict, dtype: str = "bf16") -> float:
    """FLOP/byte at the roofline ridge: above it a kernel is compute-
    bound on this device, below it memory-bound."""
    fl = peaks["peak_flops"]
    f = float(fl.get(dtype) or fl.get("f32") or 0.0)
    bw = float(peaks["hbm_gbps"]) * 1e9
    return f / bw if bw else 0.0


def classify_intensity(flops: Optional[float], bytes_accessed: Optional[float],
                       peaks: Optional[dict],
                       dtype: str = "bf16") -> dict:
    """Arithmetic intensity + compute/memory-bound verdict for one
    kernel family.  Unknown inputs degrade to ``bound: "unknown"``."""
    out = {"intensity": None, "ridge": None, "bound": "unknown"}
    if not flops or not bytes_accessed:
        return out
    out["intensity"] = float(flops) / float(bytes_accessed)
    if peaks is None:
        return out
    ridge = ridge_intensity(peaks, dtype)
    out["ridge"] = ridge
    out["bound"] = "compute-bound" if out["intensity"] >= ridge \
        else "memory-bound"
    return out


def mfu(flops_per_sec: Optional[float], device_kind: Optional[str],
        dtype: str = "bf16") -> Optional[float]:
    """Measured-vs-peak model-FLOP utilization, None when either side
    is unknown."""
    pk = peak_flops(device_kind, dtype)
    if not flops_per_sec or not pk:
        return None
    return float(flops_per_sec) / pk


def bw_util(bytes_per_sec: Optional[float],
            device_kind: Optional[str]) -> Optional[float]:
    bw = peak_hbm_gbps(device_kind)
    if not bytes_per_sec or not bw:
        return None
    return float(bytes_per_sec) / (bw * 1e9)


# ---------------------------------------------------- per-family report

#: Which ROADMAP-item-1 lever each kernel family implicates when it
#: tops the wasted-device-time ranking ("the MFU war": DMA overlap of
#: the 726 MB coherency stack, the ~65 ms dispatch floor, the 16 MB
#: VMEM ceiling forcing cluster splits).
FAMILY_LEVERS: Dict[str, str] = {
    "fused_grid": "VMEM-ceiling cluster splitting (bigger fused tiles "
                  "per grid step) + bf16 coherency stream",
    "batched_grid": "lane-major batch widening: amortize grid overhead "
                    "across serve lanes before touching the kernel",
    "xla_predict": "move predict into the fused grid (XLA predict "
                   "re-streams the 726 MB coherency stack from HBM)",
    "lbfgs_vector": "whole-solve jit: vector work is dispatch-dominated, "
                    "fuse more iterations per device program",
    "dma_infeed": "DMA/compute overlap: double-buffer the coherency "
                  "stack transfer behind the previous tile's solve",
    "other": "attribute first: grow the family classifier until this "
             "bucket is <5% of device time",
    "host_gaps": "~65 ms dispatch floor: fewer, larger device programs "
                 "(whole-solve jit amortization)",
}


def build_report(attribution: dict, ledger: Optional[Dict[str, dict]],
                 device_kind: Optional[str],
                 dtype: str = "bf16") -> dict:
    """Join a devprof attribution (measured per-family device time +
    per-module execution counts) with the cost-analysis ledger
    (per-dispatch flops/bytes per instrumented fn) into roofline rows.

    Returns ``{"device_kind", "peaks", "rows", "total_device_us",
    "attributed_us", "coverage", "dispatch"}`` where each row carries
    family, device time, share, flops/bytes (when the ledger resolves
    them), intensity/bound, MFU, BW-util and the implicated lever,
    ranked by device time (the wasted-time ordering: at 0.14% MFU
    every second of device time is ~99.9% waste, so time IS waste)."""
    from sagecal_tpu.obs.devprof import classify_kernel

    peaks = lookup_peaks(device_kind)
    fams = attribution.get("families", {})
    modules = attribution.get("modules", {})
    total_us = float(attribution.get("total_device_us", 0.0))

    # fold ledger per-dispatch flops/bytes into per-family totals using
    # the SAME classifier the trace events went through, scaled by the
    # module execution counts observed in this trace window
    fam_flops: Dict[str, float] = {}
    fam_bytes: Dict[str, float] = {}
    if ledger:
        for mod, info in modules.items():
            st = ledger.get(mod)
            if st is None:
                continue
            fam = info.get("family") or classify_kernel(mod, "")
            n = max(int(info.get("n_exec", 1)), 1)
            fl = float(st.get("flops") or 0.0)
            by = float(st.get("bytes_accessed") or 0.0)
            if fl:
                fam_flops[fam] = fam_flops.get(fam, 0.0) + fl * n
            if by:
                fam_bytes[fam] = fam_bytes.get(fam, 0.0) + by * n

    rows: List[dict] = []
    attributed_us = 0.0
    for fam, f in fams.items():
        t_us = float(f.get("time_us", 0.0))
        attributed_us += t_us
        t_s = t_us / 1e6
        fl, by = fam_flops.get(fam), fam_bytes.get(fam)
        fps = (fl / t_s) if (fl and t_s > 0) else None
        bps = (by / t_s) if (by and t_s > 0) else None
        cls = classify_intensity(fl, by, peaks, dtype)
        rows.append({
            "family": fam,
            "device_us": round(t_us, 1),
            "share": round(t_us / total_us, 4) if total_us else None,
            "events": int(f.get("events", 0)),
            "flops": fl,
            "bytes": by,
            "intensity": cls["intensity"],
            "bound": cls["bound"],
            "mfu": (fps / peaks["peak_flops"].get(dtype,
                    peaks["peak_flops"].get("f32", 0.0))
                    if (fps and peaks and peaks["peak_flops"].get(
                        dtype, peaks["peak_flops"].get("f32"))) else None),
            "bw_util": (bps / (peaks["hbm_gbps"] * 1e9)
                        if (bps and peaks) else None),
            "lever": FAMILY_LEVERS.get(fam, FAMILY_LEVERS["other"]),
            "top_ops": f.get("top_ops", [])[:3],
        })
    rows.sort(key=lambda r: -r["device_us"])

    dispatch = attribution.get("dispatch") or {}
    if dispatch.get("gap_total_us"):
        rows.append({
            "family": "host_gaps",
            "device_us": round(float(dispatch["gap_total_us"]), 1),
            "share": None,  # gaps are BETWEEN device windows, not in them
            "events": int(dispatch.get("n_gaps", 0)),
            "flops": None, "bytes": None, "intensity": None,
            "bound": "idle", "mfu": None, "bw_util": None,
            "lever": FAMILY_LEVERS["host_gaps"],
            "top_ops": [],
        })

    return {
        "device_kind": device_kind,
        "peaks": peaks,
        "dtype": dtype,
        "rows": rows,
        "total_device_us": total_us,
        "attributed_us": attributed_us,
        "coverage": (attributed_us / total_us) if total_us else 0.0,
        "dispatch": dispatch,
    }


def set_kernel_gauges(report: dict) -> None:
    """Export per-kernel-family MFU / BW-util / device-seconds gauges —
    the per-kernel replacement for the retired run-level v5e gauges."""
    reg = get_registry()
    for r in report.get("rows", []):
        fam = r["family"]
        reg.gauge_set("kernel_device_seconds", r["device_us"] / 1e6,
                      help="measured device seconds per kernel family "
                           "(device-profile attribution)", family=fam)
        if r.get("mfu") is not None:
            reg.gauge_set("kernel_mfu", float(r["mfu"]),
                          help="measured-vs-peak model-FLOP utilization "
                               "per kernel family (PEAK_TABLE peaks)",
                          family=fam)
        if r.get("bw_util") is not None:
            reg.gauge_set("kernel_bw_util", float(r["bw_util"]),
                          help="measured-vs-peak HBM bandwidth "
                               "utilization per kernel family",
                          family=fam)


def _fmt(v, pct=False, si=False) -> str:
    if v is None:
        return "-"
    if pct:
        return f"{v * 100:.2f}%"
    if si:
        for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("k", 1e3)):
            if abs(v) >= div:
                return f"{v / div:.2f}{unit}"
        return f"{v:.0f}"
    return f"{v:.3g}"


def format_report(report: dict) -> str:
    """Human rendering for ``diag roofline``."""
    kind = report.get("device_kind") or "?"
    peaks = report.get("peaks")
    lines: List[str] = []
    if peaks is None:
        lines.append(
            f"roofline: UNKNOWN device kind {kind!r} — no PEAK_TABLE "
            f"entry, MFU/BW-util omitted (add one in obs/roofline.py "
            f"rather than trusting a wrong peak)")
    else:
        fl = peaks["peak_flops"]
        dtype = report.get("dtype", "bf16")
        pk = fl.get(dtype, fl.get("f32"))
        tag = " [NOMINAL CI entry, not hardware truth]" \
            if peaks.get("nominal") else ""
        lines.append(
            f"roofline: {peaks['label']} ({kind}) — peak "
            f"{_fmt(pk, si=True)}FLOP/s {dtype}, "
            f"{peaks['hbm_gbps']:.0f} GB/s HBM, ridge "
            f"{ridge_intensity(peaks, dtype):.1f} FLOP/byte{tag}")
    tot = report.get("total_device_us", 0.0)
    cov = report.get("coverage", 0.0)
    lines.append(f"device time: {tot / 1e3:.3f} ms across "
                 f"{len(report.get('rows', []))} families, "
                 f"{cov * 100:.1f}% attributed")
    hdr = (f"{'family':<14}{'device ms':>11}{'share':>8}{'flops':>9}"
           f"{'bytes':>9}{'int.':>7}{'bound':>15}{'MFU':>8}"
           f"{'BW-util':>9}  lever")
    lines.append(hdr)
    for r in report.get("rows", []):
        lines.append(
            f"{r['family']:<14}{r['device_us'] / 1e3:>11.3f}"
            f"{_fmt(r['share'], pct=True):>8}"
            f"{_fmt(r['flops'], si=True):>9}"
            f"{_fmt(r['bytes'], si=True):>9}"
            f"{_fmt(r['intensity']):>7}"
            f"{r['bound']:>15}"
            f"{_fmt(r['mfu'], pct=True):>8}"
            f"{_fmt(r['bw_util'], pct=True):>9}  {r['lever']}")
    d = report.get("dispatch") or {}
    if d:
        lines.append(
            f"dispatch gaps: {d.get('n_gaps', 0)} gaps "
            f"{d.get('gap_total_us', 0.0) / 1e3:.1f} ms total "
            f"(mean {d.get('gap_mean_us', 0.0) / 1e3:.1f} ms, "
            f"max {d.get('gap_max_us', 0.0) / 1e3:.1f} ms) across "
            f"{d.get('n_windows', 0)} device windows; "
            f"busy fraction {d.get('amortization', 0.0) * 100:.1f}% — "
            f"the whole-solve-jit amortization of the dispatch floor")
    return "\n".join(lines)
