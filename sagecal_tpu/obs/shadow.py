"""Shadow-solve differential auditing: numerical truth on live traffic.

The repo carries four production paths that all claim to compute the
same calibration (XLA predict+cost, fused single-lane Pallas, the
batched MXU grid, hierarchical sky prediction) and two coherency
precisions (f32, bf16) — but every parity claim lives in one-shot
tests at fixed shapes.  This module measures the disagreement on REAL
traffic instead: a deterministic seeded sampler picks a configurable
fraction of serve/fleet requests, and AFTER the production result
manifest is on disk (never on the latency path, wall-clock
budget-bounded per worker) the same packed inputs are re-solved on the
reference path — XLA predict, f32 coherencies, single lane — and the
disagreement is appended to a schema-versioned O_APPEND JSONL drift
ledger next to the result manifests.

Each record carries: the final-cost relative delta, the gain relative
error (max and per-station), the chi^2 relative delta, the production
``kernel_path`` + ``choose_batched_path`` reason, bucket, dtypes, the
shadow re-solve's own wall time, and a verdict from
:data:`DRIFT_TOLERANCES` — the ONLY place drift tolerances live
(mirroring ``roofline.PEAK_TABLE``: policy is a table, not scattered
constants).  Aggregation, gauges, watchdog wiring and the ``diag
drift`` report live in :mod:`sagecal_tpu.obs.drift`.

Off-path guarantee: with ``shadow_rate == 0`` no auditor is ever
constructed and the serve/fleet dispatch byte-for-byte matches a build
without the feature (pinned in tests/test_drift.py) — the auditor only
ever READS production outputs that already shipped.

Module-level imports are stdlib + numpy only (the ``obs`` package
contract); jax is imported lazily inside the re-solve so the ledger
readers (``diag drift``) work on hosts without a backend.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

DRIFT_SCHEMA_VERSION = 1
DRIFT_KIND = "shadow_drift"

#: default drift-ledger filename inside a serve/fleet out-dir
DRIFT_FILE = "drift.jsonl"

#: the reference side of every path pair: XLA predict, f32 coherency
#: stack, single lane.  One fixed reference keeps the ledger a star —
#: every production path compares against the same truth anchor — so
#: distributions with different ``path_pair`` labels stay comparable.
REFERENCE_PATH = "xla/f32"

#: record keys every valid drift row must carry
_REQUIRED_DRIFT_KEYS = (
    "schema_version", "kind", "ts", "request_id", "path_pair",
    "kernel_path", "kernel_path_reason", "bucket", "coh_dtype",
    "solver_dtype", "cost_rel_delta", "gain_rel_err_max",
    "chi2_rel_delta", "verdict", "reasons", "shadow_s",
)

# ------------------------------------------------------ tolerance policy

#: Central per-path-pair drift tolerance policy — the ONLY place drift
#: tolerances live (the ``roofline.PEAK_TABLE`` discipline: numeric
#: policy is one audited table, never constants scattered through call
#: sites).  Keys are ``"<kernel_path>/<coh_dtype>|xla/f32"``; the value
#: bounds each ledger metric (relative quantities, dimensionless).
#:
#: Rationale per pair:
#: - ``xla/f32`` production differs from the reference only by lane
#:   batching (vmap may re-associate reductions); solvers/batched.py
#:   documents the batched solve as bit-close (<= 1e-5) to sequential
#:   solves, so the bound sits one decade above that.
#: - ``fused*/f32`` additionally swaps the predict+cost math onto the
#:   Pallas kernels (different accumulation order, f32 accumulators);
#:   kernel parity tests hold ~1e-5..1e-4, bounded at 1e-3 on gains.
#: - ``fused*/bf16`` stores the coherency stack in bfloat16 (~3
#:   significant decimal digits); the EM structure recovers most of it
#:   but per-station gain errors in the few-1e-2 range are expected and
#:   acceptable — that is precisely the trade the precision schedule
#:   (ROADMAP item 1) wants continuously measured before flipping.
#: - ``default`` covers pairs not yet characterized (e.g. a future GPU
#:   path): deliberately loose so an uncharacterized path reports
#:   rather than false-alarms, while still catching gross breakage.
DRIFT_TOLERANCES: Dict[str, dict] = {
    "xla/f32|xla/f32": {
        "cost_rel_delta": 1e-4,
        "gain_rel_err_max": 5e-4,
        "chi2_rel_delta": 1e-4,
    },
    "fused/f32|xla/f32": {
        "cost_rel_delta": 5e-4,
        "gain_rel_err_max": 1e-3,
        "chi2_rel_delta": 5e-4,
    },
    "fused_batch/f32|xla/f32": {
        "cost_rel_delta": 5e-4,
        "gain_rel_err_max": 1e-3,
        "chi2_rel_delta": 5e-4,
    },
    "fused/bf16|xla/f32": {
        "cost_rel_delta": 2e-2,
        "gain_rel_err_max": 8e-2,
        "chi2_rel_delta": 5e-2,
    },
    "fused_batch/bf16|xla/f32": {
        "cost_rel_delta": 2e-2,
        "gain_rel_err_max": 8e-2,
        "chi2_rel_delta": 5e-2,
    },
    "default": {
        "cost_rel_delta": 1e-1,
        "gain_rel_err_max": 2e-1,
        "chi2_rel_delta": 1e-1,
    },
}

#: relative-error floor: deltas against a reference value smaller than
#: this are measured against the floor instead (a 1e-30 residual must
#: not turn numeric dust into an infinite relative delta)
_REL_EPS = 1e-12

#: test-only hook: a float in this env var perturbs the REFERENCE gain
#: solution by that relative amount (deterministically seeded per
#: request), so the injected-drift fixture can prove end to end that a
#: real disagreement reaches ``diag drift`` exit 1.  Never set in
#: production; documented in USER_MANUAL.
INJECT_DRIFT_ENV = "SAGECAL_SHADOW_INJECT_DRIFT"


def path_pair(kernel_path: str, coh_dtype: str) -> str:
    """The ledger's path-pair label for one production dispatch."""
    return f"{kernel_path}/{coh_dtype}|{REFERENCE_PATH}"


def lookup_tolerances(pair: str) -> dict:
    """The :data:`DRIFT_TOLERANCES` row for a path pair (the
    ``default`` row for pairs not yet characterized)."""
    return DRIFT_TOLERANCES.get(pair, DRIFT_TOLERANCES["default"])


def drift_path(out_dir: str) -> str:
    return os.path.join(out_dir, DRIFT_FILE)


# ------------------------------------------------------------- sampling


def shadow_sampled(request_id: str, rate: float, seed: int = 0) -> bool:
    """Deterministic membership test: does this request fall in the
    shadow sample at ``rate``?

    Pure function of ``(seed, request_id)`` — crc32 of the seeded id
    mapped to [0, 1) — so the same seed always samples the same request
    ids regardless of scheduler, worker or arrival order (pinned in
    tests/test_drift.py), re-runs audit the same traffic slice, and
    the fleet needs no coordination to agree on the sample."""
    rate = float(rate)
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    h = zlib.crc32(f"{int(seed)}:{request_id}".encode("utf-8"))
    return (h / 2.0 ** 32) < rate


# ------------------------------------------------------- drift metrics


def _rel_delta(prod: float, ref: float) -> float:
    return abs(float(prod) - float(ref)) / max(abs(float(ref)), _REL_EPS)


def compute_drift_metrics(p_prod, p_ref, res1_prod: float,
                          res1_ref: float,
                          chi2_prod: Optional[float],
                          chi2_ref: Optional[float]) -> dict:
    """Differential metrics between a production solve and its shadow
    reference solve (both host numpy; ``p_*`` is the packed real gain
    vector ``(M, nchunk, 8N)``, station-major 8-per-station as in
    ``core.types.params_to_jones``).

    ``gain_rel_err_station[s]`` is the max absolute parameter error of
    station ``s`` over all clusters/chunks, relative to the reference's
    own max magnitude for that station — per-station attribution is
    what turns "bf16 drifted" into "station 43 drifted", the same
    station-resolution discipline as the chi^2 watchdog."""
    p_prod = np.asarray(p_prod, np.float64)
    p_ref = np.asarray(p_ref, np.float64)
    # (..., 8N) -> (..., N, 8): per-station parameter blocks
    sp = p_prod.reshape(p_prod.shape[:-1] + (-1, 8))
    sr = p_ref.reshape(p_ref.shape[:-1] + (-1, 8))
    nsta = sp.shape[-2]
    axes = tuple(i for i in range(sp.ndim) if i != sp.ndim - 2)
    abs_err = np.abs(sp - sr).max(axis=axes) if sp.size else \
        np.zeros(nsta)
    ref_mag = np.abs(sr).max(axis=axes) if sr.size else np.ones(nsta)
    station = abs_err / np.maximum(ref_mag, _REL_EPS)
    if not np.all(np.isfinite(station)):
        station = np.where(np.isfinite(station), station, np.inf)
    metrics = {
        "cost_rel_delta": _rel_delta(res1_prod, res1_ref),
        "gain_rel_err_max": float(station.max()) if station.size else 0.0,
        "gain_rel_err_station": [round(float(s), 12) for s in station],
    }
    if chi2_prod is not None and chi2_ref is not None:
        metrics["chi2_rel_delta"] = _rel_delta(chi2_prod, chi2_ref)
    return metrics


def drift_verdict(metrics: dict, pair: str):
    """Apply the tolerance policy row for ``pair`` to one record's
    metrics.  Returns ``(verdict, reasons)`` — ``"ok"`` or
    ``"drift_exceeded"`` (drift is degraded-not-diverged: the
    production result already shipped and may well be fine; the ledger
    exists so a human — or ``--abort-on-drift`` — decides)."""
    tol = lookup_tolerances(pair)
    reasons: List[str] = []
    for name, bound in tol.items():
        v = metrics.get(name)
        if v is None:
            continue
        v = float(v)
        if not np.isfinite(v):
            reasons.append(f"{name} is non-finite")
        elif v > float(bound):
            reasons.append(f"{name} {v:.3e} exceeds {pair} "
                           f"tolerance {bound:.1e}")
    return ("drift_exceeded", reasons) if reasons else ("ok", reasons)


# ------------------------------------------------------------ the ledger


def _chi2_total(quality) -> Optional[float]:
    from sagecal_tpu.obs.quality import quality_summary, quality_to_host

    s = quality_summary(quality_to_host(quality))
    tot = s.get("chi2_total")
    return None if tot is None else float(tot)


class ShadowAuditor:
    """Sampled shadow re-solves + the O_APPEND drift ledger.

    One auditor per serve/fleet process.  The service calls
    :meth:`audit` once per completed (manifest-written) request; the
    auditor decides membership via :func:`shadow_sampled`, enforces the
    per-process wall-clock budget, re-solves the SAME packed inputs on
    the reference path and appends one drift record.  Rows share the
    EventLog durability contract — one ``os.write`` on an ``O_APPEND``
    fd per record, so fleet workers appending to a shared out-dir never
    interleave and a killed run keeps every record up to the kill."""

    def __init__(self, out_dir: str, rate: float, budget_s: float = 60.0,
                 seed: int = 0, device=None, log=print):
        self.rate = float(rate)
        self.budget_s = float(budget_s)
        self.seed = int(seed)
        self.device = device
        self.log = log
        os.makedirs(out_dir, exist_ok=True)
        self.path = drift_path(out_dir)
        self._fd: Optional[int] = os.open(
            self.path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
        self.spent_s = 0.0
        self.sampled = 0
        self.audited = 0
        self.budget_skipped = 0
        self.exceeded: List[str] = []  # request ids over tolerance
        from sagecal_tpu.obs.events import writer_identity

        self._writer = writer_identity()
        self._seq = 0

    # -- membership / budget -------------------------------------------

    def wants(self, request_id: str) -> bool:
        if not shadow_sampled(request_id, self.rate, self.seed):
            return False
        self.sampled += 1
        if self.spent_s >= self.budget_s:
            # budget exhaustion is counted, never queued: the ledger's
            # sampling story stays honest (diag drift reports the skip
            # count so a starved budget can't masquerade as clean)
            self.budget_skipped += 1
            return False
        return True

    # -- the shadow re-solve -------------------------------------------

    def _reference_solve(self, entry):
        """Re-solve ``entry``'s packed inputs on the reference path:
        XLA predict, f32 coherency stack, single lane.  Uses the
        entry's own RNG key — ``derive_lane_keys`` makes the key a pure
        function of request identity, so the randomized solver stream
        (OS subset draws, robust nu ordering) replays exactly and the
        differential isolates the KERNEL PATH, not the RNG."""
        from sagecal_tpu.solvers.sage import solve_tile

        ref_cfg = entry.scfg.replace(use_fused_predict=False,
                                     coh_dtype="f32")
        # fresh p0 copy: the jitted packed solve DONATES its gains
        # carry, and entry.p0 must stay intact for diagnostics
        return solve_tile(entry.data, entry.cdata,
                          np.array(entry.p0, copy=True), ref_cfg,
                          key=entry.key, device=self.device)

    def audit(self, entry, bucket: str, kernel_path: str,
              path_reason: str, p_prod, res1_prod: float,
              quality_prod, elog=None) -> Optional[dict]:
        """Shadow-audit one completed request (AFTER its result
        manifest is written).  Returns the appended drift record, or
        None when the request is unsampled / over budget."""
        if not self.wants(entry.req.request_id):
            return None
        t0 = time.time()
        ref = self._reference_solve(entry)
        p_ref = np.asarray(ref.p, np.float64)
        res1_ref = float(np.asarray(ref.res_1))
        chi2_ref = None if ref.quality is None else _chi2_total(ref.quality)

        inject = float(os.environ.get(INJECT_DRIFT_ENV, "0") or "0")
        if inject != 0.0:
            # deterministic per-request perturbation of the REFERENCE:
            # the production result is untouched, so the fixture proves
            # the full detect path without shipping a wrong solution
            rng = np.random.default_rng(
                zlib.crc32(entry.req.request_id.encode("utf-8")))
            p_ref = p_ref * (1.0 + inject) \
                + inject * rng.standard_normal(p_ref.shape)

        pair = path_pair(kernel_path, entry.scfg.coh_dtype)
        metrics = compute_drift_metrics(
            np.asarray(p_prod, np.float64), p_ref,
            float(res1_prod), res1_ref,
            _chi2_total(quality_prod), chi2_ref)
        verdict, reasons = drift_verdict(metrics, pair)
        shadow_s = time.time() - t0
        self.spent_s += shadow_s
        self.audited += 1
        if verdict != "ok":
            self.exceeded.append(entry.req.request_id)

        record = {
            "schema_version": DRIFT_SCHEMA_VERSION,
            "kind": DRIFT_KIND, "ts": t0,
            "request_id": entry.req.request_id,
            "tenant": entry.req.tenant,
            "path_pair": pair,
            "kernel_path": kernel_path,
            "kernel_path_reason": path_reason,
            "bucket": bucket,
            "coh_dtype": entry.scfg.coh_dtype,
            "solver_dtype": str(np.asarray(entry.p0).dtype),
            "verdict": verdict, "reasons": reasons,
            "shadow_s": shadow_s,
            "res_1_ref": res1_ref,
        }
        record.update(metrics)
        # audit stamps, appended after the v1 layout (obs/ledger.py)
        record["writer"] = self._writer
        record["mono"] = time.monotonic()
        record["seq"] = self._seq
        self._seq += 1
        fd = self._fd
        if fd is not None:
            os.write(fd, (json.dumps(record) + "\n").encode("utf-8"))

        from sagecal_tpu.obs.drift import check_drift

        check_drift(elog, record, log=self.log)
        return record

    # -- lifecycle -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        return {
            "rate": self.rate, "sampled": self.sampled,
            "audited": self.audited,
            "budget_skipped": self.budget_skipped,
            "budget_s": self.budget_s,
            "spent_s": self.spent_s,
            "exceeded": list(self.exceeded),
        }

    def close(self) -> None:
        fd, self._fd = self._fd, None
        if fd is not None:
            os.close(fd)

    def __enter__(self) -> "ShadowAuditor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ------------------------------------------------------------- readers


def read_drift(path: str) -> List[dict]:
    """Load a drift ledger's records (skips blank/corrupt/foreign lines
    — a killed worker may leave a truncated tail)."""
    out: List[dict] = []
    if not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(row, dict) and row.get("kind") == DRIFT_KIND:
                out.append(row)
    out.sort(key=lambda r: float(r.get("ts", 0.0)))
    return out


def validate_drift(rows) -> List[str]:
    """Structural problems of a drift ledger (empty list = valid):
    required keys present, schema version known, metrics finite and
    non-negative, verdict consistent with the tolerance table."""
    problems: List[str] = []
    if not rows:
        return ["no drift records"]
    for i, row in enumerate(rows):
        for k in _REQUIRED_DRIFT_KEYS:
            if k not in row:
                problems.append(f"record {i}: missing key {k}")
        sv = row.get("schema_version")
        if sv is not None and sv != DRIFT_SCHEMA_VERSION:
            problems.append(f"record {i}: schema_version {sv} != "
                            f"{DRIFT_SCHEMA_VERSION}")
        for k in ("cost_rel_delta", "gain_rel_err_max", "chi2_rel_delta",
                  "shadow_s"):
            v = row.get(k)
            if v is None:
                continue
            if not isinstance(v, (int, float)) or v < 0:
                problems.append(f"record {i}: {k}={v!r} not a "
                                f"non-negative number")
        verdict = row.get("verdict")
        if verdict not in (None, "ok", "drift_exceeded"):
            problems.append(f"record {i}: unknown verdict {verdict!r}")
        pair = row.get("path_pair")
        if verdict in ("ok", "drift_exceeded") and isinstance(pair, str):
            want, _ = drift_verdict(row, pair)
            if want != verdict:
                problems.append(
                    f"record {i}: verdict {verdict} disagrees with the "
                    f"tolerance policy for {pair} (expected {want})")
    return problems
