"""Per-tenant SLOs: error budgets, multi-window burn rates, overload signal.

A serve deployment promises each tenant an SLO: a latency deadline and
an availability objective ("99% of requests succeed within 2 s").  This
module turns the aggregated latency/verdict stream into the standard
SRE control signals, **report-only** — nothing here sheds or reorders
work; it emits the numbers a scheduler can act on later:

- **error budget** — ``1 - availability``: the fraction of requests
  allowed to miss (diverge, or blow the deadline) per window.
- **burn rate** — ``error_rate / error_budget`` over a trailing window:
  1.0 spends the budget exactly at the sustainable pace, >1 exhausts it
  early.  Evaluated over SHORT and LONG windows simultaneously
  (multi-window alerting): an alert fires only when *every* window
  burns above ``alert_burn``, so a brief blip (short window spikes,
  long window calm) and an old incident (long window elevated, short
  window recovered) both stay quiet.
- **``slo_burn_alert`` events** with firing/cleared edge semantics and
  ``serve_slo_*`` gauges for the scrape side.
- **``shed_recommended``** — true while the short-window burn exceeds
  ``shed_burn`` (default 10x: the "page now" fast-burn threshold);
  PR 12's admission control consumes this bit.

Specs come from a ``slo.json`` (``--slo``) or ride inside the request
manifest under a top-level ``"slos"`` key.  Import-light: stdlib only,
usable by ``diag serve`` post-hoc on machines without jax.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import time
from typing import Any, Deque, Dict, Iterable, List, Optional, Sequence, Tuple

SLO_SCHEMA_VERSION = 1

#: trailing evaluation windows, seconds (short, long)
DEFAULT_WINDOWS_S = (300.0, 3600.0)
DEFAULT_ALERT_BURN = 2.0
DEFAULT_SHED_BURN = 10.0


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One tenant's objective.  ``availability`` is the success target
    (0.99 = 1% error budget); a request errs when it diverges OR its
    latency exceeds ``deadline_s``."""

    tenant: str
    deadline_s: float
    availability: float = 0.99
    windows_s: Tuple[float, float] = DEFAULT_WINDOWS_S
    alert_burn: float = DEFAULT_ALERT_BURN
    shed_burn: float = DEFAULT_SHED_BURN

    def __post_init__(self):
        if not (0.0 < self.availability < 1.0):
            raise ValueError(
                f"slo[{self.tenant}]: availability must be in (0, 1), "
                f"got {self.availability}")
        if self.deadline_s <= 0.0:
            raise ValueError(
                f"slo[{self.tenant}]: deadline_s must be > 0, "
                f"got {self.deadline_s}")
        object.__setattr__(
            self, "windows_s",
            tuple(sorted(float(w) for w in self.windows_s)))

    @property
    def error_budget(self) -> float:
        return 1.0 - self.availability


def load_slo_specs(path: str) -> Dict[str, SLOSpec]:
    """Parse SLO specs from a JSON file: either a dedicated ``slo.json``
    (``{"slos": [...]}`` or a bare list) or a request manifest carrying
    a top-level ``"slos"`` key.  A request manifest without one returns
    ``{}`` (SLOs are opt-in).  Raises ``ValueError`` on a malformed
    spec or a duplicate tenant."""
    if not path:
        return {}
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        doc = doc.get("slos", [])
    if not isinstance(doc, list):
        raise ValueError(f"{path}: 'slos' must be a list")
    known = {f.name for f in dataclasses.fields(SLOSpec)}
    out: Dict[str, SLOSpec] = {}
    for i, item in enumerate(doc):
        if not isinstance(item, dict):
            raise ValueError(f"{path}: slo #{i} is not an object")
        unknown = set(item) - known
        if unknown:
            raise ValueError(
                f"{path}: slo #{i} has unknown fields {sorted(unknown)}")
        missing = {"tenant", "deadline_s"} - set(item)
        if missing:
            raise ValueError(
                f"{path}: slo #{i} missing fields {sorted(missing)}")
        kwargs = dict(item)
        if "windows_s" in kwargs:
            kwargs["windows_s"] = tuple(kwargs["windows_s"])
        spec = SLOSpec(**kwargs)
        if spec.tenant in out:
            raise ValueError(f"{path}: duplicate slo for tenant "
                             f"{spec.tenant!r}")
        out[spec.tenant] = spec
    return out


def sample_is_error(spec: SLOSpec, latency_s: float, verdict: str) -> bool:
    return verdict != "ok" or float(latency_s) > spec.deadline_s


def burn_rate(errors: int, total: int, error_budget: float) -> float:
    """``error_rate / budget``; 0 with no traffic (an idle tenant burns
    nothing)."""
    if total <= 0:
        return 0.0
    return (errors / float(total)) / max(error_budget, 1e-12)


class SLOMonitor:
    """Stateful burn-rate evaluator with alert edge semantics.

    ``observe()`` one (ts, latency, verdict) sample per completed
    request; ``evaluate()`` computes per-window burn rates and fires /
    clears ``slo_burn_alert`` events (and ``serve_slo_*`` gauges) on
    state *transitions* only, so the event stream carries edges rather
    than a line per request."""

    def __init__(self, specs: Dict[str, SLOSpec]):
        self.specs = dict(specs)
        self._samples: Dict[str, Deque[Tuple[float, bool]]] = {
            t: collections.deque() for t in self.specs}
        self._firing: Dict[str, bool] = {t: False for t in self.specs}

    @property
    def enabled(self) -> bool:
        return bool(self.specs)

    def observe(self, tenant: str, ts: float, latency_s: float,
                verdict: str) -> None:
        spec = self.specs.get(tenant)
        if spec is None:
            return
        self._samples[tenant].append(
            (float(ts), sample_is_error(spec, latency_s, verdict)))

    def _trim(self, tenant: str, now: float) -> None:
        horizon = now - self.specs[tenant].windows_s[-1]
        dq = self._samples[tenant]
        while dq and dq[0][0] < horizon:
            dq.popleft()

    def evaluate(self, now: Optional[float] = None,
                 elog=None, registry=None) -> List[Dict[str, Any]]:
        """Burn status for every tenant with a spec; emits alert edges
        and gauges when ``elog``/``registry`` are given."""
        now = time.time() if now is None else float(now)
        out: List[Dict[str, Any]] = []
        for tenant, spec in self.specs.items():
            self._trim(tenant, now)
            status = evaluate_window_burns(
                spec, self._samples[tenant], now)
            was = self._firing[tenant]
            self._firing[tenant] = status["burning"]
            status["transition"] = (
                "firing" if status["burning"] and not was
                else "cleared" if was and not status["burning"]
                else None)
            if registry is not None:
                for w, b in zip(spec.windows_s, status["burn_rates"]):
                    registry.gauge_set(
                        "serve_slo_burn_rate", b, tenant=tenant,
                        window=f"{int(w)}s",
                        help="error-budget burn rate per trailing window")
                registry.gauge_set(
                    "serve_slo_error_budget_remaining",
                    status["budget_remaining"], tenant=tenant,
                    help="fraction of the long-window error budget left")
                registry.gauge_set(
                    "serve_slo_shed_recommended",
                    1.0 if status["shed_recommended"] else 0.0,
                    tenant=tenant,
                    help="1 while short-window burn exceeds shed_burn")
            if elog is not None and status["transition"] is not None:
                elog.emit("slo_burn_alert", tenant=tenant,
                          state=status["transition"],
                          burn_rates=status["burn_rates"],
                          windows_s=list(spec.windows_s),
                          alert_burn=spec.alert_burn,
                          deadline_s=spec.deadline_s,
                          availability=spec.availability,
                          shed_recommended=status["shed_recommended"])
            out.append(status)
        return out

    def shed_recommended(self, tenant: str,
                         now: Optional[float] = None) -> bool:
        """True while the tenant's short-window burn exceeds its
        ``shed_burn`` threshold.  ``now`` pins the evaluation instant
        (admission-control tests replay recorded sample streams)."""
        spec = self.specs.get(tenant)
        if spec is None:
            return False
        status = evaluate_window_burns(
            spec, self._samples[tenant],
            time.time() if now is None else float(now))
        return status["shed_recommended"]


def evaluate_window_burns(spec: SLOSpec,
                          samples: Iterable[Tuple[float, bool]],
                          now: float) -> Dict[str, Any]:
    """Pure multi-window burn evaluation over ``(ts, is_error)``
    samples (the post-hoc path ``diag serve`` uses on manifests)."""
    samples = list(samples)
    burns: List[float] = []
    counts: List[Tuple[int, int]] = []
    for w in spec.windows_s:
        sel = [e for ts, e in samples if ts >= now - w]
        errors = sum(1 for e in sel if e)
        counts.append((errors, len(sel)))
        burns.append(burn_rate(errors, len(sel), spec.error_budget))
    burning = bool(burns) and all(b >= spec.alert_burn for b in burns)
    long_errors, long_total = counts[-1] if counts else (0, 0)
    if long_total:
        budget_remaining = 1.0 - burn_rate(
            long_errors, long_total, spec.error_budget)
    else:
        budget_remaining = 1.0
    return {
        "tenant": spec.tenant,
        "windows_s": list(spec.windows_s),
        "burn_rates": burns,
        "window_counts": counts,
        "burning": burning,
        "budget_remaining": budget_remaining,
        "shed_recommended": bool(burns) and burns[0] >= spec.shed_burn,
        "deadline_s": spec.deadline_s,
        "availability": spec.availability,
    }


def evaluate_results(specs: Dict[str, SLOSpec],
                     results: Sequence[dict],
                     now: Optional[float] = None) -> List[Dict[str, Any]]:
    """Post-hoc SLO evaluation straight from result manifests (the
    ``diag serve`` path): samples are ``(completed_at, is_error)``
    per manifest; ``now`` defaults to the latest completion so archived
    runs evaluate the same way forever."""
    by_tenant: Dict[str, List[Tuple[float, bool]]] = {}
    tmax = 0.0
    for r in results:
        spec = specs.get(str(r.get("tenant")))
        if spec is None:
            continue
        ts = float(r.get("completed_at") or r.get("enqueued_at") or 0.0)
        tmax = max(tmax, ts)
        by_tenant.setdefault(spec.tenant, []).append(
            (ts, sample_is_error(spec, float(r.get("latency_s", 0.0)),
                                 str(r.get("verdict", "")))))
    now = tmax if now is None else float(now)
    out = []
    for tenant, spec in specs.items():
        out.append(evaluate_window_burns(
            spec, by_tenant.get(tenant, []), now))
    return out


def format_slo_report(evals: Sequence[Dict[str, Any]]) -> str:
    """Per-tenant SLO budget table for ``diag serve``."""
    if not evals:
        return "(no SLO specs)"
    lines = [f"{'tenant':<16s} {'deadline':>9s} {'avail':>7s} "
             f"{'burn(short)':>12s} {'burn(long)':>11s} "
             f"{'budget left':>12s}  status"]
    for ev in evals:
        burns = ev["burn_rates"]
        short = burns[0] if burns else 0.0
        long_ = burns[-1] if burns else 0.0
        status = "BURNING" if ev["burning"] else "ok"
        if ev["shed_recommended"]:
            status += " +SHED"
        lines.append(
            f"{ev['tenant']:<16s} {ev['deadline_s']:>8.3f}s "
            f"{ev['availability']:>6.2%} {short:>11.2f}x {long_:>10.2f}x "
            f"{max(ev['budget_remaining'], 0.0):>11.1%}  {status}")
    return "\n".join(lines)
