"""Live fleet timeline: sampled queue/SLO state during a run.

Everything the fleet layer knew about itself before this module was
post-hoc: queue depth reconstructed from result manifests
(obs/aggregate.queue_depth_timeline), SLO burn evaluated after the
drain, cache behavior read from exit snapshots.  A load run needs the
*live* view — the coordinator calls :meth:`TimelineSampler.sample`
once per watch poll and each call appends ONE schema-versioned JSONL
row capturing, at that instant:

- queue depth by state (``waiting`` / ``leased`` / ``expired_leases``
  / ``done`` out of ``items``) from a single :meth:`LeaseQueue.stats`
  scan (names-only listdir counting — no item bodies are read);
- ``alive_workers`` as reported by the caller (the coordinator owns
  the Popen table);
- merged SLO-burn gauges, computed live by incrementally ingesting
  result manifests into an :class:`obs.slo.SLOMonitor` (only files not
  seen by a previous sample are parsed, so steady-state cost is
  O(new completions), not O(all completions)).  Shed manifests are
  *not* fed as burn samples — the same anti-latch rule admission
  control uses (a shed is the controller's own action, not tenant-
  visible error evidence);
- a live cache gauge: the shared AOT store's artifact count (the only
  compile-cache signal visible outside worker processes mid-run).

Rows share the EventLog durability contract: one ``os.write`` on an
``O_APPEND`` fd per sample, so concurrent writers never interleave and
a killed run keeps every row up to the kill.  Import-light (stdlib
only): ``diag load`` reads timelines on machines without jax.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Set

# v2: rows carry writer identity + mono/seq audit stamps (appended
# after the v1 keys; v1 rows remain valid)
TIMELINE_SCHEMA_VERSION = 2
_TIMELINE_KNOWN_VERSIONS = (1, 2)
TIMELINE_KIND = "fleet_timeline"

#: default timeline filename inside a fleet/load out-dir
TIMELINE_FILE = "timeline.jsonl"

#: row keys every valid sample must carry
_REQUIRED_ROW_KEYS = (
    "schema_version", "kind", "ts", "items", "done", "waiting",
    "leased", "expired_leases", "alive_workers",
)


def timeline_path(out_dir: str) -> str:
    return os.path.join(out_dir, TIMELINE_FILE)


class TimelineSampler:
    """Append one live fleet-state row per :meth:`sample` call.

    ``queue`` supplies depth-by-state; ``out_dir`` (when given)
    supplies result manifests for live burn/verdict gauges;
    ``slo_specs`` (tenant -> :class:`obs.slo.SLOSpec`) turns those
    manifests into burn rates.  All three are optional — a sampler
    with none of them still records timestamps and caller-provided
    fields, which is what the unit fixtures use."""

    def __init__(self, path: str, queue=None, out_dir: str = "",
                 slo_specs=None, aot_store: str = "",
                 clock=time.time):
        self.path = path
        self.queue = queue
        self.out_dir = out_dir
        self.aot_store = aot_store
        self.clock = clock
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._fd: Optional[int] = os.open(
            path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
        self._seen: Set[str] = set()
        self._verdicts: Dict[str, int] = {}
        from sagecal_tpu.obs.events import writer_identity

        self._writer = writer_identity()
        self._row_seq = 0
        self._monitor = None
        if slo_specs:
            from sagecal_tpu.obs.slo import SLOMonitor

            self._monitor = SLOMonitor(slo_specs)

    @property
    def closed(self) -> bool:
        return self._fd is None

    # -- manifest ingestion (incremental) ------------------------------

    def _ingest_new_manifests(self) -> None:
        if not self.out_dir or not os.path.isdir(self.out_dir):
            return
        try:
            names = os.listdir(self.out_dir)
        except OSError:
            return
        for name in sorted(names):
            if not name.endswith(".result.json") or name in self._seen:
                continue
            self._seen.add(name)
            try:
                with open(os.path.join(self.out_dir, name),
                          "r", encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError):
                # torn read of an in-flight manifest: forget it so the
                # next sample retries the (atomic-rename) final file
                self._seen.discard(name)
                continue
            if not isinstance(doc, dict) or not doc.get("request_id"):
                continue
            verdict = str(doc.get("verdict", ""))
            self._verdicts[verdict] = self._verdicts.get(verdict, 0) + 1
            if self._monitor is not None and verdict != "shed":
                # sheds don't burn (admission's anti-latch rule)
                self._monitor.observe(
                    str(doc.get("tenant", "")),
                    float(doc.get("completed_at") or 0.0),
                    float(doc.get("latency_s", 0.0)), verdict)

    def _aot_entries(self) -> Optional[int]:
        if not self.aot_store or not os.path.isdir(self.aot_store):
            return None
        try:
            return sum(1 for n in os.listdir(self.aot_store)
                       if not n.startswith("."))
        except OSError:
            return None

    # -- sampling ------------------------------------------------------

    def sample(self, now: Optional[float] = None,
               alive_workers: int = 0, **extra) -> Dict[str, Any]:
        """Capture + append one row; returns it (callers feed the same
        dict to the autoscale recommender so both see one snapshot)."""
        now = self.clock() if now is None else float(now)
        row: Dict[str, Any] = {
            "schema_version": TIMELINE_SCHEMA_VERSION,
            "kind": TIMELINE_KIND, "ts": now,
            "items": 0, "done": 0, "waiting": 0,
            "leased": 0, "expired_leases": 0,
            "alive_workers": int(alive_workers),
        }
        if self.queue is not None:
            st = self.queue.stats(now)
            row.update(items=st["items"], done=st["done"],
                       leased=st["leased"],
                       expired_leases=st["expired_leases"],
                       waiting=st.get("waiting",
                                      max(st["items"] - st["done"]
                                          - st["leased"]
                                          - st["expired_leases"], 0)))
        self._ingest_new_manifests()
        if self._verdicts:
            row["results_total"] = sum(self._verdicts.values())
            row["shed_total"] = self._verdicts.get("shed", 0)
            row["error_total"] = self._verdicts.get("error", 0)
        aot = self._aot_entries()
        if aot is not None:
            row["aot_store_entries"] = aot
        if self._monitor is not None and self._monitor.enabled:
            burns: Dict[str, List[float]] = {}
            for status in self._monitor.evaluate(now):
                burns[status["tenant"]] = [
                    round(b, 6) for b in status["burn_rates"]]
            row["slo_burn"] = burns
            row["slo_burn_max_short"] = max(
                (b[0] for b in burns.values() if b), default=0.0)
        for k, v in extra.items():
            if k not in row:
                row[k] = v
        # v2 audit stamps, appended after the v1 layout
        row.setdefault("writer", self._writer)
        row.setdefault("mono", time.monotonic())
        if "seq" not in row:
            row["seq"] = self._row_seq
            self._row_seq += 1
        fd = self._fd
        if fd is not None:
            os.write(fd, (json.dumps(row) + "\n").encode("utf-8"))
        return row

    def close(self) -> None:
        fd, self._fd = self._fd, None
        if fd is not None:
            os.close(fd)

    def __enter__(self) -> "TimelineSampler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_timeline(path: str) -> List[dict]:
    """Load a timeline's rows (skips blank/corrupt/foreign lines — a
    killed run may leave a truncated tail)."""
    out: List[dict] = []
    if not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(row, dict) and row.get("kind") == TIMELINE_KIND:
                out.append(row)
    out.sort(key=lambda r: float(r.get("ts", 0.0)))
    return out


def validate_timeline(rows) -> List[str]:
    """Structural problems of a timeline (empty list = valid): required
    keys present, schema version known, timestamps monotone, counts
    consistent (done+waiting+leased+expired == items)."""
    problems: List[str] = []
    if not rows:
        return ["no timeline rows"]
    last_ts = None
    for i, row in enumerate(rows):
        for k in _REQUIRED_ROW_KEYS:
            if k not in row:
                problems.append(f"row {i}: missing key {k}")
        sv = row.get("schema_version")
        if sv is not None and sv not in _TIMELINE_KNOWN_VERSIONS:
            problems.append(
                f"row {i}: schema_version {sv} not in "
                f"{_TIMELINE_KNOWN_VERSIONS}")
        ts = row.get("ts")
        if isinstance(ts, (int, float)):
            if last_ts is not None and ts < last_ts:
                problems.append(f"row {i}: ts not monotone")
            last_ts = float(ts)
        counts = [row.get(k) for k in
                  ("done", "waiting", "leased", "expired_leases",
                   "items")]
        if all(isinstance(c, int) for c in counts):
            if sum(counts[:4]) != counts[4]:
                problems.append(
                    f"row {i}: state counts {counts[:4]} do not sum "
                    f"to items={counts[4]}")
    return problems
