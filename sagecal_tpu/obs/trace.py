"""Distributed execution tracing: hierarchical spans + Chrome-trace export.

Host-side half of the causal-timing story (the metrics registry answers
"how much", spans answer "where and in what order").  A :class:`Tracer`
keeps a thread-local span stack so nested ``with tracer.span(...)``
blocks form a tree (trace id / span id / parent id), times each span
with ``time.monotonic()``, and appends one JSON line per finished span
to a JSONL file (``SAGECAL_TRACE_LOG``, default
``sagecal_trace.jsonl``).  ``close()`` additionally emits a Chrome
trace event file (``trace.json``) loadable in Perfetto / chrome://tracing.

Span records share the event-log vocabulary: the tracer's ``trace_id``
is set to the run manifest's ``run_id`` by the apps, so spans join
against the JSONL event stream on that id.

Per-band ADMM attribution: the whole consensus loop is ONE jitted
shard_map program, so per-band wall time cannot be measured host-side.
:func:`band_attribution` distributes a measured phase wall-time over
per-band work weights (unflagged-row fractions) into *synthetic* child
spans that sum exactly to the phase total; :func:`straggler_stats`
turns per-band seconds (real or attributed) into slowest/median ratio
and skew gauges.  Modes with a genuine host-side per-band loop
(minibatch consensus) record real band spans instead.

Discipline mirrors the rest of :mod:`sagecal_tpu.obs`:

- zero-cost when disabled — :func:`get_tracer` hands out a shared
  :class:`NullTracer` whose ``span()`` returns a reusable no-op context
  manager, so instrumented call sites never branch;
- host-side only — spans must never be opened inside jit-traced code
  (jaxlint JL002 territory); wrap the *dispatch* of a jitted function,
  not its body;
- import-light — this module imports neither jax nor numpy.

Enable with ``SAGECAL_TRACE=1``.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

# v2: spans carry writer identity + mono/seq audit stamps (appended
# after the v1 keys; v1 readers are unaffected, the offline auditor in
# obs/ledger.py accepts both versions)
SPAN_SCHEMA_VERSION = 2

_TRUTHY = ("1", "true", "yes", "on")

DEFAULT_TRACE_LOG = "sagecal_trace.jsonl"
DEFAULT_STRAGGLER_RATIO = 1.5


def _env_enabled() -> bool:
    return os.environ.get("SAGECAL_TRACE", "").strip().lower() in _TRUTHY


_enabled: Optional[bool] = None  # None -> defer to the env var


def trace_enabled() -> bool:
    """Master tracing switch: ``set_trace`` override if set, otherwise
    the ``SAGECAL_TRACE`` env var."""
    if _enabled is not None:
        return _enabled
    return _env_enabled()


def set_trace(on: Optional[bool]) -> None:
    """Force tracing on/off for this process (``None`` restores env-var
    control)."""
    global _enabled
    _enabled = on


def _jsonable(x):
    from sagecal_tpu.obs.events import _jsonable as ev_jsonable

    return ev_jsonable(x)


class _NullSpan:
    """Reusable no-op context manager (shared instance, allocation-free
    on the disabled path)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Live span handle; written to the tracer's JSONL on ``__exit__``."""

    __slots__ = ("tracer", "name", "attrs", "span_id", "parent_id",
                 "_t0_mono", "_t0_unix")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any]):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs
        self.span_id = ""
        self.parent_id = None
        self._t0_mono = 0.0
        self._t0_unix = 0.0

    def __enter__(self) -> "_Span":
        tr = self.tracer
        stack = tr._stack()
        self.parent_id = stack[-1] if stack else None
        self.span_id = tr._new_span_id()
        stack.append(self.span_id)
        self._t0_unix = time.time()
        self._t0_mono = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur = time.monotonic() - self._t0_mono
        tr = self.tracer
        stack = tr._stack()
        if stack and stack[-1] == self.span_id:
            stack.pop()
        elif self.span_id in stack:  # unbalanced exit: drop down to us
            del stack[stack.index(self.span_id):]
        attrs = self.attrs
        if exc_type is not None:
            attrs = dict(attrs)
            attrs["error"] = exc_type.__name__
        tr._write_span(self.name, self.span_id, self.parent_id,
                       self._t0_unix, dur, attrs)
        return False


class Tracer:
    """Process tracer: thread-local span stacks, one JSONL line per
    finished span (single ``os.write`` on an ``O_APPEND`` fd, so
    multi-process writers interleave whole lines), Chrome-trace export
    on :meth:`close`."""

    enabled = True

    def __init__(self, path: str, trace_id: Optional[str] = None,
                 chrome_path: Optional[str] = None):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._fd: Optional[int] = os.open(
            path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
        if trace_id is None:
            import uuid

            trace_id = uuid.uuid4().hex[:12]
        self.trace_id = trace_id
        self.chrome_path = chrome_path or default_chrome_path(path)
        self._local = threading.local()
        self._ids = itertools.count(1)
        self._pid = os.getpid()
        self._lock = threading.Lock()
        from sagecal_tpu.obs.events import writer_identity

        self._writer = writer_identity()
        self._seq = itertools.count(0)

    def _stack(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
        return stack

    def _new_span_id(self) -> str:
        return f"{self._pid:x}.{next(self._ids):x}"

    def current_span_id(self) -> Optional[str]:
        stack = self._stack()
        return stack[-1] if stack else None

    def allocate_span_id(self) -> str:
        """Reserve a span id to hand out (e.g. embed in a result
        manifest) before the span itself is recorded via
        :meth:`add_span` with ``span_id=``."""
        return self._new_span_id()

    def span(self, name: str, **attrs) -> _Span:
        """Context manager timing a nested span; attrs land in the
        record's ``attrs`` object."""
        return _Span(self, name, attrs)

    def add_span(self, name: str, seconds: float, *,
                 parent_id: Optional[str] = None,
                 start_unix: Optional[float] = None,
                 trace_id: Optional[str] = None,
                 span_id: Optional[str] = None,
                 **attrs) -> str:
        """Record an already-measured span (used for synthetic per-band
        / per-round attribution children, and for serve's per-request
        lifecycle chains).  ``trace_id`` overrides the tracer-wide id so
        one process can write many logical traces (one per request);
        ``span_id`` records under a previously
        :meth:`allocate_span_id`-reserved id.  Returns the span id so
        callers can parent further children under it."""
        if span_id is None:
            span_id = self._new_span_id()
        if start_unix is None:
            start_unix = time.time() - seconds
        if parent_id is None:
            parent_id = self.current_span_id()
        self._write_span(name, span_id, parent_id, start_unix,
                         float(seconds), attrs, trace_id=trace_id)
        return span_id

    def _write_span(self, name: str, span_id: str,
                    parent_id: Optional[str], ts: float, dur: float,
                    attrs: Dict[str, Any],
                    trace_id: Optional[str] = None) -> None:
        rec = {
            "kind": "span",
            "schema_version": SPAN_SCHEMA_VERSION,
            "trace_id": trace_id or self.trace_id,
            "span_id": span_id,
            "parent_id": parent_id,
            "name": name,
            "ts": ts,
            "dur": dur,
            "pid": self._pid,
            "tid": threading.get_ident(),
            "thread": threading.current_thread().name,
        }
        if attrs:
            rec["attrs"] = {str(k): _jsonable(v) for k, v in attrs.items()}
        # v2 audit stamps, appended after the v1 layout: writer
        # identity + per-writer sequence + a monotonic reading taken at
        # write time (same-writer ordering under wall-clock steps)
        rec["writer"] = self._writer
        rec["mono"] = time.monotonic()
        rec["seq"] = next(self._seq)
        line = (json.dumps(rec) + "\n").encode("utf-8")
        fd = self._fd
        if fd is None:
            return
        try:
            os.write(fd, line)  # one write per line: atomic under O_APPEND
        except OSError:
            pass
        from sagecal_tpu.obs.flight import note_activity

        note_activity("span", name=name, dur=dur)

    def close(self) -> None:
        """Close the JSONL fd and (re)write the Chrome trace file from
        every span recorded so far at :attr:`path`."""
        with self._lock:
            fd, self._fd = self._fd, None
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass
        try:
            spans = read_spans(self.path)
            if spans:
                write_chrome_trace(spans, self.chrome_path)
        except OSError:
            pass


class NullTracer:
    """No-op tracer handed out when tracing is disabled: ``span()``
    returns a shared allocation-free context manager, everything else
    returns immediately.  Shared singleton."""

    enabled = False

    def span(self, name: str, **attrs) -> _NullSpan:
        return _NULL_SPAN

    def add_span(self, name, seconds, *, parent_id=None, start_unix=None,
                 trace_id=None, span_id=None, **attrs) -> None:
        return None

    def current_span_id(self) -> None:
        return None

    def allocate_span_id(self) -> str:
        return ""

    def close(self) -> None:
        pass


_NULL = NullTracer()
_TRACER: Optional[Tracer] = None
_TRACER_LOCK = threading.Lock()


def default_trace_path() -> str:
    return os.environ.get("SAGECAL_TRACE_LOG") or DEFAULT_TRACE_LOG


def default_chrome_path(trace_path: str) -> str:
    base = trace_path[:-6] if trace_path.endswith(".jsonl") else trace_path
    return base + ".trace.json"


def configure_tracer(run_id: Optional[str] = None,
                     path: Optional[str] = None) -> Optional[Tracer]:
    """App entry point: install the process tracer (correlated with the
    run manifest's ``run_id``) when tracing is enabled.  Returns None
    when disabled.  The first configuration wins; later calls return
    the existing tracer."""
    global _TRACER
    if not trace_enabled():
        return None
    with _TRACER_LOCK:
        if _TRACER is None:
            _TRACER = Tracer(path or default_trace_path(), trace_id=run_id)
        return _TRACER


def get_tracer() -> Any:
    """The process tracer when tracing is on (auto-configured from env
    on first use), else the shared :class:`NullTracer`."""
    tr = _TRACER
    if tr is not None:
        return tr
    if not trace_enabled():
        return _NULL
    return configure_tracer() or _NULL


def close_tracer() -> None:
    """Flush + close the process tracer (writes the Chrome trace file);
    the next :func:`configure_tracer` starts fresh."""
    global _TRACER
    with _TRACER_LOCK:
        tr, _TRACER = _TRACER, None
    if tr is not None:
        tr.close()


# ---------------------------------------------------------------------------
# span file readers / Chrome trace export


def read_spans(path: str) -> List[dict]:
    """Load span records from a span JSONL file (tolerates foreign /
    corrupt lines the same way :func:`obs.events.read_events` does)."""
    from sagecal_tpu.obs.events import read_events

    return [r for r in read_events(path) if r.get("kind") == "span"]


def to_chrome_trace(spans: Sequence[dict]) -> dict:
    """Convert span records to the Chrome trace event format (JSON
    object flavour: ``{"traceEvents": [...]}``) — Perfetto and
    chrome://tracing both load it directly.

    Lanes: spans carry an optional ``attrs.lane`` (e.g. ``band3`` for
    synthetic per-band children); otherwise the recording thread is the
    lane.  Timestamps are rebased to the earliest span = 0 µs.
    """
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(float(s.get("ts", 0.0)) for s in spans)
    lanes: Dict[Tuple[int, str], int] = {}
    events: List[dict] = []
    pids = sorted({int(s.get("pid", 0)) for s in spans})
    for s in spans:
        pid = int(s.get("pid", 0))
        attrs = s.get("attrs") or {}
        lane = str(attrs.get("lane") or s.get("thread") or s.get("tid", 0))
        key = (pid, lane)
        if key not in lanes:
            lanes[key] = len([k for k in lanes if k[0] == pid]) + 1
        args = dict(attrs)
        args["span_id"] = s.get("span_id")
        if s.get("parent_id"):
            args["parent_id"] = s.get("parent_id")
        args["trace_id"] = s.get("trace_id")
        events.append({
            "name": s.get("name", "?"),
            "ph": "X",
            "ts": (float(s.get("ts", 0.0)) - t0) * 1e6,
            "dur": max(float(s.get("dur", 0.0)), 0.0) * 1e6,
            "pid": pid,
            "tid": lanes[key],
            "cat": str(attrs.get("kind", "span")),
            "args": args,
        })
    meta: List[dict] = []
    for pid in pids:
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": f"sagecal-tpu pid={pid}"}})
    for (pid, lane), tid in sorted(lanes.items(), key=lambda kv: kv[1]):
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": lane}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_chrome_trace(spans: Sequence[dict], path: str) -> str:
    """Write :func:`to_chrome_trace` output atomically; returns path."""
    doc = to_chrome_trace(spans)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# span-tree analysis (pure python; used by `diag trace` and tests)


def build_span_tree(spans: Sequence[dict]):
    """Return ``(roots, children)``: root span records (no parent, or
    parent missing from the file) and a ``parent_id -> [child, ...]``
    map, both in start-time order."""
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}
    children: Dict[str, List[dict]] = {}
    roots: List[dict] = []
    for s in spans:
        pid = s.get("parent_id")
        if pid and pid in by_id:
            children.setdefault(pid, []).append(s)
        else:
            roots.append(s)
    key = lambda s: float(s.get("ts", 0.0))  # noqa: E731
    roots.sort(key=key)
    for v in children.values():
        v.sort(key=key)
    return roots, children


def format_span_tree(spans: Sequence[dict], max_children: int = 12) -> str:
    """Indented span-tree rendering (durations in seconds)."""
    roots, children = build_span_tree(spans)
    lines: List[str] = []

    def emit(s: dict, depth: int) -> None:
        attrs = s.get("attrs") or {}
        extra = ""
        tag = []
        if attrs.get("synthetic"):
            tag.append("synthetic")
        for k in ("band", "round", "tile"):
            if k in attrs:
                tag.append(f"{k}={attrs[k]}")
        if tag:
            extra = "  [" + " ".join(tag) + "]"
        lines.append(
            f"{'  ' * depth}{s.get('name','?'):<24s}"
            f" {float(s.get('dur',0.0)):10.4f}s{extra}")
        kids = children.get(s.get("span_id"), [])
        for c in kids[:max_children]:
            emit(c, depth + 1)
        if len(kids) > max_children:
            lines.append(f"{'  ' * (depth + 1)}... {len(kids) - max_children}"
                         " more children elided")

    for r in roots:
        emit(r, 0)
    return "\n".join(lines)


def critical_path(spans: Sequence[dict]) -> List[dict]:
    """Greedy critical path: from the longest root, repeatedly descend
    into the longest child.  A useful first answer to "where did the
    wall-clock go" without needing precise overlap accounting."""
    roots, children = build_span_tree(spans)
    if not roots:
        return []
    path = [max(roots, key=lambda s: float(s.get("dur", 0.0)))]
    while True:
        kids = children.get(path[-1].get("span_id"), [])
        if not kids:
            return path
        path.append(max(kids, key=lambda s: float(s.get("dur", 0.0))))


def aggregate_by_name(spans: Sequence[dict]) -> Dict[str, dict]:
    """Per-span-name totals: ``{name: {count, total, max}}``."""
    out: Dict[str, dict] = {}
    for s in spans:
        a = out.setdefault(s.get("name", "?"),
                           {"count": 0, "total": 0.0, "max": 0.0})
        dur = float(s.get("dur", 0.0))
        a["count"] += 1
        a["total"] += dur
        a["max"] = max(a["max"], dur)
    return out


def band_seconds_from_spans(spans: Sequence[dict]) -> Dict[int, float]:
    """Sum span durations per ``attrs.band`` (real or synthetic)."""
    out: Dict[int, float] = {}
    for s in spans:
        attrs = s.get("attrs") or {}
        if "band" in attrs:
            try:
                b = int(attrs["band"])
            except (TypeError, ValueError):
                continue
            out[b] = out.get(b, 0.0) + float(s.get("dur", 0.0))
    return out


# ---------------------------------------------------------------------------
# straggler attribution


def band_attribution(total_seconds: float,
                     weights: Sequence[float]) -> List[float]:
    """Distribute a measured wall-time over per-band work weights.

    The weights are per-band work proxies (unflagged-row fractions for
    the mesh ADMM; padding bands carry weight 0 and get 0 s).  Falls
    back to a uniform split when the weights are all zero/negative.
    The returned list sums to ``total_seconds`` exactly (last band
    absorbs the float residue) so synthesized child spans reconcile
    with the parent phase."""
    w = [max(float(x), 0.0) for x in weights]
    n = len(w)
    if n == 0:
        return []
    tot = sum(w)
    if tot <= 0.0:
        w = [1.0] * n
        tot = float(n)
    out = [total_seconds * x / tot for x in w]
    out[-1] += total_seconds - sum(out)
    return out


def straggler_ratio_threshold() -> float:
    """Slowest/median ratio above which a band counts as a straggler
    (``SAGECAL_STRAGGLER_RATIO``, default 1.5)."""
    try:
        return float(os.environ.get("SAGECAL_STRAGGLER_RATIO", ""))
    except ValueError:
        return DEFAULT_STRAGGLER_RATIO


def straggler_stats(band_seconds: Sequence[float],
                    ratio_thresh: Optional[float] = None) -> dict:
    """Imbalance gauges over per-band seconds (real or attributed):
    slowest/median ratio, relative skew ``(max-mean)/mean``, the worst
    band, and a detection verdict at ``ratio_thresh`` (default from
    :func:`straggler_ratio_threshold`).  Delegates the array math to
    :func:`sagecal_tpu.parallel.consensus.band_imbalance` so the
    definition lives next to the other consensus health metrics.

    Reading the straggler table under bounded staleness (the
    ``--consensus-staleness`` async rounds of
    ``parallel/async_consensus.py``): a heavy band refreshing every
    ``p`` rounds bills its solve time to 1-in-``p`` rounds, so its
    per-round attributed seconds — and hence this ratio — drop by
    ~``p``x relative to the synchronous schedule.  A PERSISTENT high
    ratio in async mode therefore means the refresh periods no longer
    match the actual skew (e.g. flag fractions drifted since the
    periods were derived) rather than an unscheduled slow band."""
    if ratio_thresh is None:
        ratio_thresh = straggler_ratio_threshold()
    secs = [float(x) for x in band_seconds]
    if not secs:
        return {"ratio": 1.0, "skew": 0.0, "argmax": 0, "median": 0.0,
                "detected": False, "threshold": ratio_thresh,
                "band_seconds": []}
    from sagecal_tpu.parallel.consensus import band_imbalance

    ratio, skew, worst = band_imbalance(secs)
    srt = sorted(secs)
    n = len(srt)
    med = (srt[n // 2] if n % 2 else 0.5 * (srt[n // 2 - 1] + srt[n // 2]))
    return {
        "ratio": float(ratio),
        "skew": float(skew),
        "argmax": int(worst),
        "median": float(med),
        "detected": bool(float(ratio) > ratio_thresh and n > 1),
        "threshold": float(ratio_thresh),
        "band_seconds": secs,
    }


def format_straggler_table(band_seconds: Dict[int, float],
                           ratio_thresh: Optional[float] = None) -> str:
    """Per-band straggler table for ``diag trace``."""
    if not band_seconds:
        return "(no per-band spans)"
    bands = sorted(band_seconds)
    secs = [band_seconds[b] for b in bands]
    stats = straggler_stats(secs, ratio_thresh)
    total = sum(secs) or 1.0
    lines = [f"{'band':>6s} {'seconds':>12s} {'share':>8s} "
             f"{'vs median':>10s}"]
    for b, s in zip(bands, secs):
        vs = s / stats["median"] if stats["median"] > 0 else float("inf")
        mark = "  <-- straggler" if (
            stats["detected"] and b == bands[stats["argmax"]]) else ""
        lines.append(f"{b:>6d} {s:>12.4f} {s / total:>7.1%} "
                     f"{vs:>9.2f}x{mark}")
    verdict = ("STRAGGLER DETECTED" if stats["detected"] else "balanced")
    lines.append(
        f"slowest/median {stats['ratio']:.2f}x (threshold "
        f"{stats['threshold']:.2f}x), skew {stats['skew']:+.2f} -> {verdict}")
    return "\n".join(lines)


def format_trace_report(spans: Sequence[dict],
                        ratio_thresh: Optional[float] = None) -> str:
    """Full ``diag trace`` report: summary, span tree, per-name
    attribution, critical path, per-band straggler table."""
    if not spans:
        return "(no spans)"
    traces = sorted({s.get("trace_id") for s in spans if s.get("trace_id")})
    tmin = min(float(s.get("ts", 0.0)) for s in spans)
    tmax = max(float(s.get("ts", 0.0)) + float(s.get("dur", 0.0))
               for s in spans)
    out = [
        f"spans: {len(spans)}  traces: {len(traces)} "
        f"({', '.join(traces[:4])}{'...' if len(traces) > 4 else ''})",
        f"wall window: {tmax - tmin:.4f}s",
        "",
        "span tree:",
        format_span_tree(spans),
        "",
        "attribution by span name:",
    ]
    agg = aggregate_by_name(spans)
    out.append(f"{'name':<26s} {'count':>6s} {'total_s':>10s} {'max_s':>10s}")
    for name, a in sorted(agg.items(), key=lambda kv: -kv[1]["total"]):
        out.append(f"{name:<26s} {a['count']:>6d} {a['total']:>10.4f} "
                   f"{a['max']:>10.4f}")
    path = critical_path(spans)
    out.append("")
    out.append("critical path: " + " > ".join(
        f"{s.get('name','?')}({float(s.get('dur',0.0)):.3f}s)"
        for s in path))
    out.append("")
    out.append("per-band attribution (straggler table):")
    out.append(format_straggler_table(band_seconds_from_spans(spans),
                                      ratio_thresh))
    return "\n".join(out)
