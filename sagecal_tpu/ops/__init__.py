from sagecal_tpu.ops import special, rime  # noqa: F401
