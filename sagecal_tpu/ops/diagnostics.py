"""Influence-function diagnostics: the ``-i`` flag.

Redesign of ``calculate_diagnostics_gpu``
(``/root/reference/src/lib/Radio/diagnostics.c:1040-1182``, kernels
``influence_function.cu:84-505``, decl ``Dirac_radio.h:668-709``):
instead of residuals, write the *influence function* of the calibration
— how strongly a perturbation of the visibility on one baseline leaks
into the residual of every baseline through the solved gains — so users
can identify baselines whose data dominate (or are suppressed by) the
direction-dependent solutions.

Math (per cluster k, at the solved gains; first channel only, F==1 as
in the reference):

1. ``H = d g / d vec(J)`` where ``g = df/d conj(vec(J))`` is the
   Wirtinger gradient of the data misfit ``f = sum ||V - J_p C J_q^H||^2``
   over the station-stacked ``X in C^{2N x 2}`` (column-major vec,
   4N complex).  Blocks per baseline (p, q)  [kernel_hessian]:
     (col p, row p) += kron(((C J_q^H)(C J_q^H)^H)^T, I_2)
     (col q, row q) += kron(((J_p C)^H (J_p C))^T,   I_2)
     (col q, row p) += kron(-conj(C), R)
     (col p, row q) += kron(-C^T,     R^H)
   Small diagonal entries are conditioned to 1, and with consensus info
   (rho, Bpoly, Binv) the spectral-constraint curvature
   ``0.5 rho Fd1`` is added to the diagonal (diagnostics.c:716-748).
2. ``AdV[:, b] = sum_t vec((1+j) ones(2,2) (J_q C^H))`` at station-p row
   blocks — the gradient perturbation from nudging every element of
   V on per-timeslot baseline b by (1+j)  [kernel_d_solutions].
3. ``U = lstsq(H, AdV)`` — the gain sensitivity dJ/dV
   (diagnostics.c my_cgels call).
4. ``dR[b', b] += vec(-U_p(b) (sum_t C J_q^H))`` on rows b' sharing
   station p — the residual change on baseline b' from the perturbation
   on b  [kernel_d_residuals; only the sta1 (p) block, as the kernel].
5. Per correlation c in the vec order [00, 10, 01, 11]: eigenvalues of
   the (Nbase x Nbase) complex matrix ``dR[:, :, c]`` replace the
   residuals: baseline b's 8 reals become
   [Re l_0(b), Im l_0(b), ..., Re l_3(b), Im l_3(b)], replicated over
   the tile's timeslots  [find_eigenvalues, diagnostics.c:847-1010].

The pthread/2-GPU fan-out and hand-written kron kernels of the
reference dissolve into batched einsums + one scatter-add; the
non-Hermitian eigensolve runs on the host (np.linalg.eigvals) because
XLA's TPU backend has no general eig — matching the reference, which
also hands this step to a solver library (cusolverDnXgeev).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from sagecal_tpu.core.types import VisData, params_to_jones
from sagecal_tpu.solvers.sage import ClusterData, predict_full_model


def _kron4(A, B):
    """Batched np.kron for (rows, 2, 2) blocks -> (rows, 4, 4)."""
    return jnp.einsum("rab,rij->raibj", A, B).reshape(A.shape[0], 4, 4)


def _vec_idx_assemble(blocks_colrow, N):
    """(N, N, 4, 4) station blocks [col_sta, row_sta] -> (4N, 4N) with the
    column-major vec(X) layout: index(c, s, r) = c*2N + 2 s + r."""
    # blocks[m, n, 2c1+r1, 2c2+r2] -> H[c1*2N+2n+r1, c2*2N+2m+r2]
    b = blocks_colrow.reshape(N, N, 2, 2, 2, 2)  # (m, n, c1, r1, c2, r2)
    H = jnp.transpose(b, (2, 1, 3, 4, 0, 5))  # (c1, n, r1, c2, m, r2)
    return H.reshape(4 * N, 4 * N)


def _cluster_hessian(C, R, Jp, Jq, ant_p, ant_q, N):
    """H = dg/dvec(J): (4N, 4N) complex  [kernel_hessian].

    C/R: (rows, 2, 2) coherency + residual; Jp/Jq: (rows, 2, 2) per-row
    gains (already chunk-gathered).
    """
    rows = C.shape[0]
    herm = lambda m: jnp.conj(jnp.swapaxes(m, -1, -2))
    CJqH = C @ herm(Jq)  # (rows, 2, 2)
    JpC = Jp @ C
    Mpp = CJqH @ herm(CJqH)
    Mqq = herm(JpC) @ JpC
    I2 = jnp.broadcast_to(jnp.eye(2, dtype=C.dtype), (rows, 2, 2))
    Bpp = _kron4(jnp.swapaxes(Mpp, -1, -2), I2)
    Bqq = _kron4(jnp.swapaxes(Mqq, -1, -2), I2)
    Bqp = _kron4(-jnp.conj(C), R)  # (col q, row p)
    Bpq = _kron4(-jnp.swapaxes(C, -1, -2), herm(R))  # (col p, row q)
    blocks = jnp.zeros((N, N, 4, 4), C.dtype)
    blocks = blocks.at[ant_p, ant_p].add(Bpp)
    blocks = blocks.at[ant_q, ant_q].add(Bqq)
    blocks = blocks.at[ant_q, ant_p].add(Bqp)
    blocks = blocks.at[ant_p, ant_q].add(Bpq)
    return _vec_idx_assemble(blocks, N)


def _condition_diag(H, extra=0.0):
    """Flagged stations leave 0 on the diagonal -> set to 1; optionally
    add the consensus curvature (diagnostics.c:710-748)."""
    d = jnp.diagonal(H)
    d1 = jnp.where(jnp.abs(d) < 1e-5, 1.0 + 0.0j, d) + extra
    return H - jnp.diag(d) + jnp.diag(d1)


def consensus_hessian_addition(rho_k, Bpoly, Binv_k):
    """0.5 * rho * Fd1 diagonal addition from the frequency-consensus
    constraint (diagnostics.c:716-748; analysis_uvwdir.m ln 170-180).

    Bpoly: (Npoly,) this band's basis row; Binv_k: (Npoly, Npoly)
    per-cluster pseudo-inverse of sum_f rho_f B_f B_f^T.
    """
    bfBibf = Bpoly @ (Binv_k @ Bpoly)
    Fd = 1.0 - bfBibf
    Fdd = Fd * Fd
    Fd1 = Fdd * (1.0 + Fdd / jnp.maximum(1.0 - Fdd, 1e-12))
    return 0.5 * rho_k * Fd1


def influence_function(
    data: VisData,
    cdata: ClusterData,
    p: jax.Array,
    rho: Optional[jax.Array] = None,
    Bpoly: Optional[jax.Array] = None,
    Binv: Optional[jax.Array] = None,
) -> np.ndarray:
    """Influence eigenvalues in place of residuals: (F, 4, rows) complex
    (flat layout; every channel carries the same values, as the
    reference computes F==1 and replicates).

    p: (M, nchunk_max, 8N) solved parameters; rho/Bpoly/Binv: optional
    consensus info (per-cluster rho (M,), basis row (Npoly,), inverses
    (M, Npoly, Npoly)) for the constraint curvature.
    """
    M = cdata.coh.shape[0]
    N = data.nstations
    Bt = data.nbase
    T = data.tilesz
    F = data.nchan
    rows = Bt * T

    # residual at the solution, channel 0 (F==1 in the reference)
    res_flat = (data.vis - predict_full_model(p, cdata, data)) * data.mask[
        ..., None, :
    ]
    # per-row 2x2 mat views, channel 0
    def mat22(flat_c):  # (4, rows) -> (rows, 2, 2)
        return jnp.moveaxis(flat_c, -1, 0).reshape(rows, 2, 2)

    Rm = mat22(res_flat[0])
    maskr = data.mask[0]  # (rows,)

    dR = jnp.zeros((Bt, Bt, 2, 2), jnp.complex64)
    ones2 = jnp.full((2, 2), 1.0 + 1.0j, jnp.complex64)

    for k in range(M):
        Cm = mat22(cdata.coh[k, 0]) * maskr[:, None, None]
        jones = params_to_jones(p[k])  # (nchunk, N, 2, 2)
        Jp = jones[cdata.chunk_map[k], data.ant_p]
        Jq = jones[cdata.chunk_map[k], data.ant_q]
        H = _cluster_hessian(
            Cm.astype(jnp.complex64), Rm.astype(jnp.complex64),
            Jp.astype(jnp.complex64), Jq.astype(jnp.complex64),
            data.ant_p, data.ant_q, N,
        )
        extra = 0.0
        if rho is not None and Bpoly is not None and Binv is not None:
            extra = consensus_hessian_addition(rho[k], Bpoly, Binv[k])
        H = _condition_diag(H, extra)

        # AdV: (4N, Bt) gradient perturbations [kernel_d_solutions]
        herm = lambda m: jnp.conj(jnp.swapaxes(m, -1, -2))
        JqCH = (Jq @ herm(Cm)).reshape(T, Bt, 2, 2).sum(0)  # (Bt, 2, 2)
        blockp = (ones2[None] @ JqCH.astype(jnp.complex64))  # (Bt, 2, 2)
        # scatter station-p row blocks: vec index (c*2N + 2s + r)
        AdV = jnp.zeros((2, N, 2, Bt), jnp.complex64)  # (c, sta, r, col)
        bl_idx = jnp.arange(Bt)
        p_bl = data.ant_p[:Bt]  # station map constant across timeslots
        AdV = AdV.at[:, p_bl, :, bl_idx].add(
            jnp.transpose(blockp, (0, 2, 1))  # (Bt, c, r)
        )
        AdV = AdV.reshape(4 * N, Bt)

        U, *_ = jnp.linalg.lstsq(H, AdV)  # (4N, Bt) gain sensitivities
        Up = U.reshape(2, N, 2, Bt)  # (c, sta, r, col)

        # dR accumulation [kernel_d_residuals]: only the p (sta1) block
        Asum = (-(Cm @ herm(Jq))).reshape(T, Bt, 2, 2).sum(0)  # (Bt, 2, 2)
        # contribution[b_row, col, r, c] = sum_k Up[c? ...]
        Upb = jnp.transpose(Up[:, p_bl], (1, 2, 0, 3))  # (Bt, r, c, col)
        contrib = jnp.einsum(
            "brkl,bkc->blrc", Upb, Asum.astype(jnp.complex64)
        )  # (Bt, col, 2, 2)
        dR = dR + contrib

    # eigenvalues per correlation, vec order [00, 10, 01, 11]
    dR_np = np.asarray(dR)
    out = np.zeros((rows, 8), np.float64)
    for ci, (r, c) in enumerate(((0, 0), (1, 0), (0, 1), (1, 1))):
        lam = np.linalg.eigvals(dR_np[:, :, r, c])  # (Bt,)
        out[:, 2 * ci] = np.tile(lam.real, T)
        out[:, 2 * ci + 1] = np.tile(lam.imag, T)
    # -> flat (F, 4, rows) complex, replicated over channels
    cplx = out[:, 0::2] + 1j * out[:, 1::2]  # (rows, 4) in vec order
    # vec order [00,10,01,11] -> component order [00,01,10,11]
    cplx = cplx[:, [0, 2, 1, 3]]
    flat = np.broadcast_to(np.moveaxis(cplx, 0, -1)[None], (F, 4, rows))
    return np.ascontiguousarray(flat)
