"""Diffuse-sky spatial-model application: ``-D`` / recalculate path.

Redesign of ``recalculate_diffuse_coherencies``
(``/root/reference/src/lib/Radio/diffuse_predict.c:295-586``, decl
``Dirac_radio.h:228``): a shapelet-modelled diffuse cluster's
coherencies are RE-predicted with the spatial model Z applied as
per-station Jones-valued shapelet corrections —
``S_p x S_k x S_q^H`` where ``S_p`` is station p's spatial model (its
column of Z), ``S_k`` the source's shapelet decomposition (times its
Stokes coherency), all combined in shapelet space via the product
tensors (shapelet.c:640-960) so the uv evaluation stays one mode sum
per baseline.

The reference's per-station/per-baseline pthread loops become einsums
over (N, N, modes) arrays; the uv evaluation vectorizes over rows with
the same basis scan used by the ordinary shapelet predict.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from sagecal_tpu.core.types import VisData
from sagecal_tpu.ops.rime import ST_SHAPELET, ShapeletTable, SourceBatch
from sagecal_tpu.ops.shapelets import (
    shapelet_product_jones,
    shapelet_product_tensor,
    uv_mode_vectors,
)
from sagecal_tpu.ops.special import sinc_abs
from sagecal_tpu.solvers.sage import ClusterData


def spatial_station_modes(Zspat: jax.Array, N: int, sh_n0: int) -> jax.Array:
    """Spatial model Z (2N, 2G) -> per-station Jones mode sets
    (N, G, 2, 2) (the Zt transpose of diffuse_predict.c:375-386:
    station s rows 2s:2s+2, mode g cols 2g:2g+2)."""
    G = sh_n0 * sh_n0
    Z = Zspat.reshape(N, 2, G, 2)  # (station, row, mode, col)
    return jnp.transpose(Z, (0, 2, 1, 3))  # (N, G, 2, 2)


def recalculate_diffuse_coherencies(
    data: VisData,
    cdata: ClusterData,
    cid: int,
    src: SourceBatch,
    table: ShapeletTable,
    Zspat: jax.Array,
    sh_n0: int,
    sh_beta: float,
    fdelta: Optional[float] = None,
) -> ClusterData:
    """Replace cluster ``cid``'s coherencies with the spatial-model-
    corrected diffuse prediction.

    src: the cluster's sources — every member must be ST_SHAPELET
    (diffuse_predict.c:395-399 aborts otherwise); table: their mode
    sets; Zspat: (2N, 2G) complex spatial model (G = sh_n0^2).
    Returns a new ClusterData.
    """
    stypes = np.asarray(src.stype)
    if not np.all(stypes == ST_SHAPELET):
        raise ValueError("diffuse cluster must contain only shapelet sources")
    N = data.nstations
    rows = data.ant_p.shape[0]
    F = data.nchan
    if fdelta is None:
        fdelta = data.deltaf
    cdt = cdata.coh.dtype
    Zt = spatial_station_modes(jnp.asarray(Zspat, cdt), N, sh_n0)  # (N, G, 2, 2)

    acc = jnp.zeros((F, 4, rows), cdt)
    for s in range(src.nsources):
        idx = int(np.asarray(src.shapelet_idx)[s])
        n0 = table.n0max
        beta = float(np.asarray(table.beta)[idx])
        beta_img = beta / (2.0 * np.pi)  # model FT scale -> image scale
        modes = jnp.asarray(table.modes)[idx].astype(cdt)  # (n0^2,)
        # Stokes coherency of this source (C = [[I+Q, U+iV],[U-iV, I-Q]])
        I0 = jnp.asarray(src.sI0)[s]
        Q0 = jnp.asarray(src.sQ0)[s]
        U0 = jnp.asarray(src.sU0)[s]
        V0 = jnp.asarray(src.sV0)[s]
        C_st = jnp.asarray(
            [[I0 + Q0, U0 + 1j * V0], [U0 - 1j * V0, I0 - Q0]], cdt
        )
        s_coh = modes[:, None, None] * C_st[None]  # (n0^2, 2, 2)

        # C J_q^H per station (diffuse_predict.c:454): product over
        # (n0, n0, sh_n0) tensor, hermitian
        T1 = shapelet_product_tensor(n0, n0, sh_n0, beta_img, beta_img, sh_beta)
        C_Jq = shapelet_product_jones(
            T1, jnp.broadcast_to(s_coh, (N,) + s_coh.shape), Zt, hermitian=True
        )  # (N, n0^2, 2, 2)
        # J_p (C J_q^H) per station pair (diffuse_predict.c:501)
        T2 = shapelet_product_tensor(n0, sh_n0, n0, beta_img, sh_beta, beta_img)
        Jp_C_Jq = shapelet_product_jones(
            T2,
            jnp.broadcast_to(Zt[:, None], (N, N) + Zt.shape[1:]),
            jnp.broadcast_to(C_Jq[None], (N, N) + C_Jq.shape[1:]),
            hermitian=False,
        )  # (N, N, n0^2, 2, 2)

        # per-row modes by station pair, then uv evaluation
        pair = data.ant_p * N + data.ant_q  # (rows,)
        rowmodes = Jp_C_Jq.reshape(N * N, n0 * n0, 2, 2)[pair]  # (rows, m, 2, 2)
        # phase + smearing at freq0 (diffuse_predict.c:355-372 uses the
        # per-channel freq; we evaluate per channel)
        ll = jnp.asarray(src.ll)[s]
        mm = jnp.asarray(src.mm)[s]
        nn = jnp.asarray(src.nn)[s]
        G = 2.0 * jnp.pi * (data.u * ll + data.v * mm + data.w * nn)  # (rows,)
        for f in range(F):
            freq = data.freqs[f]
            ang = freq * G
            ph = jax.lax.complex(jnp.cos(ang), jnp.sin(ang))
            smear = sinc_abs(G * (0.5 * fdelta))
            # uv in wavelengths, u negated (shapelet_contrib convention)
            Av = uv_mode_vectors(
                -data.u * freq, data.v * freq, beta, n0
            ).astype(cdt)  # (rows, n0^2)
            coh_rows = jnp.einsum("rm,rmij->rij", Av, rowmodes)
            fac = (ph * smear).astype(cdt)
            contrib = coh_rows * fac[:, None, None]  # (rows, 2, 2)
            flat = jnp.moveaxis(contrib.reshape(rows, 4), 0, -1)  # (4, rows)
            acc = acc.at[f].add(flat)

    return cdata._replace(coh=cdata.coh.at[cid].set(acc))
