"""On-device calibration-quality reductions: fixed-shape side outputs.

The reference surfaces solution quality only as scattered printfs (the
per-cluster chi^2 inside ``sagefit_visibilities``, the Student's-t nu
after each EM pass) and post-hoc influence maps (``-i``,
ops/diagnostics.py).  This module turns those signals into FIXED-SHAPE
arrays computed *inside* the jitted solves so they ride out of
jit/scan/while_loop as auxiliary pytree outputs — the same contract as
:mod:`sagecal_tpu.obs.records`: no host callbacks, no data-dependent
shapes, statically gated (``collect_quality=False`` keeps every slot
``None``, an empty pytree, so the jitted output signature is unchanged
and enabling quality can never cost a recompile of the disabled path).

Three reduction families:

- **chi^2 attribution** (:func:`row_chi2` + :func:`chi2_scatter`): the
  solver's own squared-residual objective, re-scattered per station /
  per baseline / per chunk.  The invariants (pinned in
  tests/test_quality.py) are exact in exact arithmetic:
  ``chi2_chunk`` == the solver's final per-chunk cost,
  ``sum(chi2_baseline) == sum(chi2_chunk)``, and
  ``sum(chi2_station) == 2 * sum(chi2_chunk)`` (every baseline row
  charges both of its stations).
- **robust-noise statistics** (:func:`weight_stats`): a fixed-bin
  histogram of the normalized Student's-t weights, the effectively
  down-weighted fraction, and the flagged fraction — the observable form
  of the reference's IRLS weights (``update_w_and_nu``).
- **gain health** (:func:`gain_health`): NaN/Inf sentinels, per-station
  amplitude and its spread across chunk lanes, circular phase spread,
  and departure-from-identity (a warm start that drifts far from its
  initialization is the round-5 bf16 divergence signature).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from sagecal_tpu.core.types import params_to_jones

# Fixed weight-histogram bin count: part of the jitted output shape, so
# it is a module constant, not a runtime knob.
WEIGHT_HIST_BINS = 16
# A normalized Student's-t weight below this marks the visibility as
# effectively down-weighted (w = (nu+1)/(nu+e^2) scaled to [0, 1]).
DOWNWEIGHT_THRESH = 0.5


class SolveQuality(NamedTuple):
    """Fixed-shape quality side outputs of one solve.

    Every field is Optional: a solver fills the subset it can see
    (robust solvers add nu/weight stats, gain health needs only ``p``)
    and leaves the rest ``None`` — ``None`` is an empty pytree, so any
    statically-chosen subset keeps a fixed jitted signature.
    """

    chi2_station: Optional[jax.Array] = None   # (N,)
    chi2_baseline: Optional[jax.Array] = None  # (N, N) upper-ish sparse
    chi2_chunk: Optional[jax.Array] = None     # (nchunk,) == solver cost
    nonfinite_count: Optional[jax.Array] = None    # () count in p
    station_amp: Optional[jax.Array] = None        # (N,) mean |J|_F/sqrt2
    station_amp_spread: Optional[jax.Array] = None   # (N,) std over lanes
    station_phase_spread: Optional[jax.Array] = None  # (N,) circular
    identity_departure: Optional[jax.Array] = None    # (N,) mean |J-I|
    nu: Optional[jax.Array] = None             # () final Student's-t nu
    weight_hist: Optional[jax.Array] = None    # (WEIGHT_HIST_BINS,)
    downweighted_frac: Optional[jax.Array] = None  # () of unflagged
    flagged_frac: Optional[jax.Array] = None       # () of all elements


def row_chi2(e: jax.Array) -> jax.Array:
    """Per-row chi^2 of a flat real residual block.

    ``e``: (..., F, 8, rows) real — exactly what
    :func:`sagecal_tpu.solvers.lm._residual_flat` produces (mask and
    sqrt-weights already applied, so this is the solver's own objective
    density).  Returns (..., rows)."""
    return jnp.sum(e * e, axis=(-3, -2))


def chi2_scatter(
    row: jax.Array,
    ant_p: jax.Array,
    ant_q: jax.Array,
    chunk_map: jax.Array,
    n_stations: int,
    n_chunks: int,
):
    """Scatter a per-row chi^2 density to stations / baselines / chunks.

    ``row``: (rows,); ``ant_p``/``ant_q``/``chunk_map``: (rows,) int.
    ``n_stations``/``n_chunks`` are static (from parameter shapes).
    Returns ``(chi2_station (N,), chi2_baseline (N, N),
    chi2_chunk (n_chunks,))``.  Padded/masked rows contribute exactly
    zero (their residual is zero), so scattering them anywhere is safe.
    """
    dt = row.dtype
    chi2_station = (
        jnp.zeros((n_stations,), dt)
        .at[ant_p].add(row)
        .at[ant_q].add(row)
    )
    chi2_baseline = jnp.zeros((n_stations, n_stations), dt).at[
        ant_p, ant_q
    ].add(row)
    chi2_chunk = jnp.zeros((n_chunks,), dt).at[chunk_map].add(row)
    return chi2_station, chi2_baseline, chi2_chunk


def weight_stats(sqrt_w: jax.Array, nu: jax.Array, mask8: jax.Array,
                 dof: float = 1.0):
    """Student's-t weight statistics for one solve.

    ``sqrt_w``: sqrt of the IRLS weights w = (nu+dof)/(nu+e^2),
    broadcastable against the (F, 8, rows) residual; ``mask8``:
    broadcastable 0/1 validity.  ``dof`` is the weight numerator offset
    — 1 for the LM family's per-element weights
    (solvers/robust.update_w_and_nu), 2 for the RTR family's
    max-over-elements weights (solvers/rtr._robust_weights_and_nu).
    Returns ``(weight_hist (WEIGHT_HIST_BINS,), downweighted_frac (),
    flagged_frac ())`` — the histogram is of the weights normalized by
    their maximum (nu+dof)/nu to [0, 1] and counts only unflagged
    elements."""
    dt = sqrt_w.dtype
    w = sqrt_w * sqrt_w
    wn = jnp.clip(w * (nu / (nu + dof)), 0.0, 1.0)
    m = jnp.broadcast_to(jnp.asarray(mask8, dt), wn.shape)
    idx = jnp.clip(
        (wn * WEIGHT_HIST_BINS).astype(jnp.int32), 0, WEIGHT_HIST_BINS - 1
    )
    hist = jnp.zeros((WEIGHT_HIST_BINS,), dt).at[idx.reshape(-1)].add(
        m.reshape(-1)
    )
    n_valid = jnp.maximum(jnp.sum(m), 1.0)
    downweighted = jnp.sum(m * (wn < DOWNWEIGHT_THRESH)) / n_valid
    flagged = 1.0 - jnp.sum(m) / m.size
    return hist, downweighted, flagged


def gain_health(p: jax.Array):
    """Gain-health metrics of a parameter block.

    ``p``: (..., 8N) real Jones parameters; all leading axes (clusters,
    hybrid chunk lanes) are treated as lanes and reduced, giving
    per-station summaries.  Returns ``(nonfinite_count (),
    station_amp (N,), station_amp_spread (N,),
    station_phase_spread (N,), identity_departure (N,))``.

    - amplitude: Frobenius norm / sqrt(2) of each 2x2 Jones (1.0 for
      identity); spread is the std across lanes.
    - phase spread: circular (1 - |mean resultant|) of the J00 phase
      across lanes — 0 for coherent lanes, -> 1 for uniformly scattered.
    - identity departure: mean ||J - I||_F / sqrt(2) across lanes; large
      values on a warm start mean the solution ran away from its
      initialization.

    Non-finite parameters are counted, then sanitized to zero before the
    summaries so a single NaN station cannot NaN-poison every reduction.
    """
    dt = p.dtype
    nonfinite = jnp.sum(~jnp.isfinite(p)).astype(dt)
    J = params_to_jones(jnp.where(jnp.isfinite(p), p, 0.0))
    lanes = J.reshape((-1,) + J.shape[-3:])  # (L, N, 2, 2)
    amp = jnp.sqrt(
        jnp.sum(jnp.abs(lanes) ** 2, axis=(-2, -1)) / 2.0
    )  # (L, N)
    station_amp = jnp.mean(amp, axis=0)
    station_amp_spread = jnp.std(amp, axis=0)
    phase = jnp.angle(lanes[..., 0, 0])  # (L, N)
    resultant = jnp.abs(
        jnp.mean(jax.lax.complex(jnp.cos(phase), jnp.sin(phase)), axis=0)
    )
    station_phase_spread = 1.0 - resultant
    eye = jnp.eye(2, dtype=lanes.dtype)
    dep = jnp.sqrt(
        jnp.sum(jnp.abs(lanes - eye) ** 2, axis=(-2, -1)) / 2.0
    )
    identity_departure = jnp.mean(dep, axis=0)
    return (nonfinite, station_amp.astype(dt),
            station_amp_spread.astype(dt),
            station_phase_spread.astype(dt), identity_departure.astype(dt))


def residual_quality(
    e: jax.Array,
    p: jax.Array,
    ant_p: jax.Array,
    ant_q: jax.Array,
    chunk_map: jax.Array,
    n_chunks: int,
    nu: Optional[jax.Array] = None,
    sqrt_w: Optional[jax.Array] = None,
    mask8: Optional[jax.Array] = None,
    weight_dof: float = 1.0,
) -> SolveQuality:
    """One-call quality bundle for the LM-family solvers.

    ``e``: the final (F, 8, rows) real residual (weights applied);
    ``p``: (..., 8N) final parameters.  Robust solvers additionally pass
    ``nu``/``sqrt_w``/``mask8`` (and ``weight_dof``, see
    :func:`weight_stats`) to fill the weight statistics."""
    n_stations = p.shape[-1] // 8
    row = row_chi2(e)
    chi2_st, chi2_bl, chi2_ch = chi2_scatter(
        row, ant_p, ant_q, chunk_map, n_stations, n_chunks
    )
    nonfinite, amp, amp_sp, ph_sp, dep = gain_health(p)
    q = SolveQuality(
        chi2_station=chi2_st, chi2_baseline=chi2_bl, chi2_chunk=chi2_ch,
        nonfinite_count=nonfinite, station_amp=amp,
        station_amp_spread=amp_sp, station_phase_spread=ph_sp,
        identity_departure=dep,
    )
    if nu is not None and sqrt_w is not None:
        hist, down, flag = weight_stats(
            sqrt_w, nu,
            mask8 if mask8 is not None else jnp.ones_like(sqrt_w),
            dof=weight_dof,
        )
        q = q._replace(nu=jnp.asarray(nu, e.dtype), weight_hist=hist,
                       downweighted_frac=down, flagged_frac=flag)
    return q
