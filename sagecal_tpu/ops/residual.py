"""Residual calculation, correction, and simulation semantics.

Redesign of ``/root/reference/src/lib/Radio/residual.c``: subtract the
solution-corrupted model from the data (``calculate_residuals_multifreq``
:940), optionally correct the residual by the regularized inverse of one
cluster's solutions (``mat_invert`` :163, the ``-E ccid`` option with
MMSE damping rho and a phase-only variant), and the predict/simulate
entry points (``predict_visibilities_multifreq[_withsol]`` :1257, :1621)
with the ``-a`` add/subtract semantics (``SIMUL_*``,
Dirac_radio.h:78-80).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from sagecal_tpu.core.types import VisData, corrupt_flat_2sided, params_to_jones
from sagecal_tpu.parallel.manifold import extract_phases
from sagecal_tpu.solvers.sage import ClusterData, predict_full_model

# simulation modes (roles of SIMUL_ONLY/ADD/SUB, Dirac_radio.h:78-80)
SIMUL_ONLY = 1  # write model in place of data        (-a 1)
SIMUL_ADD = 2  # add model to data                    (-a 2)
SIMUL_SUB = 3  # subtract model from data             (-a 3)


def mat_invert_reg(J: jax.Array, rho: float) -> jax.Array:
    """Regularized 2x2 inverse inv(J + rho I) with determinant guard
    (``mat_invert``, residual.c:163-196)."""
    a = J[..., 0, 0] + rho
    b = J[..., 0, 1]
    c = J[..., 1, 0]
    d = J[..., 1, 1] + rho
    det = a * d - b * c
    det = jnp.where(jnp.sqrt(jnp.abs(det)) <= rho, det + rho, det)
    inv_det = 1.0 / det
    row0 = jnp.stack([d, -b], axis=-1)
    row1 = jnp.stack([-c, a], axis=-1)
    return jnp.stack([row0, row1], axis=-2) * inv_det[..., None, None]


def correction_jones(
    p_ccid: jax.Array, rho: float = 1e-9, phase_only: bool = False
) -> jax.Array:
    """Per-station correction matrices inv(J_ccid + rho I):
    (nchunk, N, 2, 2).  ``phase_only`` reduces the solutions to their
    diagonal phases first (residual.c:955-1000 via extract_phases)."""
    jones = params_to_jones(p_ccid)  # (nchunk, N, 2, 2)
    if phase_only:
        jones = extract_phases(jones)
    return mat_invert_reg(jones, rho)


def apply_correction(vis, pinv, ant_p, ant_q, chunk_map):
    """x <- Ginv_p x Ginv_q^H per row (residual.c:880-930).

    vis: flat (F, 4, rows); pinv: (nchunk, N, 2, 2); indices (rows,)."""
    return corrupt_flat_2sided(pinv, pinv, vis, ant_p, ant_q, chunk_map)


def calculate_residuals(
    data: VisData,
    cdata: ClusterData,
    p: jax.Array,
    ccid_index: Optional[int] = None,
    rho: float = 1e-9,
    phase_only: bool = False,
) -> jax.Array:
    """Residual visibilities x - sum_k J C J^H, optionally corrected by
    cluster ``ccid_index``'s inverse solutions
    (``calculate_residuals_multifreq``, residual.c:940).

    ``ccid_index`` is the CLUSTER ARRAY INDEX of the correction cluster
    (the caller resolves the reference's ``-E ccid`` id -> index,
    residual.c:953-960).
    """
    res = data.vis - predict_full_model(p, cdata, data)
    if ccid_index is not None:
        pinv = correction_jones(p[ccid_index], rho, phase_only)
        res = apply_correction(
            res, pinv, data.ant_p, data.ant_q, cdata.chunk_map[ccid_index]
        )
    return res


def simulate_visibilities(
    data: VisData,
    cdata: ClusterData,
    p: Optional[jax.Array] = None,
    mode: int = SIMUL_ONLY,
    ignore_clusters: Sequence[int] = (),
    ccid_index: Optional[int] = None,
    rho: float = 1e-9,
    phase_only: bool = False,
) -> jax.Array:
    """Simulation modes of ``sagecal -a 1|2|3`` (fullbatch_mode.cpp:536-591).

    Without ``p``: the model is the uncorrupted sky
    (predict_visibilities_multifreq, residual.c:1257).  With ``p``: the
    model is corrupted by the given solutions
    (..._withsol, residual.c:1621), skipping clusters in
    ``ignore_clusters`` (the ``-z`` ignore file), and optionally
    correcting the OUTPUT by cluster ``ccid_index``.
    Returns the new visibility array per ``mode``.
    """
    M = cdata.coh.shape[0]
    keep = jnp.asarray(
        [1.0 if k not in set(ignore_clusters) else 0.0 for k in range(M)],
        jnp.real(cdata.coh).dtype,
    )
    if p is None:
        model = jnp.einsum("k,kfcr->fcr", keep.astype(cdata.coh.dtype), cdata.coh)
    else:
        masked = cdata._replace(coh=cdata.coh * keep[:, None, None, None])
        model = predict_full_model(p, masked, data)
    if ccid_index is not None and p is not None:
        pinv = correction_jones(p[ccid_index], rho, phase_only)
        model = apply_correction(
            model, pinv, data.ant_p, data.ant_q, cdata.chunk_map[ccid_index]
        )
    if mode == SIMUL_ADD:
        return data.vis + model
    if mode == SIMUL_SUB:
        return data.vis - model
    return model


def fused_objective(
    data: VisData,
    cdata: ClusterData,
    p: jax.Array,
    nu: Optional[jax.Array] = None,
    tile: Optional[int] = None,
    max_rows: Optional[int] = None,
) -> jax.Array:
    """Scalar calibration objective through the fused objective kernel
    (ops/rime_kernel.py): ``sum |(vis - model) * mask|^2`` when ``nu``
    is None (Gaussian), ``sum log1p(|...|^2 / nu)`` otherwise
    (Student's-t).  Production entry for eager callers (diagnostics,
    quality reports, solver harnesses): predict, residual, weighting and
    reduction happen in ONE pass over the coherency stack — the model
    and residual never round-trip HBM.  Differentiable w.r.t. ``p``
    ONLY: the fused kernel has no coherency cotangent, so requesting
    gradients w.r.t. ``cdata.coh`` (sky-model refinement) raises
    :class:`~sagecal_tpu.ops.rime_kernel.FusedSkyGradientError` rather
    than returning silent zeros — refinement routes through the XLA
    predict path (``sagecal_tpu.refine``).

    ``p``: (M, nchunk, 8N) real solver parameters.  f32 data only (the
    kernel computes in float32).
    """
    from sagecal_tpu.ops.rime_kernel import (
        FULL_CLUSTER_TILE, MAX_GRID_ROWS, fused_cost_packed_chunked,
        fused_cost_packed_hybrid_chunked, pack_gain_tables,
        pack_predict_inputs, pad_to,
    )

    if jnp.real(data.vis).dtype != jnp.float32:
        raise ValueError(
            "fused_objective requires float32 data (the Pallas kernel "
            "computes in f32); use the XLA predict path for f64"
        )
    M = cdata.coh.shape[0]
    nchunk = p.shape[1]
    mp = pad_to(M, 8)
    tile = FULL_CLUSTER_TILE if tile is None else tile
    max_rows = MAX_GRID_ROWS if max_rows is None else max_rows
    vis_ri, mask_p, coh_ri, antp, antq, cmap = pack_predict_inputs(
        data.vis, data.mask, cdata.coh, data.ant_p, data.ant_q,
        cdata.chunk_map if nchunk > 1 else None, tile, max_rows=max_rows,
    )
    jones = params_to_jones(p.astype(jnp.float32))  # (M, nchunk, N, 2, 2)
    if nchunk > 1:
        tre, tim = pack_gain_tables(jones, mp)
        return fused_cost_packed_hybrid_chunked(
            tre, tim, coh_ri, antp, antq, vis_ri, mask_p, cmap, nchunk,
            nu, tile, max_rows,
        )
    tre, tim = pack_gain_tables(jones[:, 0], mp)
    return fused_cost_packed_chunked(
        tre, tim, coh_ri, antp, antq, vis_ri, mask_p, nu, tile, max_rows,
    )


def residual_norm(res: jax.Array, mask: jax.Array) -> jax.Array:
    """||res||/n_real, the per-tile print (fullbatch_mode.cpp:636-643).
    Delegates to the solver's bookkeeping so the two stay identical.
    res: flat (F, 4, rows); mask: (F, rows)."""
    from sagecal_tpu.solvers.sage import _res_norm

    return _res_norm(res, mask, res.shape[-3] * res.shape[-1] * 8)
