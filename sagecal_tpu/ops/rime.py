"""RIME sky-model prediction: per-baseline coherencies for source clusters.

TPU-first redesign of the reference's prediction path
(``/root/reference/src/lib/Radio/predict.c:110-260`` CPU threads;
``predict_model.cu:1060`` one-CUDA-thread-per-baseline): instead of a
thread pool over baselines, the per-source phase/smear/shape factors form
a dense complex matrix ``(nchan, rows, S)`` that is contracted against the
per-source Stokes coherency matrix ``(nchan, S, 4)`` with a single batched
matmul — the FLOPs land on the MXU and the sum-over-sources is the
contraction axis.  Sources are processed in fixed-size chunks under
``lax.scan`` to bound the intermediate, so cluster size is a runtime
quantity (padded with zero-flux sources) while shapes stay static for XLA.

Math conventions (verified against the reference):
- phase term ``G = 2*pi*(u*l + v*m + w*(n-1))`` with u,v,w in seconds;
  the applied phase is ``exp(+i*G*freq)`` (predict.c:139-147, lmn built at
  readsky.c:343-346,628).
- bandwidth smearing: ``|sinc(G*fdelta/2)|`` (predict.c:150-158).
- extended sources evaluated at uv in wavelengths (``u*freq``), after the
  tangent-plane projection rotation (predict.c:33-90; angles precomputed at
  parse time, readsky.c:398-422): Gaussian ``exp(-2*pi^2*(ut^2+vt^2))``
  with sigma = fwhm-extent / (2*sqrt(2*ln2)); disk ``J1(2*pi*a*r_uv)``;
  ring ``J0(2*pi*a*r_uv)`` (matching the reference's literal use of J1 for
  the disk).
- Stokes to circular-free linear coherency: ``C = [[I+Q, U+iV],[U-iV, I-Q]]``
  (predict.c:200-212).
- spectral model ``exp(ln I0 + p1*ln(f/f0) + p2*ln^2 + p3*ln^3)`` with sign
  preserved for negative fluxes (readsky.c:353-377).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from sagecal_tpu.obs.perf import instrumented_jit
from sagecal_tpu.ops.special import bessel_j0, bessel_j1, sinc_abs

# source types (mirror STYPE_* roles; values are our own)
ST_POINT = 0
ST_GAUSSIAN = 1
ST_DISK = 2
ST_RING = 3
ST_SHAPELET = 4


@struct.dataclass
class ShapeletTable:
    """Padded struct-of-arrays table of shapelet models for one cluster
    (or sky).  Sources reference rows by ``SourceBatch.shapelet_idx``.
    Models with fewer than ``n0max`` orders zero-pad ``modes`` — exact,
    since unused basis coefficients are zero.

    modes: (K, n0max*n0max); beta/eX/eY/eP: (K,).
    """

    modes: jax.Array
    beta: jax.Array
    eX: jax.Array
    eY: jax.Array
    eP: jax.Array
    n0max: int = struct.field(pytree_node=False, default=1)

    @staticmethod
    def empty(dtype=jnp.float32) -> "ShapeletTable":
        return ShapeletTable(
            modes=jnp.zeros((1, 1), dtype),
            beta=jnp.ones((1,), dtype),
            eX=jnp.ones((1,), dtype),
            eY=jnp.ones((1,), dtype),
            eP=jnp.zeros((1,), dtype),
            n0max=1,
        )


@struct.dataclass
class SourceBatch:
    """A padded, struct-of-arrays batch of sources (one cluster, or any set).

    All fields shape (S,).  Padding sources have zero flux, making them
    exact no-ops in the contraction.  Shapelet sources carry an index into
    a separate mode table (see :mod:`sagecal_tpu.ops.shapelets`); their
    inline shape factor here is 1 and the shapelet basis contribution is
    added by the shapelet path.
    """

    ll: jax.Array
    mm: jax.Array
    nn: jax.Array  # n - 1
    sI0: jax.Array
    sQ0: jax.Array
    sU0: jax.Array
    sV0: jax.Array
    f0: jax.Array
    spec_idx: jax.Array
    spec_idx1: jax.Array
    spec_idx2: jax.Array
    stype: jax.Array  # int32
    ex_a: jax.Array  # gaussian sigma_X / disk,ring radius
    ex_b: jax.Array  # gaussian sigma_Y
    ex_cp: jax.Array  # cos(position angle)
    ex_sp: jax.Array  # sin(position angle)
    cxi: jax.Array
    sxi: jax.Array  # sin(-xi)
    cphi: jax.Array
    sphi: jax.Array  # sin(-phi)
    shapelet_idx: jax.Array  # int32, -1 if not shapelet

    @property
    def nsources(self) -> int:
        return self.ll.shape[0]


def point_source_batch(ll, mm, flux, f0=150e6, dtype=jnp.float32) -> SourceBatch:
    """Convenience constructor: unpolarized point sources (testing/simulation)."""
    ll = jnp.asarray(ll, dtype)
    S = ll.shape[0]
    z = jnp.zeros((S,), dtype)
    nn = jnp.sqrt(jnp.maximum(1.0 - ll**2 - jnp.asarray(mm, dtype) ** 2, 0.0)) - 1.0
    return SourceBatch(
        ll=ll,
        mm=jnp.asarray(mm, dtype),
        nn=nn.astype(dtype),
        sI0=jnp.asarray(flux, dtype),
        sQ0=z,
        sU0=z,
        sV0=z,
        f0=jnp.full((S,), f0, dtype),
        spec_idx=z,
        spec_idx1=z,
        spec_idx2=z,
        stype=jnp.zeros((S,), jnp.int32),
        ex_a=z,
        ex_b=z,
        ex_cp=jnp.ones((S,), dtype),
        ex_sp=z,
        cxi=jnp.ones((S,), dtype),
        sxi=z,
        cphi=jnp.ones((S,), dtype),
        sphi=z,
        shapelet_idx=jnp.full((S,), -1, jnp.int32),
    )


def pad_source_batch(src: SourceBatch, target: int) -> SourceBatch:
    """Pad with zero-flux point sources up to ``target`` sources."""
    S = src.nsources
    if S == target:
        return src
    assert S < target
    pad = target - S

    def _pad(x):
        cfg = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, cfg)

    out = jax.tree_util.tree_map(_pad, src)
    # keep f0 strictly positive in padding to avoid log(0); padding sources
    # must keep the "-1 = not a shapelet" invariant, not jnp.pad's 0
    pad_mask = jnp.arange(target) >= S
    return out.replace(
        f0=jnp.where(out.f0 <= 0, 1.0, out.f0),
        shapelet_idx=jnp.where(pad_mask, -1, out.shapelet_idx),
    )


def _spectral_flux(s0, f0, si, si1, si2, freqs):
    """Per-channel flux with sign preservation (readsky.c:353-377).

    s0,(S,) flux at f0; freqs (F,) -> (S, F).  The reference gates ALL
    spectral scaling on spec_idx != 0 (readsky.c:358): a source with
    si == 0 keeps its raw catalog flux even if si1/si2 are nonzero.
    Zero-flux handling uses the double-where pattern so gradients w.r.t.
    a zero flux are 0, not NaN.
    """
    lf = jnp.log(freqs[None, :] / f0[:, None])  # (S, F)
    zero = s0 == 0.0
    safe_abs = jnp.where(zero, 1.0, jnp.abs(s0))
    mag = jnp.exp(
        jnp.log(safe_abs)[:, None]
        + si[:, None] * lf
        + si1[:, None] * lf**2
        + si2[:, None] * lf**3
    )
    scaled = jnp.where(zero[:, None], 0.0, jnp.sign(s0)[:, None] * mag)
    return jnp.where(si[:, None] == 0.0, s0[:, None], scaled)


def _shape_factor(src: SourceBatch, u, v, w, freqs):
    """Extended-source UV attenuation, per channel: (F, rows, S) real.

    u,v,w (rows,) in seconds; freqs (F,).
    """
    # tangent-plane projection (predict.c:38-44), still in seconds
    up = (
        u[:, None] * src.cxi[None, :]
        - v[:, None] * src.cphi[None, :] * src.sxi[None, :]
        + w[:, None] * src.sphi[None, :] * src.sxi[None, :]
    )  # (rows, S)
    vp = (
        u[:, None] * src.sxi[None, :]
        + v[:, None] * src.cphi[None, :] * src.cxi[None, :]
        - w[:, None] * src.sphi[None, :] * src.cxi[None, :]
    )
    # scale to wavelengths per channel: (F, rows, S)
    upf = freqs[:, None, None] * up[None]
    vpf = freqs[:, None, None] * vp[None]
    # gaussian (predict.c:46-58)
    ut = src.ex_a[None, None, :] * (src.ex_cp[None, None, :] * upf - src.ex_sp[None, None, :] * vpf)
    vt = src.ex_b[None, None, :] * (src.ex_sp[None, None, :] * upf + src.ex_cp[None, None, :] * vpf)
    gauss = jnp.exp(-2.0 * jnp.pi**2 * (ut**2 + vt**2))
    # disk/ring (predict.c:61-90)
    ruv = 2.0 * jnp.pi * src.ex_a[None, None, :] * jnp.sqrt(upf**2 + vpf**2)
    disk = bessel_j1(ruv)
    ring = bessel_j0(ruv)
    st = src.stype[None, None, :]
    fac = jnp.where(st == ST_GAUSSIAN, gauss, 1.0)
    fac = jnp.where(st == ST_DISK, disk, fac)
    fac = jnp.where(st == ST_RING, ring, fac)
    return fac


def _shapelet_factor(c: SourceBatch, tab: ShapeletTable, u, v, w, freqs):
    """Complex shapelet uv factor (F, rows, chunk) for the chunk's
    ST_SHAPELET members (``shapelet_contrib``, shapelet.c:141-188):
    tangent-plane projection with negated signs, (1/eX, 1/eY, eP) linear
    transform, mode sum, scaled by 2*pi*a*b."""
    from sagecal_tpu.ops.shapelets import uv_mode_vectors

    idx = jnp.clip(c.shapelet_idx, 0, tab.modes.shape[0] - 1)
    beta = tab.beta[idx]
    a = 1.0 / tab.eX[idx]
    b = 1.0 / tab.eY[idx]
    eP = tab.eP[idx]
    modes = tab.modes[idx]  # (chunk, n0max^2)
    up = (
        -u[:, None] * c.cxi[None, :]
        + v[:, None] * c.cphi[None, :] * c.sxi[None, :]
        - w[:, None] * c.sphi[None, :] * c.sxi[None, :]
    )  # (rows, chunk), seconds
    vp = (
        -u[:, None] * c.sxi[None, :]
        - v[:, None] * c.cphi[None, :] * c.cxi[None, :]
        + w[:, None] * c.sphi[None, :] * c.cxi[None, :]
    )
    upf = freqs[:, None, None] * up[None]  # wavelengths (F, rows, chunk)
    vpf = freqs[:, None, None] * vp[None]
    cp, sp = jnp.cos(eP), jnp.sin(eP)
    ut = a * (cp * upf - sp * vpf)
    vt = b * (sp * upf + cp * vpf)
    Av = uv_mode_vectors(-ut, vt, beta, tab.n0max)  # (F, rows, chunk, n0^2)
    sfac = jnp.einsum("frsm,sm->frs", Av, modes.astype(Av.dtype))
    return (2.0 * jnp.pi) * (a * b)[None, None, :] * sfac


def resolve_source_flags(
    src: SourceBatch, shapelets: Optional[ShapeletTable] = None,
) -> tuple:
    """Host-side resolution of the static predict flags
    ``(has_extended, has_shapelet)`` from a CONCRETE source batch.

    Callers that dispatch :func:`predict_coherencies` from inside a
    trace (vmap / jit / grad) must resolve these once, host-side, on
    the concrete template batch and pass them through explicitly —
    the in-function probe cannot see a tracer's values and its
    conservative fallback silently flips the static arguments
    (= a recompile and the slow extended-source path).
    """
    stype_np = np.asarray(src.stype)
    has_extended = bool(np.any(stype_np != ST_POINT))
    has_shapelet = bool(np.any(stype_np == ST_SHAPELET))
    if has_shapelet and shapelets is None:
        raise ValueError(
            "SourceBatch contains ST_SHAPELET sources but no ShapeletTable "
            "was supplied — they would silently predict as point sources"
        )
    return has_extended, has_shapelet


def predict_coherencies(
    u: jax.Array,
    v: jax.Array,
    w: jax.Array,
    freqs: jax.Array,
    src: SourceBatch,
    fdelta: float = 0.0,
    source_chunk: int = 32,
    shapelets: Optional[ShapeletTable] = None,
    tdelta: float = 0.0,
    dec0: float = 0.0,
    *,
    has_extended: Optional[bool] = None,
    has_shapelet: Optional[bool] = None,
) -> jax.Array:
    """Sum of source coherencies on every baseline row: (F, 4, rows) complex
    (canonical flat layout, components [XX, XY, YX, YY] on axis -2).

    The jitted, differentiable equivalent of ``precalculate_coherencies``
    (predict.c:503) for one cluster — and of ``predict_visibilities``'s
    per-cluster inner loop.  ``fdelta`` is the *per-channel* bandwidth for
    smearing (the reference passes total-bandwidth/Nchan when predicting
    channel-averaged data).

    ``shapelets``: mode table for ST_SHAPELET members.  NOTE: shapelet
    uv factors are evaluated at each channel's frequency, not the
    reference's freq0-only approximation (predict.c:200).

    ``tdelta``/``dec0``: integration time (s) and field declination for
    time smearing (``time_smear``, predict.c:93-107); 0 disables.

    ``has_extended``/``has_shapelet``: the STATIC source-type flags,
    resolved once by the caller (:func:`resolve_source_flags`).  They
    select the compiled program — flipping either is a recompile — so
    any call site reachable from inside a trace must pass them
    explicitly; the legacy in-function stype probe (deprecated) only
    runs when they are left ``None`` and falls back to the
    conservative extended path when ``stype`` is a tracer.
    """
    if has_extended is None or has_shapelet is None:
        # DEPRECATED probe: behavior depends on trace context (a tracer
        # stype silently selects the conservative flags = a different
        # compiled program than the same call made eagerly).  Kept only
        # for callers that always run host-side on concrete batches.
        try:
            stype_np = np.asarray(src.stype)
            probed_ext = bool(np.any(stype_np != ST_POINT))
            probed_sh = bool(np.any(stype_np == ST_SHAPELET))
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            import warnings

            warnings.warn(
                "predict_coherencies called under a trace without explicit "
                "has_extended/has_shapelet: falling back to the conservative "
                "extended-source program (a silent recompile vs the eager "
                "call).  Resolve the flags host-side with "
                "resolve_source_flags and pass them through.",
                DeprecationWarning, stacklevel=2)
            probed_ext = True
            probed_sh = shapelets is not None
        if has_extended is None:
            has_extended = probed_ext
        if has_shapelet is None:
            has_shapelet = probed_sh
    if shapelets is None:
        if has_shapelet:
            raise ValueError(
                "SourceBatch contains ST_SHAPELET sources but no ShapeletTable "
                "was supplied — they would silently predict as point sources"
            )
        has_shapelet = False
        shapelets = ShapeletTable.empty(u.dtype)
    return _predict_coherencies(
        u, v, w, freqs, src, shapelets,
        float(fdelta), int(source_chunk), has_extended, has_shapelet,
        float(tdelta), float(dec0),
    )


def time_smear_factor(ll, mm, dec0, tdelta, u, v, w, freqs):
    """EW-array time-smearing attenuation (predict.c:93-107):
    1.0645*erf(0.8326*prod)/prod, prod = omega_E * tdelta * |b|_lambda *
    sqrt(l^2 + (sin(dec0) m)^2).  Shapes: u,v,w (rows,), ll,mm (S,),
    freqs (F,) -> (F, rows, S)."""
    from jax.scipy.special import erf

    bl = jnp.sqrt(u * u + v * v + w * w)  # seconds
    ds = jnp.sin(dec0) * mm
    r1 = jnp.sqrt(ll * ll + ds * ds)  # (S,)
    prod = (
        7.2921150e-5 * tdelta
        * freqs[:, None, None] * bl[None, :, None] * r1[None, None, :]
    )
    safe = jnp.maximum(prod, 1e-30)
    return jnp.where(prod > 1e-12, 1.0645 * erf(0.8326 * safe) / safe, 1.0)


@functools.partial(
    instrumented_jit, name="predict_coherencies",
    static_argnums=(6, 7, 8, 9, 10, 11))
def _predict_coherencies(
    u, v, w, freqs, src, shapelets, fdelta, source_chunk, has_extended,
    has_shapelet, tdelta, dec0,
):
    rows = u.shape[0]
    F = freqs.shape[0]
    S = src.nsources
    chunk = min(source_chunk, S) if S > 0 else 1
    nchunks = -(-S // chunk)
    padded = pad_source_batch(src, nchunks * chunk)
    # reshape every per-source leaf to (nchunks, chunk)
    chunked = jax.tree_util.tree_map(
        lambda x: x.reshape((nchunks, chunk) + x.shape[1:]), padded
    )

    cdtype = jnp.complex64 if u.dtype == jnp.float32 else jnp.complex128

    def one_chunk(acc, c: SourceBatch):
        # phase term G (rows, chunk), seconds
        G = 2.0 * jnp.pi * (
            u[:, None] * c.ll[None, :]
            + v[:, None] * c.mm[None, :]
            + w[:, None] * c.nn[None, :]
        )
        # per-channel complex phase (F, rows, chunk)
        ang = freqs[:, None, None] * G[None]
        ph = jax.lax.complex(jnp.cos(ang), jnp.sin(ang))
        smear = sinc_abs(G * (0.5 * fdelta))[None]  # (1, rows, chunk)
        if tdelta > 0.0:
            smear = smear * time_smear_factor(
                c.ll, c.mm, dec0, tdelta, u, v, w, freqs
            )
        if has_extended:
            amp = (smear * _shape_factor(c, u, v, w, freqs)).astype(ph.real.dtype)
        else:
            amp = jnp.broadcast_to(smear, ph.shape).astype(ph.real.dtype)
        phs = ph * amp  # (F, rows, chunk)
        if has_shapelet:
            fac_s = _shapelet_factor(c, shapelets, u, v, w, freqs)
            sel = (c.stype == ST_SHAPELET)[None, None, :]
            phs = jnp.where(sel, ph * smear * fac_s.astype(phs.dtype), phs)
        # Stokes coherency (chunk, F, 4) complex
        I = _spectral_flux(c.sI0, c.f0, c.spec_idx, c.spec_idx1, c.spec_idx2, freqs)
        Q = _spectral_flux(c.sQ0, c.f0, c.spec_idx, c.spec_idx1, c.spec_idx2, freqs)
        U = _spectral_flux(c.sU0, c.f0, c.spec_idx, c.spec_idx1, c.spec_idx2, freqs)
        V = _spectral_flux(c.sV0, c.f0, c.spec_idx, c.spec_idx1, c.spec_idx2, freqs)
        C = jnp.stack(
            [I + Q, U + 1j * V, U - 1j * V, I - Q], axis=-1
        ).astype(cdtype)  # (chunk, F, 4)
        # contraction over sources: batched matmul (F, chunk, 4)^T @ (F, rows, chunk)
        # -> canonical (F, 4, rows) flat layout
        contrib = jnp.einsum("frs,sfc->fcr", phs, C)
        return acc + contrib, None

    init = jnp.zeros((F, 4, rows), cdtype)
    acc, _ = jax.lax.scan(one_chunk, init, chunked)
    return acc


def predict_model(
    u, v, w, freqs, clusters, fdelta=0.0, jones=None, ant_p=None, ant_q=None,
    source_chunk: int = 32, shapelet_tables=None,
):
    """Full-sky model visibilities: sum over a list of clusters, each
    optionally corrupted by its own Jones solution.

    ``clusters``: list of SourceBatch.  ``jones``: optional (nclus, N, 2, 2).
    ``shapelet_tables``: optional per-cluster ShapeletTable (or None).
    Returns canonical flat (F, 4, rows).  Equivalent of
    ``predict_visibilities_multifreq[_withsol]`` (residual.c:1257,1621).
    """
    from sagecal_tpu.core.types import corrupt_flat

    if not clusters:
        raise ValueError("predict_model: empty cluster list")
    total = None
    for ci, src in enumerate(clusters):
        tab = shapelet_tables[ci] if shapelet_tables is not None else None
        coh = predict_coherencies(
            u, v, w, freqs, src, fdelta, source_chunk, shapelets=tab
        )
        if jones is not None:
            coh = corrupt_flat(jones[ci], coh, ant_p, ant_q)
        total = coh if total is None else total + coh
    return total


def uv_cut_mask(u, v, freq0, uvmin=0.0, uvmax=1e20):
    """1.0 where baseline length (wavelengths) is inside [uvmin, uvmax] —
    the reference's flag=2 exclusion (predict.c precalculate, uvdist check)."""
    uvdist = jnp.sqrt(u**2 + v**2) * freq0
    return ((uvdist >= uvmin) & (uvdist <= uvmax)).astype(u.dtype)
