"""Fused RIME predict Pallas kernel — the hot op of joint calibration.

Computes ``V(f, r) = sum_m Jp^m(r) C^m(f, r) Jq^m(r)^H`` (the full-model
predict of ``minimize_viz_full_pth``, ``/root/reference/src/lib/Dirac/
lmfit.c:692``; CUDA analog ``predict_model.cu``) in ONE pass over the
coherency stack.

Why a kernel: the XLA formulation in :func:`sagecal_tpu.solvers.sage.
predict_full_model` (one-hot gain matmuls + sixteen multiply-reduce
contractions) materializes ~15 buffer-scale intermediates in HBM —
measured 95 ms per forward at the north-star shape (62 stn / 100
clusters / 60 ts x 2 ch), an effective 8 GB/s against the 726 MB
coherency stack vs the chip's 819 GB/s.  The fused kernel streams each
coherency block through VMEM exactly once.

Grid design: ONE grid dimension over row tiles.  The full cluster axis
rides inside each block — at the north-star shape a (104, 2, 8, 512)
f32 coherency block is 3.4 MB, comfortably inside VMEM — so the forward
writes each output block exactly once (no cross-step accumulation) and
the kernel body is straight-line VPU/MXU code:

1. build the station one-hot selectors from the tile's antenna indices,
2. expand per-row gains with one MXU matmul per 2x2 component
   ``(Mp, NPAD) @ (NPAD, T)`` (component-major tables: no sublane
   reshapes anywhere in the nc=1 kernel bodies),
3. evaluate the 2x2 RIME products ``Jp (C Jq^H)`` as component
   arithmetic on ``(Mp, T)`` vregs, reduce over clusters, store.

The backward kernel has the same structure and accumulates gain-table
cotangents across row tiles (``dtab += dJ @ onehot^T`` — the reference's
``mderiv.cu`` role); both are wired into :func:`fused_predict_packed`
with ``jax.custom_vjp``.  Gradients flow to the gain tables only: the
solver never differentiates w.r.t. coherencies (per-tile constants, like
the reference's precalculated ``coh`` array).

On top of the predict, :func:`fused_cost_packed` fuses the ENTIRE
objective — predict, masked residual, Student's-t (or Gaussian)
weighting, and the scalar reduction — into the same single pass, so a
``value_and_grad`` never streams a model-sized buffer to or from HBM
(see the "fused objective" section below).

Everything crosses the kernel boundary as REAL arrays (re/im packed on
a leading axis): the axon TPU runtime cannot transfer complex arrays,
and packed reals keep every buffer's minor-most axis long (rows), so
the TPU (8, 128) tiling pads nothing (core/types.py layout rationale).
Gain tables and outputs are f32; ``coh_ri`` may be f32 or bfloat16 —
bf16 planes are upcast to f32 at the VMEM load (``_load_coh_planes``),
halving the dominant HBM stream at ~3 significant digits of coherency
precision (a throughput knob, not the production default).

Layout contracts:
  tab_re/tab_im: (4, Mp*nc, NPAD) component-major gain tables — plane k
    holds 2x2 component k (row-major [J00, J01, J10, J11]) for every
    (cluster, chunk) row ``m*nc + c``; Mp = clusters padded to a
    multiple of 8 (sublane alignment), NPAD = stations padded to 128.
  coh_ri: (Mp, F, 8, rowsp) packed coherencies, component axis
    [re XX, re XY, re YX, re YY, im XX, im XY, im YX, im YY].
  ant_p/ant_q: (1, rowsp) int32 station index per row.
  output model_ri: (F, 8, rowsp), same component packing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NPAD = 128  # station axis padded to one MXU/VPU lane tile
DEF_TILE = 512  # rows per grid step


def _use_interpret() -> bool:
    return jax.default_backend() not in ("tpu",)


def _sel_dot(t, oh):
    """Selection matmul ``t @ oh`` (``oh`` 0/1 one-hot) in exact f32.

    The TPU MXU multiplies f32 as bf16 passes by default, rounding
    every selected value to ~3 digits (measured 3.7e-3 rel error at
    the kernel output on the v5e) — enough to diverge warm-started
    calibration tiles.  Precision.HIGHEST restores exact f32 (1e-7).
    A 2-pass hi/lo split (exact selections, 4.8e-6 rel) was tried and
    MEASURED SLOWER whole-bench (28.8 vs 32.7 it/s): the VPU
    decomposition costs more than the four MXU passes it saves, on
    either operand size.  Mosaic does not support Precision.HIGH."""
    return jnp.dot(t, oh, preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)


def _expand_gains(tabre_ref, tabim_ref, oh, mp, T, nc=1, cmap=None):
    """(4, Mp*nc, NPAD) component-major tables x (NPAD, T) one-hot ->
    4 re + 4 im (Mp, T) per-row gain components, one MXU selection per
    component (see _sel_dot) — NO sublane reshapes in the nc=1 path
    (kept Mosaic-friendly on purpose: minor-dim relayouts are a prime
    suspect in the remote-compile stall documented in the verify
    skill).

    ``nc > 1`` is the reference's hybrid time-chunk mode (one solution
    per chunk of the tile, lmfit.c:86-87): the tables carry one row
    block per (cluster, chunk) and ``cmap`` (Mp, T) selects each row's
    chunk — a static unrolled select over the (small) chunk count."""
    re, im = [], []
    if nc == 1:
        for k in range(4):
            re.append(_sel_dot(tabre_ref[k], oh))
            im.append(_sel_dot(tabim_ref[k], oh))
        return re, im
    sels = [(cmap == c).astype(jnp.float32) for c in range(nc)]  # (Mp, T)
    for k in range(4):
        g_re = _sel_dot(tabre_ref[k], oh)
        g_im = _sel_dot(tabim_ref[k], oh)
        gr = g_re.reshape(mp, nc, T)  # leading-dim split only
        gi = g_im.reshape(mp, nc, T)
        acc_r = acc_i = 0.0
        for c in range(nc):
            acc_r = acc_r + sels[c] * gr[:, c, :]
            acc_i = acc_i + sels[c] * gi[:, c, :]
        re.append(acc_r)
        im.append(acc_i)
    return re, im


def _rowsum_dot(a, b):
    """(Mp', T) x (NPAD, T) -> (Mp', NPAD), contracting T — dot_general
    with the contraction on the trailing dims so no transpose op is
    ever materialized.  Precision.HIGHEST, NOT the _sel_dot hi/lo
    trick: here the split would run on the big (Mp, T) cotangent
    operand, and the VPU decomposition costs more than the four MXU
    passes it saves (measured 27.4 vs 32.7 it/s whole-bench on the
    v5e).  HIGHEST keeps the accumulated gain-table cotangents exact
    f32."""
    return jax.lax.dot_general(
        a, b, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


def _chunk_route(dj, mp, T, nc, sels):
    """Route one component's per-row cotangent (Mp, T) to its
    per-(cluster, chunk) rows (Mp*nc, T) for the hybrid mode.
    ``sels``: pre-computed chunk-selector masks (hoisted by the caller
    so the 16 uses per backward body don't re-trace nc compares)."""
    if nc == 1:
        return dj
    parts = [(sels[c] * dj)[:, None, :] for c in range(nc)]
    return jnp.concatenate(parts, axis=1).reshape(mp * nc, T)


def _cjqh(c_re, c_im, q_re, q_im):
    """A = C Jq^H on (Mp, T) components: A_aj = sum_b C_ab conj(Jq_jb);
    2x2 index ab = 2a+b.  Shared by the forward products and by the
    backward pass (which caches A for the cotangent contractions)."""
    a_re, a_im = {}, {}
    for a in range(2):
        for j in range(2):
            re = im = 0.0
            for b in range(2):
                cr, ci = c_re[2 * a + b], c_im[2 * a + b]
                qr, qi = q_re[2 * j + b], q_im[2 * j + b]
                re = re + cr * qr + ci * qi
                im = im + ci * qr - cr * qi
            a_re[a, j], a_im[a, j] = re, im
    return a_re, a_im


def _jp_a(p_re, p_im, a_re, a_im):
    """V = Jp A: V_ij = sum_a Jp_ia A_aj.  Returns the 8 packed planes
    [reXX..reYY, imXX..imYY] BEFORE the cluster reduction."""
    v_re, v_im = [None] * 4, [None] * 4
    for i in range(2):
        for j in range(2):
            re = im = 0.0
            for a in range(2):
                pr, pi = p_re[2 * i + a], p_im[2 * i + a]
                ar, ai = a_re[a, j], a_im[a, j]
                re = re + pr * ar - pi * ai
                im = im + pr * ai + pi * ar
            v_re[2 * i + j], v_im[2 * i + j] = re, im
    return v_re, v_im


def _rime_products(c_re, c_im, p_re, p_im, q_re, q_im):
    """V = Jp (C Jq^H) expanded on (Mp, T) components."""
    a_re, a_im = _cjqh(c_re, c_im, q_re, q_im)
    return _jp_a(p_re, p_im, a_re, a_im)


def _onehots(antp_ref, antq_ref, T):
    n_iota = jax.lax.broadcasted_iota(jnp.int32, (NPAD, T), 0)
    ohp = (n_iota == antp_ref[:]).astype(jnp.float32)
    ohq = (n_iota == antq_ref[:]).astype(jnp.float32)
    return ohp, ohq


def _load_coh_planes(coh_ref, f):
    """Load one frequency's 4 re + 4 im coherency planes, upcasting to
    f32 at the VMEM load so a bfloat16 coherency stack (halved HBM
    stream — the bandwidth-bound knob) computes in full f32."""
    c_re = [coh_ref[:, f, k, :].astype(jnp.float32) for k in range(4)]
    c_im = [coh_ref[:, f, 4 + k, :].astype(jnp.float32) for k in range(4)]
    return c_re, c_im


def _fwd_store(coh_ref, out_ref, p_re, p_im, q_re, q_im, F):
    # per-plane (1, T) slice stores — no stack/concatenate relayouts
    for f in range(F):
        c_re, c_im = _load_coh_planes(coh_ref, f)
        v_re, v_im = _rime_products(c_re, c_im, p_re, p_im, q_re, q_im)
        for k in range(4):
            out_ref[f, k:k + 1, :] = jnp.sum(v_re[k], axis=0, keepdims=True)
            out_ref[f, 4 + k:5 + k, :] = jnp.sum(v_im[k], axis=0,
                                                 keepdims=True)


def _fwd_kernel(antp_ref, antq_ref, tabre_ref, tabim_ref, coh_ref, out_ref,
                *, F, MP, T):
    ohp, ohq = _onehots(antp_ref, antq_ref, T)
    p_re, p_im = _expand_gains(tabre_ref, tabim_ref, ohp, MP, T)
    q_re, q_im = _expand_gains(tabre_ref, tabim_ref, ohq, MP, T)
    _fwd_store(coh_ref, out_ref, p_re, p_im, q_re, q_im, F)


def _fwd_kernel_hybrid(antp_ref, antq_ref, cmap_ref, tabre_ref, tabim_ref,
                       coh_ref, out_ref, *, F, MP, T, NC):
    ohp, ohq = _onehots(antp_ref, antq_ref, T)
    cmap = cmap_ref[:]
    p_re, p_im = _expand_gains(tabre_ref, tabim_ref, ohp, MP, T, NC, cmap)
    q_re, q_im = _expand_gains(tabre_ref, tabim_ref, ohq, MP, T, NC, cmap)
    _fwd_store(coh_ref, out_ref, p_re, p_im, q_re, q_im, F)


def _shape_args(tab_re, coh_ri, tile, nc):
    four, mrows, npad = tab_re.shape
    Mp, F, _, rowsp = coh_ri.shape
    assert four == 4 and npad == NPAD and mrows == Mp * nc and Mp % 8 == 0
    assert rowsp % tile == 0, (rowsp, tile)
    return Mp, F, rowsp, rowsp // tile


def _row_spec(tile):
    return pl.BlockSpec((1, tile), lambda r: (0, r), memory_space=pltpu.VMEM)


def _tab_spec(nrows):
    # component-major (4, Mp*nc, NPAD)
    return pl.BlockSpec((4, nrows, NPAD), lambda r: (0, 0, 0),
                        memory_space=pltpu.VMEM)


def _coh_spec(Mp, F, tile):
    return pl.BlockSpec((Mp, F, 8, tile), lambda r: (0, 0, 0, r),
                        memory_space=pltpu.VMEM)


def _cmap_spec(Mp, tile):
    return pl.BlockSpec((Mp, tile), lambda r: (0, r),
                        memory_space=pltpu.VMEM)


def _fused_predict_fwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q, *, tile,
                            nc=1, cmap=None):
    Mp, F, rowsp, R = _shape_args(tab_re, coh_ri, tile, nc)
    if nc == 1:
        kernel = functools.partial(_fwd_kernel, F=F, MP=Mp, T=tile)
        specs = [_row_spec(tile), _row_spec(tile),
                 _tab_spec(Mp), _tab_spec(Mp), _coh_spec(Mp, F, tile)]
        args = (ant_p, ant_q, tab_re, tab_im, coh_ri)
    else:
        kernel = functools.partial(_fwd_kernel_hybrid, F=F, MP=Mp, T=tile,
                                   NC=nc)
        specs = [_row_spec(tile), _row_spec(tile), _cmap_spec(Mp, tile),
                 _tab_spec(Mp * nc), _tab_spec(Mp * nc),
                 _coh_spec(Mp, F, tile)]
        args = (ant_p, ant_q, cmap, tab_re, tab_im, coh_ri)
    return pl.pallas_call(
        kernel,
        grid=(R,),
        in_specs=specs,
        out_specs=pl.BlockSpec((F, 8, tile), lambda r: (0, 0, r),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((F, 8, rowsp), jnp.float32),
        interpret=_use_interpret(),
    )(*args)


# ---------------------------------------------------------------- backward


def _g_from_ref(g_ref):
    """Predict-kernel cotangent source: the upstream model cotangent is
    an HBM buffer streamed in per grid step; read frequency f's 4 re +
    4 im (1, T) planes."""
    def g_of(f, c_re, c_im, a_re, a_im):
        del c_re, c_im, a_re, a_im
        return ([g_ref[f, k:k + 1, :] for k in range(4)],
                [g_ref[f, 4 + k:5 + k, :] for k in range(4)])
    return g_of


def _bwd_accumulate(coh_ref, g_of, p_re, p_im, q_re, q_im, F, MP, T):
    """Per-row gain cotangents dJp/dJq (4 x (MP, T) re/im each),
    accumulated over freq.  ``g_of(f, c_re, c_im, a_re, a_im)`` supplies
    frequency f's model cotangent as 4 re + 4 im (1, T) planes — either
    read from an HBM cotangent buffer (predict kernel, :func:`_g_from_
    ref`) or formed in-register from the residual (objective kernel,
    which never materializes the model or residual in HBM)."""
    djp_re = [jnp.zeros((MP, T), jnp.float32) for _ in range(4)]
    djp_im = [jnp.zeros((MP, T), jnp.float32) for _ in range(4)]
    djq_re = [jnp.zeros((MP, T), jnp.float32) for _ in range(4)]
    djq_im = [jnp.zeros((MP, T), jnp.float32) for _ in range(4)]

    for f in range(F):
        c_re, c_im = _load_coh_planes(coh_ref, f)
        a_re, a_im = _cjqh(c_re, c_im, q_re, q_im)  # reused by g_of
        g_re, g_im = g_of(f, c_re, c_im, a_re, a_im)

        # dJp_ia += sum_j g_ij * conj(A_aj)
        for i in range(2):
            for a in range(2):
                re = im = 0.0
                for j in range(2):
                    gr, gi = g_re[2 * i + j], g_im[2 * i + j]
                    ar, ai = a_re[a, j], a_im[a, j]
                    re = re + gr * ar + gi * ai
                    im = im + gi * ar - gr * ai
                djp_re[2 * i + a] = djp_re[2 * i + a] + re
                djp_im[2 * i + a] = djp_im[2 * i + a] + im

        # dA_aj = sum_i conj(Jp_ia) g_ij ; dJq_jb += sum_a conj(dA_aj) C_ab
        da_re, da_im = {}, {}
        for a in range(2):
            for j in range(2):
                re = im = 0.0
                for i in range(2):
                    pr, pi = p_re[2 * i + a], p_im[2 * i + a]
                    gr, gi = g_re[2 * i + j], g_im[2 * i + j]
                    re = re + pr * gr + pi * gi
                    im = im + pr * gi - pi * gr
                da_re[a, j], da_im[a, j] = re, im
        for j in range(2):
            for b in range(2):
                re = im = 0.0
                for a in range(2):
                    dr, di = da_re[a, j], da_im[a, j]
                    cr, ci = c_re[2 * a + b], c_im[2 * a + b]
                    re = re + dr * cr + di * ci
                    im = im + dr * ci - di * cr
                djq_re[2 * j + b] = djq_re[2 * j + b] + re
                djq_im[2 * j + b] = djq_im[2 * j + b] + im

    return (djp_re, djp_im), (djq_re, djq_im)


def _bwd_store(dtabre_ref, dtabim_ref, djp, djq, ohp, ohq, MP, T, nc=1,
               cmap=None):
    """Scatter per-row gain cotangents to table rows, one component at
    a time: dtab[k] += dJ_k (Mp*nc, T) contracted with the one-hot over
    T (dot_general on trailing dims — no transpose op), accumulated
    over row tiles via the revisited (4, Mp*nc, NPAD) output block."""
    r = pl.program_id(0)
    sels = (None if nc == 1 else
            [(cmap == c).astype(jnp.float32) for c in range(nc)])
    for k in range(4):
        dre = (_rowsum_dot(_chunk_route(djp[0][k], MP, T, nc, sels), ohp)
               + _rowsum_dot(_chunk_route(djq[0][k], MP, T, nc, sels), ohq))
        dim = (_rowsum_dot(_chunk_route(djp[1][k], MP, T, nc, sels), ohp)
               + _rowsum_dot(_chunk_route(djq[1][k], MP, T, nc, sels), ohq))

        @pl.when(r == 0)
        def _init(dre=dre, dim=dim, k=k):
            dtabre_ref[k] = dre
            dtabim_ref[k] = dim

        @pl.when(r != 0)
        def _acc(dre=dre, dim=dim, k=k):
            dtabre_ref[k] = dtabre_ref[k] + dre
            dtabim_ref[k] = dtabim_ref[k] + dim


def _bwd_kernel(antp_ref, antq_ref, tabre_ref, tabim_ref, coh_ref, g_ref,
                dtabre_ref, dtabim_ref, *, F, MP, T):
    ohp, ohq = _onehots(antp_ref, antq_ref, T)
    p_re, p_im = _expand_gains(tabre_ref, tabim_ref, ohp, MP, T)
    q_re, q_im = _expand_gains(tabre_ref, tabim_ref, ohq, MP, T)
    djp, djq = _bwd_accumulate(coh_ref, _g_from_ref(g_ref), p_re, p_im,
                               q_re, q_im, F, MP, T)
    _bwd_store(dtabre_ref, dtabim_ref, djp, djq, ohp, ohq, MP, T)


def _bwd_kernel_hybrid(antp_ref, antq_ref, cmap_ref, tabre_ref, tabim_ref,
                       coh_ref, g_ref, dtabre_ref, dtabim_ref,
                       *, F, MP, T, NC):
    ohp, ohq = _onehots(antp_ref, antq_ref, T)
    cmap = cmap_ref[:]
    p_re, p_im = _expand_gains(tabre_ref, tabim_ref, ohp, MP, T, NC, cmap)
    q_re, q_im = _expand_gains(tabre_ref, tabim_ref, ohq, MP, T, NC, cmap)
    djp, djq = _bwd_accumulate(coh_ref, _g_from_ref(g_ref), p_re, p_im,
                               q_re, q_im, F, MP, T)
    _bwd_store(dtabre_ref, dtabim_ref, djp, djq, ohp, ohq, MP, T, NC, cmap)


def _fused_predict_bwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q, g_ri,
                            *, tile, nc=1, cmap=None):
    Mp, F, rowsp, R = _shape_args(tab_re, coh_ri, tile, nc)
    mrows = Mp * nc
    g_spec = pl.BlockSpec((F, 8, tile), lambda r: (0, 0, r),
                          memory_space=pltpu.VMEM)
    if nc == 1:
        kernel = functools.partial(_bwd_kernel, F=F, MP=Mp, T=tile)
        specs = [_row_spec(tile), _row_spec(tile),
                 _tab_spec(Mp), _tab_spec(Mp),
                 _coh_spec(Mp, F, tile), g_spec]
        args = (ant_p, ant_q, tab_re, tab_im, coh_ri, g_ri)
    else:
        kernel = functools.partial(_bwd_kernel_hybrid, F=F, MP=Mp, T=tile,
                                   NC=nc)
        specs = [_row_spec(tile), _row_spec(tile), _cmap_spec(Mp, tile),
                 _tab_spec(Mp * nc), _tab_spec(Mp * nc),
                 _coh_spec(Mp, F, tile), g_spec]
        args = (ant_p, ant_q, cmap, tab_re, tab_im, coh_ri, g_ri)
    return pl.pallas_call(
        kernel,
        grid=(R,),
        in_specs=specs,
        out_specs=[_tab_spec(mrows), _tab_spec(mrows)],
        out_shape=[
            jax.ShapeDtypeStruct((4, mrows, NPAD), jnp.float32),
            jax.ShapeDtypeStruct((4, mrows, NPAD), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(*args)


# ------------------------------------------------------------ public API

# Capability flag for the sky-model refinement path (sagecal_tpu/refine):
# the fused kernel's backward emits gain-table cotangents ONLY — it has
# no coherency cotangent, so sky-parameter gradients cannot flow through
# it.  Refinement must route its predict through the XLA path
# (solvers.sage.predict_full_model / ops.rime.predict_coherencies);
# requesting a coherency gradient here raises FusedSkyGradientError via
# sky_constant() instead of silently returning zeros.
FUSED_COHERENCY_COTANGENT = False

# Machine-checkable form of the same contract: the argument(s) whose
# cotangent the capability flag governs.  jaxlint's JL013
# (cotangent-completeness) accepts a None cotangent slot for any
# custom_vjp argument named here while the flag is False, and reports
# the pair as a broken promise if the flag is ever flipped True without
# the backward actually producing the cotangent.
FUSED_COHERENCY_COTANGENT_ARGS = ("coh_ri",)


class FusedSkyGradientError(NotImplementedError):
    """A caller requested coherency (sky-parameter) gradients through
    the fused Pallas kernel, whose backward pass only produces gain
    cotangents.  Silent-zero cotangents are never returned."""


@jax.custom_vjp
def sky_constant(coh_ri):
    """Identity marking ``coh_ri`` a solver constant on the fused path.

    Forward is a no-op.  Reverse-mode differentiation THROUGH this op —
    i.e. any request for a coherency/sky cotangent from the fused
    kernels — raises :class:`FusedSkyGradientError` at backward-trace
    time instead of fabricating a silent zero (the hazard the refine
    subsystem's finite-difference pins would otherwise miss).  Gain-only
    differentiation never touches the backward rule, so every solver
    path is unaffected."""
    return coh_ri


def _sky_constant_fwd(coh_ri):
    return coh_ri, None


def _sky_constant_bwd(_, g):
    raise FusedSkyGradientError(
        "gradients w.r.t. coherencies are not implemented by the fused "
        "Pallas kernel (its backward emits gain-table cotangents only); "
        "route sky-model refinement through the XLA predict path "
        "(refine.objective / solvers.sage.predict_full_model) instead "
        "of the fused objective"
    )


sky_constant.defvjp(_sky_constant_fwd, _sky_constant_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def fused_predict_packed(tab_re, tab_im, coh_ri, ant_p, ant_q,
                         tile=DEF_TILE):
    """Full-model RIME predict, packed-real layout (module docstring).

    Differentiable w.r.t. ``tab_re``/``tab_im`` only — coherencies are
    per-tile constants in every solver path (the chunked wrappers guard
    them with :func:`sky_constant`, which raises on any coherency
    cotangent request rather than returning silent zeros)."""
    return _fused_predict_fwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q,
                                   tile=tile)


def _vjp_fwd(tab_re, tab_im, coh_ri, ant_p, ant_q, tile):
    out = _fused_predict_fwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q,
                                  tile=tile)
    return out, (tab_re, tab_im, coh_ri, ant_p, ant_q)


def _vjp_bwd(tile, res, g_ri):
    tab_re, tab_im, coh_ri, ant_p, ant_q = res
    dre, dim = _fused_predict_bwd_impl(
        tab_re, tab_im, coh_ri, ant_p, ant_q, g_ri, tile=tile
    )
    return dre, dim, None, None, None


fused_predict_packed.defvjp(_vjp_fwd, _vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def fused_predict_packed_hybrid(tab_re, tab_im, coh_ri, ant_p, ant_q, cmap,
                                nc, tile=DEF_TILE):
    """Hybrid-chunk variant (reference nchunk > 1, lmfit.c:86-87):
    ``tab_re/tab_im`` are component-major (4, Mp*nc, NPAD) with one
    row per (cluster, chunk) in each component plane, ``cmap``
    (Mp, rowsp) int32 selects each row's chunk.  ``nc`` is static.
    Differentiable w.r.t. ``tab_re``/``tab_im`` ONLY — a coherency
    cotangent request raises through :func:`sky_constant` at the
    chunked wrappers (never silent zeros)."""
    return _fused_predict_fwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q,
                                   tile=tile, nc=nc, cmap=cmap)


def _vjp_fwd_h(tab_re, tab_im, coh_ri, ant_p, ant_q, cmap, nc, tile):
    out = _fused_predict_fwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q,
                                  tile=tile, nc=nc, cmap=cmap)
    return out, (tab_re, tab_im, coh_ri, ant_p, ant_q, cmap)


def _vjp_bwd_h(nc, tile, res, g_ri):
    tab_re, tab_im, coh_ri, ant_p, ant_q, cmap = res
    dre, dim = _fused_predict_bwd_impl(
        tab_re, tab_im, coh_ri, ant_p, ant_q, g_ri, tile=tile, nc=nc,
        cmap=cmap,
    )
    return dre, dim, None, None, None, None


fused_predict_packed_hybrid.defvjp(_vjp_fwd_h, _vjp_bwd_h)


# On-chip VMEM budget (round-5 hardware findings, v5e): the kernel
# body's scoped stack scales with Mp * tile against a 16 MB scoped-vmem
# limit.  At the north-star cluster count (Mp=104) the FORWARD needs
# tile <= 256 (512 -> 20.9 MB FAILS, 256 -> ~10.5 MB ok) and the
# BACKWARD — which carries 16 (Mp, T) cotangent accumulators — needs
# tile <= 128 (256 -> 19.7 MB FAILS).  128 is the safe production tile
# for any differentiated path at full cluster count.  The OBJECTIVE
# kernels below add only (F, 8, tile) vis + (F, tile) mask blocks and a
# (1, tile) accumulator on top of the predict footprint (~80 KB at
# F=2, tile=128 — noise next to the 16 (Mp, T) cotangent accumulators),
# so the same tile bounds hold.  Large row counts
# are CHUNKED at the XLA level (lax.map) to keep each Mosaic grid
# short; NOTE the dominant "compile time" observed for big closures was
# actually the axon AOT relay ingesting closure constants at ~2 MB/s —
# always pass big arrays as jit ARGUMENTS.
FULL_CLUSTER_TILE = 128
MAX_GRID_ROWS = 32768  # rows per lax.map chunk


def _chunk_plan(rowsp: int, tile: int, max_rows: int):
    """(n_chunks, chunk) for splitting ``rowsp`` rows, or None when one
    grid suffices.  Single copy of the math shared by both chunked
    wrappers; chunked_rowsp() pads so the validation always holds."""
    max_rows = _tile_floor(max_rows, tile)
    if rowsp <= max_rows:
        return None
    n = -(-rowsp // max_rows)
    chunk = rowsp // n
    if chunk * n != rowsp or chunk % tile:
        raise ValueError(
            f"rowsp={rowsp} must be n_chunks*chunk with chunk a multiple "
            f"of tile={tile}; pad with chunked_rowsp()")
    return n, chunk


def _map_row_chunks(one, n, chunk, F, rowsp):
    assert n * chunk == rowsp, (
        f"chunk plan must cover the row axis exactly: "
        f"{n} * {chunk} != {rowsp}"
    )
    out = jax.lax.map(one, jnp.arange(n))        # (n, F, 8, chunk)
    return out.transpose(1, 2, 0, 3).reshape(F, 8, rowsp)


def fused_predict_packed_chunked(tab_re, tab_im, coh_ri, ant_p, ant_q,
                                 tile=FULL_CLUSTER_TILE,
                                 max_rows=MAX_GRID_ROWS):
    """Full-model predict for row counts too long for one Mosaic grid.

    Splits the row axis into ``n = ceil(rowsp / max_rows)`` equal chunks
    (caller pads ``rowsp`` to ``n * chunk`` with ``chunked_rowsp``) and
    ``lax.map``s the fused kernel over them — one kernel compile at a
    known-good grid length, reused across chunks and LBFGS iterations.
    Gradients flow to the gain tables through the map like the unchunked
    call."""
    _, F, _, rowsp = coh_ri.shape
    plan = _chunk_plan(rowsp, tile, max_rows)
    # coherencies are constants of the solve on BOTH branches: the same
    # sky_constant guard (raise on coherency cotangent, not silent
    # zeros) keeps the plan-None and chunked paths identical
    coh_ri = sky_constant(coh_ri)
    # antenna index maps are integer data constants: stop_gradient is
    # the identity on them, and makes the backward's None cotangent
    # slots statically provable (JL013) — no cotangent ever requested
    ant_p = jax.lax.stop_gradient(ant_p)
    ant_q = jax.lax.stop_gradient(ant_q)
    if plan is None:
        return fused_predict_packed(tab_re, tab_im, coh_ri,
                                    ant_p, ant_q, tile)
    n, chunk = plan

    def one(i):
        c = jax.lax.dynamic_slice_in_dim(coh_ri, i * chunk, chunk, axis=3)
        p = jax.lax.dynamic_slice_in_dim(ant_p, i * chunk, chunk, axis=1)
        q = jax.lax.dynamic_slice_in_dim(ant_q, i * chunk, chunk, axis=1)
        return fused_predict_packed(tab_re, tab_im, c, p, q, tile)

    return _map_row_chunks(one, n, chunk, F, rowsp)


def fused_predict_packed_hybrid_chunked(tab_re, tab_im, coh_ri, ant_p,
                                        ant_q, cmap, nc,
                                        tile=FULL_CLUSTER_TILE,
                                        max_rows=MAX_GRID_ROWS):
    """Hybrid-chunk (nc > 1) analog of fused_predict_packed_chunked:
    ``cmap`` (Mp, rowsp) is sliced along the row axis with the other
    per-row arrays."""
    _, F, _, rowsp = coh_ri.shape
    plan = _chunk_plan(rowsp, tile, max_rows)
    coh_ri = sky_constant(coh_ri)
    # integer data constants (see fused_predict_packed_chunked)
    ant_p = jax.lax.stop_gradient(ant_p)
    ant_q = jax.lax.stop_gradient(ant_q)
    cmap = jax.lax.stop_gradient(cmap)
    if plan is None:
        return fused_predict_packed_hybrid(
            tab_re, tab_im, coh_ri, ant_p, ant_q, cmap, nc, tile)
    n, chunk = plan

    def one(i):
        c = jax.lax.dynamic_slice_in_dim(coh_ri, i * chunk, chunk, axis=3)
        p = jax.lax.dynamic_slice_in_dim(ant_p, i * chunk, chunk, axis=1)
        q = jax.lax.dynamic_slice_in_dim(ant_q, i * chunk, chunk, axis=1)
        cm = jax.lax.dynamic_slice_in_dim(cmap, i * chunk, chunk, axis=1)
        return fused_predict_packed_hybrid(
            tab_re, tab_im, c, p, q, cm, nc, tile)

    return _map_row_chunks(one, n, chunk, F, rowsp)


def _tile_floor(max_rows: int, tile: int) -> int:
    """Largest tile multiple <= max_rows — both chunking functions
    derive the chunk bound this way so chunked_rowsp() output always
    satisfies fused_predict_packed_chunked()'s validation."""
    if max_rows < tile:
        raise ValueError(f"max_rows={max_rows} smaller than tile={tile}")
    return max_rows - max_rows % tile


def chunked_rowsp(rows: int, tile: int = FULL_CLUSTER_TILE,
                  max_rows: int = MAX_GRID_ROWS) -> int:
    """Smallest padded row count that is n equal tile-aligned chunks of
    at most ``max_rows`` rows (n chosen minimal)."""
    max_rows = _tile_floor(max_rows, tile)
    rowsp = pad_to(rows, tile)
    if rowsp <= max_rows:
        return rowsp
    n = -(-rowsp // max_rows)
    # ceil(rowsp/n) <= max_rows (from n's definition) and max_rows is a
    # tile multiple, so the tile-padded chunk stays <= max_rows; and
    # chunk >= rowsp/n > (n-1)*max_rows/n means the consumer recomputes
    # the same n from chunk*n.
    return pad_to(-(-rowsp // n), tile) * n


# ---------------------------------------------------- fused objective
#
# One grid pass that streams each coherency block through VMEM once and
# emits per-tile PARTIAL COSTS directly: predict Jp C Jq^H, residual
# (vis - model) * mask, Student's-t weighting log1p(e^2 / nu) (Gaussian
# e^2 as the nu -> inf degenerate case), reduced on-chip into a
# revisited (1, tile) accumulator block.  Compared with the predict
# kernel + XLA cost, this removes TWO buffer-scale HBM streams per
# value_and_grad: the forward never writes model_ri and the backward
# re-forms the residual cotangent in-register instead of reading a
# model-sized upstream cotangent buffer.  nu crosses the boundary as a
# (1, 1) f32 SMEM scalar so a traced nu (the EM's mean_nu) does not
# recompile the kernel; ``robust`` is static (Gaussian skips the
# transcendental entirely).


def _vis_spec(F, tile):
    return pl.BlockSpec((F, 8, tile), lambda r: (0, 0, r),
                        memory_space=pltpu.VMEM)


def _mask_spec(F, tile):
    return pl.BlockSpec((F, tile), lambda r: (0, r),
                        memory_space=pltpu.VMEM)


def _nu_spec():
    return pl.BlockSpec((1, 1), lambda r: (0, 0), memory_space=pltpu.SMEM)


def _residual_planes(vis_ref, mask_ref, f, v_re, v_im):
    """Masked residual d = (vis - sum_m V) * mask for frequency f as
    4 complex-component (d_re, d_im) (1, T) plane pairs, formed from
    the per-cluster products without ever storing the model."""
    m = mask_ref[f:f + 1, :]
    out = []
    for k in range(4):
        d_re = (vis_ref[f, k:k + 1, :]
                - jnp.sum(v_re[k], axis=0, keepdims=True)) * m
        d_im = (vis_ref[f, 4 + k:5 + k, :]
                - jnp.sum(v_im[k], axis=0, keepdims=True)) * m
        out.append((d_re, d_im))
    return m, out


def _obj_partial(coh_ref, vis_ref, mask_ref, nu, robust,
                 p_re, p_im, q_re, q_im, F, T):
    """Per-lane partial cost (1, T) for one row tile: sum over freq and
    complex components of e2 (Gaussian) or log1p(e2/nu) (robust), with
    e2 the squared masked residual.  Padded rows/clusters carry zero
    mask/coherency, so they contribute exactly 0."""
    part = jnp.zeros((1, T), jnp.float32)
    for f in range(F):
        c_re, c_im = _load_coh_planes(coh_ref, f)
        v_re, v_im = _rime_products(c_re, c_im, p_re, p_im, q_re, q_im)
        _, d = _residual_planes(vis_ref, mask_ref, f, v_re, v_im)
        for k in range(4):
            d_re, d_im = d[k]
            e2 = d_re * d_re + d_im * d_im
            part = part + (jnp.log1p(e2 / nu) if robust else e2)
    return part


def _obj_store(cost_ref, part):
    r = pl.program_id(0)

    @pl.when(r == 0)
    def _init():
        cost_ref[:, :] = part

    @pl.when(r != 0)
    def _acc():
        cost_ref[:, :] = cost_ref[:, :] + part


def _obj_fwd_kernel(antp_ref, antq_ref, tabre_ref, tabim_ref, coh_ref,
                    vis_ref, mask_ref, nu_ref, cost_ref, *, F, MP, T,
                    robust):
    ohp, ohq = _onehots(antp_ref, antq_ref, T)
    p_re, p_im = _expand_gains(tabre_ref, tabim_ref, ohp, MP, T)
    q_re, q_im = _expand_gains(tabre_ref, tabim_ref, ohq, MP, T)
    part = _obj_partial(coh_ref, vis_ref, mask_ref, nu_ref[0, 0], robust,
                        p_re, p_im, q_re, q_im, F, T)
    _obj_store(cost_ref, part)


def _obj_fwd_kernel_hybrid(antp_ref, antq_ref, cmap_ref, tabre_ref,
                           tabim_ref, coh_ref, vis_ref, mask_ref, nu_ref,
                           cost_ref, *, F, MP, T, NC, robust):
    ohp, ohq = _onehots(antp_ref, antq_ref, T)
    cmap = cmap_ref[:]
    p_re, p_im = _expand_gains(tabre_ref, tabim_ref, ohp, MP, T, NC, cmap)
    q_re, q_im = _expand_gains(tabre_ref, tabim_ref, ohq, MP, T, NC, cmap)
    part = _obj_partial(coh_ref, vis_ref, mask_ref, nu_ref[0, 0], robust,
                        p_re, p_im, q_re, q_im, F, T)
    _obj_store(cost_ref, part)


def _g_from_residual(vis_ref, mask_ref, nu, robust, p_re, p_im):
    """Objective-kernel cotangent source: re-form the model from the
    cached A = C Jq^H (no HBM traffic), take the residual, and emit the
    model cotangent of the scalar cost in-register:
      g = -2 * mask * d              (Gaussian,  d(e2)/d(model))
      g = -2 * mask * d / (nu + e2)  (robust, d(log1p(e2/nu))/d(model))
    The upstream scalar cost cotangent is applied OUTSIDE the kernel."""
    def g_of(f, c_re, c_im, a_re, a_im):
        del c_re, c_im
        v_re, v_im = _jp_a(p_re, p_im, a_re, a_im)
        m, d = _residual_planes(vis_ref, mask_ref, f, v_re, v_im)
        g_re, g_im = [], []
        for k in range(4):
            d_re, d_im = d[k]
            if robust:
                w = 2.0 / (nu + d_re * d_re + d_im * d_im)
            else:
                w = 2.0
            g_re.append(-w * m * d_re)
            g_im.append(-w * m * d_im)
        return g_re, g_im
    return g_of


def _obj_bwd_kernel(antp_ref, antq_ref, tabre_ref, tabim_ref, coh_ref,
                    vis_ref, mask_ref, nu_ref, dtabre_ref, dtabim_ref,
                    *, F, MP, T, robust):
    ohp, ohq = _onehots(antp_ref, antq_ref, T)
    p_re, p_im = _expand_gains(tabre_ref, tabim_ref, ohp, MP, T)
    q_re, q_im = _expand_gains(tabre_ref, tabim_ref, ohq, MP, T)
    g_of = _g_from_residual(vis_ref, mask_ref, nu_ref[0, 0], robust,
                            p_re, p_im)
    djp, djq = _bwd_accumulate(coh_ref, g_of, p_re, p_im, q_re, q_im,
                               F, MP, T)
    _bwd_store(dtabre_ref, dtabim_ref, djp, djq, ohp, ohq, MP, T)


def _obj_bwd_kernel_hybrid(antp_ref, antq_ref, cmap_ref, tabre_ref,
                           tabim_ref, coh_ref, vis_ref, mask_ref, nu_ref,
                           dtabre_ref, dtabim_ref, *, F, MP, T, NC, robust):
    ohp, ohq = _onehots(antp_ref, antq_ref, T)
    cmap = cmap_ref[:]
    p_re, p_im = _expand_gains(tabre_ref, tabim_ref, ohp, MP, T, NC, cmap)
    q_re, q_im = _expand_gains(tabre_ref, tabim_ref, ohq, MP, T, NC, cmap)
    g_of = _g_from_residual(vis_ref, mask_ref, nu_ref[0, 0], robust,
                            p_re, p_im)
    djp, djq = _bwd_accumulate(coh_ref, g_of, p_re, p_im, q_re, q_im,
                               F, MP, T)
    _bwd_store(dtabre_ref, dtabim_ref, djp, djq, ohp, ohq, MP, T, NC, cmap)


def _fused_cost_fwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri,
                         mask_p, nu_arr, *, robust, tile, nc=1, cmap=None):
    Mp, F, rowsp, R = _shape_args(tab_re, coh_ri, tile, nc)
    assert vis_ri.shape == (F, 8, rowsp) and mask_p.shape == (F, rowsp)
    if nc == 1:
        kernel = functools.partial(_obj_fwd_kernel, F=F, MP=Mp, T=tile,
                                   robust=robust)
        specs = [_row_spec(tile), _row_spec(tile),
                 _tab_spec(Mp), _tab_spec(Mp), _coh_spec(Mp, F, tile),
                 _vis_spec(F, tile), _mask_spec(F, tile), _nu_spec()]
        args = (ant_p, ant_q, tab_re, tab_im, coh_ri, vis_ri, mask_p,
                nu_arr)
    else:
        kernel = functools.partial(_obj_fwd_kernel_hybrid, F=F, MP=Mp,
                                   T=tile, NC=nc, robust=robust)
        specs = [_row_spec(tile), _row_spec(tile), _cmap_spec(Mp, tile),
                 _tab_spec(Mp * nc), _tab_spec(Mp * nc),
                 _coh_spec(Mp, F, tile),
                 _vis_spec(F, tile), _mask_spec(F, tile), _nu_spec()]
        args = (ant_p, ant_q, cmap, tab_re, tab_im, coh_ri, vis_ri,
                mask_p, nu_arr)
    part = pl.pallas_call(
        kernel,
        grid=(R,),
        in_specs=specs,
        out_specs=pl.BlockSpec((1, tile), lambda r: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, tile), jnp.float32),
        interpret=_use_interpret(),
    )(*args)
    # final lane reduction of the (1, tile) accumulator happens in XLA:
    # tile floats, not a buffer-scale stream
    return jnp.sum(part)


def _fused_cost_bwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri,
                         mask_p, nu_arr, *, robust, tile, nc=1, cmap=None):
    Mp, F, rowsp, R = _shape_args(tab_re, coh_ri, tile, nc)
    mrows = Mp * nc
    if nc == 1:
        kernel = functools.partial(_obj_bwd_kernel, F=F, MP=Mp, T=tile,
                                   robust=robust)
        specs = [_row_spec(tile), _row_spec(tile),
                 _tab_spec(Mp), _tab_spec(Mp), _coh_spec(Mp, F, tile),
                 _vis_spec(F, tile), _mask_spec(F, tile), _nu_spec()]
        args = (ant_p, ant_q, tab_re, tab_im, coh_ri, vis_ri, mask_p,
                nu_arr)
    else:
        kernel = functools.partial(_obj_bwd_kernel_hybrid, F=F, MP=Mp,
                                   T=tile, NC=nc, robust=robust)
        specs = [_row_spec(tile), _row_spec(tile), _cmap_spec(Mp, tile),
                 _tab_spec(Mp * nc), _tab_spec(Mp * nc),
                 _coh_spec(Mp, F, tile),
                 _vis_spec(F, tile), _mask_spec(F, tile), _nu_spec()]
        args = (ant_p, ant_q, cmap, tab_re, tab_im, coh_ri, vis_ri,
                mask_p, nu_arr)
    return pl.pallas_call(
        kernel,
        grid=(R,),
        in_specs=specs,
        out_specs=[_tab_spec(mrows), _tab_spec(mrows)],
        out_shape=[
            jax.ShapeDtypeStruct((4, mrows, NPAD), jnp.float32),
            jax.ShapeDtypeStruct((4, mrows, NPAD), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9))
def _fused_cost(tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri, mask_p,
                nu_arr, robust, tile):
    return _fused_cost_fwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q,
                                vis_ri, mask_p, nu_arr, robust=robust,
                                tile=tile)


def _cost_vjp_fwd(tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri, mask_p,
                  nu_arr, robust, tile):
    out = _fused_cost_fwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q,
                               vis_ri, mask_p, nu_arr, robust=robust,
                               tile=tile)
    return out, (tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri, mask_p,
                 nu_arr)


def _cost_vjp_bwd(robust, tile, res, gbar):
    tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri, mask_p, nu_arr = res
    dre, dim = _fused_cost_bwd_impl(
        tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri, mask_p, nu_arr,
        robust=robust, tile=tile,
    )
    # the kernel emits d(cost)/d(tab); scale by the upstream scalar
    # cotangent here (one scalar-times-table op, not a kernel input)
    return (gbar * dre, gbar * dim, None, None, None, None, None, None)


_fused_cost.defvjp(_cost_vjp_fwd, _cost_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11))
def _fused_cost_hybrid(tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri,
                       mask_p, nu_arr, cmap, nc, robust, tile):
    return _fused_cost_fwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q,
                                vis_ri, mask_p, nu_arr, robust=robust,
                                tile=tile, nc=nc, cmap=cmap)


def _cost_vjp_fwd_h(tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri, mask_p,
                    nu_arr, cmap, nc, robust, tile):
    out = _fused_cost_fwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q,
                               vis_ri, mask_p, nu_arr, robust=robust,
                               tile=tile, nc=nc, cmap=cmap)
    return out, (tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri, mask_p,
                 nu_arr, cmap)


def _cost_vjp_bwd_h(nc, robust, tile, res, gbar):
    (tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri, mask_p, nu_arr,
     cmap) = res
    dre, dim = _fused_cost_bwd_impl(
        tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri, mask_p, nu_arr,
        robust=robust, tile=tile, nc=nc, cmap=cmap,
    )
    return (gbar * dre, gbar * dim, None, None, None, None, None, None,
            None)


_fused_cost_hybrid.defvjp(_cost_vjp_fwd_h, _cost_vjp_bwd_h)


def _nu_cell(nu):
    """nu as the kernel's (1, 1) f32 SMEM cell.  ``nu=None`` (Gaussian)
    passes 1.0, which the kernel never reads (``robust`` is static)."""
    if nu is None:
        return jnp.ones((1, 1), jnp.float32)
    return jnp.asarray(nu, jnp.float32).reshape(1, 1)


def fused_cost_packed(tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri,
                      mask_p, nu=None, tile=DEF_TILE):
    """Scalar calibration objective in one fused pass (section comment
    above): ``sum log1p(|((vis - Jp C Jq^H) * mask)|^2 / nu)`` when
    ``nu`` is given (Student's-t / robust), ``sum |...|^2`` when ``nu``
    is None (Gaussian).  ``nu`` may be a traced scalar (the EM's
    mean_nu).  Differentiable w.r.t. ``tab_re``/``tab_im`` only, via a
    backward kernel that never materializes the model or residual in
    HBM."""
    robust = nu is not None
    # data constants of the solve: stop_gradient (identity for values)
    # makes the backward's None cotangent slots statically provable
    # (JL013) — differentiation w.r.t. these args is never requested
    ant_p = jax.lax.stop_gradient(ant_p)
    ant_q = jax.lax.stop_gradient(ant_q)
    vis_ri = jax.lax.stop_gradient(vis_ri)
    mask_p = jax.lax.stop_gradient(mask_p)
    nu_arr = jax.lax.stop_gradient(_nu_cell(nu))
    return _fused_cost(tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri,
                       mask_p, nu_arr, robust, tile)


def fused_cost_packed_hybrid(tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri,
                             mask_p, cmap, nc, nu=None, tile=DEF_TILE):
    """Hybrid-chunk (nc > 1) objective: tables carry one row block per
    (cluster, chunk), ``cmap`` (Mp, rowsp) selects each row's chunk."""
    robust = nu is not None
    # data constants of the solve (see fused_cost_packed)
    ant_p = jax.lax.stop_gradient(ant_p)
    ant_q = jax.lax.stop_gradient(ant_q)
    vis_ri = jax.lax.stop_gradient(vis_ri)
    mask_p = jax.lax.stop_gradient(mask_p)
    cmap = jax.lax.stop_gradient(cmap)
    nu_arr = jax.lax.stop_gradient(_nu_cell(nu))
    return _fused_cost_hybrid(tab_re, tab_im, coh_ri, ant_p, ant_q,
                              vis_ri, mask_p, nu_arr, cmap, nc,
                              robust, tile)


def fused_cost_packed_chunked(tab_re, tab_im, coh_ri, ant_p, ant_q,
                              vis_ri, mask_p, nu=None,
                              tile=FULL_CLUSTER_TILE,
                              max_rows=MAX_GRID_ROWS):
    """Fused objective for row counts too long for one Mosaic grid:
    per-row arrays are sliced into equal tile-aligned chunks (see
    fused_predict_packed_chunked) and the per-chunk scalar costs summed.
    vis/mask stay stop_gradient data constants; coherencies go through
    the sky_constant guard (raise on a sky-gradient request, matching
    the predict wrappers — never silent zeros)."""
    _, F, _, rowsp = coh_ri.shape
    plan = _chunk_plan(rowsp, tile, max_rows)
    nu_arr = jax.lax.stop_gradient(_nu_cell(nu))
    robust = nu is not None
    coh_ri = sky_constant(coh_ri)
    # integer data constants (see fused_cost_packed)
    ant_p = jax.lax.stop_gradient(ant_p)
    ant_q = jax.lax.stop_gradient(ant_q)
    if plan is None:
        return _fused_cost(tab_re, tab_im, coh_ri,
                           ant_p, ant_q, jax.lax.stop_gradient(vis_ri),
                           jax.lax.stop_gradient(mask_p), nu_arr, robust,
                           tile)
    n, chunk = plan

    def one(i):
        c = jax.lax.dynamic_slice_in_dim(coh_ri, i * chunk, chunk, axis=3)
        p = jax.lax.dynamic_slice_in_dim(ant_p, i * chunk, chunk, axis=1)
        q = jax.lax.dynamic_slice_in_dim(ant_q, i * chunk, chunk, axis=1)
        v = jax.lax.dynamic_slice_in_dim(vis_ri, i * chunk, chunk, axis=2)
        m = jax.lax.dynamic_slice_in_dim(mask_p, i * chunk, chunk, axis=1)
        return _fused_cost(tab_re, tab_im, c, p, q,
                           jax.lax.stop_gradient(v),
                           jax.lax.stop_gradient(m), nu_arr, robust, tile)

    return jnp.sum(jax.lax.map(one, jnp.arange(n)))


def fused_cost_packed_hybrid_chunked(tab_re, tab_im, coh_ri, ant_p, ant_q,
                                     vis_ri, mask_p, cmap, nc, nu=None,
                                     tile=FULL_CLUSTER_TILE,
                                     max_rows=MAX_GRID_ROWS):
    """Hybrid-chunk (nc > 1) analog of fused_cost_packed_chunked."""
    _, F, _, rowsp = coh_ri.shape
    plan = _chunk_plan(rowsp, tile, max_rows)
    nu_arr = jax.lax.stop_gradient(_nu_cell(nu))
    robust = nu is not None
    coh_ri = sky_constant(coh_ri)
    # integer data constants (see fused_cost_packed)
    ant_p = jax.lax.stop_gradient(ant_p)
    ant_q = jax.lax.stop_gradient(ant_q)
    cmap = jax.lax.stop_gradient(cmap)
    if plan is None:
        return _fused_cost_hybrid(
            tab_re, tab_im, coh_ri, ant_p, ant_q,
            jax.lax.stop_gradient(vis_ri), jax.lax.stop_gradient(mask_p),
            nu_arr, cmap, nc, robust, tile)
    n, chunk = plan

    def one(i):
        c = jax.lax.dynamic_slice_in_dim(coh_ri, i * chunk, chunk, axis=3)
        p = jax.lax.dynamic_slice_in_dim(ant_p, i * chunk, chunk, axis=1)
        q = jax.lax.dynamic_slice_in_dim(ant_q, i * chunk, chunk, axis=1)
        v = jax.lax.dynamic_slice_in_dim(vis_ri, i * chunk, chunk, axis=2)
        m = jax.lax.dynamic_slice_in_dim(mask_p, i * chunk, chunk, axis=1)
        cm = jax.lax.dynamic_slice_in_dim(cmap, i * chunk, chunk, axis=1)
        return _fused_cost_hybrid(
            tab_re, tab_im, c, p, q,
            jax.lax.stop_gradient(v), jax.lax.stop_gradient(m), nu_arr,
            cm, nc, robust, tile)

    return jnp.sum(jax.lax.map(one, jnp.arange(n)))


# ---------------------------------------------- batched fused objective
#
# One Pallas grid evaluating the fused objective for a BATCH of lanes
# (independent same-shape solves — the serve path's tenants).  The lane
# axis is folded into the GEMM M dimension: batched gain tables are
# (4, B*Mp, NPAD) lane-major, so the one-hot selection matmuls become
# (B*Mp, NPAD) @ (NPAD, T) — B times the MXU rows of a solo dispatch
# per pass, instead of B separate grids of tiny 2x2 arithmetic.  All
# the solo (rows, T)-plane helpers (_expand_gains, _load_coh_planes,
# _rime_products, _bwd_accumulate, _bwd_store) are reused unchanged
# with rows := B*Mp; only the residual/cost stage is lane-aware:
# per-lane cluster reduction via a leading-dim (B*Mp, T) -> (B, Mp, T)
# reshape (a pure sublane view — no minor-dim relayout), per-lane
# masked residual against (B, T) vis planes, per-lane partial costs
# accumulated into a (B, rowsp) output.  The backward forms each
# lane's residual cotangent in-register and broadcasts it back across
# the lane's Mp cluster rows, then the solo accumulate/scatter path
# runs unchanged on (B*Mp, T) planes.
#
# Capability contract (enforced host-side by solvers.batched):
#   - nc == 1 only (no hybrid time chunks on the batched path);
#   - ant_p/ant_q SHARED across lanes (one (1, rowsp) plane — a serve
#     bucket guarantees identical baseline geometry);
#   - per-lane nu crosses as a (B, NPAD) f32 plane (column-replicated
#     scalar per lane; a traced EM mean_nu never recompiles);
#   - VMEM: the backward carries 16 (B*Mp, T) accumulators, so the
#     solo tile bound applies with B*Mp in the cluster-row position
#     (B*Mp <~ 104 at tile 128 on the v5e — the serve shapes' 8-row
#     cluster blocks allow B up to 13 at full tile).
#
# Ragged-lane guard: replication-padded lanes are neutralized by
# zeroing their mask plane at pack time (``valid``), which makes their
# cost exactly 0.0 and their gain cotangent exactly 0 — the padded
# lane cannot perturb the batch and is discarded host-side.


def _shape_args_batch(tab_re, coh_ri, vis_ri, mask_p, tile):
    four, mrows, npad = tab_re.shape
    B, F, eight, rowsp = vis_ri.shape
    assert four == 4 and npad == NPAD and eight == 8
    assert mrows % B == 0, (mrows, B)
    Mp = mrows // B
    assert coh_ri.shape == (mrows, F, 8, rowsp), (coh_ri.shape, vis_ri.shape)
    assert mask_p.shape == (B, F, rowsp)
    assert Mp % 8 == 0 and rowsp % tile == 0, (Mp, rowsp, tile)
    return B, Mp, F, rowsp, rowsp // tile


def _bvis_spec(B, F, tile):
    return pl.BlockSpec((B, F, 8, tile), lambda r: (0, 0, 0, r),
                        memory_space=pltpu.VMEM)


def _bmask_spec(B, F, tile):
    return pl.BlockSpec((B, F, tile), lambda r: (0, 0, r),
                        memory_space=pltpu.VMEM)


def _bnu_spec(B):
    return pl.BlockSpec((B, NPAD), lambda r: (0, 0),
                        memory_space=pltpu.VMEM)


def _lane_sum(plane, B, MP, T):
    """Per-lane cluster reduction: (B*MP, T) product plane -> (B, T).
    Leading-dim reshape only (a sublane-order view, Mosaic-safe like
    the hybrid path's (mp, nc, T) split)."""
    return jnp.sum(plane.reshape(B, MP, T), axis=1)


def _lane_bcast(g, B, MP, T):
    """Inverse routing for the backward: a lane's (B, T) residual
    cotangent replicated across its MP cluster rows -> (B*MP, T), so
    the solo _bwd_accumulate arithmetic applies unchanged."""
    return jnp.broadcast_to(g[:, None, :], (B, MP, T)).reshape(B * MP, T)


def _residual_planes_batch(vis_ref, mask_ref, f, v_re, v_im, B, MP, T):
    """Per-lane masked residual d = (vis - sum_m V) * mask for
    frequency f: 4 complex-component (d_re, d_im) (B, T) plane pairs."""
    m = mask_ref[:, f, :]  # (B, T)
    out = []
    for k in range(4):
        d_re = (vis_ref[:, f, k, :] - _lane_sum(v_re[k], B, MP, T)) * m
        d_im = (vis_ref[:, f, 4 + k, :] - _lane_sum(v_im[k], B, MP, T)) * m
        out.append((d_re, d_im))
    return m, out


def _obj_partial_batch(coh_ref, vis_ref, mask_ref, nu_ref, robust,
                       p_re, p_im, q_re, q_im, B, F, MP, T):
    """Per-lane partial cost (B, T) for one row tile (the batched
    analog of _obj_partial; nu broadcasts per lane as a (B, 1) column
    against the (B, T) residual planes)."""
    part = jnp.zeros((B, T), jnp.float32)
    nu = nu_ref[:, 0:1] if robust else None
    for f in range(F):
        c_re, c_im = _load_coh_planes(coh_ref, f)
        v_re, v_im = _rime_products(c_re, c_im, p_re, p_im, q_re, q_im)
        _, d = _residual_planes_batch(vis_ref, mask_ref, f, v_re, v_im,
                                      B, MP, T)
        for k in range(4):
            d_re, d_im = d[k]
            e2 = d_re * d_re + d_im * d_im
            part = part + (jnp.log1p(e2 / nu) if robust else e2)
    return part


def _obj_fwd_kernel_batch(antp_ref, antq_ref, tabre_ref, tabim_ref,
                          coh_ref, vis_ref, mask_ref, nu_ref, cost_ref,
                          *, B, F, MP, T, robust):
    ohp, ohq = _onehots(antp_ref, antq_ref, T)
    p_re, p_im = _expand_gains(tabre_ref, tabim_ref, ohp, B * MP, T)
    q_re, q_im = _expand_gains(tabre_ref, tabim_ref, ohq, B * MP, T)
    # each grid step owns its own (B, tile) output block — no revisit
    cost_ref[:, :] = _obj_partial_batch(
        coh_ref, vis_ref, mask_ref, nu_ref, robust,
        p_re, p_im, q_re, q_im, B, F, MP, T)


def _g_from_residual_batch(vis_ref, mask_ref, nu_ref, robust, p_re, p_im,
                           B, MP, T):
    """Batched objective cotangent source: per-lane g planes (the solo
    _g_from_residual weights, per lane) broadcast back across each
    lane's cluster rows so _bwd_accumulate consumes (B*MP, T) planes."""
    def g_of(f, c_re, c_im, a_re, a_im):
        del c_re, c_im
        v_re, v_im = _jp_a(p_re, p_im, a_re, a_im)
        m, d = _residual_planes_batch(vis_ref, mask_ref, f, v_re, v_im,
                                      B, MP, T)
        g_re, g_im = [], []
        for k in range(4):
            d_re, d_im = d[k]
            if robust:
                w = 2.0 / (nu_ref[:, 0:1] + d_re * d_re + d_im * d_im)
            else:
                w = 2.0
            g_re.append(_lane_bcast(-w * m * d_re, B, MP, T))
            g_im.append(_lane_bcast(-w * m * d_im, B, MP, T))
        return g_re, g_im
    return g_of


def _obj_bwd_kernel_batch(antp_ref, antq_ref, tabre_ref, tabim_ref,
                          coh_ref, vis_ref, mask_ref, nu_ref,
                          dtabre_ref, dtabim_ref, *, B, F, MP, T, robust):
    ohp, ohq = _onehots(antp_ref, antq_ref, T)
    p_re, p_im = _expand_gains(tabre_ref, tabim_ref, ohp, B * MP, T)
    q_re, q_im = _expand_gains(tabre_ref, tabim_ref, ohq, B * MP, T)
    g_of = _g_from_residual_batch(vis_ref, mask_ref, nu_ref, robust,
                                  p_re, p_im, B, MP, T)
    djp, djq = _bwd_accumulate(coh_ref, g_of, p_re, p_im, q_re, q_im,
                               F, B * MP, T)
    _bwd_store(dtabre_ref, dtabim_ref, djp, djq, ohp, ohq, B * MP, T)


def _fused_cost_batch_fwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q,
                               vis_ri, mask_p, nu_rows, *, robust, tile):
    B, Mp, F, rowsp, R = _shape_args_batch(tab_re, coh_ri, vis_ri, mask_p,
                                           tile)
    kernel = functools.partial(_obj_fwd_kernel_batch, B=B, F=F, MP=Mp,
                               T=tile, robust=robust)
    part = pl.pallas_call(
        kernel,
        grid=(R,),
        in_specs=[_row_spec(tile), _row_spec(tile),
                  _tab_spec(B * Mp), _tab_spec(B * Mp),
                  _coh_spec(B * Mp, F, tile),
                  _bvis_spec(B, F, tile), _bmask_spec(B, F, tile),
                  _bnu_spec(B)],
        out_specs=pl.BlockSpec((B, tile), lambda r: (0, r),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, rowsp), jnp.float32),
        interpret=_use_interpret(),
    )(ant_p, ant_q, tab_re, tab_im, coh_ri, vis_ri, mask_p, nu_rows)
    # per-lane final reduction in XLA: B*rowsp floats, not buffer-scale
    return jnp.sum(part, axis=-1)


def _fused_cost_batch_bwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q,
                               vis_ri, mask_p, nu_rows, *, robust, tile):
    B, Mp, F, rowsp, R = _shape_args_batch(tab_re, coh_ri, vis_ri, mask_p,
                                           tile)
    kernel = functools.partial(_obj_bwd_kernel_batch, B=B, F=F, MP=Mp,
                               T=tile, robust=robust)
    return pl.pallas_call(
        kernel,
        grid=(R,),
        in_specs=[_row_spec(tile), _row_spec(tile),
                  _tab_spec(B * Mp), _tab_spec(B * Mp),
                  _coh_spec(B * Mp, F, tile),
                  _bvis_spec(B, F, tile), _bmask_spec(B, F, tile),
                  _bnu_spec(B)],
        out_specs=[_tab_spec(B * Mp), _tab_spec(B * Mp)],
        out_shape=[
            jax.ShapeDtypeStruct((4, B * Mp, NPAD), jnp.float32),
            jax.ShapeDtypeStruct((4, B * Mp, NPAD), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(ant_p, ant_q, tab_re, tab_im, coh_ri, vis_ri, mask_p, nu_rows)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9))
def _fused_cost_batch(tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri,
                      mask_p, nu_rows, robust, tile):
    return _fused_cost_batch_fwd_impl(
        tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri, mask_p, nu_rows,
        robust=robust, tile=tile)


def _cost_vjp_fwd_b(tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri, mask_p,
                    nu_rows, robust, tile):
    out = _fused_cost_batch_fwd_impl(
        tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri, mask_p, nu_rows,
        robust=robust, tile=tile)
    return out, (tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri, mask_p,
                 nu_rows)


def _cost_vjp_bwd_b(robust, tile, res, gbar):
    tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri, mask_p, nu_rows = res
    dre, dim = _fused_cost_batch_bwd_impl(
        tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri, mask_p, nu_rows,
        robust=robust, tile=tile)
    # the kernel emits d(cost_b)/d(tab); the upstream cotangent is now
    # PER LANE (B,) — scale each lane's Mp-row table block outside the
    # kernel (one row-broadcast multiply, not a kernel input)
    B = vis_ri.shape[0]
    Mp = tab_re.shape[1] // B
    scale = jnp.repeat(gbar, Mp)[None, :, None]  # (1, B*Mp, 1)
    return (scale * dre, scale * dim, None, None, None, None, None, None)


_fused_cost_batch.defvjp(_cost_vjp_fwd_b, _cost_vjp_bwd_b)


def _nu_rows(nu, B):
    """Per-lane nu as the batched kernel's (B, NPAD) f32 VMEM plane
    (column-replicated).  ``nu=None`` (Gaussian) passes ones, which the
    kernel never reads (``robust`` is static).  Scalar nu broadcasts to
    every lane; a (B,) array carries each lane's EM mean_nu."""
    if nu is None:
        return jnp.ones((B, NPAD), jnp.float32)
    nu = jnp.asarray(nu, jnp.float32)
    return jnp.broadcast_to(nu.reshape(-1, 1) if nu.ndim else nu,
                            (B, NPAD))


def fused_cost_packed_batch(tab_re, tab_im, coh_ri, ant_p, ant_q, vis_ri,
                            mask_p, nu=None, tile=FULL_CLUSTER_TILE,
                            max_rows=MAX_GRID_ROWS):
    """Per-lane calibration objectives for a batch of lanes in ONE fused
    grid (section comment above): returns the (B,) vector of per-lane
    costs ``sum log1p(|((vis_b - Jp_b C_b Jq_b^H) * mask_b)|^2 / nu_b)``
    (robust; Gaussian ``sum |...|^2`` when ``nu`` is None).

    Layout: ``tab_re/tab_im`` (4, B*Mp, NPAD) lane-major batched tables
    (:func:`pack_gain_tables_batch`); ``coh_ri`` (B*Mp, F, 8, rowsp)
    f32 or bf16; ``ant_p/ant_q`` (1, rowsp) SHARED across lanes;
    ``vis_ri`` (B, F, 8, rowsp); ``mask_p`` (B, F, rowsp); ``nu`` a
    scalar or (B,) per-lane array (may be traced).  Differentiable
    w.r.t. the tables only; the per-lane upstream cotangent is applied
    as a row-block scale outside the kernel.  Rows beyond one Mosaic
    grid are chunked exactly like the solo wrapper (per-chunk (B,)
    costs summed)."""
    B = vis_ri.shape[0]
    rowsp = coh_ri.shape[-1]
    plan = _chunk_plan(rowsp, tile, max_rows)
    nu_arr = jax.lax.stop_gradient(_nu_rows(nu, B))
    robust = nu is not None
    coh_ri = sky_constant(coh_ri)
    # integer data constants (see fused_cost_packed)
    ant_p = jax.lax.stop_gradient(ant_p)
    ant_q = jax.lax.stop_gradient(ant_q)
    if plan is None:
        return _fused_cost_batch(
            tab_re, tab_im, coh_ri, ant_p, ant_q,
            jax.lax.stop_gradient(vis_ri), jax.lax.stop_gradient(mask_p),
            nu_arr, robust, tile)
    n, chunk = plan

    def one(i):
        c = jax.lax.dynamic_slice_in_dim(coh_ri, i * chunk, chunk, axis=3)
        p = jax.lax.dynamic_slice_in_dim(ant_p, i * chunk, chunk, axis=1)
        q = jax.lax.dynamic_slice_in_dim(ant_q, i * chunk, chunk, axis=1)
        v = jax.lax.dynamic_slice_in_dim(vis_ri, i * chunk, chunk, axis=3)
        m = jax.lax.dynamic_slice_in_dim(mask_p, i * chunk, chunk, axis=2)
        return _fused_cost_batch(tab_re, tab_im, c, p, q,
                                 jax.lax.stop_gradient(v),
                                 jax.lax.stop_gradient(m), nu_arr, robust,
                                 tile)

    return jnp.sum(jax.lax.map(one, jnp.arange(n)), axis=0)


# --------------------------------------------------- packing conveniences


def pad_to(n: int, mult: int) -> int:
    return -(-n // mult) * mult


def pack_gain_tables(jones, mp: int):
    """(M, N, 2, 2) — or (M, nc, N, 2, 2) hybrid — complex Jones ->
    component-major (tab_re, tab_im) of shape (4, mp*nc, NPAD) f32:
    plane k holds component k (row-major [J00, J01, J10, J11]) for
    every (cluster, chunk) row ``m*nc + c``."""
    if jones.ndim == 5:
        M, nc, N = jones.shape[0], jones.shape[1], jones.shape[2]
    else:
        M, nc, N = jones.shape[0], 1, jones.shape[1]
    if N > NPAD:
        raise ValueError(
            f"fused RIME kernel supports at most NPAD={NPAD} stations, "
            f"got N={N}; use the XLA predict path (or the rows-sharded "
            f"solver) for larger arrays"
        )
    flat = jones.reshape(M * nc, N, 4)  # row-major J00, J01, J10, J11
    tab = jnp.transpose(flat, (2, 0, 1))  # (4, M*nc, N)
    tab = jnp.pad(tab, ((0, 0), (0, nc * (mp - M)), (0, NPAD - N)))
    return (jnp.real(tab).astype(jnp.float32),
            jnp.imag(tab).astype(jnp.float32))


def pack_predict_inputs(vis, mask, coh, ant_p, ant_q, chunk_map=None,
                        tile=DEF_TILE, max_rows=None):
    """Pad/pack complex (F, 4, rows) visibilities, (M, F, 4, rows)
    coherencies, mask and antenna indices into the kernel's layout
    contract: rows padded to a multiple of ``tile`` (or to equal
    tile-aligned ``max_rows`` chunks for the chunked kernels, when
    given), clusters padded to a multiple of 8, re/im concatenated on
    the component axis, ant indices as (1, rowsp) int32.  Returns
    (vis_ri, mask_p, coh_ri, antp, antq, cmap_or_None).  jnp-based: use
    inside jit (padded regions carry zero coherency and zero mask, so
    they contribute nothing to any cost or gradient)."""
    M, rows = coh.shape[0], coh.shape[-1]
    mp = pad_to(M, 8)
    rowsp = (chunked_rowsp(rows, tile, max_rows) if max_rows
             else pad_to(rows, tile))
    pad_r = rowsp - rows
    coh_ri = jnp.concatenate(
        [jnp.real(coh), jnp.imag(coh)], axis=-2
    ).astype(jnp.float32)
    coh_ri = jnp.pad(coh_ri, ((0, mp - M), (0, 0), (0, 0), (0, pad_r)))
    vis_ri = jnp.concatenate(
        [jnp.real(vis), jnp.imag(vis)], axis=-2
    ).astype(jnp.float32)
    vis_ri = jnp.pad(vis_ri, ((0, 0), (0, 0), (0, pad_r)))
    mask_p = jnp.pad(mask.astype(jnp.float32), ((0, 0), (0, pad_r)))
    antp = jnp.pad(ant_p.astype(jnp.int32)[None, :], ((0, 0), (0, pad_r)))
    antq = jnp.pad(ant_q.astype(jnp.int32)[None, :], ((0, 0), (0, pad_r)))
    cmap = None
    if chunk_map is not None:
        cmap = jnp.pad(chunk_map.astype(jnp.int32),
                       ((0, mp - M), (0, pad_r)))
    return vis_ri, mask_p, coh_ri, antp, antq, cmap


def pack_gain_tables_batch(jones_b, mp: int):
    """(B, M, N, 2, 2) complex Jones -> lane-major component-major
    batched tables (tab_re, tab_im) of shape (4, B*mp, NPAD) f32: lane
    b's cluster block occupies rows [b*mp, (b+1)*mp) of every component
    plane (nc=1 only — the batched kernel has no hybrid-chunk mode)."""
    B, M, N = jones_b.shape[0], jones_b.shape[1], jones_b.shape[2]
    if N > NPAD:
        raise ValueError(
            f"fused RIME kernel supports at most NPAD={NPAD} stations, "
            f"got N={N}; use the XLA predict path for larger arrays"
        )
    flat = jones_b.reshape(B, M, N, 4)  # row-major J00, J01, J10, J11
    tab = jnp.transpose(flat, (3, 0, 1, 2))  # (4, B, M, N)
    tab = jnp.pad(tab, ((0, 0), (0, 0), (0, mp - M), (0, NPAD - N)))
    tab = tab.reshape(4, B * mp, NPAD)
    return (jnp.real(tab).astype(jnp.float32),
            jnp.imag(tab).astype(jnp.float32))


def pack_cost_inputs_batch(vis_b, mask_b, coh_b, ant_p, ant_q,
                           tile=FULL_CLUSTER_TILE, max_rows=MAX_GRID_ROWS,
                           valid=None):
    """Pad/pack a batch of same-shape lanes into the batched objective
    kernel's layout contract: complex ``vis_b`` (B, F, 4, rows) ->
    ``vis_ri`` (B, F, 8, rowsp); ``mask_b`` (B, F, rows) -> ``mask_p``
    (B, F, rowsp); complex ``coh_b`` (B, M, F, 4, rows) -> ``coh_ri``
    (B*mp, F, 8, rowsp) lane-major; SHARED ``ant_p/ant_q`` (rows,) ->
    (1, rowsp) int32.  ``valid`` (B,) optionally zeroes whole lanes'
    masks — the replication-padded ragged-lane guard: a zeroed lane's
    cost and gain cotangent are exactly 0 through the kernel (Gaussian
    0, robust log1p(0)), so padded lanes cannot perturb the batch.
    jnp-based: use inside jit.  Returns (vis_ri, mask_p, coh_ri, antp,
    antq)."""
    B, M, rows = coh_b.shape[0], coh_b.shape[1], coh_b.shape[-1]
    mp = pad_to(M, 8)
    rowsp = chunked_rowsp(rows, tile, max_rows)
    pad_r = rowsp - rows
    coh_ri = jnp.concatenate(
        [jnp.real(coh_b), jnp.imag(coh_b)], axis=-2
    ).astype(jnp.float32)
    coh_ri = jnp.pad(
        coh_ri, ((0, 0), (0, mp - M), (0, 0), (0, 0), (0, pad_r))
    ).reshape(B * mp, coh_b.shape[2], 8, rowsp)
    vis_ri = jnp.concatenate(
        [jnp.real(vis_b), jnp.imag(vis_b)], axis=-2
    ).astype(jnp.float32)
    vis_ri = jnp.pad(vis_ri, ((0, 0), (0, 0), (0, 0), (0, pad_r)))
    mask_p = jnp.pad(mask_b.astype(jnp.float32),
                     ((0, 0), (0, 0), (0, pad_r)))
    if valid is not None:
        mask_p = mask_p * jnp.asarray(valid, jnp.float32)[:, None, None]
    antp = jnp.pad(ant_p.astype(jnp.int32)[None, :], ((0, 0), (0, pad_r)))
    antq = jnp.pad(ant_q.astype(jnp.int32)[None, :], ((0, 0), (0, pad_r)))
    return vis_ri, mask_p, coh_ri, antp, antq


def unpack_gain_grads_batch(dre, dim, B: int, M: int, N: int):
    """Inverse of :func:`pack_gain_tables_batch` for cotangents:
    (4, B*mp, NPAD) pair -> (B, M, N, 2, 2) re/im arrays."""
    mp = dre.shape[1] // B
    out = []
    for d in (dre, dim):
        d = d.reshape(4, B, mp, NPAD)[:, :, :M, :N]
        out.append(jnp.transpose(d, (1, 2, 3, 0)).reshape(B, M, N, 2, 2))
    return out[0], out[1]


def unpack_gain_grads(dre, dim, M: int, N: int):
    """Inverse of :func:`pack_gain_tables` for cotangents:
    (4, mp*nc, NPAD) pair -> complex-as-pair (M, N, 2, 2) re/im
    arrays (nc=1 tables)."""
    dre = jnp.transpose(dre[:, :M, :N], (1, 2, 0)).reshape(M, N, 2, 2)
    dim = jnp.transpose(dim[:, :M, :N], (1, 2, 0)).reshape(M, N, 2, 2)
    return dre, dim


# Instrumented jitted entry for eager callers and bench: ``tile`` and
# ``max_rows`` are compile-time grid parameters, so changing either is
# a visible recompile in the obs/perf compile counter.
from sagecal_tpu.obs.perf import instrumented_jit  # noqa: E402

fused_predict_packed_chunked_jit = instrumented_jit(
    fused_predict_packed_chunked, name="fused_predict_packed_chunked",
    static_argnames=("tile", "max_rows"))

fused_cost_packed_chunked_jit = instrumented_jit(
    fused_cost_packed_chunked, name="fused_cost_packed_chunked",
    static_argnames=("tile", "max_rows"))
