"""Fused RIME predict Pallas kernel — the hot op of joint calibration.

Computes ``V(f, r) = sum_m Jp^m(r) C^m(f, r) Jq^m(r)^H`` (the full-model
predict of ``minimize_viz_full_pth``, ``/root/reference/src/lib/Dirac/
lmfit.c:692``; CUDA analog ``predict_model.cu``) in ONE pass over the
coherency stack.

Why a kernel: the XLA formulation in :func:`sagecal_tpu.solvers.sage.
predict_full_model` (one-hot gain matmuls + sixteen multiply-reduce
contractions) materializes ~15 buffer-scale intermediates in HBM —
measured 95 ms per forward at the north-star shape (62 stn / 100
clusters / 60 ts x 2 ch), an effective 8 GB/s against the 726 MB
coherency stack vs the chip's 819 GB/s.  The fused kernel streams each
coherency block through VMEM exactly once: per (row-tile, cluster-chunk)
grid step it

1. builds the station one-hot selectors in VMEM from the tile's antenna
   indices (re-built once per row tile),
2. expands per-row gains with four small MXU matmuls
   ``(4*MC, Npad) @ (Npad, T)``,
3. evaluates the 2x2 RIME products ``Jp (C Jq^H)`` as component
   arithmetic on ``(MC, T)`` vregs (VPU), and
4. accumulates the cluster-reduction into the revisited output block.

The backward pass is a second kernel with the same structure that
produces gain-table cotangents via the transposed one-hot matmuls
(``dtab += dJ @ onehot^T``) — the reference's ``mderiv.cu`` role.  Both
are wired into :func:`fused_predict_packed` with ``jax.custom_vjp``;
gradients flow to the gain tables only (the solver never differentiates
w.r.t. coherencies — they are per-tile constants, like the reference's
precalculated ``coh`` array).

Everything crosses the kernel boundary as REAL f32 (re/im packed on a
leading axis): the axon TPU runtime cannot transfer complex arrays, and
packed reals keep every buffer's minor-most axis long (rows), so the
TPU (8, 128) tiling pads nothing (core/types.py layout rationale).

Layout contracts:
  tab_re/tab_im: (M4p, Npad) gain tables, row ``4*m + comp`` with comp
    row-major [J00, J01, J10, J11]; M4p = 4*Mp, Mp = M padded to a
    multiple of MC, Npad = stations padded to 128.
  coh_ri: (Mp, F, 8, rowsp) packed coherencies, component axis
    [re XX, re XY, re YX, re YY, im XX, im XY, im YX, im YY].
  ant_p/ant_q: (1, rowsp) int32 station index per row.
  output model_ri: (F, 8, rowsp), same component packing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NPAD = 128  # station axis padded to one MXU/VPU lane tile
DEF_TILE = 512  # rows per grid step
DEF_MC = 8  # clusters per grid step (sublane-aligned)


def _use_interpret() -> bool:
    return jax.default_backend() not in ("tpu",)


# ---------------------------------------------------------------- forward


def _fwd_kernel(antp_ref, antq_ref, tabre_ref, tabim_ref, coh_ref, out_ref,
                ohp_ref, ohq_ref, *, F, MC, T):
    c = pl.program_id(1)

    @pl.when(c == 0)
    def _build_onehots():
        # (Npad, T) station selectors for this row tile; padded stations
        # (n >= N) never match an antenna index, padded rows carry
        # arbitrary gains but zero coherencies.  Keep everything 2D —
        # (1, T) blocks broadcast directly against the iota.
        n_iota = jax.lax.broadcasted_iota(jnp.int32, (NPAD, T), 0)
        ohp_ref[:] = (n_iota == antp_ref[:]).astype(jnp.float32)
        ohq_ref[:] = (n_iota == antq_ref[:]).astype(jnp.float32)

    # Gain expansion: (4*MC, Npad) @ (Npad, T) -> per-row gains (MXU).
    gp_re = jnp.dot(tabre_ref[:], ohp_ref[:], preferred_element_type=jnp.float32)
    gp_im = jnp.dot(tabim_ref[:], ohp_ref[:], preferred_element_type=jnp.float32)
    gq_re = jnp.dot(tabre_ref[:], ohq_ref[:], preferred_element_type=jnp.float32)
    gq_im = jnp.dot(tabim_ref[:], ohq_ref[:], preferred_element_type=jnp.float32)

    def comp(g, k):
        return g.reshape(MC, 4, T)[:, k, :]  # (MC, T)

    p_re = [comp(gp_re, k) for k in range(4)]
    p_im = [comp(gp_im, k) for k in range(4)]
    q_re = [comp(gq_re, k) for k in range(4)]
    q_im = [comp(gq_im, k) for k in range(4)]

    freq_acc = []
    for f in range(F):
        c_re = [coh_ref[:, f, k, :] for k in range(4)]
        c_im = [coh_ref[:, f, 4 + k, :] for k in range(4)]

        # A = C Jq^H: A_aj = sum_b C_ab conj(Jq_jb); 2x2 index ab = 2a+b.
        a_re, a_im = {}, {}
        for a in range(2):
            for j in range(2):
                re = im = 0.0
                for b in range(2):
                    cr, ci = c_re[2 * a + b], c_im[2 * a + b]
                    qr, qi = q_re[2 * j + b], q_im[2 * j + b]
                    # C * conj(Q)
                    re = re + cr * qr + ci * qi
                    im = im + ci * qr - cr * qi
                a_re[a, j], a_im[a, j] = re, im

        # V = Jp A: V_ij = sum_a Jp_ia A_aj, reduced over the MC axis.
        sums = [None] * 8
        for i in range(2):
            for j in range(2):
                vre = vim = 0.0
                for a in range(2):
                    pr, pi = p_re[2 * i + a], p_im[2 * i + a]
                    ar, ai = a_re[a, j], a_im[a, j]
                    vre = vre + pr * ar - pi * ai
                    vim = vim + pr * ai + pi * ar
                k = 2 * i + j
                sums[k] = jnp.sum(vre, axis=0, keepdims=True)  # (1, T)
                sums[4 + k] = jnp.sum(vim, axis=0, keepdims=True)
        freq_acc.append(jnp.concatenate(sums, axis=0))  # (8, T)
    acc = jnp.stack(freq_acc, axis=0)  # (F, 8, T) — one full-block store

    @pl.when(c == 0)
    def _init():
        out_ref[:] = acc

    @pl.when(c != 0)
    def _acc():
        out_ref[:] = out_ref[:] + acc


def _fused_predict_fwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q,
                            *, tile, mc):
    M4p, npad = tab_re.shape
    Mp, F, _, rowsp = coh_ri.shape
    assert npad == NPAD and M4p == 4 * Mp
    assert rowsp % tile == 0 and Mp % mc == 0, (rowsp, tile, Mp, mc)
    R, C = rowsp // tile, Mp // mc

    kernel = functools.partial(_fwd_kernel, F=F, MC=mc, T=tile)
    return pl.pallas_call(
        kernel,
        grid=(R, C),
        in_specs=[
            pl.BlockSpec((1, tile), lambda r, c: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), lambda r, c: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((4 * mc, NPAD), lambda r, c: (c, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((4 * mc, NPAD), lambda r, c: (c, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((mc, F, 8, tile), lambda r, c: (c, 0, 0, r),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((F, 8, tile), lambda r, c: (0, 0, r),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((F, 8, rowsp), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((NPAD, tile), jnp.float32),
            pltpu.VMEM((NPAD, tile), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(ant_p, ant_q, tab_re, tab_im, coh_ri)


# ---------------------------------------------------------------- backward


def _bwd_kernel(antp_ref, antq_ref, tabre_ref, tabim_ref, coh_ref, g_ref,
                dtabre_ref, dtabim_ref, *, F, MC, T):
    r = pl.program_id(1)

    # One-hots both orientations (rebuilt per step: r varies fastest).
    n_iota_nt = jax.lax.broadcasted_iota(jnp.int32, (NPAD, T), 0)
    ohp = (n_iota_nt == antp_ref[:]).astype(jnp.float32)
    ohq = (n_iota_nt == antq_ref[:]).astype(jnp.float32)

    gp_re = jnp.dot(tabre_ref[:], ohp, preferred_element_type=jnp.float32)
    gp_im = jnp.dot(tabim_ref[:], ohp, preferred_element_type=jnp.float32)
    gq_re = jnp.dot(tabre_ref[:], ohq, preferred_element_type=jnp.float32)
    gq_im = jnp.dot(tabim_ref[:], ohq, preferred_element_type=jnp.float32)

    def comp(g, k):
        return g.reshape(MC, 4, T)[:, k, :]

    p_re = [comp(gp_re, k) for k in range(4)]
    p_im = [comp(gp_im, k) for k in range(4)]
    q_re = [comp(gq_re, k) for k in range(4)]
    q_im = [comp(gq_im, k) for k in range(4)]

    # Accumulate dJp / dJq on (MC, T) vregs over freq.
    djp_re = [jnp.zeros((MC, T), jnp.float32) for _ in range(4)]
    djp_im = [jnp.zeros((MC, T), jnp.float32) for _ in range(4)]
    djq_re = [jnp.zeros((MC, T), jnp.float32) for _ in range(4)]
    djq_im = [jnp.zeros((MC, T), jnp.float32) for _ in range(4)]

    for f in range(F):
        c_re = [coh_ref[:, f, k, :] for k in range(4)]
        c_im = [coh_ref[:, f, 4 + k, :] for k in range(4)]
        g_re = [g_ref[f, k:k + 1, :] for k in range(4)]  # (1, T)
        g_im = [g_ref[f, 4 + k:5 + k, :] for k in range(4)]

        # Recompute A = C Jq^H.
        a_re, a_im = {}, {}
        for a in range(2):
            for j in range(2):
                re = im = 0.0
                for b in range(2):
                    cr, ci = c_re[2 * a + b], c_im[2 * a + b]
                    qr, qi = q_re[2 * j + b], q_im[2 * j + b]
                    re = re + cr * qr + ci * qi
                    im = im + ci * qr - cr * qi
                a_re[a, j], a_im[a, j] = re, im

        # dJp_ia += sum_j g_ij * conj(A_aj)
        for i in range(2):
            for a in range(2):
                re = im = 0.0
                for j in range(2):
                    gr, gi = g_re[2 * i + j], g_im[2 * i + j]
                    ar, ai = a_re[a, j], a_im[a, j]
                    re = re + gr * ar + gi * ai
                    im = im + gi * ar - gr * ai
                djp_re[2 * i + a] = djp_re[2 * i + a] + re
                djp_im[2 * i + a] = djp_im[2 * i + a] + im

        # dA_aj = sum_i conj(Jp_ia) g_ij ; dJq_jb += sum_a conj(dA_aj) C_ab
        da_re, da_im = {}, {}
        for a in range(2):
            for j in range(2):
                re = im = 0.0
                for i in range(2):
                    pr, pi = p_re[2 * i + a], p_im[2 * i + a]
                    gr, gi = g_re[2 * i + j], g_im[2 * i + j]
                    re = re + pr * gr + pi * gi
                    im = im + pr * gi - pi * gr
                da_re[a, j], da_im[a, j] = re, im
        for j in range(2):
            for b in range(2):
                re = im = 0.0
                for a in range(2):
                    dr, di = da_re[a, j], da_im[a, j]
                    cr, ci = c_re[2 * a + b], c_im[2 * a + b]
                    re = re + dr * cr + di * ci
                    im = im + dr * ci - di * cr
                djq_re[2 * j + b] = djq_re[2 * j + b] + re
                djq_im[2 * j + b] = djq_im[2 * j + b] + im

    # Scatter to stations: dtab[m4, n] += dJ (MC4, T) @ onehot^T (T, Npad).
    djp_re_m = jnp.stack(djp_re, axis=1).reshape(4 * MC, T)
    djp_im_m = jnp.stack(djp_im, axis=1).reshape(4 * MC, T)
    djq_re_m = jnp.stack(djq_re, axis=1).reshape(4 * MC, T)
    djq_im_m = jnp.stack(djq_im, axis=1).reshape(4 * MC, T)
    dre = (jnp.dot(djp_re_m, ohp.T, preferred_element_type=jnp.float32)
           + jnp.dot(djq_re_m, ohq.T, preferred_element_type=jnp.float32))
    dim = (jnp.dot(djp_im_m, ohp.T, preferred_element_type=jnp.float32)
           + jnp.dot(djq_im_m, ohq.T, preferred_element_type=jnp.float32))

    @pl.when(r == 0)
    def _init():
        dtabre_ref[:] = dre
        dtabim_ref[:] = dim

    @pl.when(r != 0)
    def _acc():
        dtabre_ref[:] = dtabre_ref[:] + dre
        dtabim_ref[:] = dtabim_ref[:] + dim


def _fused_predict_bwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q, g_ri,
                            *, tile, mc):
    M4p, npad = tab_re.shape
    Mp, F, _, rowsp = coh_ri.shape
    R, C = rowsp // tile, Mp // mc

    kernel = functools.partial(_bwd_kernel, F=F, MC=mc, T=tile)
    return pl.pallas_call(
        kernel,
        grid=(C, R),
        in_specs=[
            pl.BlockSpec((1, tile), lambda c, r: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), lambda c, r: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((4 * mc, NPAD), lambda c, r: (c, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((4 * mc, NPAD), lambda c, r: (c, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((mc, F, 8, tile), lambda c, r: (c, 0, 0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((F, 8, tile), lambda c, r: (0, 0, r),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((4 * mc, NPAD), lambda c, r: (c, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((4 * mc, NPAD), lambda c, r: (c, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M4p, NPAD), jnp.float32),
            jax.ShapeDtypeStruct((M4p, NPAD), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(ant_p, ant_q, tab_re, tab_im, coh_ri, g_ri)


# ------------------------------------------------------------ public API


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def fused_predict_packed(tab_re, tab_im, coh_ri, ant_p, ant_q,
                         tile=DEF_TILE, mc=DEF_MC):
    """Full-model RIME predict, packed-real layout (module docstring).

    Differentiable w.r.t. ``tab_re``/``tab_im`` only — coherencies are
    per-tile constants in every solver path (wrap in
    ``jax.lax.stop_gradient`` at call sites for clarity)."""
    return _fused_predict_fwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q,
                                   tile=tile, mc=mc)


def _vjp_fwd(tab_re, tab_im, coh_ri, ant_p, ant_q, tile, mc):
    out = _fused_predict_fwd_impl(tab_re, tab_im, coh_ri, ant_p, ant_q,
                                  tile=tile, mc=mc)
    return out, (tab_re, tab_im, coh_ri, ant_p, ant_q)


def _vjp_bwd(tile, mc, res, g_ri):
    tab_re, tab_im, coh_ri, ant_p, ant_q = res
    dre, dim = _fused_predict_bwd_impl(
        tab_re, tab_im, coh_ri, ant_p, ant_q, g_ri, tile=tile, mc=mc
    )
    return dre, dim, None, None, None


fused_predict_packed.defvjp(_vjp_fwd, _vjp_bwd)


# --------------------------------------------------- packing conveniences


def pad_to(n: int, mult: int) -> int:
    return -(-n // mult) * mult


def pack_gain_tables(jones, mp: int):
    """(M, N, 2, 2) complex Jones -> (tab_re, tab_im) of shape
    (4*mp, NPAD) f32, rows ``4*m + comp`` comp row-major."""
    M, N = jones.shape[0], jones.shape[1]
    flat = jones.reshape(M, N, 4)  # row-major J00, J01, J10, J11
    tab = jnp.transpose(flat, (0, 2, 1)).reshape(4 * M, N)
    tab = jnp.pad(tab, ((0, 4 * mp - 4 * M), (0, NPAD - N)))
    return (jnp.real(tab).astype(jnp.float32),
            jnp.imag(tab).astype(jnp.float32))


def unpack_gain_grads(dre, dim, M: int, N: int):
    """Inverse of :func:`pack_gain_tables` for cotangents: (4*mp, NPAD)
    pair -> complex-as-pair (M, N, 2, 2) re/im arrays."""
    dre = dre[: 4 * M, :N].reshape(M, 4, N)
    dim = dim[: 4 * M, :N].reshape(M, 4, N)
    dre = jnp.transpose(dre, (0, 2, 1)).reshape(M, N, 2, 2)
    dim = jnp.transpose(dim, (0, 2, 1)).reshape(M, N, 2, 2)
    return dre, dim
