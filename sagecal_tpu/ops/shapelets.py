"""Shapelet (Gauss-Hermite) source models: UV- and image-plane bases.

Redesign of ``/root/reference/src/lib/Radio/shapelet.c``.  The reference
evaluates Hermite polynomials with a doubly-recursive function per uv
point per mode (``H_e``, shapelet.c:31) inside the per-baseline thread
loop; here the 1-D basis is one ``lax.scan`` recurrence producing all
``n0`` orders for every point at once, and the 2-D mode tensor is an
outer product — the mode sum over n0^2 coefficients becomes a matmul
over points.

Math (verified against shapelet.c:49-188):
- 1-D dimensionless basis  phi_n(x) = H_n(x) exp(-x^2/2) /
  sqrt(2^(n+1) n!)   (shapelet.c:88-97; physicists' Hermite).
- 2-D UV mode (n1,n2) at (u,v):  sign * phi_n1(u*beta) * phi_n2(v*beta),
  real when n1+n2 even (sign (-1)^((n1+n2)/2)), imaginary when odd
  (sign (-1)^((n1+n2-1)/2)) — the i^(n1+n2) factor of the Fourier
  transform of the image-plane basis.
- source contribution: 2*pi * a*b * sum_modes  c_m * mode_m evaluated at
  the projected, (1/eX,1/eY,eP)-transformed, u-negated uv point
  (shapelet.c:141-188; uv supplied in wavelengths, predict.c:200).
- image-plane basis (for the ``restore`` tool):  phi_n(x/beta) /
  sqrt(beta) with the same normalization.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


def hermite_basis_1d(x: jax.Array, n0: int) -> jax.Array:
    """phi_n(x) = H_n(x) exp(-x^2/2)/sqrt(2^(n+1) n!) for n < n0.

    x: (...,) -> (..., n0).  One scan over orders; each step is O(points).
    """
    expv = jnp.exp(-0.5 * x * x)
    # normalization 1/sqrt(2^(n+1) n!)
    lognorm = np.array(
        [-0.5 * ((n + 1) * math.log(2.0) + math.lgamma(n + 1)) for n in range(n0)]
    )
    norm = jnp.asarray(np.exp(lognorm), x.dtype)
    if n0 == 1:
        return (expv * norm[0])[..., None]

    def step(carry, n):
        h_nm1, h_nm2 = carry
        h_n = 2.0 * x * h_nm1 - 2.0 * (n - 1).astype(x.dtype) * h_nm2
        return (h_n, h_nm1), h_n

    h0 = jnp.ones_like(x)
    h1 = 2.0 * x
    _, hs = jax.lax.scan(step, (h1, h0), jnp.arange(2, n0))
    H = jnp.concatenate(
        [h0[None], h1[None], hs], axis=0
    )  # (n0, ...)
    H = jnp.moveaxis(H, 0, -1)  # (..., n0)
    return H * expv[..., None] * norm


def uv_mode_signs(n0: int):
    """(sign, is_imag) arrays of shape (n0, n0) for modes (n1, n2)
    (shapelet.c:110-127); index [n2, n1] matches the reference's
    column-major mode ordering modes[n2*n0+n1]."""
    n1 = np.arange(n0)[None, :]
    n2 = np.arange(n0)[:, None]
    s = n1 + n2
    is_imag = (s % 2) == 1
    sign = np.where(is_imag, (-1.0) ** (((s - 1) // 2) % 2), (-1.0) ** ((s // 2) % 2))
    return sign, is_imag


def uv_mode_vectors(u: jax.Array, v: jax.Array, beta: float, n0: int) -> jax.Array:
    """Complex mode tensor (..., n0*n0): mode (n1,n2) at flat index
    n2*n0+n1 (``calculate_uv_mode_vectors_scalar``, shapelet.c:49-137,
    with the real/imag parity folded into a complex value)."""
    pu = hermite_basis_1d(u * beta, n0)  # (..., n0) over n1
    pv = hermite_basis_1d(v * beta, n0)  # (..., n0) over n2
    prod = pv[..., :, None] * pu[..., None, :]  # (..., n2, n1)
    sign, is_imag = uv_mode_signs(n0)
    fac = jnp.asarray(np.where(is_imag, 1j, 1.0) * sign, jnp.complex64 if u.dtype == jnp.float32 else jnp.complex128)
    out = prod * fac
    return out.reshape(out.shape[:-2] + (n0 * n0,))


@struct.dataclass
class ShapeletModel:
    """One shapelet source's model (``exinfo_shapelet``,
    Dirac_common.h:74-85): modes c_m (n0*n0,), scale beta, optional
    linear transform (eX, eY, eP) and tangent-plane projection angles."""

    modes: jax.Array  # (n0*n0,)
    beta: float = struct.field(pytree_node=False)
    n0: int = struct.field(pytree_node=False)
    eX: float = struct.field(pytree_node=False, default=1.0)
    eY: float = struct.field(pytree_node=False, default=1.0)
    eP: float = struct.field(pytree_node=False, default=0.0)


def shapelet_uv_contrib(
    u, v, w, model: ShapeletModel,
    cxi=1.0, sxi=0.0, cphi=1.0, sphi=0.0, use_projection: bool = True,
):
    """Complex visibility-plane factor of a shapelet source at uv points
    given in WAVELENGTHS (``shapelet_contrib``, shapelet.c:141-188).

    u, v, w: (...,) arrays.  Returns complex (...,).
    """
    if use_projection:
        up = -u * cxi + v * cphi * sxi - w * sphi * sxi
        vp = -u * sxi - v * cphi * cxi + w * sphi * cxi
    else:
        up, vp = u, v
    a = 1.0 / model.eX
    b = 1.0 / model.eY
    cp, sp = math.cos(model.eP), math.sin(model.eP)
    ut = a * (cp * up - sp * vp)
    vt = b * (sp * up + cp * vp)
    # decomposition of f(-l, m): negate u
    Av = uv_mode_vectors(-ut, vt, model.beta, model.n0)  # (..., n0^2) complex
    s = Av @ model.modes.astype(Av.dtype)
    return 2.0 * jnp.pi * a * b * s


def image_mode_matrix(l, m, beta: float, n0: int) -> jax.Array:
    """Image-plane basis matrix (..., n0*n0): mode (n1,n2) evaluated at
    (l, m)/beta, normalized by 1/beta (``shapelet_modes`` role;
    shapelet.c image-plane half).  Used by the restore tool and the
    spatial-regularization basis."""
    pu = hermite_basis_1d(l / beta, n0) / jnp.sqrt(jnp.asarray(beta, l.dtype))
    pv = hermite_basis_1d(m / beta, n0) / jnp.sqrt(jnp.asarray(beta, l.dtype))
    prod = pv[..., :, None] * pu[..., None, :]
    return prod.reshape(prod.shape[:-2] + (n0 * n0,))


def shapelet_product_tensor(
    L: int, M: int, N: int, alpha: float, beta: float, gamma: float,
    normalize: bool = True,
) -> np.ndarray:
    """1-D shapelet multiplication tensor B[l; m, n]: the decomposition
    of phi_m(x/beta) * phi_n(x/gamma) onto phi_l(x/alpha)
    (``shapelet_product_tensor``, shapelet.c:640-692; triple-Hermite
    recurrence ``L_mat`` shapelet.c:533-628 — standard shapelet algebra,
    Refregier 2003 eq. set).  Host-side numpy, precomputed once.

    Returns (L, M, N), normalized by (L*M*N)^(1/8)/||B||_F like the
    reference (the spatial-model amplitude scale is arbitrary).
    """
    nu = 1.0 / math.sqrt(alpha ** -2 + beta ** -2 + gamma ** -2)
    a, b, c = (math.sqrt(2.0) * nu / s for s in (alpha, beta, gamma))
    # H recurrence: H(0,0,0)=1; zero for odd l+m+n;
    # H(l+1,m,n) = 2l(a^2-1)H(l-1,m,n) + 2m a b H(l,m-1,n) + 2n a c H(l,m,n-1)
    # (+ cyclic versions raising m and n)
    H = np.zeros((L + 1, M + 1, N + 1))
    H[0, 0, 0] = 1.0

    def val(l, m, n):
        if l < 0 or m < 0 or n < 0:
            return 0.0
        return H[l, m, n]

    for tot in range(0, L + M + N, 2):
        # fill all entries with l+m+n == tot+2 from entries at tot
        for l in range(0, L + 1):
            for m in range(0, M + 1):
                n = tot + 2 - l - m
                if n < 0 or n > N:
                    continue
                # raise whichever index is raisable; use the n-raising
                # relation when n>0, else m, else l
                if n > 0:
                    H[l, m, n] = (
                        2.0 * (n - 1) * (c * c - 1.0) * val(l, m, n - 2)
                        + 2.0 * l * c * a * val(l - 1, m, n - 1)
                        + 2.0 * m * c * b * val(l, m - 1, n - 1)
                    )
                elif m > 0:
                    H[l, m, n] = (
                        2.0 * (m - 1) * (b * b - 1.0) * val(l, m - 2, n)
                        + 2.0 * n * b * c * val(l, m, n - 1)
                        + 2.0 * l * b * a * val(l - 1, m - 1, n)
                    )
                else:
                    H[l, m, n] = (
                        2.0 * (l - 1) * (a * a - 1.0) * val(l - 2, m, n)
                        + 2.0 * m * a * b * val(l - 1, m - 1, n)
                        + 2.0 * n * a * c * val(l - 1, m, n - 1)
                    )
    B = np.zeros((L, M, N))
    for l in range(L):
        for m in range(M):
            for n in range(N):
                if (l + m + n) % 2 == 0:
                    B[l, m, n] = nu * H[l, m, n] / math.sqrt(
                        2.0 ** (l + m + n) * math.sqrt(math.pi)
                        * math.factorial(l) * math.factorial(m)
                        * math.factorial(n) * alpha * beta * gamma
                    )
    # our basis functions have norm^2 = sqrt(pi)/2 (not 1), so the exact
    # product-decomposition coefficient is (2/sqrt(pi)) * <fg, B_l> =
    # pi^(1/4) * the raw formula value (verified against quadrature)
    B = B * math.pi ** 0.25
    # the reference rescales by (LMN)^(1/8)/||B||_F (shapelet.c:685-688)
    # — an arbitrary overall scale absorbed by the fitted spatial model;
    # normalize=False keeps the EXACT product decomposition (used by the
    # image-plane identity test)
    if normalize:
        nrm = np.linalg.norm(B)
        if nrm > 0:
            B = B * ((L * M * N) ** 0.125 / nrm)
    return B


def shapelet_product_jones(T, f, g, hermitian: bool = False):
    """2-D Jones-valued shapelet product h = f x g(^H)
    (``shapelet_product_jones``, shapelet.c:864-960): every mode
    coefficient of f/g/h is a 2x2 Jones matrix; the 2-D product tensor
    is the Kronecker square of the 1-D tensor ``T`` (L, M, N).

    f: (..., M*M, 2, 2) with flat mode index m2*M + m1 (column-major 2-D
    modes, matching :func:`uv_mode_vectors`); g: (..., N*N, 2, 2);
    returns h: (..., L*L, 2, 2) with flat index l2*L + l1.
    """
    L, M, N = T.shape
    T = jnp.asarray(T)
    fm = f.reshape(f.shape[:-3] + (M, M, 2, 2))  # [m2, m1]
    gm = g.reshape(g.shape[:-3] + (N, N, 2, 2))
    if hermitian:
        gm = jnp.conj(jnp.swapaxes(gm, -1, -2))
    # FG[..., m2, m1, n2, n1, i, j] = f[m2,m1] @ g(H)[n2,n1]
    FG = jnp.einsum("...abik,...cdkj->...abcdij", fm, gm)
    h = jnp.einsum("lac,kbd,...abcdij->...lkij", T.astype(FG.dtype), T.astype(FG.dtype), FG)
    return h.reshape(h.shape[:-4] + (L * L, 2, 2))


def hermite_product_tensor(n0a: int, n0b: int, n0c: int, nquad: int = 64):
    """3-way Hermite-basis product integrals T[i,j,k] =
    int phi_i(x) phi_j(x) phi_k(x) dx via Gauss-Hermite quadrature
    (the ``shapelet_product`` tensors, shapelet.c:523-553, used to apply
    a spatial model Z to a shapelet diffuse sky).  Host-side numpy
    (precomputed once), returns (n0a, n0b, n0c)."""
    x, wq = np.polynomial.hermite.hermgauss(nquad)
    # our phi_n(x) includes exp(-x^2/2); quadrature weight exp(-x^2) is
    # the product of two of the three gaussians; multiply back the third
    # explicitly: phi_i phi_j phi_k = H~_i H~_j H~_k exp(-3x^2/2)
    def phi(n, xx):
        H = np.polynomial.hermite.hermval(xx, np.eye(max(n0a, n0b, n0c))[n])
        return H / np.sqrt(2.0 ** (n + 1) * math.factorial(n))

    T = np.zeros((n0a, n0b, n0c))
    ex = np.exp(-0.5 * x * x)  # the third gaussian factor
    for i in range(n0a):
        pi = phi(i, x)
        for j in range(n0b):
            pj = phi(j, x)
            for k in range(n0c):
                pk = phi(k, x)
                T[i, j, k] = np.sum(wq * pi * pj * pk * ex)
    return jnp.asarray(T)
