"""Special functions evaluated on-device.

The reference calls libm ``j0``/``j1`` for ring/disk sources
(``/root/reference/src/lib/Radio/predict.c:73,90``).  TPUs have no Bessel
primitives, so we evaluate the classic Abramowitz & Stegun 9.4.1-9.4.6
rational/asymptotic approximations (|error| < 5e-8 over the full range) —
pure polynomial + trig, which the VPU executes branch-free via ``where``.
"""

from __future__ import annotations

import jax.numpy as jnp


def bessel_j0(x):
    """J0(x) for real x (A&S 9.4.1 / 9.4.3)."""
    ax = jnp.abs(x)
    # small branch: t = (x/3)^2
    t = (ax / 3.0) ** 2
    small = (
        1.0
        + t * (-2.2499997
        + t * (1.2656208
        + t * (-0.3163866
        + t * (0.0444479
        + t * (-0.0039444
        + t * 0.0002100)))))
    )
    # large branch: s = 3/x
    safe = jnp.maximum(ax, 3.0)
    s = 3.0 / safe
    f0 = (
        0.79788456
        + s * (-0.00000077
        + s * (-0.00552740
        + s * (-0.00009512
        + s * (0.00137237
        + s * (-0.00072805
        + s * 0.00014476)))))
    )
    th0 = (
        safe
        - 0.78539816
        + s * (-0.04166397
        + s * (-0.00003954
        + s * (0.00262573
        + s * (-0.00054125
        + s * (-0.00029333
        + s * 0.00013558)))))
    )
    large = f0 * jnp.cos(th0) / jnp.sqrt(safe)
    return jnp.where(ax < 3.0, small, large)


def bessel_j1(x):
    """J1(x) for real x (A&S 9.4.4 / 9.4.6); odd in x."""
    ax = jnp.abs(x)
    t = (ax / 3.0) ** 2
    small = ax * (
        0.5
        + t * (-0.56249985
        + t * (0.21093573
        + t * (-0.03954289
        + t * (0.00443319
        + t * (-0.00031761
        + t * 0.00001109)))))
    )
    safe = jnp.maximum(ax, 3.0)
    s = 3.0 / safe
    f1 = (
        0.79788456
        + s * (0.00000156
        + s * (0.01659667
        + s * (0.00017105
        + s * (-0.00249511
        + s * (0.00113653
        + s * (-0.00020033))))))
    )
    th1 = (
        safe
        - 2.35619449
        + s * (0.12499612
        + s * (0.00005650
        + s * (-0.00637879
        + s * (0.00074348
        + s * (0.00079824
        + s * (-0.00029166))))))
    )
    large = f1 * jnp.cos(th1) / jnp.sqrt(safe)
    return jnp.sign(x) * jnp.where(ax < 3.0, small, large)


def sinc_abs(x):
    """|sin(x)/x| with the x==0 limit, the reference's bandwidth-smearing
    factor (predict.c:152-158)."""
    safe = jnp.where(x == 0.0, 1.0, x)
    return jnp.abs(jnp.where(x == 0.0, 1.0, jnp.sin(safe) / safe))
