"""Coordinate / time transforms: ITRF, az/el, GMST, precession.

Reimplements ``/root/reference/src/lib/Radio/transforms.c`` (NOVAS- and
Vallado-derived formulas) as vectorized numpy/jax functions.  These run
host-side during setup (beam pointing, source precession), so plain
numpy is used; each also works on jnp arrays for jitted beam paths.
"""

from __future__ import annotations

import numpy as np

ASEC2RAD = 4.848136811095359935899141e-6  # arcsec -> rad (NOVAS constant)


def xyz2llh(x, y, z):
    """ITRF2000 (m) -> (longitude, latitude [rad], height [m]).

    WGS84 ellipsoid, single-iteration Bowring approximation
    (transforms.c:35-88).
    """
    a = 6378137.0
    f = 1.0 / 298.257223563
    b = (1.0 - f) * a
    e2 = 2 * f - f * f
    ep2 = (a * a - b * b) / (b * b)
    p = np.sqrt(x * x + y * y)
    lon = np.arctan2(y, x)
    theta = np.arctan(z * a / (p * b))
    st, ct = np.sin(theta), np.cos(theta)
    lat = np.arctan((z + ep2 * b * st**3) / (p - e2 * a * ct**3))
    sl, cl = np.sin(lat), np.cos(lat)
    r = a / np.sqrt(1.0 - e2 * sl * sl)
    h = p / cl - r
    return lon, lat, h


def jd2gmst(time_jd):
    """JD (days) -> Greenwich Mean Sidereal Time angle (degrees)
    (transforms.c:138-147, Vallado eq; Horner form)."""
    t = (np.asarray(time_jd) - 2451545.0) / 36525.0
    theta = 67310.54841 + t * (
        (876600.0 * 3600.0 + 8640184.812866) + t * (0.093104 - (6.2e-5) * t)
    )
    # reference: fmod(theta, 86400*sign(theta))/240 then fmod 360
    theta = np.fmod(theta, 86400.0 * np.sign(theta)) / 240.0
    return np.fmod(theta, 360.0)


def radec2azel_gmst(ra, dec, longitude, latitude, thetaGMST):
    """(ra, dec) [rad] -> (az, el) [rad] given GMST angle in degrees
    (transforms.c:156-180).  Vectorized over any broadcastable shapes."""
    thetaLST = thetaGMST + np.degrees(longitude)
    LHA = np.fmod(thetaLST - np.degrees(ra), 360.0)
    sl, cl = np.sin(latitude), np.cos(latitude)
    sd, cd = np.sin(dec), np.cos(dec)
    sh, ch = np.sin(np.radians(LHA)), np.cos(np.radians(LHA))
    tmp = sl * sd + cl * cd * ch
    el = np.arcsin(tmp)
    se, ce = np.sin(el), np.cos(el)
    az = np.fmod(np.arctan2(-sh * cd / ce, (sd - se * sl) / (ce * cl)), 2.0 * np.pi)
    az = np.where(az < 0, az + 2.0 * np.pi, az)
    return az, el


def radec2azel(ra, dec, longitude, latitude, time_jd):
    """(ra, dec) [rad] at JD -> (az, el) [rad] (transforms.c:100-130)."""
    return radec2azel_gmst(ra, dec, longitude, latitude, jd2gmst(time_jd))


def get_precession_params(jd_tdb2):
    """Precession rotation matrix J2000 -> epoch jd_tdb2: (3, 3).

    Capitaine et al. (2003) 4-angle formulation
    (transforms.c:186-266; column-major Tr in the reference — here a
    standard row-major matrix, applied as Tr @ pos).
    """
    eps0 = 84381.406
    t = (jd_tdb2 - 2451545.0) / 36525.0
    psia = ((((-0.0000000951 * t + 0.000132851) * t - 0.00114045) * t - 1.0790069) * t
            + 5038.481507) * t
    omegaa = ((((0.0000003337 * t - 0.000000467) * t - 0.00772503) * t + 0.0512623) * t
              - 0.025754) * t + eps0
    chia = ((((-0.0000000560 * t + 0.000170663) * t - 0.00121197) * t - 2.3814292) * t
            + 10.556403) * t
    eps0 = eps0 * ASEC2RAD
    psia = psia * ASEC2RAD
    omegaa = omegaa * ASEC2RAD
    chia = chia * ASEC2RAD
    sa, ca = np.sin(eps0), np.cos(eps0)
    sb, cb = np.sin(-psia), np.cos(-psia)
    sc, cc = np.sin(-omegaa), np.cos(-omegaa)
    sd, cd = np.sin(chia), np.cos(chia)
    # R3(chi) R1(-omega) R3(-psi) R1(eps0); rows match transforms.c Tr
    # layout read column-major (Tr[0],Tr[3],Tr[6] = first row).
    return np.array(
        [
            [cd * cb - sb * sd * cc,
             cd * sb * ca + sd * cc * cb * ca - sa * sd * sc,
             cd * sb * sa + sd * cc * cb * sa + ca * sd * sc],
            [-sd * cb - sb * cd * cc,
             -sd * sb * ca + cd * cc * cb * ca - sa * cd * sc,
             -sd * sb * sa + cd * cc * cb * sa + ca * cd * sc],
            [sb * sc,
             -sc * cb * ca - sa * cc,
             -sc * cb * sa + cc * ca],
        ]
    )


def precess_radec(ra0, dec0, Tr):
    """Precess J2000 (ra0, dec0) [rad] by matrix Tr (transforms.c:268-291).

    NOTE the reference's unconventional spherical convention: position
    vector (cos(ra) sin(dec), sin(ra) sin(dec), cos(dec)) — dec measured
    from the pole — and dec from arctan(rho/z); reproduced verbatim so
    precessed sky models match the reference's byte-for-byte.
    """
    ra0 = np.asarray(ra0)
    dec0 = np.asarray(dec0)
    pos1 = np.stack(
        [np.cos(ra0) * np.sin(dec0), np.sin(ra0) * np.sin(dec0),
         np.broadcast_to(np.cos(dec0), ra0.shape)], axis=-1
    )
    pos2 = pos1 @ np.asarray(Tr).T
    ra = np.arctan2(pos2[..., 1], pos2[..., 0])
    dec = np.arctan(
        np.sqrt(pos2[..., 0] ** 2 + pos2[..., 1] ** 2) / pos2[..., 2]
    )
    return ra, dec


def radec_to_lmn(ra, dec, ra0, dec0):
    """Direction cosines (l, m, n-1) of (ra, dec) about phase center
    (ra0, dec0) — the conversion at readsky.c:343-346."""
    sd, cd = np.sin(dec), np.cos(dec)
    sd0, cd0 = np.sin(dec0), np.cos(dec0)
    dra = ra - ra0
    l = cd * np.sin(dra)
    m = sd * cd0 - cd * sd0 * np.cos(dra)
    n = sd * sd0 + cd * cd0 * np.cos(dra)
    return l, m, n - 1.0


def lmn_to_radec(ll, mm, ra0, dec0):
    """Inverse of :func:`radec_to_lmn`: sky coordinates of direction
    cosines (l, m) about phase center (ra0, dec0).  Needed by the
    beam-aware predict path, which evaluates az/el per source from
    (ra, dec) while the source batches carry only lmn."""
    ll = np.asarray(ll)
    mm = np.asarray(mm)
    n = np.sqrt(np.maximum(1.0 - ll * ll - mm * mm, 0.0))
    sd0, cd0 = np.sin(dec0), np.cos(dec0)
    dec = np.arcsin(np.clip(mm * cd0 + n * sd0, -1.0, 1.0))
    ra = ra0 + np.arctan2(ll, n * cd0 - mm * sd0)
    return ra, dec


def precess_radec_equatorial(ra, dec, Tr):
    """Precess J2000 (ra, dec) [rad] with the STANDARD equatorial
    spherical convention — the application path's source/pointing
    precession (``Data::precess_source_locations``,
    src/MS/data.cpp:1616-1645, casacore IAU2000).  The casacore
    version composes precession with nutation; the nutation term
    (<= ~9 arcsec) is omitted here, small against the ~20 arcmin/26 yr
    precession it corrects.  Contrast :func:`precess_radec`, which
    reproduces transforms.c:268's pole-referenced convention
    byte-for-byte for the sky-model path."""
    ra = np.asarray(ra, np.float64)
    dec = np.asarray(dec, np.float64)
    pos = np.stack(
        [np.cos(dec) * np.cos(ra), np.cos(dec) * np.sin(ra),
         np.broadcast_to(np.sin(dec), np.shape(ra))], axis=-1
    )
    p2 = pos @ np.asarray(Tr).T
    ra2 = np.arctan2(p2[..., 1], p2[..., 0])
    dec2 = np.arcsin(np.clip(p2[..., 2], -1.0, 1.0))
    return ra2, dec2
