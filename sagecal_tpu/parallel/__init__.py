"""Distributed / multi-frequency consensus layer (mesh-parallel ADMM)."""
