"""Consensus-constrained local calibration: the ADMM "x-step".

Redesign of ``sagefit_visibilities_admm`` (``/root/reference/src/lib/
Dirac/admm_solve.c:221``): an EM pass over clusters where each
per-cluster solve minimizes the data misfit PLUS the scaled-Lagrangian
consensus terms ``y^T (J - BZ) + rho/2 ||J - BZ||^2`` (cost contract
Dirac.h:1182-1195).  The reference dispatches to RTR/NSD/LM ADMM
variants per solver mode; here the augmented terms enter the batched
LM's normal equations exactly (they are quadratic), so one lock-step
solver covers all chunks, and the EM structure is the shared
:func:`sagecal_tpu.solvers.sage.em_residual_scan`.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from sagecal_tpu.core.types import VisData
from sagecal_tpu.solvers.lm import LMConfig, _residual_rows, lm_solve
from sagecal_tpu.solvers.robust import update_w_and_nu
from sagecal_tpu.solvers.sage import (
    ClusterData,
    _res_norm,
    em_residual_scan,
    predict_full_model,
)


class AdmmLocalResult(NamedTuple):
    p: jax.Array  # (M, nchunk_max, 8N)
    res_0: jax.Array
    res_1: jax.Array


def admm_sagefit(
    data: VisData,
    cdata: ClusterData,
    p0: jax.Array,
    Y: jax.Array,
    BZ: jax.Array,
    rho: jax.Array,
    max_emiter: int = 1,
    lm_config: LMConfig = LMConfig(),
    robust_nu: Optional[float] = None,
) -> AdmmLocalResult:
    """One worker's ADMM x-update for one tile.

    Args:
      p0, Y, BZ: (M, nchunk_max, 8N) real — current solution, scaled
        Lagrange multipliers, and consensus target B_f Z (the same BZ is
        applied to every hybrid chunk of a cluster, as in
        rtr_solve_robust_admm).
      rho: (M,) per-cluster penalties (already fratio-scaled by the
        caller, sagecal_master.cpp:709-723).
      robust_nu: optional Student's-t nu — when given, each cluster solve
        is IRLS-weighted by w = (nu+1)/(nu+e^2) from the residual at the
        incoming solution (the robust ADMM path's E-step).
    """
    rows, F = data.vis.shape[0], data.vis.shape[1]
    nreal = rows * F * 8

    full0 = predict_full_model(p0, cdata, data)
    res_0 = _res_norm(data.vis - full0, data.mask, nreal)

    mask8 = jnp.repeat(data.mask, 8, axis=-1) if robust_nu is not None else None

    def solve_one(xeff, coh_k, cmap_k, p_k, extras_k):
        y_k, bz_k, rho_k = extras_k
        if robust_nu is not None:
            ed = _residual_rows(
                p_k, coh_k, xeff, data.mask, data.ant_p, data.ant_q, cmap_k, None
            )
            sqrt_w, _ = update_w_and_nu(
                ed, jnp.asarray(robust_nu, p_k.dtype), mask=mask8
            )
        else:
            sqrt_w = None
        res = lm_solve(
            xeff, coh_k, data.mask, data.ant_p, data.ant_q, cmap_k, p_k,
            lm_config, sqrt_weights=sqrt_w,
            admm_y=y_k, admm_bz=bz_k, admm_rho=rho_k,
        )
        return res.p, None

    p = p0
    for _ in range(max_emiter):
        p, _ = em_residual_scan(data, cdata, p, (Y, BZ, rho), solve_one)

    full1 = predict_full_model(p, cdata, data)
    res_1 = _res_norm(data.vis - full1, data.mask, nreal)
    return AdmmLocalResult(p=p, res_0=res_0, res_1=res_1)


def admm_dual_update(Y, p, BZ, rho):
    """Y <- Y + rho (J - BZ) (sagecal_slave.cpp:831): the scaled dual
    ascent step.  Shapes (M, nchunk_max, 8N); rho (M,)."""
    return Y + rho[:, None, None] * (p - BZ)
