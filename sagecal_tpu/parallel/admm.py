"""Consensus-constrained local calibration: the ADMM "x-step".

Redesign of ``sagefit_visibilities_admm`` (``/root/reference/src/lib/
Dirac/admm_solve.c:221``): an EM pass over clusters where each
per-cluster solve minimizes the data misfit PLUS the scaled-Lagrangian
consensus terms ``y^T (J - BZ) + rho/2 ||J - BZ||^2`` (cost contract
Dirac.h:1182-1195).  Like the reference, the local solver is dispatched
on solver mode: the CPU reference always runs robust RTR-ADMM
(admm_solve.c:346 ``rtr_solve_nocuda_robust_admm``) and the GPU
pipeline picks NSD-ADMM for ``SM_NSD_RLBFGS`` (admm_solve.c:463-467);
here LM/RTR/NSD all carry the augmented terms, so any mode works:

- ``SM_LM_LBFGS`` / ``SM_OSLM_LBFGS``: batched LM with the quadratic
  terms folded into the normal equations (lm.py).
- ``SM_RTR_OSLM_LBFGS``: plain RTR-ADMM.
- ``SM_RTR_OSRLM_RLBFGS`` (+ any robust mode except NSD): Student's-t
  robust RTR-ADMM — the reference MPI slave's default local solver.
- ``SM_NSD_RLBFGS``: robust NSD-ADMM.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from sagecal_tpu.core.types import VisData
from sagecal_tpu.solvers.lm import LMConfig, _residual_flat, lm_solve
from sagecal_tpu.solvers.robust import update_w_and_nu
from sagecal_tpu.utils.precision import true_f32
from sagecal_tpu.solvers.sage import (
    SM_LM_LBFGS,
    SM_NSD_RLBFGS,
    SM_RTR_OSLM_LBFGS,
    SM_RTR_OSRLM_RLBFGS,
    _ROBUST_MODES,
    ClusterData,
    _res_norm,
    em_residual_scan,
    predict_full_model,
)


class AdmmLocalResult(NamedTuple):
    p: jax.Array  # (M, nchunk_max, 8N)
    res_0: jax.Array
    res_1: jax.Array
    # tuple of per-EM-pass IterTrace pytrees (leading cluster axis) when
    # collect_trace=True, else None — an empty pytree, so the jitted
    # output signature is unchanged
    trace: Optional[tuple] = None


@true_f32
def admm_sagefit(
    data: VisData,
    cdata: ClusterData,
    p0: jax.Array,
    Y: jax.Array,
    BZ: jax.Array,
    rho: jax.Array,
    max_emiter: int = 1,
    lm_config: LMConfig = LMConfig(),
    robust_nu: Optional[float] = None,
    solver_mode: int = SM_LM_LBFGS,
    nulow: float = 2.0,
    nuhigh: float = 30.0,
    collect_trace: bool = False,
) -> AdmmLocalResult:
    """One worker's ADMM x-update for one tile.

    Args:
      p0, Y, BZ: (M, nchunk_max, 8N) real — current solution, scaled
        Lagrange multipliers, and consensus target B_f Z (the same BZ is
        applied to every hybrid chunk of a cluster, as in
        rtr_solve_robust_admm).
      rho: (M,) per-cluster penalties (already fratio-scaled by the
        caller, sagecal_master.cpp:709-723).
      robust_nu: optional Student's-t nu — when given with an LM mode,
        each cluster solve is IRLS-weighted by w = (nu+1)/(nu+e^2) from
        the residual at the incoming solution (the robust ADMM path's
        E-step); robust RTR/NSD modes run their own nu EM instead.
      solver_mode: SM_* dispatch (see module docstring).
    """
    F, rows = data.vis.shape[-3], data.vis.shape[-1]
    nreal = rows * F * 8

    full0 = predict_full_model(p0, cdata, data)
    res_0 = _res_norm(data.vis - full0, data.mask, nreal)

    use_rtr = solver_mode in (SM_RTR_OSLM_LBFGS, SM_RTR_OSRLM_RLBFGS)
    use_nsd = solver_mode == SM_NSD_RLBFGS
    robust = solver_mode in _ROBUST_MODES
    mask8 = (
        data.mask[..., None, :]  # broadcasts over the (F, 8, rows) residual
        if (robust_nu is not None and not (use_rtr or use_nsd))
        else None
    )
    nu0 = jnp.asarray(
        robust_nu if robust_nu is not None else nulow, p0.dtype
    )

    def solve_one(xeff, coh_k, cmap_k, p_k, extras_k):
        y_k, bz_k, rho_k = extras_k
        if use_rtr or use_nsd:
            from sagecal_tpu.solvers.rtr import (
                RTRConfig,
                nsd_solve_robust,
                rtr_solve,
                rtr_solve_robust,
            )

            itmax = lm_config.itmax
            if use_nsd:
                res, _ = nsd_solve_robust(
                    xeff, coh_k, data.mask, data.ant_p, data.ant_q, cmap_k,
                    p_k, itmax=itmax + 15, nu0=nu0, nulow=nulow,
                    nuhigh=nuhigh,
                    admm_y=y_k, admm_bz=bz_k, admm_rho=rho_k,
                    collect_trace=collect_trace,
                )
            elif robust:
                res, _ = rtr_solve_robust(
                    xeff, coh_k, data.mask, data.ant_p, data.ant_q, cmap_k,
                    p_k,
                    RTRConfig(itmax_rsd=itmax + 5, itmax_rtr=itmax + 10),
                    nu0=nu0, nulow=nulow, nuhigh=nuhigh,
                    admm_y=y_k, admm_bz=bz_k, admm_rho=rho_k,
                    collect_trace=collect_trace,
                )
            else:
                res = rtr_solve(
                    xeff, coh_k, data.mask, data.ant_p, data.ant_q, cmap_k,
                    p_k,
                    RTRConfig(itmax_rsd=itmax + 5, itmax_rtr=itmax + 10),
                    admm_y=y_k, admm_bz=bz_k, admm_rho=rho_k,
                    collect_trace=collect_trace,
                )
            return res.p, res.trace
        if robust_nu is not None:
            ed = _residual_flat(
                p_k, coh_k, xeff, data.mask, data.ant_p, data.ant_q, cmap_k, None
            )
            sqrt_w, _ = update_w_and_nu(
                ed, jnp.asarray(robust_nu, p_k.dtype), mask=mask8
            )
        else:
            sqrt_w = None
        res = lm_solve(
            xeff, coh_k, data.mask, data.ant_p, data.ant_q, cmap_k, p_k,
            lm_config, sqrt_weights=sqrt_w,
            admm_y=y_k, admm_bz=bz_k, admm_rho=rho_k,
            collect_trace=collect_trace,
        )
        return res.p, res.trace

    p = p0
    traces = []
    for _ in range(max_emiter):
        p, tr = em_residual_scan(data, cdata, p, (Y, BZ, rho), solve_one)
        if collect_trace:
            traces.append(tr)

    full1 = predict_full_model(p, cdata, data)
    res_1 = _res_norm(data.vis - full1, data.mask, nreal)
    return AdmmLocalResult(
        p=p, res_0=res_0, res_1=res_1,
        trace=tuple(traces) if collect_trace else None,
    )


def admm_dual_update(Y, p, BZ, rho):
    """Y <- Y + rho (J - BZ) (sagecal_slave.cpp:831): the scaled dual
    ascent step.  Shapes (M, nchunk_max, 8N); rho (M,)."""
    return Y + rho[:, None, None] * (p - BZ)


def round_work_weights(nadmm: int, nslots: int, plain_emiter: int = 2,
                       max_emiter: int = 1):
    """Static per-ADMM-round work model (host-side, plain floats).

    The mesh ADMM runs its whole nadmm loop as one jitted program, so
    per-round host timing does not exist; this models each round's
    x-step solver work for wall-clock attribution (obs/trace.py):
    round 0 plain-solves ALL ``nslots`` local sub-band slots with
    ``plain_emiter`` EM passes plus the manifold alignment, rounds >= 1
    solve one active slot with ``max_emiter`` passes (the
    Sbegin/Scurrent/Send rotation — see parallel/mesh.py).  Returns
    ``nadmm`` positive weights proportional to modeled solver work;
    the z-step psum is negligible next to the x-steps (PAPERS.md,
    "Unwrapping ADMM")."""
    if nadmm <= 0:
        return []
    w0 = float(max(nslots, 1) * max(plain_emiter, 1))
    return [w0] + [float(max(max_emiter, 1))] * (nadmm - 1)
