"""Consensus-constrained local calibration: the ADMM "x-step".

Redesign of ``sagefit_visibilities_admm`` (``/root/reference/src/lib/
Dirac/admm_solve.c:221``): an EM pass over clusters where each
per-cluster solve minimizes the data misfit PLUS the scaled-Lagrangian
consensus terms ``y^T (J - BZ) + rho/2 ||J - BZ||^2`` (cost contract
Dirac.h:1182-1195).  Like the reference, the local solver is dispatched
on solver mode: the CPU reference always runs robust RTR-ADMM
(admm_solve.c:346 ``rtr_solve_nocuda_robust_admm``) and the GPU
pipeline picks NSD-ADMM for ``SM_NSD_RLBFGS`` (admm_solve.c:463-467);
here LM/RTR/NSD all carry the augmented terms, so any mode works:

- ``SM_LM_LBFGS`` / ``SM_OSLM_LBFGS``: batched LM with the quadratic
  terms folded into the normal equations (lm.py).
- ``SM_RTR_OSLM_LBFGS``: plain RTR-ADMM.
- ``SM_RTR_OSRLM_RLBFGS`` (+ any robust mode except NSD): Student's-t
  robust RTR-ADMM — the reference MPI slave's default local solver.
- ``SM_NSD_RLBFGS``: robust NSD-ADMM.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from sagecal_tpu.core.types import VisData
from sagecal_tpu.solvers.lm import LMConfig, _residual_flat, lm_solve
from sagecal_tpu.solvers.robust import update_w_and_nu
from sagecal_tpu.utils.precision import true_f32
from sagecal_tpu.solvers.sage import (
    SM_LM_LBFGS,
    SM_NSD_RLBFGS,
    SM_RTR_OSLM_LBFGS,
    SM_RTR_OSRLM_RLBFGS,
    _ROBUST_MODES,
    ClusterData,
    _res_norm,
    em_residual_scan,
    predict_full_model,
)


class AdmmLocalResult(NamedTuple):
    p: jax.Array  # (M, nchunk_max, 8N)
    res_0: jax.Array
    res_1: jax.Array
    # tuple of per-EM-pass IterTrace pytrees (leading cluster axis) when
    # collect_trace=True, else None — an empty pytree, so the jitted
    # output signature is unchanged
    trace: Optional[tuple] = None


@true_f32
def admm_sagefit(
    data: VisData,
    cdata: ClusterData,
    p0: jax.Array,
    Y: jax.Array,
    BZ: jax.Array,
    rho: jax.Array,
    max_emiter: int = 1,
    lm_config: LMConfig = LMConfig(),
    robust_nu: Optional[float] = None,
    solver_mode: int = SM_LM_LBFGS,
    nulow: float = 2.0,
    nuhigh: float = 30.0,
    collect_trace: bool = False,
    cluster_slice=None,
) -> AdmmLocalResult:
    """One worker's ADMM x-update for one tile.

    Args:
      p0, Y, BZ: (M, nchunk_max, 8N) real — current solution, scaled
        Lagrange multipliers, and consensus target B_f Z (the same BZ is
        applied to every hybrid chunk of a cluster, as in
        rtr_solve_robust_admm).
      rho: (M,) per-cluster penalties (already fratio-scaled by the
        caller, sagecal_master.cpp:709-723).
      robust_nu: optional Student's-t nu — when given with an LM mode,
        each cluster solve is IRLS-weighted by w = (nu+1)/(nu+e^2) from
        the residual at the incoming solution (the robust ADMM path's
        E-step); robust RTR/NSD modes run their own nu EM instead.
      solver_mode: SM_* dispatch (see module docstring).
      cluster_slice: optional ``(start, count)`` fine-grained factor
        node — only the ``count`` clusters from (dynamic) ``start`` are
        re-solved and dual-coupled this pass; the rest stay fixed but
        remain subtracted from the residual (em_residual_scan).  Only
        the sliced rows of Y/BZ/rho are read.
    """
    F, rows = data.vis.shape[-3], data.vis.shape[-1]
    nreal = rows * F * 8

    full0 = predict_full_model(p0, cdata, data)
    res_0 = _res_norm(data.vis - full0, data.mask, nreal)

    use_rtr = solver_mode in (SM_RTR_OSLM_LBFGS, SM_RTR_OSRLM_RLBFGS)
    use_nsd = solver_mode == SM_NSD_RLBFGS
    robust = solver_mode in _ROBUST_MODES
    mask8 = (
        data.mask[..., None, :]  # broadcasts over the (F, 8, rows) residual
        if (robust_nu is not None and not (use_rtr or use_nsd))
        else None
    )
    nu0 = jnp.asarray(
        robust_nu if robust_nu is not None else nulow, p0.dtype
    )

    def solve_one(xeff, coh_k, cmap_k, p_k, extras_k):
        y_k, bz_k, rho_k = extras_k
        if use_rtr or use_nsd:
            from sagecal_tpu.solvers.rtr import (
                RTRConfig,
                nsd_solve_robust,
                rtr_solve,
                rtr_solve_robust,
            )

            itmax = lm_config.itmax
            if use_nsd:
                res, _ = nsd_solve_robust(
                    xeff, coh_k, data.mask, data.ant_p, data.ant_q, cmap_k,
                    p_k, itmax=itmax + 15, nu0=nu0, nulow=nulow,
                    nuhigh=nuhigh,
                    admm_y=y_k, admm_bz=bz_k, admm_rho=rho_k,
                    collect_trace=collect_trace,
                )
            elif robust:
                res, _ = rtr_solve_robust(
                    xeff, coh_k, data.mask, data.ant_p, data.ant_q, cmap_k,
                    p_k,
                    RTRConfig(itmax_rsd=itmax + 5, itmax_rtr=itmax + 10),
                    nu0=nu0, nulow=nulow, nuhigh=nuhigh,
                    admm_y=y_k, admm_bz=bz_k, admm_rho=rho_k,
                    collect_trace=collect_trace,
                )
            else:
                res = rtr_solve(
                    xeff, coh_k, data.mask, data.ant_p, data.ant_q, cmap_k,
                    p_k,
                    RTRConfig(itmax_rsd=itmax + 5, itmax_rtr=itmax + 10),
                    admm_y=y_k, admm_bz=bz_k, admm_rho=rho_k,
                    collect_trace=collect_trace,
                )
            return res.p, res.trace
        if robust_nu is not None:
            ed = _residual_flat(
                p_k, coh_k, xeff, data.mask, data.ant_p, data.ant_q, cmap_k, None
            )
            sqrt_w, _ = update_w_and_nu(
                ed, jnp.asarray(robust_nu, p_k.dtype), mask=mask8
            )
        else:
            sqrt_w = None
        res = lm_solve(
            xeff, coh_k, data.mask, data.ant_p, data.ant_q, cmap_k, p_k,
            lm_config, sqrt_weights=sqrt_w,
            admm_y=y_k, admm_bz=bz_k, admm_rho=rho_k,
            collect_trace=collect_trace,
        )
        return res.p, res.trace

    p = p0
    traces = []
    for _ in range(max_emiter):
        p, tr = em_residual_scan(data, cdata, p, (Y, BZ, rho), solve_one,
                                 cluster_slice=cluster_slice)
        if collect_trace:
            traces.append(tr)

    full1 = predict_full_model(p, cdata, data)
    res_1 = _res_norm(data.vis - full1, data.mask, nreal)
    return AdmmLocalResult(
        p=p, res_0=res_0, res_1=res_1,
        trace=tuple(traces) if collect_trace else None,
    )


def admm_dual_update(Y, p, BZ, rho):
    """Y <- Y + rho (J - BZ) (sagecal_slave.cpp:831): the scaled dual
    ascent step.  Shapes (M, nchunk_max, 8N); rho (M,)."""
    return Y + rho[:, None, None] * (p - BZ)


def round_work_weights(nadmm: int, nslots: int, plain_emiter: int = 2,
                       max_emiter: int = 1, slot_rows=None,
                       cluster_groups: int = 1):
    """Static per-ADMM-round work model (host-side, plain floats).

    The mesh ADMM runs its whole nadmm loop as one jitted program, so
    per-round host timing does not exist; this models each round's
    x-step solver work for wall-clock attribution (obs/trace.py):
    round 0 plain-solves ALL ``nslots`` local sub-band slots with
    ``plain_emiter`` EM passes plus the manifold alignment, rounds >= 1
    solve one active slot with ``max_emiter`` passes (the
    Sbegin/Scurrent/Send rotation — see parallel/mesh.py).  Returns
    ``nadmm`` positive weights proportional to modeled solver work;
    the z-step psum is negligible next to the x-steps (PAPERS.md,
    "Unwrapping ADMM").

    ``slot_rows``: optional per-slot UNFLAGGED-row counts (or any
    per-slot work proxy, e.g. ``nrows * fratio``).  Without it every
    slot is assumed to carry the same rows — exactly the uniformity
    that flag-skewed bands break, and that the synthetic band
    attribution would otherwise paper over: a round's solver work is
    dominated by its active slot's unflagged data, so round r >= 1 is
    weighted by slot ``(r-1) % nslots``'s rows (normalized to a mean of
    1 so the uniform case is unchanged) and round 0 by their sum.

    ``cluster_groups``: fine-grained consensus decomposition — rounds
    solve 1/cluster_groups of the clusters, so per-round x-step work
    shrinks accordingly (the group rotation is the fast axis:
    round r >= 1 is slot ``((r-1)//cluster_groups) % nslots``).
    """
    if nadmm <= 0:
        return []
    nslots = max(nslots, 1)
    if slot_rows is not None and len(slot_rows) and sum(slot_rows) > 0:
        mean = float(sum(slot_rows)) / len(slot_rows)
        rel = [float(r) / mean for r in slot_rows]
        # fold multi-band-per-slot groupings down to nslots entries
        if len(rel) != nslots:
            per = max(len(rel) // nslots, 1)
            rel = [sum(rel[s * per:(s + 1) * per]) / per
                   for s in range(nslots)]
    else:
        rel = [1.0] * nslots
    cg = max(cluster_groups, 1)
    w0 = float(sum(rel) * max(plain_emiter, 1))
    ws = [w0]
    for r in range(1, nadmm):
        s = ((r - 1) // cg) % nslots
        ws.append(float(max(max_emiter, 1)) * rel[s] / cg)
    return ws


def factor_schedule(nadmm: int, nslots: int, cluster_groups: int = 1,
                    band_weights=None, ndev: int = 1):
    """Host-built static (slot, cluster-group) schedule for the mesh
    ADMM's fine-grained rounds (parallel/mesh.py ConsensusConfig).

    Returns ``(slot_sched, group_sched)`` int arrays of shape
    ``(nadmm-1, ndev)``: round r's active sub-band slot and cluster
    group per mesh device.  The default (no ``band_weights``) is the
    uniform rotation — groups fastest, then the Sbegin/Scurrent/Send
    slot rotation — identical on every device.

    ``band_weights``: per-BAND unflagged-row counts, length
    ``nslots * ndev`` with band ``d * nslots + s`` on device d (the
    contiguous sharding of parallel/mesh.py).  When given, each device
    allocates its slot visits proportionally to ITS bands' weights
    (largest-remainder apportionment over the nadmm-1 rounds) — the
    shard_map-level rebalancing: a device whose heavy band carries 3x
    the rows of its light band visits the heavy slot ~3x as often, so
    flag-skewed bands stop starving while dead slots stop billing
    rounds.  Group rotation stays the fast axis within each device's
    visit sequence.
    """
    nrounds = max(nadmm - 1, 0)
    cg = max(cluster_groups, 1)
    nslots = max(nslots, 1)
    slot_sched = np.zeros((nrounds, ndev), np.int32)
    group_sched = np.zeros((nrounds, ndev), np.int32)
    for r in range(nrounds):
        group_sched[r, :] = r % cg
    if band_weights is None:
        for r in range(nrounds):
            slot_sched[r, :] = (r // cg) % nslots
        return slot_sched, group_sched
    w = np.asarray(band_weights, float).reshape(ndev, nslots)
    w = np.maximum(w, 1e-12)
    nvisits = (nrounds + cg - 1) // cg
    for d in range(ndev):
        share = w[d] / w[d].sum() * nvisits
        counts = np.floor(share).astype(int)
        rem = share - counts
        for s in np.argsort(-rem)[: nvisits - counts.sum()]:
            counts[s] += 1
        counts = np.maximum(counts, 1 if nvisits >= nslots else 0)
        # interleave visits (round-robin over remaining budget) so a
        # heavy slot's extra visits spread across the run
        visits = []
        left = counts.copy()
        while len(visits) < nvisits:
            for s in range(nslots):
                if left[s] > 0:
                    visits.append(s)
                    left[s] -= 1
            if left.sum() <= 0 and len(visits) < nvisits:
                visits.extend([int(np.argmax(w[d]))] * (nvisits - len(visits)))
        for r in range(nrounds):
            slot_sched[r, d] = visits[r // cg]
    return slot_sched, group_sched
