"""Bounded-staleness consensus rounds for the host-driven band ADMM.

The minibatch consensus loop (``apps/minibatch.py``) and the async
smoke in ``__graft_entry__.py`` run their band x-steps sequentially on
the host, so a flag-skewed band makes every synchronous round as
expensive as its heaviest member.  This module implements the
asynchronous alternative from "Asynchronous distributed ADMM"-style
bounded staleness (see PAPERS.md, arXiv:1603.02526 fine-grained
decomposition + the transpose-reduction Gram objects of
arXiv:1504.02147): each band refreshes its basis-sized Gram
contribution ``B_f^T (Y_f + rho_f J_f)`` on its own deterministic
period, the Z solve consumes the freshest stored term of EVERY band
with a ``discount**age`` rho-weighting, and a band's term older than
``staleness`` rounds drops out of the solve entirely (it is starved —
the watchdog criterion in :func:`consensus.consensus_health`).

Determinism is the design center: refresh periods are a pure function
of the per-band work weights and the staleness bound, the round counter
advances by one per consensus round, and the whole ledger (ages +
stored Gram terms + counter) serializes to flat arrays — so an elastic
checkpoint carries it and ``--resume`` replays the exact same
refresh schedule (tests/test_async_consensus.py).

``staleness = 0`` degenerates to periods of all-ones: every band
refreshes every round and the trajectory is bit-identical to the
synchronous loop.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def refresh_periods(band_weights: Sequence[float],
                    staleness: int) -> np.ndarray:
    """Deterministic per-band refresh periods from work weights.

    ``band_weights``: per-band work proxies (unflagged-row counts — the
    same quantity :func:`sagecal_tpu.parallel.admm.round_work_weights`
    wants as ``slot_rows``).  The LIGHTEST band sets the unit of round
    work and refreshes every round; a band carrying ``k`` times that
    work refreshes every ``round(k)`` rounds so its average per-round
    cost matches the light bands' — capped at ``staleness + 1`` so its
    stored Gram term is never older than the bound when it is consumed.
    ``staleness <= 0`` returns all-ones (the synchronous schedule).
    """
    w = np.asarray([max(float(x), 0.0) for x in band_weights], float)
    n = w.size
    if n == 0:
        return np.zeros((0,), np.int64)
    if staleness <= 0:
        return np.ones((n,), np.int64)
    pos = w[w > 0]
    unit = float(pos.min()) if pos.size else 1.0
    rel = np.where(w > 0, w / max(unit, 1e-30), 1.0)
    per = np.clip(np.rint(rel).astype(np.int64), 1, int(staleness) + 1)
    return per


def band_active(round_index: int, periods: np.ndarray) -> np.ndarray:
    """Which bands refresh in consensus round ``round_index`` (bool,
    per band).  Offsets are staggered by band index so same-period
    bands don't all land on the same round."""
    per = np.asarray(periods, np.int64)
    idx = np.arange(per.size)
    return (round_index % per) == (idx % per)


class StalenessLedger:
    """Ages + stored Gram terms of an async consensus run.

    ``ages[b]`` is how many rounds ago band ``b`` last refreshed its
    stored numerator term ``zterms[b]`` (shape (M, Npoly, K) each).  A
    band that has never contributed has age -1 and a zero term; both
    are excluded from the Z solve.  The ledger (plus the round counter)
    is the complete async state: checkpointing ``to_arrays()`` and
    restoring with ``from_arrays()`` resumes the exact trajectory.
    """

    def __init__(self, nbands: int, zshape, dtype, round_index: int = 0):
        self.ages = np.full((nbands,), -1, np.int64)
        self.zterms = np.zeros((nbands,) + tuple(zshape), dtype)
        self.round_index = int(round_index)

    def record(self, band: int, zterm) -> None:
        """Band ``band`` refreshed this round: store its fresh term."""
        self.zterms[band] = np.asarray(zterm)
        self.ages[band] = 0

    def advance(self) -> None:
        """Close the round: every previously-seen term ages by one."""
        self.ages = np.where(self.ages >= 0, self.ages + 1, self.ages)
        self.round_index += 1

    def weights(self, staleness: Optional[int],
                discount: float = 1.0) -> np.ndarray:
        """Per-band Z-solve weights: ``discount**age`` within the bound,
        0 for never-seen or over-age terms (the rho-discount of
        :func:`consensus.staleness_weights`, with age counted from the
        stored term's refresh round)."""
        ages = np.maximum(self.ages, 0)
        w = np.asarray(discount, float) ** ages
        w = np.where(self.ages < 0, 0.0, w)
        if staleness is not None:
            w = np.where(ages > int(staleness), 0.0, w)
        return w

    # ------------------------------------------------- checkpoint I/O

    def to_arrays(self, prefix: str = "ledger") -> dict:
        return {
            f"{prefix}.ages": self.ages.copy(),
            f"{prefix}.zterms": self.zterms.copy(),
            f"{prefix}.round": np.asarray([self.round_index], np.int64),
        }

    @classmethod
    def from_arrays(cls, arrs: dict, prefix: str = "ledger",
                    dtype=None) -> "StalenessLedger":
        z = np.asarray(arrs[f"{prefix}.zterms"])
        led = cls(z.shape[0], z.shape[1:], dtype or z.dtype,
                  round_index=int(np.asarray(arrs[f"{prefix}.round"])[0]))
        led.zterms = z.astype(dtype) if dtype is not None else z.copy()
        led.ages = np.asarray(arrs[f"{prefix}.ages"], np.int64).copy()
        return led

    @staticmethod
    def present(arrs: dict, prefix: str = "ledger") -> bool:
        return f"{prefix}.zterms" in arrs


def stale_weighted_z(ledger: StalenessLedger, B, rho, weights):
    """The rho-discounted Z solve over the ledger's stored Gram terms.

    num = sum_f w_f zterm_f,  P_m = sum_f w_f rho[f,m] B_f B_f^T,
    Z = pinv(P) num — exactly the synchronous
    :func:`consensus.update_global_z` when every weight is 1 and every
    term is fresh.  ``B`` (Nf, Npoly), ``rho`` (Nf, M), ``weights``
    (Nf,) from :meth:`StalenessLedger.weights`.  Falls back to the
    unweighted solve when every band is starved (all weights 0) so the
    consensus never collapses to a zero division.
    """
    import jax.numpy as jnp

    from sagecal_tpu.parallel import consensus

    w = np.asarray(weights, float)
    if not np.any(w > 0):
        w = np.ones_like(w)
    wj = jnp.asarray(w, B.dtype)
    num = jnp.einsum("f,fmpk->mpk", wj, jnp.asarray(ledger.zterms, B.dtype))
    Bii = consensus.find_prod_inverse_full(B, wj[:, None] * rho)
    return consensus.update_global_z(num, Bii)
