"""Consensus-ADMM polynomial math: frequency-smoothness constraints.

TPU-first re-design of ``/root/reference/src/lib/Dirac/consensus_poly.c``.
The reference runs this on the MPI master as pthread loops over clusters;
here every routine is a pure jitted array op, batched over clusters, and
the frequency sums that the master accumulated from worker messages
become ``lax.psum`` terms on a ``freq`` mesh axis (see
:mod:`sagecal_tpu.parallel.mesh`).

Conventions:
  B: (Nf, Npoly) real basis matrix, row f = basis evaluated at freqs[f]
     (the reference stores B column-major Npoly x Nf, consensus_poly.c:39).
  Z: (M, Npoly, K) global consensus variable; K = 8N (or 8N realified
     params of any shape).  The constraint is J_f ~ sum_p B[f,p] Z[:,p].
  rho: (Nf, M) per-frequency, per-cluster regularization.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# polynomial types (consensus_poly.c:21-28)
POLY_ORDINARY = 0
POLY_NORMALIZED = 1
POLY_BERNSTEIN = 2
POLY_RATIONAL = 3  # [1, (f-f0)/f0, (f0/f-1), ...]


@dataclasses.dataclass(frozen=True)
class ConsensusConfig:
    """Static configuration of the consensus (Z-step) layer.

    ``zstep``:
      "grouped"  — the classic replicated z-step: every device psums the
        full basis-sized numerator (M, Npoly, K) and solves Z locally
        (parallel/mesh._zstep_grouped).
      "reduced"  — transpose-reduced scattered z-step ("Unwrapping ADMM",
        arXiv:1504.02147): the Gram numerator is ``psum_scatter``-ed over
        the solution axis so each device holds only a K/ndev shard of Z,
        the global solve is a tiny local einsum on the shard, and the
        active band's consensus target B_f Z comes back through a single
        ``all_to_all`` — per-round collective bytes drop from
        ~Npoly*M*K to ~(Npoly/ndev + M_active/M)*M*K.

    ``cluster_groups``: fine-grained consensus decomposition
      (arXiv:1603.02526) — the per-band x-step is split below band
      granularity into this many cluster factor groups, each round
      solving one (band slot, cluster group) factor node with its own
      duals.  Communication and x-step work per round scale with
      M/cluster_groups.  1 = classic whole-band rounds.

    ``staleness``: bounded-staleness rounds — contributions older than
      this many rounds are dropped from the Z solve (mesh mode: slots of
      the Scurrent rotation whose stored Yhat is older than K rounds).
      ``None`` = unbounded (the reference's multiplexed semantics).

    ``staleness_discount``: rho-discount applied per round of age to a
      stale band's Gram contribution (both the numerator term and its
      rho in the denominator), so a Z solve leans on fresh bands:
      weight = discount**age.  1.0 = no discounting.

    ``slot_schedule`` / ``group_schedule``: optional host-built static
      schedules (see :func:`sagecal_tpu.parallel.admm.factor_schedule`)
      of shape (nadmm-1,) or (nadmm-1, ndev) — per-round active slot /
      cluster group, optionally per mesh device (shard_map-level
      rebalancing: devices whose bands carry more unflagged rows get
      proportionally more visits).  ``None`` = the uniform
      Sbegin/Scurrent/Send rotation.
    """

    zstep: str = "grouped"
    cluster_groups: int = 1
    staleness: Optional[int] = None
    staleness_discount: float = 1.0
    slot_schedule: Optional[np.ndarray] = None
    group_schedule: Optional[np.ndarray] = None

    @property
    def is_default(self) -> bool:
        return (
            self.zstep == "grouped"
            and self.cluster_groups == 1
            and self.staleness is None
            and self.staleness_discount == 1.0
            and self.slot_schedule is None
            and self.group_schedule is None
        )


def setup_polynomials(freqs, f0: float, Npoly: int, ptype: int = POLY_BERNSTEIN):
    """Basis matrix B (Nf, Npoly).  Mirrors ``setup_polynomials``
    (consensus_poly.c:39-186) including the Bernstein min/max frequency
    normalization and the odd/even split of the rational type-3 basis."""
    freqs = np.asarray(freqs, np.float64)
    Nf = freqs.shape[0]
    B = np.zeros((Nf, Npoly))
    if ptype in (POLY_ORDINARY, POLY_NORMALIZED):
        frat = (freqs - f0) / f0
        B[:, 0] = 1.0
        for p in range(1, Npoly):
            B[:, p] = B[:, p - 1] * frat
        if ptype == POLY_NORMALIZED:
            nrm = np.sqrt(np.sum(B**2, axis=0))
            B = np.where(nrm[None, :] > 0, B / np.where(nrm == 0, 1, nrm)[None, :], 0.0)
    elif ptype == POLY_BERNSTEIN:
        fmax, fmin = freqs.max(), freqs.min()
        x = (freqs - fmin) / max(fmax - fmin, 1e-300)
        n = Npoly - 1
        from math import comb

        for p in range(Npoly):
            B[:, p] = comb(n, p) * x**p * (1.0 - x) ** (n - p)
    elif ptype == POLY_RATIONAL:
        B[:, 0] = 1.0
        frat = (freqs - f0) / f0
        last = frat.copy()
        for p in range(1, Npoly, 2):
            B[:, p] = last
            last = last * frat
        frat = f0 / freqs - 1.0
        last = frat.copy()
        for p in range(2, Npoly, 2):
            B[:, p] = last
            last = last * frat
    else:
        raise ValueError(f"unknown polynomial type {ptype}")
    return jnp.asarray(B)


def find_prod_inverse(B, fratio=None):
    """pinv(sum_f w_f B_f B_f^T): (Npoly, Npoly).  ``find_prod_inverse``
    (consensus_poly.c:196): weights are the per-frequency unflagged-data
    ratios."""
    Nf = B.shape[0]
    w = jnp.ones((Nf,), B.dtype) if fratio is None else jnp.asarray(fratio)
    P = jnp.einsum("f,fp,fq->pq", w, B, B)
    return jnp.linalg.pinv(P)


def find_prod_inverse_full(B, rho, alpha=None):
    """Per-cluster pinv(sum_f rho[f,m] B_f B_f^T [+ alpha_m I]): (M, Npoly,
    Npoly).  ``find_prod_inverse_full[_fed]`` (consensus_poly.c:465,547);
    the federated variant's alpha*I ties local to global Z."""
    P = jnp.einsum("fm,fp,fq->mpq", rho, B, B)
    if alpha is not None:
        Np = B.shape[1]
        P = P + alpha[:, None, None] * jnp.eye(Np, dtype=B.dtype)[None]
    return jnp.linalg.pinv(P)


def accumulate_z_term(B_f, Yrho_f):
    """One frequency's additive contribution to the z right-hand side:
    outer(B_f, Y_f + rho_f J_f).

    B_f: (Npoly,) this frequency's basis row; Yrho_f: (M, K).
    Returns (M, Npoly, K).  The master's accumulation loop
    (sagecal_master.cpp:841-852) — on a mesh this is followed by
    ``lax.psum`` over the freq axis.
    """
    return B_f[None, :, None] * Yrho_f[:, None, :]


def update_global_z(z, Bii):
    """Z = Bii applied along the Npoly axis of z: (M, Npoly, K).

    ``update_global_z_multi`` (consensus_poly.c:778): per cluster,
    Z_m = Bii_m @ z_m (Bii symmetric).
    """
    return jnp.einsum("mpq,mqk->mpk", Bii, z)


def bz_for_freq(Z, B_f):
    """The per-frequency consensus target B_f Z: (M, K) from Z (M, Npoly, K).
    What the master sends each worker per ADMM iteration
    (sagecal_master.cpp:770-800)."""
    return jnp.einsum("p,mpk->mk", B_f, Z)


def update_rho_bb(rho, rho_upper, dY, dJ, eps: float = 1e-12,
                  dj_floor: float = 1e-6):
    """Barzilai-Borwein adaptive penalty update, per cluster.

    ``update_rho_bb`` (consensus_poly.c:860-911): with deltaY = Yhat -
    Yhat_old and deltaJ = J - J_old per cluster, compute the spectral
    steps alphaSD = <dY,dY>/<dY,dJ>, alphaMG = <dY,dJ>/<dJ,dJ>, pick
    alphaMG if 2*alphaMG > alphaSD else alphaSD - alphaMG/2, and accept
    only under sufficient correlation (>0.2) and 0.001 < alpha < upper.

    rho, rho_upper: (M,); dY, dJ: (M, K) per-cluster flattened deltas.

    ``dj_floor``: per-element RMS floor on dJ below which the update is
    rejected and rho kept.  On a CONVERGED cluster dJ -> 0 while dY
    stays finite, so ``<dJ,dJ>`` passes the absolute ``eps`` check yet
    alphaMG = <dY,dJ>/<dJ,dJ> blows up toward ``rho_upper`` — a huge
    penalty jump on exactly the band that needed none, which
    destabilizes late (and especially stale/async) rounds.  Gains are
    O(1) normalized Jones params, so an absolute RMS floor is
    scale-correct here.
    """
    ip12 = jnp.sum(dY * dJ, axis=-1)
    ip11 = jnp.sum(dY * dY, axis=-1)
    ip22 = jnp.sum(dJ * dJ, axis=-1)
    safe12 = jnp.where(jnp.abs(ip12) < eps, 1.0, ip12)
    corr = ip12 / jnp.sqrt(jnp.maximum(ip11 * ip22, eps))
    alphaSD = ip11 / safe12
    alphaMG = ip12 / jnp.where(ip22 < eps, 1.0, ip22)
    alphahat = jnp.where(2.0 * alphaMG > alphaSD, alphaMG, alphaSD - 0.5 * alphaMG)
    nk = jnp.asarray(dJ.shape[-1], ip22.dtype)
    ok = (
        (ip12 > eps)
        & (ip11 > eps)
        & (ip22 > eps)
        & (ip22 > nk * (dj_floor * dj_floor))
        & (corr > 0.2)
        & (alphahat > 1e-3)
        & (alphahat < rho_upper)
    )
    return jnp.where(ok, alphahat, rho)


def slot_staleness_ages(active_slot, nslots):
    """Ages of every multiplexed slot's stored Yhat right after slot
    ``active_slot`` refreshed: slot s was last active ``(active_slot -
    s) mod nslots`` rounds ago (the Scurrent rotation of
    sagecal_master.cpp:157-206).  Returns (nslots,) int ages."""
    s = jnp.arange(nslots)
    return jnp.mod(active_slot - s, nslots)


def staleness_weights(ages, staleness=None, discount: float = 1.0,
                      dtype=None):
    """Per-contribution Z-solve weights from staleness ages.

    ``weight = discount**age`` for contributions within the bound,
    0 for contributions older than ``staleness`` rounds (``None`` =
    unbounded).  Applied to BOTH the Gram numerator term B_f (Y_f +
    rho_f J_f) and that band's rho in the denominator, this is exactly
    a rho-discount: a stale band still pulls the consensus toward its
    last solution, just with a proportionally weaker penalty.
    """
    ages = jnp.asarray(ages)
    if dtype is None:
        dtype = jnp.result_type(float)  # x64-aware default
    w = jnp.asarray(discount, dtype) ** ages.astype(dtype)
    if staleness is not None:
        w = jnp.where(ages <= staleness, w, jnp.zeros_like(w))
    return w


def soft_threshold(z, lam):
    """Elementwise soft threshold (``soft_threshold_z``,
    consensus_poly.c:1044)."""
    return jnp.sign(z) * jnp.maximum(jnp.abs(z) - lam, 0.0)


def admm_dual_residual(Z_new, Z_old):
    """Per-real-parameter dual residual ||Z_old - Z_new||/sqrt(size)
    (sagecal_master.cpp:878-885)."""
    d = (Z_new - Z_old).ravel()
    return jnp.linalg.norm(d) / jnp.sqrt(d.shape[0])


def consensus_health(
    primal_res_band,
    dual_res_band,
    trend_thresh: float = 2.0,
    eps: float = 1e-30,
    ages=None,
    staleness: Optional[int] = None,
):
    """Per-band ADMM consensus health from residual trajectories.

    ``primal_res_band``/``dual_res_band``: (nadmm, Nf) per-round,
    per-band residuals (:class:`sagecal_tpu.parallel.mesh.AdmmResult`
    with ``collect_trace``).  Returns ``(ratio (Nf,), trend (Nf,),
    diverged (Nf,) bool)``:

    - ``ratio``: final primal/dual residual ratio — the standard ADMM
      balance diagnostic (Boyd §3.4.1; the reference's master prints the
      two norms side by side, sagecal_master.cpp:869-885).  Large values
      mean rho is too small for that band (consensus not enforced),
      tiny values mean rho dominates the data term.
    - ``trend``: final primal residual over the trajectory minimum —
      > 1 means the band moved AWAY from consensus after its best round.
    - ``diverged``: non-finite residuals anywhere in the trajectory, or
      ``trend > trend_thresh`` (sustained growth, not a one-round blip).

    Staleness-aware criterion (bounded-staleness rounds): ``ages`` is
    the per-band age (rounds since last refresh) at the final round.  A
    band whose contribution is ``a`` rounds stale is measured against a
    Z that moved ``a`` rounds past its last solve, so its primal
    residual legitimately rides above the fresh-band envelope; its
    trend threshold is relaxed to ``trend_thresh * (1 + a)``.  A band
    older than the configured ``staleness`` bound is STARVED — the
    scheduler stopped refreshing it — and is flagged diverged outright
    (its residual trajectory is no longer evidence of anything).

    Pure array math (works on numpy or jax inputs) so the apps' host-side
    watchdog and on-device callers share one definition.
    """
    pr = jnp.asarray(primal_res_band)
    du = jnp.asarray(dual_res_band)
    ratio = pr[-1] / jnp.maximum(du[-1], eps)
    trend = pr[-1] / jnp.maximum(jnp.min(pr, axis=0), eps)
    nonfinite = ~(
        jnp.all(jnp.isfinite(pr), axis=0) & jnp.all(jnp.isfinite(du), axis=0)
    )
    thresh = jnp.asarray(trend_thresh, trend.dtype)
    if ages is not None:
        a = jnp.asarray(ages).astype(trend.dtype)
        thresh = thresh * (1.0 + a)
    diverged = nonfinite | (trend > thresh)
    if ages is not None and staleness is not None:
        starved = jnp.asarray(ages) > staleness
        diverged = diverged | starved
    return ratio, trend, diverged


def band_imbalance(band_seconds, eps: float = 1e-30):
    """Per-band work-imbalance gauges: ``(ratio, skew, argmax)``.

    ``band_seconds``: (Nf,) per-band wall-clock (real host timings in
    minibatch consensus mode, or :func:`sagecal_tpu.obs.trace.
    band_attribution` shares of the mesh ADMM window).  Returns the
    slowest/median ratio (the straggler gauge — the mesh z-step psum
    runs at the pace of the slowest band, so ratio≈1 means the SPMD
    collective wastes nothing), the relative skew ``(max-mean)/mean``,
    and the index of the slowest band.

    Pure array math (numpy or jax inputs) like :func:`consensus_health`,
    so the host-side straggler detector (obs/trace.py) and any on-device
    caller share one definition.
    """
    t = jnp.asarray(band_seconds)
    med = jnp.median(t)
    mean = jnp.mean(t)
    ratio = jnp.max(t) / jnp.maximum(med, eps)
    skew = (jnp.max(t) - mean) / jnp.maximum(mean, eps)
    return ratio, skew, jnp.argmax(t)


def admm_primal_residual(J_flat, BZ_flat):
    """Per-real-parameter primal residual ||J - BZ||/sqrt(size): how far
    one band's local solution sits from its consensus target (the
    per-slave primal norm of sagecal_master.cpp:869-876).  Pure array
    math shared by the mesh ADMM's per-band residual telemetry
    (parallel/mesh.py) and its reference tests."""
    d = (J_flat - BZ_flat).reshape(J_flat.shape[0], -1) if J_flat.ndim > 1 \
        else (J_flat - BZ_flat)[None]
    n = jnp.sqrt(jnp.asarray(d.shape[-1], d.dtype))
    out = jnp.sqrt(jnp.sum(d * d, axis=-1)) / n
    return out if J_flat.ndim > 1 else out[0]
