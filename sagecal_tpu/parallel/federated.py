"""Federated-averaging distributed mode: local consensus with a global
quotient-manifold average.

Redesign of the stochastic MPI pair
(``/root/reference/src/MPI/sagecal_stochastic_master.cpp`` /
``sagecal_stochastic_slave.cpp``): unlike the standard consensus mode,
the master never solves for Z — each worker keeps a LOCAL Z_f, and per
round the master only (1) averages the workers' Z on the unitary
quotient manifold and projects the mean back into each worker's frame
(``calculate_manifold_average_projectback``, stochastic_master.cpp:347),
and (2) workers tie their local Z to that average with an alpha-weighted
constraint and Lagrange multiplier X (federated pseudo-inverse with
+alpha*I, ``find_prod_inverse_full_fed``, consensus_poly.c:547;
allocations stochastic_slave.cpp:455-470).

On the mesh, the average is an ``all_gather`` of the (M, Npoly, K)
locals + replicated manifold math, and everything else stays local to
the ``freq`` shard.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from sagecal_tpu.core.types import jones_to_params, params_to_jones
from sagecal_tpu.parallel import consensus
from sagecal_tpu.parallel.admm import admm_sagefit
from sagecal_tpu.parallel.manifold import manifold_average_projectback
from sagecal_tpu.solvers.lm import LMConfig
from sagecal_tpu.utils.platform import shard_map as _shard_map


class FederatedResult(NamedTuple):
    p: jax.Array  # (Nf, M, nchunk_max, 8N)
    Z: jax.Array  # (Nf, M, Npoly, K) per-worker local consensus
    dual_res: jax.Array  # (nadmm,)


def _flat(x):
    return x.reshape(x.shape[:-2] + (-1,))


def _unflat(x, nchunk, n8):
    return x.reshape(x.shape[:-1] + (nchunk, n8))


def _fed_zavg(Z_local, axis_name, niter=10):
    """all_gather local Z's and replace each with the quotient-manifold
    mean projected into its own frame.  Z_local: (M, Npoly, K).

    CRITICAL detail from the reference: the master passes N*Npoly as the
    station count (stochastic_master.cpp:347), i.e. each cluster's FULL
    (2*N*Npoly x 2) coefficient stack is aligned by ONE unitary per
    (cluster, worker) — per-coefficient alignment would polar-factor the
    near-singular high-order blocks and inject junk rotations."""
    gath = jax.lax.all_gather(Z_local, axis_name)  # (Nf, M, Npoly, K)
    Nf, M, Npoly, K = gath.shape
    jones = params_to_jones(gath.reshape(Nf, M, Npoly * K))  # (Nf, M, Npoly*K/8, 2, 2)
    avg = manifold_average_projectback(jones, niter=niter)
    out = jones_to_params(avg)
    idx = jax.lax.axis_index(axis_name)
    return out.reshape(Nf, M, Npoly, K)[idx].astype(Z_local.dtype)


def make_federated_mesh_fn(
    mesh: Mesh,
    nadmm: int,
    axis_name: str = "freq",
    max_emiter: int = 1,
    plain_emiter: int = 2,
    lm_config: LMConfig = LMConfig(),
    alpha: float = 1.0,
    avg_cadence: int = 1,
):
    """Build the jitted federated calibration function.

    fn(data_stack, cdata_stack, p0 (Nf,M,nchunk,8N), rho (Nf,M),
       B (Nf, Npoly)) -> FederatedResult.  The local iteration mirrors
    the stochastic slave: x-step with (Y, B_f Z_f), local z-step
    z_f = pinv(rho_f B_f B_f^T + alpha I)(B_f (x) (Y + rho J) + alpha
    Zbar - X), dual updates for both Y (consensus) and X (federation).
    """

    def local_loop(data, cdata, p0, rho, B_f):
        M, nchunk_max, n8 = p0.shape
        K = nchunk_max * n8
        Npoly = B_f.shape[0]
        dtype = p0.dtype
        alpha_v = jnp.full((M,), alpha, dtype)

        # local federated pseudo-inverse: rho_f B_f B_f^T + alpha I
        P_loc = jnp.einsum("m,p,q->mpq", rho, B_f, B_f)
        P_loc = P_loc + alpha_v[:, None, None] * jnp.eye(Npoly, dtype=dtype)[None]
        Bii = jnp.linalg.pinv(P_loc)

        def zstep_local(Yhat_flat, Zbar, X):
            z = consensus.accumulate_z_term(B_f, Yhat_flat)  # (M, Npoly, K)
            z = z + alpha_v[:, None, None] * Zbar - X
            return consensus.update_global_z(z, Bii)

        # round 0: plain solve, init local Z
        zeros = jnp.zeros_like(p0)
        r0 = admm_sagefit(
            data, cdata, p0, zeros, zeros, jnp.zeros_like(rho),
            max_emiter=plain_emiter, lm_config=lm_config,
        )
        p = r0.p
        Yhat = rho[:, None, None] * p
        Zbar0 = jnp.zeros((M, Npoly, K), dtype)
        X = jnp.zeros((M, Npoly, K), dtype)
        Z = zstep_local(_flat(Yhat), Zbar0, X)
        Zbar = _fed_zavg(Z, axis_name)
        X = X + alpha_v[:, None, None] * (Z - Zbar)
        BZ = _unflat(consensus.bz_for_freq(Z, B_f), nchunk_max, n8)
        Y = Yhat - rho[:, None, None] * BZ

        def one_iter(carry, it):
            p, Y, Z, Zbar, X = carry
            BZ = _unflat(consensus.bz_for_freq(Z, B_f), nchunk_max, n8)
            loc = admm_sagefit(
                data, cdata, p, Y, BZ, rho,
                max_emiter=max_emiter, lm_config=lm_config,
            )
            p1 = loc.p
            Yhat = Y + rho[:, None, None] * p1
            Z1 = zstep_local(_flat(Yhat), Zbar, X)
            # federated averaging every avg_cadence rounds
            do_avg = (it % avg_cadence) == 0
            Zavg = _fed_zavg(Z1, axis_name)
            Zbar1 = jnp.where(do_avg, Zavg, Zbar)
            X1 = jnp.where(
                do_avg, X + alpha_v[:, None, None] * (Z1 - Zbar1), X
            )
            BZ1 = _unflat(consensus.bz_for_freq(Z1, B_f), nchunk_max, n8)
            Y1 = Yhat - rho[:, None, None] * BZ1
            # mean local-Z change across workers (replicated output)
            dres = jax.lax.pmean(
                consensus.admm_dual_residual(Z1, Z), axis_name
            )
            return (p1, Y1, Z1, Zbar1, X1), dres

        (p, Y, Z, Zbar, X), dres = jax.lax.scan(
            one_iter, (p, Y, Z, Zbar, X), jnp.arange(1, nadmm)
        )
        dres = jnp.concatenate([jnp.zeros((1,), dres.dtype), dres])
        return p[None], Z[None], dres

    fspec = P(axis_name)
    rspec = P()
    ndev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))

    @jax.jit
    def fn(data_stack, cdata_stack, p0, rho, B):
        if p0.shape[0] != ndev:
            raise ValueError(
                f"sub-band axis {p0.shape[0]} != mesh size {ndev}"
            )
        sm = _shard_map(
            lambda d, c, p, r, b: local_loop(
                jax.tree_util.tree_map(lambda x: x[0], d),
                jax.tree_util.tree_map(lambda x: x[0], c),
                p[0], r[0], b[0],
            ),
            mesh=mesh,
            in_specs=(fspec, fspec, fspec, fspec, fspec),
            out_specs=(fspec, fspec, rspec),
            check_vma=True,
        )
        p, Z, dres = sm(data_stack, cdata_stack, p0, rho, B)
        return FederatedResult(p=p, Z=Z, dual_res=dres)

    return fn


class FederatedState(NamedTuple):
    """Carried state of the stochastic federated mode — every leaf has a
    leading band axis (Nf,) sharded over the mesh.  The pytree analog of
    the stochastic slave's Z/Zavg/X/Y/pfreq/persistent-LBFGS allocations
    (sagecal_stochastic_slave.cpp:441-470, 637-638)."""

    p: jax.Array       # (Nf, M, nchunk_max, 8N) per-band solutions
    Y: jax.Array       # (Nf, M, nchunk_max, 8N) consensus duals
    Z: jax.Array       # (Nf, M, Npoly, K) per-band local consensus
    Zbar: jax.Array    # (Nf, M, Npoly, K) federated average (per frame)
    X: jax.Array       # (Nf, M, Npoly, K) federation duals
    mem: object        # LBFGSMemory with (Nf,)-leading leaves


def init_federated_state(Nf, M, nchunk_max, n8, npoly, lbfgs_m, dtype):
    from sagecal_tpu.solvers.lbfgs import LBFGSMemory

    K = nchunk_max * n8
    zeros_p = jnp.zeros((Nf, M, nchunk_max, n8), dtype)
    zeros_z = jnp.zeros((Nf, M, npoly, K), dtype)
    mem1 = LBFGSMemory.init(M * K, lbfgs_m, dtype)
    mem = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (Nf,) + x.shape).copy(), mem1
    )
    from sagecal_tpu.core.types import identity_jones, jones_to_params

    N = n8 // 8
    eye = jones_to_params(identity_jones(
        N, jnp.complex64 if dtype == jnp.float32 else jnp.complex128))
    p0 = jnp.broadcast_to(eye, (Nf, M, nchunk_max, n8)).astype(dtype)
    return FederatedState(p=p0, Y=zeros_p, Z=zeros_z, Zbar=zeros_z,
                          X=zeros_z, mem=mem)


def make_federated_minibatch_fn(
    mesh: Mesh,
    axis_name: str = "freq",
    itmax: int = 10,
    lbfgs_m: int = 7,
    alpha: float = 1.0,
    robust_nu=None,
):
    """One federated-stochastic minibatch round as a jitted mesh
    program: per band, the consensus minibatch LBFGS x-step with
    PERSISTENT memory (bfgsfit_minibatch_consensus,
    robust_batchmode_lbfgs.c:1504), Y ascent, and the local federated
    z-step z = pinv(rho B B^T + alpha I)(B(Y + rho J) + alpha Zbar - X)
    (stochastic_slave.cpp:756-850).  The federated average itself is
    :func:`make_fed_avg_fn` — called at the reference's cadence (after
    each epoch block, :856-860), not per minibatch.

    fn(data_stack, cdata_stack, state, rho (Nf, M), B (Nf, Npoly))
      -> (state, dual_res (replicated), data_cost (Nf,))
    """
    from sagecal_tpu.solvers.batchmode import bfgsfit_minibatch_consensus

    def local_step(data, cdata, st, rho, B_f):
        M, nchunk_max, n8 = st.p.shape
        K = nchunk_max * n8
        Npoly = B_f.shape[0]
        dtype = st.p.dtype
        alpha_v = jnp.full((M,), alpha, dtype)

        BZ = _unflat(consensus.bz_for_freq(st.Z, B_f), nchunk_max, n8)
        p1, mem1 = bfgsfit_minibatch_consensus(
            data, cdata, st.p, st.Y, BZ, rho, memory=st.mem,
            itmax=itmax, lbfgs_m=lbfgs_m, robust_nu=robust_nu,
        )
        Yhat = st.Y + rho[:, None, None] * p1

        P_loc = jnp.einsum("m,p,q->mpq", rho, B_f, B_f)
        P_loc = P_loc + alpha_v[:, None, None] * jnp.eye(
            Npoly, dtype=dtype)[None]
        Bii = jnp.linalg.pinv(P_loc)
        z = consensus.accumulate_z_term(B_f, _flat(Yhat))
        z = z + alpha_v[:, None, None] * st.Zbar - st.X
        Z1 = consensus.update_global_z(z, Bii)

        BZ1 = _unflat(consensus.bz_for_freq(Z1, B_f), nchunk_max, n8)
        Y1 = Yhat - rho[:, None, None] * BZ1
        dres = jax.lax.pmean(
            consensus.admm_dual_residual(Z1, st.Z), axis_name
        )
        from sagecal_tpu.solvers.batchmode import _data_cost

        cost = _data_cost(p1.reshape(-1), data, cdata,
                          (M, nchunk_max, n8), robust_nu)
        st1 = st._replace(p=p1, Y=Y1, Z=Z1, mem=mem1)
        # re-add the local (length-1) band axis for the fspec outputs
        st1 = jax.tree_util.tree_map(lambda x: x[None], st1)
        return st1, dres, cost[None]

    fspec = P(axis_name)
    rspec = P()

    @jax.jit
    def fn(data_stack, cdata_stack, state, rho, B):
        sm = _shard_map(
            lambda d, c, s, r, b: local_step(
                jax.tree_util.tree_map(lambda x: x[0], d),
                jax.tree_util.tree_map(lambda x: x[0], c),
                jax.tree_util.tree_map(lambda x: x[0], s),
                r[0], b[0],
            ),
            mesh=mesh,
            in_specs=(fspec, fspec, fspec, fspec, fspec),
            out_specs=(fspec, rspec, fspec),
            check_vma=True,
        )
        st_l, dres, cost = sm(data_stack, cdata_stack, state, rho, B)
        # shard_map strips/re-adds the band axis; state leaves keep (Nf,)
        return st_l, dres, cost

    return fn


def make_fed_avg_fn(mesh: Mesh, axis_name: str = "freq",
                    alpha: float = 1.0, niter: int = 10):
    """Federated averaging round: Zbar <- manifold average of all bands'
    Z projected back per frame; X <- X + alpha (Z - Zbar)
    (stochastic_master.cpp:347, slave:856-868)."""

    fspec = P(axis_name)

    def local(st):
        st0 = jax.tree_util.tree_map(lambda x: x[0], st)
        M = st0.Z.shape[0]
        alpha_v = jnp.asarray(alpha, st0.Z.dtype)
        Zbar = _fed_zavg(st0.Z, axis_name, niter=niter)
        X1 = st0.X + alpha_v * (st0.Z - Zbar)
        st1 = st0._replace(Zbar=Zbar, X=X1)
        return jax.tree_util.tree_map(lambda x: x[None], st1)

    @jax.jit
    def fn(state):
        return _shard_map(
            local, mesh=mesh, in_specs=(fspec,), out_specs=fspec,
            check_vma=True,
        )(state)

    return fn
