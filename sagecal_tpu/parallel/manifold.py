"""Manifold (quotient) averaging over the unitary ambiguity of Jones blocks.

Redesign of ``/root/reference/src/lib/Dirac/manifold_average.c``.  A Jones
solution J is only determined up to a right-multiplied unitary U (J C J^H
is invariant for C = U C U^H in the single-cluster sense); before
averaging per-frequency solutions the master aligns them on the quotient
manifold.  The reference loops clusters on pthreads with LAPACK zgesvd on
2x2 blocks; here everything is a vmapped batch of closed-form 2x2 polar
factors, and frequency blocks are processed as one (Nf, 2N, 2) tensor.

Algorithm (manifold_average.c:60-200, per cluster):
  1. initial chain projection of every frequency block onto a reference
     block (randomized reference index when requested);
  2. ``niter`` rounds: mean block J3, then project each block J_f onto J3
     by the Procrustes rotation U = polar(J_f^H J3), J_f <- J_f U;
  3. final: recompute the mean from the projected ensemble, then apply a
     SINGLE unitary to each ORIGINAL block: Y_f <- Y_f polar(Y_f^H J3).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def polar_unitary_2x2(A):
    """U V^H from the SVD of trailing 2x2 complex matrices (the unitary
    polar factor).  Batched; uses jnp.linalg.svd on 2x2s."""
    U, _, Vh = jnp.linalg.svd(A)
    return U @ Vh


def procrustes_project(J, J_ref):
    """min_U ||J_ref - J U|| over unitary U; returns J @ U.

    ``project_procrustes_block`` (manifold_average.c:266,346).
    J: (..., 2N, 2); J_ref: (..., 2N, 2).
    """
    A = jnp.swapaxes(jnp.conj(J), -1, -2) @ J_ref  # (..., 2, 2)
    return J @ polar_unitary_2x2(A)


def _jones_stack_to_blocks(Y):
    """(Nf, N, 2, 2) Jones -> (Nf, 2N, 2) tall blocks (column j of the
    block = column j of every station's Jones, stations stacked)."""
    Nf, N = Y.shape[0], Y.shape[1]
    return jnp.swapaxes(Y, 1, 2).reshape(Nf, 2 * N, 2)


def _blocks_to_jones_stack(B, N):
    Nf = B.shape[0]
    return jnp.swapaxes(B.reshape(Nf, 2, N, 2), 1, 2)


def manifold_average_cluster(Y, niter: int = 20, ref_idx: int = 0):
    """Align one cluster's per-frequency Jones sets; returns aligned Y and
    the quotient mean.

    Y: (Nf, N, 2, 2) complex.  Returns (Y_aligned, mean) with the same
    leading shapes ((Nf,N,2,2), (N,2,2)).
    """
    J = _jones_stack_to_blocks(Y)  # (Nf, 2N, 2)
    N = Y.shape[1]

    # 1. chain projection onto the reference block
    ref = J[ref_idx]
    J = procrustes_project(J, ref[None])

    # 2. iterative mean-and-project
    def one_round(J, _):
        J3 = jnp.mean(J, axis=0)
        return procrustes_project(J, J3[None]), None

    J, _ = jax.lax.scan(one_round, J, None, length=niter)

    # 3. single unitary applied to the originals
    J3 = jnp.mean(J, axis=0)
    J_orig = _jones_stack_to_blocks(Y)
    J_out = procrustes_project(J_orig, J3[None])
    return _blocks_to_jones_stack(J_out, N), _blocks_to_jones_stack(J3[None], N)[0]


def manifold_average(Y, niter: int = 20, ref_idx: int = 0):
    """``calculate_manifold_average`` (manifold_average.c:204): align
    per-frequency Jones over the unitary quotient, every cluster at once.

    Y: (Nf, M, N, 2, 2) complex -> aligned array, same shape.
    """
    aligned, _ = jax.vmap(
        lambda Ym: manifold_average_cluster(Ym, niter, ref_idx),
        in_axes=1,
        out_axes=(1, 0),
    )(Y)
    return aligned


def manifold_average_projectback(Y, niter: int = 10):
    """Federated-averaging variant (``calculate_manifold_average_projectback``,
    manifold_average.c:809): compute the quotient mean of the per-worker
    Z's and REPLACE every worker's copy with the mean projected back
    through each worker's own unitary frame.

    Y: (Nf, M, N, 2, 2) -> same shape, every frequency slot holding the
    consensus average expressed in its own frame.
    """

    def per_cluster(Ym):  # (Nf, N, 2, 2)
        J_orig = _jones_stack_to_blocks(Ym)
        _, mean = manifold_average_cluster(Ym, niter)
        mean_blk = _jones_stack_to_blocks(mean[None])[0]
        # express the mean in each worker's original frame:
        # U_f = polar(mean^H J_orig_f); out_f = mean U_f
        A = jnp.conj(mean_blk.T)[None] @ J_orig  # (Nf, 2, 2)
        out = mean_blk[None] @ polar_unitary_2x2(A)
        return _blocks_to_jones_stack(out, Ym.shape[1])

    return jax.vmap(per_cluster, in_axes=1, out_axes=1)(Y)


def extract_phases(J):
    """Phase-only reduction of a Jones stack: returns diag phase-only
    Jones exp(i*arg(diag(J))) (the role of ``extract_phases``,
    manifold_average.c:400, used for phase-only correction)."""
    d00 = J[..., 0, 0]
    d11 = J[..., 1, 1]
    p00 = jnp.exp(1j * jnp.angle(d00))
    p11 = jnp.exp(1j * jnp.angle(d11))
    z = jnp.zeros_like(p00)
    row0 = jnp.stack([p00, z], axis=-1)
    row1 = jnp.stack([z, p11], axis=-1)
    return jnp.stack([row0, row1], axis=-2)
