"""Mesh-parallel consensus ADMM: frequencies on a device mesh axis.

This replaces the reference's MPI master/worker star
(``/root/reference/src/MPI/sagecal_master.cpp`` /
``sagecal_slave.cpp``, p2p tags ``proto.h:24-59``) with a single SPMD
program over a ``jax.sharding.Mesh``:

- each device along the ``freq`` axis owns one OR MORE sub-bands'
  visibility tiles — the reference's "one MPI worker per group of MS";
- the ADMM x-step (:func:`sagecal_tpu.parallel.admm.admm_sagefit`) runs
  independently per shard, dispatched on solver mode (LM / robust RTR /
  NSD with the ADMM-augmented cost) like ``admm_solve.c:221``;
- the master's Z-update ``z = sum_f B_f (x) (Y_f + rho_f J_f)`` is a
  ``lax.psum`` over the freq axis (sagecal_master.cpp:841-852 was a
  recv+accumulate loop), and ``Bii = pinv(sum_f rho_f B_f B_f^T)`` is a
  psum of small (Npoly, Npoly) terms followed by a replicated pinv;
- the manifold-averaging alignment at the first iteration becomes an
  ``all_gather`` of (M, N, 2, 2) Jones blocks (small) + replicated math.

Data multiplexing (more sub-bands than devices): with Nf = G * ndev the
leading sub-band axis shards into contiguous groups of G per device
(the reference assigns contiguous MS lists per worker,
sagecal_master.cpp:60-224).  ADMM iteration ``it`` solves local group
slot ``it % G`` — the ``Sbegin/Scurrent/Send`` rotation of
sagecal_master.cpp:157-206 / README.md:139-141 — while the z-step psums
the STORED ``Yhat = Y + rho J`` of every sub-band (stale for inactive
slots, exactly the reference's multiplexed semantics where only the
active MS's Y refreshes per iteration).

Iteration protocol (matches slave/master handshake order,
sagecal_slave.cpp:727-895):
  admm 0:  plain (unaugmented) solve of ALL local slots; align J across
           sub-bands on the quotient manifold; Yhat = rho*J; z-step;
           Y = Yhat - rho*BZ.
  admm>0:  augmented solve of the active slot with (Y, BZ);
           Yhat = Y + rho*J; z-step with the NEW J; dual update against
           the NEW consensus, Y = Yhat - rho*BZ_new; optional
           Barzilai-Borwein rho update every other iteration
           (consensus_poly.c:860-911, cadence at sagecal_slave.cpp:899).

Multi-host scaling: build the Mesh over ``jax.devices()`` spanning
hosts (``jax.distributed.initialize``); the same psum/all_gather ride
ICI inside a slice and DCN across — no code change, matching SURVEY.md
section 5's mapping.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from sagecal_tpu.core.types import VisData, jones_to_params, params_to_jones
from sagecal_tpu.obs.perf import instrumented_jit
from sagecal_tpu.parallel import consensus
from sagecal_tpu.parallel.admm import admm_sagefit, factor_schedule
from sagecal_tpu.parallel.manifold import manifold_average
from sagecal_tpu.solvers.lm import LMConfig
from sagecal_tpu.solvers.sage import SM_LM_LBFGS, ClusterData
from sagecal_tpu.utils.platform import shard_map as _shard_map


class AdmmResult(NamedTuple):
    p: jax.Array  # (Nf, M, nchunk_max, 8N) per-band solutions
    Y: jax.Array  # (Nf, M, nchunk_max, 8N) duals
    Z: jax.Array  # (M, Npoly, nchunk_max*8N) consensus variable
    rho: jax.Array  # (Nf, M) final penalties
    dual_res: jax.Array  # (nadmm,) dual residual trace
    primal_res: jax.Array  # (nadmm,) mean primal residual ||J - BZ||
    Zspat: Optional[jax.Array] = None  # (2*Npoly*N*nchunk?, 2G) spatial model
    spat_res: Optional[jax.Array] = None  # (nadmm,) ||Z - Zbar|| trace
    Zspat_diff: Optional[jax.Array] = None  # (D, 2G) diffuse-constraint model
    # telemetry (collect_trace=True only; see sagecal_tpu.obs):
    primal_res_band: Optional[jax.Array] = None  # (nadmm, Nf) per-band ||J-BZ||
    dual_res_band: Optional[jax.Array] = None  # (nadmm, Nf) rho||B dZ|| per band
    rho_trace: Optional[jax.Array] = None  # (nadmm, Nf, M) penalty trajectory


class SpatialConfig(NamedTuple):
    """Spatial-regularization coupling for the mesh ADMM loop
    (the master's Zbar/Zspat/X machinery, sagecal_master.cpp:887-930).

    Phi: (Meff, 2G, 2) per-effective-cluster spatial basis blocks
      (:func:`sagecal_tpu.parallel.spatial.build_spatial_basis`);
    Phikk: (2G, 2G) = sum_k Phi_k Phi_k^H + lambda I;
    alpha: (M,) per-cluster spatial coupling strengths (the -G file's
      alpha column);
    mu: L1 strength; cadence: run the FISTA update every this many ADMM
    iterations (-O admm_cadence); fista_maxiter: inner FISTA steps.

    Diffuse-sky constraint (sagecal_master.cpp:908-926, fista.c:131):
    when ``Z_diff0`` is given (the ``find_initial_spatial`` model), the
    FISTA step carries the extra term Psi^H(Zs - Zdiff) +
    gamma/2 ||Zs - Zdiff||^2, and each cadence also updates
      Zdiff <- (Zdiff0 + 0.5 Psi + 0.5 gamma Zs) / (1 + 0.5 gamma + lam_diff)
      Psi   <- Psi + gamma (Zs - Zdiff)
    The resulting Zdiff (AdmmResult.Zspat_diff) is what the diffuse
    cluster's coherencies are re-predicted from (sagecal_slave.cpp:670,
    ops/diffuse.recalculate_diffuse_coherencies).
    """

    Phi: jax.Array
    Phikk: jax.Array
    alpha: jax.Array
    mu: float = 1e-3
    cadence: int = 2
    fista_maxiter: int = 30
    Z_diff0: Optional[jax.Array] = None
    gamma: float = 0.0
    lam_diff: float = 0.0


def _flat(x):
    return x.reshape(x.shape[:-2] + (-1,))


def _unflat(x, nchunk, n8):
    return x.reshape(x.shape[:-1] + (nchunk, n8))


def _zstep_grouped(Yhat_flat, rho, B_g, axis_name, federated_alpha=None,
                   z_extra=None, weights=None):
    """psum z accumulation + replicated Bii + Z update.

    Yhat_flat (G, M, K); rho (G, M); B_g (G, Npoly) — all local
    sub-bands contribute (vmapped accumulate, summed locally, then
    psum'd across the mesh).  ``z_extra``: optional replicated
    (M, Npoly, K) addition to the accumulated z (the spatial-reg
    ``alpha Zbar - X`` term, sagecal_master.cpp:855-872).
    ``weights``: optional per-local-slot (G,) staleness discounts
    applied to both the numerator terms and the rho denominator
    (consensus.staleness_weights) — identical on every device since the
    slot rotation is."""
    terms = jax.vmap(consensus.accumulate_z_term)(B_g, Yhat_flat)
    if weights is not None:
        terms = weights[:, None, None, None] * terms
    z_local = jnp.sum(terms, axis=0)
    z = jax.lax.psum(z_local, axis_name)
    if z_extra is not None:
        z = z + z_extra
    if weights is not None:
        P_term = jnp.einsum("g,gm,gp,gq->mpq", weights, rho, B_g, B_g)
    else:
        P_term = jnp.einsum("gm,gp,gq->mpq", rho, B_g, B_g)
    P_sum = jax.lax.psum(P_term, axis_name)
    if federated_alpha is not None:
        Np = B_g.shape[-1]
        P_sum = P_sum + federated_alpha[:, None, None] * jnp.eye(
            Np, dtype=P_sum.dtype
        )[None]
    Bii = jnp.linalg.pinv(P_sum)
    return consensus.update_global_z(z, Bii)


def _zbar_blocks_of_z(Z, M, Npoly, nchunk, n8):
    """Param-space Z (M, Npoly, nchunk*n8) -> complex spatial blocks
    (M*nchunk, 2*N*Npoly, 2) — the master's Z->Zbar reshaping
    (sagecal_master.cpp:889-906); hybrid chunks become separate
    effective clusters as in the reference."""
    N = n8 // 8
    J = params_to_jones(Z.reshape(M, Npoly, nchunk, n8))
    X = jnp.transpose(J, (0, 2, 1, 3, 4, 5))  # (M, nchunk, Npoly, N, 2, 2)
    return X.reshape(M * nchunk, Npoly * N * 2, 2)


def _z_of_zbar_blocks(Xb, M, Npoly, nchunk, n8):
    """Inverse of :func:`_zbar_blocks_of_z`."""
    N = n8 // 8
    J = Xb.reshape(M, nchunk, Npoly, N, 2, 2)
    J = jnp.transpose(J, (0, 2, 1, 3, 4, 5))  # (M, Npoly, nchunk, N, 2, 2)
    return jones_to_params(J).reshape(M, Npoly, nchunk * n8)


def make_admm_mesh_fn(
    mesh: Mesh,
    nadmm: int,
    axis_name: str = "freq",
    max_emiter: int = 1,
    plain_emiter: int = 2,
    lm_config: LMConfig = LMConfig(),
    use_manifold_align: bool = True,
    bb_rho: bool = False,
    rho_upper: float = 1e3,
    solver_mode: int = SM_LM_LBFGS,
    robust_nu: Optional[float] = None,
    spatial: Optional[SpatialConfig] = None,
    collect_trace: bool = False,
    consensus_cfg: Optional[consensus.ConsensusConfig] = None,
):
    """Build the jitted mesh-wide ADMM calibration function.

    The returned fn takes leading-axis-``Nf`` stacks (sharded over the
    ``freq`` mesh axis; Nf must be a multiple of the mesh size — pad
    with zero-weight bands otherwise):
      fn(data_stack: VisData pytree with (Nf, ...) leaves,
         cdata_stack: ClusterData pytree (Nf, ...),
         p0: (Nf, M, nchunk_max, 8N), rho: (Nf, M), B: (Nf, Npoly))
    and returns an :class:`AdmmResult`.  The whole Nadmm loop runs in one
    jit/shard_map program.

    ``solver_mode``/``robust_nu`` select the local x-step solver the way
    ``sagefit_visibilities_admm`` dispatches (see
    :func:`sagecal_tpu.parallel.admm.admm_sagefit`).

    ``spatial``: optional :class:`SpatialConfig` — couples the consensus
    Z to a smooth spatial model across directions, INSIDE the ADMM
    iteration at the reference's cadence (sagecal_master.cpp:855-930):
    the z-step gains ``+ alpha Zbar - X`` with a federated ``+alpha I``
    in the Bii inverse, and every ``cadence`` iterations the spatial
    model Zspat is re-fit by FISTA, Zbar <- Zspat Phi, and the Lagrange
    multiplier X steps by ``alpha (Z - Zbar)``.  All spatial state is
    replicated across the mesh (it is master-side math in the
    reference — tiny compared to the sharded x-steps).

    ``collect_trace``: statically enables ADMM telemetry — the result
    additionally carries per-band primal/dual residual norms and the
    full rho trajectory per iteration (``primal_res_band`` /
    ``dual_res_band`` (nadmm, Nf), ``rho_trace`` (nadmm, Nf, M)); the
    Barzilai-Borwein penalty adaptation is exactly what these exist to
    monitor.  Off (default) the jitted signature is unchanged.

    ``consensus_cfg``: optional :class:`sagecal_tpu.parallel.consensus.
    ConsensusConfig` selecting the consensus round structure — the
    transpose-reduced scattered z-step, fine-grained cluster factor
    groups, per-device slot schedules, and in-mesh bounded-staleness
    weighting.  ``None`` (default) keeps the classic grouped rounds and
    emits the exact original program.
    """

    ccfg = (consensus_cfg if consensus_cfg is not None
            else consensus.ConsensusConfig())
    if ccfg.zstep not in ("grouped", "reduced"):
        raise ValueError(f"unknown zstep {ccfg.zstep!r}")
    cg = max(int(ccfg.cluster_groups), 1)
    fine = cg > 1
    use_staleness = (
        ccfg.staleness is not None or ccfg.staleness_discount != 1.0
    )
    if use_staleness and (fine or ccfg.slot_schedule is not None
                          or ccfg.group_schedule is not None):
        raise ValueError(
            "in-mesh bounded staleness composes with the uniform "
            "whole-band rotation only; fine-grained / rebalanced "
            "staleness is the minibatch async-consensus path"
        )
    reduced = ccfg.zstep == "reduced"
    if reduced and ccfg.group_schedule is not None:
        gs = np.asarray(ccfg.group_schedule)
        if gs.ndim == 2 and not np.all(gs == gs[:, :1]):
            raise ValueError(
                "reduced z-step needs a device-uniform group schedule "
                "(the incremental Gram delta rows must align across "
                "the mesh)"
            )
    # full Z is needed replicated every round for the spatial coupling
    # and the per-band residual telemetry; there the reduced mode keeps
    # the scattered solve but all_gathers Z back per round (still far
    # below the grouped psum of the full numerator).
    zmode = "grouped" if not reduced else (
        "reduced_gather" if (spatial is not None or collect_trace)
        else "reduced_scatter"
    )
    # with fixed rho, no staleness discounts and no federated alpha the
    # Bii denominator never changes — hoist its psum out of the round
    # loop entirely (the grouped path psums it every round).
    den_static = (
        reduced and not bb_rho and not use_staleness and spatial is None
    )
    have_sched = (
        fine or ccfg.slot_schedule is not None
        or ccfg.group_schedule is not None
    )
    ndev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))

    def _fit(data, cdata, p, Y, BZ, rho_m, emiter, cluster_slice=None):
        return admm_sagefit(
            data, cdata, p, Y, BZ, rho_m,
            max_emiter=emiter, lm_config=lm_config,
            solver_mode=solver_mode, robust_nu=robust_nu,
            cluster_slice=cluster_slice,
        )

    def local_loop(data: VisData, cdata: ClusterData, p0, rho, B_g):
        # all array leaves carry the local sub-band group axis G
        G, M, nchunk_max, n8 = p0.shape
        K = nchunk_max * n8
        Npoly = B_g.shape[-1]
        zeros_g = jnp.zeros_like(p0[0])
        if M % cg != 0:
            raise ValueError(
                f"cluster_groups {cg} must divide the cluster count {M}"
            )
        Mg = M // cg
        if reduced:
            if K % ndev != 0:
                raise ValueError(
                    f"reduced z-step needs the solution size {K} "
                    f"divisible by the mesh size {ndev}; use "
                    "zstep='grouped'"
                )
            Ks = K // ndev
        if have_sched:
            # host-built static (slot, group) schedule, one column per
            # mesh device (shard_map-level rebalancing)
            slot_np, group_np = factor_schedule(
                nadmm, G, cluster_groups=cg, ndev=ndev
            )
            if ccfg.slot_schedule is not None:
                s = np.asarray(ccfg.slot_schedule, np.int32)
                slot_np = np.broadcast_to(
                    s[:, None] if s.ndim == 1 else s, (nadmm - 1, ndev)
                )
            if ccfg.group_schedule is not None:
                s = np.asarray(ccfg.group_schedule, np.int32)
                group_np = np.broadcast_to(
                    s[:, None] if s.ndim == 1 else s, (nadmm - 1, ndev)
                )
            slot_arr = jnp.asarray(slot_np, jnp.int32)
            group_arr = jnp.asarray(group_np, jnp.int32)

        # ---- admm 0: plain solve of every local slot -------------------
        def plain_one(_, inp):
            d_g, c_g, p_g, rho_g = inp
            r = _fit(d_g, c_g, p_g, zeros_g, zeros_g,
                     jnp.zeros_like(rho_g), plain_emiter)
            return None, r.p

        _, p = jax.lax.scan(plain_one, None, (data, cdata, p0, rho))

        if use_manifold_align:
            # master-side unitary-ambiguity fix over ALL Nf sub-bands
            # (sagecal_master.cpp:826-838)
            jones = params_to_jones(p)  # (G, M, nchunk, N, 2, 2)
            gath = jax.lax.all_gather(jones, axis_name)  # (ndev, G, ...)
            nd_, G_, Mm = gath.shape[0], gath.shape[1], gath.shape[2]
            gflat = gath.reshape(nd_ * G_, Mm, -1, 2, 2)
            aligned = manifold_average(gflat, niter=20)
            idx = jax.lax.axis_index(axis_name)
            own = aligned.reshape((nd_, G_) + aligned.shape[1:])[idx]
            p = jones_to_params(own.reshape(jones.shape)).astype(p0.dtype)

        Yhat = rho[:, :, None, None] * p  # Y=0 so Yhat = rho*J

        use_spatial = spatial is not None
        if use_spatial:
            M_ = p0.shape[1]
            K = nchunk_max * n8
            Zbar_flat0 = jnp.zeros((M_, B_g.shape[-1], K), p0.dtype)
            Xsp0 = jnp.zeros_like(Zbar_flat0)
            D = 2 * (n8 // 8) * B_g.shape[-1]
            twoG = spatial.Phikk.shape[0]
            Zspat0 = jnp.zeros((D, twoG), jnp.complex64 if p0.dtype == jnp.float32
                               else jnp.complex128)
            alpha_sp = spatial.alpha.astype(p0.dtype)
            use_diff = spatial.Z_diff0 is not None
            if use_diff:
                Zdiff0_c = jnp.asarray(spatial.Z_diff0, Zspat0.dtype)

            def spatial_update(Z, Xsp, Zdiff, Psi):
                """FISTA re-fit + Zbar/X updates (cadenced), optionally
                with the diffuse constraint (master:908-926)."""
                from sagecal_tpu.parallel.spatial import (
                    spatial_model_apply, update_spatialreg_fista,
                )

                Zbar_c = _zbar_blocks_of_z(Z, M_, B_g.shape[-1], nchunk_max, n8)
                Zs = update_spatialreg_fista(
                    Zbar_c, spatial.Phikk.astype(Zspat0.dtype),
                    spatial.Phi.astype(Zspat0.dtype),
                    spatial.mu, maxiter=spatial.fista_maxiter,
                    Z_diff=Zdiff if use_diff else None,
                    Psi=Psi if use_diff else None,
                    gamma=spatial.gamma if use_diff else 0.0,
                )
                if use_diff:
                    # Zdiff prox + Psi ascent (master:919-926)
                    g = spatial.gamma
                    Zdiff = (Zdiff0_c + 0.5 * Psi + 0.5 * g * Zs) / (
                        1.0 + 0.5 * g + spatial.lam_diff
                    )
                    Psi = Psi + g * (Zs - Zdiff)
                Zbar_new_c = spatial_model_apply(Zs, spatial.Phi.astype(Zs.dtype))
                Zbar_new = _z_of_zbar_blocks(
                    Zbar_new_c, M_, B_g.shape[-1], nchunk_max, n8
                ).astype(p0.dtype)
                Zerr = Z - Zbar_new
                Xsp_new = Xsp + alpha_sp[:, None, None] * Zerr
                sres = jnp.linalg.norm(Zerr.ravel()) / Zerr.size
                return Zbar_new, Xsp_new, Zs, sres, Zdiff, Psi

        def bz_of(Z_, g):
            return _unflat(
                consensus.bz_for_freq(Z_, B_g[g]), nchunk_max, n8
            )

        # ---- round-0 consensus -----------------------------------------
        if zmode == "grouped":
            Z = _zstep_grouped(_flat(Yhat), rho, B_g, axis_name)
        else:
            # transpose reduction (arXiv:1504.02147): the basis-sized
            # Gram numerator lives psum_scatter'd over the solution
            # axis, so each device solves only its K/ndev shard of Z and
            # per-round collectives carry Gram deltas, never full
            # (M, Npoly, K) stacks.
            B_full = jax.lax.all_gather(B_g, axis_name, axis=0,
                                        tiled=True)

            def _num_scatter(Yhat_flat, weights=None):
                terms = jax.vmap(consensus.accumulate_z_term)(
                    B_g, Yhat_flat
                )
                if weights is not None:
                    terms = weights[:, None, None, None] * terms
                z_local = jnp.sum(terms, axis=0)
                return jax.lax.psum_scatter(
                    z_local, axis_name, scatter_dimension=2, tiled=True
                )

            def _den_inv(rho_cur, weights=None, federated_alpha=None):
                if weights is not None:
                    P_term = jnp.einsum(
                        "g,gm,gp,gq->mpq", weights, rho_cur, B_g, B_g
                    )
                else:
                    P_term = jnp.einsum(
                        "gm,gp,gq->mpq", rho_cur, B_g, B_g
                    )
                P_sum = jax.lax.psum(P_term, axis_name)
                if federated_alpha is not None:
                    P_sum = P_sum + federated_alpha[:, None, None] * \
                        jnp.eye(Npoly, dtype=P_sum.dtype)[None]
                return jnp.linalg.pinv(P_sum)

            def a2a_bz(Zsh_, slot_row, group_row, g):
                """Active consensus target B_f Z from the sharded Z:
                every device computes the partial on ITS K-shard for
                EVERY device's active (slot, group) factor, and one
                all_to_all hands each device its own band's rows back
                in shard order."""
                if slot_row is None:
                    band_ids = jnp.arange(ndev) * G + g
                else:
                    band_ids = jnp.arange(ndev) * G + slot_row
                rows = B_full[band_ids]  # (ndev, Npoly)
                if group_row is None:
                    starts = jnp.zeros((ndev,), jnp.int32)
                else:
                    starts = (group_row * Mg).astype(jnp.int32)

                def part(brow, st):
                    blk = jax.lax.dynamic_slice(
                        Zsh_, (st, jnp.int32(0), jnp.int32(0)),
                        (Mg, Npoly, Ks),
                    )
                    return jnp.einsum("p,mpk->mk", brow, blk)

                partials = jax.vmap(part)(rows, starts)  # (ndev,Mg,Ks)
                got = jax.lax.all_to_all(
                    partials, axis_name, split_axis=0, concat_axis=0,
                    tiled=True,
                )
                bz = jnp.moveaxis(got, 0, 1).reshape(Mg, K)
                return _unflat(bz, nchunk_max, n8)

            num_shard = _num_scatter(_flat(Yhat))
            Bii0 = _den_inv(rho)
            Zsh = consensus.update_global_z(num_shard, Bii0)
            Z = jax.lax.all_gather(Zsh, axis_name, axis=2, tiled=True)

        BZ_all = jax.vmap(lambda g: bz_of(Z, g))(jnp.arange(G))
        Y = Yhat - rho[:, :, None, None] * BZ_all

        def band_residuals(p_cur, Z_new, Z_old, rho_cur):
            """Per-local-band primal ||J - BZ|| and dual rho||B dZ||
            norms (both /sqrt(n), the scaling of the scalar pres)."""
            BZn = jax.vmap(lambda g: bz_of(Z_new, g))(jnp.arange(G))
            BZo = jax.vmap(lambda g: bz_of(Z_old, g))(jnp.arange(G))
            pr = _flat(p_cur - BZn)  # (G, M, K)
            rn = jnp.sqrt(jnp.asarray(pr[0].size, pr.dtype))
            prn = jnp.sqrt(jnp.sum(pr * pr, axis=(1, 2))) / rn
            dd = _flat(rho_cur[:, :, None, None] * (BZn - BZo))
            ddn = jnp.sqrt(jnp.sum(dd * dd, axis=(1, 2))) / rn
            return prn, ddn

        # ---- admm > 0: rotate over local slots -------------------------
        def one_iter(carry, it):
            p, Y, Zc, rho, Yhat_all, Yhat_prev, p_prev, spstate = carry
            if have_sched:
                slot_row = jax.lax.dynamic_index_in_dim(
                    slot_arr, it - 1, keepdims=False
                )
                group_row = jax.lax.dynamic_index_in_dim(
                    group_arr, it - 1, keepdims=False
                )
                did = jax.lax.axis_index(axis_name)
                g = slot_row[did]
                c0 = group_row[did] * Mg
            else:
                slot_row = group_row = None
                g = (it - 1) % G  # active local slot (Scurrent rotation)
                c0 = 0
            csl = (c0, Mg) if fine else None
            i0 = jnp.int32(0)  # index dtype anchor for dynamic updates

            def sl(x):
                """Active cluster-factor rows (fine-grained consensus
                decomposition, arXiv:1603.02526); identity for
                whole-band rounds."""
                if not fine:
                    return x
                return jax.lax.dynamic_slice_in_dim(x, c0, Mg, axis=0)

            d_g = jax.tree_util.tree_map(
                lambda x: jax.lax.dynamic_index_in_dim(x, g, keepdims=False),
                data,
            )
            c_g = jax.tree_util.tree_map(
                lambda x: jax.lax.dynamic_index_in_dim(x, g, keepdims=False),
                cdata,
            )
            p_g = p[g]
            Y_g = Y[g]
            rho_g = rho[g]
            if use_staleness:
                ages = consensus.slot_staleness_ages(g, G)
                w = consensus.staleness_weights(
                    ages, ccfg.staleness, ccfg.staleness_discount,
                    dtype=p0.dtype,
                )
            else:
                w = None
            if zmode == "grouped":
                Z = Zc
                BZ_g = bz_of(Z, g)
            elif zmode == "reduced_gather":
                Z, Zsh, num_shard = Zc
                BZ_g = bz_of(Z, g)
            else:
                Zsh, num_shard = Zc
                BZ_g = a2a_bz(Zsh, slot_row, group_row, g)  # active rows
                if fine:
                    pad = jnp.zeros((M,) + BZ_g.shape[1:], BZ_g.dtype)
                    BZ_g = jax.lax.dynamic_update_slice(
                        pad, BZ_g, (c0, i0, i0)
                    )
            loc = _fit(d_g, c_g, p_g, Y_g, BZ_g, rho_g, max_emiter,
                       cluster_slice=csl)
            p1_g = loc.p
            if fine:
                Yhat_act = sl(Y_g) + sl(rho_g)[:, None, None] * sl(p1_g)
                Yhat_all1 = jax.lax.dynamic_update_slice(
                    Yhat_all, Yhat_act[None], (g, c0, i0, i0)
                )
            else:
                Yhat_act = Y_g + rho_g[:, None, None] * p1_g
                Yhat_all1 = Yhat_all.at[g].set(Yhat_act)
            p1 = p.at[g].set(p1_g)
            if use_spatial:
                Zbar_flat, Xsp = spstate[0], spstate[1]
                z_extra = alpha_sp[:, None, None] * Zbar_flat - Xsp
            if zmode == "grouped":
                if use_spatial:
                    Z1 = _zstep_grouped(
                        _flat(Yhat_all1), rho, B_g, axis_name,
                        federated_alpha=alpha_sp, z_extra=z_extra,
                        weights=w,
                    )
                else:
                    Z1 = _zstep_grouped(_flat(Yhat_all1), rho, B_g,
                                        axis_name, weights=w)
                Zc1 = Z1
                BZ1_g = bz_of(Z1, g)
                BZ1_act = sl(BZ1_g)
                dres = consensus.admm_dual_residual(Z1, Z)
            else:
                if use_staleness:
                    num_shard1 = _num_scatter(_flat(Yhat_all1), weights=w)
                else:
                    # incremental transpose reduction: only the active
                    # (slot, group) factor's Yhat moved this round, so
                    # only its basis-outer-product delta crosses the
                    # mesh (the group schedule is device-uniform, so
                    # the Mg delta rows align across devices)
                    old_act = (
                        jax.lax.dynamic_slice(
                            Yhat_all, (g, c0, i0, i0),
                            (1, Mg, nchunk_max, n8),
                        )[0]
                        if fine else Yhat_all[g]
                    )
                    delta = consensus.accumulate_z_term(
                        B_g[g], _flat(Yhat_act - old_act)
                    )
                    dsh = jax.lax.psum_scatter(
                        delta, axis_name, scatter_dimension=2, tiled=True
                    )
                    if fine:
                        cur = jax.lax.dynamic_slice(
                            num_shard, (c0, i0, i0), (Mg, Npoly, Ks)
                        )
                        num_shard1 = jax.lax.dynamic_update_slice(
                            num_shard, cur + dsh, (c0, i0, i0)
                        )
                    else:
                        num_shard1 = num_shard + dsh
                if den_static:
                    Bii = Bii0
                else:
                    Bii = _den_inv(
                        rho, weights=w,
                        federated_alpha=alpha_sp if use_spatial else None,
                    )
                num_solve = num_shard1
                if use_spatial:
                    did_z = jax.lax.axis_index(axis_name)
                    num_solve = num_solve + jax.lax.dynamic_slice_in_dim(
                        z_extra, did_z * Ks, Ks, axis=2
                    )
                Zsh1 = consensus.update_global_z(num_solve, Bii)
                if zmode == "reduced_gather":
                    Z1 = jax.lax.all_gather(Zsh1, axis_name, axis=2,
                                            tiled=True)
                    BZ1_g = bz_of(Z1, g)
                    BZ1_act = sl(BZ1_g)
                    dres = consensus.admm_dual_residual(Z1, Z)
                    Zc1 = (Z1, Zsh1, num_shard1)
                else:
                    BZ1_act = a2a_bz(Zsh1, slot_row, group_row, g)
                    dd = (Zsh1 - Zsh).ravel()
                    dres = jnp.sqrt(
                        jax.lax.psum(jnp.sum(dd * dd), axis_name)
                    ) / jnp.sqrt(jnp.asarray(M * Npoly * K, dd.dtype))
                    Zc1 = (Zsh1, num_shard1)
            if use_spatial:
                # cadenced spatial re-fit (sagecal_master.cpp:887-930)
                do_sp = (it % spatial.cadence) == 0
                spstate1 = jax.lax.cond(
                    do_sp,
                    lambda args: spatial_update(
                        args[0], args[1][1], args[1][4], args[1][5]
                    ),
                    lambda args: args[1],
                    (Z1, spstate),
                )
            else:
                spstate1 = spstate
            if fine:
                Ynew_act = Yhat_act - sl(rho_g)[:, None, None] * BZ1_act
                Y1 = jax.lax.dynamic_update_slice(
                    Y, Ynew_act[None], (g, c0, i0, i0)
                )
            else:
                Y1 = Y.at[g].set(Yhat_act - rho_g[:, None, None] * BZ1_act)
            pr = _flat((sl(p1_g) if fine else p1_g) - BZ1_act)
            pres = jax.lax.pmean(
                jnp.linalg.norm(pr.ravel()) / jnp.sqrt(pr.size), axis_name
            )
            if bb_rho:
                if fine:
                    dY = _flat(Yhat_act) - _flat(sl(Yhat_prev[g]))
                    dJ = _flat(sl(p1_g)) - _flat(sl(p_prev[g]))
                    rho_new_act = consensus.update_rho_bb(
                        sl(rho_g),
                        jnp.full((Mg,), rho_upper, rho_g.dtype), dY, dJ,
                    )
                    visit = (it - 1) // (G * cg)
                    upd = jnp.where(visit % 2 == 1, rho_new_act,
                                    sl(rho_g))
                    rho1 = jax.lax.dynamic_update_slice(
                        rho, upd[None], (g, c0)
                    )
                else:
                    dY = _flat(Yhat_act) - _flat(Yhat_prev[g])
                    dJ = _flat(p1_g) - _flat(p_prev[g])
                    rho_new_g = consensus.update_rho_bb(
                        rho_g, jnp.full_like(rho_g, rho_upper), dY, dJ
                    )
                    # BB cadence: update every other visit to this slot
                    # (sagecal_slave.cpp:899)
                    visit = (it - 1) // G
                    rho1 = rho.at[g].set(
                        jnp.where(visit % 2 == 1, rho_new_g, rho_g)
                    )
            else:
                rho1 = rho
            if fine:
                Yhat_prev1 = jax.lax.dynamic_update_slice(
                    Yhat_prev, Yhat_act[None], (g, c0, i0, i0)
                )
                p_prev1 = jax.lax.dynamic_update_slice(
                    p_prev, sl(p1_g)[None], (g, c0, i0, i0)
                )
            else:
                Yhat_prev1 = Yhat_prev.at[g].set(Yhat_act)
                p_prev1 = p_prev.at[g].set(p1_g)
            sres_out = spstate1[3] if use_spatial else jnp.zeros((), p0.dtype)
            ys = (dres, pres, sres_out)
            if collect_trace:
                prn, ddn = band_residuals(p1, Z1, Z, rho1)
                ys = ys + (prn, ddn, rho1)
            return (p1, Y1, Zc1, rho1, Yhat_all1, Yhat_prev1, p_prev1,
                    spstate1), ys

        spstate0 = (
            (Zbar_flat0, Xsp0, Zspat0, jnp.zeros((), p0.dtype),
             Zdiff0_c if use_spatial and use_diff else Zspat0,
             jnp.zeros_like(Zspat0))
            if use_spatial
            else jnp.zeros((), p0.dtype)
        )
        if zmode == "grouped":
            Zc0 = Z
        elif zmode == "reduced_gather":
            Zc0 = (Z, Zsh, num_shard)
        else:
            Zc0 = (Zsh, num_shard)
        init = (p, Y, Zc0, rho, Yhat, Yhat, p, spstate0)
        if collect_trace:
            # iteration-0 rows: residuals of the plain solve vs the first
            # consensus (dual term is 0 by construction, dZ = 0)
            prn0, _ = band_residuals(p, Z, Z, rho)
            rho0 = rho
        carry, ys = jax.lax.scan(one_iter, init, jnp.arange(1, nadmm))
        (p, Y, Zc, rho, _, _, _, spstate) = carry
        if zmode == "grouped":
            Z = Zc
        elif zmode == "reduced_gather":
            Z = Zc[0]
        else:
            # one-time reassembly of the replicated consensus result
            Z = jax.lax.all_gather(Zc[0], axis_name, axis=2, tiled=True)
        (dres, pres, sres) = ys[:3]
        dres = jnp.concatenate([jnp.zeros((1,), dres.dtype), dres])
        pres = jnp.concatenate([jnp.zeros((1,), pres.dtype), pres])
        sres = jnp.concatenate([jnp.zeros((1,), sres.dtype), sres])
        Zspat_out = spstate[2] if use_spatial else jnp.zeros((1, 1), jnp.complex64)
        Zdiff_out = (
            spstate[4] if use_spatial and use_diff
            else jnp.zeros((1, 1), jnp.complex64)
        )
        out = (p, Y, Z, rho, dres, pres, Zspat_out, sres, Zdiff_out)
        if collect_trace:
            prn_t, ddn_t, rho_t = ys[3:]
            prn_t = jnp.concatenate([prn0[None], prn_t])
            ddn_t = jnp.concatenate([jnp.zeros_like(prn0)[None], ddn_t])
            rho_t = jnp.concatenate([rho0[None], rho_t])
            out = out + (prn_t, ddn_t, rho_t)
        return out

    fspec = P(axis_name)
    rspec = P()
    out_specs = (fspec, fspec, rspec, fspec, rspec, rspec, rspec, rspec,
                 rspec)
    if collect_trace:
        # band-axis telemetry shards on axis 1 (axis 0 is the iteration)
        bspec = P(None, axis_name)
        out_specs = out_specs + (bspec, bspec, bspec)

    @instrumented_jit(name="mesh.admm")
    def fn(data_stack, cdata_stack, p0, rho, B):
        Nf = p0.shape[0]
        if Nf % ndev != 0:
            raise ValueError(
                f"sub-band count {Nf} must be a multiple of the mesh size "
                f"{ndev}; pad with zero-weight bands (rho=0, mask=0) first"
            )
        sm = _shard_map(
            local_loop,
            mesh=mesh,
            in_specs=(fspec, fspec, fspec, fspec, fspec),
            out_specs=out_specs,
            check_vma=True,
        )
        out = sm(data_stack, cdata_stack, p0, rho, B)
        p, Y, Z, rho_f, dres, pres, Zspat, sres, Zdiff = out[:9]
        extra = {}
        if collect_trace:
            extra = dict(primal_res_band=out[9], dual_res_band=out[10],
                         rho_trace=out[11])
        return AdmmResult(
            p=p, Y=Y, Z=Z, rho=rho_f, dual_res=dres, primal_res=pres,
            Zspat=Zspat, spat_res=sres, Zspat_diff=Zdiff, **extra,
        )

    def traced_fn(data_stack, cdata_stack, p0, rho, B):
        # host-side dispatch span AROUND the jitted program (never
        # inside it — jaxlint JL002 territory).  Dispatch is async, so
        # this span covers trace/compile + enqueue only; the caller owns
        # the block_until_ready that closes the device window and the
        # per-band attribution over it (apps/distributed.py).
        from sagecal_tpu.obs.trace import get_tracer

        tr = get_tracer()
        if not tr.enabled:
            return fn(data_stack, cdata_stack, p0, rho, B)
        with tr.span("mesh.admm.dispatch", kind="collective",
                     nf=int(p0.shape[0]), ndev=ndev, nadmm=nadmm,
                     async_dispatch=True):
            return fn(data_stack, cdata_stack, p0, rho, B)

    # AOT hook for the comms bench / regression gate: .lower(*args)
    # .compile() on this handle feeds obs.perf.collective_cost_analysis
    # without executing the program
    traced_fn.inner_jit = fn
    return traced_fn


def stack_for_mesh(items):
    """Stack a list of per-frequency pytrees on a new leading axis for
    sharding over the ``freq`` mesh axis.  Static (non-pytree) fields
    must be identical across items."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *items)
