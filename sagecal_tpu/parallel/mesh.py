"""Mesh-parallel consensus ADMM: frequencies on a device mesh axis.

This replaces the reference's MPI master/worker star
(``/root/reference/src/MPI/sagecal_master.cpp`` /
``sagecal_slave.cpp``, p2p tags ``proto.h:24-59``) with a single SPMD
program over a ``jax.sharding.Mesh``:

- each device along the ``freq`` axis owns one OR MORE sub-bands'
  visibility tiles — the reference's "one MPI worker per group of MS";
- the ADMM x-step (:func:`sagecal_tpu.parallel.admm.admm_sagefit`) runs
  independently per shard, dispatched on solver mode (LM / robust RTR /
  NSD with the ADMM-augmented cost) like ``admm_solve.c:221``;
- the master's Z-update ``z = sum_f B_f (x) (Y_f + rho_f J_f)`` is a
  ``lax.psum`` over the freq axis (sagecal_master.cpp:841-852 was a
  recv+accumulate loop), and ``Bii = pinv(sum_f rho_f B_f B_f^T)`` is a
  psum of small (Npoly, Npoly) terms followed by a replicated pinv;
- the manifold-averaging alignment at the first iteration becomes an
  ``all_gather`` of (M, N, 2, 2) Jones blocks (small) + replicated math.

Data multiplexing (more sub-bands than devices): with Nf = G * ndev the
leading sub-band axis shards into contiguous groups of G per device
(the reference assigns contiguous MS lists per worker,
sagecal_master.cpp:60-224).  ADMM iteration ``it`` solves local group
slot ``it % G`` — the ``Sbegin/Scurrent/Send`` rotation of
sagecal_master.cpp:157-206 / README.md:139-141 — while the z-step psums
the STORED ``Yhat = Y + rho J`` of every sub-band (stale for inactive
slots, exactly the reference's multiplexed semantics where only the
active MS's Y refreshes per iteration).

Iteration protocol (matches slave/master handshake order,
sagecal_slave.cpp:727-895):
  admm 0:  plain (unaugmented) solve of ALL local slots; align J across
           sub-bands on the quotient manifold; Yhat = rho*J; z-step;
           Y = Yhat - rho*BZ.
  admm>0:  augmented solve of the active slot with (Y, BZ);
           Yhat = Y + rho*J; z-step with the NEW J; dual update against
           the NEW consensus, Y = Yhat - rho*BZ_new; optional
           Barzilai-Borwein rho update every other iteration
           (consensus_poly.c:860-911, cadence at sagecal_slave.cpp:899).

Multi-host scaling: build the Mesh over ``jax.devices()`` spanning
hosts (``jax.distributed.initialize``); the same psum/all_gather ride
ICI inside a slice and DCN across — no code change, matching SURVEY.md
section 5's mapping.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from sagecal_tpu.core.types import VisData, jones_to_params, params_to_jones
from sagecal_tpu.parallel import consensus
from sagecal_tpu.parallel.admm import admm_sagefit
from sagecal_tpu.parallel.manifold import manifold_average
from sagecal_tpu.solvers.lm import LMConfig
from sagecal_tpu.solvers.sage import SM_LM_LBFGS, ClusterData


class AdmmResult(NamedTuple):
    p: jax.Array  # (Nf, M, nchunk_max, 8N) per-band solutions
    Y: jax.Array  # (Nf, M, nchunk_max, 8N) duals
    Z: jax.Array  # (M, Npoly, nchunk_max*8N) consensus variable
    rho: jax.Array  # (Nf, M) final penalties
    dual_res: jax.Array  # (nadmm,) dual residual trace
    primal_res: jax.Array  # (nadmm,) mean primal residual ||J - BZ||


def _flat(x):
    return x.reshape(x.shape[:-2] + (-1,))


def _unflat(x, nchunk, n8):
    return x.reshape(x.shape[:-1] + (nchunk, n8))


def _zstep_grouped(Yhat_flat, rho, B_g, axis_name, federated_alpha=None):
    """psum z accumulation + replicated Bii + Z update.

    Yhat_flat (G, M, K); rho (G, M); B_g (G, Npoly) — all local
    sub-bands contribute (vmapped accumulate, summed locally, then
    psum'd across the mesh)."""
    z_local = jnp.sum(
        jax.vmap(consensus.accumulate_z_term)(B_g, Yhat_flat), axis=0
    )
    z = jax.lax.psum(z_local, axis_name)
    P_term = jnp.einsum("gm,gp,gq->mpq", rho, B_g, B_g)
    P_sum = jax.lax.psum(P_term, axis_name)
    if federated_alpha is not None:
        Np = B_g.shape[-1]
        P_sum = P_sum + federated_alpha[:, None, None] * jnp.eye(
            Np, dtype=P_sum.dtype
        )[None]
    Bii = jnp.linalg.pinv(P_sum)
    return consensus.update_global_z(z, Bii)


def make_admm_mesh_fn(
    mesh: Mesh,
    nadmm: int,
    axis_name: str = "freq",
    max_emiter: int = 1,
    plain_emiter: int = 2,
    lm_config: LMConfig = LMConfig(),
    use_manifold_align: bool = True,
    bb_rho: bool = False,
    rho_upper: float = 1e3,
    solver_mode: int = SM_LM_LBFGS,
    robust_nu: Optional[float] = None,
):
    """Build the jitted mesh-wide ADMM calibration function.

    The returned fn takes leading-axis-``Nf`` stacks (sharded over the
    ``freq`` mesh axis; Nf must be a multiple of the mesh size — pad
    with zero-weight bands otherwise):
      fn(data_stack: VisData pytree with (Nf, ...) leaves,
         cdata_stack: ClusterData pytree (Nf, ...),
         p0: (Nf, M, nchunk_max, 8N), rho: (Nf, M), B: (Nf, Npoly))
    and returns an :class:`AdmmResult`.  The whole Nadmm loop runs in one
    jit/shard_map program.

    ``solver_mode``/``robust_nu`` select the local x-step solver the way
    ``sagefit_visibilities_admm`` dispatches (see
    :func:`sagecal_tpu.parallel.admm.admm_sagefit`).
    """

    def _fit(data, cdata, p, Y, BZ, rho_m, emiter):
        return admm_sagefit(
            data, cdata, p, Y, BZ, rho_m,
            max_emiter=emiter, lm_config=lm_config,
            solver_mode=solver_mode, robust_nu=robust_nu,
        )

    def local_loop(data: VisData, cdata: ClusterData, p0, rho, B_g):
        # all array leaves carry the local sub-band group axis G
        G, M, nchunk_max, n8 = p0.shape
        zeros_g = jnp.zeros_like(p0[0])

        # ---- admm 0: plain solve of every local slot -------------------
        def plain_one(_, inp):
            d_g, c_g, p_g, rho_g = inp
            r = _fit(d_g, c_g, p_g, zeros_g, zeros_g,
                     jnp.zeros_like(rho_g), plain_emiter)
            return None, r.p

        _, p = jax.lax.scan(plain_one, None, (data, cdata, p0, rho))

        if use_manifold_align:
            # master-side unitary-ambiguity fix over ALL Nf sub-bands
            # (sagecal_master.cpp:826-838)
            jones = params_to_jones(p)  # (G, M, nchunk, N, 2, 2)
            gath = jax.lax.all_gather(jones, axis_name)  # (ndev, G, ...)
            ndev, G_, Mm = gath.shape[0], gath.shape[1], gath.shape[2]
            gflat = gath.reshape(ndev * G_, Mm, -1, 2, 2)
            aligned = manifold_average(gflat, niter=20)
            idx = jax.lax.axis_index(axis_name)
            own = aligned.reshape((ndev, G_) + aligned.shape[1:])[idx]
            p = jones_to_params(own.reshape(jones.shape)).astype(p0.dtype)

        Yhat = rho[:, :, None, None] * p  # Y=0 so Yhat = rho*J
        Z = _zstep_grouped(_flat(Yhat), rho, B_g, axis_name)

        def bz_of(Z_, g):
            return _unflat(
                consensus.bz_for_freq(Z_, B_g[g]), nchunk_max, n8
            )

        BZ_all = jax.vmap(lambda g: bz_of(Z, g))(jnp.arange(G))
        Y = Yhat - rho[:, :, None, None] * BZ_all

        # ---- admm > 0: rotate over local slots -------------------------
        def one_iter(carry, it):
            p, Y, Z, rho, Yhat_all, Yhat_prev, p_prev = carry
            g = (it - 1) % G  # active local slot (Scurrent rotation)
            d_g = jax.tree_util.tree_map(
                lambda x: jax.lax.dynamic_index_in_dim(x, g, keepdims=False),
                data,
            )
            c_g = jax.tree_util.tree_map(
                lambda x: jax.lax.dynamic_index_in_dim(x, g, keepdims=False),
                cdata,
            )
            p_g = p[g]
            Y_g = Y[g]
            rho_g = rho[g]
            BZ_g = bz_of(Z, g)
            loc = _fit(d_g, c_g, p_g, Y_g, BZ_g, rho_g, max_emiter)
            p1_g = loc.p
            Yhat_g = Y_g + rho_g[:, None, None] * p1_g
            p1 = p.at[g].set(p1_g)
            Yhat_all1 = Yhat_all.at[g].set(Yhat_g)
            Z1 = _zstep_grouped(_flat(Yhat_all1), rho, B_g, axis_name)
            BZ1_g = bz_of(Z1, g)
            Y1 = Y.at[g].set(Yhat_g - rho_g[:, None, None] * BZ1_g)
            dres = consensus.admm_dual_residual(Z1, Z)
            pr = _flat(p1_g - BZ1_g)
            pres = jax.lax.pmean(
                jnp.linalg.norm(pr.ravel()) / jnp.sqrt(pr.size), axis_name
            )
            if bb_rho:
                dY = _flat(Yhat_g) - _flat(Yhat_prev[g])
                dJ = _flat(p1_g) - _flat(p_prev[g])
                rho_new_g = consensus.update_rho_bb(
                    rho_g, jnp.full_like(rho_g, rho_upper), dY, dJ
                )
                # BB cadence: update every other visit to this slot
                # (sagecal_slave.cpp:899)
                visit = (it - 1) // G
                rho1 = rho.at[g].set(
                    jnp.where(visit % 2 == 1, rho_new_g, rho_g)
                )
            else:
                rho1 = rho
            Yhat_prev1 = Yhat_prev.at[g].set(Yhat_g)
            p_prev1 = p_prev.at[g].set(p1_g)
            return (p1, Y1, Z1, rho1, Yhat_all1, Yhat_prev1, p_prev1), (
                dres, pres,
            )

        init = (p, Y, Z, rho, Yhat, Yhat, p)
        (p, Y, Z, rho, _, _, _), (dres, pres) = jax.lax.scan(
            one_iter, init, jnp.arange(1, nadmm)
        )
        dres = jnp.concatenate([jnp.zeros((1,), dres.dtype), dres])
        pres = jnp.concatenate([jnp.zeros((1,), pres.dtype), pres])
        return p, Y, Z, rho, dres, pres

    fspec = P(axis_name)
    rspec = P()

    ndev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))

    @jax.jit
    def fn(data_stack, cdata_stack, p0, rho, B):
        Nf = p0.shape[0]
        if Nf % ndev != 0:
            raise ValueError(
                f"sub-band count {Nf} must be a multiple of the mesh size "
                f"{ndev}; pad with zero-weight bands (rho=0, mask=0) first"
            )
        sm = jax.shard_map(
            local_loop,
            mesh=mesh,
            in_specs=(fspec, fspec, fspec, fspec, fspec),
            out_specs=(fspec, fspec, rspec, fspec, rspec, rspec),
            check_vma=False,
        )
        p, Y, Z, rho_f, dres, pres = sm(data_stack, cdata_stack, p0, rho, B)
        return AdmmResult(p=p, Y=Y, Z=Z, rho=rho_f, dual_res=dres, primal_res=pres)

    return fn


def stack_for_mesh(items):
    """Stack a list of per-frequency pytrees on a new leading axis for
    sharding over the ``freq`` mesh axis.  Static (non-pytree) fields
    must be identical across items."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *items)
