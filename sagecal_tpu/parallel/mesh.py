"""Mesh-parallel consensus ADMM: frequencies on a device mesh axis.

This replaces the reference's MPI master/worker star
(``/root/reference/src/MPI/sagecal_master.cpp`` /
``sagecal_slave.cpp``, p2p tags ``proto.h:24-59``) with a single SPMD
program over a ``jax.sharding.Mesh``:

- each device along the ``freq`` axis owns one sub-band's visibility
  tile — the reference's "one MPI worker per group of MS";
- the ADMM x-step (:func:`sagecal_tpu.parallel.admm.admm_sagefit`) runs
  independently per shard;
- the master's Z-update ``z = sum_f B_f (x) (Y_f + rho_f J_f)`` is a
  ``lax.psum`` over the freq axis (sagecal_master.cpp:841-852 was a
  recv+accumulate loop), and ``Bii = pinv(sum_f rho_f B_f B_f^T)`` is a
  psum of small (Npoly, Npoly) terms followed by a replicated pinv;
- the manifold-averaging alignment at the first iteration becomes an
  ``all_gather`` of (M, N, 2, 2) Jones blocks (small) + replicated math.

Iteration protocol (matches slave/master handshake order,
sagecal_slave.cpp:727-895):
  admm 0:  plain (unaugmented) solve; align J across frequencies on the
           quotient manifold; Yhat = rho*J; z-step; Y = Yhat - rho*BZ.
  admm>0:  augmented solve with (Y, BZ); Yhat = Y + rho*J; z-step with
           the NEW J; dual update against the NEW consensus,
           Y = Yhat - rho*BZ_new; optional Barzilai-Borwein rho update
           every other iteration (consensus_poly.c:860-911, cadence at
           sagecal_slave.cpp:899).

Multi-host scaling: build the Mesh over ``jax.devices()`` spanning
hosts (``jax.distributed.initialize``); the same psum/all_gather ride
ICI inside a slice and DCN across — no code change, matching SURVEY.md
section 5's mapping.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from sagecal_tpu.core.types import VisData, jones_to_params, params_to_jones
from sagecal_tpu.parallel import consensus
from sagecal_tpu.parallel.admm import admm_sagefit
from sagecal_tpu.parallel.manifold import manifold_average
from sagecal_tpu.solvers.lm import LMConfig
from sagecal_tpu.solvers.sage import ClusterData


class AdmmResult(NamedTuple):
    p: jax.Array  # (Nf, M, nchunk_max, 8N) per-band solutions
    Y: jax.Array  # (Nf, M, nchunk_max, 8N) duals
    Z: jax.Array  # (M, Npoly, nchunk_max*8N) consensus variable
    rho: jax.Array  # (Nf, M) final penalties
    dual_res: jax.Array  # (nadmm,) dual residual trace
    primal_res: jax.Array  # (nadmm,) mean primal residual ||J - BZ||


def _flat(x):
    return x.reshape(x.shape[:-2] + (-1,))


def _unflat(x, nchunk, n8):
    return x.reshape(x.shape[:-1] + (nchunk, n8))


def _zstep(Yhat_flat, rho, B_f, axis_name, federated_alpha=None):
    """psum z accumulation + replicated Bii + Z update.  Yhat_flat (M, K)."""
    z = jax.lax.psum(consensus.accumulate_z_term(B_f, Yhat_flat), axis_name)
    P_term = jnp.einsum("m,p,q->mpq", rho, B_f, B_f)
    P_sum = jax.lax.psum(P_term, axis_name)
    if federated_alpha is not None:
        Np = B_f.shape[0]
        P_sum = P_sum + federated_alpha[:, None, None] * jnp.eye(Np, dtype=P_sum.dtype)[None]
    Bii = jnp.linalg.pinv(P_sum)
    return consensus.update_global_z(z, Bii)


def make_admm_mesh_fn(
    mesh: Mesh,
    nadmm: int,
    axis_name: str = "freq",
    max_emiter: int = 1,
    plain_emiter: int = 2,
    lm_config: LMConfig = LMConfig(),
    use_manifold_align: bool = True,
    bb_rho: bool = False,
    rho_upper: float = 1e3,
):
    """Build the jitted mesh-wide ADMM calibration function.

    The returned fn takes leading-axis-``Nf`` stacks (sharded over the
    ``freq`` mesh axis):
      fn(data_stack: VisData pytree with (Nf, ...) leaves,
         cdata_stack: ClusterData pytree (Nf, ...),
         p0: (Nf, M, nchunk_max, 8N), rho: (Nf, M), B: (Nf, Npoly))
    and returns an :class:`AdmmResult`.  The whole Nadmm loop runs in one
    jit/shard_map program.
    """

    def local_loop(data: VisData, cdata: ClusterData, p0, rho, B_f):
        M, nchunk_max, n8 = p0.shape
        zeros = jnp.zeros_like(p0)

        # ---- admm 0: plain solve (sagecal_slave.cpp:727 sagefit) -------
        r0 = admm_sagefit(
            data, cdata, p0, zeros, zeros, jnp.zeros_like(rho),
            max_emiter=plain_emiter, lm_config=lm_config,
        )
        p = r0.p
        if use_manifold_align:
            # master-side unitary-ambiguity fix (sagecal_master.cpp:826-838)
            jones = params_to_jones(p)  # (M, nchunk, N, 2, 2)
            gath = jax.lax.all_gather(jones, axis_name)  # (Nf, M, nchunk, N, 2, 2)
            Nf = gath.shape[0]
            gflat = gath.reshape(Nf, M, -1, 2, 2)
            aligned = manifold_average(gflat, niter=20)
            idx = jax.lax.axis_index(axis_name)
            p = jones_to_params(aligned[idx].reshape(jones.shape)).astype(p0.dtype)

        Yhat = rho[:, None, None] * p  # Y=0 so Yhat = rho*J
        Z = _zstep(_flat(Yhat), rho, B_f, axis_name)
        BZ = _unflat(consensus.bz_for_freq(Z, B_f), nchunk_max, n8)
        Y = Yhat - rho[:, None, None] * BZ

        # ---- admm > 0 ---------------------------------------------------
        def one_iter(carry, it):
            p, Y, Z, rho, Yhat_prev, p_prev = carry
            BZ = _unflat(consensus.bz_for_freq(Z, B_f), nchunk_max, n8)
            loc = admm_sagefit(
                data, cdata, p, Y, BZ, rho,
                max_emiter=max_emiter, lm_config=lm_config,
            )
            p1 = loc.p
            Yhat = Y + rho[:, None, None] * p1
            Z1 = _zstep(_flat(Yhat), rho, B_f, axis_name)
            BZ1 = _unflat(consensus.bz_for_freq(Z1, B_f), nchunk_max, n8)
            Y1 = Yhat - rho[:, None, None] * BZ1
            dres = consensus.admm_dual_residual(Z1, Z)
            pr = _flat(p1 - BZ1)
            pres = jax.lax.pmean(
                jnp.linalg.norm(pr.ravel()) / jnp.sqrt(pr.size), axis_name
            )
            if bb_rho:
                dY = _flat(Yhat) - _flat(Yhat_prev)
                dJ = _flat(p1) - _flat(p_prev)
                rho_new = consensus.update_rho_bb(
                    rho, jnp.full_like(rho, rho_upper), dY, dJ
                )
                # BB cadence: update every other iteration
                # (sagecal_slave.cpp:899)
                rho1 = jnp.where(it % 2 == 0, rho_new, rho)
            else:
                rho1 = rho
            return (p1, Y1, Z1, rho1, Yhat, p1), (dres, pres)

        init = (p, Y, Z, rho, Yhat, p)
        (p, Y, Z, rho, _, _), (dres, pres) = jax.lax.scan(
            one_iter, init, jnp.arange(1, nadmm)
        )
        dres = jnp.concatenate([jnp.zeros((1,), dres.dtype), dres])
        pres = jnp.concatenate([jnp.zeros((1,), pres.dtype), pres])
        return p[None], Y[None], Z, rho[None], dres, pres

    fspec = P(axis_name)
    rspec = P()

    ndev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))

    @jax.jit
    def fn(data_stack, cdata_stack, p0, rho, B):
        if p0.shape[0] != ndev:
            raise ValueError(
                f"leading (sub-band) axis {p0.shape[0]} != mesh size {ndev}; "
                "data multiplexing (more sub-bands than devices) is not yet "
                "supported — group sub-bands per device first"
            )
        sm = jax.shard_map(
            lambda d, c, p, r, b: local_loop(
                jax.tree_util.tree_map(lambda x: x[0], d),
                jax.tree_util.tree_map(lambda x: x[0], c),
                p[0], r[0], b[0],
            ),
            mesh=mesh,
            in_specs=(fspec, fspec, fspec, fspec, fspec),
            out_specs=(fspec, fspec, rspec, fspec, rspec, rspec),
            check_vma=False,
        )
        p, Y, Z, rho_f, dres, pres = sm(data_stack, cdata_stack, p0, rho, B)
        return AdmmResult(p=p, Y=Y, Z=Z, rho=rho_f, dual_res=dres, primal_res=pres)

    return fn


def stack_for_mesh(items):
    """Stack a list of per-frequency pytrees on a new leading axis for
    sharding over the ``freq`` mesh axis.  Static (non-pytree) fields
    must be identical across items."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *items)
