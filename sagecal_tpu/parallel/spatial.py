"""Spatial regularization of consensus solutions + model-order selection.

Redesign of ``/root/reference/src/lib/Dirac/fista.c`` (elastic-net
regression of the consensus variable Z onto a spatial basis Phi by
FISTA) and ``mdl.c`` (AIC/MDL scan over polynomial orders, the ``-M``
master option).  The master-side pthread loops become jitted
``lax.scan``/einsum bodies.

Conventions (fista.c:20-36):
  Zs:    (2*Npoly*N, 2G) complex — the spatial model being estimated;
  Zbar:  (M, 2*Npoly*N, 2) — per-cluster consensus blocks;
  Phi:   (M, 2G, 2) — per-cluster spatial basis blocks;
  Phikk: (2G, 2G) = sum_k Phi_k Phi_k^H + lambda I.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sagecal_tpu.parallel import consensus

FISTA_L_MIN = 1e-9
FISTA_L_MAX = 1e9


def build_spatial_basis(ll, mm, n0: int, beta: float):
    """Per-cluster spatial basis blocks Phi: (M, 2G, 2), G = n0*n0,
    from shapelet image-plane modes evaluated at the cluster centroids
    (the master's basis setup, sagecal_master.cpp:293-423):
    Phi_k = kron(phi(l_k, m_k), I_2)."""
    from sagecal_tpu.ops.shapelets import image_mode_matrix

    phi = image_mode_matrix(jnp.asarray(ll), jnp.asarray(mm), beta, n0)  # (M, G)
    M, G = phi.shape
    eye = jnp.eye(2, dtype=jnp.complex128)
    Phi = jnp.einsum("mg,ij->mgij", phi.astype(jnp.complex128), eye)
    return Phi.reshape(M, 2 * G, 2)  # rows ordered (g, i)


def phikk_matrix(Phi, lam: float = 1e-6):
    """sum_k Phi_k Phi_k^H + lambda I: (2G, 2G)."""
    P = jnp.einsum("mac,mbc->ab", Phi, jnp.conj(Phi))
    return P + lam * jnp.eye(P.shape[0], dtype=P.dtype)


def _soft_threshold_complex(z, thresh):
    """Independent re/im soft threshold (fista.c:86-99)."""
    re = jnp.sign(jnp.real(z)) * jnp.maximum(jnp.abs(jnp.real(z)) - thresh, 0.0)
    im = jnp.sign(jnp.imag(z)) * jnp.maximum(jnp.abs(jnp.imag(z)) - thresh, 0.0)
    return jax.lax.complex(re, im)


def update_spatialreg_fista(
    Zbar, Phikk, Phi, mu: float, maxiter: int = 40,
    Z_diff=None, Psi=None, gamma: float = 0.0,
):
    """Zs = argmin sum_k ||Zbar_k - Zs Phi_k||^2 + lambda ||Zs||^2 +
    mu ||Zs||_1 [+ Psi^H (Zs - Z_diff) + gamma/2 ||Zs - Z_diff||^2]
    by FISTA (``update_spatialreg_fista[_with_diffconstraint]``,
    fista.c:38,131).  Returns Zs (D, 2G) where D = Zbar.shape[1].
    """
    M, D, _ = Zbar.shape
    twoG = Phikk.shape[0]
    # Lipschitz constant of the gradient = lambda_max(Phikk) (exact for
    # this quadratic).  The reference uses ||Phikk||_F^2 (fista.c:46),
    # a large overestimate that slows convergence ~100x for no benefit;
    # Phikk is tiny (2G x 2G) so the eigendecomposition is free.
    L = jnp.max(jnp.linalg.eigvalsh(Phikk))
    L = jnp.clip(jnp.real(L), FISTA_L_MIN, FISTA_L_MAX)
    if gamma > 0.0:
        L = L + gamma

    ZbPh = jnp.einsum("mdc,mgc->dg", Zbar, jnp.conj(Phi))  # sum_k Zbar_k Phi_k^H

    def step(carry, _):
        Z, Y, t = carry
        gradf = Y @ Phikk - ZbPh
        if Z_diff is not None:
            gradf = gradf + 0.5 * Psi + 0.5 * gamma * (Y - Z_diff)
        Ynew = Y - gradf / L
        Znew = _soft_threshold_complex(Ynew, mu / L)
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        Yn = Znew + ((t - 1.0) / t_new) * (Znew - Z)
        return (Znew, Yn, t_new), None

    Z0 = jnp.zeros((D, twoG), Zbar.dtype)
    (Z, _, _), _ = jax.lax.scan(
        step, (Z0, Z0, jnp.asarray(1.0, jnp.real(Zbar).dtype)), None,
        length=maxiter,
    )
    return Z


def spatial_model_apply(Zs, Phi):
    """Predicted per-cluster blocks Zs Phi_k: (M, D, 2) — the constraint
    target Zbar ~ Zs Phi used in the master's X update
    (sagecal_master.cpp:887-930)."""
    return jnp.einsum("dg,mgc->mdc", Zs, Phi)


def minimum_description_length(
    J, rho, freqs, freq0: float, weight=None,
    polytype: int = consensus.POLY_BERNSTEIN,
    Kstart: int = 1, Kfinish: int = 5,
) -> Tuple[np.ndarray, np.ndarray, int, int]:
    """Scan consensus polynomial orders and score AIC/MDL
    (``minimum_description_length``, mdl.c:43-260).

    J: (F, M, K) rho-scaled solutions (the master's weight*rho*J blocks,
    K = 8N); rho: (M,); weight: (F,) per-frequency unflagged fractions.
    Returns (aic, mdl, best_aic_order, best_mdl_order).
    """
    J = jnp.asarray(J)
    F, M, K = J.shape
    rho = jnp.asarray(rho)
    w = jnp.ones((F,)) if weight is None else jnp.asarray(weight)
    aic = []
    mdl = []
    orders = list(range(Kstart, Kfinish + 1))
    inv_rho = jnp.where(rho > 0, 1.0 / jnp.where(rho == 0, 1.0, rho), 0.0)
    for Npoly in orders:
        ptype = consensus.POLY_NORMALIZED if Npoly == 1 else polytype
        B = consensus.setup_polynomials(np.asarray(freqs), freq0, Npoly, ptype)
        B = jnp.asarray(B, J.dtype)
        Bi = consensus.find_prod_inverse(B, w)  # (Npoly, Npoly)
        # z accumulation: sum_f B[f,p] * J[f] then 1/rho per cluster
        z = jnp.einsum("fp,fmk->mpk", B, J) * inv_rho[:, None, None]
        Z = jnp.einsum("pq,mqk->mpk", Bi, z)  # (M, Npoly, K)
        # residual: J[f] - weight*rho*(B Z)
        BZ = jnp.einsum("fp,mpk->fmk", B, Z)
        scaled = BZ * (rho[None, :, None] * w[:, None, None])
        res = (J - scaled) * (
            inv_rho[None, :, None]
            * jnp.where(w[:, None, None] > 0, 1.0 / jnp.maximum(w[:, None, None], 1e-30), 0.0)
        )
        RSS = float(jnp.sum(res**2)) / (K * M)
        aic.append(F * np.log(RSS / F) + 2.0 * Npoly)
        mdl.append(0.5 * F * np.log(RSS / F) + 0.5 * Npoly * np.log(F))
    aic = np.asarray(aic)
    mdl = np.asarray(mdl)
    return aic, mdl, orders[int(np.argmin(aic))], orders[int(np.argmin(mdl))]
