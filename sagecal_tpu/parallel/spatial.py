"""Spatial regularization of consensus solutions + model-order selection.

Redesign of ``/root/reference/src/lib/Dirac/fista.c`` (elastic-net
regression of the consensus variable Z onto a spatial basis Phi by
FISTA) and ``mdl.c`` (AIC/MDL scan over polynomial orders, the ``-M``
master option).  The master-side pthread loops become jitted
``lax.scan``/einsum bodies.

Conventions (fista.c:20-36):
  Zs:    (2*Npoly*N, 2G) complex — the spatial model being estimated;
  Zbar:  (M, 2*Npoly*N, 2) — per-cluster consensus blocks;
  Phi:   (M, 2G, 2) — per-cluster spatial basis blocks;
  Phikk: (2G, 2G) = sum_k Phi_k Phi_k^H + lambda I.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sagecal_tpu.parallel import consensus

FISTA_L_MIN = 1e-9
FISTA_L_MAX = 1e9


def _cdtype():
    """Widest complex dtype the process supports: complex128 under x64,
    complex64 otherwise (a hard c128 request in a non-x64 process only
    earns a truncation warning and silently runs c64 anyway)."""
    return jnp.complex128 if jax.config.jax_enable_x64 else jnp.complex64


def _fdtype():
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _assoc_legendre(l: int, m: int, x):
    """Associated Legendre P_l^m(x) with the Condon-Shortley phase, by
    the standard recurrence (elementbeam.c:560-588 ``P``)."""
    x = np.asarray(x, np.float64)
    pmm = np.ones_like(x)
    if m > 0:
        somx2 = np.sqrt((1.0 - x) * (1.0 + x))
        fact = 1.0
        for _ in range(1, m + 1):
            pmm = pmm * (-fact) * somx2
            fact += 2.0
    if l == m:
        return pmm
    pmmp1 = x * (2.0 * m + 1.0) * pmm
    if l == m + 1:
        return pmmp1
    pll = pmm
    for i in range(m + 2, l + 1):
        pll = ((2.0 * i - 1.0) * x * pmmp1 - (i + m - 1.0) * pmm) / (i - m)
        pmm = pmmp1
        pmmp1 = pll
    return pll


def sharmonic_mode_matrix(theta, phi, n0: int) -> np.ndarray:
    """Spherical-harmonic basis (Nt, n0^2) complex — one mode vector per
    (theta, phi) point (``sharmonic_modes``, elementbeam.c:600-816 /
    Dirac_radio.h:376).

    Mode order: l = 0..n0-1, then m = -l..l (negative m stored as the
    conjugate of the +|m| mode, WITHOUT an extra (-1)^m — the
    reference's own convention, elementbeam.c:768-775).  Y_l^m =
    0.5*sqrt((2l+1)/pi*(l-m)!/(l+m)!) * P_l^m(cos th) * e^{i m ph}.
    theta in [0, pi/2], phi in [0, 2 pi).  Host-side numpy: the basis is
    built once per run over M cluster centroids."""
    theta = np.atleast_1d(np.asarray(theta, np.float64))
    phi = np.atleast_1d(np.asarray(phi, np.float64))
    Nt = theta.shape[0]
    ct = np.cos(theta)
    out = np.empty((Nt, n0 * n0), np.complex128)
    idx = 0
    for l in range(n0):
        pos = {}
        for m in range(0, l + 1):
            pre = 0.5 * math.sqrt(
                (2.0 * l + 1.0) / math.pi
                * math.factorial(l - m) / math.factorial(l + m)
            )
            pos[m] = pre * _assoc_legendre(l, m, ct) * np.exp(1j * m * phi)
        for mi in range(0, 2 * l + 1):
            m_true = mi - l
            out[:, idx] = (np.conj(pos[-m_true]) if m_true < 0
                           else pos[m_true])
            idx += 1
    return out


def spatial_basis_modes(ll, mm, n0: int, beta: Optional[float] = None,
                        basis: str = "shapelet"):
    """Raw mode matrix (M, G) over cluster centroids, either basis
    (the master's ``spatialreg_basis`` switch, sagecal_master.cpp:359-367
    and 380-397):
      shapelet:  modes at (-l, m) — the diffuse sky shapelet model is in
        (-l, m), master:360-362 — with auto scale
        beta = 4*sqrt(l_max^2/M) when ``beta`` is None (master:380);
      sharmonic: modes at (r, th) = (sqrt(l^2+m^2)*pi/2, atan2(m, l))
        (master:364-366), no scale parameter.
    Returns (modes (M, G) complex128, beta_used)."""
    ll = np.asarray(ll, np.float64)
    mm = np.asarray(mm, np.float64)
    if basis == "sharmonic":
        rr = np.sqrt(ll * ll + mm * mm) * (np.pi / 2.0)
        tt = np.arctan2(mm, ll)
        return sharmonic_mode_matrix(rr, tt, n0), 0.0
    if basis != "shapelet":
        raise ValueError(f"unknown spatial basis {basis!r}")
    from sagecal_tpu.ops.shapelets import image_mode_matrix

    if beta is None or beta <= 0.0:
        l_max = max(float(np.max(np.abs(ll))), float(np.max(np.abs(mm))),
                    1e-12)
        beta = 4.0 * math.sqrt(l_max * l_max / max(len(ll), 1))
    phi = np.asarray(
        image_mode_matrix(jnp.asarray(-ll), jnp.asarray(mm), beta, n0),
        np.complex128,
    )
    return phi, float(beta)


def basis_blocks(modes) -> jax.Array:
    """Mode matrix (M, G) -> per-cluster blocks Phi_k = kron(phi_k, I_2):
    (M, 2G, 2), rows ordered (g, i) (sagecal_master.cpp:408-414)."""
    modes = jnp.asarray(modes, _cdtype())
    M, G = modes.shape
    eye = jnp.eye(2, dtype=modes.dtype)
    Phi = jnp.einsum("mg,ij->mgij", modes, eye)
    return Phi.reshape(M, 2 * G, 2)


def build_spatial_basis(ll, mm, n0: int, beta: Optional[float] = None,
                        basis: str = "shapelet"):
    """Per-cluster spatial basis blocks Phi: (M, 2G, 2), G = n0*n0,
    evaluated at the cluster centroids (the master's basis setup,
    sagecal_master.cpp:293-423).  See :func:`spatial_basis_modes` for
    the basis/scale conventions."""
    modes, _ = spatial_basis_modes(ll, mm, n0, beta, basis)
    return basis_blocks(modes)


def phikk_matrix(Phi, lam: float = 1e-6):
    """sum_k Phi_k Phi_k^H + lambda I: (2G, 2G)."""
    P = jnp.einsum("mac,mbc->ab", Phi, jnp.conj(Phi))
    return P + lam * jnp.eye(P.shape[0], dtype=P.dtype)


def _soft_threshold_complex(z, thresh):
    """Independent re/im soft threshold (fista.c:86-99)."""
    re = jnp.sign(jnp.real(z)) * jnp.maximum(jnp.abs(jnp.real(z)) - thresh, 0.0)
    im = jnp.sign(jnp.imag(z)) * jnp.maximum(jnp.abs(jnp.imag(z)) - thresh, 0.0)
    return jax.lax.complex(re, im)


def update_spatialreg_fista(
    Zbar, Phikk, Phi, mu: float, maxiter: int = 40,
    Z_diff=None, Psi=None, gamma: float = 0.0,
):
    """Zs = argmin sum_k ||Zbar_k - Zs Phi_k||^2 + lambda ||Zs||^2 +
    mu ||Zs||_1 [+ Psi^H (Zs - Z_diff) + gamma/2 ||Zs - Z_diff||^2]
    by FISTA (``update_spatialreg_fista[_with_diffconstraint]``,
    fista.c:38,131).  Returns Zs (D, 2G) where D = Zbar.shape[1].
    """
    M, D, _ = Zbar.shape
    twoG = Phikk.shape[0]
    # Lipschitz constant of the gradient = lambda_max(Phikk) (exact for
    # this quadratic).  The reference uses ||Phikk||_F^2 (fista.c:46),
    # a large overestimate that slows convergence ~100x for no benefit;
    # Phikk is tiny (2G x 2G) so the eigendecomposition is free.
    L = jnp.max(jnp.linalg.eigvalsh(Phikk))
    L = jnp.clip(jnp.real(L), FISTA_L_MIN, FISTA_L_MAX)
    if gamma > 0.0:
        L = L + gamma

    ZbPh = jnp.einsum("mdc,mgc->dg", Zbar, jnp.conj(Phi))  # sum_k Zbar_k Phi_k^H

    def step(carry, _):
        Z, Y, t = carry
        gradf = Y @ Phikk - ZbPh
        if Z_diff is not None:
            gradf = gradf + 0.5 * Psi + 0.5 * gamma * (Y - Z_diff)
        Ynew = Y - gradf / L
        Znew = _soft_threshold_complex(Ynew, mu / L)
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        Yn = Znew + ((t - 1.0) / t_new) * (Znew - Z)
        return (Znew, Yn, t_new), None

    Z0 = jnp.zeros((D, twoG), Zbar.dtype)
    (Z, _, _), _ = jax.lax.scan(
        step, (Z0, Z0, jnp.asarray(1.0, jnp.real(Zbar).dtype)), None,
        length=maxiter,
    )
    return Z


def spatial_model_apply(Zs, Phi):
    """Predicted per-cluster blocks Zs Phi_k: (M, D, 2) — the constraint
    target Zbar ~ Zs Phi used in the master's X update
    (sagecal_master.cpp:887-930)."""
    return jnp.einsum("dg,mgc->mdc", Zs, Phi)


def minimum_description_length(
    J, rho, freqs, freq0: float, weight=None,
    polytype: int = consensus.POLY_BERNSTEIN,
    Kstart: int = 1, Kfinish: int = 5,
) -> Tuple[np.ndarray, np.ndarray, int, int]:
    """Scan consensus polynomial orders and score AIC/MDL
    (``minimum_description_length``, mdl.c:43-260).

    J: (F, M, K) rho-scaled solutions (the master's weight*rho*J blocks,
    K = 8N); rho: (M,); weight: (F,) per-frequency unflagged fractions.
    Returns (aic, mdl, best_aic_order, best_mdl_order).
    """
    J = jnp.asarray(J)
    F, M, K = J.shape
    rho = jnp.asarray(rho)
    w = jnp.ones((F,)) if weight is None else jnp.asarray(weight)
    aic = []
    mdl = []
    orders = list(range(Kstart, Kfinish + 1))
    inv_rho = jnp.where(rho > 0, 1.0 / jnp.where(rho == 0, 1.0, rho), 0.0)
    for Npoly in orders:
        ptype = consensus.POLY_NORMALIZED if Npoly == 1 else polytype
        B = consensus.setup_polynomials(np.asarray(freqs), freq0, Npoly, ptype)
        B = jnp.asarray(B, J.dtype)
        Bi = consensus.find_prod_inverse(B, w)  # (Npoly, Npoly)
        # z accumulation: sum_f B[f,p] * J[f] then 1/rho per cluster
        z = jnp.einsum("fp,fmk->mpk", B, J) * inv_rho[:, None, None]
        Z = jnp.einsum("pq,mqk->mpk", Bi, z)  # (M, Npoly, K)
        # residual: J[f] - weight*rho*(B Z)
        BZ = jnp.einsum("fp,mpk->fmk", B, Z)
        scaled = BZ * (rho[None, :, None] * w[:, None, None])
        res = (J - scaled) * (
            inv_rho[None, :, None]
            * jnp.where(w[:, None, None] > 0, 1.0 / jnp.maximum(w[:, None, None], 1e-30), 0.0)
        )
        RSS = float(jnp.sum(res**2)) / (K * M)
        aic.append(F * np.log(RSS / F) + 2.0 * Npoly)
        mdl.append(0.5 * F * np.log(RSS / F) + 0.5 * Npoly * np.log(F))
    aic = np.asarray(aic)
    mdl = np.asarray(mdl)
    return aic, mdl, orders[int(np.argmin(aic))], orders[int(np.argmin(mdl))]


def find_initial_spatial(B, modes, N: int) -> jax.Array:
    """Initial diffuse spatial model Zdiff0: (2*N*Npoly, 2G) such that
    B_f Zdiff0 Phi_k ~ 1_N kron I_2 for every frequency f and cluster k
    (``find_initial_spatial``, consensus_poly.c:1113; intent stated at
    sagecal_master.cpp:658-660).

    Closed form: Zdiff0 rows (p, station i, comp a), Npoly-major in our
    mesh flattening (mesh._zbar_blocks_of_z);
    Zdiff0[p*2N + 2i + a, 2g + b] = c_p * delta_ab * s_g with
      c = pinv(sum_f b_f b_f^T) sum_f b_f          (frequency fit of 1)
      s = (sum_k phi_k)^H pinv(sum_k phi_k phi_k^H) (spatial fit of 1).
    NOTE the reference's assembly loop scales by sum_f b_f instead of
    the pseudo-inverse product its own comment derives
    (consensus_poly.c:1455 vs master:660); we implement the derivation.

    B: (Nf, Npoly) real; modes: (Meff, G) complex (spatial_basis_modes).
    """
    B = np.asarray(B, np.float64)
    sum_b = B.sum(axis=0)
    c = np.linalg.pinv(B.T @ B) @ sum_b  # (Npoly,)
    phi = np.asarray(modes, np.complex128)  # (Meff, G)
    sum_phi = phi.sum(axis=0)
    P = phi.T @ np.conj(phi)  # sum_k phi_k phi_k^H
    s = np.conj(sum_phi) @ np.linalg.pinv(P)  # (G,)
    Zc = np.tile(np.kron(s[None, :], np.eye(2)), (N, 1))  # (2N, 2G)
    Z0 = np.concatenate([cp * Zc for cp in c], axis=0)  # (Npoly*2N, 2G)
    return jnp.asarray(Z0)


def bz_spatial(Zs, B_f, N: int) -> jax.Array:
    """Per-frequency spatial model B_f x Zs: (2N, 2G) from the full
    Zs (2*N*Npoly, 2G), Npoly-major rows — the slave's reduction of the
    master-sent spatial model before the diffuse re-predict
    (sagecal_slave.cpp:670-684)."""
    Zs = jnp.asarray(Zs)
    Npoly = B_f.shape[-1]
    blocks = Zs.reshape(Npoly, 2 * N, Zs.shape[-1])
    return jnp.einsum("p,pij->ij", jnp.asarray(B_f, _fdtype()), blocks)
