"""Differentiable sky-model refinement (ROADMAP item 5).

Bilevel calibration: an outer LBFGS over sky parameters (fluxes,
spectral indices, positions, shapelet coefficients — see
:class:`~sagecal_tpu.refine.skyparams.SkySpec`) wrapped around the
inner gain solve, with gradients through the inner fixed point via the
implicit function theorem (``jax.custom_vjp`` + CG adjoint) or
truncated unrolling.  Coherencies are recomputed from the sky inside
the objective — the XLA predict path; the fused Pallas kernel has no
coherency cotangent and fails loudly if asked
(``ops.rime_kernel.FusedSkyGradientError``).
"""

from sagecal_tpu.refine.implicit import (
    cg_solve,
    gauss_newton_solve,
    make_inner_solver,
)
from sagecal_tpu.refine.objective import (
    RefineProblem,
    cluster_coherencies,
    cluster_data_from_theta,
    inner_cost,
    outer_cost,
    require_xla_predict,
    residual_vec,
)
from sagecal_tpu.refine.outer import (
    RefineResult,
    make_outer_value_and_grad,
    run_refine,
)
from sagecal_tpu.refine.skyparams import SkySpec

__all__ = [
    "RefineProblem",
    "RefineResult",
    "SkySpec",
    "cg_solve",
    "cluster_coherencies",
    "cluster_data_from_theta",
    "gauss_newton_solve",
    "inner_cost",
    "make_inner_solver",
    "make_outer_value_and_grad",
    "outer_cost",
    "require_xla_predict",
    "residual_vec",
    "run_refine",
]
