"""Differentiating through the inner calibration solve.

Two interchangeable gradient routes for ``d p*(theta) / d theta``, both
pinned against finite differences in tests/test_refine.py:

- **implicit** (default): the JAX-AMG pattern (arXiv:2606.09001) — run
  the inner solver however convergence is best achieved, then apply the
  implicit function theorem at its fixed point via ``jax.custom_vjp``.
  At ``grad_p f(p*, theta) = 0`` the adjoint system is
  ``H v = pbar`` with ``H = d^2f/dp^2``, solved matrix-free with CG;
  the theta cotangent is ``-d/dtheta <grad_p f(p*, theta), v>``.
  Memory is O(1) in inner iteration count and the backward cost is a
  handful of Hessian-vector products.
- **unrolled**: reverse-differentiate straight through a
  fixed-iteration inner solve.  Exact for what the solver actually
  computed (even far from the fixed point) but costs memory linear in
  the iteration count — the truncated fallback for ill-conditioned
  problems where the IFT premise (a converged fixed point) is shaky.

The inner solver itself is a damped Gauss-Newton under ``lax.scan``
with a fixed iteration budget — deliberately NOT the production
``sagefit``/``lbfgs_fit`` drivers, whose ``lax.while_loop`` control
flow is not reverse-differentiable and would silently break the
unrolled route.

Adjoint matvec options: ``"hvp"`` (default) is the exact
Hessian-vector product of the inner cost via jvp-of-grad;
``"jtj"`` is the Gauss-Newton approximation ``J^T J v + ridge v``
(cheaper, exact when residuals vanish at the fit).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from sagecal_tpu.refine.objective import (
    RefineProblem,
    inner_cost,
    residual_vec,
)


def cg_solve(matvec: Callable, b: jnp.ndarray, iters: int) -> jnp.ndarray:
    """Fixed-iteration conjugate gradients on SPD ``matvec`` — plain
    ``lax.scan`` so it is itself reverse-differentiable (the unrolled
    route runs CG inside every GN step).  Guards keep iterations past
    convergence exact no-ops (rs -> 0 freezes the state) instead of
    dividing by zero."""
    x0 = jnp.zeros_like(b)
    tiny = jnp.asarray(jnp.finfo(b.dtype).tiny, b.dtype)

    def step(carry, _):
        x, r, p, rs = carry
        Ap = matvec(p)
        denom = jnp.dot(p, Ap)
        ok = denom > tiny
        alpha = jnp.where(ok, rs / jnp.where(ok, denom, 1.0), 0.0)
        x1 = x + alpha * p
        r1 = r - alpha * Ap
        rs1 = jnp.dot(r1, r1)
        beta = jnp.where(rs > tiny, rs1 / jnp.where(rs > tiny, rs, 1.0), 0.0)
        p1 = r1 + beta * p
        return (x1, r1, p1, rs1), None

    (x, _, _, _), _ = jax.lax.scan(
        step, (x0, b, b, jnp.dot(b, b)), None, length=iters)
    return x


def _inner_grad(problem: RefineProblem, p, theta):
    return jax.grad(inner_cost, argnums=1)(problem, p, theta)


def _hessian_matvec(problem: RefineProblem, p, theta, v, matvec: str,
                    damping: float = 0.0):
    """d^2 f / dp^2 @ v, exact ("hvp") or Gauss-Newton ("jtj")."""
    if matvec == "jtj":
        rfn = lambda pp: residual_vec(problem, pp, theta)  # noqa: E731
        _, Jv = jax.jvp(rfn, (p,), (v,))
        _, vjp = jax.vjp(rfn, p)
        return vjp(Jv)[0] + (problem.ridge + damping) * v
    if matvec != "hvp":
        raise ValueError(f"unknown adjoint matvec {matvec!r} "
                         "(expected 'hvp' or 'jtj')")
    _, Hv = jax.jvp(lambda pp: _inner_grad(problem, pp, theta), (p,), (v,))
    return Hv + damping * v


def gauss_newton_solve(
    problem: RefineProblem,
    theta: jnp.ndarray,
    p0: jnp.ndarray,
    iters: int = 12,
    cg_iters: int = 32,
    damping: float = 1e-6,
) -> jnp.ndarray:
    """Damped Gauss-Newton on the inner cost, fixed iteration budget.

    Each step solves ``(J^T J + (ridge + damping) I) dp = -grad_p f``
    with CG — all ``lax.scan``, so the whole solve reverse-
    differentiates for the unrolled route."""

    def step(p, _):
        rfn = lambda pp: residual_vec(problem, pp, theta)  # noqa: E731
        r, vjp = jax.vjp(rfn, p)
        g = vjp(r)[0] + problem.ridge * (p - problem.anchor())

        def mv(v):
            _, Jv = jax.jvp(rfn, (p,), (v,))
            return vjp(Jv)[0] + (problem.ridge + damping) * v

        dp = cg_solve(mv, -g, cg_iters)
        return p + dp, None

    p, _ = jax.lax.scan(step, p0, None, length=iters)
    return p


def make_inner_solver(
    problem: RefineProblem,
    iters: int = 12,
    cg_iters: int = 32,
    damping: float = 1e-6,
    gradient: str = "implicit",
    adjoint_cg_iters: int = 64,
    adjoint_matvec: str = "hvp",
) -> Callable:
    """``solve(theta, p0) -> p*`` with the chosen gradient route.

    ``gradient="implicit"``: custom_vjp applying the IFT adjoint at the
    returned point (CG on the inner Hessian, see module docstring);
    ``gradient="unrolled"``: plain reverse-mode through the fixed
    GN iteration budget (truncated backprop)."""
    if gradient == "unrolled":
        return functools.partial(
            gauss_newton_solve, problem,
            iters=iters, cg_iters=cg_iters, damping=damping)
    if gradient != "implicit":
        raise ValueError(f"unknown gradient route {gradient!r} "
                         "(expected 'implicit' or 'unrolled')")

    @jax.custom_vjp
    def solve(theta, p0):
        return gauss_newton_solve(problem, theta, p0, iters=iters,
                                  cg_iters=cg_iters, damping=damping)

    def fwd(theta, p0):
        pstar = gauss_newton_solve(problem, theta, p0, iters=iters,
                                   cg_iters=cg_iters, damping=damping)
        return pstar, (theta, pstar)

    def bwd(res, pbar):
        theta, pstar = res
        v = cg_solve(
            lambda u: _hessian_matvec(problem, pstar, theta, u,
                                      adjoint_matvec),
            pbar, adjoint_cg_iters)
        # -(d^2 f / dtheta dp)^T v, as grad_theta of the scalar
        # <grad_p f(p*, theta), v> with p* held fixed
        gtheta = jax.grad(
            lambda th: jnp.dot(_inner_grad(problem, pstar, th), v))(theta)
        return -gtheta, jnp.zeros_like(pstar)

    solve.defvjp(fwd, bwd)
    return solve
