"""Differentiable bilevel objectives for sky-model refinement.

The inner (calibration) and outer (refinement) problems share one
residual: ``r(p, theta) = mask * (vis - sum_k J_p^k C^k(theta)
J_q^kH)`` — with the crucial difference from every solver path that the
cluster coherencies ``C^k(theta)`` are RECOMPUTED from sky parameters
inside the objective (``ops.rime.predict_coherencies``) instead of
being treated as constants.  That is what lets gradients flow from
residuals through the calibration solve into fluxes, spectral indices,
positions and shapelet coefficients.

This is the XLA predict path by construction: the fused Pallas kernel
has no coherency cotangent (``ops.rime_kernel.FUSED_COHERENCY_COTANGENT
is False`` — requesting one raises ``FusedSkyGradientError``), so the
refinement subsystem checks that capability flag and never routes
through the fused objective.

Inner vs outer cost, and why they differ:

- inner  ``f(p, theta) = 0.5 ||r||^2 + 0.5 ridge ||p - p_anchor||^2``
- outer  ``h(p, theta) = 0.5 ||r||^2``

The gain ridge (anchor = identity gains by default) does two jobs.  It
breaks the flux/gain degeneracy — a per-cluster flux scale ``s`` is
exactly absorbed by gains scaled ``1/sqrt(s)``, so without the prior
the outer gradient w.r.t. a single-source cluster's flux would vanish
identically.  And it makes the inner objective differ from the outer
one, so the implicit-function-theorem adjoint term is nonzero and the
finite-difference pins in tests/test_refine.py actually exercise it.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax.numpy as jnp

from sagecal_tpu.core.types import VisData
from sagecal_tpu.ops.rime import ShapeletTable, SourceBatch, predict_coherencies
from sagecal_tpu.refine.skyparams import SkySpec
from sagecal_tpu.solvers.sage import ClusterData, predict_full_model


def require_xla_predict(use_fused_predict: bool) -> None:
    """Refinement capability check: the fused kernel cannot produce the
    coherency cotangents refinement needs — fail loudly at config time
    rather than at backward-trace time."""
    from sagecal_tpu.ops.rime_kernel import FUSED_COHERENCY_COTANGENT

    if use_fused_predict and not FUSED_COHERENCY_COTANGENT:
        raise ValueError(
            "sky-model refinement requires the XLA predict path: the "
            "fused Pallas kernel's backward emits gain cotangents only "
            "(FUSED_COHERENCY_COTANGENT=False). Drop --fused for the "
            "refine app."
        )


@dataclasses.dataclass(frozen=True)
class RefineProblem:
    """Everything the bilevel objectives close over (host-level arrays;
    never traced).  ``p`` is handled FLAT — ``(M * 8N,)`` real — and
    reshaped to the solver layout ``(M, 1, 8N)`` at the predict;
    refinement is restricted to nchunk=1 solves."""

    data: VisData
    clusters: List[SourceBatch]
    tables: Optional[List[Optional[ShapeletTable]]]
    spec: SkySpec
    fdelta: float = 0.0
    ridge: float = 1e-2
    p_anchor: Optional[jnp.ndarray] = None  # flat (M*8N,); None = identity
    source_chunk: int = 32

    @property
    def nclusters(self) -> int:
        return len(self.clusters)

    @property
    def nstations(self) -> int:
        return self.data.nstations

    @property
    def nparams_p(self) -> int:
        return self.nclusters * 8 * self.nstations

    def identity_gains(self) -> jnp.ndarray:
        """Flat identity-Jones start/anchor: J = I for every
        (cluster, station) — [1,0, 0,0, 0,0, 1,0] per station in the
        solver's real packing (core.types.jones_to_params layout)."""
        from sagecal_tpu.core.types import jones_to_params

        eye = jnp.broadcast_to(
            jnp.eye(2, dtype=jnp.result_type(self.data.vis)),
            (self.nclusters, self.nstations, 2, 2),
        )
        return jones_to_params(eye).reshape(-1).astype(
            jnp.real(self.data.vis).dtype)

    def anchor(self) -> jnp.ndarray:
        return (self.p_anchor if self.p_anchor is not None
                else self.identity_gains())


def cluster_coherencies(problem: RefineProblem, theta: jnp.ndarray):
    """(M, F, 4, rows) complex coherency stack recomputed from the free
    sky parameters — the differentiable analog of
    ``solvers.sage.build_cluster_data``'s precomputed ``coh``."""
    from sagecal_tpu.ops.rime import resolve_source_flags

    clusters, tables = problem.spec.apply(
        theta, problem.clusters, problem.tables)
    d = problem.data
    cohs = []
    for ci, src in enumerate(clusters):
        tab = tables[ci] if tables is not None else None
        # static flags from the CONCRETE template batch — under the
        # outer-loop trace `src` carries tracers and the in-function
        # probe would silently flip to the extended-source program
        tmpl_tab = (problem.tables[ci]
                    if problem.tables is not None else None)
        has_ext, has_sh = resolve_source_flags(
            problem.clusters[ci], tmpl_tab)
        cohs.append(predict_coherencies(
            d.u, d.v, d.w, d.freqs, src, problem.fdelta,
            problem.source_chunk, shapelets=tab,
            has_extended=has_ext, has_shapelet=has_sh,
        ))
    return jnp.stack(cohs, axis=0)


def cluster_data_from_theta(problem: RefineProblem,
                            theta: jnp.ndarray) -> ClusterData:
    coh = cluster_coherencies(problem, theta)
    M, _, _, rows = coh.shape
    return ClusterData(
        coh=coh,
        chunk_map=jnp.zeros((M, rows), jnp.int32),
        nchunk=jnp.ones((M,), jnp.int32),
    )


def residual_vec(problem: RefineProblem, p_flat: jnp.ndarray,
                 theta: jnp.ndarray) -> jnp.ndarray:
    """Masked residual as one flat REAL vector (re and im stacked) —
    the shared residual of both bilevel levels, differentiable in both
    arguments."""
    d = problem.data
    cdata = cluster_data_from_theta(problem, theta)
    p = p_flat.reshape(problem.nclusters, 1, 8 * problem.nstations)
    model = predict_full_model(p, cdata, d)
    diff = (d.vis - model) * d.mask[:, None, :]
    return jnp.concatenate(
        [jnp.real(diff).reshape(-1), jnp.imag(diff).reshape(-1)])


def outer_cost(problem: RefineProblem, p_flat: jnp.ndarray,
               theta: jnp.ndarray) -> jnp.ndarray:
    """h(p, theta) = 0.5 ||r||^2 — the pure misfit the refinement
    minimizes at the inner fixed point."""
    r = residual_vec(problem, p_flat, theta)
    return 0.5 * jnp.dot(r, r)


def inner_cost(problem: RefineProblem, p_flat: jnp.ndarray,
               theta: jnp.ndarray) -> jnp.ndarray:
    """f(p, theta) = h + 0.5 ridge ||p - anchor||^2 — the calibration
    objective whose fixed point defines p*(theta)."""
    dp = p_flat - problem.anchor()
    return (outer_cost(problem, p_flat, theta)
            + 0.5 * problem.ridge * jnp.dot(dp, dp))
