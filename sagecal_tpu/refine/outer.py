"""The outer refinement loop: LBFGS over sky parameters around the
inner calibration solve.

Host-driven by design: each outer iteration is ONE ``lbfgs_fit`` step
(``itmax=1``) with the :class:`~sagecal_tpu.solvers.lbfgs.LBFGSMemory`
carried across calls — the same persistent-curvature idiom the
minibatch solver uses — so the host loop can emit a per-iteration
refine trace, checkpoint the full outer state (theta + memory) at
every iteration boundary, and stop/resume anywhere.  The expensive
part, the bilevel value-and-grad (inner GN solve + IFT adjoint or
unrolled backprop), is jitted once per run with the warm-start gains
as a traced argument, so iterating never recompiles.
"""

from __future__ import annotations

from typing import Callable, List, NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp

from sagecal_tpu.refine.implicit import make_inner_solver
from sagecal_tpu.refine.objective import RefineProblem, outer_cost
from sagecal_tpu.solvers.lbfgs import LBFGSMemory, lbfgs_fit


class RefineResult(NamedTuple):
    theta: jnp.ndarray  # refined sky parameters (flat, SkySpec layout)
    p: jnp.ndarray  # inner gains at the final theta, flat (M*8N,)
    cost: float  # outer misfit at the final theta
    gradnorm: float
    iterations: int  # outer iterations actually run
    trace: List[dict]  # one entry per outer iteration
    memory: LBFGSMemory  # outer curvature (resume carry)


def make_outer_value_and_grad(problem: RefineProblem, **inner_kwargs):
    """(solve, vg, cost): jitted ``solve(theta, p0) -> p*``,
    ``vg(theta, p0) -> (h, dh/dtheta)`` with gradients through the
    inner fixed point, and the cost-only entry for line searches."""
    solve = make_inner_solver(problem, **inner_kwargs)

    def outer_fn(theta, p0):
        pstar = solve(theta, p0)
        return outer_cost(problem, pstar, theta)

    return (jax.jit(solve), jax.jit(jax.value_and_grad(outer_fn)),
            jax.jit(outer_fn))


def run_refine(
    problem: RefineProblem,
    theta0: Optional[jnp.ndarray] = None,
    outer_iters: int = 10,
    lbfgs_m: int = 7,
    gradient: str = "implicit",
    inner_iters: int = 12,
    cg_iters: int = 32,
    damping: float = 1e-6,
    adjoint_cg_iters: int = 64,
    adjoint_matvec: str = "hvp",
    warm_start: bool = True,
    tol: float = 0.0,
    p_start: Optional[jnp.ndarray] = None,
    memory: Optional[LBFGSMemory] = None,
    start_iter: int = 0,
    on_iteration: Optional[Callable[[int, jnp.ndarray, LBFGSMemory,
                                     jnp.ndarray, dict], None]] = None,
    fns=None,
) -> RefineResult:
    """Refine the free sky parameters by outer LBFGS.

    ``on_iteration(it, theta, memory, p_warm, entry)`` fires after
    every outer iteration — the refine app's checkpoint/trace hook.
    ``p_start``/``memory``/``start_iter`` are the resume carries (pass
    the values recovered from a checkpoint to continue a run).
    ``warm_start`` feeds each iteration's converged inner gains as the
    next iteration's inner start point (elastic warm-start idiom);
    the gradient stays exact either way — the IFT adjoint only needs
    the fixed point actually reached.
    ``tol > 0`` stops early once the outer gradient norm falls below
    it.
    ``fns`` — an existing ``(solve, vg, cost_only)`` triple from
    :func:`make_outer_value_and_grad`; reusing one across several
    ``run_refine`` calls on the same problem skips their recompiles
    (the inner/adjoint kwargs are ignored in that case)."""
    if theta0 is None:
        theta0 = problem.spec.theta0(problem.clusters, problem.tables)
    theta = jnp.asarray(theta0)
    p_warm = (jnp.asarray(p_start) if p_start is not None
              else problem.identity_gains())
    mem = (memory if memory is not None
           else LBFGSMemory.init(theta.shape[0], lbfgs_m, theta.dtype))
    solve, vg, cost_only = fns if fns is not None else (
        make_outer_value_and_grad(
            problem, iters=inner_iters, cg_iters=cg_iters,
            damping=damping, gradient=gradient,
            adjoint_cg_iters=adjoint_cg_iters,
            adjoint_matvec=adjoint_matvec))

    trace: List[dict] = []
    cost = gradnorm = float("nan")
    it = start_iter
    for it in range(start_iter, outer_iters):
        p0 = p_warm

        def vg_fn(th, _p0=p0):
            return vg(th, _p0)

        def cost_fn(th, _p0=p0):
            return cost_only(th, _p0)

        res = lbfgs_fit(cost_fn, None, theta, itmax=1, M=lbfgs_m,
                        memory=mem, vg_fn=vg_fn)
        theta, mem = res.p, res.memory
        cost, gradnorm = float(res.cost), float(res.gradnorm)
        pstar = solve(theta, p0)
        if warm_start:
            p_warm = pstar
        entry = {
            "iter": it,
            "cost": cost,
            "gradnorm": gradnorm,
            "theta": np.asarray(theta).tolist(),
        }
        trace.append(entry)
        if on_iteration is not None:
            on_iteration(it, theta, mem, p_warm, entry)
        if tol > 0.0 and gradnorm < tol:
            break
    pstar = solve(theta, p_warm)
    return RefineResult(theta=theta, p=pstar, cost=cost,
                        gradnorm=gradnorm, iterations=it + 1 - start_iter,
                        trace=trace, memory=mem)
