"""Free sky-parameter specification for differentiable refinement.

The refinement subsystem optimizes a FLAT real vector ``theta`` over a
caller-chosen subset of sky-model parameters — per-source fluxes,
spectral indices, positions, shapelet mode coefficients — while the
rest of the sky stays frozen at its catalog values.  :class:`SkySpec`
is the static (hashable, non-pytree) description of which parameters
are free; it packs the current cluster list into ``theta`` and applies
a ``theta`` back onto the clusters with pure functional updates
(``.at[].set``), so the whole application is differentiable and the
cluster structure (source counts, types, shapelet tables) never
changes shape under the optimizer.

The reference C pipeline cannot express any of this: its coherencies
are precomputed constants (predict.c) and no gradient path exists from
residuals to the sky catalog.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp

from sagecal_tpu.ops.rime import ShapeletTable, SourceBatch


class SkySpec:
    """Which sky parameters are free, as static (cluster, source) keys.

    - ``flux``: entries ``(cluster, source)`` — free ``sI0`` values;
    - ``spec``: entries ``(cluster, source)`` — free spectral indices
      (``spec_idx``; note the reference's si==0 gate in
      ``_spectral_flux``: a freed spectral index that passes exactly
      through 0 kinks the model there, so seed it nonzero);
    - ``pos``: entries ``(cluster, source)`` — free (ll, mm) pairs
      (``nn`` is recomputed, staying on the celestial sphere);
    - ``modes``: entries ``(cluster, flat_mode_index)`` — free shapelet
      coefficients of that cluster's table row 0 (single-model tables,
      the fixture/diffuse-cluster case).

    ``theta`` layout is the concatenation [flux, spec, ll, mm, modes]
    in the order the keys were given.
    """

    def __init__(
        self,
        flux: Sequence[Tuple[int, int]] = (),
        spec: Sequence[Tuple[int, int]] = (),
        pos: Sequence[Tuple[int, int]] = (),
        modes: Sequence[Tuple[int, int]] = (),
    ):
        self.flux = tuple((int(c), int(s)) for c, s in flux)
        self.spec = tuple((int(c), int(s)) for c, s in spec)
        self.pos = tuple((int(c), int(s)) for c, s in pos)
        self.modes = tuple((int(c), int(m)) for c, m in modes)

    @property
    def nparams(self) -> int:
        return (len(self.flux) + len(self.spec) + 2 * len(self.pos)
                + len(self.modes))

    def __repr__(self):  # stable key for config fingerprints
        return (f"SkySpec(flux={self.flux}, spec={self.spec}, "
                f"pos={self.pos}, modes={self.modes})")

    # ------------------------------------------------------------ pack

    def theta0(
        self,
        clusters: List[SourceBatch],
        tables: Optional[List[Optional[ShapeletTable]]] = None,
        dtype=None,
    ) -> jnp.ndarray:
        """Current values of the free parameters as the flat start
        vector (the refinement start point — typically the perturbed
        catalog)."""
        vals = []
        for c, s in self.flux:
            vals.append(clusters[c].sI0[s])
        for c, s in self.spec:
            vals.append(clusters[c].spec_idx[s])
        for c, s in self.pos:
            vals.append(clusters[c].ll[s])
        for c, s in self.pos:
            vals.append(clusters[c].mm[s])
        for c, m in self.modes:
            if tables is None or tables[c] is None:
                raise ValueError(
                    f"SkySpec frees shapelet mode {m} of cluster {c} "
                    f"but that cluster has no ShapeletTable")
            vals.append(tables[c].modes[0, m])
        if not vals:
            raise ValueError("SkySpec frees no parameters")
        th = jnp.stack(vals)
        return th.astype(dtype) if dtype is not None else th

    # ----------------------------------------------------------- apply

    def apply(
        self,
        theta: jnp.ndarray,
        clusters: List[SourceBatch],
        tables: Optional[List[Optional[ShapeletTable]]] = None,
    ) -> Tuple[List[SourceBatch], Optional[List[Optional[ShapeletTable]]]]:
        """Clusters/tables with the free parameters replaced by
        ``theta`` (functional ``.at[].set`` updates — differentiable
        w.r.t. ``theta``)."""
        out = list(clusters)
        out_t = list(tables) if tables is not None else None
        j = 0
        for c, s in self.flux:
            out[c] = out[c].replace(
                sI0=out[c].sI0.at[s].set(theta[j].astype(out[c].sI0.dtype)))
            j += 1
        for c, s in self.spec:
            out[c] = out[c].replace(
                spec_idx=out[c].spec_idx.at[s].set(
                    theta[j].astype(out[c].spec_idx.dtype)))
            j += 1
        npos = len(self.pos)
        for i, (c, s) in enumerate(self.pos):
            ll = theta[j + i].astype(out[c].ll.dtype)
            mm = theta[j + npos + i].astype(out[c].mm.dtype)
            nn = jnp.sqrt(jnp.maximum(1.0 - ll**2 - mm**2, 0.0)) - 1.0
            out[c] = out[c].replace(
                ll=out[c].ll.at[s].set(ll),
                mm=out[c].mm.at[s].set(mm),
                nn=out[c].nn.at[s].set(nn.astype(out[c].nn.dtype)),
            )
        j += 2 * npos
        for c, m in self.modes:
            if out_t is None or out_t[c] is None:
                raise ValueError(
                    f"SkySpec frees shapelet mode {m} of cluster {c} "
                    f"but that cluster has no ShapeletTable")
            tab = out_t[c]
            out_t[c] = tab.replace(
                modes=tab.modes.at[0, m].set(
                    theta[j].astype(tab.modes.dtype)))
            j += 1
        return out, out_t
