"""Multi-tenant calibration service.

Turns a request manifest (many independent tenant/dataset/tile solves)
into full device programs: same-shape requests batch through the
vmapped solver entries (solvers/batched.py), buckets compile once
behind an executable cache, and per-tenant tile prefetch double-buffers
the HDF5 I/O under the device compute.  ``sagecal-tpu serve`` is the
CLI (apps/serve.py); USER_MANUAL.md "Serving" is the operator chapter.
"""

from sagecal_tpu.serve.bucket import BucketSpec, bucket_of, pad_indices
from sagecal_tpu.serve.cache import ExecutableCache
from sagecal_tpu.serve.request import (
    SolveRequest,
    load_requests,
    result_manifest_path,
    write_result_manifest,
)
from sagecal_tpu.serve.service import CalibrationService

__all__ = [
    "BucketSpec", "bucket_of", "pad_indices", "ExecutableCache",
    "SolveRequest", "load_requests", "result_manifest_path",
    "write_result_manifest", "CalibrationService",
]
