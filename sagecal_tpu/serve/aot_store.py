"""Persistent cross-worker AOT executable artifact store.

The in-process :class:`~sagecal_tpu.serve.cache.ExecutableCache` makes
the SECOND batch of a bucket free; this store makes the second WORKER
free.  Each artifact is one serialized compiled executable
(``jax.experimental.serialize_executable``) written by whichever fleet
worker compiled the bucket first; any worker that touches the same
bucket later deserializes and loads it — **zero compiles**, pinned by
the ``serve_executable_cache_*`` counters (a loaded worker records
``aot_hits`` and no ``compiles``).

Key contract: an artifact is only valid for the exact program it was
compiled from, so the key digests

- the complete :class:`~sagecal_tpu.serve.bucket.BucketSpec` (abstract
  shapes + static VisData metadata),
- the numerics ``config_fingerprint`` (solver knobs + precision),
- the batch width (the executable is specialized on B),
- the jax AND jaxlib versions plus the backend platform — an executable
  compiled by yesterday's jaxlib, or for a different backend, must
  never load.

File format: one JSON header line (version fields, checked BEFORE any
unpickling) followed by the pickled ``(payload, in_tree, out_tree)``
triple.  Writes are atomic (tmp + ``os.replace``), so a concurrently
reading worker sees either nothing or a whole artifact; a corrupted or
header-mismatched file is treated as a miss (clean recompile) and
counted, never a crash.

Security note: artifacts embed pickled pytree definitions, so the
store directory must be trusted to the same degree as the code tree
itself (same trust level as the persistent XLA compilation cache).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
from typing import Any, Callable, Optional

AOT_STORE_SCHEMA_VERSION = 1

_MAGIC = "sagecal-aot-artifact"

_runtime_ready = False


def _ensure_cpu_runtime() -> None:
    """Register the runtime libraries a deserialized executable calls
    into.  Compiling registers them as a side effect (jaxlib's LAPACK
    shim fills its scipy function-pointer table inside
    ``prepare_lapack_call`` at lowering time), but a worker that LOADS
    every bucket from the store never lowers anything — and the first
    eigh/qr custom call then jumps through a null pointer (hard
    SIGSEGV, not a catchable exception).  ``_lapack.initialize()`` is
    idempotent, so call it before the first deserialize."""
    global _runtime_ready
    if _runtime_ready:
        return
    try:
        from jaxlib.cpu import _lapack

        _lapack.initialize()
    except Exception:
        # non-CPU wheels may lack the shim; loaded executables for
        # those backends don't use it
        pass
    _runtime_ready = True


def _version_fields() -> dict:
    import jax
    import jaxlib

    try:
        backend = jax.default_backend()
    except Exception:
        backend = "unknown"
    return {
        "schema": AOT_STORE_SCHEMA_VERSION,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": backend,
    }


def artifact_key(bucket, fingerprint: str, batch: int) -> str:
    """Stable digest naming one (bucket, numerics, batch-width,
    toolchain) executable."""
    doc = json.dumps(
        {
            "bucket": list(bucket),
            "fingerprint": fingerprint,
            "batch": int(batch),
            **_version_fields(),
        },
        sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(doc.encode("utf-8")).hexdigest()[:32]


class AOTArtifactStore:
    """One directory of ``aot-<key>.bin`` artifacts shared by a fleet.

    ``load`` returns the callable compiled executable or ``None`` (any
    failure — absent, torn, version-mismatched, unloadable — is a miss;
    the caller recompiles).  ``save`` is best-effort: a full disk or a
    lost race never fails the solve that produced the executable."""

    def __init__(self, root: str):
        self.root = root
        #: human-readable detail of the most recent load/save failure
        #: (surfaced in worker logs; failures are also counted in the
        #: registry as serve_executable_cache_aot_errors_total)
        self.last_error: Optional[str] = None

    def path_for(self, key: str) -> str:
        return os.path.join(self.root, f"aot-{key}.bin")

    # -- read side ----------------------------------------------------

    def load(self, bucket, fingerprint: str, batch: int
             ) -> Optional[Callable]:
        key = artifact_key(bucket, fingerprint, batch)
        path = self.path_for(key)
        try:
            with open(path, "rb") as f:
                header = json.loads(f.readline().decode("utf-8"))
                if header.get("magic") != _MAGIC:
                    raise ValueError("bad magic")
                mine = _version_fields()
                for k, v in mine.items():
                    if header.get(k) != v:
                        raise ValueError(
                            f"version mismatch: {k}={header.get(k)!r} "
                            f"(this process: {v!r})")
                payload, in_tree, out_tree = pickle.load(f)
        except FileNotFoundError:
            self._count("aot_misses", bucket)
            return None
        except Exception as e:
            # torn, corrupted, or stale-toolchain artifact: a clean
            # recompile (which then overwrites it) is the recovery
            self._count("aot_errors", bucket)
            self.last_error = f"{path}: {e!r}"
            return None
        try:
            from jax.experimental import serialize_executable as se

            _ensure_cpu_runtime()
            loaded = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:
            self._count("aot_errors", bucket)
            self.last_error = f"{path}: {e!r}"
            return None
        self._count("aot_hits", bucket)
        return loaded

    # -- write side ---------------------------------------------------

    def save(self, bucket, fingerprint: str, batch: int,
             compiled: Any) -> Optional[str]:
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
            key = artifact_key(bucket, fingerprint, batch)
            path = self.path_for(key)
            os.makedirs(self.root, exist_ok=True)
            header = dict(_version_fields(), magic=_MAGIC,
                          bucket=bucket.short(),
                          fingerprint=fingerprint[:12], batch=int(batch))
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(json.dumps(header, sort_keys=True).encode("utf-8"))
                f.write(b"\n")
                pickle.dump((payload, in_tree, out_tree), f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._count("aot_saves", bucket)
            return path
        except Exception as e:
            self.last_error = f"{self.root}: {e!r}"
            return None

    # -- counters -----------------------------------------------------

    def _count(self, kind: str, bucket) -> None:
        try:
            from sagecal_tpu.obs.registry import get_registry

            get_registry().counter_inc(
                f"serve_executable_cache_{kind}_total",
                help=f"cross-worker AOT artifact store lookups ({kind})",
                bucket=bucket.short())
        except Exception:
            pass
