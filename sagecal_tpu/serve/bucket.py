"""Shape bucketing for the multi-tenant serve path.

XLA compiles one executable per abstract signature, so a service that
accepted arbitrary request shapes would recompile constantly.  The
bucketer maps every loaded request onto a :class:`BucketSpec` — the
complete abstract identity of the batched solve program — and the
scheduler accumulates same-bucket requests into batches of the
configured size.  A small set of buckets therefore covers the whole
request mix with a small set of compiled executables (serve/cache.py).

The spec must capture EVERYTHING that changes the compiled program:

- array shapes: stations, baseline rows, tile size, channels, cluster
  count, chunk padding, the 8N gain dof;
- dtype (f32/f64 runs never share an executable);
- the VisData STATIC fields (``freq0``, ``deltaf``, ``deltat`` ride in
  the pytree treedef, not in array data — two requests that differ only
  in observing frequency still need, and get, different executables).

Solver options (SageConfig) are deliberately NOT part of the bucket:
they key the executable cache separately via
:func:`sagecal_tpu.elastic.checkpoint.config_fingerprint`, so the
bucket answers "can these solves share one device program's shapes"
and the fingerprint answers "same numerics".

Ragged last batch: a bucket that drains with ``k < B`` pending requests
is padded to ``B`` by REPLICATING real entries (round-robin over the
``k``); the padded lanes solve real, finite data — no masked-to-zero
degenerate systems — and :func:`pad_indices` hands back the validity
mask so the scheduler discards their results on the host.
"""

from __future__ import annotations

from typing import List, NamedTuple, Tuple

import numpy as np


class BucketSpec(NamedTuple):
    """Abstract identity of one batched-solve executable (batch axis
    excluded — the executable is additionally specialized on B)."""

    nstations: int
    nbase: int          # baseline rows per tile (tilesz * nbase_per_t)
    tilesz: int
    nchan: int          # channels after the serve path's averaging
    nclus: int          # M, sky clusters
    nchunk_max: int     # chunk padding of the gains carry
    dof: int            # 8 * nstations, per chunk
    dtype: str          # "float32" / "float64"
    freq0: float        # VisData static fields: same treedef or bust
    deltaf: float
    deltat: float

    def short(self) -> str:
        """Compact tag for jit names / logs / manifests, e.g.
        ``N7xB84xT2xC1xM2``."""
        return (f"N{self.nstations}xB{self.nbase}xT{self.tilesz}"
                f"xC{self.nchan}xM{self.nclus}")


def bucket_of(data, cdata, p0: np.ndarray) -> BucketSpec:
    """The bucket a loaded request lands in, from its tile data,
    cluster coherencies and initial gains."""
    return BucketSpec(
        nstations=int(data.nstations),
        nbase=int(data.vis.shape[-1]),
        tilesz=int(data.tilesz),
        nchan=int(data.vis.shape[0]),
        nclus=int(cdata.coh.shape[0]),
        nchunk_max=int(p0.shape[1]),
        dof=int(p0.shape[2]),
        dtype=str(np.asarray(p0).dtype),
        freq0=float(data.freq0),
        deltaf=float(data.deltaf),
        deltat=float(data.deltat),
    )


def pad_indices(k: int, batch: int) -> Tuple[List[int], np.ndarray]:
    """Source indices filling a ragged group of ``k`` real entries up
    to ``batch`` lanes, plus the per-lane validity mask.

    ``k >= batch`` is the full-batch case (identity, all valid);
    ``k < batch`` replicates real entries round-robin into the padding
    lanes.  ``k == 0`` is a caller bug."""
    if k <= 0:
        raise ValueError("pad_indices: empty bucket group")
    if k >= batch:
        idx = list(range(k))
        return idx, np.ones(k, dtype=bool)
    idx = list(range(k)) + [i % k for i in range(batch - k)]
    valid = np.zeros(batch, dtype=bool)
    valid[:k] = True
    return idx, valid
