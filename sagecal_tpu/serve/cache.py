"""Bucketed executable cache for the serve path.

One entry per ``(BucketSpec, solver fingerprint)``: a named
``instrumented_jit`` wrapper of the vmapped batched solve
(:func:`sagecal_tpu.solvers.batched.sagefit_packed_batch`).  Reusing
the SAME wrapper object for every same-bucket batch is what makes the
second submission of an already-bucketed shape compile nothing — jax
caches the executable on the wrapper, and the wrapper's
``perf_stats()`` entry proves it (``compiles == 1`` across N batches).

Hit/miss counters live in two places on purpose:

- plain ints on the cache object (``hits``/``misses``/``stats()``) so
  tests and the bench can assert reuse with telemetry off;
- registry counters ``serve_executable_cache_{hits,misses}_total``
  (labelled by bucket) so ``diag prom`` exports them in production.

This cache is per-service-instance and in-memory; the CROSS-process
layer underneath it is the persistent XLA compilation cache
(``SAGECAL_COMPILE_CACHE``, obs/perf.py): a restarted server misses
here on first touch of each bucket but deserializes yesterday's
executable instead of recompiling.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Tuple

from sagecal_tpu.serve.bucket import BucketSpec


class ExecutableCache:
    """Maps ``(bucket, fingerprint)`` -> the jitted batched-solve
    callable, building (and counting) on miss."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[BucketSpec, str], Callable] = {}
        self.hits = 0
        self.misses = 0

    def get(self, bucket: BucketSpec, fingerprint: str) -> Callable:
        """The executable wrapper for this bucket+numerics, creating it
        on first touch.  The returned callable has the
        ``sagefit_packed_batch`` signature and donates ``p0``."""
        return self.get_with_status(bucket, fingerprint)[0]

    def get_with_status(self, bucket: BucketSpec,
                        fingerprint: str) -> Tuple[Callable, bool]:
        """Like :meth:`get` but also reports whether the lookup hit
        (``(fn, True)``) or built a fresh wrapper (``(fn, False)``) —
        the serve lifecycle tracer names its span ``cache_hit`` vs
        ``compile`` off this bit."""
        key = (bucket, fingerprint)
        with self._lock:
            fn = self._entries.get(key)
            if fn is not None:
                self.hits += 1
                self._count("hits", bucket)
                return fn, True
            self.misses += 1
            self._count("misses", bucket)
            from sagecal_tpu.obs.perf import instrumented_jit
            from sagecal_tpu.solvers.batched import sagefit_packed_batch

            # named per bucket so `diag perf` attributes compile time
            # to the shape class that paid it
            fn = instrumented_jit(
                sagefit_packed_batch,
                name=f"serve_batch[{bucket.short()}#{fingerprint[:8]}]",
                donate_argnames=("p0",),
            )
            self._entries[key] = fn
            return fn, False

    def _count(self, kind: str, bucket: BucketSpec) -> None:
        try:
            from sagecal_tpu.obs.registry import get_registry

            get_registry().counter_inc(
                f"serve_executable_cache_{kind}_total",
                help="serve bucketed-executable cache lookups "
                     f"({kind})", bucket=bucket.short())
        except Exception:
            pass

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "entries": len(self._entries)}
