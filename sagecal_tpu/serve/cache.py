"""Bucketed executable cache for the serve path.

One entry per ``(BucketSpec, solver fingerprint)``: the callable that
runs the vmapped batched solve
(:func:`sagecal_tpu.solvers.batched.sagefit_packed_batch`).  Reusing
the SAME entry for every same-bucket batch is what makes the second
submission of an already-bucketed shape compile nothing — the
executable lives on the entry, and its ``perf_stats()`` record proves
it (``compiles == 1`` across N batches).

Two tiers:

1. **in-process** (always on) — a dict of named ``instrumented_jit``
   wrappers (or loaded AOT executables); the second batch of a bucket
   in THIS process is a hit.
2. **cross-worker AOT artifact store** (opt-in, ``store=``) — the
   serve/aot_store.py layer: on an in-process miss the cache first
   tries to LOAD a serialized executable some other worker already
   compiled (zero compiles, reported as a cache hit so the request
   lifecycle records ``cache_hit`` rather than ``compile``); on a
   store miss it AOT-compiles explicitly (``jit().lower().compile()``,
   attributed through :func:`~sagecal_tpu.obs.perf.note_compile` under
   the same ``serve_batch[...]`` name) and SAVES the artifact so the
   next worker joining the fleet compiles nothing.

Hit/miss counters live in two places on purpose:

- plain ints on the cache object (``hits``/``misses``/``stats()``) so
  tests and the bench can assert reuse with telemetry off;
- registry counters ``serve_executable_cache_{hits,misses}_total``
  (labelled by bucket) plus the store-tier
  ``serve_executable_cache_{aot_hits,aot_misses,aot_errors,aot_saves,
  compiles}_total`` so ``diag prom`` exports them in production and the
  fleet tests pin "worker B compiled nothing" from a metrics snapshot.

Without a store this module behaves exactly as before (the legacy
cross-process layer is the persistent XLA compilation cache,
``SAGECAL_COMPILE_CACHE``, obs/perf.py): a restarted server misses
here on first touch of each bucket but deserializes yesterday's HLO
instead of recompiling from scratch.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

from sagecal_tpu.serve.bucket import BucketSpec


class _AOTExecutable:
    """A compiled (or store-loaded) executable wrapped to look like the
    instrumented-jit entry: callable with the full
    ``sagefit_packed_batch`` signature, carrying the ``serve_batch[...]``
    ``name`` the lifecycle tracer uses for compile-time attribution.

    If a loaded executable refuses a call (device/sharding drift
    between the saving and loading worker), the wrapper permanently
    falls back to a fresh instrumented jit — slower (one compile) but
    never wrong."""

    def __init__(self, compiled, name: str, batched_fused: bool = False):
        self._compiled = compiled
        self.name = name
        self.batched_fused = batched_fused
        self._fallback: Optional[Callable] = None

    def __call__(self, *args):
        if self._fallback is not None:
            return self._fallback(*args)
        try:
            return self._compiled(*args)
        except Exception:
            from sagecal_tpu.obs.perf import instrumented_jit

            self._fallback = instrumented_jit(
                _solve_fn(self.batched_fused), name=self.name,
                donate_argnames=("p0",))
            return self._fallback(*args)


def _solve_fn(batched_fused: bool) -> Callable:
    """The batched-solve entry with the kernel path BAKED IN: the
    ``batched_fused`` flag is compile-time static (it selects between
    the batched fused Pallas grid and the vmapped paths), so each cache
    entry closes over its routing decision instead of threading a
    static argument through jit/AOT signatures."""
    import functools

    from sagecal_tpu.solvers.batched import sagefit_packed_batch

    if not batched_fused:
        return sagefit_packed_batch
    return functools.partial(sagefit_packed_batch, batched_fused=True)


class ExecutableCache:
    """Maps ``(bucket, fingerprint)`` -> the batched-solve callable,
    building (and counting) on miss; with an
    :class:`~sagecal_tpu.serve.aot_store.AOTArtifactStore` attached,
    misses consult the cross-worker artifact tier before compiling."""

    def __init__(self, store=None):
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[BucketSpec, str], Callable] = {}
        self.store = store
        self.hits = 0
        self.misses = 0

    def get(self, bucket: BucketSpec, fingerprint: str) -> Callable:
        """The executable wrapper for this bucket+numerics, creating it
        on first touch.  The returned callable has the
        ``sagefit_packed_batch`` signature and donates ``p0``."""
        return self.get_with_status(bucket, fingerprint)[0]

    def get_with_status(self, bucket: BucketSpec, fingerprint: str,
                        example_args: Optional[tuple] = None,
                        batched_fused: bool = False,
                        ) -> Tuple[Callable, bool]:
        """Like :meth:`get` but also reports whether the lookup avoided
        a compile (``(fn, True)``) or must compile (``(fn, False)``) —
        the serve lifecycle tracer names its span ``cache_hit`` vs
        ``compile`` off this bit.  A store LOAD reports True: the
        request never waits on a compiler.  ``example_args`` (the
        actual batch arguments) enables the store tier — without them
        the cache can only hand back a lazy jit wrapper.
        ``batched_fused`` selects the kernel path baked into a NEW
        entry (:func:`_solve_fn`); it must be deterministic per
        (bucket, fingerprint) — :func:`sagecal_tpu.solvers.batched.
        choose_batched_path` is, because every input to its decision is
        part of the bucket or the fingerprint."""
        key = (bucket, fingerprint)
        with self._lock:
            fn = self._entries.get(key)
            if fn is not None:
                self.hits += 1
                self._count("hits", bucket)
                return fn, True
            self.misses += 1
            self._count("misses", bucket)
            if self.store is not None and example_args is not None:
                fn, hit = self._from_store(bucket, fingerprint,
                                           example_args, batched_fused)
            else:
                fn, hit = self._lazy_jit(bucket, fingerprint,
                                         batched_fused), False
            self._entries[key] = fn
            return fn, hit

    # -- build paths ---------------------------------------------------

    @staticmethod
    def entry_name(bucket: BucketSpec, fingerprint: str) -> str:
        # named per bucket so `diag perf` attributes compile time to
        # the shape class that paid it
        return f"serve_batch[{bucket.short()}#{fingerprint[:8]}]"

    def _lazy_jit(self, bucket: BucketSpec, fingerprint: str,
                  batched_fused: bool = False) -> Callable:
        from sagecal_tpu.obs.perf import instrumented_jit

        return instrumented_jit(
            _solve_fn(batched_fused),
            name=self.entry_name(bucket, fingerprint),
            donate_argnames=("p0",),
        )

    def _from_store(self, bucket: BucketSpec, fingerprint: str,
                    example_args: tuple, batched_fused: bool = False
                    ) -> Tuple[Callable, bool]:
        """Store tier: load (zero compiles) or compile-and-save."""
        import jax

        from sagecal_tpu.obs.perf import note_compile

        batch_w = int(example_args[6].shape[0])  # p0 leading axis
        name = self.entry_name(bucket, fingerprint)
        loaded = self.store.load(bucket, fingerprint, batch_w)
        if loaded is not None:
            return _AOTExecutable(loaded, name, batched_fused), True
        jitted = jax.jit(_solve_fn(batched_fused),
                         donate_argnames=("p0",))
        t0 = time.perf_counter()
        lowered = jitted.lower(*example_args)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        flops = by = None
        try:
            from sagecal_tpu.obs.perf import _cost_analysis

            flops, by = _cost_analysis(compiled)
        except Exception:
            pass
        note_compile(name, t1 - t0, t2 - t1, flops, by, aot=True)
        self._count("compiles", bucket)
        self.store.save(bucket, fingerprint, batch_w, compiled)
        return _AOTExecutable(compiled, name, batched_fused), False

    def _count(self, kind: str, bucket: BucketSpec) -> None:
        try:
            from sagecal_tpu.obs.registry import get_registry

            get_registry().counter_inc(
                f"serve_executable_cache_{kind}_total",
                help="serve bucketed-executable cache lookups "
                     f"({kind})", bucket=bucket.short())
        except Exception:
            pass

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "entries": len(self._entries)}
