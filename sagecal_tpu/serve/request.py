"""Serve request/result manifests.

A request manifest is one JSON document describing a batch of
independent calibration requests from one or more tenants::

    {
      "requests": [
        {
          "request_id": "fieldA-t0",
          "tenant": "lofar-eor",
          "dataset": "/data/fieldA.vis.h5",
          "sky_model": "/data/fieldA.sky",
          "cluster_file": "/data/fieldA.sky.cluster",   # optional
          "t0": 0,                                      # tile start
          "tilesz": 2,
          "solver_mode": 1,                             # optional knobs
          "max_emiter": 1, "max_iter": 2, "max_lbfgs": 6
        },
        ...
      ]
    }

(a bare JSON list of request objects is accepted too).  Omitted solver
knobs inherit the service defaults (apps/config.py ServeConfig);
``cluster_file`` defaults to ``<sky_model>.cluster``; ``out_solutions``
defaults to ``<out_dir>/<request_id>.solutions``.

Each completed request gets a RESULT manifest
``<out_dir>/<request_id>.result.json`` — verdict, residuals, the
bucket it solved in, latency — so a tenant polls one file per request
instead of parsing the shared event log.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Dict, List, Optional

_ID_RE = re.compile(r"^[A-Za-z0-9._-]+$")

#: solver knobs a request may override (everything else is service-wide)
SOLVER_KNOBS = ("solver_mode", "max_emiter", "max_iter", "max_lbfgs",
                "lbfgs_m", "nulow", "nuhigh", "randomize")


@dataclasses.dataclass
class SolveRequest:
    request_id: str
    tenant: str
    dataset: str
    sky_model: str
    t0: int
    tilesz: int
    cluster_file: str = ""
    out_solutions: str = ""
    in_column: str = "vis"
    # lifecycle trace id: carried through to the result manifest so one
    # logical trace survives process boundaries and --resume; derived
    # from the request_id when the submitter doesn't pick one
    trace_id: str = ""
    # upstream enqueue wall-clock (unix).  A fronting queue (the fleet's
    # LeaseQueue) sets this so queue_wait_s in the result manifest spans
    # the WHOLE wait, not just the service-internal round-robin; 0 means
    # the service stamps its own submit time
    enqueued_at: float = 0.0
    # None = inherit the ServeConfig default
    solver_mode: Optional[int] = None
    max_emiter: Optional[int] = None
    max_iter: Optional[int] = None
    max_lbfgs: Optional[int] = None
    lbfgs_m: Optional[int] = None
    nulow: Optional[float] = None
    nuhigh: Optional[float] = None
    randomize: Optional[bool] = None

    def __post_init__(self):
        if not _ID_RE.match(self.request_id):
            raise ValueError(
                f"request_id {self.request_id!r} must match "
                f"{_ID_RE.pattern} (it names output files)")
        if not self.cluster_file:
            self.cluster_file = self.sky_model + ".cluster"
        if not self.trace_id:
            self.trace_id = f"req-{self.request_id}"


def load_requests(path: str) -> List[SolveRequest]:
    """Parse a request manifest; raises ``ValueError`` on a malformed
    document, a missing required field, or a duplicate request_id."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        doc = doc.get("requests")
    if not isinstance(doc, list) or not doc:
        raise ValueError(
            f"{path}: expected a JSON list of requests (or an object "
            f"with a non-empty 'requests' list)")
    out: List[SolveRequest] = []
    seen = set()
    fields = {f.name for f in dataclasses.fields(SolveRequest)}
    for i, item in enumerate(doc):
        if not isinstance(item, dict):
            raise ValueError(f"{path}: request #{i} is not an object")
        unknown = set(item) - fields
        if unknown:
            raise ValueError(
                f"{path}: request #{i} has unknown fields "
                f"{sorted(unknown)}")
        missing = {"request_id", "tenant", "dataset", "sky_model",
                   "t0", "tilesz"} - set(item)
        if missing:
            raise ValueError(
                f"{path}: request #{i} missing required fields "
                f"{sorted(missing)}")
        req = SolveRequest(**item)
        if req.request_id in seen:
            raise ValueError(
                f"{path}: duplicate request_id {req.request_id!r}")
        seen.add(req.request_id)
        out.append(req)
    return out


def result_manifest_path(out_dir: str, request_id: str) -> str:
    return os.path.join(out_dir, f"{request_id}.result.json")


def write_result_manifest(out_dir: str, result: Dict[str, Any]) -> str:
    """Atomically write one request's result manifest (tmp + replace,
    same torn-read guarantee as the elastic checkpoints — a polling
    tenant never sees half a verdict)."""
    os.makedirs(out_dir, exist_ok=True)
    path = result_manifest_path(out_dir, result["request_id"])
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1, sort_keys=True, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return path
