"""Multi-tenant calibration service: the batch scheduler.

One process serves many independent (tenant, dataset, tile) solve
requests.  Three mechanisms turn that request mix into full device
programs instead of a one-at-a-time dispatch loop:

1. **vmapped batch solves** — same-bucket requests stack into ONE
   jitted program (solvers/batched.py); solves/sec scales with the
   batch because the dispatch floor and the under-utilized small-shape
   kernels are paid once per batch, not once per request.
2. **bucketed executable cache** — requests bucket by abstract shape
   (serve/bucket.py) and numerics fingerprint; each bucket compiles
   once and every later batch of that shape reuses the executable
   (serve/cache.py proves it with hit counters + ``compiles == 1``).
3. **double-buffered prefetch** — every (tenant, dataset) stream gets
   its own io/dataset.py :class:`TilePrefetcher` with ``depth=2``, so
   the HDF5 read + host packing of the next requests overlaps the
   device solve of the current batch; prefetchers are closed (threads
   reaped) as each stream drains, and remain registered with the
   obs/flight.py crash path until then.

Scheduling is round-robin across tenants: each turn pops one request
from one tenant's queue, so a tenant with a deep queue cannot starve
the others; batches therefore interleave tenants whenever their
requests share a bucket (the executable doesn't care whose data it
solves).

Elastic: each tenant owns a namespaced CheckpointManager
(``<ckpt_dir>/tenants/<tenant>``) recording which of its requests have
completed; a preempted server re-run with ``--resume`` skips those and
drains only the remainder (results already on disk are untouched).
"""

from __future__ import annotations

import collections
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from sagecal_tpu.serve.bucket import BucketSpec, bucket_of, pad_indices
from sagecal_tpu.serve.cache import ExecutableCache
from sagecal_tpu.serve.request import SolveRequest, write_result_manifest


def _merge_sage_config(cfg, req: SolveRequest):
    """Request solver knobs (None = inherit) over the service-wide
    ServeConfig defaults -> (SageConfig, numerics fingerprint)."""
    from sagecal_tpu.elastic.checkpoint import config_fingerprint
    from sagecal_tpu.obs import telemetry_enabled
    from sagecal_tpu.solvers.sage import SageConfig

    knobs = dict(
        solver_mode=(cfg.solver_mode if req.solver_mode is None
                     else req.solver_mode),
        max_emiter=(cfg.max_emiter if req.max_emiter is None
                    else req.max_emiter),
        max_iter=cfg.max_iter if req.max_iter is None else req.max_iter,
        max_lbfgs=(cfg.max_lbfgs if req.max_lbfgs is None
                   else req.max_lbfgs),
        lbfgs_m=cfg.lbfgs_m if req.lbfgs_m is None else req.lbfgs_m,
        nulow=cfg.nulow if req.nulow is None else req.nulow,
        nuhigh=cfg.nuhigh if req.nuhigh is None else req.nuhigh,
        randomize=(cfg.randomize if req.randomize is None
                   else req.randomize),
    )
    # fused-kernel routing is service-wide, f32-only (the fullbatch
    # precedent: a fused request under use_f64 silently stays on XLA)
    use_fused = getattr(cfg, "use_fused_predict", False) \
        and not cfg.use_f64
    coh_dtype = getattr(cfg, "coh_dtype", "f32")
    scfg = SageConfig(
        collect_telemetry=False,  # batched lanes report via quality
        collect_quality=True,     # per-request verdicts are the product
        use_fused_predict=use_fused,
        coh_dtype=coh_dtype,
        **knobs,
    )
    fp = config_fingerprint(use_f64=cfg.use_f64,
                            use_fused_predict=use_fused,
                            coh_dtype=coh_dtype,
                            collect=telemetry_enabled(), **knobs)
    return scfg, fp


class _StreamPool:
    """Bounded pool of double-buffered prefetch streams.

    One stream per (tenant, dataset, tilesz, column) request sequence,
    exactly as before — but opened lazily on first touch and capped at
    ``cap`` concurrently-open :class:`TilePrefetcher` instances
    (``cap <= 0`` = unbounded, the legacy behavior).  Above the cap the
    least-recently-used stream is CLOSED (its reader threads reaped and
    its HDF5 handle released) and transparently reopened from its
    remaining tiles when next touched; each close-for-capacity is
    counted in ``serve_prefetch_evictions_total``.  Without a cap a
    fleet worker claiming requests across many tenants×datasets holds
    one open prefetcher (threads + file handles + depth×tile buffers)
    per stream simultaneously — unbounded fleet-wide."""

    def __init__(self, cap: int):
        self.cap = int(cap)
        self.evictions = 0
        self._specs: Dict[tuple, dict] = {}
        self._open_streams: "collections.OrderedDict[tuple, dict]" = \
            collections.OrderedDict()

    def register(self, skey: tuple, t0s: List[int], dtype) -> None:
        from sagecal_tpu.io.dataset import VisDataset

        _, dpath, _tilesz, _column = skey
        ds = VisDataset(dpath, "r")
        meta = ds.meta
        ds.close()
        self._specs[skey] = {"t0s": list(t0s), "pos": 0, "meta": meta,
                             "dtype": dtype}

    def meta(self, skey: tuple):
        return self._specs[skey]["meta"]

    def next_tile(self, skey: tuple):
        """The next (t0, (data,)) of this stream, opening/reopening its
        prefetcher as needed and closing it when the stream drains."""
        st = self._open_streams.get(skey)
        if st is None:
            st = self._open(skey)
        else:
            self._open_streams.move_to_end(skey)
        spec = self._specs[skey]
        got = next(st["it"])
        spec["pos"] += 1
        if spec["pos"] >= len(spec["t0s"]):
            # drained: the iterator just consumed its sentinel; reap
            # the reader threads now instead of at run teardown
            st["pf"].close()
            self._open_streams.pop(skey, None)
        return got

    def _open(self, skey: tuple) -> dict:
        from sagecal_tpu.io.dataset import TilePrefetcher

        while self.cap > 0 and len(self._open_streams) >= self.cap:
            _vkey, vst = self._open_streams.popitem(last=False)
            vst["pf"].close()
            self.evictions += 1
            try:
                from sagecal_tpu.obs.registry import get_registry

                get_registry().counter_inc(
                    "serve_prefetch_evictions_total",
                    help="prefetch streams closed for capacity "
                         "(reopened from remaining tiles on next touch)")
            except Exception:
                pass
        spec = self._specs[skey]
        _, dpath, tilesz, column = skey
        pf = TilePrefetcher(
            dpath, spec["t0s"][spec["pos"]:],
            [dict(average_channels=True, dtype=spec["dtype"],
                  column=column)],
            tilesz, depth=2)
        st = {"pf": pf, "it": iter(pf.__enter__())}
        self._open_streams[skey] = st
        return st

    def close(self) -> None:
        for st in self._open_streams.values():
            st["pf"].close()
        self._open_streams.clear()


class _Entry:
    """One loaded, solve-ready request."""

    __slots__ = ("req", "data", "cdata", "p0", "key", "scfg", "meta",
                 "nclus", "nchunk_max", "enqueued_at", "started_at")

    def __init__(self, req, data, cdata, p0, key, scfg, meta,
                 nclus, nchunk_max):
        self.req = req
        self.data = data
        self.cdata = cdata
        self.p0 = p0
        self.key = key
        self.scfg = scfg
        self.meta = meta
        self.nclus = nclus
        self.nchunk_max = nchunk_max
        # request-lifecycle wall-clock marks (set by the scheduler)
        self.enqueued_at = 0.0
        self.started_at = 0.0


class CalibrationService:
    """Drains a request manifest through bucketed batch solves.

    ``run()`` returns a summary dict (per-request results, latency
    percentiles, executable-cache stats) used by the CLI, the bench and
    the tests."""

    def __init__(self, cfg, log=print, device=None, aot_store=None):
        self.cfg = cfg
        self.log = log
        self.device = device
        self.cache = ExecutableCache(store=aot_store)
        self._sky_cache: Dict[tuple, tuple] = {}
        self._results: List[Dict[str, Any]] = []
        self._latencies: List[float] = []
        self._diverged_abort: Optional[tuple] = None
        self._slo = None  # SLOMonitor, built in run() from cfg.slo
        # shadow-solve auditor (obs/shadow.py), built in run() iff
        # cfg.shadow_rate > 0 — with the rate at 0 no auditor object
        # exists and the dispatch path is byte-identical to a build
        # without the feature (pinned in tests/test_drift.py)
        self.shadow = None

    # -- data loading --------------------------------------------------

    def _sky(self, req: SolveRequest, ra0, dec0, dtype):
        from sagecal_tpu.io.skymodel import load_sky

        key = (os.path.abspath(req.sky_model),
               os.path.abspath(req.cluster_file),
               float(ra0), float(dec0), str(dtype))
        hit = self._sky_cache.get(key)
        if hit is None:
            hit = load_sky(req.sky_model, req.cluster_file, ra0, dec0,
                           dtype=dtype)
            self._sky_cache[key] = hit
        return hit

    def _load_entry(self, req: SolveRequest, data, meta) -> _Entry:
        """Tile data (already prefetched) -> solve-ready entry:
        coherencies, identity gains carry, per-request RNG key."""
        import zlib

        import jax.numpy as jnp

        from sagecal_tpu.core.types import identity_jones, jones_to_params
        from sagecal_tpu.solvers.sage import build_cluster_data

        dtype = np.float64 if self.cfg.use_f64 else np.float32
        cdtype = np.complex128 if self.cfg.use_f64 else np.complex64
        clusters, cdefs, shapelets = self._sky(
            req, meta.ra0, meta.dec0, dtype)
        nchunks = [cd.nchunk for cd in cdefs]
        nchunk_max = max(nchunks)
        M = len(clusters)
        N = meta.nstations
        cdata = build_cluster_data(data, clusters, nchunks,
                                   shapelets=shapelets)
        eye = jones_to_params(identity_jones(N, cdtype))
        p0 = np.asarray(
            jnp.broadcast_to(eye, (M, nchunk_max, 8 * N)).astype(dtype))
        scfg, fp = _merge_sage_config(self.cfg, req)
        # per-request key derived from the FULL request identity via the
        # shared batched-solver helper — a pure function of the request,
        # so the randomized solver stream reproduces across restarts,
        # schedulers and batch slots (the old 4-byte-prefix seed
        # collided for ids sharing a prefix, and re-deriving per
        # submission made robust solves scheduler-dependent)
        from sagecal_tpu.solvers.batched import derive_lane_keys

        lane_id = zlib.crc32(req.request_id.encode())
        key = np.asarray(derive_lane_keys(0, [lane_id])[0])
        entry = _Entry(req, data, cdata, p0, key, scfg, meta, M,
                       nchunk_max)
        return entry, fp

    # -- batch dispatch ------------------------------------------------

    def _dispatch(self, bucket: BucketSpec, fingerprint: str,
                  entries: List[_Entry], batch: int, elog,
                  padded_flush: bool) -> None:
        """Stack ``entries`` into one vmapped solve; unpack each real
        lane into its request's solutions file + result manifest."""
        import jax

        idx, valid = pad_indices(len(entries), batch)
        k = len(entries)
        t_pack = time.time()

        def stack(get):
            return jax.tree_util.tree_map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]),
                *[get(entries[i]) for i in idx])

        data_b = stack(lambda e: e.data.replace(vis=None))
        cdata_b = stack(lambda e: e.cdata._replace(coh=None))
        vis = np.stack([np.asarray(entries[i].data.vis) for i in idx])
        coh = np.stack([np.asarray(entries[i].cdata.coh) for i in idx])
        p0 = np.stack([entries[i].p0 for i in idx])
        keys = np.stack([entries[i].key for i in idx])
        scfg = entries[0].scfg

        # kernel-path capability check on the CONCRETE stacked batch
        # (host numpy): one Pallas grid for the whole batch when it
        # passes, vmapped solo kernels / XLA otherwise.  Deterministic
        # per (bucket, fingerprint), so the executable-cache entry and
        # the static batched_fused flag always agree.
        from sagecal_tpu.solvers.batched import choose_batched_path

        kernel_path, path_reason = choose_batched_path(
            data_b, cdata_b, p0, scfg)
        batched_fused = kernel_path == "fused_batch"

        args = (data_b, cdata_b, vis.real, vis.imag, coh.real, coh.imag,
                p0, scfg, keys, np.asarray(valid, bool))
        if self.device is not None:
            args = jax.device_put(args, self.device)
        pack_s = time.time() - t_pack
        # compile time shows up either inside get_with_status (AOT
        # store path) or inside the first call of the lazy wrapper;
        # both land between `tic` and the host sync, and the perf-stats
        # delta splits compile out of execute so the lifecycle's
        # compile|cache_hit span is honest either way
        name = self.cache.entry_name(bucket, fingerprint)
        compile_before = self._compile_seconds_by_name(name)
        tic = time.time()
        fn, cache_hit = self.cache.get_with_status(
            bucket, fingerprint, example_args=args,
            batched_fused=batched_fused)
        out = fn(*args)
        # materialize on host before unpacking lanes (one sync)
        p_host = np.asarray(out.p)
        res0_host = np.asarray(out.res_0)
        res1_host = np.asarray(out.res_1)
        div_host = np.asarray(out.diverged)
        nu_host = np.asarray(out.mean_nu)
        solve_s = time.time() - tic
        compile_s = 0.0 if cache_hit else max(
            self._compile_seconds_by_name(name) - compile_before, 0.0)
        timing = {
            "t_pack": t_pack, "pack_s": pack_s, "t_exec": tic,
            "solve_s": solve_s, "cache_hit": cache_hit,
            "compile_s": min(compile_s, solve_s),
        }
        if elog is not None:
            elog.emit("serve_batch_dispatched", bucket=bucket.short(),
                      fingerprint=fingerprint[:12], size=k,
                      batch=len(idx), padded=padded_flush,
                      seconds=solve_s,
                      kernel_path=kernel_path,
                      kernel_path_reason=path_reason,
                      cache=self.cache.stats())
        # unpack over the FULL batch width with an explicit validity
        # guard: replication-padded lanes (valid[lane] is False) carry
        # a copy of some real request's data, so their solve outputs —
        # and in particular their quality structures — must never reach
        # _finish_request, or a padded tail lane could fire a spurious
        # quality_degraded / solver_diverged verdict for a request that
        # already has its real verdict from its own lane.
        lane_quality = {}
        for lane in range(len(idx)):
            if not valid[lane]:
                continue
            lane_quality[lane] = (
                None if out.quality is None else jax.tree_util.tree_map(
                    lambda x: x[lane], out.quality))
            self._finish_request(
                entries[lane], bucket, lane, len(idx),
                p_host[lane], float(res0_host[lane]),
                float(res1_host[lane]), bool(div_host[lane]),
                float(nu_host[lane]), lane_quality[lane],
                elog, timing, kernel_path, path_reason)
        if self.shadow is not None:
            # shadow audits run strictly AFTER every manifest of the
            # batch is on disk — the re-solve shares the process but
            # never the latency path of any request in flight
            for lane in range(len(idx)):
                if not valid[lane]:
                    continue
                self.shadow.audit(
                    entries[lane], bucket.short(), kernel_path,
                    path_reason, p_host[lane],
                    float(res1_host[lane]), lane_quality[lane], elog)

    @staticmethod
    def _compile_seconds_by_name(name: str) -> float:
        """Cumulative compile seconds attributed to a named executable
        entry (0.0 when perf stats are unavailable)."""
        try:
            from sagecal_tpu.obs.perf import perf_stats

            if not name:
                return 0.0
            return float(perf_stats().get(name, {}).get(
                "compile_seconds", 0.0))
        except Exception:
            return 0.0

    def _finish_request(self, entry: _Entry, bucket, lane, batch,
                        p, res0, res1, diverged, mean_nu, quality,
                        elog, timing, kernel_path: str = "xla",
                        path_reason: str = "") -> None:
        from sagecal_tpu.core.types import params_to_jones
        from sagecal_tpu.io import solutions as solio
        from sagecal_tpu.obs.quality import check_and_emit
        from sagecal_tpu.obs.registry import get_registry

        req, meta = entry.req, entry.meta
        t_unpack = time.time()
        # divergence guard, same residual-ratio policy as fullbatch
        ratio_blown = (not np.isfinite(res1) or res1 == 0.0
                       or res1 > self.cfg.res_ratio * res0)
        verdict, reasons = "ok", []
        if quality is not None:
            verdict, reasons = check_and_emit(
                elog, quality, log=self.log, tile=req.t0, app="serve",
                tenant=req.tenant, request_id=req.request_id)
        if diverged or ratio_blown:
            if verdict != "diverged" and elog is not None:
                elog.emit("solver_diverged",
                          reasons=[f"residual_ratio:{res0:.3e}->{res1:.3e}"],
                          tile=req.t0, app="serve", tenant=req.tenant,
                          request_id=req.request_id)
            verdict = "diverged"
            reasons = reasons + [f"residual_ratio:{res0:.3e}->{res1:.3e}"]

        out_path = req.out_solutions or os.path.join(
            self.cfg.out_dir, f"{req.request_id}.solutions")
        N, M, nchunk_max = meta.nstations, entry.nclus, entry.nchunk_max
        jsol = np.asarray(params_to_jones(p)).reshape(
            M * nchunk_max, N, 2, 2)
        # tmp + replace: the published solutions file is whole at
        # every instant (a reader never sees a header without its
        # solutions)
        tmp_path = f"{out_path}.tmp.{os.getpid()}"
        with open(tmp_path, "w") as fh:
            solio.write_header(
                fh, meta.freq0, meta.deltaf,
                meta.deltat * req.tilesz / 60.0, N, M, M * nchunk_max)
            solio.append_solutions(fh, jsol)
        os.replace(tmp_path, out_path)

        from sagecal_tpu.obs.trace import get_tracer

        tracer = get_tracer()
        t_write = time.time()
        queue_wait = max(entry.started_at - entry.enqueued_at, 0.0)
        result = {
            "request_id": req.request_id, "tenant": req.tenant,
            "dataset": req.dataset, "t0": req.t0, "tilesz": req.tilesz,
            "verdict": verdict, "reasons": reasons,
            "res_0": res0, "res_1": res1, "mean_nu": mean_nu,
            "bucket": bucket.short(), "batch": batch, "lane": lane,
            # which kernel actually solved this request, and why the
            # capability check chose it — the bench already stamps
            # this; operators get it per result (diag serve columns)
            "kernel_path": kernel_path,
            "kernel_path_reason": path_reason,
            "solutions": out_path,
            # wall-clock lifecycle: latency reconstructable from the
            # manifest alone, no live gauges needed
            "enqueued_at": entry.enqueued_at,
            "started_at": entry.started_at,
            "completed_at": t_write,
            "queue_wait_s": queue_wait,
            "latency_s": t_write - entry.enqueued_at,
            "trace_id": req.trace_id,
        }
        if tracer.enabled:
            result["span_id"] = tracer.allocate_span_id()
        write_result_manifest(self.cfg.out_dir, result)
        write_s = time.time() - t_write
        latency = result["latency_s"]
        self._latencies.append(latency)
        if tracer.enabled:
            self._emit_lifecycle(tracer, entry, bucket, lane, batch,
                                 verdict, timing, t_unpack, t_write,
                                 write_s, result["span_id"])
        self._results.append(result)
        reg = get_registry()
        reg.counter_inc("serve_requests_total", tenant=req.tenant,
                        verdict=verdict,
                        help="serve requests completed, by verdict")
        reg.observe("serve_request_latency_seconds",
                    result["latency_s"], tenant=req.tenant,
                    help="submit -> result-manifest latency")
        reg.observe("serve_queue_wait_seconds", queue_wait,
                    tenant=req.tenant,
                    help="enqueue -> scheduler-pop wait")
        if self._slo is not None and self._slo.enabled:
            self._slo.observe(req.tenant, result["completed_at"],
                              latency, verdict)
            self._slo.evaluate(now=result["completed_at"], elog=elog,
                               registry=reg)
        if elog is not None:
            elog.emit("request_done", **result)
        self.log(f"request {req.request_id} [{req.tenant}]: "
                 f"{verdict} residual {res0:.6f} -> {res1:.6f} "
                 f"(bucket {bucket.short()}, lane {lane}/{batch}, "
                 f"{result['latency_s']:.1f}s)")
        if verdict == "diverged" and self.cfg.abort_on_divergence \
                and self._diverged_abort is None:
            # raised after the whole batch's manifests are on disk
            self._diverged_abort = (req.request_id, req.t0, reasons)

    def _emit_lifecycle(self, tracer, entry: _Entry, bucket, lane,
                        batch, verdict, timing, t_unpack, t_write,
                        write_s, root_id) -> None:
        """One trace per request: ``serve.request`` root spanning
        enqueue -> manifest write, with the full phase chain as
        children.  Batch-shared phases (pack/compile/execute) are
        billed to every lane of the batch, marked ``shared`` with the
        batch width, so per-request traces stay self-contained while
        fleet totals divide by the batch attr.  The root records under
        the pre-allocated ``root_id`` already written into the result
        manifest — that is the pointer that lets a later process (or a
        --resume continuation) join manifest and trace."""
        req = entry.req
        tid = req.trace_id
        base = dict(request_id=req.request_id, tenant=req.tenant,
                    bucket=bucket.short(), lane=lane, batch=batch)
        # parent_id="" (not None) pins the root above any ambient span
        # stack; readers treat missing/unknown parents as roots
        tracer.add_span(
            "serve.request", t_write + write_s - entry.enqueued_at,
            parent_id="", start_unix=entry.enqueued_at, trace_id=tid,
            span_id=root_id, verdict=verdict, **base)

        def child(name, start, dur, **attrs):
            tracer.add_span(name, max(dur, 0.0), parent_id=root_id,
                            start_unix=start, trace_id=tid,
                            **dict(base, **attrs))

        child("enqueue", entry.enqueued_at,
              entry.started_at - entry.enqueued_at)
        child("schedule", entry.started_at,
              timing["t_pack"] - entry.started_at)
        child("pack", timing["t_pack"], timing["pack_s"], shared=True)
        exec_s = timing["solve_s"] - timing["compile_s"]
        if timing["cache_hit"]:
            child("cache_hit", timing["t_pack"] + timing["pack_s"], 0.0)
        else:
            child("compile", timing["t_exec"], timing["compile_s"],
                  shared=True)
        child("execute", timing["t_exec"] + timing["compile_s"], exec_s,
              shared=True)
        child("unpack", t_unpack, t_write - t_unpack)
        child("write_manifest", t_write, write_s)

    def _build_slo_monitor(self):
        """SLO specs from ``cfg.slo`` (a slo.json) or, failing that, a
        top-level ``"slos"`` key inside the request manifest."""
        from sagecal_tpu.obs.slo import SLOMonitor, load_slo_specs

        specs = {}
        if self.cfg.slo:
            specs = load_slo_specs(self.cfg.slo)
        elif self.cfg.requests and os.path.exists(self.cfg.requests):
            specs = load_slo_specs(self.cfg.requests)
        return SLOMonitor(specs)

    # -- the scheduler -------------------------------------------------

    def run(self, requests: List[SolveRequest], elog=None
            ) -> Dict[str, Any]:
        import jax

        from sagecal_tpu.elastic.checkpoint import (
            CheckpointManager, config_fingerprint,
        )
        from sagecal_tpu.obs.quality import DivergenceAbort
        from sagecal_tpu.obs.registry import get_registry

        cfg, reg = self.cfg, get_registry()
        t_start = time.time()
        os.makedirs(cfg.out_dir, exist_ok=True)
        self._slo = self._build_slo_monitor()
        shadow_owned = False
        if self.shadow is None \
                and float(getattr(cfg, "shadow_rate", 0.0) or 0.0) > 0.0:
            # a fleet worker injects its own persistent auditor before
            # run() (budget is per WORKER, not per claim cycle); the
            # standalone service builds and owns one per run
            from sagecal_tpu.obs.shadow import ShadowAuditor

            self.shadow = ShadowAuditor(
                cfg.out_dir, rate=cfg.shadow_rate,
                budget_s=float(getattr(cfg, "shadow_budget_s", 60.0)),
                seed=int(getattr(cfg, "shadow_seed", 0)),
                device=self.device, log=self.log)
            shadow_owned = True

        # -- per-tenant elastic state: which requests already finished
        tenants = list(dict.fromkeys(r.tenant for r in requests))
        by_tenant = {t: [r for r in requests if r.tenant == t]
                     for t in tenants}
        ckmgrs: Dict[str, CheckpointManager] = {}
        done_flags: Dict[str, np.ndarray] = {}
        skipped = 0
        resumed_metrics: List[tuple] = []  # (metrics_ts, state)
        for t in tenants:
            reqs = by_tenant[t]
            fp = config_fingerprint(
                app="serve", tenant=t,
                requests=[(r.request_id, os.path.abspath(r.dataset),
                           r.t0, r.tilesz, r.in_column) for r in reqs],
                use_f64=cfg.use_f64)
            flags = np.zeros(len(reqs), np.uint8)
            if cfg.resume or cfg.checkpoint_every > 0:
                mgr = CheckpointManager(
                    os.path.join(
                        cfg.checkpoint_dir
                        or os.path.join(cfg.out_dir, "serve.ckpt"),
                        "tenants", t),
                    fp, "serve", every=max(cfg.checkpoint_every, 1),
                    elog=elog, log=self.log)
                ckmgrs[t] = mgr
                if cfg.resume:
                    found = mgr.resume()
                    if found is not None:
                        rmeta, rarr, rpath = found
                        flags = np.asarray(
                            rarr["done"], np.uint8).copy()
                        n = int(flags.sum())
                        skipped += n
                        self.log(f"resume[{t}]: {n}/{len(reqs)} "
                                 f"requests already served ({rpath})")
                        if isinstance(rmeta, dict) \
                                and rmeta.get("metrics"):
                            resumed_metrics.append(
                                (float(rmeta.get("metrics_ts", 0.0)),
                                 rmeta["metrics"]))
                        if elog is not None:
                            for r, f in zip(reqs, flags):
                                if f:
                                    elog.emit("request_skipped_resume",
                                              request_id=r.request_id,
                                              tenant=t)
            done_flags[t] = flags
        if resumed_metrics and reg.enabled:
            # every tenant checkpoint snapshots the whole process-wide
            # registry, so restore only the NEWEST one: counters stay
            # monotonic across the preemption without double-counting
            _, state = max(resumed_metrics, key=lambda x: x[0])
            reg.restore_state(state)

        # -- queues (post-resume) and double-buffered prefetch streams.
        # A stream is one (tenant, dataset, tilesz, column) request
        # sequence; its prefetcher loads tiles in exactly the order the
        # round-robin will pop them.
        queues = {
            t: collections.deque(
                r for r, f in zip(by_tenant[t], done_flags[t]) if not f)
            for t in tenants}
        enqueued_at = {
            r.request_id: getattr(r, "enqueued_at", 0.0) or time.time()
            for t in tenants for r in queues[t]}
        for t in tenants:
            reg.gauge_set("serve_queue_depth", len(queues[t]),
                          tenant=t,
                          help="requests waiting in this tenant's queue")

        dtype = np.float64 if cfg.use_f64 else np.float32
        stream_t0s: Dict[tuple, List[int]] = {}
        for t in tenants:
            for r in queues[t]:
                skey = (t, os.path.abspath(r.dataset), r.tilesz,
                        r.in_column)
                stream_t0s.setdefault(skey, []).append(r.t0)
        pool = _StreamPool(getattr(cfg, "max_streams", 0))
        for skey, t0s in stream_t0s.items():
            pool.register(skey, t0s, dtype)

        pending: Dict[tuple, List[_Entry]] = collections.defaultdict(list)
        served = 0

        def mark_done(entry: _Entry) -> None:
            nonlocal served
            served += 1
            t = entry.req.tenant
            i = next(i for i, r in enumerate(by_tenant[t])
                     if r.request_id == entry.req.request_id)
            done_flags[t][i] = 1
            if t in ckmgrs:
                extra = {}
                if reg.enabled:
                    # registry snapshot rides the elastic checkpoint:
                    # a --resume restores it, so counters survive
                    # preemptions instead of silently resetting
                    extra = dict(metrics=reg.export_state(),
                                 metrics_ts=time.time())
                ckmgrs[t].update(
                    int(done_flags[t].sum()) - 1,
                    {"done": done_flags[t]},
                    requests_done=int(done_flags[t].sum()),
                    tenant=t, **extra)

        def dispatch(bkey, padded_flush):
            bucket, fp = bkey
            entries = pending.pop(bkey)
            self._dispatch(bucket, fp, entries, cfg.batch, elog,
                           padded_flush)
            for e in entries:
                mark_done(e)

        try:
            # round-robin drain: one request per tenant per turn
            alive = True
            while alive:
                alive = False
                for t in tenants:
                    if not queues[t]:
                        continue
                    alive = True
                    req = queues[t].popleft()
                    t_pop = time.time()
                    reg.gauge_set("serve_queue_depth", len(queues[t]),
                                  tenant=t)
                    skey = (t, os.path.abspath(req.dataset),
                            req.tilesz, req.in_column)
                    t0, (data,) = pool.next_tile(skey)
                    if t0 != req.t0:
                        raise RuntimeError(
                            f"prefetch order mismatch for "
                            f"{req.request_id}: got tile {t0}, "
                            f"expected {req.t0}")
                    entry, fp = self._load_entry(
                        req, data, pool.meta(skey))
                    entry.enqueued_at = enqueued_at.get(
                        req.request_id, t_start)
                    entry.started_at = t_pop
                    bkey = (bucket_of(data, entry.cdata, entry.p0), fp)
                    pending[bkey].append(entry)
                    if len(pending[bkey]) >= cfg.batch:
                        dispatch(bkey, padded_flush=False)
            # ragged flush: pad the leftovers of each bucket
            for bkey in list(pending):
                dispatch(bkey, padded_flush=True)
        finally:
            # streams drain exactly when their queues do, so on the
            # success path every stream already closed on its sentinel;
            # on an error path pool.close() reaps the still-open ones
            # (crash-flusher contract: no leaked reader threads)
            pool.close()
            if self.shadow is not None and shadow_owned:
                self.shadow.close()
            for mgr in ckmgrs.values():
                mgr.flush()
                mgr.close()
            if reg.enabled:
                # one cumulative snapshot per worker: the aggregation
                # side (obs/aggregate.py) merges the fleet's snapshots
                # and keeps the newest per worker id
                from sagecal_tpu.obs.aggregate import (
                    metrics_snapshot_path, write_metrics_snapshot,
                )

                try:
                    write_metrics_snapshot(
                        metrics_snapshot_path(cfg.out_dir),
                        registry=reg)
                except OSError:
                    pass

        wall = time.time() - t_start
        lat = sorted(self._latencies)
        p50 = lat[len(lat) // 2] if lat else 0.0
        summary = {
            "requests": len(requests), "served": served,
            "skipped_resume": skipped,
            "tenants": len(tenants), "buckets": self.cache.stats(),
            "wall_s": wall,
            "solves_per_sec": served / wall if wall > 0 else 0.0,
            "p50_latency_s": p50,
            "prefetch_evictions": pool.evictions,
            "results": self._results,
        }
        if self.shadow is not None:
            summary["shadow"] = self.shadow.stats()
        if self._slo is not None and self._slo.enabled:
            summary["slo"] = self._slo.evaluate(registry=reg)
        if elog is not None:
            elog.emit("run_done", app="serve",
                      **{k: v for k, v in summary.items()
                         if k != "results"})
        if self._diverged_abort is not None:
            rid, t0, reasons = self._diverged_abort
            raise DivergenceAbort(
                f"request {rid} (tile {t0}) diverged: "
                f"{'; '.join(reasons)}")
        if self.shadow is not None and self.shadow.exceeded \
                and getattr(cfg, "abort_on_drift", False):
            # opt-in escalation, after every manifest and the full
            # drift ledger are on disk (report-only is the default —
            # the shipped results may well be fine; the ledger exists
            # so this decision is explicit)
            raise DivergenceAbort(
                "shadow drift exceeded tolerance for request(s) "
                + ", ".join(self.shadow.exceeded)
                + "; aborting (abort_on_drift)")
        return summary
