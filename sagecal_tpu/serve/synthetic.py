"""Synthetic multi-tenant request workloads.

``sagecal-tpu serve --synthetic N`` (and the serve smoke in
tpu_kernel_check.sh, and the throughput bench) need a reproducible
mixed-shape request mix without real observations on disk.  This
module simulates small datasets across a couple of shape classes and
writes a request manifest spread over a few tenants — enough to
exercise bucketing (two buckets), ragged padding (odd counts), and the
per-tenant queues.
"""

from __future__ import annotations

import json
import math
import os
from typing import List, Tuple

import numpy as np

# two-point-source sky shared by every synthetic dataset (same model as
# the elastic/serve test fixtures)
_SKY = (
    "P1 0 0 0.0 51 0 0.0 2.0 0 0 0 0 0 0 0 0 0 0 150e6\n"
    "P2 0 2 0.0 50 30 0.0 1.0 0 0 0 0 0 0 0 0 0 0 150e6\n"
)
_CLUSTER = "1 1 P1\n2 1 P2\n"

#: (nstations, ntime, nchan) shape classes the mix cycles through;
#: two classes -> two buckets
SHAPE_CLASSES: Tuple[Tuple[int, int, int], ...] = ((7, 4, 2), (8, 4, 2))


def make_synthetic_workload(workdir: str, n_requests: int,
                            n_tenants: int = 2, tilesz: int = 2,
                            shapes=SHAPE_CLASSES) -> str:
    """Simulate datasets + write ``<workdir>/requests.json``; returns
    the manifest path.  Requests cycle tenants round-robin and shape
    classes per tenant, so every tenant's stream is homogeneous (one
    prefetcher each) while the service still sees a mixed bucket set."""
    from sagecal_tpu.io.dataset import simulate_dataset
    from sagecal_tpu.io.simulate import random_jones
    from sagecal_tpu.io.skymodel import load_sky

    os.makedirs(workdir, exist_ok=True)
    sky = os.path.join(workdir, "sky.txt")
    with open(sky, "w") as f:
        f.write(_SKY)
    with open(sky + ".cluster", "w") as f:
        f.write(_CLUSTER)
    dec0 = math.radians(51.0)

    datasets = {}

    def dataset_for(tenant_i: int, shape) -> str:
        key = (tenant_i, shape)
        if key in datasets:
            return datasets[key]
        import h5py

        nstations, ntime, nchan = shape
        path = os.path.join(
            workdir, f"tenant{tenant_i}_N{nstations}.vis.h5")
        clusters, _, _ = load_sky(sky, sky + ".cluster", 0.0, dec0,
                                  dtype=np.float64)
        simulate_dataset(
            path, nstations=nstations, ntime=ntime, nchan=nchan,
            clusters=clusters,
            jones=random_jones(len(clusters), nstations,
                               seed=17 + tenant_i, amp=0.1,
                               dtype=np.complex128),
            noise_sigma=1e-4, seed=tenant_i, dec0=dec0)
        with h5py.File(path, "r+") as f:
            f.attrs["ra0"] = 0.0
            f.attrs["dec0"] = dec0
        datasets[key] = path
        return path

    requests: List[dict] = []
    for i in range(n_requests):
        tenant_i = i % n_tenants
        shape = shapes[tenant_i % len(shapes)]
        nstations, ntime, nchan = shape
        path = dataset_for(tenant_i, shape)
        ntiles = max(ntime // tilesz, 1)
        requests.append({
            "request_id": f"req{i:03d}",
            "tenant": f"tenant{tenant_i}",
            "dataset": path,
            "sky_model": sky,
            "t0": (i // n_tenants % ntiles) * tilesz,
            "tilesz": tilesz,
            "solver_mode": 1,
            "max_emiter": 1, "max_iter": 2, "max_lbfgs": 4,
        })
    manifest = os.path.join(workdir, "requests.json")
    # tmp + replace: a concurrently-starting worker never reads a
    # half-written request manifest
    tmp = f"{manifest}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"requests": requests}, f, indent=1)
    os.replace(tmp, manifest)
    return manifest
