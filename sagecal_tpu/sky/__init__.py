"""Hierarchical sky prediction: tree-clustered far-field coherencies
for wide fields (ROADMAP item 4).

Public surface:

- :func:`sagecal_tpu.sky.predict.predict_coherencies_hier` — drop-in,
  differentiable variant of ``ops.rime.predict_coherencies`` with an
  (order, theta) error knob;
- :func:`sagecal_tpu.sky.predict.build_hier_plan` /
  :class:`sagecal_tpu.sky.predict.HierPlan` — the host-side routing
  reused across calls;
- :func:`sagecal_tpu.sky.predict.sampled_error_estimate` — the
  a-posteriori check the quality watchdog gauges;
- :func:`sagecal_tpu.sky.farfield.apriori_rel_bound` — the analytic
  truncation bound;
- :func:`sagecal_tpu.sky.tree.build_source_tree` /
  :func:`sagecal_tpu.sky.tree.partition_by_tree` — host-side tree and
  the effective-cluster collapse for the widefield workload.
"""

from sagecal_tpu.sky.farfield import apriori_rel_bound
from sagecal_tpu.sky.predict import (
    HierPlan,
    build_hier_plan,
    gather_sources,
    predict_coherencies_hier,
    sampled_error_estimate,
)
from sagecal_tpu.sky.tree import (
    SourceTree,
    build_source_tree,
    partition_by_tree,
)

__all__ = [
    "HierPlan",
    "SourceTree",
    "apriori_rel_bound",
    "build_hier_plan",
    "build_source_tree",
    "gather_sources",
    "partition_by_tree",
    "predict_coherencies_hier",
    "sampled_error_estimate",
]
