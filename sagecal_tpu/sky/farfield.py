"""Far-field low-rank expansion of the RIME phase about node centroids.

For a source s in a tree node with centroid ``(l0, m0, n0-1)`` the
per-row, per-channel phase splits as

    f*G_s = f*G_0 + y_s,   y_s = 2*pi*f*(u*dl + v*dm + w*dn)

(``G`` as in :mod:`sagecal_tpu.ops.rime`: ``2*pi*(u*l + v*m + w*(n-1))``
with u,v,w in seconds).  Truncating ``exp(i*y)`` at multipole order p,

    exp(i*y) = sum_{k<=p} (i*y)^k / k!  + R_p,   |R_p| <= |y|^{p+1}/(p+1)!

and expanding ``y^k`` multinomially separates source factors from
baseline factors:

    coh(f,c,r) ~= exp(i*f*G_0(r)) * sum_{a+b+c<=p}
        (i*2*pi*f)^{a+b+c} / (a! b! c!) * u^a v^b w^c * M_abc(f,p)

with the per-node AGGREGATE MOMENTS

    M_abc(f,p) = sum_{s in node} stokes_s(f,p) * dl^a dm^b dn^c

(``stokes_s`` the per-source REAL Stokes fluxes with the spectral
model applied; the constant linear Stokes-to-coherency map commutes
with every contraction and is applied last).  The node sum over
sources happens ONCE in the moments; the per-(node, tile) work is a
dense (rows, nmoments) x (F, npol, nmoments) REAL contraction —
exactly the kind of small dense matmul the MXU wants, with total
bytes independent of the source count.  ``npol`` is 1 when the
concrete sky is unpolarized (the wide-field norm — a 4x traffic cut
the plan selects statically) and 4 otherwise.

Everything here is jax and differentiable: moments are linear in the
source fluxes and smooth in the positions, so gradients of the
hierarchical predict flow through to the sky parameters (the
refine-adoption requirement pinned by tests/test_sky_hier.py).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from sagecal_tpu.ops.rime import SourceBatch, _spectral_flux


def multipole_table(order: int) -> tuple:
    """Host-side enumeration of the multi-indices with |(a,b,c)| <= p.

    Returns ``(abc, invfact, degree)``: ``abc`` (Q, 3) int exponents,
    ``invfact`` (Q,) float 1/(a! b! c!), ``degree`` (Q,) int a+b+c.
    Ordered by total degree so truncation to a lower order is a prefix.
    """
    if order < 0:
        raise ValueError("order must be >= 0")
    rows = []
    for k in range(order + 1):
        for a in range(k, -1, -1):
            for b in range(k - a, -1, -1):
                c = k - a - b
                rows.append((a, b, c))
    abc = np.asarray(rows, np.int64)
    invfact = np.asarray(
        [1.0 / (math.factorial(a) * math.factorial(b) * math.factorial(c))
         for a, b, c in rows], np.float64)
    degree = abc.sum(axis=1)
    return abc, invfact, degree


def apriori_rel_bound(order: int, theta: float) -> float:
    """Taylor-remainder bound on the far-field truncation error.

    Every admissible (node, tile) pair satisfies ``|y| <= theta`` for
    all of its rows/channels, so the pointwise error of the expanded
    node contribution is at most ``theta^(p+1)/(p+1)!`` times the
    node's summed ABSOLUTE coherency amplitude.  Normalized by the
    total absolute source amplitude this is the sky-wide relative
    bound the quality watchdog verifies a-posteriori."""
    if theta <= 0:
        return 0.0
    return float(theta) ** (order + 1) / math.factorial(order + 1)


def source_stokes(src: SourceBatch, freqs: jax.Array,
                  npol: int) -> jax.Array:
    """Per-source STOKES fluxes (S, F, npol) REAL with the spectral
    model applied.  ``npol`` is 1 (I only — the unpolarized fast path
    the plan selects when the concrete sky has no Q/U/V) or 4
    (I, Q, U, V).  Keeping the moment pipeline in the real Stokes
    basis halves its traffic versus coherency-basis complex moments;
    the (constant, linear) Stokes-to-coherency map is applied to the
    tiny post-contraction tensors in :func:`far_field_tile`."""
    I = _spectral_flux(src.sI0, src.f0, src.spec_idx, src.spec_idx1,
                       src.spec_idx2, freqs)
    if npol == 1:
        return I[:, :, None]
    Q = _spectral_flux(src.sQ0, src.f0, src.spec_idx, src.spec_idx1,
                       src.spec_idx2, freqs)
    U = _spectral_flux(src.sU0, src.f0, src.spec_idx, src.spec_idx1,
                       src.spec_idx2, freqs)
    V = _spectral_flux(src.sV0, src.f0, src.spec_idx, src.spec_idx1,
                       src.spec_idx2, freqs)
    return jnp.stack([I, Q, U, V], axis=-1)


def _monomials(d: jax.Array, abc: np.ndarray) -> jax.Array:
    """``prod_k d[..., k]^abc[q, k]``: (..., Q) from (..., 3) via one
    cumprod power table (no repeated pow lowering)."""
    amax = int(abc.max()) if abc.size else 0
    powers = jnp.cumprod(
        jnp.concatenate(
            [jnp.ones_like(d)[..., None],
             jnp.repeat(d[..., None], max(amax, 1), axis=-1)],
            axis=-1),
        axis=-1)  # (..., 3, amax+1)
    return (powers[..., 0, abc[:, 0]]
            * powers[..., 1, abc[:, 1]]
            * powers[..., 2, abc[:, 2]])


def node_moments(
    src: SourceBatch,
    freqs: jax.Array,
    node_of_source: jax.Array,   # (L, S) flat node id per level
    node_center: jax.Array,      # (nnodes, 3)
    nnodes: int,
    abc: np.ndarray,             # (Q, 3) host exponent table
    npol: int = 4,
) -> jax.Array:
    """Aggregate Stokes moments for every routed node:
    (nnodes, F, npol, Q) REAL.

    One ``segment_sum`` per routed tree level over the shared
    per-source fluxes; ``num_segments`` is the static total node
    count, so the output shape is data-independent (JL005-clean)."""
    stokes = source_stokes(src, freqs, npol)  # (S, F, npol) real
    pos = jnp.stack([src.ll, src.mm, src.nn], axis=1)  # (S, 3)
    L = node_of_source.shape[0]

    out = jnp.zeros(
        (nnodes,) + stokes.shape[1:] + (abc.shape[0],), stokes.dtype)
    for lev in range(L):
        idx = node_of_source[lev]
        mono = _monomials(pos - node_center[idx], abc)  # (S, Q)
        data = stokes[:, :, :, None] * mono[:, None, None, :].astype(
            stokes.dtype)
        out = out + jax.ops.segment_sum(
            data, idx, num_segments=nnodes, indices_are_sorted=False)
    return out


def far_field_tile(
    u_t: jax.Array,          # (R,) one tile's rows, seconds
    v_t: jax.Array,
    w_t: jax.Array,
    freqs: jax.Array,        # (F,)
    centers: jax.Array,      # (nnodes, 3)
    moments: jax.Array,      # (nnodes, F, npol, Q) real Stokes
    far_idx: jax.Array,      # (Fmax,) flat node ids for this tile
    far_valid: jax.Array,    # (Fmax,)
    abc: np.ndarray,         # (Q, 3) host exponents
    invfact: np.ndarray,     # (Q,)
    degree: np.ndarray,      # (Q,)
    fdelta: float = 0.0,
) -> jax.Array:
    """One tile's far-field coherency contribution: (F, 4, R) complex.

    The Taylor coefficient ``(i 2 pi f)^deg`` splits into a real
    magnitude and a host-constant sign of ``i^deg``, so the node/moment
    contractions run entirely in REAL Stokes arithmetic; the complex
    centroid phase and the constant Stokes-to-coherency map touch only
    the small post-contraction (F, npol, R) tensors.

    ``fdelta > 0`` applies bandwidth smearing in the NODE-CENTROID
    approximation (``sinc`` evaluated at G0 instead of per source) —
    the smear factor varies across a node at second order in the same
    small phase argument the expansion already truncates."""
    rdtype = u_t.dtype

    ctr = centers[far_idx]                       # (Fmax, 3)
    Mg = moments[far_idx] * far_valid[:, None, None, None].astype(rdtype)
    npol = Mg.shape[2]

    # centroid phase exp(i f G0): (Fmax, F, R)
    G0 = 2.0 * jnp.pi * (
        u_t[None, :] * ctr[:, 0:1]
        + v_t[None, :] * ctr[:, 1:2]
        + w_t[None, :] * ctr[:, 2:3]
    )  # (Fmax, R)
    ang = freqs[None, :, None] * G0[:, None, :]
    phase0 = jax.lax.complex(jnp.cos(ang), jnp.sin(ang))
    if fdelta > 0.0:
        from sagecal_tpu.ops.special import sinc_abs

        phase0 = phase0 * sinc_abs(
            G0 * (0.5 * fdelta))[:, None, :].astype(rdtype)

    # baseline monomials u^a v^b w^c: (R, Q)
    P = _monomials(jnp.stack([u_t, v_t, w_t], axis=1), abc)

    # (i 2 pi f)^deg / (a! b! c!) = mag(f,q) * i^deg with i^deg a host
    # constant sign pattern: keep the contraction real
    deg = np.asarray(degree)
    mag = ((2.0 * jnp.pi) * freqs)[:, None] ** jnp.asarray(deg)[None, :]
    mag = mag * jnp.asarray(invfact, rdtype)[None, :]   # (F, Q)
    re_s = np.asarray([1.0, 0.0, -1.0, 0.0])[deg % 4]   # Re(i^deg)
    im_s = np.asarray([0.0, 1.0, 0.0, -1.0])[deg % 4]   # Im(i^deg)

    # sum over far nodes j and moments q (real einsums):
    #   S(f,p,r) = sum_j phase0(j,f,r) sum_q Mg(j,f,p,q) coef(f,q) P(r,q)
    Tr = jnp.einsum(
        "jfpq,rq->jfpr", Mg * (mag * jnp.asarray(re_s, rdtype))[
            None, :, None, :], P)
    Ti = jnp.einsum(
        "jfpq,rq->jfpr", Mg * (mag * jnp.asarray(im_s, rdtype))[
            None, :, None, :], P)
    S = jnp.einsum("jfr,jfpr->fpr", phase0, jax.lax.complex(Tr, Ti))

    # constant Stokes -> coherency map on the contracted tensor
    if npol == 1:
        z = jnp.zeros_like(S[:, 0])
        return jnp.stack([S[:, 0], z, z, S[:, 0]], axis=1)
    I, Qs, U, V = S[:, 0], S[:, 1], S[:, 2], S[:, 3]
    return jnp.stack(
        [I + Qs, U + 1j * V, U - 1j * V, I - Qs], axis=1)
