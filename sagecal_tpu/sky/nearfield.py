"""Near-field (non-admissible) exact prediction on gathered subsets.

The routing (:func:`sagecal_tpu.sky.tree.route_tiles`) leaves every
(node, baseline-tile) pair that fails the well-separation criterion as
a per-tile list of SOURCE indices.  This module gathers those subsets
into one fixed-shape batched :class:`~sagecal_tpu.ops.rime.SourceBatch`
(tiles x max_near, zero-flux padded) and routes them through the
EXISTING exact predict — same phase/smear/spectral math, same
gradients — vmapped over tiles.

Padding contract: a padded slot gathers source 0 but multiplies every
Stokes flux by the 0/1 validity mask, which makes it an EXACT no-op in
the coherency contraction (the same invariant pad_source_batch relies
on); ``f0`` is pinned to the gathered (positive) value so the spectral
log never sees 0.  tests/test_sky_hier.py pins the exactly-zero
contribution.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sagecal_tpu.ops.rime import SourceBatch, predict_coherencies


def gather_near_batch(
    src: SourceBatch,
    near_src: jax.Array,     # (T, Nmax) source ids, 0-padded
    near_valid: jax.Array,   # (T, Nmax) 0/1
) -> SourceBatch:
    """Batched per-tile near-field SourceBatch: every field (T, Nmax).

    Differentiable in the source parameters (plain gathers); the
    validity mask zeroes the padded slots' fluxes only — positions and
    shape parameters ride along untouched so dtypes/invariants hold.
    """
    g = jax.tree_util.tree_map(lambda x: x[near_src], src)
    val = near_valid.astype(src.sI0.dtype)
    ival = near_valid.astype(jnp.int32)
    return g.replace(
        sI0=g.sI0 * val, sQ0=g.sQ0 * val, sU0=g.sU0 * val,
        sV0=g.sV0 * val,
        # padded slots are plain points regardless of the gathered type
        stype=g.stype * ival,
        shapelet_idx=jnp.where(near_valid > 0, g.shapelet_idx, -1),
    )


def near_field_tiles(
    u_t: jax.Array,          # (T, R) tiled rows, seconds
    v_t: jax.Array,
    w_t: jax.Array,
    freqs: jax.Array,
    src: SourceBatch,
    near_src: jax.Array,
    near_valid: jax.Array,
    fdelta: float = 0.0,
    source_chunk: int = 32,
) -> jax.Array:
    """Near-field coherencies per tile: (T, F, 4, R) complex.

    One vmapped exact predict over the gathered subsets.  The static
    source-type flags are passed explicitly (the satellite-2 contract:
    under this vmap the legacy stype probe would silently flip to the
    conservative extended-source program)."""
    batch = gather_near_batch(src, near_src, near_valid)

    def one(u, v, w, s):
        return predict_coherencies(
            u, v, w, freqs, s, fdelta, source_chunk,
            has_extended=False, has_shapelet=False)

    return jax.vmap(one)(u_t, v_t, w_t, batch)
