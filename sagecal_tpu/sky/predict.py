"""Public hierarchical prediction entry: drop-in, differentiable
variant of :func:`sagecal_tpu.ops.rime.predict_coherencies` for
wide-field (10k+ source) point skies.

``predict_coherencies_hier`` returns the same canonical (F, 4, rows)
complex coherency stack, computed as

- FAR FIELD: per-node order-p phase-gradient expansions about the
  tree-node centroids (:mod:`sagecal_tpu.sky.farfield`) for every
  (node, baseline-tile) pair passing the well-separation criterion
  ``2*pi*fmax*|b|*r_node <= theta``;
- NEAR FIELD: the existing exact predict on the gathered residual
  source subsets (:mod:`sagecal_tpu.sky.nearfield`), zero-flux padded
  to the max near list.

The error knob is ``(order, theta)``: the a-priori pointwise bound is
``theta^(order+1)/(order+1)!`` relative to the summed absolute source
amplitude (:func:`sagecal_tpu.sky.farfield.apriori_rel_bound`), and
:func:`sampled_error_estimate` measures the a-posteriori error against
exact prediction on a random baseline subsample — the number the
quality watchdog (:func:`sagecal_tpu.obs.quality.check_hier_predict`)
gauges and verdicts.

Plan/compute split: :func:`build_hier_plan` runs ONCE per (uvw tile
set, sky geometry) on the host (concrete positions required); the
compiled compute consumes the plan's fixed-shape index arrays, so the
same plan serves repeated calls, other orders (routing depends only on
theta), and gradient traces where the source batch is a tracer.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from sagecal_tpu.obs.perf import instrumented_jit
from sagecal_tpu.ops.rime import ST_POINT, SourceBatch, predict_coherencies
from sagecal_tpu.sky.farfield import (
    apriori_rel_bound,
    far_field_tile,
    multipole_table,
    node_moments,
)
from sagecal_tpu.sky.nearfield import near_field_tiles
from sagecal_tpu.sky.tree import (
    HierRouting,
    SourceTree,
    build_source_tree,
    route_tiles,
)


@dataclasses.dataclass(frozen=True)
class HierPlan:
    """One sky x uvw-tile-set routing, device-ready.

    ``tree``/``routing`` keep the host-side numpy bookkeeping (stats,
    bound accounting); the jnp members are what the compiled predict
    consumes.  Reusable across calls with the same uvw rows and source
    POSITIONS — fluxes/spectra may differ (and may be tracers)."""

    tree: SourceTree
    routing: HierRouting
    theta: float
    node_of_source: jax.Array    # (L_used, S) int32, far-used levels only
    node_center: jax.Array       # (nnodes, 3)
    far_idx: jax.Array           # (T, Fmax) int32
    far_valid: jax.Array         # (T, Fmax)
    near_src: jax.Array          # (T, Nmax) int32
    near_valid: jax.Array        # (T, Nmax)
    # baseline-length row ordering: tiles are length-homogeneous so
    # short-baseline tiles admit COARSE nodes (the routing is per-tile
    # max |b|); row_inv scatters the tiled result back to canonical
    # row order
    row_perm: jax.Array          # (rows,) int32
    row_inv: jax.Array           # (rows,) int32
    used_levels: tuple = ()      # tree levels with >= 1 far node
    # 1 = unpolarized fast path (concrete sky had no Q/U/V at build
    # time), 4 = full Stokes.  Static: fixes the compiled program's
    # polarization structure, so gradients w.r.t. Q/U/V fluxes need a
    # plan built with force_polarized=True.
    npol: int = 4

    @property
    def nnodes(self) -> int:
        return self.tree.nnodes

    @property
    def use_far(self) -> bool:
        return self.routing.far_pairs > 0

    @property
    def use_near(self) -> bool:
        return self.routing.near_sources_total > 0

    def stats(self) -> dict:
        r = self.routing
        return {
            "depth": self.tree.depth,
            "nnodes": self.nnodes,
            "ntiles": r.ntiles,
            "tile_rows": r.tile_rows,
            "far_pairs": r.far_pairs,
            "max_far": r.max_far,
            "near_sources_total": r.near_sources_total,
            "max_near": r.max_near,
            "theta": self.theta,
        }


def build_hier_plan(
    u, v, w, freqs, src: SourceBatch,
    *,
    theta: float = 1.5,
    leaf_size: int = 32,
    tile_rows: int = 128,
    depth: Optional[int] = None,
    force_polarized: bool = False,
) -> HierPlan:
    """Host-side plan construction (concrete positions required).

    Raises on non-point batches: extended/shapelet sources have
    uv-dependent amplitudes the far-field expansion does not model —
    route those clusters through the exact predict instead.

    ``force_polarized`` keeps the full-Stokes moment pipeline even for
    an unpolarized sky (needed to differentiate through the plan
    w.r.t. Q/U/V fluxes)."""
    st = np.asarray(src.stype)
    if bool(np.any(st != ST_POINT)):
        raise ValueError(
            "predict_coherencies_hier supports point-source batches only; "
            "extended/shapelet clusters must use the exact "
            "predict_coherencies path")
    ll = np.asarray(src.ll, np.float64)
    mm = np.asarray(src.mm, np.float64)
    nn = np.asarray(src.nn, np.float64)
    tree = build_source_tree(ll, mm, nn, leaf_size=leaf_size, depth=depth)

    uu = np.asarray(u, np.float64)
    vv = np.asarray(v, np.float64)
    ww = np.asarray(w, np.float64)
    rows = int(uu.shape[0])
    # sort rows by baseline length so each tile's max |b| is as small
    # as its members allow: short-baseline tiles then admit COARSE
    # nodes (one expansion covering thousands of sources) instead of
    # being dragged to the leaves by one long row
    row_perm = np.argsort(
        np.sqrt(uu * uu + vv * vv + ww * ww), kind="stable")
    routing = route_tiles(
        tree, uu[row_perm], vv[row_perm], ww[row_perm],
        float(np.max(np.asarray(freqs))), float(theta),
        tile_rows=tile_rows)
    row_inv = np.empty_like(row_perm)
    row_inv[row_perm] = np.arange(rows)

    far_nodes = routing.far_idx[routing.far_valid > 0]
    if far_nodes.size:
        levs = np.searchsorted(
            tree.level_offset, far_nodes, side="right") - 1
        used_levels = tuple(sorted({int(x) for x in levs}))
    else:
        used_levels = ()
    # moments are only needed on levels the far routing references
    nos = (tree.node_of_source[list(used_levels)] if used_levels
           else tree.node_of_source[:0])

    unpol = not (
        bool(np.any(np.asarray(src.sQ0)))
        or bool(np.any(np.asarray(src.sU0)))
        or bool(np.any(np.asarray(src.sV0))))
    npol = 1 if (unpol and not force_polarized) else 4

    rdtype = np.asarray(u).dtype
    return HierPlan(
        tree=tree, routing=routing, theta=float(theta),
        node_of_source=jnp.asarray(nos, jnp.int32),
        node_center=jnp.asarray(tree.node_center, rdtype),
        far_idx=jnp.asarray(routing.far_idx, jnp.int32),
        far_valid=jnp.asarray(routing.far_valid, rdtype),
        near_src=jnp.asarray(routing.near_src, jnp.int32),
        near_valid=jnp.asarray(routing.near_valid, rdtype),
        row_perm=jnp.asarray(row_perm, jnp.int32),
        row_inv=jnp.asarray(row_inv, jnp.int32),
        used_levels=used_levels,
        npol=npol,
    )


@functools.partial(
    instrumented_jit, name="predict_coherencies_hier",
    static_argnums=(11, 12, 13, 14, 15, 16, 17))
def _hier_core(
    u_t, v_t, w_t, freqs, src, node_of_source, node_center,
    far_idx, far_valid, near_src, near_valid,
    order, nnodes, fdelta, source_chunk, use_far, use_near, npol,
):
    abc, invfact, degree = multipole_table(order)
    T, R = u_t.shape
    F = freqs.shape[0]
    cdtype = (jnp.complex64 if u_t.dtype == jnp.float32
              else jnp.complex128)
    total = jnp.zeros((T, F, 4, R), cdtype)
    if use_far:
        moments = node_moments(
            src, freqs, node_of_source, node_center, nnodes, abc,
            npol=npol)

        def one_far(u, v, w, fi, fv):
            return far_field_tile(
                u, v, w, freqs, node_center, moments, fi, fv,
                abc, invfact, degree, fdelta=fdelta)

        total = total + jax.vmap(one_far)(
            u_t, v_t, w_t, far_idx, far_valid)
    if use_near:
        total = total + near_field_tiles(
            u_t, v_t, w_t, freqs, src, near_src, near_valid,
            fdelta, source_chunk)
    # (T, F, 4, R) -> canonical flat (F, 4, T*R)
    return jnp.moveaxis(total, 0, 2).reshape(F, 4, T * R)


def predict_coherencies_hier(
    u: jax.Array,
    v: jax.Array,
    w: jax.Array,
    freqs: jax.Array,
    src: SourceBatch,
    *,
    order: int = 8,
    theta: float = 1.5,
    leaf_size: int = 32,
    tile_rows: int = 128,
    fdelta: float = 0.0,
    source_chunk: int = 32,
    plan: Optional[HierPlan] = None,
    return_plan: bool = False,
):
    """Hierarchical sum of point-source coherencies: (F, 4, rows)
    complex, drop-in for :func:`~sagecal_tpu.ops.rime.predict_coherencies`.

    ``order`` (multipole/Taylor order p) and ``theta`` (well-separation
    phase budget, radians; <= 0 forces everything through the exact
    near-field path) are the error knobs — a-priori pointwise error
    <= ``apriori_rel_bound(order, theta)`` x the summed absolute source
    amplitude.  ``fdelta`` applies exact bandwidth smearing on the
    near-field path and the node-centroid approximation on the far
    field.  Pass a prebuilt ``plan`` to amortize routing across calls
    (or to call with tracer fluxes under grad/jit); ``return_plan``
    returns ``(coh, plan)`` for reuse."""
    if plan is None:
        plan = build_hier_plan(
            u, v, w, freqs, src, theta=theta, leaf_size=leaf_size,
            tile_rows=tile_rows)
    T, R = plan.routing.ntiles, plan.routing.tile_rows
    rows = plan.routing.rows
    pad = T * R - rows
    # rows enter in the plan's baseline-length order and leave canonical
    u_t = jnp.pad(u[plan.row_perm], (0, pad)).reshape(T, R)
    v_t = jnp.pad(v[plan.row_perm], (0, pad)).reshape(T, R)
    w_t = jnp.pad(w[plan.row_perm], (0, pad)).reshape(T, R)
    coh = _hier_core(
        u_t, v_t, w_t, freqs, src,
        plan.node_of_source, plan.node_center,
        plan.far_idx, plan.far_valid, plan.near_src, plan.near_valid,
        int(order), plan.nnodes, float(fdelta), int(source_chunk),
        plan.use_far, plan.use_near, plan.npol,
    )[:, :, :rows][:, :, plan.row_inv]
    return (coh, plan) if return_plan else coh


def sampled_error_estimate(
    u, v, w, freqs, src: SourceBatch, coh_hier,
    nsample: int = 32,
    seed: int = 0,
    fdelta: float = 0.0,
    source_chunk: int = 32,
) -> dict:
    """A-posteriori error of a hierarchical prediction: exact predict
    on a random baseline-row subsample vs the corresponding rows of
    ``coh_hier``.  Host-side (concrete arrays).  Returns a dict with
    ``rel_err`` (max abs deviation over the sample, normalized by the
    sample's max exact amplitude), ``abs_err``, ``nsample`` and the
    sampled ``rows`` — the numbers the quality watchdog verifies
    against the knob."""
    rows = int(np.asarray(u).shape[0])
    rng = np.random.default_rng(seed)
    k = int(min(max(nsample, 1), rows))
    sel = np.sort(rng.choice(rows, size=k, replace=False))
    exact = predict_coherencies(
        jnp.asarray(np.asarray(u)[sel]),
        jnp.asarray(np.asarray(v)[sel]),
        jnp.asarray(np.asarray(w)[sel]),
        freqs, src, fdelta, source_chunk,
        has_extended=False, has_shapelet=False)
    exact = np.asarray(exact)
    h = np.asarray(coh_hier)[:, :, sel]
    abs_err = float(np.max(np.abs(h - exact))) if exact.size else 0.0
    scale = float(np.max(np.abs(exact))) if exact.size else 0.0
    rel = abs_err / scale if scale > 0 else 0.0
    return {
        "rel_err": rel,
        "abs_err": abs_err,
        "scale": scale,
        "nsample": k,
        "rows": sel,
    }


def gather_sources(src: SourceBatch, idx) -> SourceBatch:
    """Sub-batch of ``src`` at the given source indices (host helper
    for the tree-partitioned effective clusters)."""
    idx = jnp.asarray(np.asarray(idx, np.int64))
    return jax.tree_util.tree_map(lambda x: x[idx], src)


__all__ = [
    "HierPlan",
    "apriori_rel_bound",
    "build_hier_plan",
    "gather_sources",
    "predict_coherencies_hier",
    "sampled_error_estimate",
]
