"""Host-side source tree: a fixed-depth quadtree (2^d-ary over the
(l, m) tangent plane) built into FIXED-SHAPE index/offset arrays.

The hierarchical predict (:mod:`sagecal_tpu.sky.predict`) needs two
things from the tree, and both must be jit-consumable:

- a per-level node assignment for every source, so per-node aggregate
  moments are one ``segment_sum`` per level (fixed ``num_segments`` =
  the level's node count — no data-dependent shapes, jaxlint
  JL005-clean by construction);
- a routing of (node, baseline-tile) pairs into an admissible
  FAR-FIELD list (low-rank expansion) and a residual NEAR-FIELD source
  list per tile, padded to the maxima so every downstream gather and
  contraction has a static shape.

Everything in this module is plain numpy executed once per (uvw tile,
sky) on the host — the analog of the reference's cluster bookkeeping
that precedes ``precalculate_coherencies``.  The jax-side consumers
treat the returned arrays as constants of the compiled program.

Geometry conventions match :mod:`sagecal_tpu.ops.rime`: positions are
direction cosines (l, m) with ``nn = n - 1``; node radii are measured
in the full (l, m, n) 3-space so the Cauchy–Schwarz admissibility
bound ``|u·Δl + v·Δm + w·Δn| <= |b| * r`` holds exactly.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class SourceTree:
    """Fixed-depth quadtree over source positions (all host numpy).

    Nodes of every level live in ONE flat index space: level ``lev``
    occupies ``[level_offset[lev], level_offset[lev] + 4**lev)``.
    """

    depth: int                      # leaves are level `depth`
    level_offset: np.ndarray        # (depth+2,) flat offsets; [-1] = nnodes
    node_center: np.ndarray         # (nnodes, 3) member centroid (l, m, n-1)
    node_radius: np.ndarray         # (nnodes,) max member distance to center
    node_count: np.ndarray          # (nnodes,) member sources
    node_of_source: np.ndarray      # (depth+1, S) flat node id per level
    # leaf -> member sources: perm[leaf_start[i] : leaf_start[i]+leaf_count[i]]
    perm: np.ndarray                # (S,) source ids sorted by leaf
    leaf_start: np.ndarray          # (4**depth,)
    leaf_count: np.ndarray          # (4**depth,)

    @property
    def nnodes(self) -> int:
        return int(self.level_offset[-1])

    @property
    def nsources(self) -> int:
        return int(self.perm.shape[0])


def choose_depth(nsources: int, leaf_size: int, max_depth: int = 6) -> int:
    """Smallest depth whose 4^d leaves hold ~``leaf_size`` sources on
    average (the error knob does not depend on this — only the
    far/near work split does)."""
    d = 0
    while 4 ** d * max(int(leaf_size), 1) < nsources and d < max_depth:
        d += 1
    return d


def build_source_tree(
    ll, mm, nn, leaf_size: int = 32, depth: Optional[int] = None,
) -> SourceTree:
    """Build the fixed-depth tree over concrete source positions.

    ``ll``/``mm``/``nn`` are the (S,) position arrays of a
    :class:`~sagecal_tpu.ops.rime.SourceBatch` (``nn`` = n - 1),
    materialized host-side.  ``depth`` overrides the leaf-size-derived
    choice (``depth=0`` degenerates to one root node = one dense
    far-field expansion for the whole sky).
    """
    ll = np.asarray(ll, np.float64)
    mm = np.asarray(mm, np.float64)
    nn = np.asarray(nn, np.float64)
    S = ll.shape[0]
    if S == 0:
        raise ValueError("build_source_tree: empty source batch")
    if depth is None:
        depth = choose_depth(S, leaf_size)
    depth = int(depth)

    # bounding square over (l, m); epsilon keeps the max coordinate
    # strictly inside the last cell
    lmin, mmin = float(ll.min()), float(mm.min())
    extent = max(float(ll.max()) - lmin, float(mm.max()) - mmin, 1e-12)
    extent *= 1.0 + 1e-9

    nlev = depth + 1
    level_sizes = [4 ** lev for lev in range(nlev)]
    level_offset = np.concatenate(
        [[0], np.cumsum(level_sizes)]).astype(np.int64)
    nnodes = int(level_offset[-1])

    node_of_source = np.zeros((nlev, S), np.int64)
    for lev in range(nlev):
        ncell = 2 ** lev
        ix = np.floor((ll - lmin) / extent * ncell).astype(np.int64)
        iy = np.floor((mm - mmin) / extent * ncell).astype(np.int64)
        ix = np.clip(ix, 0, ncell - 1)
        iy = np.clip(iy, 0, ncell - 1)
        node_of_source[lev] = level_offset[lev] + iy * ncell + ix

    # member centroids / radii / counts over the flat node space
    pos = np.stack([ll, mm, nn], axis=1)  # (S, 3)
    node_count = np.zeros(nnodes, np.int64)
    node_center = np.zeros((nnodes, 3), np.float64)
    for lev in range(nlev):
        idx = node_of_source[lev]
        node_count += np.bincount(idx, minlength=nnodes)
        for k in range(3):
            node_center[:, k] += np.bincount(
                idx, weights=pos[:, k], minlength=nnodes)
    cnt = np.maximum(node_count, 1)
    node_center /= cnt[:, None]

    node_radius = np.zeros(nnodes, np.float64)
    for lev in range(nlev):
        idx = node_of_source[lev]
        d2 = np.sum((pos - node_center[idx]) ** 2, axis=1)
        np.maximum.at(node_radius, idx, np.sqrt(d2))

    # leaf membership lists (offset/count into one permutation)
    leaf_local = node_of_source[depth] - level_offset[depth]
    perm = np.argsort(leaf_local, kind="stable").astype(np.int64)
    leaf_count = np.bincount(leaf_local, minlength=4 ** depth).astype(
        np.int64)
    leaf_start = np.concatenate([[0], np.cumsum(leaf_count)[:-1]]).astype(
        np.int64)

    return SourceTree(
        depth=depth, level_offset=level_offset, node_center=node_center,
        node_radius=node_radius, node_count=node_count,
        node_of_source=node_of_source, perm=perm,
        leaf_start=leaf_start, leaf_count=leaf_count,
    )


@dataclasses.dataclass(frozen=True)
class HierRouting:
    """Fixed-shape far/near routing of one uvw tile set against one
    tree (all host numpy; padded to the per-tile maxima)."""

    ntiles: int
    tile_rows: int                  # rows per tile (uvw padded to fill)
    rows: int                       # true (unpadded) row count
    far_idx: np.ndarray             # (T, Fmax) flat node ids (0-padded)
    far_valid: np.ndarray           # (T, Fmax) float64 0/1
    near_src: np.ndarray            # (T, Nmax) source ids (0-padded)
    near_valid: np.ndarray          # (T, Nmax) float64 0/1
    # bookkeeping for the a-priori bound / stats
    theta: float = 0.0
    far_pairs: int = 0
    near_sources_total: int = 0

    @property
    def max_far(self) -> int:
        return int(self.far_idx.shape[1])

    @property
    def max_near(self) -> int:
        return int(self.near_src.shape[1])


def _pad_up(n: int, mult: int) -> int:
    return max(mult, -(-n // mult) * mult)


def route_tiles(
    tree: SourceTree,
    u, v, w,
    fmax: float,
    theta: float,
    tile_rows: int = 128,
    pad_far: int = 8,
    pad_near: int = 64,
) -> HierRouting:
    """Admissibility-route every (leaf node, baseline tile) pair.

    A leaf is ADMISSIBLE for a tile when the worst-case phase-argument
    excursion across it satisfies the well-separation criterion

        ``x_max = 2*pi * fmax * max|b|_tile * r_leaf <= theta``

    (``u``/``v``/``w`` in seconds, ``fmax`` in Hz, so ``fmax*|b|`` is
    the baseline length in wavelengths; ``r_leaf`` is the leaf's OWN
    member radius, so the Taylor remainder bound is tight per expanded
    node).  Admissible occupied leaves join the tile's FAR list; the
    rest spill their member sources into the tile's NEAR list.
    Expanding at one fixed level keeps the aggregate moments to a
    single segment-sum pass over the sources — the multi-level variant
    pays one full (S, F, 4, Q) materialization per level for a small
    far-list saving.  ``theta <= 0`` forces everything near-field (the
    exact-fallback mode the parity tests pin).

    Lists are padded to shared maxima (rounded up to ``pad_far`` /
    ``pad_near`` so repeated tiles bucket into few compiled shapes).
    """
    u = np.asarray(u, np.float64)
    v = np.asarray(v, np.float64)
    w = np.asarray(w, np.float64)
    rows = int(u.shape[0])
    tile_rows = int(min(tile_rows, max(rows, 1)))
    ntiles = -(-rows // tile_rows)

    blen = np.sqrt(u * u + v * v + w * w)
    bmax = np.zeros(ntiles, np.float64)
    for t in range(ntiles):
        seg = blen[t * tile_rows:(t + 1) * tile_rows]
        bmax[t] = float(seg.max()) if seg.size else 0.0

    depth = tree.depth
    off = int(tree.level_offset[depth])
    occ = np.nonzero(tree.leaf_count > 0)[0]          # occupied leaf locals
    r_occ = tree.node_radius[off + occ]
    scale = 2.0 * math.pi * float(fmax) * bmax        # (T,)
    # (T, nocc) admissibility in one outer comparison
    adm = (scale[:, None] * r_occ[None, :] <= theta) if theta > 0 else (
        np.zeros((ntiles, occ.size), bool))

    far_lists = []
    near_lists = []
    far_pairs = 0
    for t in range(ntiles):
        far_t = list(off + occ[adm[t]])
        near_t: list = []
        for local in occ[~adm[t]]:
            s0 = int(tree.leaf_start[local])
            near_t.extend(tree.perm[s0:s0 + int(tree.leaf_count[local])])
        far_pairs += len(far_t)
        far_lists.append(far_t)
        near_lists.append(near_t)

    fmax_n = _pad_up(max((len(x) for x in far_lists), default=0), pad_far)
    nmax_n = _pad_up(max((len(x) for x in near_lists), default=0), pad_near)
    far_idx = np.zeros((ntiles, fmax_n), np.int64)
    far_valid = np.zeros((ntiles, fmax_n), np.float64)
    near_src = np.zeros((ntiles, nmax_n), np.int64)
    near_valid = np.zeros((ntiles, nmax_n), np.float64)
    for t in range(ntiles):
        nf, nn_ = len(far_lists[t]), len(near_lists[t])
        if nf:
            far_idx[t, :nf] = far_lists[t]
            far_valid[t, :nf] = 1.0
        if nn_:
            near_src[t, :nn_] = near_lists[t]
            near_valid[t, :nn_] = 1.0

    return HierRouting(
        ntiles=ntiles, tile_rows=tile_rows, rows=rows,
        far_idx=far_idx, far_valid=far_valid,
        near_src=near_src, near_valid=near_valid,
        theta=float(theta), far_pairs=far_pairs,
        near_sources_total=int(near_valid.sum()),
    )


def partition_by_tree(tree: SourceTree, nclusters: int) -> list:
    """Group sources into at most ``nclusters`` spatially compact
    EFFECTIVE clusters using the shallowest tree level with enough
    occupied nodes — the host-side "hierarchical collapse" the
    widefield workload feeds to the packed solver.  Returns a list of
    (S_k,) source-index arrays (every source in exactly one group,
    groups ordered by descending membership)."""
    if nclusters < 1:
        raise ValueError("nclusters must be >= 1")
    lev = 0
    for cand in range(tree.depth + 1):
        lo, hi = int(tree.level_offset[cand]), int(tree.level_offset[cand + 1])
        if int(np.count_nonzero(tree.node_count[lo:hi])) >= nclusters:
            lev = cand
            break
        lev = cand
    idx = tree.node_of_source[lev]
    order = np.argsort(idx, kind="stable")
    groups = [
        order[s] for s in _split_runs(idx[order])
    ]
    groups.sort(key=len, reverse=True)
    while len(groups) > nclusters:
        # merge the smallest group into the smallest survivor
        small = groups.pop()
        tgt = min(range(nclusters), key=lambda i: len(groups[i]))
        groups[tgt] = np.concatenate([groups[tgt], small])
    return [np.sort(g) for g in groups]


def _split_runs(sorted_vals: np.ndarray) -> list:
    """Slices of equal-value runs in an already-sorted array."""
    if sorted_vals.size == 0:
        return []
    bounds = np.nonzero(np.diff(sorted_vals))[0] + 1
    edges = np.concatenate([[0], bounds, [sorted_vals.size]])
    return [slice(int(edges[i]), int(edges[i + 1]))
            for i in range(len(edges) - 1)]
