from sagecal_tpu.solvers import lbfgs, lm, robust  # noqa: F401
