from sagecal_tpu.solvers import lbfgs, lbfgsb, lm, robust  # noqa: F401
from sagecal_tpu.solvers.lbfgsb import LBFGSBResult, lbfgsb_fit  # noqa: F401
from sagecal_tpu.solvers.sharded import pad_rows_to, sharded_joint_fit  # noqa: F401,E501
