"""Batched (vmapped) solver entry points for the multi-tenant serve
path (:mod:`sagecal_tpu.serve`).

Production traffic is thousands of independent (field, epoch, sub-band)
calibration requests; dispatching them one ``solve_tile`` at a time
leaves the chip idle between programs and pays the dispatch floor per
request.  These entries ``jax.vmap`` a whole *batch* of same-shape
solves — gains carry, LBFGS curvature memory, RNG keys all grow a
leading batch axis — into ONE device program over the existing packed
entries (``solvers/sage.sagefit_packed``, ``solvers/batchmode``), so
solves/sec scales with the batch instead of with dispatch count.

Layout contract (the serve bucketer produces exactly this):

- every array leaf of ``data``/``cdata`` and every packed re/im array
  carries a leading batch axis ``B``;
- static metadata (tilesz, nbase, nstations, freq0, ...) is SHARED
  across the batch — that is what a serve *bucket* means;
- ``p0`` is ``(B, M, nchunk_max, 8N)`` and is DONATED: the serve layer
  rebuilds it from host numpy per submission and threads the RESULT
  gains forward, never the input buffer (jaxlint JL007 convention,
  same as the single-solve entry);
- padded lanes of a ragged last bucket REPLICATE real entries
  round-robin (finite math, no degenerate all-masked solves); their
  results are discarded on the host.

vmap of the solver's ``lax.while_loop``s masks per-lane updates once a
lane's own termination test fires, so a batched solve is bit-close
(<= 1e-5, tests/test_serve.py) to the K sequential solves — not
bit-identical, because batched reductions may re-associate.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

import numpy as np

from sagecal_tpu.core.types import VisData
from sagecal_tpu.obs.perf import instrumented_jit
from sagecal_tpu.solvers.batchmode import bfgsfit_minibatch
from sagecal_tpu.solvers.lbfgs import LBFGSMemory
from sagecal_tpu.solvers.sage import (
    ClusterData,
    SageConfig,
    SageResult,
    sagefit_batched_fused,
    sagefit_packed,
)

# VMEM ceiling of the batched fused BACKWARD kernel: its in-register
# accumulators are sixteen (B*Mp, tile) f32 planes, so B*Mp is bounded
# exactly like the solo kernel's padded cluster count at tile 128 (the
# hardware-proven FULL_CLUSTER_TILE configuration — ops/rime_kernel.py
# batched section comment).  LAST-RESORT fallback only: the live bound
# comes from the banked VMEM table (KERNEL_VMEM_TABLE.json, regenerated
# by tools/kernel_vmem_table.py from the symbolic footprint model) via
# :func:`batch_rows_bound` — the model admits MORE rows for bf16
# coherencies (the bf16 operand block halves) where this constant is
# the conservative f32 value.
_BATCH_ROWS_MAX = 104

# (path, mtime) -> parsed table; the serve path calls
# choose_batched_path per bucket, so the table read must not be a
# per-call disk hit
_TABLE_CACHE: dict = {}


def _vmem_table_path() -> str:
    override = os.environ.get("SAGECAL_KERNEL_VMEM_TABLE")
    if override:
        return override
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "KERNEL_VMEM_TABLE.json")


def _load_vmem_table():
    path = _vmem_table_path()
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    key = (path, mtime)
    if _TABLE_CACHE.get("key") == key:
        return _TABLE_CACHE["table"]
    try:
        with open(path, "r") as fh:
            table = json.load(fh)
    except (OSError, ValueError):
        return None
    _TABLE_CACHE["key"] = key
    _TABLE_CACHE["table"] = table
    return table


def batch_rows_bound(coh_dtype: str = "f32",
                     tile: Optional[int] = None) -> int:
    """Row bound (B*Mp) of the batched fused backward kernel.

    Resolution order: ``$SAGECAL_KERNEL_VMEM_TABLE`` / the banked
    repo-root ``KERNEL_VMEM_TABLE.json`` (written by
    ``tools/kernel_vmem_table.py``), then a live
    :mod:`sagecal_tpu.analysis.kernelmodel` computation, then the
    hardware-proven f32 constant.  ``coh_dtype="bf16"`` legitimately
    admits more rows than f32 — the coherency VMEM block halves."""
    table = _load_vmem_table()
    if table is not None:
        try:
            t = tile if tile is not None else int(
                table["constants"]["FULL_CLUSTER_TILE"])
            return int(table["batch_rows_max"][coh_dtype][str(t)])
        except (KeyError, TypeError, ValueError):
            pass
    try:
        from sagecal_tpu.analysis.kernelmodel import load_model
        from sagecal_tpu.ops.rime_kernel import FULL_CLUSTER_TILE
        model = load_model()
        return int(model.batch_rows_max(
            tile if tile is not None else FULL_CLUSTER_TILE, coh_dtype))
    except Exception:
        return _BATCH_ROWS_MAX


def _batch_axes(tree):
    """An ``in_axes`` pytree mapping every array leaf of ``tree`` to
    axis 0 (None leaves — the stripped complex slots — stay None)."""
    return jax.tree_util.tree_map(lambda _: 0, tree)


def derive_lane_keys(seed: int, lane_ids) -> jax.Array:
    """Stable per-lane PRNG keys from lane IDENTITIES, not submission
    order: ``key_i = fold_in(PRNGKey(seed), lane_ids[i])``.

    Hoisted out of the dispatch loop (the serve layer used to re-split a
    fresh key per submission) so a request's randomized solver stream —
    OS-LM subset draws, robust nu estimation order — is a function of
    the request itself and reproduces identically whichever scheduler,
    worker or batch slot executes it."""
    base = jax.random.PRNGKey(seed)
    ids = jnp.asarray(np.asarray(lane_ids), jnp.uint32)
    return jax.vmap(lambda i: jax.random.fold_in(base, i))(ids)


def choose_batched_path(data, cdata, p0, config: SageConfig):
    """Host-side capability check routing a batch to the best kernel
    path — the batched analog of the solo chunked-fallback machinery.

    Returns ``(path, reason)`` with path one of:

    - ``"fused_batch"`` — one Pallas grid for the whole batch
      (:func:`sagecal_tpu.solvers.sage.sagefit_batched_fused`);
    - ``"fused"`` — vmapped solo fused kernels (capability shortfall is
      batch-specific: hybrid chunks, unshared baselines, VMEM bound);
    - ``"xla"`` — vmapped XLA predict (fused path disabled or unusable).

    All checks are CONCRETE (host numpy) — call before jit dispatch.
    ``data``/``cdata`` leaves carry the leading batch axis; ``p0`` is
    (B, M, nchunk_max, 8N)."""
    from sagecal_tpu.ops.rime_kernel import NPAD, pad_to

    if not config.use_fused_predict:
        return "xla", "fused predict disabled in config"
    B, M, nchunk_max, n8 = p0.shape
    if np.asarray(p0).dtype != np.float32:
        return "xla", "fused kernels require float32 parameters/data"
    if n8 // 8 > NPAD:
        return "xla", f"N={n8 // 8} exceeds the kernel's NPAD={NPAD}"
    if config.param_bound > 0.0:
        return "xla", "param_bound uses the (XLA-only) bounded LBFGS"
    if config.collect_telemetry:
        return "xla", "telemetry traces are XLA-path only"
    if nchunk_max > 1:
        return "fused", "hybrid time chunks: batched kernel is nc==1 only"
    ant_p = np.asarray(data.ant_p)
    ant_q = np.asarray(data.ant_q)
    if not (np.all(ant_p == ant_p[:1]) and np.all(ant_q == ant_q[:1])):
        return "fused", "lanes do not share baseline geometry"
    rows_max = batch_rows_bound(coh_dtype=config.coh_dtype)
    if B * pad_to(M, 8) > rows_max:
        return "fused", (
            f"B*Mp={B * pad_to(M, 8)} exceeds the backward kernel's "
            f"VMEM accumulator bound ({rows_max}, "
            f"coh_dtype={config.coh_dtype})")
    return "fused_batch", "all batched-kernel capability checks passed"


def sagefit_packed_batch(
    data: VisData,
    cdata: ClusterData,
    vis_re: jax.Array,
    vis_im: jax.Array,
    coh_re: jax.Array,
    coh_im: jax.Array,
    p0: jax.Array,
    config: SageConfig = SageConfig(),
    keys: Optional[jax.Array] = None,
    valid: Optional[jax.Array] = None,
    batched_fused: bool = False,
) -> SageResult:
    """``B`` independent tile solves as one vmapped device program.

    Same REAL-boundary contract as :func:`sagefit_packed`, with a
    leading batch axis on every array: ``vis_*`` is ``(B, F, 4, rows)``,
    ``coh_*`` is ``(B, M, F, 4, rows)``, ``p0`` is
    ``(B, M, nchunk_max, 8N)`` and ``keys`` is ``(B, 2)`` (one PRNG key
    per lane, so randomized OS subsets stay independent per request;
    derive them from request identity with :func:`derive_lane_keys`).
    Returns a :class:`SageResult` whose leaves all carry the batch axis.

    ``batched_fused`` (STATIC; set it from :func:`choose_batched_path`)
    routes the joint-LBFGS phase through the batched fused Pallas kernel
    (:func:`sagecal_tpu.solvers.sage.sagefit_batched_fused`) instead of
    vmapping B solo solves; ``valid`` (B,) then pins replication-padded
    lanes to exactly zero cost/cotangent in that phase.  On the vmapped
    paths ``valid`` is ignored — padded lanes run replicated finite
    solves whose results the host discards, as before.
    """
    if keys is None:
        keys = jax.random.split(jax.random.PRNGKey(0), vis_re.shape[0])
    if batched_fused:
        vis = jax.lax.complex(vis_re, vis_im)
        coh = jax.lax.complex(coh_re, coh_im)
        return sagefit_batched_fused(
            data.replace(vis=vis), cdata._replace(coh=coh), p0, config,
            keys, valid,
        )

    def one(d, cd, vr, vi, cr, ci, p, k):
        return sagefit_packed(d, cd, vr, vi, cr, ci, p, config, k)

    return jax.vmap(
        one,
        in_axes=(_batch_axes(data), _batch_axes(cdata), 0, 0, 0, 0, 0, 0),
    )(data, cdata, vis_re, vis_im, coh_re, coh_im, p0, keys)


# the serve executable cache wraps per-bucket jits itself (serve/cache.py
# keys them by abstract signature + config fingerprint); this module-level
# entry is the library surface for direct use and for the bench, named so
# its compiles are attributable in `diag perf`.  The batch gains carry is
# donated, exactly like the single-solve entry's p0.
sagefit_packed_batch_jit = instrumented_jit(
    sagefit_packed_batch, name="sagefit_packed_batch",
    static_argnames=("batched_fused",),
    donate_argnames=("p0",))


def lbfgs_minibatch_batch(
    data: VisData,
    cdata: ClusterData,
    p0: jax.Array,
    memory: Optional[LBFGSMemory] = None,
    itmax: int = 10,
    lbfgs_m: int = 7,
    robust_nu: Optional[float] = None,
) -> Tuple[jax.Array, LBFGSMemory]:
    """``B`` independent minibatch joint-LBFGS steps as one program.

    vmap of :func:`sagecal_tpu.solvers.batchmode.bfgsfit_minibatch`:
    ``p0`` is ``(B, M, nchunk_max, 8N)`` and ``memory`` (when resuming a
    stream) is an :class:`LBFGSMemory` whose every leaf carries the
    batch axis — each tenant's curvature pairs persist independently
    across its minibatches.  Returns ``(p_new, memory)`` with batched
    leaves; thread both into the next call (donated — rebuild from the
    results, not the inputs).
    """
    B = p0.shape[0]
    if memory is None:
        n = int(p0.shape[1] * p0.shape[2] * p0.shape[3])
        one_mem = LBFGSMemory.init(n, lbfgs_m, p0.dtype)
        memory = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x, (B,) + x.shape), one_mem)

    def one(d, cd, p, mem):
        return bfgsfit_minibatch(d, cd, p, memory=mem, itmax=itmax,
                                 lbfgs_m=lbfgs_m, robust_nu=robust_nu)

    return jax.vmap(
        one,
        in_axes=(_batch_axes(data), _batch_axes(cdata), 0,
                 _batch_axes(memory)),
    )(data, cdata, p0, memory)


lbfgs_minibatch_batch_jit = instrumented_jit(
    lbfgs_minibatch_batch, name="lbfgs_minibatch_batch",
    static_argnames=("itmax", "lbfgs_m", "robust_nu"),
    donate_argnames=("p0", "memory"))
