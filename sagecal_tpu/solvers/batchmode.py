"""Minibatch (stochastic) joint LBFGS fits over visibility data.

Redesign of ``robust_batchmode_lbfgs.c``: ``bfgsfit_minibatch_
visibilities`` (:1446) and ``bfgsfit_minibatch_consensus`` (:1504,
contract Dirac.h:325-340).  All clusters' parameters are solved jointly
by LBFGS on one minibatch of (multi-channel) data; curvature pairs and
gradient-variance statistics persist ACROSS minibatches through the
:class:`sagecal_tpu.solvers.lbfgs.LBFGSMemory` pytree (the reference's
``persistent_data_t``).  The consensus variant adds the scaled-
Lagrangian terms y^T (p - BZ) + rho/2 ||p - BZ||^2 per cluster — the
in-process band-ADMM and the MPI stochastic modes both build on it.

Gradients come from autodiff of the one jitted cost (the reference
hand-writes threaded gradients, robust_lbfgs.c:155+).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from sagecal_tpu.core.types import VisData
from sagecal_tpu.solvers.lbfgs import LBFGSMemory, lbfgs_fit
from sagecal_tpu.solvers.sage import ClusterData, predict_full_model
from sagecal_tpu.utils.precision import true_f32


def _data_cost(pflat, data: VisData, cdata: ClusterData, shape, robust_nu):
    M, nchunk, n8 = shape
    pa = pflat.reshape(M, nchunk, n8)
    model = predict_full_model(pa, cdata, data)
    diff = (data.vis - model) * data.mask[..., None, :]
    e2 = jnp.real(diff) ** 2 + jnp.imag(diff) ** 2
    if robust_nu is not None:
        return jnp.sum(jnp.log1p(e2 / robust_nu))
    return jnp.sum(e2)


@true_f32
def bfgsfit_minibatch(
    data: VisData,
    cdata: ClusterData,
    p0: jax.Array,
    memory: Optional[LBFGSMemory] = None,
    itmax: int = 10,
    lbfgs_m: int = 7,
    robust_nu: Optional[float] = None,
) -> Tuple[jax.Array, LBFGSMemory]:
    """One minibatch joint LBFGS step
    (``bfgsfit_minibatch_visibilities``, robust_batchmode_lbfgs.c:1446).

    p0: (M, nchunk_max, 8N).  Returns (p_new, memory) — thread the
    memory into the next minibatch call.
    """
    shape = p0.shape
    pflat = p0.reshape(-1)
    if memory is None:
        memory = LBFGSMemory.init(pflat.shape[0], lbfgs_m, pflat.dtype)

    def cost(pf):
        return _data_cost(pf, data, cdata, shape, robust_nu)

    fit = lbfgs_fit(
        cost, None, pflat, itmax=itmax, M=lbfgs_m, memory=memory, minibatch=True
    )
    return fit.p.reshape(shape), fit.memory


@true_f32
def bfgsfit_minibatch_consensus(
    data: VisData,
    cdata: ClusterData,
    p0: jax.Array,
    Y: jax.Array,
    BZ: jax.Array,
    rho: jax.Array,
    memory: Optional[LBFGSMemory] = None,
    itmax: int = 10,
    lbfgs_m: int = 7,
    robust_nu: Optional[float] = None,
) -> Tuple[jax.Array, LBFGSMemory]:
    """Consensus variant (``bfgsfit_minibatch_consensus``,
    robust_batchmode_lbfgs.c:1504): adds y^T (p - BZ) + rho/2 ||p-BZ||^2
    to the minibatch cost.  Y/BZ: (M, nchunk_max, 8N); rho: (M,).
    """
    shape = p0.shape
    pflat = p0.reshape(-1)
    if memory is None:
        memory = LBFGSMemory.init(pflat.shape[0], lbfgs_m, pflat.dtype)

    def cost(pf):
        pa = pf.reshape(shape)
        d = pa - BZ
        aug = jnp.sum(Y * d) + 0.5 * jnp.sum(
            rho[:, None, None] * d * d
        )
        return _data_cost(pf, data, cdata, shape, robust_nu) + aug

    fit = lbfgs_fit(
        cost, None, pflat, itmax=itmax, M=lbfgs_m, memory=memory, minibatch=True
    )
    return fit.p.reshape(shape), fit.memory
