"""LBFGS with persistent minibatch memory — jit-compiled, lax control flow.

Re-design of the reference's CPU LBFGS (``/root/reference/src/lib/Dirac/
lbfgs.c``): the generic cost/grad callback contract of ``lbfgs_fit``
(Dirac.h:158-178) becomes "any jax-traceable ``cost_fn(p)->scalar`` /
``grad_fn(p)->(n,)``"; the pthread-parallel gradient evaluation becomes
whatever XLA parallelism lives inside those callables; the hand-rolled
circular y/s store (``persistent_data_t``, Dirac.h:84-110) becomes the
:class:`LBFGSMemory` pytree, carried across minibatches by the caller
(the functional analog of ``lbfgs_persist_init/reset/clear``).

Faithfully reproduced behaviors:
- two-loop recursion over an M-slot circular store, newest-first ordering
  (``mult_hessian``, lbfgs.c:33-113);
- Armijo backtracking with c=1e-4, halving, max 15 halvings
  (``linesearch_backtrack``, lbfgs.c:444-475);
- minibatch mode (lbfgs.c:717-953): skip storing the (s,y) pair on the
  first iteration after a batch switch; trust-region regularization
  ``y += 1e-6 s`` when ||g|| > 1e-3; online gradient-variance step-size
  control ``alphabar = 10/(1 + sum|avg_sq| / ((niter-1)*||g||))``
  (lbfgs.c:796-824) with Welford-style running average across batches.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from sagecal_tpu.utils.precision import true_f32
from flax import struct

CLM_STOP_THRESH = 1e-9
CLM_EPSILON = 1e-12


@struct.dataclass
class LBFGSMemory:
    """Persistent LBFGS state (pytree version of ``persistent_data_t``)."""

    s: jax.Array  # (M, n) parameter differences
    y: jax.Array  # (M, n) gradient differences
    rho: jax.Array  # (M,) 1/(y.s)
    vacant: jax.Array  # int32 next slot to fill
    nfilled: jax.Array  # int32 number of valid pairs
    niter: jax.Array  # int32 global iteration count (across batches)
    running_avg: jax.Array  # (n,) online mean of batch gradients
    running_avg_sq: jax.Array  # (n,) online sum of squared deviations

    @staticmethod
    def init(n: int, M: int = 7, dtype=jnp.float32) -> "LBFGSMemory":
        return LBFGSMemory(
            s=jnp.zeros((M, n), dtype),
            y=jnp.zeros((M, n), dtype),
            rho=jnp.zeros((M,), dtype),
            vacant=jnp.zeros((), jnp.int32),
            nfilled=jnp.zeros((), jnp.int32),
            niter=jnp.zeros((), jnp.int32),
            running_avg=jnp.zeros((n,), dtype),
            running_avg_sq=jnp.zeros((n,), dtype),
        )

    def reset(self) -> "LBFGSMemory":
        """``lbfgs_persist_reset`` equivalent (Dirac.h:133-136)."""
        return LBFGSMemory.init(self.s.shape[1], self.s.shape[0], self.s.dtype)


def _two_loop_direction(g: jax.Array, mem: LBFGSMemory) -> jax.Array:
    """-H_k g via the two-loop recursion with masked circular slots."""
    M = mem.s.shape[0]
    k = jnp.arange(M)
    # slot index of the (k+1)-th most recent pair
    newest_first = jnp.mod(mem.vacant - 1 - k, M)
    valid = k < mem.nfilled  # (M,) newest-first validity
    s = mem.s[newest_first]  # (M, n) newest first
    y = mem.y[newest_first]
    rho = mem.rho[newest_first]

    def loop1(carry, inp):
        q = carry
        s_i, y_i, rho_i, ok = inp
        alpha_i = jnp.where(ok, rho_i * jnp.dot(s_i, q), 0.0)
        q = q - alpha_i * y_i
        return q, alpha_i

    q, alphas = jax.lax.scan(loop1, g, (s, y, rho, valid))
    # initial Hessian scaling gamma = s.y / y.y of the newest pair
    y0 = y[0]
    s0 = s[0]
    yy = jnp.dot(y0, y0)
    gamma = jnp.where(
        (mem.nfilled > 0) & (yy > 0.0), jnp.dot(s0, y0) / jnp.maximum(yy, 1e-30), 1.0
    )
    r = gamma * q

    def loop2(carry, inp):
        r = carry
        s_i, y_i, rho_i, alpha_i, ok = inp
        beta = jnp.where(ok, rho_i * jnp.dot(y_i, r), 0.0)
        r = r + s_i * jnp.where(ok, alpha_i - beta, 0.0)
        return r, None

    # oldest -> newest: reverse the newest-first arrays
    r, _ = jax.lax.scan(
        loop2, r, (s[::-1], y[::-1], rho[::-1], alphas[::-1], valid[::-1])
    )
    return -r


ARMIJO_C = 1e-4  # sufficient-decrease constant (lbfgs.c:444-475)


def _armijo_bad(f_new, fold, alpha, product):
    """The (shared) sufficient-decrease rejection test.  ``product`` =
    ARMIJO_C * p.g, computed ONCE per iteration so the fused first-trial
    accept and the halving loop apply bit-identical arithmetic."""
    return jnp.isnan(f_new) | (f_new > fold + alpha * product)


def _armijo_rest(cost_fn, x, p, a0, fold, f_a0, product):
    """Armijo halving loop (lbfgs.c:444-475: at most 15 halvings) with
    the first trial's cost ``f_a0`` already in hand.  Returns
    ``(alpha, halvings)`` — the halving count feeds the telemetry
    line-search evaluation counter."""

    def cond(st):
        ci, alpha, fnew = st
        return (ci < 15) & _armijo_bad(fnew, fold, alpha, product)

    def body(st):
        ci, alpha, _ = st
        alpha = alpha * 0.5
        return ci + 1, alpha, cost_fn(x + alpha * p)

    ci, alpha, _ = jax.lax.while_loop(cond, body, (0, a0, f_a0))
    return alpha, ci


class LBFGSResult(NamedTuple):
    p: jax.Array
    memory: LBFGSMemory
    cost: jax.Array
    gradnorm: jax.Array
    iterations: jax.Array
    # per-iteration IterTrace (obs.records) when collect_trace=True, else
    # None — an empty pytree, so the jitted output signature is unchanged
    trace: Optional[tuple] = None


@true_f32
def lbfgs_fit(
    cost_fn: Callable,
    grad_fn: Optional[Callable],
    p0: jax.Array,
    itmax: int = 50,
    M: int = 7,
    memory: Optional[LBFGSMemory] = None,
    minibatch: bool = False,
    collect_trace: bool = False,
    vg_fn: Optional[Callable] = None,
) -> LBFGSResult:
    """Generic LBFGS fit (``lbfgs_fit``, Dirac.h:175 / lbfgs.c:479,717).

    ``minibatch=True`` reproduces ``lbfgs_fit_minibatch``: pass the
    ``memory`` returned from the previous batch's call; curvature pairs,
    iteration counts, and gradient-variance statistics persist.  With
    ``minibatch=False`` and no memory this is the full-batch fit (fresh
    memory, alphabar=1).

    ``vg_fn(p) -> (cost, grad)`` overrides the default fused
    value-and-grad.  Callers whose gradient CANNOT be obtained by
    differentiating ``cost_fn`` must pass it: under ``shard_map`` a
    ``psum``'d cost transposes to a device-local cotangent, so
    ``value_and_grad(cost_fn)`` yields each device only its shard's
    gradient — the correct global gradient is
    ``psum(value_and_grad(local_cost)(p))`` (solvers/sharded.py).
    """
    n = p0.shape[0]
    # fused value+gradient: the reverse pass shares its forward with the
    # cost, so (f, g) together cost ~one gradient — carrying f through
    # the loop then saves the cost_fn(x) re-evaluation Armijo would
    # otherwise make every iteration (one full pass over the coherency
    # stack on the calibration cost)
    if vg_fn is None:
        if grad_fn is None:
            vg_fn = jax.value_and_grad(cost_fn)
        else:
            def vg_fn(x):
                return cost_fn(x), grad_fn(x)
    fresh = memory is None
    if fresh:
        memory = LBFGSMemory.init(n, M, p0.dtype)

    f0, g0 = vg_fn(p0)
    gradnrm0 = jnp.linalg.norm(g0)

    # minibatch batch-switch bookkeeping (lbfgs.c:794-826): runs once per
    # call, before the iteration loop, iff a previous batch ran.
    if minibatch:
        batch_changed = memory.niter > 0
        niter1 = memory.niter + 1

        def upd(mem):
            g_min_rold = g0 - mem.running_avg
            ravg = mem.running_avg + g_min_rold / niter1.astype(p0.dtype)
            g_min_rnew = g0 - ravg
            ravg_sq = mem.running_avg_sq + g_min_rold * g_min_rnew
            return mem.replace(running_avg=ravg, running_avg_sq=ravg_sq)

        memory = jax.tree_util.tree_map(
            lambda a, b: jnp.where(batch_changed, a, b), upd(memory), memory
        )
        alphabar = jnp.where(
            batch_changed,
            10.0
            / (
                1.0
                + jnp.sum(jnp.abs(memory.running_avg_sq))
                / (jnp.maximum(memory.niter, 1).astype(p0.dtype) * jnp.maximum(gradnrm0, 1e-30))
            ),
            1.0,
        )
    else:
        batch_changed = jnp.asarray(False)
        alphabar = jnp.asarray(1.0, p0.dtype)

    from sagecal_tpu.obs.records import init_trace, write_trace

    trace0 = init_trace(itmax, (), p0.dtype) if collect_trace else None

    def cond(state):
        ck, x, f, g, gradnrm, mem, done, trace = state
        return (ck < itmax) & (~done)

    def body(state):
        ck, x, f, g, gradnrm, mem, done, trace = state
        pk = _two_loop_direction(g, mem)
        # Evaluate value_and_grad AT the first Armijo trial point: when
        # the full step passes the sufficient-decrease test (the common
        # case once the inverse-Hessian scale is warm), the iteration
        # costs ONE fused (f, g) pass — ~2 cost-equivalents — instead
        # of trial + separate value_and_grad (~3).  The accepted step
        # matches the plain backtracking search in every case (shared
        # _armijo_bad predicate, same product); only the evaluation
        # count changes.  On reject, fall back to the cost-only
        # halving loop and take (f, g) at the accepted alpha.
        a0 = jnp.asarray(alphabar, x.dtype)
        x_t = x + a0 * pk
        f_t, g_t = vg_fn(x_t)
        product = ARMIJO_C * jnp.dot(pk, g)
        first_ok = ~_armijo_bad(f_t, f, a0, product)

        def accept_first(_):
            return a0, f_t, g_t, jnp.ones((), x.dtype)

        def backtrack(_):
            alpha, halvings = _armijo_rest(cost_fn, x, pk, a0, f, f_t, product)
            fb, gb = vg_fn(x + alpha * pk)
            # first trial + each halving + the fused re-eval at alpha
            return alpha, fb, gb, 2.0 + halvings.astype(x.dtype)

        alphak, f1, g1, ls_evals = jax.lax.cond(first_ok, accept_first,
                                                backtrack, None)
        step_ok = jnp.isfinite(alphak) & (jnp.abs(alphak) >= CLM_EPSILON)
        x1 = x + alphak * pk
        gradnrm1 = jnp.linalg.norm(g1)
        grad_ok = jnp.isfinite(gradnrm1) & (gradnrm1 > CLM_STOP_THRESH)

        # store the curvature pair unless this is the first iteration of a
        # changed batch (lbfgs.c:849-880)
        store = step_ok & ~(batch_changed & (ck == 0))
        sk = x1 - x
        yk = g1 - g
        yk = yk + jnp.where(gradnrm1 > 1e-3, 1e-6, 0.0) * sk  # lbfgs.c:871-874
        # Positive-curvature guard (f32 robustness): near a converged
        # point y.s can underflow to 0 (or go negative on a noisy
        # Armijo step); storing rho = 1/(y.s) = inf then poisons every
        # later two-loop direction with inf*0 = NaN.  Require
        # y.s > eps*|y||s| (relative, scale-free) before storing, with
        # eps the machine epsilon OF THE RUNNING DTYPE — so f64 runs
        # keep reference-equivalent behavior (lbfgs.c stores every
        # pair; f64 eps only rejects pairs that are non-positive to
        # machine precision) while f32 stays protected.
        ys = jnp.dot(yk, sk)
        curv_eps = jnp.finfo(yk.dtype).eps
        curv_ok = ys > curv_eps * jnp.linalg.norm(yk) * jnp.linalg.norm(sk)
        store = store & curv_ok  # NaN/inf ys already fail curv_ok
        rho_k = jnp.where(curv_ok, 1.0 / jnp.maximum(ys, 1e-38), 0.0)
        slot = mem.vacant

        def do_store(mem):
            return mem.replace(
                s=mem.s.at[slot].set(sk),
                y=mem.y.at[slot].set(yk),
                rho=mem.rho.at[slot].set(rho_k),
                vacant=jnp.mod(slot + 1, mem.s.shape[0]),
                nfilled=jnp.minimum(mem.nfilled + 1, mem.s.shape[0]),
            )

        mem1 = jax.tree_util.tree_map(
            lambda a, b: jnp.where(store, a, b), do_store(mem), mem
        )
        # niter counts every iteration across batches (lbfgs.c:793)
        mem1 = mem1.replace(niter=mem.niter + 1)
        # only advance when the step was usable
        x_next = jnp.where(step_ok, x1, x)
        f_next = jnp.where(step_ok, f1, f)
        g_next = jnp.where(step_ok, g1, g)
        gradnrm_next = jnp.where(step_ok, gradnrm1, gradnrm)
        done_next = (~step_ok) | (~grad_ok)
        if trace is not None:
            trace = write_trace(
                trace, ck,
                cost=f_next,
                grad_norm=gradnrm_next,
                step=alphak,
                ls_evals=ls_evals,
            )
        return (ck + 1, x_next, f_next, g_next, gradnrm_next, mem1,
                done_next, trace)

    from sagecal_tpu.utils.platform import match_vma

    start_done = ~(jnp.isfinite(gradnrm0) & (gradnrm0 > CLM_STOP_THRESH))
    ck, x, f, g, gradnrm, mem, _, trace = jax.lax.while_loop(
        cond, body,
        match_vma((jnp.asarray(0), p0, f0, g0, gradnrm0, memory,
                   start_done, trace0), p0),
    )
    return LBFGSResult(p=x, memory=mem, cost=f, gradnorm=gradnrm,
                       iterations=ck, trace=trace)


def _bdot(a, b):
    """Per-lane dot: (B, n) x (B, n) -> (B,)."""
    return jnp.einsum("bn,bn->b", a, b)


def _bnorm(a):
    return jnp.sqrt(_bdot(a, a))


def _bexpand(mask, leaf):
    """(B,) predicate broadcast against a (B, ...) carry leaf."""
    return mask.reshape((mask.shape[0],) + (1,) * (leaf.ndim - 1))


def batched_memory(B: int, n: int, M: int = 7,
                   dtype=jnp.float32) -> LBFGSMemory:
    """Fresh :class:`LBFGSMemory` with every leaf carrying a leading
    batch axis ``B`` — the per-lane curvature store of
    :func:`lbfgs_fit_batched`."""
    one = LBFGSMemory.init(n, M, dtype)
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (B,) + x.shape), one)


def _two_loop_direction_batched(g: jax.Array, mem: LBFGSMemory) -> jax.Array:
    """Per-lane -H_k g: the two-loop recursion of
    :func:`_two_loop_direction` with a leading batch axis on g (B, n)
    and on every memory leaf.  Per-lane circular indexing is a
    take_along_axis gather; the scan runs over the M slot axis with all
    lanes in lock-step (exactly what vmap of the solo recursion
    builds)."""
    Bsz, Mslots, _ = mem.s.shape
    k = jnp.arange(Mslots)
    newest_first = jnp.mod(mem.vacant[:, None] - 1 - k[None, :], Mslots)
    valid = k[None, :] < mem.nfilled[:, None]  # (B, M) newest-first
    s = jnp.take_along_axis(mem.s, newest_first[:, :, None], axis=1)
    y = jnp.take_along_axis(mem.y, newest_first[:, :, None], axis=1)
    rho = jnp.take_along_axis(mem.rho, newest_first, axis=1)

    def loop1(q, inp):
        s_i, y_i, rho_i, ok = inp  # (B, n), (B, n), (B,), (B,)
        alpha_i = jnp.where(ok, rho_i * _bdot(s_i, q), 0.0)
        return q - alpha_i[:, None] * y_i, alpha_i

    q, alphas = jax.lax.scan(
        loop1, g, (s.swapaxes(0, 1), y.swapaxes(0, 1), rho.T, valid.T))
    y0, s0 = y[:, 0], s[:, 0]
    yy = _bdot(y0, y0)
    gamma = jnp.where(
        (mem.nfilled > 0) & (yy > 0.0),
        _bdot(s0, y0) / jnp.maximum(yy, 1e-30), 1.0)
    r = gamma[:, None] * q

    def loop2(r, inp):
        s_i, y_i, rho_i, alpha_i, ok = inp
        beta = jnp.where(ok, rho_i * _bdot(y_i, r), 0.0)
        return r + s_i * jnp.where(ok, alpha_i - beta, 0.0)[:, None], None

    r, _ = jax.lax.scan(
        loop2, r,
        (s[:, ::-1].swapaxes(0, 1), y[:, ::-1].swapaxes(0, 1),
         rho[:, ::-1].T, alphas[::-1], valid[:, ::-1].T))
    return -r


def _armijo_rest_batched(cost_fn, x, p, a0, fold, f_a0, product, live):
    """Per-lane Armijo halving (vmap semantics of :func:`_armijo_rest`):
    each lane halves while ITS OWN test fails, frozen once it passes;
    the loop runs until no live lane is still failing.  ``live`` masks
    out lanes that already accepted the first trial (or finished the
    outer loop) so a pathological frozen lane cannot spin the batch."""

    def bad(ci, alpha, fnew):
        return live & (ci < 15) & _armijo_bad(fnew, fold, alpha, product)

    def cond(st):
        ci, alpha, fnew = st
        return jnp.any(bad(ci, alpha, fnew))

    def body(st):
        ci, alpha, fnew = st
        b = bad(ci, alpha, fnew)
        alpha1 = jnp.where(b, alpha * 0.5, alpha)
        f1 = cost_fn(x + alpha1[:, None] * p)
        return (jnp.where(b, ci + 1, ci), alpha1,
                jnp.where(b, f1, fnew))

    ci, alpha, _ = jax.lax.while_loop(
        cond, body, (jnp.zeros(a0.shape, jnp.int32), a0, f_a0))
    return alpha, ci


@true_f32
def lbfgs_fit_batched(
    cost_fn: Callable,
    p0: jax.Array,
    itmax: int = 50,
    M: int = 7,
    memory: Optional[LBFGSMemory] = None,
    minibatch: bool = False,
    vg_fn: Optional[Callable] = None,
) -> LBFGSResult:
    """``B`` independent LBFGS fits advancing in lock-step so EVERY cost
    and gradient evaluation is ONE batched call — the driver for the
    batched fused objective kernel (``ops.rime_kernel.
    fused_cost_packed_batch``), where a vmap of :func:`lbfgs_fit` would
    fall back to B solo kernel dispatches.

    ``cost_fn``: (B, n) -> (B,) per-lane costs; lanes MUST be
    independent (lane b's cost depends only on row b — that is what
    makes the default pullback-of-ones gradient per-lane exact).
    ``p0``: (B, n).  ``memory``: per-lane :class:`LBFGSMemory`
    (leading B on every leaf, see :func:`batched_memory`).

    Per-lane semantics match ``jax.vmap(lbfgs_fit)`` (same predicates,
    same masked-carry advancement — a lane whose own termination fires
    freezes while the others run), but not bit-identically: batched
    reductions re-associate, like the rest of the serve batch path.
    Telemetry traces are not collected on the batched path."""
    B, n = p0.shape
    if vg_fn is None:
        def vg_fn(x):
            costs, pull = jax.vjp(cost_fn, x)
            (g,) = pull(jnp.ones_like(costs))
            return costs, g
    if memory is None:
        memory = batched_memory(B, n, M, p0.dtype)

    f0, g0 = vg_fn(p0)
    gradnrm0 = _bnorm(g0)

    if minibatch:
        batch_changed = memory.niter > 0  # (B,)
        niter1 = memory.niter + 1

        def upd(mem):
            g_min_rold = g0 - mem.running_avg
            ravg = (mem.running_avg
                    + g_min_rold / niter1.astype(p0.dtype)[:, None])
            g_min_rnew = g0 - ravg
            ravg_sq = mem.running_avg_sq + g_min_rold * g_min_rnew
            return mem.replace(running_avg=ravg, running_avg_sq=ravg_sq)

        memory = jax.tree_util.tree_map(
            lambda a, b: jnp.where(_bexpand(batch_changed, a), a, b),
            upd(memory), memory)
        alphabar = jnp.where(
            batch_changed,
            10.0 / (
                1.0
                + jnp.sum(jnp.abs(memory.running_avg_sq), axis=-1)
                / (jnp.maximum(memory.niter, 1).astype(p0.dtype)
                   * jnp.maximum(gradnrm0, 1e-30))
            ),
            1.0,
        )
    else:
        batch_changed = jnp.zeros((B,), bool)
        alphabar = jnp.ones((B,), p0.dtype)

    def cond(state):
        ck, x, f, g, gradnrm, mem, done = state
        return jnp.any((ck < itmax) & (~done))

    def body(state):
        ck, x, f, g, gradnrm, mem, done = state
        active = (ck < itmax) & (~done)
        pk = _two_loop_direction_batched(g, mem)
        a0 = jnp.asarray(alphabar, x.dtype)
        x_t = x + a0[:, None] * pk
        f_t, g_t = vg_fn(x_t)
        product = ARMIJO_C * _bdot(pk, g)
        first_ok = ~_armijo_bad(f_t, f, a0, product)
        need_bt = active & ~first_ok

        def accept_all(_):
            return a0, f_t, g_t, jnp.ones((B,), x.dtype)

        def backtrack_some(_):
            alpha, halvings = _armijo_rest_batched(
                cost_fn, x, pk, a0, f, f_t, product, need_bt)
            fb, gb = vg_fn(x + alpha[:, None] * pk)
            f1 = jnp.where(need_bt, fb, f_t)
            g1 = jnp.where(need_bt[:, None], gb, g_t)
            evals = jnp.where(need_bt, 2.0 + halvings.astype(x.dtype),
                              1.0)
            return alpha, f1, g1, evals

        # one REAL branch (traced-scalar cond): the all-accept common
        # case costs exactly one fused (f, g) pass, like the solo path
        alphak, f1, g1, _ = jax.lax.cond(
            jnp.any(need_bt), backtrack_some, accept_all, None)
        step_ok = jnp.isfinite(alphak) & (jnp.abs(alphak) >= CLM_EPSILON)
        x1 = x + alphak[:, None] * pk
        gradnrm1 = _bnorm(g1)
        grad_ok = jnp.isfinite(gradnrm1) & (gradnrm1 > CLM_STOP_THRESH)

        store = step_ok & ~(batch_changed & (ck == 0))
        sk = x1 - x
        yk = g1 - g
        yk = yk + jnp.where(gradnrm1 > 1e-3, 1e-6, 0.0)[:, None] * sk
        ys = _bdot(yk, sk)
        curv_eps = jnp.finfo(yk.dtype).eps
        curv_ok = ys > curv_eps * _bnorm(yk) * _bnorm(sk)
        store = store & curv_ok
        rho_k = jnp.where(curv_ok, 1.0 / jnp.maximum(ys, 1e-38), 0.0)
        slot = mem.vacant  # (B,)
        bidx = jnp.arange(B)

        def do_store(mem):
            return mem.replace(
                s=mem.s.at[bidx, slot].set(sk),
                y=mem.y.at[bidx, slot].set(yk),
                rho=mem.rho.at[bidx, slot].set(rho_k),
                vacant=jnp.mod(slot + 1, mem.s.shape[1]),
                nfilled=jnp.minimum(mem.nfilled + 1, mem.s.shape[1]),
            )

        mem1 = jax.tree_util.tree_map(
            lambda a, b: jnp.where(_bexpand(store, a), a, b),
            do_store(mem), mem)
        mem1 = mem1.replace(niter=mem.niter + 1)
        # frozen lanes keep their whole carry (the vmap-of-while mask)
        mem_next = jax.tree_util.tree_map(
            lambda a, b: jnp.where(_bexpand(active, a), a, b), mem1, mem)
        adv = active & step_ok
        x_next = jnp.where(adv[:, None], x1, x)
        f_next = jnp.where(adv, f1, f)
        g_next = jnp.where(adv[:, None], g1, g)
        gradnrm_next = jnp.where(adv, gradnrm1, gradnrm)
        done_next = jnp.where(active, (~step_ok) | (~grad_ok), done)
        return (jnp.where(active, ck + 1, ck), x_next, f_next, g_next,
                gradnrm_next, mem_next, done_next)

    from sagecal_tpu.utils.platform import match_vma

    start_done = ~(jnp.isfinite(gradnrm0) & (gradnrm0 > CLM_STOP_THRESH))
    ck, x, f, g, gradnrm, mem, _ = jax.lax.while_loop(
        cond, body,
        match_vma((jnp.zeros((B,), jnp.int32), p0, f0, g0, gradnrm0,
                   memory, start_done), p0),
    )
    return LBFGSResult(p=x, memory=mem, cost=f, gradnorm=gradnrm,
                       iterations=ck, trace=None)


# jitted module entry with compile/recompile telemetry (obs/perf.py):
# cost_fn/grad_fn are static (hashed by identity — a new closure is a
# new signature), as are the compile-time loop bounds
from sagecal_tpu.obs.perf import instrumented_jit  # noqa: E402

# The iteration carry — start params and (for minibatch resumes) the
# LBFGS memory — is DONATED: both are consumed, never reused by any
# caller, and at production size p0 alone is ~M*8N floats per tile, so
# the donation saves one carry-size HBM copy per dispatch (jaxlint
# JL007 pins this convention).  Callers must not touch the donated
# buffers after the call; pass a fresh/host array per solve.
lbfgs_fit_jit = instrumented_jit(
    lbfgs_fit, name="lbfgs_fit",
    donate_argnames=("p0", "memory"),
    static_argnames=("cost_fn", "grad_fn", "itmax", "M", "minibatch",
                     "collect_trace", "vg_fn"))
