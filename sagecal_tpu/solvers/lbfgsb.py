"""Bound-constrained limited-memory BFGS (L-BFGS-B).

Covers the public optimizer contract of ``lbfgsb_fit``
(``/root/reference/src/lib/Dirac/lbfgsb.c``, decl Dirac.h:1843; demo
use ``test/Dirac/demo.c:90``): minimize f(x) subject to elementwise
``lb <= x <= ub`` with a limited-memory quasi-Newton model.

TPU-first structural choices (vs the reference's compact-representation
W/Y/S/M matrices, lbfgsb.c / ``persistent_data_t`` Dirac.h:107-109):

- the quasi-Newton model is the same masked circular (s, y) store and
  two-loop recursion used by :mod:`sagecal_tpu.solvers.lbfgs` — no
  dense n x 2m workspace materialization;
- the *generalized Cauchy point* is found on the projected-gradient
  path with the standard breakpoint sweep (Byrd-Lu-Nocedal-Zhu
  algorithm CP): breakpoints are sorted once (XLA sort, static shape)
  and the sweep is a ``lax.while_loop`` with the quadratic model
  q(t) along the piecewise-linear path, using the diagonal-scaled model
  B ~ theta I (the two-loop memory enters the subspace step instead);
- the *subspace minimization* over the free set runs the two-loop
  direction masked to free variables, followed by a projected
  backtracking (Armijo) line search — the gradient-projection /
  subspace-step family of the reference, in lock-step-compilable form.

Everything is jittable: fixed iteration bounds, masked convergence.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from sagecal_tpu.solvers.lbfgs import LBFGSMemory, _two_loop_direction
from sagecal_tpu.utils.precision import true_f32


class LBFGSBResult(NamedTuple):
    p: jax.Array
    cost: jax.Array
    iterations: jax.Array


def _project(x, lb, ub):
    return jnp.clip(x, lb, ub)


def _cauchy_point(x, g, lb, ub, theta):
    """Generalized Cauchy point on the projected-gradient path under the
    diagonal model q(t) = g'd(t) + 0.5*theta*||d(t)||^2.

    For a pure diagonal model the piecewise-quadratic breakpoint sweep
    of the full algorithm collapses analytically: on every segment of
    the projected path the model derivative is gg_mov*(theta*t - 1), so
    the first local minimizer is always t* = 1/theta regardless of which
    coordinates have frozen — hence xc = P(x - g/theta) exactly.  (The
    memory-corrected curvature enters through the SUBSPACE step instead,
    which is where the reference's W/M matrices act too.)

    Returns (xc, free_mask): the Cauchy point and the variables not at a
    bound there."""
    xc = _project(x - g / theta, lb, ub)
    eps = 10.0 * jnp.finfo(x.dtype).eps
    at_bound = (xc <= lb + eps) | (xc >= ub - eps)
    return xc, ~at_bound


@true_f32
def lbfgsb_fit(
    cost_fn: Callable[[jax.Array], jax.Array],
    grad_fn: Optional[Callable[[jax.Array], jax.Array]],
    p0: jax.Array,
    lb: jax.Array,
    ub: jax.Array,
    itmax: int = 50,
    M: int = 7,
    factr_tol: float = 1e-12,
    pg_tol: float = 1e-10,
    max_ls: int = 20,
) -> LBFGSBResult:
    """Minimize ``cost_fn`` subject to ``lb <= p <= ub``.

    ``grad_fn=None`` uses ``jax.grad(cost_fn)`` — the reference requires
    a hand-written gradient callback; autodiff replaces it.
    Jittable; mirrors the ``lbfgs_fit`` calling convention."""
    if grad_fn is None:
        grad_fn = jax.grad(cost_fn)
    lb = jnp.broadcast_to(jnp.asarray(lb, p0.dtype), p0.shape)
    ub = jnp.broadcast_to(jnp.asarray(ub, p0.dtype), p0.shape)
    x0 = _project(p0, lb, ub)
    n = x0.shape[0]
    mem0 = LBFGSMemory.init(n, M, x0.dtype)

    def step(carry, _):
        x, f, g, mem, theta, done, it = carry

        xc, free = _cauchy_point(x, g, lb, ub, theta)
        # subspace direction from the quasi-Newton memory on the free
        # set; bound variables step straight to their Cauchy values
        d_qn = _two_loop_direction(g, mem)
        d = jnp.where(free, d_qn, xc - x)
        # fall back to projected steepest descent if not a descent dir
        d = jnp.where(jnp.vdot(g, d) < 0.0, d, -g)

        # projected Armijo backtracking
        def ls_cond(st):
            k, alpha, ok = st
            return (k < max_ls) & (~ok)

        def ls_body(st):
            k, alpha, _ = st
            xt = _project(x + alpha * d, lb, ub)
            ok = cost_fn(xt) <= f + 1e-4 * jnp.vdot(g, xt - x)
            return k + 1, jnp.where(ok, alpha, alpha * 0.5), ok

        _, alpha, ls_ok = jax.lax.while_loop(
            ls_cond, ls_body, (0, jnp.asarray(1.0, x.dtype), jnp.asarray(False))
        )
        x1 = _project(x + alpha * d, lb, ub)
        f1 = cost_fn(x1)
        g1 = grad_fn(x1)

        s = x1 - x
        y = g1 - g
        sy = jnp.vdot(s, y)
        yy = jnp.vdot(y, y)
        good_pair = sy > 1e-10 * jnp.sqrt(jnp.vdot(s, s)) * jnp.sqrt(yy)

        def push(m: LBFGSMemory) -> LBFGSMemory:
            slot = m.vacant
            return m.replace(
                s=m.s.at[slot].set(s),
                y=m.y.at[slot].set(y),
                rho=m.rho.at[slot].set(1.0 / sy),
                vacant=jnp.mod(slot + 1, m.s.shape[0]),
                nfilled=jnp.minimum(m.nfilled + 1, m.s.shape[0]),
            )

        mem1 = jax.lax.cond(
            good_pair & ls_ok & (~done), push, lambda m: m, mem
        )
        theta1 = jnp.where(good_pair, yy / jnp.where(sy == 0, 1.0, sy), theta)
        theta1 = jnp.clip(theta1, 1e-8, 1e12)

        improved = ls_ok & (f1 < f) & (~done)
        x2 = jnp.where(improved, x1, x)
        f2 = jnp.where(improved, f1, f)
        g2 = jnp.where(improved, g1, g)
        # projected-gradient convergence (the reference's pgtol role)
        pg = x2 - _project(x2 - g2, lb, ub)
        small = jnp.max(jnp.abs(pg)) < pg_tol
        flat = jnp.abs(f - f1) <= factr_tol * jnp.maximum(
            1.0, jnp.maximum(jnp.abs(f), jnp.abs(f1))
        )
        done1 = done | small | (improved & flat) | (~ls_ok)
        it1 = it + (~done).astype(it.dtype)
        return (x2, f2, g2, mem1, theta1, done1, it1), None

    f0 = cost_fn(x0)
    g0 = grad_fn(x0)
    init = (
        x0, f0, g0, mem0, jnp.asarray(1.0, x0.dtype),
        jnp.asarray(False), jnp.asarray(0),
    )
    (x, f, _, _, _, _, it), _ = jax.lax.scan(step, init, None, length=itmax)
    return LBFGSBResult(p=x, cost=f, iterations=it)
