"""Levenberg-Marquardt for per-cluster Jones solves — batched, TPU-first.

Redesign of ``clevmar_der_single_nocuda`` / ``oslevmar_der_single_nocuda``
(``/root/reference/src/lib/Dirac/clmfit.c``, contract at Dirac.h:544-559,
849-931).  The reference materializes the full (8*Nbase*tilesz x 8N)
Jacobian per cluster and runs one LM loop per hybrid chunk on pthreads.
Here the structure of the RIME is exploited instead: each residual row
(one baseline, 8F reals) depends only on the 16 parameters of its two
stations, so J^T J is assembled from per-row 16x16 blocks scattered into a
(nchunk, N, N, 8, 8) block grid, and J^T e from per-row 16-vectors — one
fused pass over all rows for ALL hybrid chunks at once.  The LM iterations
for all chunks then run in lock-step inside a single ``lax.while_loop``
(per-chunk damping/acceptance state, masked once a chunk terminates), and
the tiny dense (8N x 8N) solves are a vmapped Cholesky.  This removes the
reference's pthread fan-out and its per-chunk sequential loop
(lmfit.c:897-967) in one stroke.

Termination mirrors the levmar contract (Dirac.h:544-559): max
iterations, gradient inf-norm < eps1, relative step < eps2, cost < eps3;
damping update is Nielsen's: accept -> mu *= max(1/3, 1-(2*rho-1)^3),
nu=2; reject -> mu *= nu, nu *= 2.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from flax import struct

from sagecal_tpu.core.types import corrupt_flat, params_to_jones, reals_of_flat
from sagecal_tpu.obs.records import init_trace, write_trace
from sagecal_tpu.ops.quality import SolveQuality, residual_quality
from sagecal_tpu.utils.precision import true_f32

# Row-block size for the Jacobian-assembly scan: bounds the per-block
# (RB, F*8, 8) Jacobian intermediates so assembly memory is O(block), not
# O(rows) — at the 62-stn/100-cluster/60-ts shape the unblocked
# intermediates would be ~1 GB each after TPU tile padding.
_ROW_BLOCK = 8192


@struct.dataclass
class LMConfig:
    itmax: int = struct.field(pytree_node=False, default=10)
    tau: float = struct.field(pytree_node=False, default=1e-3)
    eps1: float = struct.field(pytree_node=False, default=1e-15)
    eps2: float = struct.field(pytree_node=False, default=1e-15)
    eps3: float = struct.field(pytree_node=False, default=1e-15)


class LMResult(NamedTuple):
    p: jax.Array  # (nchunk, 8N)
    cost0: jax.Array  # (nchunk,) initial cost
    cost: jax.Array  # (nchunk,) final cost
    iterations: jax.Array
    # per-iteration IterTrace (obs.records) when collect_trace=True, else
    # None — an empty pytree, so the jitted output signature is unchanged
    trace: Optional[tuple] = None
    # SolveQuality (ops.quality) when collect_quality=True, same contract
    quality: Optional[SolveQuality] = None


def _residual_flat(p_all, coh, vis, mask, ant_p, ant_q, chunk_map, sqrt_w):
    """Real residual elements (F, 8, rows): reals of (vis - J_p C J_q^H)
    * mask * sqrt_w, in the reference's 8-real ordering (Dirac.h:1617).

    p_all: (nchunk, 8N) real params; vis/coh flat (F, 4, rows).
    """
    model = corrupt_flat(params_to_jones(p_all), coh, ant_p, ant_q, chunk_map)
    diff = (vis - model) * mask[..., None, :]
    r = reals_of_flat(diff)  # (F, 8, rows)
    if sqrt_w is not None:
        r = r * sqrt_w
    return r


def _row_model(pp, qq, C, mask_row, sqrt_w_row):
    """Model for ONE row as a function of its two stations' 16 params.

    pp, qq: (8,) real params; C: (F,2,2) complex. Returns (F*8,) reals
    ordered (f, i, j, re/im) — identical to one row of
    :func:`_residual_flat`'s (F, 8) elements.
    """
    Jp = params_to_jones(pp)[0]  # (2,2)
    Jq = params_to_jones(qq)[0]
    m = Jp @ C @ jnp.conj(Jq.T)
    r = jnp.stack([jnp.real(m), jnp.imag(m)], axis=-1) * mask_row[:, None, None, None]
    r = r.reshape(-1)
    if sqrt_w_row is not None:
        r = r * sqrt_w_row
    return r


def _pad_rows(x, padr, axis=-1):
    if padr == 0:
        return x
    cfg = [(0, 0)] * x.ndim
    cfg[axis % x.ndim] = (0, padr)
    return jnp.pad(x, cfg)


def _assemble_normal_eq(p_all, coh, vis, mask, ant_p, ant_q, chunk_map, nchunk, sqrt_w):
    """Row-blocked pass -> (JTJ (nchunk,8N,8N), JTe (nchunk,8N), cost (nchunk,)).

    Sign convention: residual e = vis - model, Jacobian taken of the
    *model*, so the gradient of 0.5||e||^2 is -J^T e; we return JTe = J^T e
    (the LM step solves (JTJ + mu I) dp = JTe).

    Each residual row depends only on its two stations' 16 parameters, so
    J^T J is assembled from per-row 8x8 blocks scattered into an
    (nchunk, N, N, 8, 8) grid — the TPU answer to the reference's full
    (8*Nbase*tilesz x 8N) Jacobian materialization (clmfit.c).  Rows are
    processed in blocks of ``_ROW_BLOCK`` under ``lax.scan`` so the
    per-row mat-form intermediates stay bounded at any tile size.
    """
    N = p_all.shape[-1] // 8
    dtype = p_all.dtype
    rows = ant_p.shape[0]
    F = vis.shape[-3]

    e = _residual_flat(p_all, coh, vis, mask, ant_p, ant_q, chunk_map, sqrt_w)
    cost = jnp.zeros((nchunk,), dtype).at[chunk_map].add(jnp.sum(e * e, axis=(0, 1)))

    pblk = p_all.reshape(nchunk, N, 8)

    nblk = -(-rows // _ROW_BLOCK)
    RB = -(-rows // nblk)
    padr = nblk * RB - rows
    coh_b = jnp.moveaxis(
        _pad_rows(coh, padr).reshape(F, 4, nblk, RB), 2, 0
    )  # (nblk, F, 4, RB)
    mask_b = jnp.moveaxis(_pad_rows(mask, padr).reshape(F, nblk, RB), 1, 0)
    e_b = jnp.moveaxis(_pad_rows(e, padr).reshape(F, 8, nblk, RB), 2, 0)
    ap_b = _pad_rows(ant_p, padr).reshape(nblk, RB)
    aq_b = _pad_rows(ant_q, padr).reshape(nblk, RB)
    cm_b = _pad_rows(chunk_map, padr).reshape(nblk, RB)
    with_w = sqrt_w is not None
    if with_w:
        sw_full = jnp.broadcast_to(sqrt_w, e.shape)
        sw_b = jnp.moveaxis(_pad_rows(sw_full, padr).reshape(F, 8, nblk, RB), 2, 0)
    else:
        sw_b = jnp.zeros((nblk, 1, 1, 1), dtype)  # unused placeholder

    jac_fn = jax.vmap(
        jax.jacfwd(_row_model, argnums=(0, 1)),
        in_axes=(0, 0, 0, 0, 0 if with_w else None),
    )

    def block(carry, xs):
        JTJ, JTe = carry
        coh_k, mask_k, e_k, ap, aq, cm, sw_k = xs
        C = jnp.moveaxis(coh_k, -1, 0).reshape(RB, F, 2, 2)
        mrow = jnp.moveaxis(mask_k, -1, 0)  # (RB, F)
        erow = jnp.moveaxis(e_k, -1, 0).reshape(RB, F * 8)
        swrow = (
            jnp.moveaxis(sw_k, -1, 0).reshape(RB, F * 8) if with_w else None
        )
        pp = pblk[cm, ap]  # (RB, 8)
        qq = pblk[cm, aq]
        Jp, Jq = jac_fn(pp, qq, C, mrow, swrow)  # (RB, F8, 8) each
        App = jnp.einsum("rki,rkj->rij", Jp, Jp)
        Apq = jnp.einsum("rki,rkj->rij", Jp, Jq)
        Aqq = jnp.einsum("rki,rkj->rij", Jq, Jq)
        gp = jnp.einsum("rki,rk->ri", Jp, erow)
        gq = jnp.einsum("rki,rk->ri", Jq, erow)
        JTJ = JTJ.at[cm, ap, ap].add(App)
        JTJ = JTJ.at[cm, ap, aq].add(Apq)
        JTJ = JTJ.at[cm, aq, ap].add(jnp.swapaxes(Apq, -1, -2))
        JTJ = JTJ.at[cm, aq, aq].add(Aqq)
        JTe = JTe.at[cm, ap].add(gp)
        JTe = JTe.at[cm, aq].add(gq)
        return (JTJ, JTe), None

    from sagecal_tpu.utils.platform import match_vma

    JTJ0 = jnp.zeros((nchunk, N, N, 8, 8), dtype)
    JTe0 = jnp.zeros((nchunk, N, 8), dtype)
    (JTJ, JTe), _ = jax.lax.scan(
        block, match_vma((JTJ0, JTe0), e), (coh_b, mask_b, e_b, ap_b, aq_b, cm_b, sw_b)
    )
    JTJ = JTJ.transpose(0, 1, 3, 2, 4).reshape(nchunk, 8 * N, 8 * N)
    JTe = JTe.reshape(nchunk, 8 * N)
    return JTJ, JTe, cost


def _cost_only(p_all, coh, vis, mask, ant_p, ant_q, chunk_map, nchunk, sqrt_w):
    e = _residual_flat(p_all, coh, vis, mask, ant_p, ant_q, chunk_map, sqrt_w)
    return jnp.zeros((nchunk,), p_all.dtype).at[chunk_map].add(
        jnp.sum(e * e, axis=(0, 1))
    )


def _solve_spd(A, b):
    """Batched damped-normal-equation solve via Cholesky with SVD-free
    jitter fallback (the reference offers Cholesky/QR/SVD by ``linsolv``;
    on TPU a jittered Cholesky covers the QR/SVD rescue role)."""
    n = A.shape[-1]
    eye = jnp.eye(n, dtype=A.dtype)

    def chol_solve(Ai, bi):
        L, lower = jax.scipy.linalg.cho_factor(Ai + 1e-9 * eye, lower=True)
        x = jax.scipy.linalg.cho_solve((L, lower), bi)
        ok = jnp.all(jnp.isfinite(x))
        x2 = jnp.linalg.solve(Ai + 1e-5 * eye, bi)
        return jnp.where(ok, x, x2)

    return jax.vmap(chol_solve)(A, b)


@true_f32
def lm_solve(
    vis: jax.Array,
    coh: jax.Array,
    mask: jax.Array,
    ant_p: jax.Array,
    ant_q: jax.Array,
    chunk_map: jax.Array,
    p0: jax.Array,
    config: LMConfig = LMConfig(),
    sqrt_weights: Optional[jax.Array] = None,
    itmax_dynamic: Optional[jax.Array] = None,
    admm_y: Optional[jax.Array] = None,
    admm_bz: Optional[jax.Array] = None,
    admm_rho: Optional[jax.Array] = None,
    collect_trace: bool = False,
    collect_quality: bool = False,
) -> LMResult:
    """Solve min_p sum_rows ||vis - J_p C J_q^H||^2 per hybrid chunk.

    ``collect_quality``: statically enables the fixed-shape quality side
    outputs (ops/quality.py): chi^2 attribution of the final residual
    per station / baseline / chunk plus gain health of the final p.
    Attribution is of the DATA term only — in ADMM-augmented solves the
    consensus terms are excluded, so ``quality.chi2_chunk`` equals the
    reported ``cost`` exactly only for plain solves.

    ``itmax_dynamic``: optional traced iteration bound (the SAGE driver's
    weighted per-cluster iteration allocation, lmfit.c:859-882);
    ``config.itmax`` stays the static compile-time ceiling.

    ADMM augmentation (``admm_y/admm_bz`` (nchunk, 8N), ``admm_rho``
    scalar): adds ``y^T(p - bz) + rho/2 ||p - bz||^2`` to the cost — the
    consensus-constrained local solve of ``sagefit_visibilities_admm``
    (admm_solve.c:221; cost contract Dirac.h:1182-1195).  The augmented
    term is exactly quadratic, so it enters the normal equations as
    ``JTJ += rho I`` and ``JTe -= y + rho (p - bz)``.

    Args:
      vis: (F, 4, rows) complex effective data for this cluster (flat).
      coh: (F, 4, rows) complex precomputed cluster coherencies (flat).
      mask: (F, rows) flag mask.
      ant_p/ant_q: (rows,) station indices.
      chunk_map: (rows,) int32 hybrid-chunk index of each row.
      p0: (nchunk, 8N) initial parameters.
      sqrt_weights: optional (F, 8, rows)-broadcastable robust sqrt-weights.
    Returns LMResult with per-chunk solutions.
    """
    nchunk = p0.shape[0]
    args = (coh, vis, mask, ant_p, ant_q, chunk_map, nchunk, sqrt_weights)
    with_admm = admm_y is not None
    if with_admm:
        rho = jnp.asarray(admm_rho, p0.dtype)

        def aug_cost(p, c):
            d = p - admm_bz
            return c + jnp.sum(admm_y * d, axis=-1) + 0.5 * rho * jnp.sum(d * d, axis=-1)

        # JTe carries the HALF-gradient convention (grad of sum(e*e) is
        # -2*JTe), so the augmented terms enter at half strength too:
        # gradient 0.5*y + 0.5*rho*(p-bz), Hessian 0.5*rho*I — exactly the
        # reference's factors (rtr_solve_robust_admm.c:680-689,941-942).
        def aug_grad(p):
            return 0.5 * (admm_y + rho * (p - admm_bz))

    else:

        def aug_cost(p, c):
            return c

        def aug_grad(p):
            return jnp.zeros_like(p)

    JTJ, JTe, cost0 = _assemble_normal_eq(p0, *args)
    cost0 = aug_cost(p0, cost0)
    # mu_0 = tau * max(diag(JTJ)) per chunk (levmar init)
    diag0 = jnp.diagonal(JTJ, axis1=-2, axis2=-1)
    mu0 = config.tau * jnp.max(diag0, axis=-1)

    it_bound = (
        jnp.asarray(config.itmax)
        if itmax_dynamic is None
        else jnp.minimum(config.itmax, itmax_dynamic)
    )

    # trace is None (empty pytree) when collection is off, so the
    # while_loop carry — and the jitted output signature — is unchanged
    trace0 = init_trace(config.itmax, (nchunk,), p0.dtype) if collect_trace else None

    def cond(st):
        it, p, cost, mu, nu, done, trace = st
        return (it < it_bound) & (~jnp.all(done))

    def body(st):
        it, p, cost, mu, nu, done, trace = st
        JTJ, JTe, _ = _assemble_normal_eq(p, *args)
        JTe = JTe - aug_grad(p)
        n8 = p.shape[-1]
        damp = mu + 0.5 * rho if with_admm else mu
        A = JTJ + damp[:, None, None] * jnp.eye(n8, dtype=p.dtype)[None]
        dp = _solve_spd(A, JTe)
        pnew = p + dp
        cost_new = aug_cost(pnew, _cost_only(pnew, *args))
        # gain ratio (cost - cost_new) / (dp.(damp*dp + JTe)): the
        # predicted decrease of the (possibly ADMM-augmented) quadratic
        # model must use the same damping the step was solved with —
        # damp = mu + rho/2 in consensus solves — or the ratio
        # misestimates and mu adaptation drifts for large rho
        denom = jnp.sum(dp * (damp[:, None] * dp + JTe), axis=-1)
        gain = (cost - cost_new) / jnp.where(denom == 0.0, 1e-30, denom)
        accept = (gain > 0.0) & jnp.isfinite(cost_new) & (~done)
        fac = jnp.maximum(1.0 / 3.0, 1.0 - (2.0 * gain - 1.0) ** 3)
        mu_acc = mu * fac
        mu_rej = mu * nu
        p1 = jnp.where(accept[:, None], pnew, p)
        cost1 = jnp.where(accept, cost_new, cost)
        mu1 = jnp.where(done, mu, jnp.where(accept, mu_acc, mu_rej))
        nu1 = jnp.where(done, nu, jnp.where(accept, 2.0, 2.0 * nu))
        # termination (per chunk)
        g_inf = jnp.max(jnp.abs(JTe), axis=-1)
        small_step = jnp.linalg.norm(dp, axis=-1) <= config.eps2 * (
            jnp.linalg.norm(p1, axis=-1) + config.eps2
        )
        done1 = done | (g_inf <= config.eps1) | small_step | (cost1 <= config.eps3)
        if trace is not None:
            trace = write_trace(
                trace, it,
                cost=cost1,
                grad_norm=g_inf,
                step=jnp.linalg.norm(dp, axis=-1),
                ls_evals=jnp.where(done, 0.0, 1.0).astype(cost1.dtype),
            )
        return it + 1, p1, cost1, mu1, nu1, done1, trace

    from sagecal_tpu.utils.platform import match_vma

    nu0 = jnp.full((nchunk,), 2.0, p0.dtype)
    done0 = jnp.zeros((nchunk,), bool)
    it, p, cost, _, _, _, trace = jax.lax.while_loop(
        cond, body,
        match_vma((jnp.asarray(0), p0, cost0, mu0, nu0, done0, trace0), p0),
    )
    quality = None
    if collect_quality:
        e1 = _residual_flat(
            p, coh, vis, mask, ant_p, ant_q, chunk_map, sqrt_weights
        )
        quality = residual_quality(
            e1, p, ant_p, ant_q, chunk_map, nchunk
        )
    return LMResult(p=p, cost0=cost0, cost=cost, iterations=it, trace=trace,
                    quality=quality)


@true_f32
def os_lm_solve(
    vis, coh, mask, ant_p, ant_q, chunk_map, p0,
    config: LMConfig = LMConfig(),
    sqrt_weights: Optional[jax.Array] = None,
    nsubsets: int = 4,
    key: Optional[jax.Array] = None,
    collect_trace: bool = False,
    collect_quality: bool = False,
) -> LMResult:
    """Ordered-subsets accelerated LM (``oslevmar_der_single_nocuda``,
    Dirac.h:907): each outer iteration runs one LM pass on a random subset
    of rows.  Subsets are realized as masks (static shapes) — rows outside
    the active subset get zero mask, so every pass touches all rows but
    only the subset contributes; per-subset cost is rescaled by the subset
    fraction, mirroring the reference's per-subset normal equations.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    rows = vis.shape[-1]
    perm = jax.random.permutation(key, rows)
    subset_of_row = jnp.zeros((rows,), jnp.int32).at[perm].set(
        jnp.arange(rows, dtype=jnp.int32) % nsubsets
    )
    sub_cfg = LMConfig(
        itmax=max(1, config.itmax // nsubsets),
        tau=config.tau, eps1=config.eps1, eps2=config.eps2, eps3=config.eps3,
    )
    p = p0
    cost0 = None
    traces = []
    for s in range(nsubsets):
        m_s = mask * (subset_of_row == s)[None, :].astype(mask.dtype)
        res = lm_solve(
            vis, coh, m_s, ant_p, ant_q, chunk_map, p, sub_cfg, sqrt_weights,
            collect_trace=collect_trace,
        )
        p = res.p
        if cost0 is None:
            cost0 = res.cost0 * nsubsets
        if collect_trace:
            traces.append(res.trace)
    # per-subset traces concatenate on the iteration axis: the OS pass IS
    # one LM run whose iterations cycle through subsets
    trace = (
        jax.tree_util.tree_map(lambda *xs: jnp.concatenate(xs, axis=0), *traces)
        if collect_trace
        else None
    )
    final_cost = _cost_only(
        p, coh, vis, mask, ant_p, ant_q, chunk_map, p0.shape[0], sqrt_weights
    )
    quality = None
    if collect_quality:
        # attribution of the FULL-mask residual at the final p (each
        # subset pass only ever saw its own rows; quality reports the
        # solver's final objective over all of them)
        e1 = _residual_flat(
            p, coh, vis, mask, ant_p, ant_q, chunk_map, sqrt_weights
        )
        quality = residual_quality(
            e1, p, ant_p, ant_q, chunk_map, p0.shape[0]
        )
    return LMResult(p=p, cost0=cost0, cost=final_cost,
                    iterations=jnp.asarray(config.itmax), trace=trace,
                    quality=quality)


# Jitted module entries (obs/perf.py): inside the packed SAGE solve
# these solvers are traced as part of one big jit; the standalone
# wrappers below are for eager callers (tests, notebooks, partial
# pipelines) and record compile/recompile + cost-analysis telemetry
# under SAGECAL_TELEMETRY=1.  A changed LMConfig is a new static
# signature, i.e. a visible recompile.
from sagecal_tpu.obs.perf import instrumented_jit  # noqa: E402

lm_solve_jit = instrumented_jit(
    lm_solve, name="lm_solve",
    static_argnames=("collect_trace", "collect_quality"))
os_lm_solve_jit = instrumented_jit(
    os_lm_solve, name="os_lm_solve",
    static_argnames=("nsubsets", "collect_trace", "collect_quality"))
