"""Robust Student's-t noise model: EM weights and nu estimation.

Reimplements ``update_w_and_nu`` / ``update_nu`` (``/root/reference/src/
lib/Dirac/updatenu.c:136,263``) and the IRLS wrapper logic of
``rlevmar_der_single_nocuda`` (``robustlm.c``; decl Dirac.h:744): the EM
E-step computes per-residual-element weights w = (nu+1)/(nu + e^2), the
M-step is a weighted LM solve with sqrt(w)-scaled residuals, and nu is
re-estimated by a digamma-score grid search over [nulow, nuhigh]
(Nd=30 points, argmin |score|).  All of it is jit-compatible: the grid
search is a vectorized reduction, the digamma comes from
``jax.scipy.special.digamma``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.scipy.special import digamma

from sagecal_tpu.solvers.lm import LMConfig, LMResult, _residual_flat, lm_solve
from sagecal_tpu.utils.precision import true_f32


def update_w_and_nu(
    ed: jax.Array,
    nu0: jax.Array,
    nulow: float = 2.0,
    nuhigh: float = 30.0,
    Nd: int = 30,
    mask: Optional[jax.Array] = None,
):
    """E-step + nu grid search (updatenu.c:136-253).

    ed: residual elements (any shape, reals).  Returns (sqrt_w, nu):
    sqrt-weights of ed's shape and the new scalar nu, chosen on a grid of
    Nd points in [nulow, nuhigh] by minimizing
    |psi((nu+1)/2) - ln((nu+1)/2) - psi(nu/2) + ln(nu/2) + mean(ln w - w) + 1|.
    ``mask`` restricts the mean to valid elements (flagged data carries
    w=1 so it stays inert downstream).
    """
    w = (nu0 + 1.0) / (nu0 + ed * ed)
    q = w - jnp.log(w)  # per-element, positive
    if mask is not None:
        mfull = jnp.broadcast_to(mask, w.shape)
        msum = jnp.maximum(jnp.sum(mfull), 1.0)
        sumq = jnp.sum(jnp.abs(q) * mfull) / msum
        w = jnp.where(mfull > 0, w, 1.0)
    else:
        sumq = jnp.mean(jnp.abs(q))
    deltanu = (nuhigh - nulow) / Nd
    grid = nulow + deltanu * jnp.arange(Nd)
    score = (
        digamma(grid * 0.5 + 0.5)
        - jnp.log((grid + 1.0) * 0.5)
        - digamma(grid * 0.5)
        + jnp.log(grid * 0.5)
        - sumq
        + 1.0
    )
    nu = grid[jnp.argmin(jnp.abs(score))]
    return jnp.sqrt(w), nu


def update_nu_aecm(
    logsumw: jax.Array,
    nu_old: jax.Array,
    p: int = 8,
    nulow: float = 2.0,
    nuhigh: float = 30.0,
    Nd: int = 30,
):
    """AECM nu update (updatenu.c:263-341): solve for nu in
    psi((nu_old+p)/2) - ln((nu_old+p)/2) - psi(nu/2) + ln(nu/2)
    + logsumw + 1 = 0, logsumw = mean(ln w_i - w_i)."""
    dgm = digamma((nu_old + p) * 0.5) - jnp.log((nu_old + p) * 0.5)
    deltanu = (nuhigh - nulow) / Nd
    grid = nulow + deltanu * jnp.arange(Nd)
    score = -digamma(grid * 0.5) + jnp.log(grid * 0.5) + logsumw + dgm + 1.0
    # keep the caller's dtype: under x64 the grid is f64 and would
    # otherwise promote an f32 EM carry (caught by the config-3 AOT test)
    return grid[jnp.argmin(jnp.abs(score))].astype(jnp.result_type(nu_old))


@true_f32
def robust_lm_solve(
    vis, coh, mask, ant_p, ant_q, chunk_map, p0,
    nu0: float = 2.0,
    nulow: float = 2.0,
    nuhigh: float = 30.0,
    em_iters: int = 3,
    config: LMConfig = LMConfig(),
    collect_trace: bool = False,
    collect_quality: bool = False,
):
    """Robust LM: EM over (weights, nu) wrapping weighted LM solves
    (``rlevmar_der_single_nocuda``, robustlm.c; Dirac.h:744).

    Returns (LMResult, nu).  With ``collect_trace`` the result's trace
    stacks the EM stages in front: ``(em_iters + 1, itmax, nchunk)`` per
    field (final weighted solve last), with the trace's ``nu`` field set
    to the Student's-t nu in effect during each stage.

    ``collect_quality`` additionally fills the result's quality slot
    (ops/quality.py): the final weighted solve's chi^2 attribution and
    gain health, enriched with the converged nu and Student's-t weight
    statistics (histogram, down-weighted and flagged fractions).
    """
    mask8 = mask[..., None, :]  # broadcasts over the (F, 8, rows) residual

    def em_step(carry, _):
        p, nu, sqrt_w = carry
        res = lm_solve(
            vis, coh, mask, ant_p, ant_q, chunk_map, p, config,
            sqrt_weights=sqrt_w, collect_trace=collect_trace,
        )
        ed = _residual_flat(res.p, coh, vis, mask, ant_p, ant_q, chunk_map, None)
        sqrt_w_new, nu_new = update_w_and_nu(ed, nu, nulow, nuhigh, mask=mask8)
        ys = res.cost
        if collect_trace:
            # nu in effect for this stage is the carried nu (it built the
            # weights the solve just used)
            tr = res.trace._replace(
                nu=jnp.broadcast_to(nu, res.trace.nu.shape).astype(res.trace.nu.dtype)
            )
            ys = (res.cost, tr)
        return (res.p, nu_new, sqrt_w_new), ys

    # E-step FIRST: weights from the residual at p0, so gross outliers are
    # suppressed before they can poison the first fit.  (The reference's
    # first M-step is unweighted, robustlm.c:2231-2257 — safe there only
    # because SAGE hands it a warm start from the previous tile; from a
    # cold start the unweighted fit can lock the EM into a bad basin.)
    ed0 = _residual_flat(p0, coh, vis, mask, ant_p, ant_q, chunk_map, None)
    sqrt_w0, nu_e = update_w_and_nu(
        ed0, jnp.asarray(nu0, p0.dtype), nulow, nuhigh, mask=mask8
    )
    init = (p0, nu_e, sqrt_w0)
    (p, nu, sqrt_w), ys = jax.lax.scan(em_step, init, None, length=em_iters)
    # final weighted solve with converged weights
    res = lm_solve(
        vis, coh, mask, ant_p, ant_q, chunk_map, p, config,
        sqrt_weights=sqrt_w, collect_trace=collect_trace,
        collect_quality=collect_quality,
    )
    if collect_quality:
        from sagecal_tpu.ops.quality import weight_stats

        hist, down, flag = weight_stats(sqrt_w, nu, mask8)
        res = res._replace(quality=res.quality._replace(
            nu=jnp.asarray(nu, p0.dtype), weight_hist=hist,
            downweighted_frac=down, flagged_frac=flag,
        ))
    if collect_trace:
        _, em_traces = ys  # IterTrace stacked (em_iters, itmax, ...)
        final_tr = res.trace._replace(
            nu=jnp.broadcast_to(nu, res.trace.nu.shape).astype(res.trace.nu.dtype)
        )
        full = jax.tree_util.tree_map(
            lambda em, fin: jnp.concatenate([em, fin[None]], axis=0),
            em_traces, final_tr,
        )
        res = res._replace(trace=full)
    return res, nu


def whiten_uv_weights(u, v, freq0):
    """uv-density pre-whitening weight for the -W option
    (``whiten_data``/``ncp_weight``, updatenu.c:341-360):
    w(d) = 1/(1 + 1.8 exp(-0.05 d)), d = sqrt(u^2+v^2) wavelengths,
    1.0 beyond 400 wavelengths."""
    ud = jnp.sqrt(u * u + v * v) * freq0
    w = 1.0 / (1.0 + 1.8 * jnp.exp(-0.05 * ud))
    return jnp.where(ud > 400.0, 1.0, w)


# jitted module entry with compile/recompile telemetry (see
# sagecal_tpu/obs/perf.py; the em_iters EM ladder is a static python
# loop, so a changed em_iters is a visible recompile)
from sagecal_tpu.obs.perf import instrumented_jit  # noqa: E402

robust_lm_solve_jit = instrumented_jit(
    robust_lm_solve, name="robust_lm_solve",
    static_argnames=("em_iters", "collect_trace", "collect_quality"))
