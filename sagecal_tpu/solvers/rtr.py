"""Riemannian Trust Region + Nesterov steepest descent on the Jones
quotient manifold.

Redesign of ``/root/reference/src/lib/Dirac/rtr_solve.c`` (ICASSP'13
solver; entry ``rtr_solve_nocuda`` decl Dirac.h:1132), the robust
variants (``rtr_solve_robust.c``) and ``nsd_solve_nocuda_robust``
(rtr_solve_robust.c:1878).  The reference evaluates cost/gradient/
Hessian with pthread scatter-add loops guarded by per-station mutexes;
here the Euclidean gradient and the Hessian-vector product come from
``jax.grad`` / ``jax.jvp`` of the one jitted cost function, the
per-station scatter is an XLA ``segment-sum`` (race-free by
construction), and hybrid chunks solve in lock-step under ``vmap``.

Faithfully reproduced structure (rtr_solve.c:1208-1556):
- solution space: X in C^{2N x 2} (station-stacked Jones columns),
  quotient by the right unitary U(2) ambiguity;
- metric  g(eta, gamma) = 2 Re trace(eta^H gamma)  (fns_g, :323);
- horizontal projection  z - X Om  with  Om M + M Om = X^H z - z^H X,
  M = X^H X, solved as a 4x4 Sylvester system (fns_proj, :340);
- retraction R(x, eta) = x + eta (fns_R, :419 — additive, not QR);
- per-station gradient normalization by inverse baseline counts,
  scaled to max 1 (fns_fcount, :99-180);
- RSD (Armijo) warmup iterations, then TR with truncated CG
  (tcg_solve, :887): theta=1, kappa=0.1, eta1=1e-4, eta2=0.99,
  alpha1=0.25, alpha2=3.5, Delta_bar=min(f0, 0.01), Delta0=Delta_bar/8,
  rho regularization f0*1e-6;
- NSD: Nesterov acceleration theta_{k+1}=2/(1+sqrt(1+4/theta_k^2)) with
  adaptive Barzilai-Borwein-style step and growth/shrink 1.01/0.5
  (rtr_solve_robust.c:2020-2085).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from flax import struct

from sagecal_tpu.core.types import params_to_jones, jones_to_params
from sagecal_tpu.utils.precision import true_f32


@struct.dataclass
class RTRConfig:
    itmax_rsd: int = struct.field(pytree_node=False, default=2)
    itmax_rtr: int = struct.field(pytree_node=False, default=10)
    max_inner: int = struct.field(pytree_node=False, default=10)
    theta: float = struct.field(pytree_node=False, default=1.0)
    kappa: float = struct.field(pytree_node=False, default=0.1)
    eta1: float = struct.field(pytree_node=False, default=1e-4)
    eta2: float = struct.field(pytree_node=False, default=0.99)
    alpha1: float = struct.field(pytree_node=False, default=0.25)
    alpha2: float = struct.field(pytree_node=False, default=3.5)
    epsilon: float = struct.field(pytree_node=False, default=1e-12)


class RTRResult(NamedTuple):
    p: jax.Array  # (nchunk, 8N)
    cost0: jax.Array  # (nchunk,)
    cost: jax.Array  # (nchunk,)
    # per-iteration IterTrace (obs.records) when collect_trace=True, else
    # None — an empty pytree, so the jitted output signature is unchanged
    trace: Optional[tuple] = None
    # SolveQuality (ops.quality) when collect_quality=True, same contract
    quality: Optional[tuple] = None


# ---------------------------------------------------------------------------
# geometry: metric, projection
# ---------------------------------------------------------------------------

def _g(eta, gamma):
    """Metric 2*Re<eta, gamma> on (N, 2, 2) tangent arrays (fns_g)."""
    return 2.0 * jnp.sum(jnp.real(jnp.conj(eta) * gamma))


def _project(x, z):
    """Horizontal projection z - X Om (fns_proj, rtr_solve.c:340).

    x, z: (N, 2, 2) station Jones stacks; the 2Nx2 matrix view is
    X[2s+r, c] = x[s, r, c].
    """
    N = x.shape[0]
    X = x.reshape(2 * N, 2)
    Z = z.reshape(2 * N, 2)
    M = jnp.conj(X.T) @ X  # (2, 2)
    R = jnp.conj(X.T) @ Z
    R = R - jnp.conj(R.T)  # X^H Z - Z^H X
    eye = jnp.eye(2, dtype=x.dtype)
    A = jnp.kron(eye, M) + jnp.kron(M.T, eye)  # acts on vec_colmajor(Om)
    b = R.T.reshape(-1)  # column-major vec of R
    u = jnp.linalg.solve(A + 1e-12 * jnp.eye(4, dtype=x.dtype), b)
    Om = u.reshape(2, 2).T  # back from column-major
    out = Z - X @ Om
    return out.reshape(N, 2, 2)


# ---------------------------------------------------------------------------
# cost / gradient / hessian-vector (per chunk lane)
# ---------------------------------------------------------------------------

def _model_rows(x, coh, ant_p, ant_q):
    from sagecal_tpu.core.types import corrupt_flat

    return corrupt_flat(x, coh, ant_p, ant_q)


def _make_fns(vis, coh, rowmask, ant_p, ant_q, sqrt_w, admm=None):
    """Build (cost, grad, hess) closures for one chunk lane.

    vis/coh: flat (F, 4, rows) complex; rowmask: (F, rows) —
    already restricted to this chunk's rows; sqrt_w: optional robust
    sqrt-weights broadcastable against (F, 4, rows).

    ``admm``: optional (Yc, BZc, rho) consensus terms ((N,2,2) complex
    Lagrange multipliers / target, scalar penalty): the augmented cost
    ``Re tr(Y^H (X-BZ)) + rho/2 ||X-BZ||^2`` of the ADMM solvers
    (rtr_solve_robust_admm.c:199-215).  Following the reference, the
    ADMM gradient terms ``0.5 Y + 0.5 rho (X-BZ)`` and Hessian term
    ``0.5 rho eta`` are added AFTER the per-station iw normalization of
    the data gradient (rtr_solve_robust_admm.c:680-689,941-942) and
    before projection.
    """

    def admm_cost(x):
        if admm is None:
            return jnp.asarray(0.0, vis.real.dtype)
        Yc, BZc, rho = admm
        d = x - BZc
        return jnp.sum(jnp.real(jnp.conj(Yc) * d)) + 0.5 * rho * jnp.sum(
            jnp.real(d) ** 2 + jnp.imag(d) ** 2
        )

    def cost_c(x):
        res = (vis - _model_rows(x, coh, ant_p, ant_q)) * rowmask[..., None, :]
        if sqrt_w is not None:
            res = res * sqrt_w
        return jnp.sum(jnp.real(res) ** 2 + jnp.imag(res) ** 2) + admm_cost(x)

    def data_cost_c(x):
        res = (vis - _model_rows(x, coh, ant_p, ant_q)) * rowmask[..., None, :]
        if sqrt_w is not None:
            res = res * sqrt_w
        return jnp.sum(jnp.real(res) ** 2 + jnp.imag(res) ** 2)

    def cost_ri(xri):
        return data_cost_c(jax.lax.complex(xri[..., 0], xri[..., 1]))

    def egrad(x):
        """DATA Euclidean gradient in the fns convention:
        0.5*(df/dre + i df/dim) so that df along eta = g(egrad, eta).
        ADMM terms are added separately (un-iw-weighted)."""
        xri = jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)
        gri = jax.grad(cost_ri)(xri)
        return 0.5 * jax.lax.complex(gri[..., 0], gri[..., 1])

    def grad_fn(x, iw):
        """Weighted, projected Riemannian gradient (fns_fgrad)."""
        g = egrad(x) * iw[:, None, None]
        if admm is not None:
            Yc, BZc, rho = admm
            g = g + 0.5 * (Yc + rho * (x - BZc))
        return _project(x, g)

    def hess_fn(x, eta, iw):
        """Projected directional derivative of the weighted Euclidean
        gradient (fns_fhess): jvp through egrad."""

        def weg(xx):
            return egrad(xx) * iw[:, None, None]

        # jvp over complex inputs: drive through the re/im stacking
        def weg_ri(xri):
            out = weg(jax.lax.complex(xri[..., 0], xri[..., 1]))
            return jnp.stack([jnp.real(out), jnp.imag(out)], axis=-1)

        xri = jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)
        tri = jnp.stack([jnp.real(eta), jnp.imag(eta)], axis=-1)
        _, dri = jax.jvp(weg_ri, (xri,), (tri,))
        h = jax.lax.complex(dri[..., 0], dri[..., 1])
        if admm is not None:
            h = h + 0.5 * admm[2] * eta
        return _project(x, h)

    return cost_c, grad_fn, hess_fn


def _station_iw(rowmask, ant_p, ant_q, N):
    """Inverse baseline-count weights, scaled to max 1
    (fns_fcount, rtr_solve.c:99-180).  rowmask: (F, rows)."""
    good = (jnp.sum(rowmask, axis=0) > 0).astype(rowmask.dtype)
    cnt = jnp.zeros((N,), rowmask.dtype).at[ant_p].add(good).at[ant_q].add(good)
    iw = jnp.where(cnt > 0, 1.0 / jnp.maximum(cnt, 1), 0.0)
    mx = jnp.max(iw)
    return jnp.where(mx > 0, iw / mx, iw)


# ---------------------------------------------------------------------------
# truncated CG (tcg_solve, rtr_solve.c:887-1080)
# ---------------------------------------------------------------------------

def _tcg(x, grad, Delta, hess, cfg: RTRConfig):
    N = x.shape[0]
    zeros = jnp.zeros_like(x)
    r = grad
    r_r = _g(r, r)
    norm_r0 = jnp.sqrt(r_r)
    z = r
    z_r = _g(z, r)
    delta = -z
    state = dict(
        j=jnp.asarray(0), eta=zeros, Heta=zeros, r=r, delta=delta,
        e_Pe=jnp.asarray(0.0), e_Pd=_g(zeros, delta), d_Pd=z_r, z_r=z_r,
        stop=jnp.asarray(False),
    )

    Deltasq = Delta * Delta

    def cond(s):
        return (s["j"] < cfg.max_inner) & (~s["stop"])

    def body(s):
        Hxd = hess(x, s["delta"])
        d_Hd = _g(s["delta"], Hxd)
        alpha = s["z_r"] / jnp.where(d_Hd == 0.0, 1e-30, d_Hd)
        e_Pe_new = s["e_Pe"] + 2.0 * alpha * s["e_Pd"] + alpha * alpha * s["d_Pd"]

        # negative curvature or TR boundary -> tau step and stop
        hit = (d_Hd <= 0.0) | (e_Pe_new >= Deltasq)
        disc = s["e_Pd"] ** 2 + s["d_Pd"] * (Deltasq - s["e_Pe"])
        tau = (-s["e_Pd"] + jnp.sqrt(jnp.maximum(disc, 0.0))) / jnp.where(
            s["d_Pd"] == 0.0, 1e-30, s["d_Pd"]
        )
        step = jnp.where(hit, tau, alpha)
        eta_new = s["eta"] + step * s["delta"]
        Heta_new = s["Heta"] + step * Hxd

        r_new = s["r"] + alpha * Hxd
        r_r_new = _g(r_new, r_new)
        norm_r = jnp.sqrt(r_r_new)
        # linear/superlinear convergence test
        kconv = norm_r <= norm_r0 * jnp.minimum(norm_r0**cfg.theta, cfg.kappa)
        stop = hit | kconv

        z_new = r_new  # identity preconditioner
        z_r_new = _g(z_new, r_new)
        beta = z_r_new / jnp.where(s["z_r"] == 0.0, 1e-30, s["z_r"])
        delta_new = -z_new + beta * s["delta"]
        e_Pd_new = beta * (s["e_Pd"] + step * s["d_Pd"])
        d_Pd_new = z_r_new + beta * beta * s["d_Pd"]

        return dict(
            j=s["j"] + 1,
            eta=eta_new, Heta=Heta_new,
            r=jnp.where(stop, s["r"], r_new),
            delta=delta_new,
            e_Pe=jnp.where(hit, s["e_Pe"], e_Pe_new),
            e_Pd=e_Pd_new, d_Pd=d_Pd_new, z_r=z_r_new,
            stop=stop,
        )

    from sagecal_tpu.utils.platform import match_vma

    out = jax.lax.while_loop(cond, body, match_vma(state, grad))
    return out["eta"], out["Heta"], out["j"]


# ---------------------------------------------------------------------------
# single-chunk RTR / NSD
# ---------------------------------------------------------------------------

def _rtr_single(
    vis, coh, rowmask, ant_p, ant_q, x0, cfg: RTRConfig, sqrt_w, itmax_dyn=None,
    admm=None, collect_trace: bool = False,
):
    """``itmax_dyn``: optional traced base iteration budget; the RSD/TR
    bounds become min(static, dyn+5)/min(static, dyn+10), matching the
    reference's this_itermax+5/+10 call-site offsets (lmfit.c:936).
    ``admm``: optional (Yc, BZc, rho) consensus augmentation
    (rtr_solve_nocuda_robust_admm, rtr_solve_robust_admm.c)."""
    N = x0.shape[0]
    cost_c, grad_fn, hess_fn = _make_fns(
        vis, coh, rowmask, ant_p, ant_q, sqrt_w, admm
    )
    iw = _station_iw(rowmask, ant_p, ant_q, N)
    rsd_bound = (
        jnp.asarray(cfg.itmax_rsd)
        if itmax_dyn is None
        else jnp.minimum(cfg.itmax_rsd, itmax_dyn + 5)
    )
    rtr_bound = (
        jnp.asarray(cfg.itmax_rtr)
        if itmax_dyn is None
        else jnp.minimum(cfg.itmax_rtr, itmax_dyn + 10)
    )

    def hess(x, eta):
        return hess_fn(x, eta, iw)

    fx0 = cost_c(x0)

    # ---- RSD warmup with Armijo backtracking (armijostep) -------------
    def rsd_iter(x, i):
        g = grad_fn(x, iw)
        fx = cost_c(x)
        gg = _g(g, g)
        beta0 = jnp.asarray(1.0, gg.dtype)

        def armijo_cond(st):
            k, beta = st
            return (k < 12) & (cost_c(x - beta * g) > fx - 1e-4 * beta * gg)

        def armijo_body(st):
            k, beta = st
            return k + 1, beta * 0.5

        k, beta = jax.lax.while_loop(armijo_cond, armijo_body, (0, beta0))
        improved = (cost_c(x - beta * g) < fx) & (i < rsd_bound)
        return jnp.where(improved, x - beta * g, x), None

    x, _ = jax.lax.scan(rsd_iter, x0, jnp.arange(cfg.itmax_rsd))

    fx = cost_c(x)
    Delta_bar = jnp.minimum(fx, 0.01)
    Delta0 = Delta_bar * 0.125
    rho_reg0 = fx * 1e-6

    from sagecal_tpu.obs.records import init_trace, write_trace

    trace0 = init_trace(cfg.itmax_rtr, (), fx.real.dtype) if collect_trace else None

    def tr_cond(s):
        return (s["k"] < rtr_bound) & (~s["stop"])

    def tr_body(s):
        x, fx, Delta = s["x"], s["fx"], s["Delta"]
        g = grad_fn(x, iw)
        eta, Heta, cg_j = _tcg(x, g, Delta, hess, cfg)
        x_prop = x + eta  # fns_R: additive retraction
        fx_prop = cost_c(x_prop)
        rhonum = fx - fx_prop
        rhoden = -_g(g, eta) - 0.5 * _g(Heta, eta)
        rho_reg = jnp.maximum(1.0, fx) * rho_reg0
        rho = (rhonum + rho_reg) / jnp.where(
            rhoden + rho_reg == 0.0, 1e-30, rhoden + rho_reg
        )
        model_dec = rhoden > 0.0
        accept = (rho > cfg.eta1) & model_dec & (fx_prop < fx)
        Delta_new = jnp.where(
            rho < cfg.eta1,
            Delta * cfg.alpha1,
            jnp.where(
                (rho > cfg.eta2) & model_dec,
                jnp.minimum(Delta * cfg.alpha2, Delta_bar),
                Delta,
            ),
        )
        x1 = jnp.where(accept, x_prop, x)
        fx1 = jnp.where(accept, fx_prop, fx)
        gnorm = jnp.sqrt(_g(g, g))
        st = dict(
            k=s["k"] + 1, x=x1, fx=fx1, Delta=Delta_new,
            stop=gnorm < cfg.epsilon,
        )
        if collect_trace:
            # ls_evals records the inner truncated-CG iteration count —
            # the TR analog of line-search cost evaluations
            st["trace"] = write_trace(
                s["trace"], s["k"],
                cost=fx1,
                grad_norm=gnorm,
                step=jnp.sqrt(jnp.maximum(_g(eta, eta), 0.0)),
                ls_evals=cg_j.astype(fx1.dtype),
            )
        return st

    from sagecal_tpu.utils.platform import match_vma

    state0 = dict(k=jnp.asarray(0), x=x, fx=fx, Delta=Delta0,
                  stop=jnp.asarray(False))
    if collect_trace:
        state0["trace"] = trace0
    out = jax.lax.while_loop(tr_cond, tr_body, match_vma(state0, x))
    # guard: never return something worse than the input
    better = out["fx"] <= fx0
    xf = jnp.where(better, out["x"], x0)
    return xf, fx0, jnp.where(better, out["fx"], fx0), out.get("trace")


def _nsd_single(
    vis, coh, rowmask, ant_p, ant_q, x0, itmax, sqrt_w, itmax_dyn=None,
    admm=None, collect_trace: bool = False,
):
    """Nesterov accelerated manifold descent
    (nsd_solve_nocuda_robust, rtr_solve_robust.c:1878-2090).
    ``itmax_dyn``: traced bound, effective limit min(itmax, dyn+15)
    (the reference's this_itermax+15 call-site offset, lmfit.c:953).
    ``admm``: optional (Yc, BZc, rho) consensus augmentation
    (nsd_solve_cuda_robust_admm_fl's CPU analog)."""
    N = x0.shape[0]
    cost_c, grad_fn, hess_fn = _make_fns(
        vis, coh, rowmask, ant_p, ant_q, sqrt_w, admm
    )
    iw = _station_iw(rowmask, ant_p, ant_q, N)
    bound = (
        jnp.asarray(itmax)
        if itmax_dyn is None
        else jnp.minimum(itmax, itmax_dyn + 15)
    )
    fx0 = cost_c(x0)

    g0 = grad_fn(x0, iw)
    h0 = hess_fn(x0, x0, iw)
    hnrm = jnp.sqrt(jnp.sum(jnp.abs(h0) ** 2))
    t0 = jnp.maximum(1.0 / jnp.where(hnrm == 0.0, 1e30, hnrm), 1e-6)

    def body(carry, i):
        x, z, g, t, theta, done = carry
        done = done | (i >= bound)
        active = ~done
        x_prop = x
        z_prop = z
        x1 = z - t * g
        gn = jnp.sqrt(jnp.sum(jnp.abs(g) ** 2))
        xn = jnp.sqrt(jnp.sum(jnp.abs(x1) ** 2))
        done1 = done | (gn * t / jnp.maximum(1.0, xn) < 1e-6)
        theta1 = 2.0 / (1.0 + jnp.sqrt(1.0 + 4.0 / (theta * theta)))
        z1 = (2.0 - theta1) * x1 - (1.0 - theta1) * x_prop
        g_old = g
        g1 = grad_fn(z1, iw)
        ydiff = z_prop - z1
        gdiff = g_old - g1
        ydn = jnp.sqrt(jnp.sum(jnp.abs(ydiff) ** 2))
        dot = jnp.sum(
            jnp.real(ydiff) * jnp.real(gdiff) + jnp.imag(ydiff) * jnp.imag(gdiff)
        )
        bad = jnp.isnan(dot) | jnp.isinf(dot)
        t_hat = 0.5 * ydn * ydn / jnp.maximum(jnp.abs(dot), 1e-30)
        t1 = jnp.minimum(1.01 * t, jnp.maximum(0.5 * t, t_hat))
        done2 = done1 | bad
        keep = lambda a, b: jnp.where(done2, a, b)
        carry1 = (
            keep(x, x1), keep(z, z1), keep(g, g1), keep(t, t1),
            keep(theta, theta1), done2,
        )
        if not collect_trace:
            return carry1, None
        # per-iteration telemetry costs one extra cost eval per step —
        # paid only in collect_trace builds (static gate)
        nanv = jnp.asarray(jnp.nan, t.dtype)
        mark = lambda v: jnp.where(active, v, nanv)
        return carry1, (mark(cost_c(carry1[0])), mark(gn), mark(t))

    from sagecal_tpu.utils.platform import match_vma

    (x, _, _, _, _, _), ys = jax.lax.scan(
        body,
        match_vma(
            (x0, x0, g0, t0, jnp.asarray(1.0, t0.dtype), jnp.asarray(False)),
            x0,
        ),
        jnp.arange(itmax),
    )
    if collect_trace:
        from sagecal_tpu.obs.records import IterTrace

        costs, gns, ts = ys
        trace = IterTrace(
            cost=costs, grad_norm=gns, step=ts,
            ls_evals=jnp.zeros_like(costs),
            nu=jnp.full((itmax,), jnp.nan, costs.dtype),
        )
    else:
        trace = None
    fx = cost_c(x)
    better = fx <= fx0
    return jnp.where(better, x, x0), fx0, jnp.where(better, fx, fx0), trace


# ---------------------------------------------------------------------------
# public, chunk-batched entry points
# ---------------------------------------------------------------------------

def _quality_of(p, vis, coh, mask, ant_p, ant_q, chunk_map,
                sqrt_w=None, nu=None):
    """Quality bundle (ops/quality.py) at the final solution ``p``.

    Uses the LM residual path (lm._residual_flat) — for rows of chunk c
    it evaluates the same J_p C J_q^H model with chunk c's parameters the
    per-lane RTR cost uses, so ``chi2_chunk`` equals the solver's final
    per-chunk DATA cost exactly.  ADMM consensus terms are excluded
    (``RTRResult.cost`` includes them when ``admm_*`` is given)."""
    from sagecal_tpu.ops.quality import residual_quality
    from sagecal_tpu.solvers.lm import _residual_flat

    e = _residual_flat(p, coh, vis, mask, ant_p, ant_q, chunk_map, sqrt_w)
    return residual_quality(
        e, p, ant_p, ant_q, chunk_map, p.shape[0],
        nu=nu, sqrt_w=sqrt_w, mask8=mask[..., None, :],
        weight_dof=2.0,  # RTR robust weights are (nu+2)/(nu+e^2)
    )


def _chunked(solver):
    def run(
        vis, coh, mask, ant_p, ant_q, chunk_map, p0, *args,
        admm_y=None, admm_bz=None, admm_rho=None, **kwargs,
    ):
        nchunk = p0.shape[0]
        x0 = params_to_jones(p0)  # (nchunk, N, 2, 2)

        if admm_y is not None:
            # param-space duals/targets -> complex Jones stacks; the real
            # dot y.(p-bz) equals Re tr(Y^H (X-BZ)) elementwise
            Yc = params_to_jones(admm_y)  # (nchunk, N, 2, 2)
            BZc = params_to_jones(admm_bz)
            rho = jnp.broadcast_to(
                jnp.asarray(admm_rho, p0.dtype), (nchunk,)
            )

            def lane(c, x0_c, y_c, bz_c, r_c):
                rowmask = mask * (chunk_map == c)[None, :].astype(mask.dtype)
                return solver(
                    vis, coh, rowmask, ant_p, ant_q, x0_c, *args,
                    admm=(y_c, bz_c, r_c), **kwargs,
                )

            xf, c0, c1, tr = jax.vmap(lane)(jnp.arange(nchunk), x0, Yc, BZc, rho)
        else:

            def lane(c, x0_c):
                rowmask = mask * (chunk_map == c)[None, :].astype(mask.dtype)
                return solver(
                    vis, coh, rowmask, ant_p, ant_q, x0_c, *args, **kwargs
                )

            xf, c0, c1, tr = jax.vmap(lane)(jnp.arange(nchunk), x0)
        if tr is not None:
            # vmapped per-lane traces are (nchunk, itmax); present them
            # iteration-major like the LM trace: (itmax, nchunk)
            tr = jax.tree_util.tree_map(lambda a: jnp.moveaxis(a, 0, 1), tr)
        return RTRResult(p=jones_to_params(xf), cost0=c0, cost=c1, trace=tr)

    return run


@true_f32
def rtr_solve(
    vis, coh, mask, ant_p, ant_q, chunk_map, p0,
    config: RTRConfig = RTRConfig(),
    sqrt_weights: Optional[jax.Array] = None,
    itmax_dynamic=None,
    admm_y=None, admm_bz=None, admm_rho=None,
    collect_trace: bool = False,
    collect_quality: bool = False,
) -> RTRResult:
    """Batched-over-chunks RTR solve (``rtr_solve_nocuda``, Dirac.h:1132).

    Args mirror :func:`sagecal_tpu.solvers.lm.lm_solve`; ``sqrt_weights``
    optional (F, 4, rows)-broadcastable robust sqrt-weights;
    ``itmax_dynamic`` optional traced per-call iteration budget (the
    SAGE driver's weighted allocation).  ``admm_y/admm_bz`` (nchunk, 8N)
    + scalar ``admm_rho`` switch on the consensus-augmented cost
    (``rtr_solve_nocuda_admm``/``..._robust_admm``, decl
    Dirac.h:1182-1195).  ``collect_quality`` statically enables the
    fixed-shape quality side outputs (:func:`_quality_of`; data term
    only under ADMM).
    """
    out = _chunked(_rtr_single)(
        vis, coh, mask, ant_p, ant_q, chunk_map, p0, config, sqrt_weights,
        itmax_dynamic, admm_y=admm_y, admm_bz=admm_bz, admm_rho=admm_rho,
        collect_trace=collect_trace,
    )
    if collect_quality:
        out = out._replace(quality=_quality_of(
            out.p, vis, coh, mask, ant_p, ant_q, chunk_map,
            sqrt_w=sqrt_weights))
    return out


@true_f32
def nsd_solve(
    vis, coh, mask, ant_p, ant_q, chunk_map, p0,
    itmax: int = 10,
    sqrt_weights: Optional[jax.Array] = None,
    itmax_dynamic=None,
    admm_y=None, admm_bz=None, admm_rho=None,
    collect_trace: bool = False,
    collect_quality: bool = False,
) -> RTRResult:
    """Batched Nesterov steepest descent (``nsd_solve_nocuda_robust``,
    Dirac.h:1166); ADMM-augmented when ``admm_y/admm_bz/admm_rho`` given
    (``nsd_solve_nocuda_robust_admm``, decl Dirac.h:1207-1224).
    ``collect_quality`` as in :func:`rtr_solve`."""
    out = _chunked(_nsd_single)(
        vis, coh, mask, ant_p, ant_q, chunk_map, p0, itmax, sqrt_weights,
        itmax_dynamic, admm_y=admm_y, admm_bz=admm_bz, admm_rho=admm_rho,
        collect_trace=collect_trace,
    )
    if collect_quality:
        out = out._replace(quality=_quality_of(
            out.p, vis, coh, mask, ant_p, ant_q, chunk_map,
            sqrt_w=sqrt_weights))
    return out


def _robust_weights_and_nu(
    vis, coh, mask, ant_p, ant_q, chunk_map, p, nu, nulow, nuhigh
):
    """Per-baseline Student's-t weights w = (nu+2)/(nu + max_elem |e|^2)
    — the reference's LIVE variant using the max over the four complex
    residual elements with an AECM p=2 nu update
    (rtr_solve_robust.c:258, update_nu(...,2,...) at :374; the 8-variate
    sum form on :257 is commented out there)."""
    from sagecal_tpu.core.types import corrupt_flat, params_to_jones as _p2j
    from sagecal_tpu.solvers.robust import update_nu_aecm

    x = _p2j(p)  # (nchunk, N, 2, 2)
    model = corrupt_flat(x, coh, ant_p, ant_q, chunk_map)
    res = (vis - model) * mask[..., None, :]
    e2 = jnp.max(
        jnp.real(res) ** 2 + jnp.imag(res) ** 2, axis=-2
    )  # (F, rows): max over the 4 complex elements
    w = (nu + 2.0) / (nu + e2)
    w = jnp.where(mask > 0, w, 1.0)
    msum = jnp.maximum(jnp.sum(mask), 1.0)
    logsumw = jnp.sum((jnp.log(w) - w) * mask) / msum
    nu1 = update_nu_aecm(logsumw, nu, p=2, nulow=nulow, nuhigh=nuhigh)
    return jnp.sqrt(w)[..., None, :], nu1


@true_f32
def rtr_solve_robust(
    vis, coh, mask, ant_p, ant_q, chunk_map, p0,
    config: RTRConfig = RTRConfig(),
    nu0=2.0, nulow: float = 2.0, nuhigh: float = 30.0,
    em_iters: int = 2,
    itmax_dynamic=None,
    admm_y=None, admm_bz=None, admm_rho=None,
    collect_trace: bool = False,
    collect_quality: bool = False,
):
    """Student's-t EM wrapping RTR (``rtr_solve_nocuda_robust``,
    Dirac.h:1145): E-step per-baseline weights (see
    :func:`_robust_weights_and_nu`), M-step a weighted RTR solve.
    ``nu0`` may be a traced value (the SAGE driver carries nu across EM
    passes, lmfit.c:940-947).  With ``admm_*`` given this is
    ``rtr_solve_nocuda_robust_admm`` (rtr_solve_robust_admm.c:1427),
    the reference MPI slave's default local solver.
    Returns (RTRResult, nu).

    ``collect_quality`` fills the result's quality slot with the chi^2
    attribution and weight statistics of the FINAL post-loop weight
    re-estimate (the same weights the returned nu is estimated from) —
    the weighted objective at the converged solution, not the last EM
    stage's stale-weight cost."""

    def em(carry, _):
        p, nu = carry
        sqrt_w, nu1 = _robust_weights_and_nu(
            vis, coh, mask, ant_p, ant_q, chunk_map, p, nu, nulow, nuhigh
        )
        out = rtr_solve(
            vis, coh, mask, ant_p, ant_q, chunk_map, p, config,
            sqrt_weights=sqrt_w, itmax_dynamic=itmax_dynamic,
            admm_y=admm_y, admm_bz=admm_bz, admm_rho=admm_rho,
            collect_trace=collect_trace,
        )
        ys = (out.cost0, out.cost)
        if collect_trace:
            tr = out.trace._replace(
                nu=jnp.broadcast_to(nu1, out.trace.nu.shape).astype(
                    out.trace.nu.dtype)
            )
            ys = ys + (tr,)
        return (out.p, nu1), ys

    from sagecal_tpu.utils.platform import match_vma

    (p, nu), ys = jax.lax.scan(
        em, match_vma((p0, jnp.asarray(nu0, p0.dtype)), p0), None,
        length=em_iters
    )
    c0s, c1s = ys[0], ys[1]
    trace = ys[2] if collect_trace else None  # (em_iters, itmax, nchunk)
    # re-estimate nu from the FINAL solution (the reference updates the
    # weights/nu once more after the loop, rtr_solve_robust.c:1625)
    sqrt_w_f, nu = _robust_weights_and_nu(
        vis, coh, mask, ant_p, ant_q, chunk_map, p, nu, nulow, nuhigh
    )
    quality = None
    if collect_quality:
        quality = _quality_of(
            p, vis, coh, mask, ant_p, ant_q, chunk_map,
            sqrt_w=sqrt_w_f, nu=nu)
    return RTRResult(p=p, cost0=c0s[0], cost=c1s[-1], trace=trace,
                     quality=quality), nu


@true_f32
def nsd_solve_robust(
    vis, coh, mask, ant_p, ant_q, chunk_map, p0,
    itmax: int = 10,
    nu0=2.0, nulow: float = 2.0, nuhigh: float = 30.0,
    em_iters: int = 2,
    itmax_dynamic=None,
    admm_y=None, admm_bz=None, admm_rho=None,
    collect_trace: bool = False,
    collect_quality: bool = False,
):
    """Robust Nesterov descent (``nsd_solve_nocuda_robust``,
    rtr_solve_robust.c:1878): the same Student's-t EM around
    :func:`nsd_solve`, with nu re-estimated from the residual after each
    solve (rtr_solve_robust.c:2104-2105).  With ``admm_*`` given this is
    the NSD-ADMM local solver (``nsd_solve_nocuda_robust_admm``, decl
    Dirac.h:1207).  Returns (RTRResult, nu).  ``collect_quality`` as in
    :func:`rtr_solve_robust` (final-weight attribution)."""

    def em(carry, _):
        p, nu = carry
        sqrt_w, nu1 = _robust_weights_and_nu(
            vis, coh, mask, ant_p, ant_q, chunk_map, p, nu, nulow, nuhigh
        )
        out = nsd_solve(
            vis, coh, mask, ant_p, ant_q, chunk_map, p, itmax,
            sqrt_weights=sqrt_w, itmax_dynamic=itmax_dynamic,
            admm_y=admm_y, admm_bz=admm_bz, admm_rho=admm_rho,
            collect_trace=collect_trace,
        )
        ys = (out.cost0, out.cost)
        if collect_trace:
            tr = out.trace._replace(
                nu=jnp.broadcast_to(nu1, out.trace.nu.shape).astype(
                    out.trace.nu.dtype)
            )
            ys = ys + (tr,)
        return (out.p, nu1), ys

    from sagecal_tpu.utils.platform import match_vma

    (p, nu), ys = jax.lax.scan(
        em, match_vma((p0, jnp.asarray(nu0, p0.dtype)), p0), None,
        length=em_iters
    )
    c0s, c1s = ys[0], ys[1]
    trace = ys[2] if collect_trace else None  # (em_iters, itmax, nchunk)
    # final-solution nu re-estimate (rtr_solve_robust.c:2104)
    sqrt_w_f, nu = _robust_weights_and_nu(
        vis, coh, mask, ant_p, ant_q, chunk_map, p, nu, nulow, nuhigh
    )
    quality = None
    if collect_quality:
        quality = _quality_of(
            p, vis, coh, mask, ant_p, ant_q, chunk_map,
            sqrt_w=sqrt_w_f, nu=nu)
    return RTRResult(p=p, cost0=c0s[0], cost=c1s[-1], trace=trace,
                     quality=quality), nu


# jitted module entries with compile/recompile telemetry (obs/perf.py)
from sagecal_tpu.obs.perf import instrumented_jit  # noqa: E402

rtr_solve_jit = instrumented_jit(
    rtr_solve, name="rtr_solve",
    static_argnames=("collect_trace", "collect_quality"))
nsd_solve_jit = instrumented_jit(
    nsd_solve, name="nsd_solve",
    static_argnames=("itmax", "collect_trace", "collect_quality"))
rtr_solve_robust_jit = instrumented_jit(
    rtr_solve_robust, name="rtr_solve_robust",
    static_argnames=("em_iters", "collect_trace", "collect_quality"))
