"""SAGE/EM calibration driver: expectation over clusters, per-cluster solves.

Redesign of ``sagefit_visibilities`` (``/root/reference/src/lib/Dirac/
lmfit.c:777-1083``).  The EM structure is kept — clusters are solved
sequentially against the residual with all other cluster models removed
(the data dependency is fundamental to SAGE) — but it runs as a
``lax.scan`` over a *stacked, padded* cluster axis inside one jit: the
residual visibilities are the scan carry, the per-cluster LM/robust
solves are the lock-step batched solvers of :mod:`sagecal_tpu.solvers.lm`,
and hybrid time chunks are solved simultaneously (not looped as in
lmfit.c:897-967).  The reference's two-GPU cluster pipeline
(lmfit_cuda.c:451-551) has no analog because nothing here is
device-specific — XLA owns scheduling.

Reproduced reference behaviors:
- weighted LM-iteration allocation across clusters by previous cost
  reduction, alternating with equal allocation when ``randomize`` is on
  (lmfit.c:859-882, 986-1009): itermax becomes a traced per-cluster bound
  of the LM while_loop;
- robust solves only on the final EM iteration for the LM-family modes,
  with the mean Student's-t nu carried to the joint LBFGS
  (lmfit.c:915-935, 1011-1025);
- final joint LBFGS over all 8*N*Mt parameters, Gaussian
  ``sum(e^2)`` or robust ``sum(log(1+e^2/nu))`` cost
  (lbfgs_fit_wrapper / lbfgs_fit_robust_wrapper; robust_lbfgs.c:61-76),
  with gradients by autodiff instead of the hand-written threaded
  gradient (robust_lbfgs.c:155+);
- res_0/res_1 = ||data - full model|| / n bookkeeping and the
  "worse-than-initial" signal (lmfit.c:1049-1052, return -1).

Solver modes mirror Dirac.h:1607-1613.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from sagecal_tpu.core.types import VisData, corrupt_flat, params_to_jones
from sagecal_tpu.obs.perf import instrumented_jit
from sagecal_tpu.ops.rime import SourceBatch, predict_coherencies
from sagecal_tpu.solvers.lbfgs import lbfgs_fit
from sagecal_tpu.solvers.lm import LMConfig, lm_solve, os_lm_solve
from sagecal_tpu.solvers.robust import robust_lm_solve
from sagecal_tpu.utils.precision import true_f32

# solver modes (values match Dirac.h:1607-1613)
SM_OSLM_LBFGS = 0
SM_LM_LBFGS = 1
SM_RLM_RLBFGS = 2
SM_OSLM_OSRLM_RLBFGS = 3
SM_RTR_OSLM_LBFGS = 4
SM_RTR_OSRLM_RLBFGS = 5
SM_NSD_RLBFGS = 6

_ROBUST_MODES = (SM_RLM_RLBFGS, SM_OSLM_OSRLM_RLBFGS, SM_RTR_OSRLM_RLBFGS, SM_NSD_RLBFGS)


@struct.dataclass
class SageConfig:
    max_emiter: int = struct.field(pytree_node=False, default=3)
    max_iter: int = struct.field(pytree_node=False, default=10)
    max_lbfgs: int = struct.field(pytree_node=False, default=10)
    lbfgs_m: int = struct.field(pytree_node=False, default=7)
    solver_mode: int = struct.field(pytree_node=False, default=SM_LM_LBFGS)
    nulow: float = struct.field(pytree_node=False, default=2.0)
    nuhigh: float = struct.field(pytree_node=False, default=30.0)
    randomize: bool = struct.field(pytree_node=False, default=True)
    em_rounds_robust: int = struct.field(pytree_node=False, default=2)
    # Optional elementwise box bound |p_i| <= param_bound on the joint
    # LBFGS pass: 0 disables (plain LBFGS).  The reference ships the
    # same bounded optimizer as a public API (lbfgsb_fit, Dirac.h:1843;
    # demo test/Dirac/demo.c:90); bounding the solved gain parameters is
    # its natural calibration use (runaway-gain containment).
    param_bound: float = struct.field(pytree_node=False, default=0.0)
    # Route the joint-LBFGS cost through the fused Pallas RIME kernel
    # (ops/rime_kernel.py) — one pass over the coherency stack per
    # evaluation vs the XLA predict's multiple buffer-scale
    # intermediates.  f32 data only.
    use_fused_predict: bool = struct.field(pytree_node=False, default=False)
    # Coherency-stack storage dtype on the fused path: "f32" (default)
    # or "bf16" (halves the dominant HBM stream; the kernel upcasts at
    # the VMEM load and accumulates in f32 — ~3 significant digits of
    # coherency precision, a throughput knob validated by the quality
    # watchdog, NOT for the final 1e-6-bar solve).  Ignored on the XLA
    # path.
    coh_dtype: str = struct.field(pytree_node=False, default="f32")
    # Static ceiling multiplier for the weighted per-cluster iteration
    # allocation (lmfit.c:859-882): a high-error cluster may be granted up
    # to iter_budget_cap * max_iter iterations by the -R weighting.  The
    # reference has no static ceiling (this_itermax+5/+10/+15,
    # lmfit.c:936-953), but on TPU the RSD warmup is a static-length scan
    # and the TR/NSD loops carry compile-time bounds, so the ceiling is an
    # intentional compile-time/runtime tradeoff: raise it if profiling
    # shows clusters exhausting their dynamic budget.
    iter_budget_cap: int = struct.field(pytree_node=False, default=3)
    # Collect per-iteration solver telemetry (obs.records.IterTrace) from
    # every per-cluster solve and the joint LBFGS, returned in
    # SageResult.telemetry.  Static: off builds the exact same jaxpr as
    # before (telemetry slots are None = empty pytrees).
    collect_telemetry: bool = struct.field(pytree_node=False, default=False)
    # Collect fixed-shape solution-quality side outputs (ops/quality.py):
    # per-cluster SolveQuality from the FINAL EM pass's solves (leading
    # cluster axis) plus a whole-solution bundle at the returned
    # parameters, in SageResult.quality.  Same static-gate contract as
    # collect_telemetry: off builds the identical jaxpr.
    collect_quality: bool = struct.field(pytree_node=False, default=False)


class ClusterData(NamedTuple):
    """Stacked per-cluster arrays crossing into jit (all static shapes).

    ``coh`` uses the canonical flat layout (see
    :mod:`sagecal_tpu.core.types`): rows minor-most so the TPU (8, 128)
    tile pads only the rows tail — the trailing-2x2 layout of round 2
    measured a 64x padding blow-up (726 MB logical -> 46.47 GB
    allocation) at the 62-station/100-cluster shape.
    """

    coh: jax.Array  # (M, F, 4, rows) complex cluster coherencies
    chunk_map: jax.Array  # (M, rows) int32 row -> hybrid chunk
    nchunk: jax.Array  # (M,) int32 actual chunk counts


class SageResult(NamedTuple):
    p: jax.Array  # (M, nchunk_max, 8N) solved parameters
    res_0: jax.Array  # initial residual norm / n
    res_1: jax.Array  # final residual norm / n
    mean_nu: jax.Array
    diverged: jax.Array  # bool, res_1 > res_0 (the reference's -1 return)
    # {"em": tuple of per-EM-pass IterTrace pytrees (leading cluster
    # axis), "lbfgs": joint-LBFGS IterTrace} when
    # config.collect_telemetry, else None (empty pytree — jitted output
    # signature unchanged)
    telemetry: Optional[dict] = None
    # {"em": SolveQuality stacked over clusters from the final EM pass,
    # "final": whole-solution SolveQuality (chi^2 attribution of the
    # full residual at the returned p + gain health)} when
    # config.collect_quality, else None (same empty-pytree contract)
    quality: Optional[dict] = None


def build_cluster_data(
    data: VisData, clusters: Sequence[SourceBatch], nchunks: Sequence[int],
    fdelta: Optional[float] = None,
    shapelets=None,
) -> ClusterData:
    """Precompute coherencies + chunk maps (host-side, once per tile).

    Equivalent of ``precalculate_coherencies`` for all clusters
    (predict.c:503; stored layout ``coh`` Dirac.h / fullbatch_mode.cpp:371).

    ``shapelets``: sky-global :class:`ShapeletTable` (from
    ``io.skymodel.load_sky``) for clusters containing ST_SHAPELET
    sources; those clusters take the per-cluster path.
    """
    if fdelta is None:
        fdelta = data.deltaf
    if shapelets is not None:
        from sagecal_tpu.ops.rime import ST_SHAPELET as _ST_SH

        shap_flags = [
            bool(np.any(np.asarray(c.stype) == _ST_SH)) for c in clusters
        ]
        if any(shap_flags):
            # Split: shapelet-containing clusters take the per-cluster
            # path (they need the mode table); everything else keeps the
            # batched path — one diffuse cluster must not collapse a
            # 100-cluster point sky back to 100 separate dispatches.
            plain_idx = [i for i, f in enumerate(shap_flags) if not f]
            shap_idx = [i for i, f in enumerate(shap_flags) if f]
            plain_cd = build_cluster_data(
                data, [clusters[i] for i in plain_idx],
                [nchunks[i] for i in plain_idx], fdelta,
            ) if plain_idx else None
            from sagecal_tpu.ops.rime import resolve_source_flags

            coh_parts = {}
            for i in shap_idx:
                has_ext, has_sh = resolve_source_flags(
                    clusters[i], shapelets)
                coh_parts[i] = predict_coherencies(
                    data.u, data.v, data.w, data.freqs, clusters[i],
                    fdelta, shapelets=shapelets,
                    has_extended=has_ext, has_shapelet=has_sh,
                )
            for j, i in enumerate(plain_idx):
                coh_parts[i] = plain_cd.coh[j]
            coh = jnp.stack([coh_parts[i] for i in range(len(clusters))])
            cmaps = []
            for nch in nchunks:
                tilechunk = -(-data.tilesz // nch)
                cmaps.append(jnp.minimum(
                    data.time_idx // tilechunk, nch - 1).astype(jnp.int32))
            return ClusterData(
                coh=coh,
                chunk_map=jnp.stack(cmaps),
                nchunk=jnp.asarray(list(nchunks), jnp.int32),
            )
    sizes = [int(c.ll.shape[0]) for c in clusters]
    smax, total = max(sizes), sum(sizes)
    if smax * len(clusters) <= 4 * total and len(clusters) > 1:
        # Batched path: pad every cluster to smax sources (zero-flux
        # no-op padding with pad_source_batch's f0>0 / shapelet_idx=-1
        # invariants) and evaluate clusters vmapped in BLOCKS instead
        # of M separate jit dispatches (measured: the per-cluster loop
        # dominated the app's "coherencies" phase at 100 clusters).
        # Blocking bounds the vmapped intermediates' memory at
        # BLOCK x the single-cluster working set.  Falls back to the
        # loop when padding would waste >4x the source count (heavily
        # skewed skies).  Source-type flags are computed HOST-side:
        # under vmap the stype tracer would defeat predict_coherencies'
        # point-source fast path and its shapelet guard.
        from sagecal_tpu.ops.rime import (
            ST_POINT, ST_SHAPELET, ShapeletTable, _predict_coherencies,
            pad_source_batch,
        )

        stypes = np.concatenate([np.asarray(c.stype) for c in clusters])
        if bool(np.any(stypes == ST_SHAPELET)):
            raise ValueError(
                "SourceBatch contains ST_SHAPELET sources but no "
                "ShapeletTable was supplied — they would silently "
                "predict as point sources"
            )
        has_ext = bool(np.any(stypes != ST_POINT))
        empty_tab = ShapeletTable.empty(data.u.dtype)

        # NOTE: a fresh wrapper per build_cluster_data call — the shared
        # "coherency_block" perf name aggregates them, so per-tile
        # retraces of this closure show up as a growing compile count
        @instrumented_jit(name="coherency_block")
        def _block(u, v, w, freqs, stacked):
            return jax.vmap(
                lambda s: _predict_coherencies(
                    u, v, w, freqs, s, empty_tab, float(fdelta), 32,
                    has_ext, False, 0.0, 0.0,
                )
            )(stacked)

        BLOCK = 16
        padded = [pad_source_batch(c, smax) for c in clusters]
        parts = []
        for i in range(0, len(padded), BLOCK):
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *padded[i:i + BLOCK]
            )
            parts.append(
                _block(data.u, data.v, data.w, data.freqs, stacked)
            )
        coh = jnp.concatenate(parts, axis=0)
    else:
        from sagecal_tpu.ops.rime import resolve_source_flags

        flags = [resolve_source_flags(src, shapelets) for src in clusters]
        coh = jnp.stack([
            predict_coherencies(data.u, data.v, data.w, data.freqs, src,
                                fdelta, shapelets=shapelets,
                                has_extended=he, has_shapelet=hs)
            for src, (he, hs) in zip(clusters, flags)
        ])
    cmaps = []
    for nch in nchunks:
        tilechunk = -(-data.tilesz // nch)  # ceil
        cmaps.append(
            jnp.minimum(data.time_idx // tilechunk, nch - 1).astype(jnp.int32)
        )
    return ClusterData(
        coh=coh,
        chunk_map=jnp.stack(cmaps),
        nchunk=jnp.asarray(list(nchunks), jnp.int32),
    )


def build_cluster_data_withbeam(
    data: VisData,
    clusters: Sequence[SourceBatch],
    nchunks: Sequence[int],
    geom,
    pointing,
    coeff,
    beam_mode: int,
    time_jd,
    ra0: float,
    dec0: float,
    fdelta: Optional[float] = None,
    wideband: bool = False,
    shapelets=None,
    precess: bool = True,
) -> ClusterData:
    """Beam-aware tile precompute: per cluster, evaluate the station beam
    toward each source and fold it into the coherencies
    (``precalculate_coherencies_withbeam``, predict_withbeam.c:552; the
    per-source/station/time/freq beam precompute of :487-510).

    ``geom``/``pointing``/``coeff``: see :mod:`sagecal_tpu.ops.beam`;
    ``time_jd``: (tilesz,) Julian dates of the tile's timeslots; source
    (ra, dec) are recovered from the batches' direction cosines about
    (ra0, dec0).

    ``precess``: precess source and pointing directions from J2000 to
    the tile's mid-time epoch before the az/el conversion — the app's
    ``precess_source_locations`` step (fullbatch_mode.cpp:335-338,
    data.cpp:1616-1645; skipped for the lunar ALO element, matching
    ``beam.elType!=ELEM_ALO``)."""
    from sagecal_tpu.ops.beam import beam_jones, predict_coherencies_withbeam
    from sagecal_tpu.ops.transforms import (
        get_precession_params, lmn_to_radec, precess_radec_equatorial,
    )

    if fdelta is None:
        fdelta = data.deltaf
    Tr = None
    if precess:
        jd = np.asarray(time_jd)
        Tr = get_precession_params(float(jd[len(jd) // 2]))
        pra, pdec = precess_radec_equatorial(pointing.ra0, pointing.dec0, Tr)
        bra, bdec = precess_radec_equatorial(
            pointing.b_ra0, pointing.b_dec0, Tr
        )
        pointing = pointing._replace(
            ra0=float(pra), dec0=float(pdec),
            b_ra0=float(bra), b_dec0=float(bdec),
        )
    cohs = []
    cmaps = []
    for src, nch in zip(clusters, nchunks):
        ra, dec = lmn_to_radec(np.asarray(src.ll), np.asarray(src.mm), ra0, dec0)
        if Tr is not None:
            ra, dec = precess_radec_equatorial(ra, dec, Tr)
        B = beam_jones(
            geom, pointing, coeff, ra, dec, np.asarray(time_jd),
            jnp.asarray(data.freqs), mode=beam_mode, wideband=wideband,
        ).astype(data.vis.dtype)
        cohs.append(
            predict_coherencies_withbeam(
                data.u, data.v, data.w, data.freqs, src, B,
                data.time_idx, data.ant_p, data.ant_q, fdelta,
                shapelets=shapelets,
            )
        )
        tilechunk = -(-data.tilesz // nch)
        cmap = jnp.minimum(data.time_idx // tilechunk, nch - 1).astype(jnp.int32)
        cmaps.append(cmap)
    return ClusterData(
        coh=jnp.stack(cohs),
        chunk_map=jnp.stack(cmaps),
        nchunk=jnp.asarray(list(nchunks), jnp.int32),
    )


def cluster_model(p_k, coh_k, cmap_k, ant_p, ant_q):
    """One cluster's corrupted model J_p C J_q^H: flat (F, 4, rows).

    p_k: (nchunk, 8N); coh_k: (F, 4, rows); cmap_k: (rows,)."""
    return corrupt_flat(params_to_jones(p_k), coh_k, ant_p, ant_q, cmap_k)


def predict_full_model(p_all, cdata: ClusterData, data: VisData):
    """sum_k J C J^H over all clusters (``minimize_viz_full_pth``,
    lmfit.c:692), flat (F, 4, rows).

    TPU-first formulation: instead of a sequential ``lax.scan`` over
    clusters, every per-cluster/per-row gain component is broadcast into
    an (M, rows) array by a one-hot station MATMUL (MXU work; an XLA
    gather here measured ~100 ms/op with a far worse scatter transpose
    in the backward pass), and the sum over clusters becomes sixteen
    fused multiply-reduce contractions ``einsum("kr,kfr->fr")`` — fully
    parallel over clusters, no 100-step sequential dependency in the
    joint-LBFGS gradient (the reference's threaded equivalent is
    minimize_viz_full_pth + the robust_lbfgs.c:155 gradient loops).
    """
    jones = params_to_jones(p_all)  # (M, nchunk, N, 2, 2)
    M, nchunk, N = jones.shape[0], jones.shape[1], jones.shape[2]
    cmap = cdata.chunk_map  # (M, rows)
    rdt = jnp.real(jones).dtype
    # components row-major: (M, nchunk, N, 4) -> (M*nchunk*4, N)
    tab = jnp.moveaxis(jones.reshape(M * nchunk, N, 4), 1, 2).reshape(
        M * nchunk * 4, N
    )

    def gains(ant):
        """All 4 components for every (cluster, row): 4x (M, rows)."""
        oh = (ant[None, :] == jnp.arange(N, dtype=ant.dtype)[:, None]).astype(rdt)
        v = jax.lax.complex(jnp.real(tab) @ oh, jnp.imag(tab) @ oh)
        v = v.reshape(M, nchunk, 4, -1)  # (M, nchunk, 4, rows)
        if nchunk == 1:
            g = v[:, 0]
        else:
            sel = jax.nn.one_hot(cmap, nchunk, axis=1, dtype=rdt)  # (M, nchunk, rows)
            g = jnp.einsum("mcr,mcir->mir", sel, v)
        return g[:, 0], g[:, 1], g[:, 2], g[:, 3]

    pa, pb, pc, pd = gains(data.ant_p)
    qa, qb, qc, qd = gains(data.ant_q)
    qa, qb, qc, qd = jnp.conj(qa), jnp.conj(qb), jnp.conj(qc), jnp.conj(qd)
    c00 = cdata.coh[:, :, 0, :]  # (M, F, rows)
    c01 = cdata.coh[:, :, 1, :]
    c10 = cdata.coh[:, :, 2, :]
    c11 = cdata.coh[:, :, 3, :]

    def contract(coef, w):
        # (M, rows) x (M, F, rows) -> (F, rows), reduced over clusters
        return jnp.einsum("kr,kfr->fr", coef, w)

    # V = J_p (C J_q^H) factored in two stages: W_aj = sum_b C_ab qconj_jb
    # reads the coherency stack ONCE (the 16-term single-stage expansion
    # re-read each C component four times — ~2x the HBM traffic of this
    # form, measured on chip), then V_ij = sum_ma Jp_ia W_aj.
    q = lambda g: g[:, None, :]  # (M, rows) -> (M, 1, rows) vs (M, F, rows)
    w00 = c00 * q(qa) + c01 * q(qb)
    w01 = c00 * q(qc) + c01 * q(qd)
    w10 = c10 * q(qa) + c11 * q(qb)
    w11 = c10 * q(qc) + c11 * q(qd)
    v00 = contract(pa, w00) + contract(pb, w10)
    v01 = contract(pa, w01) + contract(pb, w11)
    v10 = contract(pc, w00) + contract(pd, w10)
    v11 = contract(pc, w01) + contract(pd, w11)
    return jnp.stack([v00, v01, v10, v11], axis=-2)


def em_residual_scan(data: VisData, cdata: ClusterData, p_all, extras, solve_one,
                     cluster_slice=None):
    """One SAGE expectation pass: scan clusters with the residual as carry
    (the add-back / solve / subtract structure of lmfit.c:876-986).

    ``solve_one(xeff, coh_k, cmap_k, p_k, extras_k) -> (p_new_k, aux_k)``
    runs the per-cluster maximization against ``xeff`` = residual with
    this cluster's current model restored.  ``extras``: pytree of arrays
    with leading cluster axis (or None).  Returns (p_new (M,...), aux).

    ``cluster_slice``: optional ``(start, count)`` — solve only the
    ``count`` clusters beginning at (dynamic) index ``start``, holding
    the rest fixed.  The initial residual still subtracts the FULL model
    (fixed clusters stay subtracted throughout, exactly as if their
    scan steps ran with a no-op solver), so a sliced pass is the
    fine-grained consensus factor-node update of parallel/mesh.py:
    per-round work scales with ``count`` while the physics stays whole.
    """

    def cluster_step(xres, inp):
        coh_k, cmap_k, p_k, extras_k = inp
        model_old = cluster_model(p_k, coh_k, cmap_k, data.ant_p, data.ant_q)
        xeff = xres + model_old
        p_new, aux = solve_one(xeff, coh_k, cmap_k, p_k, extras_k)
        model_new = cluster_model(p_new, coh_k, cmap_k, data.ant_p, data.ant_q)
        return xeff - model_new, (p_new, aux)

    xres0 = data.vis - predict_full_model(p_all, cdata, data)
    xs = (cdata.coh, cdata.chunk_map, p_all, extras)
    if cluster_slice is not None:
        start, count = cluster_slice
        xs = jax.tree_util.tree_map(
            lambda x: jax.lax.dynamic_slice_in_dim(x, start, count, axis=0),
            xs,
        )
    _, (p_new, aux) = jax.lax.scan(cluster_step, xres0, xs)
    if cluster_slice is not None:
        p_new = jax.lax.dynamic_update_slice_in_dim(
            p_all, p_new, cluster_slice[0], axis=0
        )
    return p_new, aux


def _res_norm(res, mask, nreal):
    # res flat (..., F, 4, rows); mask (..., F, rows)
    r = res * mask[..., None, :]
    return jnp.sqrt(jnp.sum(jnp.abs(r) ** 2)) / nreal


def _make_fused_joint_cost(data, cdata, M, nchunk_max, n8, robust, mean_nu,
                           coh_dtype="f32"):
    """Joint-LBFGS cost through the fused OBJECTIVE kernel
    (ops/rime_kernel.py): predict, masked residual, Student's-t (or
    Gaussian) weighting and the scalar reduction all happen in ONE pass
    over the coherency stack — neither the model nor the residual ever
    round-trips HBM, forward or backward.  The packed/padded arrays are
    built ONCE here (they are constants of the LBFGS loop).  f32 only:
    the kernel computes in float32.  ``coh_dtype="bf16"`` stores the
    coherency stack as bfloat16 (halved HBM stream, f32 accumulation —
    SageConfig.coh_dtype rationale)."""
    from sagecal_tpu.ops.rime_kernel import (
        FULL_CLUSTER_TILE, MAX_GRID_ROWS, fused_cost_packed_chunked,
        fused_cost_packed_hybrid_chunked, pack_gain_tables,
        pack_predict_inputs, pad_to,
    )

    if jnp.real(data.vis).dtype != jnp.float32:
        raise ValueError(
            "use_fused_predict requires float32 data (the Pallas kernel "
            "computes in f32); run with f64 disabled or use the XLA path"
        )
    if coh_dtype not in ("f32", "bf16"):
        raise ValueError(f"coh_dtype must be 'f32' or 'bf16', got "
                         f"{coh_dtype!r}")
    # FULL_CLUSTER_TILE (128) is the largest tile whose BACKWARD kernel
    # fits the v5e 16 MB scoped-VMEM limit at ~100 clusters, and rows
    # are chunked so each Mosaic grid stays short — the hardware-proven
    # production configuration (PERF.md).
    mp = pad_to(M, 8)
    vis_ri, mask_p, coh_ri, antp, antq, cmap = pack_predict_inputs(
        data.vis, data.mask, cdata.coh, data.ant_p, data.ant_q,
        cdata.chunk_map if nchunk_max > 1 else None, FULL_CLUSTER_TILE,
        max_rows=MAX_GRID_ROWS,
    )
    if coh_dtype == "bf16":
        coh_ri = coh_ri.astype(jnp.bfloat16)
    coh_c = jax.lax.stop_gradient(coh_ri)
    nu_c = mean_nu if robust else None

    def cost_fn(pflat):
        jones = params_to_jones(
            pflat.reshape(M, nchunk_max, n8).astype(jnp.float32)
        )  # (M, nchunk, N, 2, 2)
        if nchunk_max > 1:
            tre, tim = pack_gain_tables(jones, mp)
            return fused_cost_packed_hybrid_chunked(
                tre, tim, coh_c, antp, antq, vis_ri, mask_p, cmap,
                nchunk_max, nu_c, FULL_CLUSTER_TILE, MAX_GRID_ROWS,
            )
        tre, tim = pack_gain_tables(jones[:, 0], mp)
        return fused_cost_packed_chunked(
            tre, tim, coh_c, antp, antq, vis_ri, mask_p, nu_c,
            FULL_CLUSTER_TILE, MAX_GRID_ROWS,
        )

    return cost_fn


def _make_fused_joint_cost_batch(data, cdata, B, M, n8, robust, mean_nu_b,
                                 coh_dtype="f32", valid=None):
    """Batched joint-LBFGS cost: the fused objective for B lanes in ONE
    Pallas grid (``ops.rime_kernel.fused_cost_packed_batch``), the lane
    axis folded into the MXU contraction.  ``data``/``cdata`` leaves
    carry a leading batch axis; all lanes must share ``ant_p``/``ant_q``
    (checked host-side by the router) — the kernel reads lane 0's copy.
    ``mean_nu_b``: (B,) per-lane Student's-t nu (traced; EM refinements
    never recompile).  ``valid``: optional (B,) lane mask zeroing padded
    lanes' cost and cotangent (pack_cost_inputs_batch docstring).
    nchunk_max == 1 only; f32 data only; ``coh_dtype="bf16"`` halves the
    dominant coherency HBM stream with f32 accumulation."""
    from sagecal_tpu.ops.rime_kernel import (
        FULL_CLUSTER_TILE, MAX_GRID_ROWS, fused_cost_packed_batch,
        pack_cost_inputs_batch, pack_gain_tables_batch, pad_to,
    )

    if jnp.real(data.vis).dtype != jnp.float32:
        raise ValueError(
            "the batched fused path requires float32 data (the Pallas "
            "kernel computes in f32); run with f64 disabled or use the "
            "XLA path"
        )
    if coh_dtype not in ("f32", "bf16"):
        raise ValueError(f"coh_dtype must be 'f32' or 'bf16', got "
                         f"{coh_dtype!r}")
    mp = pad_to(M, 8)
    vis_ri, mask_p, coh_ri, antp, antq = pack_cost_inputs_batch(
        data.vis, data.mask, cdata.coh, data.ant_p[0], data.ant_q[0],
        FULL_CLUSTER_TILE, max_rows=MAX_GRID_ROWS, valid=valid,
    )
    if coh_dtype == "bf16":
        coh_ri = coh_ri.astype(jnp.bfloat16)
    coh_c = jax.lax.stop_gradient(coh_ri)
    nu_c = mean_nu_b if robust else None

    def cost_fn(pflat_b):
        # (B, M*8N) -> (B,) per-lane costs, one grid for the whole batch
        jones = params_to_jones(
            pflat_b.reshape(B, M, n8).astype(jnp.float32)
        )  # (B, M, N, 2, 2)
        tre, tim = pack_gain_tables_batch(jones, mp)
        return fused_cost_packed_batch(
            tre, tim, coh_c, antp, antq, vis_ri, mask_p, nu_c,
            FULL_CLUSTER_TILE, MAX_GRID_ROWS,
        )

    return cost_fn


def _em_phase(
    data: VisData,
    cdata: ClusterData,
    p0: jax.Array,
    config: SageConfig,
    key: jax.Array,
):
    """The SAGE expectation passes of :func:`sagefit` — per-cluster
    solves and nu estimation, NO joint LBFGS and no finalization.
    Returns ``(p, mean_nu, res_0, em_traces, em_quality)``.  Factored
    out so :func:`sagefit_batched_fused` can vmap the per-cluster EM
    machinery per lane while replacing the joint-LBFGS phase with one
    batched fused kernel loop."""
    M = cdata.coh.shape[0]
    F, rows = data.vis.shape[-3], data.vis.shape[-1]
    nreal = rows * F * 8
    mode = config.solver_mode
    robust = mode in _ROBUST_MODES

    lmcfg = LMConfig(itmax=config.max_iter)
    total_iter = M * config.max_iter
    iter_bar = int(math.ceil((0.80 / M) * total_iter))

    full0 = predict_full_model(p0, cdata, data)
    res_vis0 = data.vis - full0
    res_0 = _res_norm(res_vis0, data.mask, nreal)

    def _nerr_of(res):
        # relative cost decrease -> iteration weighting (lmfit.c:971-979)
        c0 = jnp.sum(res.cost0)
        c1 = jnp.sum(res.cost)
        return jnp.where(c0 > 0.0, jnp.maximum((c0 - c1) / c0, 0.0), 0.0)

    collect = config.collect_telemetry
    collect_q = config.collect_quality

    def em_iteration(p_all, nerr, nus_in, weighted, em_idx, key):
        """One EM pass over clusters via :func:`em_residual_scan`."""
        last_em = em_idx == config.max_emiter - 1
        # quality side outputs only on the final pass: earlier iterates
        # are discarded, so attributing them would just burn reductions
        want_q = collect_q and last_em

        def _aux_of(res, nu_k):
            aux = (_nerr_of(res), nu_k)
            if collect:
                aux = aux + (res.trace,)
            if want_q:
                aux = aux + (res.quality,)
            return aux

        use_robust = robust and last_em
        # OS acceleration on non-final EM passes (lmfit.c:906-934)
        use_os = (
            mode in (SM_OSLM_LBFGS, SM_RLM_RLBFGS, SM_OSLM_OSRLM_RLBFGS)
            and not last_em
        )
        key, sub = jax.random.split(key)
        subkeys = jax.random.split(sub, M)

        def solve_one(xeff, coh_k, cmap_k, p_k, extras_k):
            nerr_k, key_k, nu_prev = extras_k
            itermax = jnp.where(
                weighted,
                (0.20 * nerr_k * total_iter).astype(jnp.int32) + iter_bar,
                config.max_iter,
            )
            # static ceilings sized from the max weighted budget the -R
            # allocation can grant (iter_budget_cap * max_iter), not bare
            # max_iter — otherwise the weighted-allocation feature would
            # no-op in RTR/NSD modes (see SageConfig.iter_budget_cap)
            iter_cap = config.max_iter * config.iter_budget_cap
            if mode == SM_RTR_OSLM_LBFGS:
                # RTR every EM pass, weighted budget (lmfit.c:936:
                # this_itermax+5 RSD, +10 TR)
                from sagecal_tpu.solvers.rtr import RTRConfig, rtr_solve

                res = rtr_solve(
                    xeff, coh_k, data.mask, data.ant_p, data.ant_q, cmap_k, p_k,
                    RTRConfig(itmax_rsd=iter_cap + 5,
                              itmax_rtr=iter_cap + 10),
                    itmax_dynamic=itermax,
                    collect_trace=collect, collect_quality=want_q,
                )
                return res.p, _aux_of(res, jnp.asarray(config.nulow, p_all.dtype))
            if mode == SM_RTR_OSRLM_RLBFGS:
                # nu carried across EM passes (lmfit.c:940-947 sets
                # robust_nu only at ci==0 and lets it persist)
                from sagecal_tpu.solvers.rtr import RTRConfig, rtr_solve_robust

                res, nu_k = rtr_solve_robust(
                    xeff, coh_k, data.mask, data.ant_p, data.ant_q, cmap_k, p_k,
                    RTRConfig(itmax_rsd=iter_cap + 5,
                              itmax_rtr=iter_cap + 10),
                    nu0=nu_prev, nulow=config.nulow, nuhigh=config.nuhigh,
                    em_iters=config.em_rounds_robust,
                    itmax_dynamic=itermax,
                    collect_trace=collect, collect_quality=want_q,
                )
                return res.p, _aux_of(res, nu_k.astype(p_all.dtype))
            if mode == SM_NSD_RLBFGS:
                # robust NSD with nu estimation (rtr_solve_robust.c:2104)
                from sagecal_tpu.solvers.rtr import nsd_solve_robust

                res, nu_k = nsd_solve_robust(
                    xeff, coh_k, data.mask, data.ant_p, data.ant_q, cmap_k, p_k,
                    itmax=iter_cap + 15,
                    nu0=nu_prev, nulow=config.nulow, nuhigh=config.nuhigh,
                    em_iters=config.em_rounds_robust,
                    itmax_dynamic=itermax,
                    collect_trace=collect, collect_quality=want_q,
                )
                return res.p, _aux_of(res, nu_k.astype(p_all.dtype))
            if use_robust:
                res, nu_k = robust_lm_solve(
                    xeff, coh_k, data.mask, data.ant_p, data.ant_q, cmap_k, p_k,
                    nu0=config.nulow, nulow=config.nulow, nuhigh=config.nuhigh,
                    em_iters=config.em_rounds_robust,
                    config=LMConfig(itmax=config.max_iter),
                    collect_trace=collect, collect_quality=want_q,
                )
            elif use_os:
                res = os_lm_solve(
                    xeff, coh_k, data.mask, data.ant_p, data.ant_q, cmap_k, p_k,
                    lmcfg, nsubsets=2, key=key_k, collect_trace=collect,
                    collect_quality=want_q,
                )
                nu_k = jnp.asarray(config.nulow, p_all.dtype)
            else:
                res = lm_solve(
                    xeff, coh_k, data.mask, data.ant_p, data.ant_q, cmap_k, p_k,
                    lmcfg, itmax_dynamic=itermax, collect_trace=collect,
                    collect_quality=want_q,
                )
                nu_k = jnp.asarray(config.nulow, p_all.dtype)
            return res.p, _aux_of(res, nu_k)

        p_new, aux = em_residual_scan(
            data, cdata, p_all, (nerr, subkeys, nus_in), solve_one
        )
        nerr_new, nus = aux[0], aux[1]
        tr = aux[2] if collect else None  # IterTrace, leading cluster axis
        # SolveQuality with leading cluster axis on the final pass
        qual = aux[-1] if want_q else None
        total = jnp.sum(nerr_new)
        nerr_norm = jnp.where(total > 0.0, nerr_new / total, nerr_new)
        return p_new, nerr_norm, nus, key, tr, qual

    p = p0
    nerr = jnp.zeros((M,), p0.dtype)
    weighted = jnp.asarray(False)
    nus = jnp.full((M,), config.nulow, p0.dtype)
    em_traces = []
    em_quality = None
    for em in range(config.max_emiter):
        p, nerr, nus, key, tr, qual = em_iteration(
            p, nerr, nus, weighted, em, key)
        if collect:
            em_traces.append(tr)
        if qual is not None:
            em_quality = qual
        if config.randomize:
            weighted = ~weighted
    mean_nu = jnp.clip(jnp.mean(nus), config.nulow, config.nuhigh)
    return p, mean_nu, res_0, em_traces, em_quality


def _finalize(
    data: VisData,
    cdata: ClusterData,
    p: jax.Array,
    res_0: jax.Array,
    mean_nu: jax.Array,
    config: SageConfig,
    lbfgs_trace,
    em_traces,
    em_quality,
) -> SageResult:
    """Final full-model residual plus telemetry/quality bundling — the
    tail of :func:`sagefit` after the joint LBFGS, shared with the
    batched fused driver (vmapped per lane there)."""
    robust = config.solver_mode in _ROBUST_MODES
    collect = config.collect_telemetry
    collect_q = config.collect_quality
    F, rows = data.vis.shape[-3], data.vis.shape[-1]
    nreal = rows * F * 8
    n8 = p.shape[2]

    full1 = predict_full_model(p, cdata, data)
    res_1 = _res_norm(data.vis - full1, data.mask, nreal)
    telemetry = (
        {"em": tuple(em_traces), "lbfgs": lbfgs_trace} if collect else None
    )
    quality = None
    if collect_q:
        # whole-solution bundle: chi^2 of the FULL residual (all cluster
        # models subtracted) attributed per station/baseline, plus gain
        # health over every (cluster, chunk) lane.  No hybrid-chunk
        # structure exists for the joint residual, so chi2_chunk is the
        # single total.
        from sagecal_tpu.core.types import reals_of_flat
        from sagecal_tpu.ops.quality import (
            SolveQuality, chi2_scatter, gain_health, row_chi2,
        )

        e = reals_of_flat((data.vis - full1) * data.mask[..., None, :])
        row = row_chi2(e)
        chi2_st, chi2_bl, chi2_ch = chi2_scatter(
            row, data.ant_p, data.ant_q, jnp.zeros_like(data.ant_p),
            n8 // 8, 1,
        )
        nonfinite, amp, amp_sp, ph_sp, dep = gain_health(p)
        final_q = SolveQuality(
            chi2_station=chi2_st, chi2_baseline=chi2_bl,
            chi2_chunk=chi2_ch, nonfinite_count=nonfinite,
            station_amp=amp, station_amp_spread=amp_sp,
            station_phase_spread=ph_sp, identity_departure=dep,
            nu=mean_nu if robust else None,
        )
        quality = {"em": em_quality, "final": final_q}
    return SageResult(
        p=p, res_0=res_0, res_1=res_1, mean_nu=mean_nu,
        diverged=res_1 > res_0, telemetry=telemetry, quality=quality,
    )


@true_f32
def sagefit(
    data: VisData,
    cdata: ClusterData,
    p0: jax.Array,
    config: SageConfig = SageConfig(),
    key: Optional[jax.Array] = None,
) -> SageResult:
    """One tile's SAGE calibration.  ``p0``: (M, nchunk_max, 8N)."""
    if key is None:
        key = jax.random.PRNGKey(0)
    M = cdata.coh.shape[0]
    nchunk_max = p0.shape[1]
    n8 = p0.shape[2]
    robust = config.solver_mode in _ROBUST_MODES
    collect = config.collect_telemetry

    p, mean_nu, res_0, em_traces, em_quality = _em_phase(
        data, cdata, p0, config, key)

    # ---- joint LBFGS over all parameters (lmfit.c:1019-1037) ----
    if config.max_lbfgs > 0:
        pflat0 = p.reshape(-1)

        if config.use_fused_predict:
            cost_fn = _make_fused_joint_cost(
                data, cdata, M, nchunk_max, n8, robust, mean_nu,
                config.coh_dtype,
            )
        else:

            def cost_fn(pflat):
                pa = pflat.reshape(M, nchunk_max, n8)
                model = predict_full_model(pa, cdata, data)
                diff = (data.vis - model) * data.mask[..., None, :]
                e2 = jnp.real(diff) ** 2 + jnp.imag(diff) ** 2
                if robust:
                    return jnp.sum(jnp.log1p(e2 / mean_nu))
                return jnp.sum(e2)

        if config.param_bound > 0.0:
            from sagecal_tpu.solvers.lbfgsb import lbfgsb_fit

            bnd = jnp.asarray(config.param_bound, pflat0.dtype)
            fitb = lbfgsb_fit(
                cost_fn, None, pflat0, lb=-bnd, ub=bnd,
                itmax=config.max_lbfgs, M=config.lbfgs_m,
            )
            p = fitb.p.reshape(M, nchunk_max, n8)
            lbfgs_trace = None  # bounded path not instrumented
        else:
            fit = lbfgs_fit(
                cost_fn, None, pflat0, itmax=config.max_lbfgs,
                M=config.lbfgs_m, collect_trace=collect,
            )
            p = fit.p.reshape(M, nchunk_max, n8)
            lbfgs_trace = fit.trace
    else:
        lbfgs_trace = None

    return _finalize(data, cdata, p, res_0, mean_nu, config, lbfgs_trace,
                     em_traces, em_quality)


@true_f32
def sagefit_batched_fused(
    data: VisData,
    cdata: ClusterData,
    p0: jax.Array,
    config: SageConfig = SageConfig(),
    keys: Optional[jax.Array] = None,
    valid: Optional[jax.Array] = None,
) -> SageResult:
    """B independent tile solves whose joint-LBFGS phase runs as ONE
    batched fused Pallas kernel loop instead of B vmapped solo solves.

    The EM phase (per-cluster LM/robust solves) is the existing
    machinery vmapped per lane (:func:`_em_phase`); the joint LBFGS —
    the hot loop that dominates serve latency — then advances all lanes
    in lock-step through :func:`sagecal_tpu.solvers.lbfgs.
    lbfgs_fit_batched`, so every cost/gradient evaluation is one
    ``fused_cost_packed_batch`` grid with the lane axis folded into the
    MXU contraction (ops/rime_kernel.py section comment).

    Layout contract (solvers/batched.py): every ``data``/``cdata`` leaf
    carries a leading batch axis B; all lanes share the SAME baseline
    geometry (``ant_p``/``ant_q`` — the serve bucket guarantees this,
    and :func:`sagecal_tpu.solvers.batched.choose_batched_path` checks
    it host-side before routing here); ``p0`` is (B, M, 1, 8N) —
    nchunk_max must be 1.  ``keys``: (B, 2) per-lane PRNG keys.
    ``valid``: optional (B,) lane mask — replication-padded lanes still
    run the EM phase on their (finite, replicated) data, but their mask
    plane is zeroed in the batched cost pack so they contribute exactly
    zero cost and zero cotangent to the LBFGS phase (the ragged-lane
    guard; their lanes go inert after the first iteration and the
    results are discarded host-side as before)."""
    B, M, nchunk_max, n8 = p0.shape
    if nchunk_max != 1:
        raise ValueError(
            "sagefit_batched_fused requires nchunk_max == 1 (the batched "
            "kernel has no hybrid-chunk selection); use the vmapped path"
        )
    if config.param_bound > 0.0 or config.collect_telemetry:
        raise ValueError(
            "batched fused path supports neither param_bound nor "
            "telemetry traces; use the vmapped path"
        )
    if keys is None:
        keys = jax.random.split(jax.random.PRNGKey(0), B)
    robust = config.solver_mode in _ROBUST_MODES

    # quality side outputs (collect_quality) vmap straight through —
    # only telemetry traces are excluded (guarded above)
    p_b, mean_nu_b, res_0_b, _, em_q = jax.vmap(
        lambda d, c, p, k: _em_phase(d, c, p, config, k)
    )(data, cdata, p0, keys)

    if config.max_lbfgs > 0:
        from sagecal_tpu.solvers.lbfgs import lbfgs_fit_batched

        cost_fn = _make_fused_joint_cost_batch(
            data, cdata, B, M, n8, robust, mean_nu_b, config.coh_dtype,
            valid,
        )
        fit = lbfgs_fit_batched(
            cost_fn, p_b.reshape(B, -1), itmax=config.max_lbfgs,
            M=config.lbfgs_m,
        )
        p_b = fit.p.reshape(B, M, nchunk_max, n8)

    return jax.vmap(
        lambda d, c, p, r0, mn, eq: _finalize(d, c, p, r0, mn, config,
                                              None, [], eq)
    )(data, cdata, p_b, res_0_b, mean_nu_b, em_q)


# ------------------------------------------------ packed device boundary


def sagefit_packed(
    data: VisData,
    cdata: ClusterData,
    vis_re: jax.Array,
    vis_im: jax.Array,
    coh_re: jax.Array,
    coh_im: jax.Array,
    p0: jax.Array,
    config: SageConfig = SageConfig(),
    key: Optional[jax.Array] = None,
) -> SageResult:
    """The whole tile solve behind a REAL-array jit boundary.

    ``sagefit`` is fully traceable, but its natural signature carries
    complex visibilities/coherencies — which cannot cross the axon TPU
    host<->device boundary (UNIMPLEMENTED; verify-skill gotcha 3).
    This wrapper takes ``data`` with ``vis=None`` and ``cdata`` with
    ``coh=None`` plus separate re/im leaves (``(F, 4, rows)`` /
    ``(M, F, 4, rows)``, rows minor-most so TPU tiling pads nothing)
    and rebuilds the complex arrays INSIDE the trace.  Every input and
    output leaf is real, so ``jax.jit(sagefit_packed)`` dispatches the
    full SAGE/EM tile solve — EM passes, per-cluster solvers, joint
    LBFGS, nu estimation — to the TPU as ONE program (also amortizing
    the ~65 ms axon dispatch floor once per tile; PERF.md).

    Matmul precision comes from the ``true_f32`` decorator on
    ``sagefit`` and every other solver entry (utils/precision.py)."""
    vis = jax.lax.complex(vis_re, vis_im)
    coh = jax.lax.complex(coh_re, coh_im)
    return sagefit(
        data.replace(vis=vis), cdata._replace(coh=coh), p0, config, key
    )


# instrumented jit (obs/perf.py): with SAGECAL_TELEMETRY=1 every new
# abstract input signature — a new tile shape or a changed static
# SageConfig — is visible as a recorded compile with lowering/compile
# wall-time and cost_analysis() flops/bytes; telemetry off is the plain
# jax.jit call.  ``p0`` (the tile's warm-start carry) is DONATED:
# solve_tile rebuilds it from numpy per call and the apps thread the
# RESULT p forward, never the input buffer (jaxlint JL007 convention).
_sagefit_packed_jit = instrumented_jit(sagefit_packed, name="sagefit_packed",
                                       donate_argnames=("p0",))


def solve_tile(
    data: VisData,
    cdata: ClusterData,
    p0: jax.Array,
    config: SageConfig = SageConfig(),
    key: Optional[jax.Array] = None,
    device=None,
) -> SageResult:
    """Host convenience around :func:`sagefit_packed`: splits re/im on
    the host (numpy views — no eager device ops and no concatenated
    double-size host buffer, safe under an axon default device) and
    dispatches the jitted packed solve.  Complex never crosses the
    boundary; on CPU this is the same math as ``sagefit``.

    ``device``: explicit target (e.g. the TPU chip while the rest of
    the pipeline runs host-side under a CPU default device — the
    fullbatch split).  Every input leaf is device_put there, including
    previously host-committed template arrays."""
    vis = np.asarray(data.vis)
    coh = np.asarray(cdata.coh)
    args = (data.replace(vis=None), cdata._replace(coh=None),
            vis.real, vis.imag, coh.real, coh.imag,
            np.asarray(p0), config, key)
    if device is not None:
        args = jax.device_put(args, device)
    return _sagefit_packed_jit(*args)
