"""Rows-sharded (data-parallel) joint calibration over a device mesh.

The reference never shards a single solve — one cluster solve always
fits one machine, and scale comes from tiling time and splitting
frequency (SURVEY §2.5).  On TPU the natural extra axis is the DATA
axis: visibility rows (baseline x time) shard across devices, the
per-shard robust cost and its gradient reduce with ``lax.psum``, and
the joint LBFGS iterates on replicated parameters — gradients are sums
over baselines (the structure the reference's ``mderiv.cu`` gradient
kernels exploit per-thread), so the collective is one scalar + one
(8*N*M,) vector per evaluation, riding ICI.

This is the TPU-native path to a SINGLE tile too large for one chip's
HBM (e.g. SKA-scale 512 stations x hundreds of clusters: the coherency
stack shards with the rows axis).

``shard_map`` with full varying-manual-axes checking; the LBFGS loop
runs replicated on every device (its work is O(M*8N) — negligible
against the sharded model evaluation).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from sagecal_tpu.utils.platform import shard_map

from sagecal_tpu.core.types import VisData
from sagecal_tpu.obs.perf import instrumented_jit
from sagecal_tpu.ops.quality import SolveQuality, chi2_scatter, gain_health
from sagecal_tpu.solvers.lbfgs import lbfgs_fit
from sagecal_tpu.solvers.sage import ClusterData, predict_full_model


def _row_spec(leaf, name: str, rows: int, axis_name: str):
    """PartitionSpec sharding the (minor-most) rows axis of a named
    per-row field.  Specs are built per FIELD NAME, never by matching
    dimension sizes — a non-row leaf whose last dim coincidentally
    equals the row count (e.g. ``nchunk`` of shape (M,) when M == rows)
    must stay replicated or the psum'd cost/grad would be wrong."""
    if leaf.shape[-1] != rows:
        raise ValueError(
            f"per-row field {name!r} must be rows-minor with "
            f"shape[-1]=={rows}, got {leaf.shape}"
        )
    return P(*([None] * (leaf.ndim - 1)), axis_name)


# The per-row fields of each container (rows minor-most, core/types.py).
# Single source of truth for both sharding specs and row padding.
_VIS_ROW_FIELDS = ("u", "v", "w", "ant_p", "ant_q", "vis", "mask",
                   "time_idx")
_CDATA_ROW_FIELDS = ("coh", "chunk_map")


def _build_specs(data: VisData, cdata: ClusterData, rows: int,
                 axis_name: str):
    """Spec pytrees for (VisData, ClusterData) with exactly the known
    per-row fields sharded (``_VIS_ROW_FIELDS`` / ``_CDATA_ROW_FIELDS``).
    freqs (F,) and nchunk (M,) stay replicated."""
    data_specs = data.replace(freqs=P(), **{
        f: _row_spec(getattr(data, f), f, rows, axis_name)
        for f in _VIS_ROW_FIELDS})
    cdata_specs = cdata._replace(nchunk=P(), **{
        f: _row_spec(getattr(cdata, f), f, rows, axis_name)
        for f in _CDATA_ROW_FIELDS})
    return data_specs, cdata_specs


def pad_rows_to(data: VisData, cdata: ClusterData, mult: int):
    """Pad the rows axis to a multiple of ``mult`` with masked rows
    (zero coherency, zero mask -> zero contribution everywhere)."""
    rows = data.vis.shape[-1]
    rowsp = -(-rows // mult) * mult
    pr = rowsp - rows
    if pr == 0:
        return data, cdata

    def pad_last(x):
        cfg = [(0, 0)] * (x.ndim - 1) + [(0, pr)]
        return jnp.pad(x, cfg)

    data = data.replace(**{
        f: pad_last(getattr(data, f)) for f in _VIS_ROW_FIELDS})
    cdata = cdata._replace(**{
        f: pad_last(getattr(cdata, f)) for f in _CDATA_ROW_FIELDS})
    return data, cdata


def make_sharded_joint_fn(
    data,
    cdata,
    p_shape: tuple,
    mesh: Mesh,
    axis_name: str = "rows",
    itmax: int = 30,
    lbfgs_m: int = 7,
    robust_nu: Optional[float] = None,
    collect_quality: bool = False,
):
    """Build the jitted rows-sharded joint-LBFGS program.

    ``data``/``cdata`` may be real arrays OR ``jax.ShapeDtypeStruct``
    pytrees (only shapes/dtypes are read here) — the latter enables AOT
    ``.lower().compile()`` at scale without materializing the arrays
    (the graded-config memory checks, tests/test_graded_shapes.py).
    Returns ``fn(data, cdata, p0) -> (p, cost, iterations)``, or
    ``(p, cost, iterations, quality)`` with ``collect_quality`` — a
    static build parameter, so the two variants are distinct programs
    and the disabled path's signature is untouched.  ``quality`` is an
    :class:`sagecal_tpu.ops.quality.SolveQuality` whose chi^2
    attribution uses the joint objective density (``e^2``, or
    ``log1p(e^2/nu)`` on the robust path) so the station/baseline sums
    and the total reproduce ``cost`` exactly; the per-shard scatters are
    psum'd across the mesh, the same one-collective-per-reduction
    pattern as the solve itself.
    """
    ndev = mesh.devices.size
    rows = data.vis.shape[-1]
    assert rows % ndev == 0, (rows, ndev)
    shp = tuple(p_shape)

    data_specs, cdata_specs = _build_specs(data, cdata, rows, axis_name)

    def local_fit(data_l, cdata_l, p0_l):
        def local_cost(pflat):
            pa = pflat.reshape(shp)
            model = predict_full_model(pa, cdata_l, data_l)
            diff = (data_l.vis - model) * data_l.mask[..., None, :]
            e2 = jnp.real(diff) ** 2 + jnp.imag(diff) ** 2
            if robust_nu is not None:
                return jnp.sum(jnp.log1p(e2 / robust_nu))
            return jnp.sum(e2)

        def cost_fn(pflat):
            return jax.lax.psum(local_cost(pflat), axis_name)

        # The gradient must be psum'd EXPLICITLY: differentiating through
        # a psum'd cost transposes the psum into a device-local
        # cotangent, so value_and_grad(cost_fn) would hand each device
        # only its own shard's gradient — per-device LBFGS trajectories
        # then diverge, and the data-dependent Armijo while_loop executes
        # different psum counts per device (an XLA collective-rendezvous
        # deadlock).  One psum of the (value, grad) tuple per evaluation
        # keeps every device on the identical global iterate.
        def vg_fn(pflat):
            return jax.lax.psum(
                jax.value_and_grad(local_cost)(pflat), axis_name
            )

        fit = lbfgs_fit(cost_fn, None, p0_l.reshape(-1), itmax=itmax,
                        M=lbfgs_m, vg_fn=vg_fn)
        pf = fit.p.reshape(shp)
        if not collect_quality:
            return pf, fit.cost, fit.iterations
        # objective density of the final iterate, scattered per station/
        # baseline on each shard's local rows, then psum'd — sums equal
        # fit.cost exactly (it is the same reduction, reassociated)
        model = predict_full_model(pf, cdata_l, data_l)
        diff = (data_l.vis - model) * data_l.mask[..., None, :]
        e2 = jnp.real(diff) ** 2 + jnp.imag(diff) ** 2
        dens = jnp.log1p(e2 / robust_nu) if robust_nu is not None else e2
        row = jnp.sum(dens, axis=(-3, -2))  # (rows_local,)
        n_st = shp[-1] // 8
        chi2_st, chi2_bl, chi2_tot = chi2_scatter(
            row, data_l.ant_p, data_l.ant_q,
            jnp.zeros_like(data_l.ant_p), n_st, 1,
        )
        chi2_st, chi2_bl, chi2_tot = jax.lax.psum(
            (chi2_st, chi2_bl, chi2_tot), axis_name
        )
        nonfinite, amp, amp_sp, ph_sp, dep = gain_health(pf)
        quality = SolveQuality(
            chi2_station=chi2_st, chi2_baseline=chi2_bl,
            chi2_chunk=chi2_tot, nonfinite_count=nonfinite,
            station_amp=amp, station_amp_spread=amp_sp,
            station_phase_spread=ph_sp, identity_departure=dep,
        )
        return pf, fit.cost, fit.iterations, quality

    out_specs = (P(), P(), P())
    if collect_quality:
        # replicated specs for exactly the fields local_fit fills; the
        # rest stay None (empty pytree) and need no spec
        out_specs = out_specs + (SolveQuality(
            chi2_station=P(), chi2_baseline=P(), chi2_chunk=P(),
            nonfinite_count=P(), station_amp=P(), station_amp_spread=P(),
            station_phase_spread=P(), identity_departure=P(),
        ),)
    fn = shard_map(
        local_fit,
        mesh=mesh,
        in_specs=(data_specs, cdata_specs, P()),
        out_specs=out_specs,
    )
    return instrumented_jit(fn, name="sharded_joint_fit")


def sharded_joint_fit(
    data: VisData,
    cdata: ClusterData,
    p0: jax.Array,
    mesh: Mesh,
    axis_name: str = "rows",
    itmax: int = 30,
    lbfgs_m: int = 7,
    robust_nu: Optional[float] = None,
    collect_quality: bool = False,
):
    """Joint LBFGS over all clusters with rows sharded over ``mesh``.

    ``p0``: (M, nchunk, 8N).  Returns (p, cost, iterations) with ``p``
    replicated — plus a psum'd :class:`SolveQuality` as a fourth element
    when ``collect_quality`` (see :func:`make_sharded_joint_fn`).  Rows
    must divide evenly by the mesh size — use :func:`pad_rows_to` first.
    """
    fn = make_sharded_joint_fn(
        data, cdata, p0.shape, mesh, axis_name=axis_name, itmax=itmax,
        lbfgs_m=lbfgs_m, robust_nu=robust_nu,
        collect_quality=collect_quality,
    )
    from sagecal_tpu.obs.trace import get_tracer

    tr = get_tracer()
    if not tr.enabled:
        return fn(data, cdata, p0)
    # host-side collective-section span around the dispatch (never
    # inside the jitted program).  Unlike the mesh ADMM there is no
    # prepare/solve pipeline to overlap here, so blocking inside the
    # span is safe and makes it cover real device wall-time.
    with tr.span("sharded_joint_fit", kind="collective",
                 ndev=int(mesh.devices.size),
                 rows=int(data.vis.shape[-1])):
        return jax.block_until_ready(fn(data, cdata, p0))
