"""Offline tools: restore (sky -> FITS image), buildsky (FITS image ->
sky model), uvwriter (lunar-frame UVW) — the reference's standalone
binaries (``/root/reference/src/restore``, ``src/buildsky``,
``src/uvwriter``)."""
