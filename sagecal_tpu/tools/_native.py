"""ctypes loader for the native tool core (``native/clusterlib.cpp``).

Builds the shared library on first use with the baked-in g++ (the
reference's equivalents are compiled C: the embedded C Clustering
Library and buildsky's island walks).  Falls back to pure numpy/scipy
implementations when no compiler is available, so the tools never hard-
fail.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    src = os.path.join(_repo_root(), "native", "clusterlib.cpp")
    so = os.path.join(_repo_root(), "native", "libsagecal_native.so")
    try:
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", so, src],
                check=True, capture_output=True, timeout=120,
            )
        lib = ctypes.CDLL(so)
        lib.label_islands.restype = ctypes.c_int
        lib.kmeans_weighted.restype = ctypes.c_int
        _LIB = lib
    except Exception:
        _LIB = None
    return _LIB


def label_islands(mask: np.ndarray) -> Tuple[np.ndarray, int]:
    """8-connected labeling: (labels int32 (ny, nx), count)."""
    mask8 = np.ascontiguousarray(mask.astype(np.int8))
    ny, nx = mask8.shape
    lib = _load()
    if lib is not None:
        labels = np.zeros((ny, nx), np.int32)
        n = lib.label_islands(
            mask8.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
            ctypes.c_int(ny), ctypes.c_int(nx),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return labels, int(n)
    # fallback: scipy 8-connected structure
    from scipy import ndimage

    labels, n = ndimage.label(mask8, structure=np.ones((3, 3), int))
    return labels.astype(np.int32), int(n)


def kmeans_weighted(
    x: np.ndarray, y: np.ndarray, w: Optional[np.ndarray], k: int,
    niter: int = 50, seed: int = 7,
) -> Tuple[np.ndarray, np.ndarray]:
    """Weighted 2-D k-means: (assignment (n,), centers (k, 2))."""
    x = np.ascontiguousarray(np.asarray(x, np.float64))
    y = np.ascontiguousarray(np.asarray(y, np.float64))
    n = x.shape[0]
    k = min(max(k, 1), max(n, 1))
    wv = (np.ascontiguousarray(np.asarray(w, np.float64))
          if w is not None else None)
    lib = _load()
    if lib is not None and n:
        assign = np.zeros((n,), np.int32)
        centers = np.zeros((k, 2), np.float64)
        pd = ctypes.POINTER(ctypes.c_double)
        lib.kmeans_weighted(
            x.ctypes.data_as(pd), y.ctypes.data_as(pd),
            wv.ctypes.data_as(pd) if wv is not None else None,
            ctypes.c_int(n), ctypes.c_int(k), ctypes.c_int(niter),
            ctypes.c_uint64(seed),
            assign.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            centers.ctypes.data_as(pd),
        )
        return assign, centers
    # numpy fallback: plain Lloyd with weighted centroids
    rng = np.random.default_rng(seed)
    idx = rng.choice(n, size=k, replace=False)
    cx, cy = x[idx].copy(), y[idx].copy()
    wv2 = wv if wv is not None else np.ones(n)
    assign = np.zeros(n, np.int32)
    for _ in range(niter):
        d2 = (x[:, None] - cx[None]) ** 2 + (y[:, None] - cy[None]) ** 2
        assign = np.argmin(d2, axis=1).astype(np.int32)
        for c in range(k):
            m = assign == c
            if np.any(m):
                cx[c] = np.average(x[m], weights=wv2[m])
                cy[c] = np.average(y[m], weights=wv2[m])
    return assign, np.stack([cx, cy], axis=1)
