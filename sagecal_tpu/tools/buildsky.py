"""``buildsky``: extract a sky model + cluster file from a FITS image.

Redesign of the reference's buildsky tool
(``/root/reference/src/buildsky/`` — island detection ``buildsky.c``,
multi-component LM fitting ``fitpixels.c``/``clmfit_nocuda.c``, model
selection by AIC/BIC/MDL ``main.c`` -a flag, weighted k-means sky
clustering ``scluster.c:675-941`` on the embedded C Clustering
Library): threshold the image against a robust noise estimate, label
islands (native 8-connected flood fill, ``native/clusterlib.cpp``),
fit 1..maxP elliptical-Gaussian components per island with
``scipy.optimize.least_squares``, pick the order by an information
criterion, and emit the LSM sky file plus a k-means cluster file.
"""

from __future__ import annotations

import argparse
import math
import sys
from typing import List, Tuple

import numpy as np

from sagecal_tpu.io.fits import read_fits_image
from sagecal_tpu.tools._native import kmeans_weighted, label_islands

_SIGMA_TO_FWHM = 2.0 * math.sqrt(2.0 * math.log(2.0))


def robust_noise(img: np.ndarray) -> float:
    """MAD-based noise sigma (buildsky's background estimate role)."""
    med = np.median(img)
    return 1.4826 * float(np.median(np.abs(img - med))) + 1e-30


def _gauss_model(params, px, py, ncomp):
    out = np.zeros_like(px, float)
    for c in range(ncomp):
        amp, x0, y0, sx, sy, pa = params[6 * c:6 * c + 6]
        ct, st = math.cos(pa), math.sin(pa)
        dx = px - x0
        dy = py - y0
        u = ct * dx + st * dy
        v = -st * dx + ct * dy
        out = out + amp * np.exp(
            -0.5 * ((u / max(abs(sx), 0.3)) ** 2
                    + (v / max(abs(sy), 0.3)) ** 2)
        )
    return out


def fit_island(
    px: np.ndarray, py: np.ndarray, flux: np.ndarray, maxP: int,
    criterion: str = "aic",
) -> Tuple[np.ndarray, int]:
    """Fit 1..maxP Gaussian components; return (params, ncomp) chosen by
    the information criterion (main.c -a: aic/bic/mdl/gtr)."""
    from scipy.optimize import least_squares

    n = flux.size
    best = None
    for ncomp in range(1, max(1, maxP) + 1):
        if 6 * ncomp >= n:
            break
        # init: brightest remaining pixels
        order = np.argsort(flux)[::-1]
        p0 = []
        for c in range(ncomp):
            i = order[min(c * max(1, n // ncomp // 2), n - 1)]
            p0 += [flux[i], px[i], py[i], 1.5, 1.5, 0.0]

        def resid(p):
            return _gauss_model(p, px, py, ncomp) - flux

        sol = least_squares(resid, np.asarray(p0), method="lm",
                            max_nfev=400 * ncomp)
        rss = float(np.sum(sol.fun ** 2)) + 1e-30
        k = 6 * ncomp
        if criterion == "bic":
            score = n * math.log(rss / n) + k * math.log(n)
        elif criterion == "mdl":
            score = 0.5 * n * math.log(rss / n) + 0.5 * k * math.log(n)
        else:  # aic (default) / gtr approximated by aic
            score = n * math.log(rss / n) + 2.0 * k
        if best is None or score < best[0]:
            best = (score, sol.x, ncomp)
    if best is None:
        # degenerate tiny island: single point at the peak
        i = int(np.argmax(flux))
        return np.asarray([flux[i], px[i], py[i], 0.5, 0.5, 0.0]), 1
    return best[1], best[2]


def _rad_to_hms(ra: float):
    h = ra * 12.0 / math.pi
    h = h % 24.0
    hh = int(h)
    mm = int((h - hh) * 60)
    ss = ((h - hh) * 60 - mm) * 60
    return hh, mm, ss


def _rad_to_dms(dec: float):
    s = -1 if dec < 0 else 1
    d = abs(dec) * 180.0 / math.pi
    dd = int(d)
    mm = int((d - dd) * 60)
    ss = ((d - dd) * 60 - mm) * 60
    return s * dd, mm, ss



def _name_sources(sources: List[dict]) -> None:
    """P = point, G = gaussian (the LSM type-from-name convention)."""
    for i, s in enumerate(sources):
        s["name"] = f"{'P' if s['point'] else 'G'}{s['island']}C{i}"


def hierarchical_cluster(l, m, ncut: int) -> np.ndarray:
    """Agglomerative centroid-linkage clustering of (l, m) positions,
    cut at ``ncut`` clusters — the reference's negative ``-k`` path
    (``hierarchical_clustering``, scluster.c:709-740: ``treecluster``
    with Euclidean metric + pairwise centroid linkage, then
    ``cuttree``).  scipy's linkage/fcluster replaces the embedded C
    Clustering Library.  Returns 0-based int assignments."""
    from scipy.cluster.hierarchy import fcluster, linkage

    pts = np.stack([np.asarray(l, float), np.asarray(m, float)], axis=1)
    n = len(pts)
    ncut = max(1, min(ncut, n))
    if n == 1:
        return np.zeros(1, np.int64)
    Z = linkage(pts, method="centroid", metric="euclidean")
    return np.asarray(fcluster(Z, t=ncut, criterion="maxclust")) - 1


def _write_cluster_file(sources: List[dict], out_cluster: str,
                        nclusters: int) -> None:
    """Cluster file: ``nclusters`` > 0 -> weighted k-means;
    < 0 -> hierarchical centroid-linkage cut at ``|nclusters|``
    (the reference's -k sign convention, buildsky main.c:43);
    0 -> one cluster per source."""
    assign = None
    if nclusters < 0 and len(sources) > 1:
        assign = hierarchical_cluster(
            [s["l"] for s in sources], [s["m"] for s in sources],
            min(-nclusters, len(sources)),
        )
    elif nclusters and len(sources) > 1:
        assign, _ = kmeans_weighted(
            [s["l"] for s in sources], [s["m"] for s in sources],
            [abs(s["flux"]) for s in sources],
            min(nclusters, len(sources)),
        )
    with open(out_cluster, "w") as fh:
        fh.write("# cluster_id hybrid source_names...\n")
        if assign is not None and len(assign):
            for cid in range(int(assign.max()) + 1):
                names = [s["name"] for s, a in zip(sources, assign)
                         if a == cid]
                if names:
                    fh.write(f"{cid + 1} 1 {' '.join(names)}\n")
        else:
            for i, s in enumerate(sources):
                fh.write(f"{i + 1} 1 {s['name']}\n")


def buildsky(
    fits_path: str,
    out_sky: str,
    out_cluster: str = None,
    threshold_sigma: float = 5.0,
    maxP: int = 3,
    nclusters: int = 0,
    criterion: str = "aic",
    min_pixels: int = 4,
    freq0: float = None,
    out_regions: str = None,
    log=print,
) -> List[dict]:
    """Extract sources; write the LSM sky + cluster files.

    ``nclusters``: 0 = one cluster per source (the reference's
    create_clusters default), > 0 = weighted k-means into that many
    clusters, < 0 = hierarchical centroid-linkage cut at ``|nclusters|``
    (the reference's -k sign convention, scluster.c / main.c:43).
    Returns the source dicts.
    """
    img, wcs, hdr = read_fits_image(fits_path)
    if freq0 is None:
        freq0 = hdr.get("CRVAL3", 150e6) or 150e6
    sigma = robust_noise(img)
    mask = img > threshold_sigma * sigma
    labels, nisl = label_islands(mask)
    log(f"buildsky: noise {sigma:.3e}, {nisl} islands above "
        f"{threshold_sigma} sigma")
    ny, nx = img.shape
    pixscale = abs(wcs.cdelt1) * math.pi / 180.0  # rad/pixel

    sources = []
    hulls = []
    for isl in range(1, nisl + 1):
        ys, xs = np.nonzero(labels == isl)
        if ys.size < min_pixels:
            continue
        hulls.append((isl, convex_hull(np.stack([xs, ys], axis=1))))
        flux = img[ys, xs]
        params, ncomp = fit_island(
            xs.astype(float), ys.astype(float), flux, maxP, criterion
        )
        for c in range(ncomp):
            amp, x0, y0, sx, sy, pa = params[6 * c:6 * c + 6]
            if amp <= 0:
                continue
            ra, dec = wcs.pixel_to_radec(x0, y0)
            l, m = wcs.pixel_to_lm(x0, y0)
            # point if the fitted extent is ~1 pixel
            is_point = max(abs(sx), abs(sy)) < 1.0
            sources.append(dict(
                ra=float(ra), dec=float(dec), l=float(l), m=float(m),
                flux=float(amp), island=isl,
                eX=0.0 if is_point else abs(sx) * pixscale * _SIGMA_TO_FWHM,
                eY=0.0 if is_point else abs(sy) * pixscale * _SIGMA_TO_FWHM,
                eP=0.0 if is_point else float(pa),
                point=is_point,
            ))
    _name_sources(sources)

    with open(out_sky, "w") as fh:
        fh.write("# name h m s d m s I Q U V spectral_index RM extent_X(rad)"
                 " extent_Y(rad) pos_angle(rad) freq0\n")
        fh.write("# generated by sagecal-tpu buildsky\n")
        for s in sources:
            hh, hm, hs = _rad_to_hms(s["ra"])
            dd, dm, ds2 = _rad_to_dms(s["dec"])
            fh.write(
                f"{s['name']} {hh} {hm} {hs:.3f} {dd} {dm} {ds2:.3f} "
                f"{s['flux']:.6f} 0 0 0 0 0 {s['eX']:.6e} {s['eY']:.6e} "
                f"{s['eP']:.6e} {freq0:.1f}\n"
            )

    out_cluster = out_cluster or out_sky + ".cluster"
    _write_cluster_file(sources, out_cluster, nclusters)
    if out_regions:
        write_ds9_regions(out_regions, sources, hulls, wcs)
    log(f"buildsky: {len(sources)} sources -> {out_sky}, {out_cluster}")
    return sources




def convex_hull(points: np.ndarray) -> np.ndarray:
    """Convex hull (Andrew monotone chain) of (N, 2) points -> hull
    vertices in counter-clockwise order.  The role of the reference's
    island boundary hulls (``hull.c:1-521``) without the embedded
    incremental C implementation."""
    pts = np.unique(np.asarray(points, float), axis=0)
    if len(pts) <= 2:
        return pts
    pts = pts[np.lexsort((pts[:, 1], pts[:, 0]))]

    def cross2(o, a, b):
        return (a[0] - o[0]) * (b[1] - o[1]) - (a[1] - o[1]) * (b[0] - o[0])

    def half(seq):
        h = []
        for q in seq:
            while len(h) >= 2 and cross2(h[-2], h[-1], q) <= 0:
                h.pop()
            h.append(q)
        return h

    lower = half(pts)
    upper = half(pts[::-1])
    return np.asarray(lower[:-1] + upper[:-1])


def write_ds9_regions(path: str, sources: List[dict], hulls, wcs) -> None:
    """DS9 region file: one point/ellipse per fitted source plus the
    convex-hull polygon of each island (the reference emits DS9 region
    output alongside the sky model, ``hull.c`` + buildsky README)."""
    deg = 180.0 / math.pi
    with open(path, "w") as fh:
        fh.write("# Region file format: DS9 (sagecal-tpu buildsky)\n")
        fh.write("global color=green\nfk5\n")
        for s in sources:
            ra, dec = s["ra"] * deg, s["dec"] * deg
            if s.get("point", True):
                fh.write(f'point({ra:.6f},{dec:.6f}) # point=cross '
                         f'text={{{s["name"]}}}\n')
            else:
                fh.write(
                    f'ellipse({ra:.6f},{dec:.6f},'
                    f'{s["eX"] * deg:.6f},{s["eY"] * deg:.6f},'
                    f'{s["eP"] * deg:.2f}) # text={{{s["name"]}}}\n'
                )
        for isl, hull in hulls:
            if len(hull) < 3:
                continue
            coords = []
            for (x, y) in hull:
                ra, dec = wcs.pixel_to_radec(float(x), float(y))
                coords += [f"{ra * deg:.6f}", f"{dec * deg:.6f}"]
            fh.write(f'polygon({",".join(coords)}) # color=yellow '
                     f'text={{island {isl}}}\n')


def fit_spectral_index(amps: np.ndarray, freqs: np.ndarray,
                       ref_freq: float, max_order: int = 3):
    """Log-polynomial spectrum fit: ln I(f) = ln I0 + si1 r + si2 r^2 +
    si3 r^3 with r = ln(f/ref_freq) — the reference's multi-frequency
    flux model (``fitmultipixels.c:441-447`` ``exp(log(p0) + p1 r +
    p2 r^2 + p3 r^3)``), fitted by least squares on the per-channel
    matched-filter amplitudes instead of the reference's nonlinear LM
    over raw pixels.  Returns (I0, [si1, si2, si3]) with the order
    clamped to the available channel count."""
    good = amps > 0
    if good.sum() < 2:
        I0 = float(amps[good][0]) if good.any() else float(np.max(amps))
        return I0, [0.0, 0.0, 0.0]
    r = np.log(freqs[good] / ref_freq)
    order = int(min(max_order, good.sum() - 1))
    A = np.vander(r, order + 1, increasing=True)  # 1, r, r^2, ...
    coef, *_ = np.linalg.lstsq(A, np.log(amps[good]), rcond=None)
    si = [0.0, 0.0, 0.0]
    for k in range(1, order + 1):
        si[k - 1] = float(coef[k])
    return float(math.exp(coef[0])), si


def buildmultisky(
    fits_paths: List[str],
    out_sky: str,
    out_cluster: str = None,
    out_regions: str = None,
    threshold_sigma: float = 5.0,
    maxP: int = 3,
    nclusters: int = 0,
    criterion: str = "aic",
    min_pixels: int = 4,
    log=print,
) -> List[dict]:
    """Multi-frequency source extraction with spectral-index fitting
    (the ``buildmultisky`` tool, ``buildmultisky.c:1-1899`` +
    ``fitmultipixels.c``): detect islands on the channel-mean image,
    fit the spatial shape there, recover each component's per-channel
    amplitude by matched filtering, fit the 3-term log-polynomial
    spectrum, and emit a 19-token (three-term-spectra, ``-F 1``) sky
    file, cluster file, and DS9 regions."""
    imgs, freqs = [], []
    wcs = None
    for path in fits_paths:
        img, w, hdr = read_fits_image(path)
        imgs.append(img)
        f = float(hdr.get("CRVAL3", 0.0))
        if f <= 0.0:
            raise ValueError(
                f"{path}: no CRVAL3 frequency in header — every channel "
                "image needs its frequency for the spectral fit"
            )
        freqs.append(f)
        wcs = wcs or w
    if len(set(freqs)) < len(freqs):
        raise ValueError(
            f"duplicate channel frequencies {sorted(freqs)} — the "
            "spectral-index fit is degenerate"
        )
    order = np.argsort(freqs)
    freqs = np.asarray(freqs)[order]
    imgs = [imgs[i] for i in order]
    cube = np.stack(imgs)  # (Nf, ny, nx)
    ref_freq = float(np.mean(freqs))
    mean_img = cube.mean(axis=0)

    sigma = robust_noise(mean_img)
    mask = mean_img > threshold_sigma * sigma
    labels, nisl = label_islands(mask)
    log(f"buildmultisky: {len(freqs)} channels "
        f"[{freqs[0]/1e6:.1f}..{freqs[-1]/1e6:.1f} MHz], noise "
        f"{sigma:.3e}, {nisl} islands")
    pixscale = abs(wcs.cdelt1) * math.pi / 180.0

    sources, hulls = [], []
    for isl in range(1, nisl + 1):
        ys, xs = np.nonzero(labels == isl)
        if ys.size < min_pixels:
            continue
        hulls.append((isl, convex_hull(np.stack([xs, ys], axis=1))))
        flux = mean_img[ys, xs]
        params, ncomp = fit_island(
            xs.astype(float), ys.astype(float), flux, maxP, criterion
        )
        for c in range(ncomp):
            amp, x0, y0, sx, sy, pa = params[6 * c:6 * c + 6]
            if amp <= 0:
                continue
            # matched-filter amplitude per channel with the mean-image
            # shape held fixed: amp_f = <img_f, g>/<g, g>
            g = _gauss_model(
                np.asarray([1.0, x0, y0, sx, sy, pa]),
                xs.astype(float), ys.astype(float), 1,
            )
            gg = float(np.dot(g, g)) + 1e-30
            amps_f = np.asarray(
                [float(np.dot(cube[f][ys, xs], g)) / gg
                 for f in range(len(freqs))]
            )
            I0, si = fit_spectral_index(amps_f, freqs, ref_freq)
            ra, dec = wcs.pixel_to_radec(x0, y0)
            l, m = wcs.pixel_to_lm(x0, y0)
            is_point = max(abs(sx), abs(sy)) < 1.0
            sources.append(dict(
                ra=float(ra), dec=float(dec), l=float(l), m=float(m),
                flux=float(I0), si=si, island=isl,
                eX=0.0 if is_point else abs(sx) * pixscale * _SIGMA_TO_FWHM,
                eY=0.0 if is_point else abs(sy) * pixscale * _SIGMA_TO_FWHM,
                eP=0.0 if is_point else float(pa),
                point=is_point,
            ))
    _name_sources(sources)

    with open(out_sky, "w") as fh:
        fh.write("# name h m s d m s I Q U V si0 si1 si2 RM eX eY eP f0\n")
        fh.write("# generated by sagecal-tpu buildmultisky (-F 1 format)\n")
        for s in sources:
            hh, hm, hs = _rad_to_hms(s["ra"])
            dd, dm, ds2 = _rad_to_dms(s["dec"])
            si = s["si"]
            fh.write(
                f"{s['name']} {hh} {hm} {hs:.3f} {dd} {dm} {ds2:.3f} "
                f"{s['flux']:.6f} 0 0 0 {si[0]:.6f} {si[1]:.6f} "
                f"{si[2]:.6f} 0 {s['eX']:.6e} {s['eY']:.6e} "
                f"{s['eP']:.6e} {ref_freq:.1f}\n"
            )

    out_cluster = out_cluster or out_sky + ".cluster"
    _write_cluster_file(sources, out_cluster, nclusters)
    if out_regions:
        write_ds9_regions(out_regions, sources, hulls, wcs)
    log(f"buildmultisky: {len(sources)} sources -> {out_sky}")
    return sources


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="sagecal-tpu-buildsky",
        description="FITS image -> LSM sky model + cluster file "
        "(reference src/buildsky)",
    )
    ap.add_argument("-f", "--fits", required=True)
    ap.add_argument("-o", "--out", default=None,
                    help="output sky file (default <fits>.sky.txt)")
    ap.add_argument("-s", "--sigma", type=float, default=5.0,
                    help="detection threshold in noise sigmas")
    ap.add_argument("-m", "--maxfit", type=int, default=3,
                    help="max Gaussian components per island (ref -m)")
    ap.add_argument("-a", "--criterion", default="aic",
                    choices=("aic", "bic", "mdl"),
                    help="model-order criterion (ref -a)")
    ap.add_argument("-Q", "--nclusters", type=int, default=0,
                    help="cluster count: >0 weighted k-means, <0 "
                    "hierarchical centroid-linkage cut at |Q| (ref -k "
                    "sign convention), 0 = one per source")
    ap.add_argument("--multi", nargs="+", default=None, metavar="FITS",
                    help="additional per-frequency FITS images: fit "
                    "spectral indices across all of them "
                    "(buildmultisky.c role)")
    ap.add_argument("--regions", default=None,
                    help="write a DS9 region file (hull.c role)")
    args = ap.parse_args(argv)
    out = args.out or args.fits + ".sky.txt"
    if args.multi:
        buildmultisky([args.fits] + list(args.multi), out,
                      out_regions=args.regions,
                      threshold_sigma=args.sigma, maxP=args.maxfit,
                      nclusters=args.nclusters, criterion=args.criterion)
        return 0
    buildsky(args.fits, out, threshold_sigma=args.sigma,
             maxP=args.maxfit, nclusters=args.nclusters,
             criterion=args.criterion, out_regions=args.regions)
    return 0


if __name__ == "__main__":
    sys.exit(main())
