"""``restore``: render a sky model into a FITS image.

Redesign of the reference's standalone restore tool
(``/root/reference/src/restore/restore.c``; per-pixel contribution math
``calculate_contribution1`` restore.c:80-208, shapelet rendering
``shapelet_lm.c``): each source is painted convolved with an elliptical
Gaussian PSF (bmaj, bmin, bpa).  The reference walks the image pixel by
pixel through a glist of sources; here every source's contribution is
one vectorized numpy/JAX expression over the pixel grid.

Faithful per-type behavior (restore.c:165-205):
- point:    I * exp(-(lr/bmaj)^2 - (mr/bmin)^2)    (peak-preserving)
- disk:     I inside radius eX, Gaussian rolloff (r-eX)/bmaj outside
- ring:     I * exp(-((r-eX)/bmaj)^2)
- gaussian: the closed-form elliptical-Gaussian x PSF convolution
  (restore.c:193-200 num/den expression), peak-preserving
- shapelet:  basis evaluation of the .modes file on the grid
  (shapelet_lm.c role) convolved approximately by the PSF via FFT-free
  direct Gaussian smoothing of the rendered patch
- spectral scaling exp(log I + si*lf + si1*lf^2 + si2*lf^3) with sign
  preservation (restore.c:148-162).
"""

from __future__ import annotations

import argparse
import math
import sys
from typing import Optional

import numpy as np

from sagecal_tpu.io.fits import FitsWCS, read_fits_image, write_fits_image
from sagecal_tpu.io.skymodel import parse_skymodel
from sagecal_tpu.ops.rime import ST_DISK, ST_GAUSSIAN, ST_POINT, ST_RING

_FWHM_TO_SIGMA = 1.0 / (2.0 * math.sqrt(2.0 * math.log(2.0)))


def _stokes_at(s, freq0: float) -> float:
    if s.spec_idx == 0.0 or s.sI == 0.0:
        return s.sI
    lf = math.log(freq0 / s.f0)
    mag = math.exp(
        math.log(abs(s.sI)) + s.spec_idx * lf + s.spec_idx1 * lf * lf
        + s.spec_idx2 * lf ** 3
    )
    return math.copysign(mag, s.sI)


def _source_lm(s, wcs: FitsWCS):
    """Source (ra, dec) -> SIN-projected (l, m) radians about the image
    center (the cels2x call of restore.c:122)."""
    ra0 = wcs.crval1 * math.pi / 180.0
    dec0 = wcs.crval2 * math.pi / 180.0
    dra = s.ra - ra0
    l = math.cos(s.dec) * math.sin(dra)
    m = (math.sin(s.dec) * math.cos(dec0)
         - math.cos(s.dec) * math.sin(dec0) * math.cos(dra))
    return l, m


def render_source(s, ll, mm, wcs, bmaj, bmin, bpa, freq0):
    """One source's contribution on the pixel grid (ll, mm in rad)."""
    sl, sm = _source_lm(s, wcs)
    l = -(ll - sl)
    m = mm - sm
    spa, cpa = math.sin(bpa), math.cos(bpa)
    lr = -l * spa + m * cpa
    mr = -l * cpa - m * spa
    I0 = _stokes_at(s, freq0)
    stype = _stype_of(s)
    if stype == ST_POINT:
        return I0 * np.exp(-((lr / bmaj) ** 2 + (mr / bmin) ** 2))
    r = np.sqrt(lr * lr + mr * mr)
    if stype == ST_DISK:
        out = np.where(
            r <= s.eX, I0, I0 * np.exp(-(((r - s.eX) / bmaj) ** 2))
        )
        return out
    if stype == ST_RING:
        return I0 * np.exp(-(((r - s.eX) / bmaj) ** 2))
    if stype == ST_GAUSSIAN:
        # closed-form PSF x source gaussian (restore.c:193-200)
        alpha, theta = s.eP, bpa
        A, B = bmaj, bmin
        a, b = s.eX * _FWHM_TO_SIGMA * 2.0, s.eY * _FWHM_TO_SIGMA * 2.0
        X, Y = l, m
        c2a, s2a = math.cos(2 * alpha), math.sin(2 * alpha)
        c2t, s2t = math.cos(2 * theta), math.sin(2 * theta)
        num = (0.5 * Y * Y * a * a + 0.5 * B * B * Y * Y
               - 0.5 * X * X * a * a * c2a + 0.5 * A * A * Y * Y
               + 0.5 * b * b * X * X + 0.5 * b * b * Y * Y
               + 0.5 * B * B * X * X + 0.5 * A * A * X * X
               + 0.5 * X * X * a * a - X * Y * a * a * s2a
               + Y * B * B * X * s2t - A * A * Y * X * s2t
               + b * b * X * Y * s2a + 0.5 * b * b * X * X * c2a
               + 0.5 * Y * Y * a * a * c2a - 0.5 * b * b * Y * Y * c2a
               + 0.5 * B * B * X * X * c2t - 0.5 * B * B * Y * Y * c2t
               - 0.5 * A * A * X * X * c2t + 0.5 * A * A * Y * Y * c2t)
        c2at = math.cos(2 * alpha - 2 * theta)
        den = (0.5 * b * b * B * B + 0.5 * a * a * B * B
               + 0.5 * b * b * A * A + 0.5 * a * a * A * A
               + A * A * B * B + a * a * b * b
               + 0.5 * b * b * A * A * c2at - 0.5 * b * b * B * B * c2at
               + 0.5 * a * a * B * B * c2at - 0.5 * a * a * A * A * c2at)
        return I0 * np.exp(-num / max(den, 1e-300))
    # shapelet: render the .modes basis on the local grid
    # (shapelet_lm.c role); modes file sits beside the sky model
    import os

    import jax.numpy as jnp

    from sagecal_tpu.io.skymodel import read_shapelet_modes
    from sagecal_tpu.ops.shapelets import image_mode_matrix

    directory = getattr(s, "_directory", ".")
    try:
        n0, beta, modes = read_shapelet_modes(s.name, directory)
    except (FileNotFoundError, OSError):
        return np.zeros_like(ll)
    phi = np.asarray(
        image_mode_matrix(jnp.asarray(-l.ravel()), jnp.asarray(m.ravel()),
                          beta, n0)
    )
    img = (phi @ np.asarray(modes)).reshape(ll.shape)
    return I0 * img


def _stype_of(s):
    from sagecal_tpu.io.skymodel import _source_type

    return _source_type(s)


def restore(
    sky_path: str,
    fits_in: str,
    fits_out: str,
    bmaj: Optional[float] = None,
    bmin: Optional[float] = None,
    bpa: float = 0.0,
    add: bool = True,
    freq0: Optional[float] = None,
) -> np.ndarray:
    """Render ``sky_path`` into ``fits_in``'s grid -> ``fits_out``.

    bmaj/bmin: PSF half-widths in radians (default: 4 pixels); ``add``
    keeps the input pixels (restore's add_to_pixel), else starts from
    zero.  Returns the output image.
    """
    img, wcs, hdr = read_fits_image(fits_in)
    ny, nx = img.shape
    if bmaj is None:
        bmaj = abs(wcs.cdelt1) * math.pi / 180.0 * 4.0
    if bmin is None:
        bmin = bmaj
    if freq0 is None:
        freq0 = hdr.get("CRVAL3", hdr.get("RESTFRQ", 150e6)) or 150e6
    px, py = np.meshgrid(np.arange(nx), np.arange(ny))
    ll, mm = wcs.pixel_to_lm(px, py)
    import os

    out = img.copy() if add else np.zeros_like(img)
    skydir = os.path.dirname(os.path.abspath(sky_path)) or "."
    for s in parse_skymodel(sky_path).values():
        s._directory = skydir  # shapelet .modes files live beside the sky
        out += render_source(s, ll, mm, wcs, bmaj, bmin, bpa, freq0)
    write_fits_image(fits_out, out, wcs)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="sagecal-tpu-restore",
        description="render a sky model into a FITS image "
        "(reference src/restore)",
    )
    ap.add_argument("-f", "--fits", required=True, help="input FITS image")
    ap.add_argument("-i", "--sky", required=True, help="LSM sky model")
    ap.add_argument("-o", "--out", required=True, help="output FITS image")
    ap.add_argument("-a", "--bmaj", type=float, default=None,
                    help="PSF major half-width (rad)")
    ap.add_argument("-b", "--bmin", type=float, default=None)
    ap.add_argument("-p", "--bpa", type=float, default=0.0)
    ap.add_argument("-z", "--zero", action="store_true",
                    help="start from a zero image instead of adding")
    args = ap.parse_args(argv)
    restore(args.sky, args.fits, args.out, args.bmaj, args.bmin, args.bpa,
            add=not args.zero)
    return 0


if __name__ == "__main__":
    sys.exit(main())
