"""``uvwriter``: recompute a dataset's UVW coordinates, including the
lunar body-fixed frame.

Redesign of ``/root/reference/src/uvwriter/uvwriter.cpp`` (rewrites MS
UVW columns in the ``MOON_ME`` frame through CSPICE) for the vis.h5
container.  CSPICE and its kernels are not in this image; the Moon's
mean-Earth/rotation frame orientation is instead evaluated from the
published IAU/WGCCRE 2009 series (alpha0, delta0, W with the E1..E13
nutation arguments) — standards data, not a code port.  Earth-frame
recomputation uses the same GMST rotation as the simulator.

For each timeslot: baseline vectors in the body-fixed frame are rotated
to the celestial frame with R = Rz(alpha0 + 90deg) Rx(90deg - delta0)
Rz(W), then projected onto the (u, v, w) triad of the phase center.
"""

from __future__ import annotations

import argparse
import math
import sys

import h5py
import numpy as np

# IAU/WGCCRE 2009 lunar orientation series (degrees; d = days since
# J2000 TDB, T = d / 36525): published constants.
_E_ARGS = [
    (125.045, -0.0529921), (250.089, -0.1059842), (260.008, 13.0120009),
    (176.625, 13.3407154), (357.529, 0.9856003), (311.589, 26.4057084),
    (134.963, 13.0649930), (276.617, 0.3287146), (34.226, 1.7484877),
    (15.134, -0.1589763), (119.743, 0.0036096), (239.961, 0.1643573),
    (25.053, 12.9590088),
]
_ALPHA_TERMS = {1: -3.8787, 2: -0.1204, 3: 0.0700, 4: -0.0172, 6: 0.0072,
                10: -0.0052, 13: 0.0043}
_DELTA_TERMS = {1: 1.5419, 2: 0.0239, 3: -0.0278, 4: 0.0068, 6: -0.0029,
                7: 0.0009, 10: 0.0008, 13: -0.0009}
_W_TERMS = {1: 3.5610, 2: 0.1208, 3: -0.0642, 4: 0.0158, 5: 0.0252,
            6: -0.0066, 7: -0.0047, 8: -0.0046, 9: 0.0028, 10: 0.0052,
            11: 0.0040, 12: 0.0019, 13: -0.0044}


def moon_orientation(jd: np.ndarray):
    """(alpha0, delta0, W) radians of the IAU_MOON frame at Julian dates."""
    d = np.asarray(jd, float) - 2451545.0
    T = d / 36525.0
    E = {i + 1: np.radians(a0 + a1 * d) for i, (a0, a1) in enumerate(_E_ARGS)}
    alpha = 269.9949 + 0.0031 * T
    delta = 66.5392 + 0.0130 * T
    W = 38.3213 + 13.17635815 * d - 1.4e-12 * d * d
    for i, c in _ALPHA_TERMS.items():
        alpha = alpha + c * np.sin(E[i])
    for i, c in _DELTA_TERMS.items():
        delta = delta + c * np.cos(E[i])
    for i, c in _W_TERMS.items():
        W = W + c * np.sin(E[i])
    return np.radians(alpha), np.radians(delta), np.radians(W)


def _rz(a):
    ca, sa = np.cos(a), np.sin(a)
    z = np.zeros_like(ca)
    o = np.ones_like(ca)
    return np.stack([
        np.stack([ca, -sa, z], -1),
        np.stack([sa, ca, z], -1),
        np.stack([z, z, o], -1),
    ], -2)


def _rx(a):
    ca, sa = np.cos(a), np.sin(a)
    z = np.zeros_like(ca)
    o = np.ones_like(ca)
    return np.stack([
        np.stack([o, z, z], -1),
        np.stack([z, ca, -sa], -1),
        np.stack([z, sa, ca], -1),
    ], -2)


def body_to_celestial(jd: np.ndarray, body: str = "moon") -> np.ndarray:
    """(T, 3, 3) rotation matrices body-fixed -> celestial at each jd."""
    if body == "moon":
        alpha, delta, W = moon_orientation(jd)
        return _rz(alpha + np.pi / 2) @ _rx(np.pi / 2 - delta) @ _rz(W)
    # earth: GMST rotation about z (the simulator's synthesis frame)
    from sagecal_tpu.ops.transforms import jd2gmst

    gmst = np.asarray([jd2gmst(j) for j in np.atleast_1d(jd)])
    return _rz(gmst)


def uvw_from_positions(xyz, ant_p, ant_q, jd, ra0, dec0, body="moon"):
    """Per-timeslot UVW (metres) for body-fixed station positions.

    xyz: (N, 3); ant_p/ant_q: (nbase,); jd: (T,).  Returns
    (T, nbase, 3)."""
    R = body_to_celestial(np.asarray(jd), body)  # (T, 3, 3)
    B = xyz[ant_p] - xyz[ant_q]  # (nbase, 3)
    Bc = np.einsum("tij,bj->tbi", R, B)  # celestial-frame baselines
    sr, cr = math.sin(ra0), math.cos(ra0)
    sd, cd = math.sin(dec0), math.cos(dec0)
    uhat = np.asarray([-sr, cr, 0.0])
    vhat = np.asarray([-cr * sd, -sr * sd, cd])
    what = np.asarray([cr * cd, sr * cd, sd])
    return np.stack(
        [Bc @ uhat, Bc @ vhat, Bc @ what], axis=-1
    )


def rewrite_uvw(h5_path: str, positions_path: str, body: str = "moon",
                log=print) -> None:
    """Rewrite /u /v /w of a vis.h5 from body-fixed station positions
    (the uvwriter main loop: read station coords + times, write UVW)."""
    xyz = np.loadtxt(positions_path)
    with h5py.File(h5_path, "r+") as f:
        ant_p = np.asarray(f["ant_p"])
        ant_q = np.asarray(f["ant_q"])
        ntime = f["u"].shape[0]
        jd0 = float(f.attrs.get("time_jd0", 2451545.0))
        dt = float(f.attrs.get("deltat", 1.0))
        ra0 = float(f.attrs["ra0"])
        dec0 = float(f.attrs["dec0"])
        jd = jd0 + np.arange(ntime) * dt / 86400.0
        if xyz.shape[0] < int(max(ant_p.max(), ant_q.max())) + 1:
            raise ValueError(
                f"{positions_path}: {xyz.shape[0]} stations < dataset needs"
            )
        uvw = uvw_from_positions(xyz, ant_p, ant_q, jd, ra0, dec0, body)
        f["u"][...] = uvw[..., 0]
        f["v"][...] = uvw[..., 1]
        f["w"][...] = uvw[..., 2]
    log(f"uvwriter: rewrote UVW of {h5_path} in the {body} frame")


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="sagecal-tpu-uvwriter",
        description="recompute dataset UVW in the lunar (or earth) frame "
        "(reference src/uvwriter; IAU 2009 lunar orientation in place of "
        "CSPICE)",
    )
    ap.add_argument("-d", "--dataset", required=True, help="vis.h5 file")
    ap.add_argument("-p", "--positions", required=True,
                    help="station positions text file (N x 3, metres, "
                    "body-fixed)")
    ap.add_argument("-b", "--body", default="moon",
                    choices=("moon", "earth"))
    args = ap.parse_args(argv)
    rewrite_uvw(args.dataset, args.positions, args.body)
    return 0


if __name__ == "__main__":
    sys.exit(main())
