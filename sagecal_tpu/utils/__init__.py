"""Runtime utilities: platform guards, profiling, structured logging."""

from sagecal_tpu.utils.platform import (  # noqa: F401
    cpu_device,
    ensure_cpu_devices,
    probe_default_backend,
)
