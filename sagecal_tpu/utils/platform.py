"""JAX platform guards for the axon TPU environment.

The axon sitecustomize force-selects the TPU platform via
``jax.config.update("jax_platforms", "axon,cpu")`` at interpreter start,
which OVERRIDES the ``JAX_PLATFORMS`` env var; a failed axon plugin
makes every backend query raise, and a wedged axon tunnel makes backend
init HANG rather than fail (verify skill gotchas 1 & 5).  These helpers
are shared by the driver entry points (``__graft_entry__.py``,
``bench.py``) and usable by applications.
"""

from __future__ import annotations

import functools
import glob
import os
import re
import subprocess
import sys

_COUNT_FLAG = "--xla_force_host_platform_device_count"

# N virtual devices time-share the host's cores, so SPMD shards can
# legitimately arrive at a collective minutes apart (e.g. a heavy robust
# RTR x-step on a single-core host); XLA CPU's default collective
# rendezvous terminates the process after ~40 s.  Raise the limits
# whenever we force the virtual-device mesh — but only the limits this
# jaxlib actually knows: XLA fatal-aborts the whole process on unknown
# XLA_FLAGS (parse_flags_from_env.cc), so every flag must be vetted
# against the installed binary before backend init.
_RENDEZVOUS_FLAGS = (
    "--xla_cpu_collective_timeout_seconds=7200",
    "--xla_cpu_collective_call_warn_stuck_timeout_seconds=600",
    "--xla_cpu_collective_call_terminate_timeout_seconds=7200",
)


@functools.lru_cache(maxsize=1)
def _xla_extension_paths() -> tuple:
    try:
        import jaxlib

        root = os.path.dirname(jaxlib.__file__)
    except Exception:
        return ()
    paths = [
        p
        for p in glob.glob(os.path.join(root, "**", "xla_extension*.so*"),
                           recursive=True)
        if "\0" not in p and os.path.isfile(p)
    ]
    return tuple(sorted(paths))


@functools.lru_cache(maxsize=None)
def _binary_knows_flags(names: tuple) -> frozenset:
    """Subset of flag `names` present as literal strings in the installed
    xla_extension binary (where XLA's flag registry keeps them)."""
    needles = {n: n.encode() for n in names}
    found = set()
    overlap = max((len(b) for b in needles.values()), default=1)
    for path in _xla_extension_paths():
        try:
            with open(path, "rb") as f:
                tail = b""
                while len(found) < len(needles):
                    buf = f.read(1 << 24)
                    if not buf:
                        break
                    hay = tail + buf
                    for n, b in needles.items():
                        if n not in found and b in hay:
                            found.add(n)
                    tail = hay[-overlap:]
        except OSError:
            continue
        if len(found) == len(needles):
            break
    return frozenset(found)


def supported_xla_flags(flags) -> tuple:
    """Filter ``--name=value`` XLA flags down to those the installed
    jaxlib recognises.  Unknown names are dropped (passing one aborts the
    process); if the binary cannot be located nothing is vouched for and
    the result is empty."""
    names = tuple(f.split("=")[0].lstrip("-") for f in flags)
    known = _binary_knows_flags(names)
    return tuple(
        f for f, n in zip(flags, names) if n in known
    )


def probe_default_backend(timeout: float = 240.0) -> bool:
    """True iff ``import jax; jax.devices()`` succeeds in a fresh process
    within `timeout` seconds.

    A hang during axon backend init cannot be recovered in-process once
    triggered, so the probe runs in a throwaway subprocess (which
    inherits PYTHONPATH and therefore the sitecustomize)."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        return r.returncode == 0
    except Exception:
        return False


def cpu_device():
    """A host CPU device, tolerating axon plugin init failure."""
    import jax

    try:
        return jax.devices("cpu")[0]
    except RuntimeError:
        # jax_platforms names axon explicitly, making its init failure
        # fatal to every backend query — retry CPU-only
        jax.config.update("jax_platforms", "cpu")
        return jax.devices("cpu")[0]


def ensure_cpu_devices(n_devices: int) -> None:
    """Force the CPU platform with >= `n_devices` virtual host devices,
    even if jax was already initialized on another platform or with a
    smaller device count."""
    flags = os.environ.get("XLA_FLAGS", "")
    # rewrite (not just append) any preset count smaller than requested
    m = re.search(_COUNT_FLAG + r"=(\d+)", flags)
    if m and int(m.group(1)) < n_devices:
        flags = re.sub(
            _COUNT_FLAG + r"=\d+", f"{_COUNT_FLAG}={n_devices}", flags
        )
        os.environ["XLA_FLAGS"] = flags
    elif not m:
        os.environ["XLA_FLAGS"] = (
            flags + f" {_COUNT_FLAG}={n_devices}"
        ).strip()
    flags = os.environ["XLA_FLAGS"]
    for f in supported_xla_flags(_RENDEZVOUS_FLAGS):
        if f.split("=")[0] not in flags:
            flags = flags + " " + f
    os.environ["XLA_FLAGS"] = flags.strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except Exception:
        pass  # backend already initialized; cleared + retried below

    def _count():
        try:
            devs = jax.devices()
        except Exception:
            return 0
        return len(devs) if devs and devs[0].platform == "cpu" else 0

    if _count() < n_devices:
        import jax.extend.backend as jeb

        jeb.clear_backends()
        try:
            jax.config.update("jax_num_cpu_devices", n_devices)
        except Exception:
            pass  # older jax: the XLA_FLAGS count set above applies
        if _count() < n_devices:
            raise RuntimeError(
                f"could not create {n_devices} virtual CPU devices "
                f"(got {_count()}); XLA_FLAGS={os.environ.get('XLA_FLAGS')}"
            )


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``: newer jax exposes it as
    ``jax.shard_map`` with varying-manual-axes checking (``check_vma``);
    jax 0.4.x only has ``jax.experimental.shard_map`` with the older
    replication checker, which rejects valid constant-initialized loop
    carries (the very thing :func:`match_vma` papers over on new jax —
    and ``lax.pcast`` does not exist on 0.4.x), so there the check is
    disabled."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def match_vma(tree, ref):
    """Promote every array leaf of ``tree`` to carry (at least) the
    varying-manual-axes of ``ref``.

    Inside ``shard_map(..., check_vma=True)`` (the default the framework
    now runs with), loop carries initialized from constants (zeros,
    identity Jones, False flags) are inferred as replicated while the
    loop bodies produce shard-varying outputs, which the type checker
    rightly rejects.  This helper inserts the
    ``jax.lax.pcast(..., to='varying')`` casts the checker asks for —
    and is a no-op outside shard_map (empty vma) or when already
    varying, so library solvers stay usable in both worlds."""
    import jax
    import jax.tree_util as jtu

    try:
        ref_vma = jax.typeof(ref).vma
    except Exception:
        return tree
    if not ref_vma:
        return tree

    def fix(x):
        try:
            missing = tuple(n for n in ref_vma if n not in jax.typeof(x).vma)
        except Exception:
            return x
        if not missing:
            return x
        return jax.lax.pcast(x, missing, to="varying")

    return jtu.tree_map(fix, tree)
