"""Spatial-model image output: portable-pixmap plots.

Redesign of ``convert_tensor_to_image`` (``/root/reference/src/lib/
Dirac/pngoutput.c:87-160``, decl Dirac.h:1595) and the master's
``plot_spatial_model`` (shapelet.c:975, called at
sagecal_master.cpp:1198): per-column-normalized square panels, a
three-segment blue->green->red colormap, binary ``P6`` PPM — no image
library needed, matching the reference's libpng-free choice.
"""

from __future__ import annotations

import numpy as np


def _colormap(vals: np.ndarray) -> np.ndarray:
    """[0,1] floats -> (..., 3) uint8 via the reference's 768-step
    blue->green->red ramp (pngoutput.c setRGB)."""
    v = np.clip((vals * 767).astype(int), 0, 767)
    off = (v % 256).astype(np.uint8)
    rgb = np.zeros(vals.shape + (3,), np.uint8)
    lo = v < 256
    mid = (v >= 256) & (v < 512)
    hi = v >= 512
    rgb[lo, 2] = off[lo]
    rgb[mid, 1] = off[mid]
    rgb[mid, 2] = 255 - off[mid]
    rgb[hi, 0] = off[hi]
    rgb[hi, 1] = 255 - off[hi]
    return rgb


def write_ppm(path: str, buffer2d: np.ndarray) -> None:
    """Write a [0,1]-valued 2-D array as a binary P6 PPM."""
    h, w = buffer2d.shape
    rgb = _colormap(np.asarray(buffer2d, float))
    with open(path, "wb") as fp:
        fp.write(f"P6\n{w} {h} 255\n".encode())
        fp.write(rgb.tobytes())


def convert_tensor_to_image(
    W: np.ndarray, path: str, normalize: bool = True
) -> None:
    """N columns of MxM patches -> a near-square panel grid image
    (``convert_tensor_to_image``): per-column [0,1] normalization with
    the reference's small-range cutoff (columns whose range is < 0.1 of
    the largest range AND < 1.0 plot as flat — noise suppression)."""
    W = np.asarray(W, float)
    if W.ndim == 2:
        N = W.shape[0]
        M = int(round(np.sqrt(W.shape[1])))
        W = W.reshape(N, M, M)
    N, M, _ = W.shape
    panel_m = int(np.ceil(np.sqrt(N)))
    P = max(panel_m, (N + panel_m - 1) // panel_m)
    img = np.zeros((P * M, P * M))
    wmin = W.reshape(N, -1).min(axis=1)
    wmax = W.reshape(N, -1).max(axis=1)
    max_diff = float(np.max(wmax - wmin)) if N else 0.0
    for col in range(N):
        lo, hi = wmin[col], wmax[col]
        if normalize:
            if (max_diff * 0.1 > hi - lo) and (hi - lo < 1.0):
                lo, hi = 0.0, 1.0
            patch = (W[col] - lo) / max(hi - lo, 1e-30)
        else:
            patch = np.clip(W[col], 0.0, 1.0)
        r, c = divmod(col, P)
        img[r * M:(r + 1) * M, c * M:(c + 1) * M] = patch
    write_ppm(path, img)


def plot_spatial_model(
    Zspat: np.ndarray,
    npoly: int,
    nstations: int,
    sh_n0: int,
    beta: float,
    path: str,
    npix: int = 64,
    extent: float = None,
) -> None:
    """Render the per-station spatial-model amplitude as one panel per
    station (``plot_spatial_model``'s shapelet-basis branch): for each
    station, image = Frobenius norm of the 2x2 Jones-valued shapelet
    series of its poly-0 block evaluated on an (l, m) grid.

    Zspat: (2*Npoly*N, 2G) complex (the mesh AdmmResult.Zspat layout).
    """
    import jax.numpy as jnp

    from sagecal_tpu.ops.shapelets import image_mode_matrix

    G = sh_n0 * sh_n0
    if extent is None:
        extent = 3.0 * beta
    grid = np.linspace(-extent, extent, npix)
    ll, mm = np.meshgrid(grid, grid)
    phi = np.asarray(
        image_mode_matrix(
            jnp.asarray(ll.ravel()), jnp.asarray(mm.ravel()), beta, sh_n0
        )
    )  # (npix^2, G)
    Z = np.asarray(Zspat).reshape(npoly, nstations, 2, G, 2)
    patches = np.zeros((nstations, npix, npix))
    for s in range(nstations):
        Zt = np.transpose(Z[0, s], (1, 0, 2))  # (G, 2, 2) poly-0 block
        J = np.einsum("pg,gij->pij", phi, Zt)  # (npix^2, 2, 2)
        patches[s] = np.linalg.norm(J, axis=(1, 2)).reshape(npix, npix)
    convert_tensor_to_image(patches, path, normalize=True)
