"""True-f32 matmul policy for solver entry points.

TPU f32 matmuls default to single-pass bf16 MXU multiplication, which
rounds the solver's linear algebra to ~3 significant digits — measured
on the v5e to diverge warm-started calibration tiles at the noise
floor where exact f32 reconverges (round 5, PERF.md "precision
chapter"; the reference computes in f64, so true f32 is the floor for
parity).  Every public solver entry traces under this context so any
caller — fullbatch, ADMM mesh, federated, or a user jitting a solver
directly — gets production precision on any backend.
"""

from __future__ import annotations

import functools

import jax


def true_f32(fn):
    """Trace ``fn`` under HIGHEST matmul precision (see module doc)."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.default_matmul_precision("highest"):
            return fn(*args, **kwargs)

    return wrapped
