"""First-class profiling: per-phase wall-clock + optional XLA traces.

The reference's only instrumentation is one ``time(0)`` print per tile
(``/root/reference/src/MS/fullbatch_mode.cpp:276,309,634-635``); SURVEY
section 5 makes ``jax.profiler`` traces + per-phase timing a first-class
feature of the rebuild.  Two layers:

- :class:`PhaseTimer` — cheap always-on wall-clock accounting per named
  phase (load / coherencies / solve / residual / write), printed as one
  summary line per tile and totals at the end of a run.  When telemetry
  is enabled (``SAGECAL_TELEMETRY=1``) every phase duration is also
  observed into the ``phase_seconds`` histogram of the process-wide
  :func:`sagecal_tpu.obs.registry.get_registry`, so ``sagecal-tpu diag
  prom`` exports the same numbers Prometheus-style; :meth:`PhaseTimer.
  tile_timings` hands the per-tile window to the JSONL event log.
- XLA device traces — set ``SAGECAL_PROFILE_DIR=/some/dir`` (or enter
  :func:`trace` yourself) to capture a TensorBoard-loadable
  ``jax.profiler`` trace of the same run; phases are annotated with
  ``jax.profiler.TraceAnnotation`` so device ops attribute to them.
  Apps use the :func:`trace` context manager, which stops the trace in
  a ``finally`` — a crash mid-run flushes a loadable trace instead of
  leaving a truncated one (the bare ``start_trace``/``stop_trace``
  pair stays for REPL use).
"""

from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional

import jax

from sagecal_tpu.obs.registry import get_registry, telemetry_enabled
from sagecal_tpu.obs.trace import get_tracer

_TRACE_DIR_ENV = "SAGECAL_PROFILE_DIR"
_active_trace: Optional[str] = None


def start_trace(log_dir: Optional[str] = None) -> Optional[str]:
    """Begin an XLA profiler trace (idempotent).  Returns the directory
    or None when tracing is not requested."""
    global _active_trace
    if _active_trace is not None:
        return _active_trace
    log_dir = log_dir or os.environ.get(_TRACE_DIR_ENV)
    if not log_dir:
        return None
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    _active_trace = log_dir
    return log_dir


def stop_trace() -> None:
    global _active_trace
    if _active_trace is not None:
        jax.profiler.stop_trace()
        _active_trace = None


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None) -> Iterator[Optional[str]]:
    """Exception-safe XLA trace scope: starts a profiler trace when
    requested (argument or ``SAGECAL_PROFILE_DIR``), yields the trace
    directory (None when tracing is off), and ALWAYS stops the trace it
    started on exit — including on an exception, so a crashed run still
    leaves a TensorBoard-loadable trace.  Nested under an already
    active trace it is a no-op passthrough (the owner stops it)."""
    owner = _active_trace is None
    d = start_trace(log_dir)
    try:
        yield d
    finally:
        if owner and d is not None:
            stop_trace()


class PhaseTimer:
    """Accumulates wall-clock per named phase across tiles."""

    def __init__(self) -> None:
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self._tile: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        # host-side tracer span (SAGECAL_TRACE=1): the NullTracer hands
        # back a shared no-op CM, so the disabled path stays allocation-
        # free; span exits also feed the flight recorder's stall clock
        with get_tracer().span(name, kind="phase"):
            with jax.profiler.TraceAnnotation(name):
                yield
        dt = time.perf_counter() - t0
        self.totals[name] += dt
        self.counts[name] += 1
        self._tile[name] = self._tile.get(name, 0.0) + dt
        # zero-cost-off: one flag check and we're done — no import, no
        # registry lookup, no label-key allocation on the hot path
        if telemetry_enabled():
            get_registry().observe(
                "phase_seconds", dt,
                help="wall-clock seconds per named pipeline phase",
                phase=name,
            )
            from sagecal_tpu.obs.perf import record_memory_watermark

            record_memory_watermark(name)

    def tile_timings(self) -> Dict[str, float]:
        """Snapshot of the current per-tile window (does not reset) —
        the per-tile payload for the JSONL event log."""
        return dict(self._tile)

    def tile_summary(self) -> str:
        """One-line per-tile breakdown; resets the per-tile window."""
        s = " ".join(f"{k}={v:.2f}s" for k, v in self._tile.items())
        self._tile = {}
        return s

    def run_summary(self) -> str:
        parts = [
            f"{k}: {self.totals[k]:.2f}s/{self.counts[k]}x"
            for k in sorted(self.totals, key=self.totals.get, reverse=True)
        ]
        return "phase totals: " + ", ".join(parts)
