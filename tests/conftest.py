"""Test configuration: hermetic 8-virtual-device CPU JAX.

Multi-device tests use JAX's host-platform device emulation in place of
the reference's copy-the-MS-N-times MPI recipe
(/root/reference/test/Calibration/README.md steps 1-4).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
# 8 virtual devices time-share this host's core(s): shards reach
# collectives far apart in wall-clock, and XLA CPU's rendezvous would
# abort the process after ~40 s (observed with the robust-RTR ADMM
# x-step).  Raise the limits for the whole suite — but only with flags
# this jaxlib build actually recognises: XLA fatal-aborts the whole test
# process on any unknown name in XLA_FLAGS.
from sagecal_tpu.utils.platform import supported_xla_flags  # noqa: E402

for f in supported_xla_flags((
    "--xla_cpu_collective_timeout_seconds=7200",
    "--xla_cpu_collective_call_warn_stuck_timeout_seconds=600",
    "--xla_cpu_collective_call_terminate_timeout_seconds=7200",
)):
    if f.split("=")[0] not in flags:
        flags = flags + " " + f
os.environ["XLA_FLAGS"] = flags.strip()

import jax  # noqa: E402

# The axon sitecustomize force-selects the TPU backend via
# jax.config.update("jax_platforms", "axon,cpu"); undo it for hermetic tests.
jax.config.update("jax_platforms", "cpu")
# The reference CPU path is double precision throughout (SURVEY.md hard
# part (c)); tests validate the f64 semantics on CPU while f32/bf16 is
# the TPU production dtype.
jax.config.update("jax_enable_x64", True)

import faulthandler  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running end-to-end test")


# Per-test hang watchdog.  The XLA collective limits above are an
# escape hatch of LAST resort (7200 s); without a per-test bound a hung
# collective takes two hours to surface.  faulthandler's timer fires
# even while the main thread is blocked inside native XLA code (where a
# SIGALRM-based timeout would never run Python): it dumps every
# thread's traceback and hard-exits, turning a silent hang into a
# diagnosis.  The dump goes to a real file on disk — NOT stderr, which
# pytest's fd-level capture redirects into an unlinked temp file that
# the hard exit would discard.  Budget: fast tests get 600 s each (the
# whole fast suite is budgeted <10 min, so any single test near 600 s
# is already broken); slow-marked deep runs get 3600 s.
_WATCHDOG_LOG = os.path.join(os.path.dirname(__file__), os.pardir,
                             ".pytest_watchdog.log")
_watchdog_file = open(_WATCHDOG_LOG, "w")


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_protocol(item, nextitem):
    limit = 3600.0 if item.get_closest_marker("slow") else 600.0
    _watchdog_file.seek(0)
    _watchdog_file.truncate()
    _watchdog_file.write(
        f"watchdog armed for {item.nodeid} (limit {limit:.0f} s); if a "
        "traceback follows, the test hung and the run was killed\n")
    _watchdog_file.flush()
    faulthandler.dump_traceback_later(limit, exit=True, file=_watchdog_file)
    try:
        yield
    finally:
        faulthandler.cancel_dump_traceback_later()


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs[:8]
