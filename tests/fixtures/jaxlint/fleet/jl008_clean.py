"""Must-not-fire fixture for JL008: the atomic staging idiom, read
mode, and a write to non-protocol state are all exempt."""
import json
import os


def write_manifest_atomic(out_dir, doc):
    path = os.path.join(out_dir, "result-r1.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def read_manifest(path):
    with open(path) as f:
        return json.load(f)


def write_scratch_note(out_dir, text):
    with open(os.path.join(out_dir, "notes.txt"), "w") as f:
        f.write(text)
