"""Should-fire fixture for JL008 (lives under fleet/ for path scope):
three non-atomic writes to protocol-state paths."""
import json
import os


def write_manifest(out_dir, doc):
    path = os.path.join(out_dir, "result-r1.json")
    with open(path, "w") as f:
        json.dump(doc, f)


def publish_lease(root, rid, doc):
    f = open(f"{root}/lease-{rid}.e000001.json", "w")
    f.write(json.dumps(doc))
    f.close()


def append_queue(queue_path, line):
    with open(queue_path, "a") as f:
        f.write(line)
