"""Must-not-fire fixture for JL010: the injectable-clock idioms
(constructor default, ``now=None`` parameter) and non-lease timing."""
import time


class Watcher:
    def __init__(self, ttl_s, clock=time.time):
        self.ttl_s = ttl_s
        self.clock = clock

    def lease_live(self, doc, now=None):
        now = time.time() if now is None else float(now)
        return float(doc.get("expires_at", 0.0)) > now

    def next_expiry(self, docs, now=None):
        now = self.clock() if now is None else float(now)
        return min(float(d["expires_at"]) for d in docs
                   if float(d["expires_at"]) > now)


def wall_elapsed(t0):
    return time.time() - t0
