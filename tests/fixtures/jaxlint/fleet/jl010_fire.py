"""Should-fire fixture for JL010 (lives under fleet/ for path scope):
raw wall-clock reads inside lease/deadline predicates."""
import time


def lease_live(doc):
    return float(doc.get("expires_at", 0.0)) > time.time()


def deadline_for(enqueued_at, ttl_s):
    deadline = time.time() + ttl_s
    return max(deadline, enqueued_at)
