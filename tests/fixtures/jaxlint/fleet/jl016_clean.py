"""Must-not-fire fixture for JL016: the registered O_APPEND
single-write emitter, the tmp + os.replace staging idiom, and a
newline-free whole-document write are all exempt."""
import json
import os


def emit_line(fd, rec):
    line = (json.dumps(rec, sort_keys=True) + "\n").encode()
    os.write(fd, line)


def stage_and_publish(path, rows):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    os.replace(tmp, path)


def write_doc(fh, doc):
    fh.write(json.dumps(doc))
