"""Should-fire fixture for JL016 (lives under fleet/ for path scope):
two JSONL appends through buffered file handles."""
import json


def append_event(fh, rec):
    fh.write(json.dumps(rec) + "\n")


def append_span(log, span):
    with open(log, "a") as f:
        f.write(json.dumps(span, sort_keys=True) + "\n")
