"""JL001 must-not-fire fixture: legal trace-time Python control flow."""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("collect_trace",))
def static_branch(x, collect_trace: bool = False):
    y = jnp.abs(x)
    if collect_trace:  # static Python bool: retrace, not a tracer leak
        y = y * 2.0
    return y


@jax.jit
def identity_checks(x, key: Optional[jax.Array] = None):
    if key is None:  # `is None` is object identity, always legal
        key = jax.random.PRNGKey(0)
    r = jnp.sum(x)
    if r is not None:  # tainted local, but still an identity check
        x = x + 1.0
    return x, key


@jax.jit
def metadata_checks(x):
    if jnp.real(x).dtype == jnp.float32:  # .dtype is static metadata
        x = x * 2.0
    y = jnp.abs(x)
    if y.shape[0] > 3:  # .shape on a tainted local is static too
        y = y[:3]
    return y


def host_only(x):
    # not jit-reachable from anywhere: plain Python branching is fine
    if jnp.sum(x) > 0:
        return 1
    return 0
