"""JL001 should-fire fixture: Python branch on a traced value in jit."""

import jax
import jax.numpy as jnp


@jax.jit
def bad_branch(x):
    r = jnp.sum(jnp.abs(x))
    if r > 1.0:  # JL001: traced comparison in Python `if`
        return x / r
    return x


@jax.jit
def bad_while(x):
    while jnp.max(x) > 1.0:  # JL001
        x = x * 0.5
    return x


@jax.jit
def bad_assert(x):
    assert jnp.all(jnp.isfinite(x))  # JL001
    return x
