"""JL002 must-not-fire fixture: legal casts and host-side syncs."""

import math

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def closure_cast(x, fdelta=1e5):
    # float() on a plain Python scalar is legal inside jit
    scale = float(fdelta) / 2.0
    # np.array on a Python-list constant folds into the trace
    norm = np.array([math.sqrt(n + 1.0) for n in range(4)])
    return x * scale + jnp.asarray(norm, x.dtype).sum()


def host_driver(x):
    # not jit-reachable: syncing on the host boundary is the point
    out = jax.jit(jnp.sum)(x)
    return float(out.block_until_ready())
