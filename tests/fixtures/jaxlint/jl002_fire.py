"""JL002 should-fire fixture: host syncs reachable from jitted code."""

import jax
import jax.numpy as jnp
import numpy as np


def leaf(x):
    return float(jnp.sum(x))  # JL002: float() on a traced value


def middle(x):
    s = jnp.abs(x)
    return leaf(s) + s.item()  # JL002: .item() device->host sync


@jax.jit
def entry(x):
    # `middle` (and through it `leaf`) is jit-reachable from here
    return middle(x)


@jax.jit
def materialize(x):
    y = jnp.exp(x)
    return np.asarray(y)  # JL002: np.asarray on a traced value


@jax.jit
def blocker(x):
    return jnp.sum(x).block_until_ready()  # JL002
