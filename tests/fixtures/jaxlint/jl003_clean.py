"""JL003 must-not-fire fixture: statics declared, or no branching."""

import jax
import jax.numpy as jnp


@jax.jit
def plain(x, normalize: bool = False):
    # bool param never drives a Python branch: jnp.where is traced
    return jnp.where(normalize, x / jnp.sum(x), x)


def fit(x, collect_trace: bool = False, robust: bool = False):
    y = jnp.sum(x * x)
    if robust:
        y = jnp.sqrt(y)
    return (y, y) if collect_trace else (y, None)


# statics declared at the wrap site: both branch drivers covered
fit_jit = jax.jit(fit, static_argnames=("collect_trace", "robust"))


@jax.jit
def positional(x, mode: bool = True):
    if mode:
        return x + 1.0
    return x - 1.0


# declared by position on a second wrap site of the same function:
# statics merge across wrap sites
positional_jit = jax.jit(positional, static_argnums=(1,))
