"""JL003 should-fire fixture: branch-controlling jit params not static."""

import jax
import jax.numpy as jnp


@jax.jit  # JL003: `robust` drives a branch but is not declared static
def solve(x, robust: bool = False):
    if robust:
        return jnp.median(x)
    return jnp.mean(x)


def fit(x, collect_trace: bool = False):
    y = jnp.sum(x * x)
    return (y, y) if collect_trace else (y, None)


# JL003: call-site wrap without static_argnames for collect_trace
fit_jit = jax.jit(fit)
