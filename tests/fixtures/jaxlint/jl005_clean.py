"""JL005 must-not-fire fixture: fixed-shape formulations."""

import jax
import jax.numpy as jnp


@jax.jit
def masked_sum(vis, mask):
    # fixed-size mask-and-weight form: shape never depends on values
    return jnp.sum(jnp.where(mask, vis, 0.0))


@jax.jit
def sized_nonzero(mask):
    # static size= escape hatch keeps the shape fixed
    return jnp.nonzero(mask, size=8, fill_value=0)


def host_side(freqs):
    # not jit-reachable: data-dependent shapes are fine on the host
    return jnp.unique(freqs)
