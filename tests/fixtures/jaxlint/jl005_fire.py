"""JL005 should-fire fixture: data-dependent shapes inside jit."""

import jax
import jax.numpy as jnp


@jax.jit
def pick_flagged(vis, mask):
    idx = jnp.nonzero(mask)  # JL005: value-dependent output shape
    return vis[idx]


@jax.jit
def dedupe(freqs):
    return jnp.unique(freqs)  # JL005


@jax.jit
def where_one_arg(w):
    return jnp.where(w > 0)  # JL005: one-argument where


@jax.jit
def boolean_mask(x):
    return x[x > 0]  # JL005: boolean-mask indexing
