"""JL006 should-fire fixture: collective outside the parallel layer
(this file deliberately lives outside parallel/ and is not sharded.py).
"""

import jax
import jax.numpy as jnp
from jax import lax


def local_residual(r):
    total = lax.psum(jnp.sum(r * r), axis_name="band")  # JL006
    return r / total


def who_am_i():
    return jax.lax.axis_index("band")  # JL006
