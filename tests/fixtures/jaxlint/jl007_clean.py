"""JL007 must-not-fire fixture: every carry-named jit parameter is
either donated (argnums or argnames, any wrap form) or declared
static, and non-carry names never match."""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0, 2))
def fit(p0, data, memory):  # p0 and memory donated by position
    return p0 + jnp.sum(data) + memory


def _step(state, grad):
    return state - 0.1 * grad


step_jit = jax.jit(_step, donate_argnames=("state",))


@functools.partial(jax.jit, static_argnames=("carry",))
def unrolled(carry, x):  # static carry is trace-time, nothing to donate
    return x + carry


@jax.jit
def predict(params, coords):  # non-carry names: rule does not match
    return params * jnp.cos(coords)
