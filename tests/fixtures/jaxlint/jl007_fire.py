"""JL007 should-fire fixture: jit entries threading carry-named
parameters (``p0``/``state``/``memory``) without donate_argnums, over
every wrap form the call graph recognizes."""

import functools

import jax
import jax.numpy as jnp


@jax.jit
def fit(p0, data):  # JL007: p0 undonated (decorator form)
    return p0 + jnp.sum(data)


def _step(state, grad):  # JL007: state undonated (call-site wrap)
    return state - 0.1 * grad


step_jit = jax.jit(_step)


def _update(memory, delta):  # JL007: memory undonated (partial form)
    return memory + delta


update_jit = functools.partial(jax.jit, _update)


@functools.partial(jax.jit, donate_argnums=(0,))
def consume(state, rhs):  # donated by argnum: must NOT fire
    return state + rhs


def _refit(p0, obs):  # donated by argname: must NOT fire
    return p0 * jnp.mean(obs)


refit_jit = jax.jit(_refit, donate_argnames=("p0",))


def plain_host(p0):  # not a jit root: must NOT fire
    return p0
