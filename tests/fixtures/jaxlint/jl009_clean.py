"""Must-not-fire fixture for JL009: the aot_store pattern — a
plain-text magic/version header validated before pickle touches the
stream, mismatch treated as a miss."""
import json
import pickle

_MAGIC = "sagecal-aot-v1"


def load_artifact(path):
    try:
        with open(path, "rb") as f:
            header = json.loads(f.readline().decode("utf-8"))
            if header.get("magic") != _MAGIC:
                raise ValueError("bad magic")
            if header.get("jaxlib_version") != "expected":
                raise ValueError("version mismatch")
            return pickle.load(f)
    except Exception:
        return None
