"""Should-fire fixture for JL009: unpickling shared artifacts with no
header gate in sight."""
import pickle


def load_artifact(path):
    with open(path, "rb") as f:
        return pickle.load(f)


def load_blob(blob):
    return pickle.loads(blob)
