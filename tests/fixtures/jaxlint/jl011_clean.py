"""Must-not-fire fixture for JL011: consuming idioms — the donated
name is rebound from the call's result (directly or by tuple
unpacking) before any further use."""
import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0,),
                   donate_argnames=("memory",))
def fit(p0, memory):
    return p0 + memory, memory


def consuming_caller(p0, memory):
    p0, memory = fit(p0, memory=memory)
    return p0, memory


def loop_caller(p0, memory):
    for _ in range(3):
        p0, memory = fit(p0, memory=memory)
    return p0, memory
