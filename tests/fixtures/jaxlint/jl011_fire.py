"""Should-fire fixture for JL011: reading a buffer after donating it
to a jit root (positional donate_argnums and keyword donate_argnames)."""
import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0,),
                   donate_argnames=("memory",))
def fit(p0, memory):
    return p0 + memory, memory


def caller(p0, memory):
    out, mem = fit(p0, memory=memory)
    total = p0.sum()
    stale = memory
    return out, mem, total, stale
