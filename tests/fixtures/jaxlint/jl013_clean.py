"""JL013 clean fixture: every None cotangent slot takes a declared
route — capability flag, stop-gradient-guarded call sites (including
the dynamic-slice passthrough), or an unconditionally-raising backward.
"""

import jax
import jax.numpy as jnp

CAP_FLAG = False
CAP_FLAG_ARGS = ("coh",)


@jax.custom_vjp
def cap_declared(x, coh):
    return x * coh


def _cd_fwd(x, coh):
    return x * coh, coh


def _cd_bwd(res, g):
    return g * res, None  # declared via CAP_FLAG / CAP_FLAG_ARGS


cap_declared.defvjp(_cd_fwd, _cd_bwd)


@jax.custom_vjp
def guarded(x, idx):
    return x + idx


def _g_fwd(x, idx):
    return x + idx, None


def _g_bwd(res, g):
    return g, None  # every call site stop-gradient-guards idx


guarded.defvjp(_g_fwd, _g_bwd)


def call_guarded_direct(x, idx):
    return guarded(x, jax.lax.stop_gradient(idx))


def call_guarded_sliced(x, idx):
    idx = jax.lax.stop_gradient(idx)
    chunk = jax.lax.dynamic_slice_in_dim(idx, 0, 4, axis=0)
    return guarded(x, chunk)


@jax.custom_vjp
def refuses(x):
    return x


def _r_fwd(x):
    return x, None


def _r_bwd(res, g):
    raise NotImplementedError("no cotangent by explicit contract")


refuses.defvjp(_r_fwd, _r_bwd)


def total(x, w):
    return jnp.sum(x) + jnp.sum(w)
