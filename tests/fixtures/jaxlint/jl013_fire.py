"""JL013 fire fixture: custom_vjp backwards that drop cotangents.

Three distinct violations: a silent-None slot with an unguarded call
site, a backward whose return arity misses a differentiable arg, and a
capability flag that PROMISES a cotangent the backward never produces.
"""

import functools

import jax
import jax.numpy as jnp


@jax.custom_vjp
def silent_zero(x, w):
    return x * w


def _sz_fwd(x, w):
    return x * w, (x, w)


def _sz_bwd(res, g):
    x, w = res
    return g * w, None  # FIRE: drops w's cotangent silently


silent_zero.defvjp(_sz_fwd, _sz_bwd)


def caller(x, w):
    # unguarded call site: w's None slot is a live zero-gradient trap
    return silent_zero(x, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def misaligned(a, b, flag):
    return a + b


def _ma_fwd(a, b, flag):
    return a + b, None


def _ma_bwd(flag, res, g):
    return (g,)  # FIRE: two differentiable args, one cotangent


misaligned.defvjp(_ma_fwd, _ma_bwd)


HAS_THETA_COTANGENT = True
HAS_THETA_COTANGENT_ARGS = ("theta",)


@jax.custom_vjp
def promised(x, theta):
    return x * theta


def _p_fwd(x, theta):
    return x * theta, theta


def _p_bwd(res, g):
    return g * res, None  # FIRE: the flag above promises a cotangent


promised.defvjp(_p_fwd, _p_bwd)


def use_promised(x, theta):
    return jnp.sum(promised(x, theta))
