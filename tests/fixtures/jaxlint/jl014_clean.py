"""JL014 clean fixture: every bf16-ingested read upcasts at the load
and every kernel matmul pins its accumulator dtype."""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _helper(coh_ref):
    return coh_ref[1, :].astype(jnp.float32)


def _kernel(coh_ref, w_ref, out_ref):
    a = coh_ref[0, :].astype(jnp.float32)
    b = _helper(coh_ref)
    sel = jnp.dot(w_ref[0, :], w_ref[1, :],
                  preferred_element_type=jnp.float32)
    out_ref[0, :] = a + b + sel


def run(coh, w):
    coh_ri = coh.astype(jnp.bfloat16)
    kernel = functools.partial(_kernel)
    args = (coh_ri, w)
    return pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[
            pl.BlockSpec((2, 128), lambda r: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, 128), lambda r: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 128), lambda r: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, 128), jnp.float32),
    )(*args)
