"""JL014 fire fixture: bf16-ingested kernel operand read without an
f32 upcast (directly and through a helper the taint propagates into),
plus a matmul without a pinned accumulator dtype."""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _helper(coh_ref):
    return coh_ref[1, :]  # FIRE: propagated bf16 ref, no upcast


def _kernel(coh_ref, w_ref, out_ref):
    a = coh_ref[0, :]  # FIRE: bf16 read, no upcast
    b = _helper(coh_ref)
    sel = jnp.dot(w_ref[0, :], w_ref[1, :])  # FIRE: unpinned accumulator
    out_ref[0, :] = a + b + sel


def run(coh, w):
    coh_ri = coh.astype(jnp.bfloat16)
    kernel = functools.partial(_kernel)
    args = (coh_ri, w)
    return pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[
            pl.BlockSpec((2, 128), lambda r: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, 128), lambda r: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 128), lambda r: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, 128), jnp.float32),
    )(*args)
