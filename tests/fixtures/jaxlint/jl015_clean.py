"""JL015 clean fixture: every BlockSpec carries a rank-consistent
index_map and an explicit memory_space."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def run(x):
    return pl.pallas_call(
        _kernel,
        grid=(4,),
        in_specs=[
            pl.BlockSpec((1, 128), lambda r: (0, r),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 128), lambda r: (0, r),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, 512), jnp.float32),
    )(x)


def row_spec(tile):
    return pl.BlockSpec((1, tile), lambda r: (0, r),
                        memory_space=pltpu.SMEM)
