"""JL900 fixture: dead imports, with the honored escape hatches."""

import json  # JL900: unused
import os  # noqa: F401  (kept: re-export convention)
import sys
from typing import List, Optional  # JL900: Optional unused

__all__ = ["sys", "use_list"]


def use_list(xs: List[int]) -> int:
    return len(xs)
