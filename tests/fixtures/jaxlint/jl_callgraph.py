"""Call-graph fixture: reachability through the repo's real wrap forms
(instrumented_jit call-site wrap, shard_map pass-through chasing,
decorator factories), plus a function that must stay unreachable."""

import jax.numpy as jnp
from jax.experimental.shard_map import shard_map

from sagecal_tpu.obs.perf import instrumented_jit


def helper(x):
    return jnp.sum(x * x)


def local_fit(x):
    return helper(x) + 1.0


# solvers/sharded.py idiom: jit(shard_map(f)) must mark f reachable
fn = shard_map(local_fit, mesh=None, in_specs=None, out_specs=None)
fit_jit = instrumented_jit(fn, name="fixture.fit")


@instrumented_jit(name="fixture.block")
def block(x):
    return helper(x) * 2.0


def host_only_report(x):
    # referenced by nothing jitted: must NOT be jit-reachable
    return str(x)
