"""Pragma fixture: every finding here carries a suppression comment,
so this file must come out clean."""

import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def suppressed_branch(x):
    r = jnp.sum(x)
    if r > 0:  # jaxlint: disable=JL001 — fixture: deliberate branch
        return x / r
    return x


def suppressed_collective(r):
    # jaxlint: disable-file=JL006
    return lax.psum(r, axis_name="band")
