"""JL004 must-not-fire fixture: the repo's x64-aware conditional idiom."""

import jax.numpy as jnp
import numpy as np


def widen_conditionally(u):
    # the deliberate idiom: wide dtype only when the input is wide
    ctype = jnp.complex64 if u.dtype == jnp.float32 else jnp.complex128
    return u.astype(ctype)


def statement_form(u):
    if u.dtype == jnp.float64:
        out = jnp.zeros(u.shape, jnp.complex128)
    else:
        out = jnp.zeros(u.shape, jnp.complex64)
    return out


def host_precompute(n):
    # numpy 64-bit on host is outside the device precision policy
    return np.zeros(n, np.float64)
