"""JL004 should-fire fixture (lives under a solvers/ path segment)."""

import jax.numpy as jnp


def accumulate(x):
    acc = jnp.zeros(x.shape, jnp.float64)  # JL004: unconditional f64
    return acc + x


def widen(u):
    return u.astype(jnp.complex128)  # JL004: unconditional c128
