"""JL012 must-not-fire fixture: the precision carve-outs."""

import jax.numpy as jnp
import numpy as np


def dispatch(coh_dtype, coh):
    # string-literal dtype dispatch is configuration, not numerics
    if coh_dtype == "bf16":
        coh = coh.astype(jnp.bfloat16)
    return coh


def same_family(cost_f32, ref_f32):
    # both sides in one float family: no implicit tolerance
    return cost_f32 < ref_f32


def single_family(x, limit):
    # only one side carries dtype intent — nothing mixed
    x_bf16 = x.astype(jnp.bfloat16)
    return x_bf16.sum() > limit


def check_stated(a, b):
    # explicit tolerance: the check states what "close" means
    return np.allclose(a, b, rtol=1e-3, atol=1e-6)


def check_positional(a, b):
    # positional rtol counts as stated
    return np.isclose(a, b, 1e-3)


def stringly(kind_bf16):
    # string-literal comparator: dtype dispatch, not numerics, even
    # when the left-hand name carries a family token
    return kind_bf16 == "f32"
