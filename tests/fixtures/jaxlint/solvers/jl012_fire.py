"""JL012 should-fire fixture (lives under a solvers/ path segment)."""

import jax.numpy as jnp
import numpy as np


def converged(cost_bf16, cost_f32):
    # JL012: compares a bf16-family value against an f32-family one —
    # the upcast encodes an implicit half-precision tolerance
    return cost_bf16 < cost_f32


def gate(coh_bf16, ref):
    ref_f32 = ref.astype(jnp.float32)
    return coh_bf16.max() > ref_f32.max()  # JL012: mixed families


def check(a, b):
    # JL012: tolerance-less allclose leans on dtype-blind defaults
    return np.allclose(a, b)
