"""Child process for the multi-host mesh ADMM test (test_multihost.py).

Two of these run concurrently (process_id 0/1), each owning 4 virtual
CPU devices of a global 8-device ``freq`` mesh, and drive the SAME
mesh ADMM program multi-process: global arrays are assembled from
per-process addressable shards, the z-step psum and manifold all_gather
cross the process boundary through the gloo CPU collectives — the DCN
layer of SURVEY §5's mapping, with `jax.distributed` standing in for
the reference's MPI world (sagecal_master.cpp).

The workload is defined ONCE in mh_common.py (shared with the
single-process comparison run in the parent test).
"""
import os
import sys

pid = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=4 "
    "--xla_cpu_collective_timeout_seconds=7200"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                           process_id=pid)

import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

import mh_common  # noqa: E402
from sagecal_tpu.parallel.mesh import make_admm_mesh_fn  # noqa: E402
from sagecal_tpu.solvers.lm import LMConfig  # noqa: E402

data_stack, cdata_stack, p0, rho, B = mh_common.build_workload()
Nf = mh_common.Nf
mesh = Mesh(np.array(jax.devices()).reshape(Nf), ("freq",))


def globalize(leaf):
    """Host-local (Nf, ...) array -> global array sharded over freq."""
    if not hasattr(leaf, "shape") or leaf.ndim == 0 or leaf.shape[0] != Nf:
        return leaf
    sh = NamedSharding(mesh, P("freq", *([None] * (leaf.ndim - 1))))
    arr = np.asarray(leaf)
    return jax.make_array_from_callback(arr.shape, sh,
                                        lambda idx: arr[idx])


data_g = jax.tree.map(globalize, data_stack)
cdata_g = jax.tree.map(globalize, cdata_stack)
p0_g, rho_g, B_g = (globalize(x) for x in (p0, rho, B))

fn = make_admm_mesh_fn(mesh, nadmm=mh_common.NADMM, max_emiter=1,
                       plain_emiter=1, lm_config=LMConfig(itmax=6),
                       bb_rho=False)
out = fn(data_g, cdata_g, p0_g, rho_g, B_g)

dual = np.asarray(jax.device_get(out.dual_res.addressable_shards[0].data)).ravel()
primal = np.asarray(jax.device_get(out.primal_res.addressable_shards[0].data)).ravel()
print("TRACE", pid, " ".join(f"{x:.10e}" for x in dual),
      "|", " ".join(f"{x:.10e}" for x in primal), flush=True)
