"""Child process for the multi-host mesh ADMM test (test_multihost.py).

Two of these run concurrently (process_id 0/1), each owning 4 virtual
CPU devices of a global 8-device ``freq`` mesh, and drive the SAME
mesh ADMM program multi-process: global arrays are assembled from
per-process addressable shards, the z-step psum and manifold all_gather
cross the process boundary through the gloo CPU collectives — the DCN
layer of SURVEY §5's mapping, with `jax.distributed` standing in for
the reference's MPI world (sagecal_master.cpp).
"""
import os
import sys

pid = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=4 "
    "--xla_cpu_collective_timeout_seconds=7200"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                           process_id=pid)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from sagecal_tpu.core.types import jones_to_params  # noqa: E402
from sagecal_tpu.io.simulate import (  # noqa: E402
    corrupt_and_observe, make_visdata, random_jones,
)
from sagecal_tpu.ops.rime import point_source_batch  # noqa: E402
from sagecal_tpu.parallel import consensus  # noqa: E402
from sagecal_tpu.parallel.mesh import make_admm_mesh_fn, stack_for_mesh  # noqa: E402
from sagecal_tpu.solvers.lm import LMConfig  # noqa: E402
from sagecal_tpu.solvers.sage import build_cluster_data  # noqa: E402

Nf, M, N, f0, Npoly = 8, 2, 6, 150e6, 2
freqs = np.linspace(130e6, 170e6, Nf)

rng = np.random.default_rng(7)
Z0 = np.asarray(random_jones(M, N, seed=1, amp=0.15, dtype=np.complex128))
Z1 = 0.05 * (rng.standard_normal((M, N, 2, 2))
             + 1j * rng.standard_normal((M, N, 2, 2)))

clusters = [
    point_source_batch([0.01], [0.02], [2.0], f0=f0, dtype=jnp.float64),
    point_source_batch([-0.02], [0.01], [1.5], f0=f0, dtype=jnp.float64),
]

bands = []
for f in range(Nf):
    frat = (freqs[f] - f0) / f0
    jones_f = jnp.asarray(Z0 + frat * Z1)
    data = make_visdata(nstations=N, tilesz=2, nchan=1, freq0=f0,
                        dtype=np.float64, seed=f)
    data = corrupt_and_observe(data, clusters, jones=jones_f,
                               noise_sigma=1e-4, seed=f)
    data = data.replace(freqs=jnp.asarray([freqs[f]], jnp.float64))
    cdata = build_cluster_data(data, clusters, [1] * M)
    bands.append((data, cdata))

data_stack = stack_for_mesh([b[0] for b in bands])
cdata_stack = stack_for_mesh([b[1] for b in bands])
p0 = jnp.stack(
    [jones_to_params(
        random_jones(M, N, seed=500, amp=0.0, dtype=np.complex128)
    )[:, None, :] for _ in range(Nf)]
)
rho = jnp.full((Nf, M), 20.0, jnp.float64)
B = jnp.asarray(
    consensus.setup_polynomials(freqs, f0, Npoly, consensus.POLY_ORDINARY)
)

mesh = Mesh(np.array(jax.devices()).reshape(Nf), ("freq",))


def globalize(leaf):
    """Host-local (Nf, ...) array -> global array sharded over freq."""
    if not hasattr(leaf, "shape") or leaf.ndim == 0 or leaf.shape[0] != Nf:
        return leaf
    sh = NamedSharding(mesh, P("freq", *([None] * (leaf.ndim - 1))))
    arr = np.asarray(leaf)
    return jax.make_array_from_callback(arr.shape, sh,
                                        lambda idx: arr[idx])


data_g = jax.tree.map(globalize, data_stack)
cdata_g = jax.tree.map(globalize, cdata_stack)
p0_g, rho_g, B_g = (globalize(x) for x in (p0, rho, B))

fn = make_admm_mesh_fn(mesh, nadmm=4, max_emiter=1, plain_emiter=1,
                       lm_config=LMConfig(itmax=6), bb_rho=False)
out = fn(data_g, cdata_g, p0_g, rho_g, B_g)

dual = np.asarray(jax.device_get(out.dual_res.addressable_shards[0].data)).ravel()
primal = np.asarray(jax.device_get(out.primal_res.addressable_shards[0].data)).ravel()
print("TRACE", pid, " ".join(f"{x:.10e}" for x in dual),
      "|", " ".join(f"{x:.10e}" for x in primal), flush=True)
