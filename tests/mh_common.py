"""Shared workload for the multi-host test pair (mh_child.py runs it in
each of two OS processes; test_multihost.py runs it single-process) —
one definition so the process-count-invariance comparison can't drift."""

import numpy as np

Nf, M, N, F0, NPOLY = 8, 2, 6, 150e6, 2
FREQS = np.linspace(130e6, 170e6, Nf)
NADMM = 4


def build_workload():
    """Returns (data_stack, cdata_stack, p0, rho, B) host-local arrays
    with leading sub-band axis Nf.  Deterministic: identical in every
    process."""
    import jax.numpy as jnp

    from sagecal_tpu.core.types import jones_to_params
    from sagecal_tpu.io.simulate import (
        corrupt_and_observe, make_visdata, random_jones,
    )
    from sagecal_tpu.ops.rime import point_source_batch
    from sagecal_tpu.parallel import consensus
    from sagecal_tpu.parallel.mesh import stack_for_mesh
    from sagecal_tpu.solvers.sage import build_cluster_data

    rng = np.random.default_rng(7)
    Z0 = np.asarray(random_jones(M, N, seed=1, amp=0.15, dtype=np.complex128))
    Z1 = 0.05 * (rng.standard_normal((M, N, 2, 2))
                 + 1j * rng.standard_normal((M, N, 2, 2)))
    clusters = [
        point_source_batch([0.01], [0.02], [2.0], f0=F0, dtype=jnp.float64),
        point_source_batch([-0.02], [0.01], [1.5], f0=F0, dtype=jnp.float64),
    ]
    bands = []
    for f in range(Nf):
        frat = (FREQS[f] - F0) / F0
        jones_f = jnp.asarray(Z0 + frat * Z1)
        data = make_visdata(nstations=N, tilesz=2, nchan=1, freq0=F0,
                            dtype=np.float64, seed=f)
        data = corrupt_and_observe(data, clusters, jones=jones_f,
                                   noise_sigma=1e-4, seed=f)
        data = data.replace(freqs=jnp.asarray([FREQS[f]], jnp.float64))
        bands.append((data, build_cluster_data(data, clusters, [1] * M)))
    p0 = jnp.stack(
        [jones_to_params(
            random_jones(M, N, seed=500, amp=0.0, dtype=np.complex128)
        )[:, None, :] for _ in range(Nf)]
    )
    rho = jnp.full((Nf, M), 20.0, jnp.float64)
    B = jnp.asarray(
        consensus.setup_polynomials(FREQS, F0, NPOLY, consensus.POLY_ORDINARY)
    )
    return (stack_for_mesh([b[0] for b in bands]),
            stack_for_mesh([b[1] for b in bands]), p0, rho, B)
