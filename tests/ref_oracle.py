"""ctypes harness around the REFERENCE CPU solver library (the anchor).

Builds ``libdirac_ref.so`` from the read-only reference checkout's CPU
source list (``/root/reference/src/lib/Dirac/CMakeLists.txt:8-94``; the
same objects the reference's non-CUDA ``add_library(dirac SHARED ...)``
compiles) and exposes ``sagefit_visibilities``
(``/root/reference/src/lib/Dirac/Dirac.h:1651``) to the tests.  This is
the plan-of-record end-to-end anchor (SURVEY.md §4, BASELINE.md): run the
ACTUAL reference solver on the same synthetic visibilities our framework
solves and diff the Jones solutions.

Nothing here copies reference code — the reference sources are compiled
from their mounted location into a gitignored build directory and called
through their public C API, exactly as a reference user would link
``-ldirac``.

Layout contracts verified against the reference sources:
  * ``x``: ``Nbase*tilesz`` rows x 8 doubles [XX XY YX YY] x (re, im)
    (``Dirac.h:1617-1618``);
  * ``coh``: ``complex double[4*M*row + 4*cluster + comp]``, components
    row-major [C00 C01 C10 C11] (``lmfit.c:101-105``);
  * per-station solver params: 8 doubles, the ROW-MAJOR 2x2 Jones
    re/im-interleaved [J00 J01 J10 J11] (``lmfit.c:90-97`` with the
    row-major ``amb()`` product at ``lmfit.c:37-43``) — note this is the
    in-memory solver order, NOT the solution-file S-order.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

REF_DIRAC = "/root/reference/src/lib/Dirac"
BUILD_DIR = os.path.join(os.path.dirname(__file__), "..", "native", "refbuild")
LIB_PATH = os.path.abspath(os.path.join(BUILD_DIR, "libdirac_ref.so"))

# The CPU (non-CUDA) object list from the reference's
# src/lib/Dirac/CMakeLists.txt (common `objects` + cpu `extra_objects`).
_CPU_OBJECTS = [
    "admm_solve", "clmfit", "manifold_average", "mdl", "myblas",
    "rtr_solve", "rtr_solve_robust_admm", "updatenu", "fista",
    "baseline_utils", "pngoutput",
    "lmfit", "consensus_poly", "lbfgs", "robust_batchmode_lbfgs",
    "robust_lbfgs", "robustlm", "rtr_solve_robust", "lbfgsb",
]
_BLAS = "/lib/x86_64-linux-gnu/libblas.so.3"
_LAPACK = "/lib/x86_64-linux-gnu/liblapack.so.3"


def build_ref_lib() -> str | None:
    """Compile + link the reference Dirac CPU library.  Returns the .so
    path, or None when the toolchain/reference/BLAS is unavailable (the
    anchor tests skip in that case)."""
    if os.path.exists(LIB_PATH):
        return LIB_PATH
    if not (os.path.isdir(REF_DIRAC) and os.path.exists(_BLAS)):
        return None
    os.makedirs(BUILD_DIR, exist_ok=True)
    objs = []
    try:
        for name in _CPU_OBJECTS:
            obj = os.path.join(BUILD_DIR, name + ".o")
            if not os.path.exists(obj):
                subprocess.run(
                    ["gcc", "-O2", "-fPIC", "-c",
                     os.path.join(REF_DIRAC, name + ".c"),
                     "-I", REF_DIRAC, "-o", obj],
                    check=True, capture_output=True, timeout=300,
                )
            objs.append(obj)
        subprocess.run(
            ["gcc", "-shared", "-o", LIB_PATH, *objs,
             _LAPACK, _BLAS, "-lpng", "-lpthread", "-lm"],
            check=True, capture_output=True, timeout=300,
        )
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError):
        return None
    return LIB_PATH


class BaselineT(ctypes.Structure):
    """``baseline_t`` (Dirac_common.h:190-196)."""
    _fields_ = [("sta1", ctypes.c_int), ("sta2", ctypes.c_int),
                ("flag", ctypes.c_ubyte)]


_PD = ctypes.POINTER(ctypes.c_double)


class ClusSourceT(ctypes.Structure):
    """``clus_source_t`` (Dirac_common.h:173-187).  The precomputed-
    coherency solver path reads only ``nchunk`` and ``p`` (lmfit.c:86-87;
    the reference's own MIC wrapper builds dummy structs the same way,
    lmfit.c:1223-1228); all other fields stay NULL/0."""
    _fields_ = [
        ("N", ctypes.c_int), ("id", ctypes.c_int),
        ("ll", _PD), ("mm", _PD), ("nn", _PD), ("sI", _PD),
        ("sQ", _PD), ("sU", _PD), ("sV", _PD),
        ("ra", _PD), ("dec", _PD),
        ("stype", ctypes.POINTER(ctypes.c_ubyte)),
        ("ex", ctypes.POINTER(ctypes.c_void_p)),
        ("nchunk", ctypes.c_int),
        ("p", ctypes.POINTER(ctypes.c_int)),
        ("sI0", _PD), ("sQ0", _PD), ("sU0", _PD), ("sV0", _PD),
        ("f0", _PD), ("spec_idx", _PD), ("spec_idx1", _PD),
        ("spec_idx2", _PD),
    ]


def load_lib():
    path = build_ref_lib()
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.sagefit_visibilities.restype = ctypes.c_int
    return lib


_COST_CB = ctypes.CFUNCTYPE(ctypes.c_double, _PD, ctypes.c_int,
                            ctypes.c_void_p)
_GRAD_CB = ctypes.CFUNCTYPE(None, _PD, _PD, ctypes.c_int, ctypes.c_void_p)


def ref_lbfgs_fit(cost, grad, p0, itmax=100, mem=7):
    """The reference's generic cost/grad-callback optimizer contract
    (``lbfgs_fit``, Dirac.h:175; demo oracle test/Dirac/demo.c):
    ``cost(p)->float`` and ``grad(p)->array`` are Python callables."""
    lib = load_lib()
    assert lib is not None
    m = len(p0)
    # copy=True: the C solver writes the solution into this buffer; a
    # no-copy pass-through would mutate the CALLER's p0 in place
    p = np.array(p0, np.float64, copy=True)

    @_COST_CB
    def c_cost(pp, mm, adata):
        arr = np.ctypeslib.as_array(pp, shape=(mm,))
        return float(cost(arr))

    @_GRAD_CB
    def c_grad(pp, gg, mm, adata):
        arr = np.ctypeslib.as_array(pp, shape=(mm,))
        g = np.ctypeslib.as_array(gg, shape=(mm,))
        g[:] = np.asarray(grad(arr), np.float64)

    lib.lbfgs_fit.restype = ctypes.c_int
    rv = lib.lbfgs_fit(c_cost, c_grad, p.ctypes.data_as(_PD),
                       ctypes.c_int(m), ctypes.c_int(itmax),
                       ctypes.c_int(mem), None, None)
    return p, rv


def ref_bfgsfit(
    u, v, w, x, nstations, nbase, tilesz, sta1, sta2, coh, m,
    p0, *, freq0=150e6, fdelta=180e3, uvmin=0.0, nthreads=1,
    max_lbfgs=20, lbfgs_m=7, solver_mode=2, mean_nu=5.0,
):
    """Run the reference ``bfgsfit_visibilities`` (Dirac.h:1683,
    lmfit.c:1126): the joint LBFGS-only multi-cluster fit — the same
    work bench.py times per iteration (full-model predict + gradient
    over all 8*N*M parameters; robust Student's-t cost when
    solver_mode is one of the R-LBFGS modes).  Shapes as in
    :func:`ref_sagefit`.  Returns (jones, res_0, res_1, retval)."""
    lib = load_lib()
    assert lib is not None
    rows = nbase * tilesz
    assert x.shape == (4, rows) and coh.shape == (m, 4, rows)

    uu = np.ascontiguousarray(u, np.float64)
    vv = np.ascontiguousarray(v, np.float64)
    ww = np.ascontiguousarray(w, np.float64)
    xr = np.empty((rows, 8), np.float64)
    xr[:, 0::2] = x.real.T
    xr[:, 1::2] = x.imag.T
    xr = np.ascontiguousarray(xr.reshape(-1))

    barr = (BaselineT * rows)()
    for i in range(rows):
        barr[i].sta1 = int(sta1[i])
        barr[i].sta2 = int(sta2[i])
        barr[i].flag = 0

    coh_ref = np.ascontiguousarray(
        np.transpose(coh, (2, 0, 1)), np.complex128
    )

    n8 = 8 * nstations
    carr = (ClusSourceT * m)()
    pidx = (ctypes.c_int * m)()
    for cm in range(m):
        pidx[cm] = n8 * cm
        carr[cm].nchunk = 1
        carr[cm].p = ctypes.cast(
            ctypes.byref(pidx, cm * ctypes.sizeof(ctypes.c_int)),
            ctypes.POINTER(ctypes.c_int),
        )

    pp = np.empty((m, nstations, 4, 2), np.float64)
    flat = p0.reshape(m, nstations, 4)
    pp[..., 0] = flat.real
    pp[..., 1] = flat.imag
    pp = np.ascontiguousarray(pp.reshape(-1))

    res_0 = ctypes.c_double(0.0)
    res_1 = ctypes.c_double(0.0)
    as_pd = lambda a: a.ctypes.data_as(_PD)
    lib.bfgsfit_visibilities.restype = ctypes.c_int
    rv = lib.bfgsfit_visibilities(
        as_pd(uu), as_pd(vv), as_pd(ww), as_pd(xr),
        ctypes.c_int(nstations), ctypes.c_int(nbase), ctypes.c_int(tilesz),
        barr, carr,
        coh_ref.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int(m), ctypes.c_int(m),
        ctypes.c_double(freq0), ctypes.c_double(fdelta),
        as_pd(pp), ctypes.c_double(uvmin), ctypes.c_int(nthreads),
        ctypes.c_int(max_lbfgs), ctypes.c_int(lbfgs_m),
        ctypes.c_int(128), ctypes.c_int(solver_mode),
        ctypes.c_double(mean_nu),
        ctypes.byref(res_0), ctypes.byref(res_1),
    )
    sol = pp.reshape(m, nstations, 4, 2)
    jones = (sol[..., 0] + 1j * sol[..., 1]).reshape(m, nstations, 2, 2)
    return jones, res_0.value, res_1.value, rv


def ref_sagefit(
    u, v, w, x, nstations, nbase, tilesz, sta1, sta2, coh, m,
    p0, *, freq0=150e6, fdelta=180e3, uvmin=0.0, nthreads=2,
    max_emiter=3, max_iter=10, max_lbfgs=10, lbfgs_m=7, linsolv=1,
    solver_mode=1, nulow=2.0, nuhigh=30.0, randomize=0,
):
    """Run the reference ``sagefit_visibilities`` (Dirac.h:1651).

    Args (numpy, float64/complex128, our canonical shapes):
      u, v, w: (rows,) in wavelength-seconds (multiplied by freq0 here,
        matching the reference's 1/c-then-*freq scaling).
      x: (4, rows) complex visibilities [XX XY YX YY].
      sta1, sta2: (rows,) int station indices.
      coh: (M, 4, rows) complex cluster coherencies.
      p0: (M, N, 2, 2) complex initial Jones.

    Returns (jones, mean_nu, res_0, res_1, retval):
      jones: (M, N, 2, 2) complex solved Jones (one chunk per cluster).
    """
    lib = load_lib()
    assert lib is not None
    rows = nbase * tilesz
    assert x.shape == (4, rows) and coh.shape == (m, 4, rows)

    uu = np.ascontiguousarray(u, np.float64)
    vv = np.ascontiguousarray(v, np.float64)
    ww = np.ascontiguousarray(w, np.float64)

    # x: row-major rows x [re, im]x4
    xr = np.empty((rows, 8), np.float64)
    xr[:, 0::2] = x.real.T
    xr[:, 1::2] = x.imag.T
    xr = np.ascontiguousarray(xr.reshape(-1))

    barr = (BaselineT * rows)()
    for i in range(rows):
        barr[i].sta1 = int(sta1[i])
        barr[i].sta2 = int(sta2[i])
        barr[i].flag = 0

    # coh[4*M*row + 4*cm + comp]
    coh_ref = np.ascontiguousarray(
        np.transpose(coh, (2, 0, 1)), np.complex128
    )  # (rows, M, 4)

    n8 = 8 * nstations
    carr = (ClusSourceT * m)()
    pidx = (ctypes.c_int * m)()
    for cm in range(m):
        pidx[cm] = n8 * cm
        carr[cm].nchunk = 1
        carr[cm].p = ctypes.cast(
            ctypes.byref(pidx, cm * ctypes.sizeof(ctypes.c_int)),
            ctypes.POINTER(ctypes.c_int),
        )

    # p: per cluster, per station: row-major J re/im interleaved
    pp = np.empty((m, nstations, 4, 2), np.float64)
    jr = p0.reshape(m, nstations, 2, 2)
    flat = jr.reshape(m, nstations, 4)  # row-major J00,J01,J10,J11
    pp[..., 0] = flat.real
    pp[..., 1] = flat.imag
    pp = np.ascontiguousarray(pp.reshape(-1))

    mean_nu = ctypes.c_double(0.0)
    res_0 = ctypes.c_double(0.0)
    res_1 = ctypes.c_double(0.0)

    as_pd = lambda a: a.ctypes.data_as(_PD)
    rv = lib.sagefit_visibilities(
        as_pd(uu), as_pd(vv), as_pd(ww), as_pd(xr),
        ctypes.c_int(nstations), ctypes.c_int(nbase), ctypes.c_int(tilesz),
        barr, carr,
        coh_ref.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int(m), ctypes.c_int(m),
        ctypes.c_double(freq0), ctypes.c_double(fdelta),
        as_pd(pp), ctypes.c_double(uvmin), ctypes.c_int(nthreads),
        ctypes.c_int(max_emiter), ctypes.c_int(max_iter),
        ctypes.c_int(max_lbfgs), ctypes.c_int(lbfgs_m),
        ctypes.c_int(128), ctypes.c_int(linsolv),
        ctypes.c_int(solver_mode),
        ctypes.c_double(nulow), ctypes.c_double(nuhigh),
        ctypes.c_int(randomize),
        ctypes.byref(mean_nu), ctypes.byref(res_0), ctypes.byref(res_1),
    )

    sol = pp.reshape(m, nstations, 4, 2)
    jones = (sol[..., 0] + 1j * sol[..., 1]).reshape(m, nstations, 2, 2)
    return jones, mean_nu.value, res_0.value, res_1.value, rv
